"""Results web UI (reference L8) — browse the store over HTTP.

Reference: jepsen/src/jepsen/web.clj — http-kit server with a home table
of runs (validity color-coded, web.clj:47-128), a file browser with
text/image previews (web.clj:194-248), and zip downloads of whole runs
(web.clj:250-292).  Rebuilt on the stdlib http.server (no extra deps);
same surface: `/` home, `/files/...` browser, `?zip` downloads.
"""

from __future__ import annotations

import html
import io
import json
import logging
import os
import shutil
import urllib.parse
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import store
from .obs import metrics as obs_metrics

log = logging.getLogger("jepsen")

STYLE = """
body { font-family: sans-serif; margin: 2em; }
table { border-collapse: collapse; }
th, td { padding: .3em .8em; text-align: left; }
tr:nth-child(even) { background: #f4f4f4; }
.valid-true { background: #c7f0c2; }
.valid-false { background: #f0c2c2; }
.valid-unknown { background: #f0e9c2; }
a { text-decoration: none; }
pre { background: #f8f8f8; padding: 1em; overflow-x: auto; }
"""


def _read_valid(run_dir: str):
    p = os.path.join(run_dir, "results.json")
    try:
        with open(p) as f:
            return json.load(f).get("valid")
    except Exception:
        return None


def home_html(base: str) -> str:
    """The run table (web.clj:47-128)."""
    rows = []
    for name, runs in sorted(store.tests(base=base).items()):
        for t, d in sorted(runs.items(), reverse=True):
            valid = _read_valid(d)
            cls = {True: "valid-true", False: "valid-false",
                   "unknown": "valid-unknown"}.get(valid, "")
            rel = urllib.parse.quote(f"{name}/{t}")
            rows.append(
                f'<tr class="{cls}"><td><a href="/files/{rel}/">{html.escape(name)}'
                f"</a></td><td>{html.escape(t)}</td>"
                f"<td>{html.escape(str(valid))}</td>"
                f'<td><a href="/files/{rel}/?zip">zip</a></td></tr>')
    campaigns = ""
    if os.path.isdir(os.path.join(base, "campaigns")):
        campaigns = '<p><a href="/campaigns">fault-injection campaigns</a></p>'
    campaigns += '<p><a href="/mc">bounded model checker</a></p>'
    return (f"<html><head><title>Jepsen</title><style>{STYLE}</style></head>"
            f"<body><h1>Jepsen results</h1>{campaigns}<table>"
            f"<tr><th>test</th><th>time</th><th>valid?</th><th></th></tr>"
            f"{''.join(rows)}</table></body></html>")


# ---------------------------------------------------------------------------
# bounded model checker panel (analyze/modelcheck.py)
# ---------------------------------------------------------------------------

#: one sweep per process per scope unless ?refresh=1 — the default
#: scopes finish in a few seconds, but a dashboard page must not
#: re-search per click.  Keyed by scope ("core" / "shell").
_MC_CACHE: dict | None = None


def mc_html(refresh: bool = False, scope: str = "core") -> str:
    """The ``/mc`` page: the family x mode expected-outcome matrix
    (clean modes must clear their scope; seeded modes must be caught
    with replaying certificates), explored-scope numbers, and each
    violation's schedule certificate with its confirm verdicts.

    ``scope`` picks the family set: ``core`` runs the abstract
    MC1xx worlds, ``shell`` lifts the live daemons' dispatch code
    onto the simulated transport (MC2xx, docs/analyze.md §12)."""
    global _MC_CACHE
    from .analyze import modelcheck as mc

    if scope not in ("core", "shell"):
        scope = "core"
    if not isinstance(_MC_CACHE, dict) or "runs" in _MC_CACHE:
        # unset, or a bare sweep dict left by an older caller —
        # promote to the per-scope cache shape
        _MC_CACHE = {}
    if scope not in _MC_CACHE or refresh:
        _MC_CACHE[scope] = (mc.run_mc_sweep(mc.SHELL_FAMILIES)
                            if scope == "shell" else mc.run_mc_sweep())
    sweep = _MC_CACHE[scope]
    shell_families = set(getattr(mc, "SHELL_FAMILIES", ()))
    rows = []
    certs = []
    for r in sweep["runs"]:
        ex = r["explored"]
        r_scope = "shell" if r["family"] in shell_families else "core"
        seeded = r["mode"] != "clean"
        expected = (not r["ok"] and all(c.get("replayed")
                                        for c in r["violations"])) \
            if seeded else r["ok"]
        cls = "valid-true" if expected else "valid-false"
        codes = sorted({c["code"] for c in r["violations"]})
        verdict = ("caught " + ", ".join(codes)) if codes else "clean"
        rows.append(
            f'<tr class="{cls}"><td>{r_scope}</td>'
            f'<td>{html.escape(r["family"])}</td>'
            f'<td>{html.escape(r["mode"])}</td>'
            f"<td>{html.escape(verdict)}</td>"
            f"<td>{ex['states']}</td><td>{ex['schedules']}</td>"
            f"<td>{ex['prune_ratio']}</td><td>{ex['complete']}</td>"
            f"<td>{'as expected' if expected else 'UNEXPECTED'}</td>"
            f"</tr>")
        for c in r["violations"]:
            sched = " → ".join(f"{e[0]}({e[1]})" if e[1] is not None
                               else e[0] for e in c["schedule"])
            conf = c.get("confirm") or {}
            certs.append(
                f"<h3>{html.escape(c['code'])} — "
                f"{html.escape(r['family'])}/{html.escape(r['mode'])}"
                f"</h3><p>{html.escape(c['detail'])}</p>"
                f"<p><code>{html.escape(sched)}</code> "
                f"({c['shrunk']['n_from']} → {c['shrunk']['n_to']} "
                f"events, minimal={c['shrunk']['minimal']}, "
                f"replayed={c['replayed']})</p>"
                f"<p>confirm [{html.escape(str(conf.get('route')))}]: "
                f"engine valid={conf.get('engine_valid')}, "
                f"audit ok={conf.get('audit_ok')} "
                f"(checked {conf.get('audit_checked')})</p>")
    status = "ok — every mode behaved as expected" if sweep["ok"] \
        else "FAILED — some mode deviated from its expected outcome"
    return (f"<html><head><title>model checker</title>"
            f"<style>{STYLE}</style></head><body>"
            f"<h1>Bounded model checker</h1>"
            f'<p><a href="/">home</a> · '
            f'<a href="/mc?scope=core">core scope</a> · '
            f'<a href="/mc?scope=shell">shell scope</a> · '
            f'<a href="/mc?scope={scope}&refresh=1">re-run sweep</a></p>'
            f"<p>scope: {scope} — {html.escape(status)} "
            f"(MC1xx/MC2xx codes, schedule certificates — "
            f"docs/analyze.md §11–§12)</p><table>"
            f"<tr><th>scope</th><th>family</th><th>mode</th>"
            f"<th>verdict</th>"
            f"<th>states</th><th>schedules</th><th>prune ratio</th>"
            f"<th>complete</th><th>expected?</th></tr>"
            f"{''.join(rows)}</table>{''.join(certs)}</body></html>")


# ---------------------------------------------------------------------------
# campaign grid (live fault-injection campaigns, jepsen_tpu/live/)
# ---------------------------------------------------------------------------


def _load_campaign(base: str, cid: str) -> dict | None:
    p = os.path.join(base, "campaigns", cid, "campaign.json")
    try:
        with open(p) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except Exception:
        return None


#: the /campaigns fleet-health strip: polls /api/stats every 5s and
#: shows the live registry's headline numbers (open runs, cache hit
#: ratio, sheds, watchdog firings) so a running fleet is glanceable
#: from the grid page itself
_HEALTH_STRIP = """
<p id="fleet-health" style="font-family:monospace"></p>
<script>
async function pollStats() {
  try {
    const r = await fetch("/api/stats");
    if (r.ok) {
      const s = await r.json();
      const v = (n) => {
        const m = s[n]; if (!m) return 0;
        const vv = m.values;
        return typeof vv === "number" ? vv
          : Object.values(vv || {}).reduce((a, b) => a + b, 0);
      };
      const d = s.derived || {};
      document.getElementById("fleet-health").textContent =
        "fleet: " + v("jtpu_stream_runs_open") + " open runs · "
        + "cache hit ratio " + (d.verdict_cache_hit_ratio ?? "n/a")
        + " · " + v("jtpu_shed_total") + " shed · watchdog "
        + v("jtpu_watchdog_total")
        + " · corpus " + v("jtpu_corpus_pool_size")
        + " · rules swept " + v("jtpu_link_rules_swept_total")
        + " · device idle " + (d.device_idle_fraction ?? "n/a")
        + " · observed prune " + (d.observed_prune_ratio ?? "n/a");
    }
  } catch (e) {}
  setTimeout(pollStats, 5000);
}
pollStats();
</script>"""


def campaigns_html(base: str) -> str:
    """The campaign index: one row per recorded campaign."""
    d = os.path.join(base, "campaigns")
    rows = []
    try:
        cids = sorted(os.listdir(d), reverse=True)
    except OSError:
        cids = []
    for cid in cids:
        c = _load_campaign(base, cid)
        if c is None:
            continue
        s = c.get("summary") or {}
        q = urllib.parse.quote(cid)
        rows.append(
            f'<tr><td><a href="/campaigns/{q}">{html.escape(cid)}</a>'
            f"</td><td>{s.get('ok', 0)}</td>"
            f"<td>{s.get('skipped', 0)}</td>"
            f"<td>{s.get('failed', 0)}</td>"
            f"<td>{s.get('detected', 0)}</td>"
            f"<td>{s.get('audited_ok', 0)}</td></tr>")
    return (f"<html><head><title>Campaigns</title><style>{STYLE}</style>"
            f"</head><body><h1>Fault-injection campaigns</h1>"
            f"<p><a href='/'>home</a></p>{_HEALTH_STRIP}<table>"
            f"<tr><th>campaign</th><th>ok</th><th>skipped</th>"
            f"<th>failed</th><th>violations detected</th>"
            f"<th>audited ok</th></tr>{''.join(rows)}</table>"
            f"</body></html>")


def campaign_html(base: str, cid: str) -> str:
    """One campaign as a family × nemesis grid: every executed cell is
    colored by its verdict and links to its run directory; skipped
    cells show their reason inline."""
    c = _load_campaign(base, cid)
    if c is None:
        return (f"<html><body>campaign {html.escape(cid)} has no "
                f"readable campaign.json</body></html>")
    cells = c.get("cells") or []
    fams = sorted({x["family"] for x in cells})
    nems = []
    for x in cells:
        if x["nemesis"] not in nems:
            nems.append(x["nemesis"])

    def cell_td(outs: list) -> str:
        parts = []
        for o in outs:
            label = "seeded: " if o.get("seeded") else ""
            # phase-time tooltip (cells.jsonl "phases"): slow cells are
            # diagnosable from the grid without rerunning them
            ph = o.get("phases") or {}
            tip = " · ".join(f"{k} {v}s" for k, v in ph.items())
            title = f' title="{html.escape(tip)}"' if tip else ""
            if o.get("status") == "ok":
                cls = {True: "valid-true",
                       False: "valid-false"}.get(o.get("valid"),
                                                 "valid-unknown")
                body = f"{label}{o.get('valid')}"
                det = o.get("detection") or {}
                if det.get("latency_s") is not None:
                    # the detection GRADE: streamed = the live verdict
                    # flipped mid-run (an online cut or the :info
                    # lookahead fork); finalize = only the close
                    # confirmed it (post-hoc marks model-less
                    # families, whose only close is the batch checker)
                    at = det.get("at") or "streamed"
                    if det.get("source") == "post-hoc":
                        at += "/post-hoc"
                    body += f" (detected in {det['latency_s']}s, {at})"
                elif det.get("at"):
                    at = det["at"]
                    if det.get("source") == "post-hoc":
                        at += "/post-hoc"
                    body += f" (detected at {at})"
                if (o.get("watchdog") or {}).get("fired"):
                    body += " [watchdog]"
                if o.get("attempts", 1) > 1:
                    body += f" [attempt {o['attempts']}]"
                rel = o.get("store")
                if rel:
                    # store paths are absolute-or-relative to the base;
                    # link via /files using the run's name/time suffix
                    tail = "/".join(str(rel).split(os.sep)[-2:])
                    body = (f'<a href="/files/{urllib.parse.quote(tail)}'
                            f'/">{html.escape(body)}</a>')
                parts.append(f'<div class="{cls}"{title}>{body}</div>')
            else:
                reason = html.escape(str(o.get("reason") or ""))
                parts.append(f'<div class="valid-unknown"{title}>'
                             f"{label}{o.get('status')}"
                             f"<br><small>{reason}</small></div>")
        return f"<td>{''.join(parts)}</td>"

    rows = []
    for f in fams:
        tds = []
        for n in nems:
            outs = [x for x in cells
                    if x["family"] == f and x["nemesis"] == n]
            tds.append(cell_td(outs))
        rows.append(f"<tr><th>{html.escape(f)}</th>{''.join(tds)}</tr>")
    s = c.get("summary") or {}
    return (f"<html><head><title>{html.escape(cid)}</title>"
            f"<style>{STYLE}</style></head><body>"
            f"<h1>campaign {html.escape(cid)}</h1>"
            f"<p><a href='/campaigns'>campaigns</a> | "
            f"<a href='/'>home</a></p>"
            f"<p>{s.get('ok', 0)} ok, {s.get('skipped', 0)} skipped, "
            f"{s.get('failed', 0)} failed — "
            f"{s.get('detected', 0)} violation(s) detected"
            f" ({s.get('streamed_detections', 0)} streamed), "
            f"{s.get('audited_ok', 0)} cell(s) audited ok</p>"
            f"<table><tr><th>family \\ nemesis</th>"
            + "".join(f"<th>{html.escape(n)}</th>" for n in nems)
            + f"</tr>{''.join(rows)}</table></body></html>")


#: unicode eighth-blocks for the depth/occupancy sparkline
_SPARK = "▁▂▃▄▅▆▇█"


def _occupancy_sparkline(st: dict, width: int = 60) -> str:
    """Frontier occupancy per BFS level as a text sparkline — the
    search's depth profile at a glance (``search_telemetry.per_level``
    col 0; empty string when the block carries no per-level rows)."""
    per = st.get("per_level")
    cols = st.get("per_level_columns") or []
    try:
        occ_i = cols.index("occupancy")
    except ValueError:
        occ_i = 0
    if not isinstance(per, list) or not per:
        return ""
    try:
        occ = [int(r[occ_i]) for r in per]
    except (TypeError, ValueError, IndexError):
        return ""
    if len(occ) > width:
        # fixed-stride downsample keeping the max of each window (a
        # spike is the interesting part of a depth profile)
        step = len(occ) / width
        occ = [max(occ[int(i * step):max(int(i * step) + 1,
                                         int((i + 1) * step))])
               for i in range(width)]
    hi = max(occ) or 1
    return ("".join(_SPARK[min(len(_SPARK) - 1,
                               (v * len(_SPARK)) // (hi + 1))]
                    for v in occ)
            + f"  ({len(per)} level(s), peak {hi})")


def result_block(result: dict) -> str:
    """The verdict panel for a run's result page: validity, engine,
    certificate summary, the static search plan when the result carries
    one (``--explain``), and the audit/shrink outcomes when present —
    so a browsing human sees WHY a verdict is trustworthy, not just
    what it was."""
    valid = result.get("valid")
    cls = {True: "valid-true", False: "valid-false",
           "unknown": "valid-unknown"}.get(valid, "")
    rows = [("valid", valid), ("engine", result.get("engine")),
            ("configs", result.get("configs"))]
    lin = result.get("linearization")
    if lin is not None:
        rows.append(("certificate",
                     f"linearization witness, {len(lin)} ops"))
    elif result.get("witness_dropped"):
        rows.append(("certificate",
                     f"witness dropped: {result['witness_dropped']}"))
    if result.get("hb_cycle") is not None:
        cyc = result["hb_cycle"]
        rows.append(("certificate",
                     f"HB cycle, {len(cyc)} forced edge(s): "
                     + " -> ".join(str(e.get("src")) for e in cyc[:6])
                     + " -> ..."))
    if result.get("queue_cycle") is not None:
        cyc = result["queue_cycle"]
        rows.append(("certificate",
                     f"queue order cycle, {len(cyc)} forced edge(s): "
                     + " -> ".join(f"{e.get('src')}[{e.get('kind')}]"
                                   for e in cyc[:6])))
    if result.get("queue_dup") is not None:
        dup = result["queue_dup"]
        rows.append(("certificate",
                     f"duplicate delivery: {len(dup.get('dequeues', ()))}"
                     f" dequeue(s) over "
                     f"{len(dup.get('enqueues', ()))} enqueue row(s)"))
    qe = result.get("queue_evidence")
    if isinstance(qe, dict):
        rows.append(("certificate",
                     f"{qe.get('kind')}: values "
                     f"{qe.get('values', [])[:6]} at event(s) "
                     f"{qe.get('rows', [])[:6]}"))
    if result.get("final_ops") is not None:
        rows.append(("blocking frontier",
                     f"{len(result['final_ops'])} ops "
                     f"{result['final_ops'][:10]}"))
    elif result.get("frontier_dropped"):
        rows.append(("blocking frontier",
                     f"dropped: {result['frontier_dropped']}"))
    hbs = result.get("hb")
    if isinstance(hbs, dict) and hbs.get("applies"):
        if hbs.get("decided") is not None:
            rows.append(("happens-before",
                         f"decided statically ({hbs.get('reason')}, "
                         f"no search)"))
        else:
            rows.append(("happens-before",
                         f"{hbs.get('must_edges', 0)} must-order "
                         f"edge(s) pruned the search "
                         f"{hbs.get('edges')}"))
    cs = result.get("constraints")
    if isinstance(cs, dict) and cs.get("applies"):
        if cs.get("decided") is not None:
            rows.append(("constraints",
                         f"[{cs.get('family')}] decided statically "
                         f"({cs.get('reason')}, no search)"))
        else:
            rows.append(("constraints",
                         f"[{cs.get('family')}] "
                         f"{cs.get('must_edges', 0)} must-order "
                         f"edge(s) pruned the search "
                         f"{cs.get('edges')}"))
    dp = result.get("dpor")
    if isinstance(dp, dict) and dp.get("enabled"):
        bits = []
        if dp.get("sleep_prunes"):
            bits.append(f"{dp['sleep_prunes']} sleep-set prune(s)")
        if dp.get("dedup_rewrites"):
            bits.append(f"{dp['dedup_rewrites']} dead-state "
                        f"rewrite(s), {dp.get('dedup_hits', 0)} "
                        f"frontier-dedup hit(s)")
        if dp.get("mask_lanes_killed") or dp.get("mask_skips"):
            bits.append(f"{dp.get('mask_lanes_killed') or dp.get('mask_skips')} "
                        f"mask-killed candidate(s)")
        if dp.get("device_masked"):
            bits.append(f"{dp.get('device_mask_rows', 0)} device-"
                        f"masked row(s)")
        rows.append(("dpor", "; ".join(bits) if bits
                     else "on (nothing to prune here)"))
    st = result.get("search_telemetry")
    if isinstance(st, dict):
        # the observed twin of the hb/dpor PREDICTED rows above: what
        # the device kernel actually did, level by level
        obs_r = st.get("observed_prune_ratio")
        pred = st.get("predicted_prune_ratio")
        line = (f"{st.get('levels', 0)} level(s) / "
                f"{st.get('slices', 0)} slice(s), max occupancy "
                f"{st.get('max_occupancy', 0)}; expanded "
                f"{st.get('expanded', 0)}, mask-killed "
                f"{st.get('mask_killed', 0)}, dedup-folded "
                f"{st.get('dedup_folds', 0)}")
        if obs_r is not None:
            line += f"; observed prune ratio {obs_r}"
            if pred is not None:
                line += (f" vs predicted {pred} "
                         f"(delta {st.get('prune_ratio_delta')})")
        if st.get("truncated"):
            line += " [per-level rows truncated]"
        rows.append(("device telemetry", line))
        spark = _occupancy_sparkline(st)
        if spark:
            rows.append(("depth/occupancy", spark))
    shb = result.get("shard_batch")
    if isinstance(shb, dict):
        # the mesh scheduler's padding story: tight per-bucket shapes
        # vs the fused single-shape counterfactual
        line = (f"{shb.get('n_buckets', 0)} bucket(s) over "
                f"{shb.get('n_devices', 0)} device(s), padding "
                f"efficiency {shb.get('padding_efficiency')}"
                f" (fused counterfactual "
                f"{shb.get('fused_padding_efficiency')}); "
                f"{shb.get('pad_keys', 0)} inert mesh pad lane(s)")
        if shb.get("overflow_redo"):
            line += f", {shb['overflow_redo']} overflow redo(s)"
        if shb.get("shard_map") is False:
            line += " [GSPMD fallback]"
        rows.append(("sharded batch", line))
    a = result.get("audit")
    if a:
        rows.append(("audit", "ok (checked %s)" % a.get("checked")
                     if a.get("ok")
                     else "FAILED: %s" % ", ".join(a.get("codes", []))))
    sh = result.get("shrink")
    if sh:
        bf = {True: "brute-force says VALID (divergence!)",
              False: "brute-force confirmed",
              None: "unconfirmed (too large)"}.get(sh.get("brute_force"))
        rows.append(("minimal counterexample",
                     f"{sh.get('n_from')} ops -> {sh.get('n_to')} "
                     f"({bf})"))
    sm = result.get("stream")
    if isinstance(sm, dict):
        # the streamed verdict next to the authoritative one: a run
        # result's "stream" is the service summary (stats nested), a
        # raw streamed result carries the stats dict directly
        st = sm.get("stream") if isinstance(sm.get("stream"), dict) \
            else sm
        rows.append(("streamed",
                     f"{sm.get('valid', st.get('valid'))} after "
                     f"{st.get('segments')} segment(s) / "
                     f"{st.get('events')} events; first verdict at "
                     f"event {st.get('first_verdict_event')}"))
    # verdict-cache reuse counters (decomposed or streamed route):
    # segment-level reuse across runs and fleets, measured not inferred
    for src in (result.get("decompose"),
                (result.get("stream") or {}).get("stream")
                if isinstance(result.get("stream"), dict) else None,
                result.get("stream")):
        if isinstance(src, dict) and "cache_hits" in src:
            rows.append(("verdict cache",
                         f"{src['cache_hits']} hits / "
                         f"{src['cache_misses']} misses / "
                         f"{src.get('cache_inserts', 0)} inserts"))
            break
    body = "".join(f"<tr><th>{html.escape(str(k))}</th>"
                   f"<td>{html.escape(str(v))}</td></tr>"
                   for k, v in rows)
    out = (f'<table class="{cls}"><caption>result</caption>{body}'
           f"</table>")
    plan = result.get("explain")
    if isinstance(plan, dict):
        # the plan block next to the verdict: dims, bucket, engine
        # route, decomposition applicability — analyze.plan's renderer
        # is the ONE formatter, here as everywhere
        try:
            from .analyze.plan import render_plan

            out += f"<h3>Search plan</h3><pre>" \
                   f"{html.escape(render_plan(plan))}</pre>"
        except Exception:  # noqa: BLE001 — a malformed stored plan
            pass           # must not take down the results page
    if sh:
        # the ONE shrink renderer, shared with linear.html — the two
        # surfaces must tell the same failure story
        from .checker.linear_report import shrink_block

        out += shrink_block(result)
    return out


#: nested result fields worth a panel of their own
_EVIDENCE = ("linearization", "witness_dropped", "final_ops",
             "frontier_dropped", "hb_cycle", "queue_cycle",
             "queue_dup", "queue_evidence", "explain", "audit",
             "shrink")


def _evidence_results(result: dict, *, max_depth: int = 5,
                      max_panels: int = 24):
    """(path, sub-result) pairs for nested verdicts carrying evidence,
    depth-first, bounded so a huge independent-key run cannot render
    an unbounded page."""
    out: list = []

    def walk(d: dict, path: str, depth: int) -> None:
        if depth > max_depth or len(out) >= max_panels:
            return
        for name, sub in d.items():
            if not isinstance(sub, dict):
                continue
            p = f"{path}/{name}" if path else str(name)
            if "valid" in sub and any(k in sub for k in _EVIDENCE):
                out.append((p, sub))
                if len(out) >= max_panels:
                    return
            walk(sub, p, depth + 1)

    walk(result, "", 0)
    return out


def _load_result(d: str) -> dict | None:
    p = os.path.join(d, "results.json")
    try:
        with open(p) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except Exception:
        return None


def live_panel(rel: str) -> str:
    """The live-verdict panel for a run directory holding a
    ``live.json`` snapshot (written by the streaming op sink,
    stream/checker.py): a status strip polled from ``/api/live/<run>``
    every 2s until the stream finalizes."""
    api = "/api/live/" + urllib.parse.quote(rel.rstrip("/"))
    return f"""
<div id="live-panel"><h3>Live verdict</h3>
<p id="live-status">loading…</p><pre id="live-json"></pre></div>
<script>
const CLS = {{"valid-so-far": "valid-true", "invalid": "valid-false",
             "open": "valid-unknown"}};
async function pollLive() {{
  let done = false;
  try {{
    const r = await fetch({json.dumps(api)});
    if (r.ok) {{
      const d = await r.json();
      const el = document.getElementById("live-status");
      el.textContent = d.status + " — " + d.events + " events, "
        + d.segments_closed + " segments closed, "
        + d.checked_rows + "/" + d.rows + " rows checked"
        + (d.final ? " — FINAL: " + d.final.valid : "");
      el.className = CLS[d.status] || "";
      document.getElementById("live-json").textContent =
        JSON.stringify(d, null, 1);
      done = !!d.final;
    }}
  }} catch (e) {{}}
  if (!done) setTimeout(pollLive, 2000);
}}
pollLive();
</script>"""


def trace_panel(rel: str) -> str:
    """The zoomable flight-recorder timeline for a run directory
    holding a ``trace.json`` (written by ``--trace`` runs): spans drawn
    per thread track, colored by category, wheel-zoom + drag-pan, span
    details on hover.  The same file loads in Perfetto for the full
    treatment — this panel is the no-tools-needed first look."""
    src = "/files/" + urllib.parse.quote(rel.rstrip("/")) + "/trace.json"
    return f"""
<div id="trace-panel"><h3>Trace timeline</h3>
<p><a href="{src}">trace.json</a> — open in
<a href="https://ui.perfetto.dev">Perfetto</a> for the full UI.
Scroll to zoom, drag to pan.</p>
<canvas id="trace-c" height="240"
        style="border:1px solid #ccc;width:100%"></canvas>
<div id="trace-hover" style="font-family:monospace">&nbsp;</div>
<script>
(async () => {{
  const r = await fetch({json.dumps(src)});
  if (!r.ok) return;
  const tr = await r.json();
  const evs = (tr.traceEvents || []).filter(e => e.ph === "X");
  if (!evs.length) return;
  const names = {{}};
  for (const e of tr.traceEvents)
    if (e.ph === "M" && e.name === "thread_name")
      names[e.tid] = e.args.name;
  const tids = [...new Set(evs.map(e => e.tid))].sort((a,b) => a-b);
  // reduce, not Math.min(...spread): a full 65k-span ring buffer
  // would blow the engine's argument limit and blank the panel
  let t0 = Infinity, t1 = -Infinity;
  for (const e of evs) {{
    if (e.ts < t0) t0 = e.ts;
    const end = e.ts + (e.dur || 0);
    if (end > t1) t1 = end;
  }}
  const c = document.getElementById("trace-c");
  c.width = c.clientWidth; const W = c.width, LANE = 22, PAD = 110;
  c.height = tids.length * LANE + 20;
  const ctx = c.getContext("2d");
  const color = cat => {{
    let h = 0; for (const ch of (cat || "")) h = (h * 31 + ch.charCodeAt(0)) % 360;
    return `hsl(${{h}},60%,60%)`;
  }};
  let view = [t0, Math.max(t1, t0 + 1)];
  function draw() {{
    ctx.clearRect(0, 0, W, c.height);
    const [v0, v1] = view, sc = (W - PAD) / (v1 - v0);
    ctx.font = "10px monospace"; ctx.fillStyle = "#333";
    tids.forEach((t, i) => ctx.fillText(
      (names[t] || ("tid " + t)).slice(0, 16), 2, i * LANE + 14));
    for (const e of evs) {{
      const x = PAD + (e.ts - v0) * sc,
            w = Math.max(1, (e.dur || 0) * sc),
            y = tids.indexOf(e.tid) * LANE + 4;
      if (x + w < PAD || x > W) continue;
      const cx = Math.max(PAD, x);
      ctx.fillStyle = color(e.cat);
      ctx.fillRect(cx, y, w - (cx - x), LANE - 8);
    }}
  }}
  c.addEventListener("wheel", ev => {{
    ev.preventDefault();
    const [v0, v1] = view, span = v1 - v0,
          fx = (ev.offsetX - PAD) / (W - PAD),
          at = v0 + fx * span,
          f = ev.deltaY > 0 ? 1.25 : 0.8;
    view = [at - (at - v0) * f, at + (v1 - at) * f]; draw();
  }});
  let drag = null;
  c.addEventListener("mousedown", ev => drag = ev.offsetX);
  c.addEventListener("mouseup", () => drag = null);
  c.addEventListener("mousemove", ev => {{
    const [v0, v1] = view, sc = (W - PAD) / (v1 - v0);
    if (drag !== null) {{
      const dt = (drag - ev.offsetX) / sc;
      view = [v0 + dt, v1 + dt]; drag = ev.offsetX; draw(); return;
    }}
    const t = v0 + (ev.offsetX - PAD) / sc,
          lane = Math.floor(ev.offsetY / LANE), tid = tids[lane];
    const hit = evs.find(e => e.tid === tid && e.ts <= t
                              && t <= e.ts + (e.dur || 0));
    document.getElementById("trace-hover").textContent = hit
      ? hit.name + " [" + hit.cat + "] "
        + ((hit.dur || 0) / 1000).toFixed(3) + " ms "
        + JSON.stringify(hit.args || {{}})
      : "\\u00a0";
  }});
  draw();
}})();
</script></div>"""


def dir_html(base: str, rel: str) -> str:
    """Directory browser (web.clj:194-248); run directories (those
    holding a results.json) get the result panel on top, a live
    streaming run (live.json present) its auto-refreshing verdict, and
    a traced run (trace.json present) the flight-recorder timeline."""
    d = os.path.join(base, rel)
    entries = sorted(os.listdir(d))
    items = []
    for e in entries:
        q = urllib.parse.quote(e)
        full = os.path.join(d, e)
        suffix = "/" if os.path.isdir(full) else ""
        items.append(f'<li><a href="{q}{suffix}">{html.escape(e)}{suffix}'
                     f"</a></li>")
    block = ""
    if os.path.isfile(os.path.join(d, "live.json")):
        block += live_panel(rel)
    if os.path.isfile(os.path.join(d, "trace.json")):
        block += trace_panel(rel)
    result = _load_result(d)
    if result is not None:
        # composed checkers nest per-checker (and per-key) results
        # arbitrarily deep ({"workload": {"results": {0: {"linear":
        # ...}}}}): render the top-level verdict plus every nested
        # verdict that carries certificate/plan/audit/shrink evidence
        block += result_block(result)
        for path, sub in _evidence_results(result):
            block += (f"<h2>{html.escape(path)}</h2>"
                      + result_block(sub))
    return (f"<html><head><style>{STYLE}</style></head><body>"
            f"<h1>{html.escape(rel)}</h1><p><a href='/'>home</a> | "
            f"<a href='?zip'>zip</a></p>{block}<ul>{''.join(items)}</ul>"
            f"</body></html>")


class _CountingWriter(io.RawIOBase):
    """File-like adapter over a socket stream for ZipFile: zipfile needs
    ``write`` and ``tell`` (for central-directory offsets); everything
    goes straight to the wire, nothing is buffered."""

    def __init__(self, sink):
        self._sink = sink
        self._pos = 0

    def writable(self):
        return True

    def write(self, b):
        self._sink.write(b)
        self._pos += len(b)
        return len(b)

    def tell(self):
        return self._pos


def write_zip(sink, base: str, rel: str, *, chunk: int = 1 << 20) -> None:
    """Stream a run directory as a zip straight into ``sink`` — the
    reference streams its zips too (web.clj:250-292); buffering a
    multi-GB run dir in memory is not an option.  Files are copied in
    ``chunk``-sized pieces through ``ZipFile.open(..., "w")``."""
    d = os.path.join(base, rel)
    with zipfile.ZipFile(_CountingWriter(sink), "w",
                         zipfile.ZIP_DEFLATED) as z:
        for root, _dirs, files in os.walk(d):
            _dirs.sort()  # deterministic archive order
            for f in sorted(files):
                full = os.path.join(root, f)
                arc = os.path.relpath(full, d)
                try:
                    zi = zipfile.ZipInfo.from_file(full, arc)
                    src = open(full, "rb")
                except OSError:
                    # a live run dir can rotate files between walk and
                    # stat/open; skip rather than abort the download
                    log.warning("zip: skipping vanished file %s", full)
                    continue
                # ZipFile.open honors the ZipInfo's compress_type (which
                # from_file defaults to STORED), not the constructor's
                zi.compress_type = zipfile.ZIP_DEFLATED
                with src, z.open(zi, "w") as dst:
                    shutil.copyfileobj(src, dst, chunk)




CONTENT_TYPES = {".html": "text/html", ".txt": "text/plain",
                 ".log": "text/plain", ".json": "application/json",
                 ".jsonl": "text/plain", ".edn": "text/plain",
                 ".png": "image/png", ".svg": "image/svg+xml",
                 ".jpg": "image/jpeg"}


class Handler(BaseHTTPRequestHandler):
    base = store.BASE

    def log_message(self, fmt, *args):  # quiet
        log.debug("web: " + fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str = "text/html",
              extra: dict | None = None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API)
        parsed = urllib.parse.urlparse(self.path)
        path = urllib.parse.unquote(parsed.path)
        if path == "/":
            self._send(200, home_html(self.base).encode())
            return
        if path == "/campaigns" or path == "/campaigns/":
            self._send(200, campaigns_html(self.base).encode())
            return
        if path == "/mc" or path == "/mc/":
            q = urllib.parse.parse_qs(parsed.query or "")
            refresh = q.get("refresh", ["0"])[0] == "1"
            scope = q.get("scope", ["core"])[0]
            self._send(200,
                       mc_html(refresh=refresh, scope=scope).encode(),
                       extra={"Cache-Control": "no-store"})
            return
        if path == "/metrics":
            # the flight recorder's Prometheus scrape surface: this
            # process's registry (point your scraper at the runner /
            # stream-service process for fleet counters)
            self._send(200, obs_metrics.render().encode(),
                       "text/plain; version=0.0.4; charset=utf-8",
                       extra={"Cache-Control": "no-store"})
            return
        if path == "/api/stats":
            # the JSON twin: raw metric values + derived ratios (cache
            # hit ratio, padding efficiency), polled by /campaigns
            self._send(200, json.dumps(obs_metrics.snapshot()).encode(),
                       "application/json",
                       extra={"Cache-Control": "no-store"})
            return
        if path.startswith("/campaigns/"):
            cid = os.path.normpath(
                path[len("/campaigns/"):]).lstrip("/")
            if cid.startswith("..") or "/" in cid:
                self._send(403, b"forbidden", "text/plain")
                return
            self._send(200, campaign_html(self.base, cid).encode())
            return
        if path.startswith("/api/live/"):
            # the live provisional verdict of a (possibly running)
            # streamed test: the op sink rewrites live.json atomically
            # as the stream moves (stream/checker.py), so this is a
            # plain read — no coordination with the runner process
            rel = os.path.normpath(path[len("/api/live/"):]).lstrip("/")
            if rel.startswith(".."):
                self._send(403, b"forbidden", "text/plain")
                return
            p = os.path.join(self.base, rel, "live.json")
            try:
                with open(p, "rb") as f:
                    body = f.read()
            except OSError:
                self._send(404, b'{"error": "no live stream"}',
                           "application/json")
                return
            self._send(200, body, "application/json",
                       extra={"Cache-Control": "no-store"})
            return
        if not path.startswith("/files/"):
            self._send(404, b"not found", "text/plain")
            return
        rel = os.path.normpath(path[len("/files/"):]).lstrip("/")
        if rel.startswith(".."):
            self._send(403, b"forbidden", "text/plain")
            return
        full = os.path.join(self.base, rel)
        if parsed.query == "zip" and os.path.isdir(full):
            name = rel.replace("/", "-") + ".zip"
            # streamed: no Content-Length; the body is delimited by
            # connection close, which REQUIRES the handler to stay on
            # HTTP/1.0 (BaseHTTPRequestHandler's default) — with
            # keep-alive the client could not tell where the zip ends
            assert self.protocol_version == "HTTP/1.0", \
                "streamed zip framing relies on close-delimited bodies"
            self.send_response(200)
            self.send_header("Content-Type", "application/zip")
            self.send_header("Content-Disposition",
                             f'attachment; filename="{name}"')
            self.end_headers()
            try:
                write_zip(self.wfile, self.base, rel)
            except (BrokenPipeError, ConnectionResetError):
                log.debug("zip: client dropped the connection")
            except Exception:  # noqa: BLE001 — status already sent: the
                # archive is truncated/corrupt; sabotage the framing by
                # closing mid-stream and say so (a zlib or read error
                # here must not masquerade as a clean 200)
                log.warning("zip: stream aborted mid-archive for %r",
                            rel, exc_info=True)
            return
        if os.path.isdir(full):
            self._send(200, dir_html(self.base, rel).encode())
            return
        if os.path.isfile(full):
            ext = os.path.splitext(full)[1]
            ctype = CONTENT_TYPES.get(ext, "application/octet-stream")
            with open(full, "rb") as f:
                self._send(200, f.read(), ctype)
            return
        self._send(404, b"not found", "text/plain")


def make_server(host: str = "0.0.0.0", port: int = 8080,
                base: str | None = None) -> ThreadingHTTPServer:
    handler = type("H", (Handler,), {"base": base or store.BASE})
    return ThreadingHTTPServer((host, port), handler)


def serve(host: str = "0.0.0.0", port: int = 8080,
          base: str | None = None) -> None:
    """web.clj:322-335."""
    srv = make_server(host, port, base)
    log.info("Web server running on http://%s:%d", host, port)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.shutdown()
