"""Consistency models — knossos.model equivalents, numeric from the start.

The reference delegates model semantics to knossos.model (used from
jepsen/src/jepsen/checker.clj:15-21 and suites passim): ``register``,
``cas-register``, ``mutex``, ``noop``, each a pure ``step(model, op) ->
model' | inconsistent`` function over immutable state.

Here each model is a :class:`ModelSpec` whose state is a fixed-width tuple
of int32 lanes, with TWO step implementations kept adjacent and
differential-tested (tests/test_models.py):

  * ``pystep`` — plain Python, used by the sequential oracle checker and by
    witness reconstruction;
  * ``jstep``  — a jit-able JAX kernel ``(state[w], f, v1, v2) ->
    (state'[w], legal)``, compiled into the TPU frontier search.

Fixed-width int state is a deliberate design constraint: the TPU engine
packs millions of model states into dense device arrays; anything that
cannot be encoded in a few int32 lanes (unbounded sets/queues) gets a
bounded-capacity encoding or stays host-side (SURVEY.md §7 "hashing model
states on TPU").

Values are pre-encoded to int32 by history.ValueEncoder; ``NIL`` means
"unknown value" (e.g. a read whose invocation hasn't been filled in), which
per knossos.model semantics is always legal and does not change state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

import jax.numpy as jnp

from ..history import NIL

State = Tuple[int, ...]


@dataclass(frozen=True)
class ModelSpec:
    """A consistency model over fixed-width integer state.

    f_codes maps op :f names to the integer codes both step functions
    dispatch on.  ``init`` is the initial state tuple.
    """

    name: str
    f_codes: dict
    state_width: int
    init: State
    pystep: Callable[[State, int, int, int], Optional[State]]
    # jstep(state: int32[w], f: int32, v1: int32, v2: int32)
    #   -> (state': int32[w], legal: bool)
    jstep: Callable
    doc: str = ""

    def step(self, state: State, f: str, value) -> Optional[State]:
        """Convenience: step by f-name with raw int/tuple value (tests)."""
        code = self.f_codes[f]
        if isinstance(value, (tuple, list)):
            v1, v2 = value
        else:
            v1, v2 = (NIL if value is None else value), NIL
        return self.pystep(state, code, v1, v2)


# ---------------------------------------------------------------------------
# register — a single read/write register (knossos.model/register)
# ---------------------------------------------------------------------------

R_READ, R_WRITE, R_CAS = 0, 1, 2


def _register_pystep(state, f, v1, v2):
    (val,) = state
    if f == R_READ:
        return state if (v1 == NIL or v1 == val) else None
    if f == R_WRITE:
        return (v1,)
    raise ValueError(f"register: bad f code {f}")


def _register_jstep(state, f, v1, v2):
    val = state[0]
    is_read = f == R_READ
    legal = jnp.where(is_read, (v1 == NIL) | (v1 == val), True)
    new_val = jnp.where(f == R_WRITE, v1, val)
    return jnp.stack([new_val]), legal


def register(initial: int = 0) -> ModelSpec:
    """A read/write register holding one int (knossos.model/register)."""
    return ModelSpec(
        name="register",
        f_codes={"read": R_READ, "write": R_WRITE},
        state_width=1,
        init=(initial,),
        pystep=_register_pystep,
        jstep=_register_jstep,
        doc="single int register; read legal iff value unknown or equal",
    )


# ---------------------------------------------------------------------------
# cas-register — read/write/compare-and-set (knossos.model/cas-register)
# The workhorse of the reference's suites: etcdemo (jepsen.etcdemo:171-185),
# zookeeper (zookeeper.clj:127-129), etcd, consul, cockroach register, ...
# ---------------------------------------------------------------------------


def _cas_register_pystep(state, f, v1, v2):
    (val,) = state
    if f == R_READ:
        return state if (v1 == NIL or v1 == val) else None
    if f == R_WRITE:
        return (v1,)
    if f == R_CAS:
        return (v2,) if val == v1 else None
    raise ValueError(f"cas-register: bad f code {f}")


def _cas_register_jstep(state, f, v1, v2):
    val = state[0]
    read_legal = (v1 == NIL) | (v1 == val)
    cas_legal = v1 == val
    legal = jnp.where(f == R_READ, read_legal,
                      jnp.where(f == R_CAS, cas_legal, True))
    new_val = jnp.where(f == R_WRITE, v1,
                        jnp.where((f == R_CAS) & cas_legal, v2, val))
    return jnp.stack([new_val]), legal


def cas_register(initial: int = NIL) -> ModelSpec:
    """Read/write/cas register.  ``cas`` takes value [expected, new].

    Default initial state is NIL (an unset register), matching
    knossos.model/cas-register with a nil initial value — a read of NIL is
    then only legal as an unknown-value read.
    """
    return ModelSpec(
        name="cas-register",
        f_codes={"read": R_READ, "write": R_WRITE, "cas": R_CAS},
        state_width=1,
        init=(initial,),
        pystep=_cas_register_pystep,
        jstep=_cas_register_jstep,
        doc="int register with compare-and-set",
    )


# ---------------------------------------------------------------------------
# mutex — a single lock (knossos.model/mutex); checked linearizable by the
# hazelcast suite's lock workload (hazelcast.clj:379-386).
# ---------------------------------------------------------------------------

M_ACQUIRE, M_RELEASE = 0, 1


def _mutex_pystep(state, f, v1, v2):
    (locked,) = state
    if f == M_ACQUIRE:
        return (1,) if not locked else None
    if f == M_RELEASE:
        return (0,) if locked else None
    raise ValueError(f"mutex: bad f code {f}")


def _mutex_jstep(state, f, v1, v2):
    locked = state[0]
    legal = jnp.where(f == M_ACQUIRE, locked == 0, locked == 1)
    new_locked = jnp.where(f == M_ACQUIRE, 1, 0)
    return jnp.stack([jnp.where(legal, new_locked, locked)]), legal


def mutex() -> ModelSpec:
    return ModelSpec(
        name="mutex",
        f_codes={"acquire": M_ACQUIRE, "release": M_RELEASE},
        state_width=1,
        init=(0,),
        pystep=_mutex_pystep,
        jstep=_mutex_jstep,
        doc="single lock; acquire legal iff free, release legal iff held",
    )


# ---------------------------------------------------------------------------
# noop — everything is legal (knossos.model/noop; jepsen.tests/noop-test)
# ---------------------------------------------------------------------------


def _noop_pystep(state, f, v1, v2):
    return state


def _noop_jstep(state, f, v1, v2):
    return state, jnp.bool_(True)


class _AnyFCodes(dict):
    """f_codes table accepting every f name (all map to code 0), so the
    noop model really does admit arbitrary histories through encode_ops."""

    def __contains__(self, key):  # noqa: D105
        return True

    def __getitem__(self, key):
        return super().get(key, 0)

    def __missing__(self, key):
        return 0


def noop() -> ModelSpec:
    return ModelSpec(
        name="noop", f_codes=_AnyFCodes(), state_width=1, init=(0,),
        pystep=_noop_pystep, jstep=_noop_jstep,
        doc="accepts every operation",
    )


# ---------------------------------------------------------------------------
# multi-register — k independent registers in one object
# (knossos.model/multi-register); reads/writes take [key value].
# ---------------------------------------------------------------------------


def multi_register(width: int, initial: int = 0) -> ModelSpec:
    """`width` registers; f value lanes are (key, value)."""

    def pystep(state, f, v1, v2):
        key = v1
        if key == NIL or not (0 <= key < width):
            return None
        if f == R_READ:
            return state if (v2 == NIL or v2 == state[key]) else None
        if f == R_WRITE:
            s = list(state)
            s[key] = v2
            return tuple(s)
        raise ValueError(f"multi-register: bad f code {f}")

    def jstep(state, f, v1, v2):
        key = jnp.clip(v1, 0, width - 1)
        in_range = (v1 >= 0) & (v1 < width)
        cur = state[key]
        read_legal = in_range & ((v2 == NIL) | (v2 == cur))
        legal = jnp.where(f == R_READ, read_legal, in_range)
        # illegal steps must leave state unchanged (the engine relies on it)
        new_state = jnp.where((f == R_WRITE) & in_range,
                              state.at[key].set(v2), state)
        return new_state, legal

    return ModelSpec(
        name="multi-register",
        f_codes={"read": R_READ, "write": R_WRITE},
        state_width=width,
        init=(initial,) * width,
        pystep=pystep,
        jstep=jstep,
        doc=f"{width} independent registers addressed by (key, value) ops",
    )


# ---------------------------------------------------------------------------
# unordered-queue — a bounded multiset (knossos.model/unordered-queue);
# enqueue always adds, dequeue of v is legal iff v is present.  The
# reference checks queue workloads by model-reducing histories
# (checker.clj:141-147, disque.clj:305, rabbitmq_test.clj:55); this model
# additionally makes them *searchable* on device: the multiset state is a
# CAPACITY-lane sorted int32 array (SURVEY.md §7's "sorted-array encodings
# with capacity caps"), so equal multisets are bit-identical and the
# engine's exact dedup applies unchanged.
# ---------------------------------------------------------------------------

Q_ENQ, Q_DEQ = 0, 1

#: empty lane marker — sorts after every real value (encoded values are
#: small non-negative ints; 2**31-1 is reserved)
Q_EMPTY = 2**31 - 1


def _uq_pystep_factory(capacity: int):
    def pystep(state, f, v1, v2):
        if v1 == NIL:
            # an op with an unknown value (crashed invoke) constrains
            # nothing and changes nothing, matching the register models'
            # NIL convention
            return state
        if f == Q_ENQ:
            if state[capacity - 1] != Q_EMPTY:
                return None  # over capacity: size the model to the history
            s = sorted(state[:capacity - 1] + (v1,))
            return tuple(s) + (Q_EMPTY,) * (capacity - len(s))
        if f == Q_DEQ:
            if v1 not in state:
                return None
            s = list(state)
            s.remove(v1)
            return tuple(s) + (Q_EMPTY,)
        raise ValueError(f"unordered-queue: bad f code {f}")

    return pystep


def _uq_jstep_factory(capacity: int):
    def jstep(state, f, v1, v2):
        idx = jnp.arange(capacity)
        nil = v1 == NIL

        # enqueue: sorted insert at position cnt = |{i: state[i] <= v}|
        room = state[capacity - 1] == Q_EMPTY
        cnt = (state <= v1).sum()
        prev = jnp.roll(state, 1)  # prev[0] unused (idx 0 is < or == cnt)
        enq = jnp.where(idx < cnt, state,
                        jnp.where(idx == cnt, v1, prev))

        # dequeue: remove the first lane equal to v (duplicates keep one)
        eq = state == v1
        present = eq.any()
        m = jnp.argmax(eq)
        nxt = jnp.concatenate(
            [state[1:], jnp.full((1,), Q_EMPTY, state.dtype)])
        deq = jnp.where(idx < m, state, nxt)

        is_enq = f == Q_ENQ
        legal = jnp.where(nil, True, jnp.where(is_enq, room, present))
        new_state = jnp.where(
            nil | ~legal, state,
            jnp.where(is_enq, enq, deq))
        return new_state, legal

    return jstep


def unordered_queue(capacity: int = 16) -> ModelSpec:
    """Bounded unordered queue (multiset).  ``capacity`` must be at least
    the largest queue length any linearization of the history can reach
    (#enqueues is always a safe bound); an enqueue past capacity is
    treated as illegal, which would wrongly fail an over-capacity legal
    history — size generously."""
    return ModelSpec(
        name=f"unordered-queue-{capacity}",
        f_codes={"enqueue": Q_ENQ, "dequeue": Q_DEQ},
        state_width=capacity,
        init=(Q_EMPTY,) * capacity,
        pystep=_uq_pystep_factory(capacity),
        jstep=_uq_jstep_factory(capacity),
        doc="bounded multiset; dequeue legal iff the value is present",
    )


# ---------------------------------------------------------------------------
# fifo-queue — knossos.model/fifo-queue: dequeue must return the OLDEST
# element.  State is a left-aligned bounded ring (front at lane 0, empty
# lanes = Q_EMPTY): enqueue appends at the fill count, dequeue matches
# lane 0 and shifts left.  Left-alignment keeps the encoding canonical,
# so the engine's exact dedup applies unchanged.
# ---------------------------------------------------------------------------


def _fq_pystep_factory(capacity: int):
    def pystep(state, f, v1, v2):
        if v1 == NIL:
            return state
        if f == Q_ENQ:
            if state[capacity - 1] != Q_EMPTY:
                return None  # over capacity: size the model generously
            cnt = sum(1 for x in state if x != Q_EMPTY)
            return state[:cnt] + (v1,) + state[cnt + 1:]
        if f == Q_DEQ:
            if state[0] == Q_EMPTY or state[0] != v1:
                return None
            return state[1:] + (Q_EMPTY,)
        raise ValueError(f"fifo-queue: bad f code {f}")

    return pystep


def _fq_jstep_factory(capacity: int):
    def jstep(state, f, v1, v2):
        idx = jnp.arange(capacity)
        nil = v1 == NIL

        room = state[capacity - 1] == Q_EMPTY
        cnt = (state != Q_EMPTY).sum()
        enq = jnp.where(idx == cnt, v1, state)

        head_ok = (state[0] != Q_EMPTY) & (state[0] == v1)
        deq = jnp.concatenate(
            [state[1:], jnp.full((1,), Q_EMPTY, state.dtype)])

        is_enq = f == Q_ENQ
        legal = jnp.where(nil, True, jnp.where(is_enq, room, head_ok))
        new_state = jnp.where(
            nil | ~legal, state,
            jnp.where(is_enq, enq, deq))
        return new_state, legal

    return jstep


def fifo_queue(capacity: int = 16) -> ModelSpec:
    """Bounded FIFO queue; see `unordered_queue` for the capacity
    contract (an enqueue past capacity is treated as illegal)."""
    return ModelSpec(
        name=f"fifo-queue-{capacity}",
        f_codes={"enqueue": Q_ENQ, "dequeue": Q_DEQ},
        state_width=capacity,
        init=(Q_EMPTY,) * capacity,
        pystep=_fq_pystep_factory(capacity),
        jstep=_fq_jstep_factory(capacity),
        doc="bounded FIFO; dequeue legal iff it returns the oldest",
    )
