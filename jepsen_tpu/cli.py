"""Command-line toolkit (reference L8).

Reference: jepsen/src/jepsen/cli.clj.  Provides the subcommand framework
suites build their mains from: shared test options (test-opt-spec,
cli.clj:52-87 — --node/--nodes-file/--username/--password/--concurrency
"3n"/--time-limit/--test-count/--tarball), option post-processing
(parse-concurrency cli.clj:125-140, rename-ssh-options 159-174,
nodes-file 176-189), the exit-code contract (cli.clj:103-114):

  0    all tests passed
  1    some test failed
  254  invalid arguments
  255  internal error

and the stock subcommands: `test` (single-test-cmd, cli.clj:297-331,
honoring --test-count) and `serve` (cli.clj:280-295, the results web UI).
"""

from __future__ import annotations

import argparse
import logging
import os
import re
import sys
import traceback
from typing import Callable

log = logging.getLogger("jepsen")

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]

EXIT_OK = 0
EXIT_INVALID = 1
EXIT_BAD_ARGS = 254
EXIT_ERROR = 255


def one_of(coll) -> str:
    keys = sorted(coll.keys() if isinstance(coll, dict) else coll)
    return "Must be one of " + ", ".join(map(str, keys))


def add_test_opts(p: argparse.ArgumentParser) -> None:
    """The shared test option surface (cli.clj:52-87)."""
    p.add_argument("-n", "--node", action="append", dest="nodes",
                   metavar="HOSTNAME", default=None,
                   help="Node(s) to run the test on; repeatable.")
    p.add_argument("--nodes-file", metavar="FILENAME",
                   help="File with node hostnames, one per line.")
    p.add_argument("--username", default="root", help="Username for logins")
    p.add_argument("--password", default="root",
                   help="Password for sudo access")
    p.add_argument("--strict-host-key-checking", action="store_true",
                   default=False, help="Whether to check host keys")
    p.add_argument("--ssh-private-key", metavar="FILE",
                   help="Path to an SSH identity file")
    p.add_argument("--concurrency", default="1n",
                   help="Worker count; an integer, optionally followed by "
                        "n to multiply by the node count (e.g. 3n).")
    p.add_argument("--test-count", type=int, default=1,
                   help="How many times to repeat the test")
    p.add_argument("--time-limit", type=int, default=60,
                   help="Test duration excluding setup/teardown, seconds")
    p.add_argument("--dummy", action="store_true", default=False,
                   help="Use the dummy remote (no SSH; harness testing)")
    p.add_argument("--lin-decompose", action="store_true", default=False,
                   help="Run linearizability checks through the "
                        "P-compositional decomposition layer "
                        "(jepsen_tpu/decompose/): per-key/per-value "
                        "splits, quiescence cuts, and the persisted "
                        "canonical-hash verdict cache.  Verdict-"
                        "identical; sets JEPSEN_TPU_LIN_DECOMPOSE so "
                        "every suite-constructed checker honors it.")
    p.add_argument("--stream", action="store_true", default=False,
                   help="Check the history INCREMENTALLY while the "
                        "test runs (jepsen_tpu/stream/): an op sink "
                        "folds quiescence segments as they close, "
                        "serves a live provisional verdict "
                        "(web UI /api/live, store live.json), and "
                        "flags a violation seconds after it happens.  "
                        "Final verdicts are identical to the post-hoc "
                        "checker.  Sets JEPSEN_TPU_STREAM=1 fleet-"
                        "wide; JEPSEN_TPU_STREAM_CACHE points the "
                        "sink at a shared verdict cache ('store' for "
                        "the persisted default).")
    p.add_argument("--explain", action="store_true", default=False,
                   help="Print the static search PLAN instead of "
                        "running the linearizability search: SearchDims"
                        ", shape bucket, engine route, and which "
                        "decompositions apply "
                        "(jepsen_tpu.analyze.explain).  Sets "
                        "JEPSEN_TPU_EXPLAIN so every suite-constructed "
                        "Linearizable checker honors it; the verdict "
                        "reports as \"unknown\" with the plan attached.")
    p.add_argument("--trace", action="store_true", default=False,
                   help="Record flight-recorder spans for the whole "
                        "run (jepsen_tpu.obs): worker ops, nemesis "
                        "injections, bucket prep/device stages, "
                        "segment folds, checker phases — exported as "
                        "Chrome-trace/Perfetto JSON to the run's "
                        "store dir (trace.json; web UI timeline "
                        "panel, python -m jepsen_tpu.obs report).  "
                        "Sets JEPSEN_TPU_TRACE=1 fleet-wide; off "
                        "costs nothing.")
    p.add_argument("--no-lint", action="store_true", default=False,
                   help="Disable the history well-formedness linter "
                        "(jepsen_tpu.analyze) that runs in front of "
                        "every linearizability check.  Sets "
                        "JEPSEN_TPU_LINT=0 fleet-wide.")
    p.add_argument("--no-hb", action="store_true", default=False,
                   help="Disable the happens-before pre-pass "
                        "(jepsen_tpu.analyze.hb) that statically "
                        "decides or prunes linearizability searches "
                        "before any engine runs.  Sets JEPSEN_TPU_HB=0 "
                        "fleet-wide; default on, verdict-identical "
                        "either way.")
    p.add_argument("--no-dpor", action="store_true", default=False,
                   help="Disable the dynamic partial-order-reduction "
                        "layer (jepsen_tpu.analyze.dpor): duplicate-op "
                        "canonical edges, host-DFS sleep sets, the "
                        "dead-value frontier dedup, and the device "
                        "must-order mask planes.  Sets "
                        "JEPSEN_TPU_DPOR=0 fleet-wide; default on, "
                        "verdict-identical either way.")
    p.add_argument("--no-shrink", action="store_true", default=False,
                   help="Disable counterexample minimization "
                        "(jepsen_tpu.analyze.shrink) in failure "
                        "reports — invalid verdicts keep their full "
                        "history instead of a ddmin'd minimal core.  "
                        "Sets JEPSEN_TPU_SHRINK=0 fleet-wide; "
                        "reporting only, never verdicts.")
    p.add_argument("--no-telemetry", action="store_true", default=False,
                   help="Disable the device-search telemetry layer "
                        "(jepsen_tpu.obs.telemetry): the per-level "
                        "aux counter block the BFS kernels return "
                        "next to the carry, the device.level / "
                        "search.telemetry spans, and the "
                        "jtpu_search_* metrics.  Sets "
                        "JEPSEN_TPU_TELEMETRY=0 fleet-wide; default "
                        "on, verdict-byte-identical either way (off "
                        "builds are the exact pre-telemetry "
                        "kernels).")
    p.add_argument("--audit", action="store_true", default=False,
                   help="Independently audit every verdict's "
                        "certificate (jepsen_tpu.analyze.audit): a "
                        "valid verdict's linearization is replayed "
                        "against the model, an invalid one's frontier "
                        "range-checked; any W-code raises AuditError. "
                        "Sets JEPSEN_TPU_AUDIT=1 fleet-wide so every "
                        "suite-constructed checker honors it.")
    p.add_argument("--compile-cache-dir", metavar="DIR", default=None,
                   help="Persistent JAX compilation-cache directory "
                        "(jax_compilation_cache_dir): compiled search "
                        "kernels survive across processes, so repeat "
                        "runs and the bucketed batch scheduler's "
                        "steady-state buckets never retrace.  Also "
                        "honored from JEPSEN_TPU_COMPILE_CACHE_DIR.")


def add_tarball_opt(p: argparse.ArgumentParser, default: str | None = None,
                    name: str = "tarball") -> None:
    """cli.clj:89-101."""
    p.add_argument(f"--{name}", default=default, metavar="URL",
                   help="URL of the DB package (file://, http://, or "
                        "https://, ending .tar/.tgz/.zip)")


def parse_concurrency(opts: dict) -> dict:
    """'3n' -> 3 × node count (cli.clj:125-140)."""
    c = str(opts.get("concurrency", "1n"))
    m = re.fullmatch(r"(\d+)(n?)", c)
    if not m:
        raise ValueError(
            f"--concurrency {c} should be an integer optionally "
            f"followed by n")
    unit = len(opts["nodes"]) if m.group(2) == "n" else 1
    opts["concurrency"] = int(m.group(1)) * unit
    return opts


def parse_nodes(opts: dict) -> dict:
    """--nodes-file wins over -n; default n1..n5 (cli.clj:176-189)."""
    if opts.get("nodes_file"):
        with open(opts["nodes_file"]) as f:
            opts["nodes"] = [ln.strip() for ln in f if ln.strip()]
    elif not opts.get("nodes"):
        opts["nodes"] = list(DEFAULT_NODES)
    return opts


def rename_ssh_options(opts: dict) -> dict:
    """Pack flat ssh flags into the test's ssh map (cli.clj:159-174)."""
    opts["ssh"] = {
        "username": opts.pop("username", "root"),
        "password": opts.pop("password", None),
        "strict_host_key_checking": opts.pop("strict_host_key_checking",
                                             False),
        "private_key_path": opts.pop("ssh_private_key", None),
    }
    return opts


def test_opt_fn(parsed: argparse.Namespace) -> dict:
    """The standard post-processing chain (cli.clj:191-198)."""
    opts = vars(parsed).copy()
    opts = parse_nodes(opts)
    opts = parse_concurrency(opts)
    opts = rename_ssh_options(opts)
    if opts.pop("lin_decompose", False):
        # suites construct their own Linearizable checkers, so the
        # opt-in travels the same fleet-wide channel as the algorithm
        # selector (JEPSEN_TPU_LIN_ALGORITHM)
        os.environ["JEPSEN_TPU_LIN_DECOMPOSE"] = "1"
        opts["lin_decompose"] = True
    if opts.pop("stream", False):
        # like --lin-decompose: core.prepare_test consults the env var,
        # so the opt-in reaches every run this process starts
        os.environ["JEPSEN_TPU_STREAM"] = "1"
        opts["stream"] = True
    if opts.pop("explain", False):
        # like --lin-decompose: suites construct their own checkers, so
        # the plan-only mode travels by env var
        os.environ["JEPSEN_TPU_EXPLAIN"] = "1"
        opts["explain"] = True
    if opts.pop("trace", False):
        # env var for children; enable(True) for THIS process — the
        # env knob is read once and cached (obs/trace.py), so a
        # process that already consulted enabled() would otherwise
        # never see the flip
        os.environ["JEPSEN_TPU_TRACE"] = "1"
        from .obs import trace as _trace

        _trace.enable(True)
        opts["trace"] = True
    if opts.pop("no_lint", False):
        os.environ["JEPSEN_TPU_LINT"] = "0"
        opts["no_lint"] = True
    if opts.pop("no_hb", False):
        os.environ["JEPSEN_TPU_HB"] = "0"
        opts["no_hb"] = True
    if opts.pop("no_dpor", False):
        os.environ["JEPSEN_TPU_DPOR"] = "0"
        opts["no_dpor"] = True
    if opts.pop("no_shrink", False):
        # like --no-lint: shrink_enabled() reads the env per call, so
        # the opt-out reaches every checker this process constructs
        os.environ["JEPSEN_TPU_SHRINK"] = "0"
        opts["no_shrink"] = True
    if opts.pop("no_telemetry", False):
        # env var for children; enable(False) for kernels this process
        # already has a telemetry module loaded for
        os.environ["JEPSEN_TPU_TELEMETRY"] = "0"
        from .obs import telemetry as _telemetry

        _telemetry.enable(False)
        opts["no_telemetry"] = True
    if opts.pop("audit", False):
        # like --lin-decompose/--explain: suites construct their own
        # checkers, so the audit opt-in travels by env var
        os.environ["JEPSEN_TPU_AUDIT"] = "1"
        opts["audit"] = True
    ccd = opts.get("compile_cache_dir")
    if ccd:
        # the env var carries the setting into spawned workers/children;
        # the config update applies it to THIS process (deferred import:
        # the CLI must not pay backend init for --help)
        os.environ["JEPSEN_TPU_COMPILE_CACHE_DIR"] = ccd
        from .util import enable_compilation_cache

        enable_compilation_cache(ccd)
    return opts


def run_test_cmd(test_fn: Callable[[dict], dict], opts: dict) -> int:
    """Run test-count tests; exit 1 on the first invalid result
    (cli.clj:325-331)."""
    from . import core

    for i in range(opts.get("test_count", 1)):
        test = test_fn(opts)
        if opts.get("dummy"):
            from .control import DummyRemote

            test.setdefault("remote", DummyRemote())
        test = core.run(test)
        valid = test.get("results", {}).get("valid")
        if valid is not True:
            return EXIT_INVALID
    return EXIT_OK


def serve_cmd(opts: dict) -> int:
    """Results web server (cli.clj:280-295)."""
    from . import web

    web.serve(host=opts.get("host", "0.0.0.0"),
              port=int(opts.get("port", 8080)))
    return EXIT_OK


def run(subcommands: dict, argv: list[str] | None = None,
        prog: str | None = None) -> int:
    """Dispatch a CLI built from {name: {opt_fn?, run, add_opts?, help?}}
    (cli.clj:203-278).  Returns the exit code; `main` wraps this in
    sys.exit."""
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(prog=prog or "jepsen")
    subs = parser.add_subparsers(dest="subcommand")
    for name, spec in subcommands.items():
        sp = subs.add_parser(name, help=spec.get("help"))
        add = spec.get("add_opts")
        if add:
            add(sp)
    try:
        parsed = parser.parse_args(argv)
    except SystemExit as e:
        return EXIT_BAD_ARGS if e.code not in (0, None) else EXIT_OK
    if not parsed.subcommand:
        parser.print_help()
        return EXIT_BAD_ARGS
    spec = subcommands[parsed.subcommand]
    try:
        opt_fn = spec.get("opt_fn", lambda p: vars(p).copy())
        opts = opt_fn(parsed)
        return spec["run"](opts)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return EXIT_BAD_ARGS
    except Exception:
        traceback.print_exc()
        return EXIT_ERROR


def single_test_cmd(test_fn: Callable[[dict], dict], *,
                    add_opts: Callable | None = None) -> dict:
    """A {test, serve} subcommand map around one test function
    (cli.clj:297-331)."""

    def add(p: argparse.ArgumentParser):
        add_test_opts(p)
        if add_opts:
            add_opts(p)

    def add_serve(p: argparse.ArgumentParser):
        p.add_argument("--host", default="0.0.0.0")
        p.add_argument("--port", default=8080, type=int)

    return {
        "test": {"add_opts": add, "opt_fn": test_opt_fn,
                 "run": lambda opts: run_test_cmd(test_fn, opts),
                 "help": "Run a test"},
        "serve": {"add_opts": add_serve, "run": serve_cmd,
                  "help": "Serve the results web UI"},
    }


def main(subcommands: dict, argv: list[str] | None = None) -> None:
    sys.exit(run(subcommands, argv))
