"""Value codec — stable bytes <-> values for stored data.

Reference: jepsen/src/jepsen/codec.clj — edn <-> byte arrays, used by
suites to serialize operation values into databases (e.g. queue payloads).
JSON plays edn's role here.
"""

from __future__ import annotations

import json
from typing import Any


def encode(value: Any) -> bytes:
    """Value -> bytes (codec.clj encode); None -> empty, like nil."""
    if value is None:
        return b""
    return json.dumps(value, separators=(",", ":"),
                      sort_keys=True).encode()


def decode(data: bytes | None) -> Any:
    """Bytes -> value (codec.clj decode); empty -> None."""
    if not data:
        return None
    if isinstance(data, (bytes, bytearray)):
        data = data.decode()
    return json.loads(data)
