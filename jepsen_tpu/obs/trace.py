"""Span tracing — the flight recorder's timeline half.

A *span* is one timed region of work (a bucket's device dispatch, a
streamed segment fold, a campaign cell's workload phase) recorded as a
plain dict into a bounded per-run ring buffer.  The API is two
primitives:

  * :func:`span` — a context manager: ``with obs.span("fold",
    run="r1", rows=128): ...`` records begin/end/attrs; when tracing
    is off it returns a shared no-op object, so an instrumented hot
    path costs one truthiness check and nothing else.
  * :func:`traced` — the decorator form for whole functions.

Spans attribute to a *run*: either the explicit ``run=`` argument (the
stream service multiplexes many runs in one process) or the
process-wide current run (:func:`set_run`, set by ``core.run`` for the
single-run case so deep instrumentation — bucket scheduler, decomposed
engine — lands in the right buffer without threading ids through every
call).  Each run gets its own :class:`SpanRecorder` ring buffer, so a
long fleet process never grows without bound: old spans fall off the
back, a finished run's buffer is dropped after export.

Export is Chrome-trace JSON (the ``traceEvents`` array of ``"X"``
complete events, microsecond timestamps) — loadable directly in
Perfetto / ``chrome://tracing`` — via :func:`chrome_trace` /
:func:`write_trace`.  ``core.run`` writes ``store/<run>/trace.json``
when tracing is on; ``python -m jepsen_tpu.obs trace <run>`` re-emits
it and ``tools/trace_report.py`` folds it into a phase-time table.

Zero dependencies; threads are first-class (the recorder appends are
atomic, thread names become Perfetto track names).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

#: process epoch every span timestamp is relative to (microseconds
#: since this module imported) — Chrome trace wants a shared monotonic
#: microsecond clock, not wall time
_EPOCH = time.perf_counter()

#: default ring-buffer capacity (spans per run).  A span dict is a few
#: hundred bytes, so the default bounds a run's recorder at ~tens of MB
#: even under per-op tracing.
DEFAULT_CAP = 65536

_TRUTHY = ("1", "true", "on", "yes")

#: module override (tests, programmatic enable); None = follow the env
_forced: bool | None = None
#: the env knob, read ONCE: ``enabled()`` sits on per-op hot paths
#: (span per client op, per fold, per slice), and an ``os.environ``
#: lookup plus ``.strip().lower()`` allocates two strings per call —
#: with tracing OFF that was the single biggest per-site cost.  The
#: cached flag makes the off path allocation-free: one function call,
#: two attribute reads, the shared ``_NOOP`` return.  Processes that
#: flip the env var mid-run must call ``enable(True)`` /
#: ``enable(None)`` to apply / re-read it — the CLI's ``--trace``
#: handler does exactly that for its own process.
_env_on: bool | None = None
#: serializes the one-time env read against concurrent first callers
#: (worker/prep/reaper threads all hit ``enabled()`` on their hot
#: paths); the hot path itself stays lock-free — double-checked
#: locking, sound here because the GIL makes the ``_env_on`` load
#: atomic and the value is computed idempotently from the env
_knob_lock = threading.Lock()


def enabled() -> bool:
    """Is tracing on?  ``JEPSEN_TPU_TRACE=1`` (the CLI's ``--trace``)
    or a programmatic :func:`enable`.  The env knob is cached after
    the first read (see ``_env_on``)."""
    global _env_on
    if _forced is not None:
        return _forced
    if _env_on is None:
        with _knob_lock:
            if _env_on is None:
                _env_on = os.environ.get(
                    "JEPSEN_TPU_TRACE", "").strip().lower() in _TRUTHY
    return _env_on


def enable(on: bool | None = True) -> None:
    """Force tracing on/off for this process (``None`` reverts to the
    env knob, re-read on next use) — the tests' and REPL's switch."""
    global _forced, _env_on
    with _knob_lock:
        if on is None:
            # clear the cache BEFORE dropping the force: a concurrent
            # enabled() must not see the stale cached knob with the
            # force already gone
            _env_on = None
        _forced = on


# ---------------------------------------------------------------------------
# recorders
# ---------------------------------------------------------------------------


class SpanRecorder:
    """A bounded ring buffer of finished spans for one run.

    Appends are ``deque.append`` on a ``maxlen`` deque — atomic under
    the GIL, so worker threads, the bucket prep thread, and the stream
    fold thread all record without a lock on the hot path."""

    def __init__(self, run: str | None = None, cap: int = DEFAULT_CAP):
        self.run = run
        self.cap = cap
        self._spans: deque = deque(maxlen=cap)
        self.dropped = 0  # spans pushed off the back, lifetime

    def __len__(self) -> int:
        return len(self._spans)

    def record(self, name: str, cat: str, t0: float, t1: float,
               args: dict | None = None) -> None:
        """Record one finished span; ``t0``/``t1`` are
        ``time.perf_counter()`` readings."""
        if len(self._spans) == self.cap:
            self.dropped += 1
        self._spans.append({
            "name": name, "cat": cat,
            "ts": round((t0 - _EPOCH) * 1e6, 1),
            "dur": round((t1 - t0) * 1e6, 1),
            "tid": threading.current_thread().name,
            "args": args or {},
        })

    def spans(self) -> list[dict]:
        return list(self._spans)

    def clear(self) -> None:
        self._spans.clear()

    def chrome_trace(self) -> dict:
        """The Chrome-trace / Perfetto JSON object: ``"X"`` complete
        events plus thread-name metadata so tracks are labelled."""
        pid = os.getpid()
        tids: dict[str, int] = {}
        events = []
        for s in self.spans():
            tid = tids.setdefault(s["tid"], len(tids) + 1)
            events.append({"name": s["name"], "cat": s["cat"],
                           "ph": "X", "ts": s["ts"], "dur": s["dur"],
                           "pid": pid, "tid": tid,
                           "args": s["args"]})
        meta = [{"name": "thread_name", "ph": "M", "pid": pid,
                 "tid": t, "args": {"name": n}}
                for n, t in tids.items()]
        if self.run is not None:
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0, "args": {"name": str(self.run)}})
        return {"traceEvents": meta + events,
                "displayTimeUnit": "ms",
                "otherData": {"run": self.run,
                              "dropped_spans": self.dropped}}


_recorders: dict = {}
_recorders_lock = threading.Lock()
_current_run: str | None = None


def recorder(run: str | None = None) -> SpanRecorder:
    """The (created-on-demand) recorder for ``run`` — ``None`` is the
    process-default buffer for spans outside any run."""
    rec = _recorders.get(run)
    if rec is None:
        with _recorders_lock:
            rec = _recorders.setdefault(run, SpanRecorder(run))
    return rec


def set_run(run: str | None) -> None:
    """Set the process-wide current run: spans with no explicit
    ``run=`` attribute to it.  ``core.run`` sets this for the duration
    of a test; services that multiplex runs pass ``run=`` explicitly
    instead."""
    global _current_run
    _current_run = run


def current_run() -> str | None:
    return _current_run


def drop_recorder(run: str | None) -> None:
    """Forget a finished run's buffer (after export) so a long fleet
    process doesn't accumulate one ring buffer per run forever."""
    with _recorders_lock:
        _recorders.pop(run, None)


# ---------------------------------------------------------------------------
# the span primitive
# ---------------------------------------------------------------------------


class _Noop:
    """The shared do-nothing span: tracing off costs one call + one
    truthiness check, allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class _Span:
    __slots__ = ("name", "cat", "run", "args", "_t0")

    def __init__(self, name: str, cat: str, run: str | None,
                 args: dict | None):
        self.name = name
        self.cat = cat
        self.run = run
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        args = self.args
        if exc_type is not None:
            args = dict(args or {})
            args["error"] = exc_type.__name__
        run = self.run if self.run is not None else _current_run
        try:
            recorder(run).record(self.name, self.cat, self._t0, t1, args)
        except Exception:  # pragma: no cover — the recorder must never
            pass           # take down the instrumented code
        return False


def span(name: str, *, cat: str = "span", run: str | None = None,
         **attrs):
    """``with obs.span("fold", run=..., rows=128): ...`` — no-op when
    tracing is off."""
    if not enabled():
        return _NOOP
    return _Span(name, cat, run, attrs or None)


def traced(name: str | None = None, *, cat: str = "span"):
    """Decorator form: ``@obs.traced()`` / ``@obs.traced("prep",
    cat="host")`` wraps the call in a span named after the function."""
    import functools

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not enabled():
                return fn(*a, **kw)
            with _Span(label, cat, None, None):
                return fn(*a, **kw)

        return wrapper

    return deco


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------


def chrome_trace(run: str | None = None) -> dict:
    """The Chrome-trace JSON for one run's recorder (``None`` = the
    default buffer)."""
    return recorder(run).chrome_trace()


def write_trace(path: str, run: str | None = None) -> str:
    """Write ``run``'s Chrome trace to ``path`` (atomically — a live
    web UI may be reading the previous snapshot); returns the path."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(chrome_trace(run), f)
    os.replace(tmp, path)
    return path
