"""``python -m jepsen_tpu.obs`` — flight-recorder CLI.

  trace <run>     print a run's Chrome trace JSON (``store/<name>/
                  <time>/trace.json``; a bare test name resolves via
                  its ``latest`` symlink, a path is used as-is) —
                  pipe to a file and load it in Perfetto
                  (https://ui.perfetto.dev) or chrome://tracing.
  report <run>    the phase-time table (device vs host vs idle) for
                  the same trace — tools/trace_report.py's engine.
  metrics         this process's Prometheus text (mostly useful under
                  a REPL; live services expose /metrics themselves).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def resolve_trace(run: str, base: str | None = None) -> str:
    """A trace.json path from a run spec: an existing file path,
    ``name/time``, or a bare test name (its ``latest`` run)."""
    from .. import store

    if os.path.isfile(run):
        return run
    base = base or store.BASE
    p = os.path.join(base, run, "trace.json")
    if os.path.isfile(p):
        return p
    latest = os.path.join(base, run, "latest", "trace.json")
    if os.path.isfile(latest):
        return latest
    raise FileNotFoundError(
        f"no trace.json for run {run!r} (looked at {p} and {latest}; "
        f"was the run traced? --trace / JEPSEN_TPU_TRACE=1)")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.obs",
        description="Flight recorder: export traces, summarize them, "
                    "dump metrics.")
    sub = p.add_subparsers(dest="cmd")
    tp = sub.add_parser("trace", help="print a run's Chrome trace JSON")
    tp.add_argument("run", help="store run (name/time), test name "
                                "(latest run), or a trace.json path")
    tp.add_argument("--base", default=None, help="store base dir")
    rp = sub.add_parser("report", help="phase-time table for a trace")
    rp.add_argument("run")
    rp.add_argument("--base", default=None)
    rp.add_argument("--json", action="store_true",
                    help="emit the table as JSON")
    sub.add_parser("metrics",
                   help="this process's Prometheus metrics text")
    args = p.parse_args(argv)

    if args.cmd == "trace":
        with open(resolve_trace(args.run, args.base)) as f:
            sys.stdout.write(f.read())
        return 0
    if args.cmd == "report":
        from .report import load_trace, phase_table, render_report

        rep = phase_table(load_trace(resolve_trace(args.run, args.base)))
        print(json.dumps(rep, indent=1) if args.json
              else render_report(rep))
        return 0
    if args.cmd == "metrics":
        from . import metrics

        sys.stdout.write(metrics.render())
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
