"""The metrics registry — the flight recorder's "right now" half.

Counters, gauges, and histograms with optional labels, rendered in
Prometheus text exposition format (``/metrics`` on the results web UI
and the stream service) and as a JSON snapshot (``/api/stats``, which
the ``/campaigns`` grid polls for live fleet health).  Zero
dependencies, one process-wide :data:`REGISTRY`.

Unlike tracing, metrics are **always on**: a counter bump is one lock
acquire + one dict update, cheap enough for every instrumentation
point that isn't a per-config inner loop.  The same points that emit
spans feed these — ops ingested, segments folded by route, lookahead
forks spawned/capped, verdict-cache and kernel-cache hits, bucket
padding, backoff exhaustions, watchdog escalations, shed lines — so
"what is the service doing right now" and "where did the wall-clock
go" are answered from one instrumentation pass.

Metric handles are created once at module scope (``M = REGISTRY.
counter("jtpu_x_total", "...")``) and bumped via ``M.inc(...)`` —
get-or-create per call would put a registry lookup on hot paths.

Naming follows Prometheus conventions: ``jtpu_`` prefix, ``_total``
suffix on counters, base-unit ``_seconds`` on histograms; label names
are closed enums (``route``, ``event``, ``reason``...), never
unbounded ids (a run id as a label would grow the registry without
bound — run-scoped detail belongs in spans and result dicts).
"""

from __future__ import annotations

import threading
import time

#: process epoch for the derived device-idle fraction (/api/stats):
#: idle = 1 - device-busy seconds / process uptime
_PROC_EPOCH = time.monotonic()


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers without the trailing .0."""
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _labels_str(names: tuple, values: tuple) -> str:
    if not names:
        return ""
    body = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + body + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, labelnames=()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {sorted(labels)}")
        return tuple(str(labels[n]) for n in self.labelnames)


class Counter(_Metric):
    """Monotonically increasing count, optionally labelled."""

    kind = "counter"

    def __init__(self, name, help_, labelnames=()):
        super().__init__(name, help_, labelnames)
        self._v: dict[tuple, float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._v[k] = self._v.get(k, 0) + n

    def value(self, **labels) -> float:
        return self._v.get(self._key(labels), 0)

    def total(self) -> float:
        """Sum across every label combination (ratio math, snapshots)."""
        return sum(self._v.values())

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._v.items())
        if not items and not self.labelnames:
            items = [((), 0)]
        for k, v in items:
            out.append(f"{self.name}"
                       f"{_labels_str(self.labelnames, k)} {_fmt(v)}")
        return out

    def snapshot(self):
        with self._lock:
            if not self.labelnames:
                return self._v.get((), 0)
            return {",".join(k): v for k, v in sorted(self._v.items())}


class Gauge(Counter):
    """A value that goes both ways (open runs, queue depths)."""

    kind = "gauge"

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def set(self, v: float, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._v[k] = float(v)

    def render(self) -> list[str]:
        out = super().render()
        out[1] = f"# TYPE {self.name} gauge"
        return out


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus ``le`` convention)."""

    kind = "histogram"

    #: default buckets: wall-clock seconds from sub-ms folds to
    #: multi-minute device searches
    DEFAULT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 60.0)

    def __init__(self, name, help_, labelnames=(), buckets=None):
        super().__init__(name, help_, labelnames)
        self.buckets = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        self._counts: dict[tuple, list[int]] = {}
        self._sum: dict[tuple, float] = {}
        self._n: dict[tuple, int] = {}

    def observe(self, v: float, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            c = self._counts.get(k)
            if c is None:
                c = self._counts[k] = [0] * len(self.buckets)
                self._sum[k] = 0.0
                self._n[k] = 0
            for i, le in enumerate(self.buckets):
                if v <= le:
                    c[i] += 1
            self._sum[k] += v
            self._n[k] += 1

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            keys = sorted(self._counts)
            for k in keys:
                base = list(zip(self.labelnames, k))
                for le, c in zip(self.buckets, self._counts[k]):
                    ls = _labels_str(
                        tuple(n for n, _ in base) + ("le",),
                        tuple(v for _, v in base) + (_fmt(le),))
                    out.append(f"{self.name}_bucket{ls} {c}")
                ls = _labels_str(
                    tuple(n for n, _ in base) + ("le",),
                    tuple(v for _, v in base) + ("+Inf",))
                out.append(f"{self.name}_bucket{ls} {self._n[k]}")
                plain = _labels_str(self.labelnames, k)
                out.append(f"{self.name}_sum{plain} "
                           f"{_fmt(round(self._sum[k], 6))}")
                out.append(f"{self.name}_count{plain} {self._n[k]}")
        return out

    def snapshot(self):
        with self._lock:
            return {",".join(k) if k else "": {
                "count": self._n[k],
                "sum": round(self._sum[k], 6)}
                for k in sorted(self._counts)}


class Registry:
    """Name -> metric; get-or-create is idempotent so modules can
    declare their handles independently."""

    def __init__(self):
        self._m: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help_, labelnames=(), **kw):
        with self._lock:
            m = self._m.get(name)
            if m is None:
                m = self._m[name] = cls(name, help_, labelnames, **kw)
            elif not isinstance(m, cls) \
                    or m.labelnames != tuple(labelnames):
                raise ValueError(f"metric {name!r} re-registered with a "
                                 f"different type or labels")
            return m

    def counter(self, name, help_, labelnames=()) -> Counter:
        return self._get(Counter, name, help_, labelnames)

    def gauge(self, name, help_, labelnames=()) -> Gauge:
        return self._get(Gauge, name, help_, labelnames)

    def histogram(self, name, help_, labelnames=(),
                  buckets=None) -> Histogram:
        return self._get(Histogram, name, help_, labelnames,
                         buckets=buckets)

    def get(self, name) -> _Metric | None:
        return self._m.get(name)

    def render(self) -> str:
        """The Prometheus text exposition body (``/metrics``)."""
        lines: list[str] = []
        for name in sorted(self._m):
            lines.extend(self._m[name].render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able {name: {type, help, values}} (``/api/stats``),
        plus the derived ratios dashboards actually want."""
        out = {name: {"type": m.kind, "help": m.help,
                      "values": m.snapshot()}
               for name, m in sorted(self._m.items())}
        out["derived"] = derived_stats(self)
        return out

    def reset(self) -> None:
        """Zero every metric IN PLACE (tests only).  The metric
        objects themselves survive — instrumented modules hold handles
        captured at import (``_M_OPS``, ``_M_SHED``, ...), and
        replacing the objects would silently orphan every one of
        them."""
        with self._lock:
            metrics = list(self._m.values())
        for m in metrics:
            with m._lock:
                if isinstance(m, Histogram):
                    m._counts.clear()
                    m._sum.clear()
                    m._n.clear()
                else:
                    m._v.clear()


def _ratio(num: float, den: float):
    return round(num / den, 4) if den else None


def derived_stats(reg: "Registry") -> dict:
    """The headline ratios: verdict/kernel cache hit ratio, bucket
    padding efficiency — computed from the raw counters so every
    surface (Prometheus, /api/stats, CLI) derives them identically."""
    out: dict = {}
    vc = reg.get("jtpu_verdict_cache_total")
    if isinstance(vc, Counter):
        h = vc.value(event="hit")
        m = vc.value(event="miss")
        out["verdict_cache_hit_ratio"] = _ratio(h, h + m)
    kc = reg.get("jtpu_kernel_cache_total")
    if isinstance(kc, Counter):
        h = kc.value(event="hit")
        m = kc.value(event="miss")
        out["kernel_cache_hit_ratio"] = _ratio(h, h + m)
    b = reg.get("jtpu_bucket_ops_total")
    if isinstance(b, Counter):
        out["bucket_padding_efficiency"] = _ratio(
            b.value(kind="useful"), b.value(kind="padded"))
    sb = reg.get("jtpu_shard_ops_total")
    if isinstance(sb, Counter):
        out["shard_padding_efficiency"] = _ratio(
            sb.value(kind="useful"), sb.value(kind="padded"))
    # device-idle fraction: of this process's lifetime, the share NOT
    # spent inside device.slice executions — the fleet strip's
    # is-the-accelerator-earning-its-keep gauge.  None until any
    # device time has been recorded (an all-host process is not
    # "100% idle accelerator", it has no accelerator story at all).
    ds = reg.get("jtpu_device_seconds_total")
    if isinstance(ds, Counter):
        busy = ds.total()
        up = max(1e-9, time.monotonic() - _PROC_EPOCH)
        out["device_idle_fraction"] = (
            round(max(0.0, 1.0 - busy / up), 4) if busy > 0 else None)
    pr = reg.get("jtpu_search_observed_prune_ratio")
    if isinstance(pr, Gauge):
        v = pr.value()
        out["observed_prune_ratio"] = v if v else None
    return out


#: the process-wide registry every instrumentation point feeds
REGISTRY = Registry()


def _declare(reg: Registry) -> None:
    """Declare the standing metric set so a fresh scrape shows the
    whole taxonomy (zeros included for the unlabelled ones) instead of
    only what has fired.  Modules re-obtain these handles by name."""
    reg.counter("jtpu_ops_total",
                "Client worker op completions by type",
                ("type",))
    reg.counter("jtpu_nemesis_ops_total",
                "Nemesis injections applied (completions)")
    reg.counter("jtpu_stream_ops_ingested_total",
                "History events ingested by streaming checkers")
    reg.counter("jtpu_stream_segments_folded_total",
                "Closed quiescence segments folded, by route",
                ("route",))
    reg.counter("jtpu_stream_forks_total",
                "Bounded :info lookahead forks, spawned vs capped",
                ("outcome",))
    reg.counter("jtpu_verdict_cache_total",
                "Verdict-cache lookups/writes (hit/miss/insert)",
                ("event",))
    reg.counter("jtpu_kernel_cache_total",
                "Compiled-kernel cache lookups (hit/miss)",
                ("event",))
    reg.counter("jtpu_bucket_ops_total",
                "Bucketed device batch rows, useful vs padded",
                ("kind",))
    reg.counter("jtpu_shard_ops_total",
                "Mesh-sharded bucketed batch rows, useful vs padded",
                ("kind",))
    reg.counter("jtpu_shed_total",
                "Ops/lines shed under backpressure, by reason",
                ("reason",))
    reg.counter("jtpu_backoff_exhausted_total",
                "Reconnect backoff schedules that ran out of budget")
    reg.counter("jtpu_watchdog_total",
                "Cell watchdog events (fired/killed)",
                ("event",))
    reg.counter("jtpu_campaign_cells_total",
                "Campaign cells finished, by status",
                ("status",))
    reg.counter("jtpu_hb_prepass_total",
                "HB pre-pass outcomes (decided_valid/decided_invalid/"
                "undecided/skipped)", ("outcome",))
    reg.counter("jtpu_hb_edges_total",
                "Forced/canonical HB edges inferred beyond real time, "
                "by kind", ("kind",))
    reg.counter("jtpu_hb_fold_total",
                "Streamed/decomposed segment folds answered by the HB "
                "interval pass")
    reg.gauge("jtpu_hb_prune_ratio",
              "pruned/raw config-bound ratio of the most recent HB "
              "pre-pass (0 = decided without search)")
    reg.counter("jtpu_dpor_sleep_prunes_total",
                "Host-DFS candidates skipped because they were "
                "sleeping (covered by an explored commuting sibling)")
    reg.counter("jtpu_dpor_dedup_total",
                "Canonical-state frontier dedup events, by site/kind",
                ("site", "event"))
    reg.counter("jtpu_dpor_mask_total",
                "Must-order mask effects by site (host frames/DFS "
                "candidates killed; masked rows shipped to device "
                "planes)", ("site",))
    reg.counter("jtpu_dpor_dup_edges_total",
                "Duplicate-op canonical must-order edges inferred")
    reg.gauge("jtpu_stream_runs_open",
              "Streaming runs currently open in this process")
    reg.histogram("jtpu_fold_seconds",
                  "Wall seconds per streamed segment fold")
    reg.histogram("jtpu_bucket_seconds",
                  "Wall seconds per bucket stage (prep/device)",
                  ("stage",))
    # device-search telemetry (obs/telemetry.py): what the kernels did
    # inside their device.slice windows, level by level
    reg.counter("jtpu_search_levels_total",
                "Device BFS levels executed (telemetry-observed)")
    reg.counter("jtpu_search_expanded_total",
                "Valid candidate lanes expanded by device BFS levels")
    reg.counter("jtpu_search_mask_killed_total",
                "Candidate lanes killed on-device by the hb/dpor "
                "must-order mask")
    reg.counter("jtpu_search_dedup_folds_total",
                "Successor states folded onto the dead-value "
                "canonical token")
    reg.counter("jtpu_search_crash_rounds_total",
                "Crash-closure rounds executed inside device BFS "
                "levels")
    reg.counter("jtpu_search_overflows_total",
                "Device BFS levels that overflowed their frontier "
                "width")
    reg.gauge("jtpu_search_observed_prune_ratio",
              "Observed surviving-lane fraction of the most recent "
              "device search (0 = decided without search)")
    reg.histogram("jtpu_search_level_occupancy",
                  "Live frontier rows per device BFS level",
                  buckets=(1, 8, 64, 512, 4096, 32768, 262144))
    # compile/transfer accounting (the fleet-warmup signal)
    reg.counter("jtpu_device_seconds_total",
                "Wall seconds spent inside device.slice executions")
    reg.counter("jtpu_device_transfer_bytes_total",
                "Host<->device bytes staged for search dispatch, "
                "by direction", ("direction",))
    reg.gauge("jtpu_device_memory_bytes",
              "bytes_in_use reported by the primary device (0 where "
              "the backend has no memory_stats)")
    # fleet tier (jepsen_tpu/fleet/): router + admission control
    reg.counter("jtpu_fleet_routed_total",
                "Run headers routed to a worker, by worker id",
                ("worker",))
    reg.counter("jtpu_fleet_rerouted_total",
                "Runs re-routed off their worker, by reason",
                ("reason",))
    reg.counter("jtpu_fleet_salvaged_total",
                "Dead-worker open runs finalized from the persist-dir "
                "salvage path")
    reg.counter("jtpu_fleet_probe_total",
                "Worker health probes, by result (ok/failed/dead)",
                ("result",))
    reg.counter("jtpu_fleet_admission_total",
                "Fleet admission decisions (accept/shed/spawn-worker)",
                ("decision",))
    reg.gauge("jtpu_fleet_workers",
              "Live (admitted, probe-passing) workers behind the "
              "router")


_declare(REGISTRY)


def render() -> str:
    return REGISTRY.render()


def snapshot() -> dict:
    return REGISTRY.snapshot()
