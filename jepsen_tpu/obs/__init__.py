"""jepsen_tpu.obs — the flight recorder: span tracing + metrics.

Jepsen's own lineage ships observability as a first-class checker
(perf.clj's latency/rate graphs, timeline.clj's HTML timeline ride
next to the linearizability verdict); this package is that idea for
the reproduction's *own* machinery.  Two halves, one instrumentation
pass:

  * **spans** (:mod:`.trace`) — where did the wall-clock go: a
    zero-dep, thread-safe ``obs.span("fold", rows=128)`` context
    manager + ``@obs.traced()`` decorator recording into bounded
    per-run ring buffers, exported as Chrome-trace/Perfetto JSON
    (``store/<run>/trace.json``, ``python -m jepsen_tpu.obs trace``,
    the web run page's timeline panel).  Off by default; the CLI's
    ``--trace`` / ``JEPSEN_TPU_TRACE=1`` turns it on, and off means
    *near-zero* cost (one truthiness check per site).
  * **metrics** (:mod:`.metrics`) — what is the service doing right
    now: always-on counters/gauges/histograms (ops ingested, segments
    folded, forks spawned/capped, verdict- and kernel-cache hits,
    bucket padding, watchdog escalations, shed lines) served in
    Prometheus text from ``/metrics`` on the results web UI and the
    stream service, plus the ``/api/stats`` JSON snapshot the
    ``/campaigns`` grid polls.

:func:`log_ctx` is the third, small piece: a LoggerAdapter stamping
``run_id=``/``conn=`` fields onto log lines so a multiplexed-service
warning is attributable to the run that caused it.
"""

from __future__ import annotations

import logging

from . import metrics  # noqa: F401  (the registry half)
from . import telemetry  # noqa: F401  (the device-search aux block)
from .metrics import REGISTRY  # noqa: F401
from .trace import (DEFAULT_CAP, SpanRecorder, chrome_trace,  # noqa: F401
                    current_run, drop_recorder, enable, enabled,
                    recorder, set_run, span, traced, write_trace)


class _CtxAdapter(logging.LoggerAdapter):
    """Prefix every message with stable ``k=v`` context fields."""

    def process(self, msg, kwargs):
        ctx = " ".join(f"{k}={v}" for k, v in self.extra.items()
                       if v is not None)
        return (f"[{ctx}] {msg}" if ctx else msg), kwargs


def log_ctx(logger: logging.Logger, **fields) -> logging.LoggerAdapter:
    """``obs.log_ctx(log, run_id=r, conn=addr)`` — an adapter whose
    lines carry the run/connection context, so a warning out of a
    service multiplexing hundreds of runs names the one that failed."""
    return _CtxAdapter(logger, fields)
