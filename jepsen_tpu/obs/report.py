"""Trace summarization — fold a trace.json into a phase-time table.

Answers the measure-then-optimize question directly: of a run's wall
clock, how much was device execution, how much host prep/fold work,
and how much nothing at all (idle — the pipelining headroom).  Used by
``tools/trace_report.py`` and ``python -m jepsen_tpu.obs report``.

Per-category *busy* time is the **interval union** of that category's
spans (two overlapped device dispatches don't double-bill), and idle
is the run extent minus the union of every non-envelope span —
envelope categories (the ``run`` span wrapping the whole test) exist
to anchor the extent, not to claim the time.
"""

from __future__ import annotations

import json

#: categories that wrap other work rather than doing any themselves
ENVELOPE_CATS = ("run",)


def load_trace(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _union_us(ivs: list[tuple[float, float]]) -> float:
    """Total length of the union of [start, end) microsecond intervals."""
    if not ivs:
        return 0.0
    ivs = sorted(ivs)
    total = 0.0
    cur_s, cur_e = ivs[0]
    for s, e in ivs[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def phase_table(trace: dict) -> dict:
    """-> {wall_s, phases: [{cat, spans, busy_s, pct}], idle_s,
    idle_pct, top: [{name, count, total_s}]} for one Chrome trace."""
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X"]
    if not events:
        return {"wall_s": 0.0, "phases": [], "idle_s": 0.0,
                "idle_pct": None, "top": []}
    t0 = min(e["ts"] for e in events)
    t1 = max(e["ts"] + e.get("dur", 0) for e in events)
    wall_us = max(0.0, t1 - t0)

    by_cat: dict[str, list] = {}
    by_name: dict[str, list] = {}
    for e in events:
        by_cat.setdefault(e.get("cat") or "span", []).append(e)
        by_name.setdefault(e.get("name") or "?", []).append(e)

    phases = []
    work_ivs = []
    for cat in sorted(by_cat):
        ivs = [(e["ts"], e["ts"] + e.get("dur", 0)) for e in by_cat[cat]]
        busy = _union_us(ivs)
        if cat not in ENVELOPE_CATS:
            work_ivs.extend(ivs)
        phases.append({"cat": cat, "spans": len(ivs),
                       "busy_s": round(busy / 1e6, 4),
                       "pct": round(100 * busy / wall_us, 1)
                       if wall_us else None})
    phases.sort(key=lambda p: -p["busy_s"])
    idle_us = max(0.0, wall_us - _union_us(work_ivs))
    top = sorted(({"name": n,
                   "count": len(es),
                   "total_s": round(sum(e.get("dur", 0)
                                        for e in es) / 1e6, 4)}
                  for n, es in by_name.items()),
                 key=lambda r: -r["total_s"])[:12]
    return {"wall_s": round(wall_us / 1e6, 4),
            "phases": phases,
            "idle_s": round(idle_us / 1e6, 4),
            "idle_pct": round(100 * idle_us / wall_us, 1)
            if wall_us else None,
            "top": top}


def render_report(rep: dict) -> str:
    """The human table the CLI prints."""
    lines = [f"wall: {rep['wall_s']}s   idle: {rep['idle_s']}s"
             + (f" ({rep['idle_pct']}%)"
                if rep.get("idle_pct") is not None else "")]
    if rep["phases"]:
        lines.append(f"{'phase':<12} {'spans':>6} {'busy_s':>10} "
                     f"{'% wall':>7}")
        for p in rep["phases"]:
            pct = "" if p["pct"] is None else f"{p['pct']:>6.1f}%"
            lines.append(f"{p['cat']:<12} {p['spans']:>6} "
                         f"{p['busy_s']:>10.4f} {pct:>7}")
    if rep["top"]:
        lines.append("")
        lines.append(f"{'span':<32} {'count':>6} {'total_s':>10}")
        for r in rep["top"]:
            lines.append(f"{r['name']:<32} {r['count']:>6} "
                         f"{r['total_s']:>10.4f}")
    return "\n".join(lines)
