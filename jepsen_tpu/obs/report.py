"""Trace summarization — fold a trace.json into a phase-time table.

Answers the measure-then-optimize question directly: of a run's wall
clock, how much was device execution, how much host prep/fold work,
and how much nothing at all (idle — the pipelining headroom).  Used by
``tools/trace_report.py`` and ``python -m jepsen_tpu.obs report``.

Per-category *busy* time is the **interval union** of that category's
spans (two overlapped device dispatches don't double-bill), and idle
is the run extent minus the union of every non-envelope span —
envelope categories (the ``run`` span wrapping the whole test) exist
to anchor the extent, not to claim the time.
"""

from __future__ import annotations

import json

#: categories that wrap other work rather than doing any themselves
ENVELOPE_CATS = ("run",)


def load_trace(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _union_us(ivs: list[tuple[float, float]]) -> float:
    """Total length of the union of [start, end) microsecond intervals."""
    if not ivs:
        return 0.0
    ivs = sorted(ivs)
    total = 0.0
    cur_s, cur_e = ivs[0]
    for s, e in ivs[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def _telemetry_table(events: list) -> dict | None:
    """The device-search telemetry section of a report, from the
    ``device.level`` / ``search.telemetry`` / ``device.compile`` /
    ``device.transfer`` spans a telemetry-on traced run records
    (obs/telemetry.py).  ``None`` when the trace predates telemetry
    (or ran with it off) — callers keep their pre-telemetry shape."""
    levels = [e for e in events if e.get("name") == "device.level"]
    tele = [e for e in events if e.get("name") == "search.telemetry"]
    compiles = [e for e in events if e.get("name") == "device.compile"]
    transfers = [e for e in events
                 if e.get("name") == "device.transfer"]
    if not (levels or tele):
        return None
    out: dict = {}
    if levels:
        per: dict[int, dict] = {}
        for e in levels:
            a = e.get("args") or {}
            lvl = int(a.get("level", 0))
            r = per.setdefault(lvl, {"level": lvl, "occupancy": 0,
                                     "expanded": 0, "mask_killed": 0,
                                     "dedup_folds": 0, "busy_s": 0.0})
            for k in ("occupancy", "expanded", "mask_killed",
                      "dedup_folds"):
                r[k] += int(a.get(k, 0))
            r["busy_s"] = round(r["busy_s"]
                                + e.get("dur", 0) / 1e6, 6)
        rows = [per[k] for k in sorted(per)]
        for r in rows:
            den = (r["expanded"] + r["mask_killed"]
                   + r["dedup_folds"])
            r["mask_kill_pct"] = (round(100 * r["mask_killed"] / den,
                                        1) if den else None)
            r["dedup_fold_pct"] = (round(100 * r["dedup_folds"] / den,
                                         1) if den else None)
        out["levels"] = rows
        out["max_occupancy"] = max(r["occupancy"] for r in rows)
    if tele:
        # one span per finished search; totals across the trace plus
        # the LAST search's predicted-vs-observed prune row (bench
        # tiers run one search per trace, so last == the search)
        tot = {"searches": len(tele), "expanded": 0, "mask_killed": 0,
               "dedup_folds": 0, "overflows": 0}
        last = (tele[-1].get("args") or {})
        for e in tele:
            a = e.get("args") or {}
            for k in ("expanded", "mask_killed", "dedup_folds",
                      "overflows"):
                tot[k] += int(a.get(k, 0) or 0)
        for k in ("observed_prune_ratio", "predicted_prune_ratio",
                  "prune_ratio_delta"):
            if last.get(k) is not None:
                tot[k] = last[k]
        if last.get("decided"):
            tot["decided"] = True
        out["search"] = tot
    if compiles:
        out["compiles"] = {
            "count": len(compiles),
            "total_s": round(sum(e.get("dur", 0)
                                 for e in compiles) / 1e6, 4),
            "persistent_cache": bool(
                (compiles[0].get("args") or {}).get(
                    "persistent_cache"))}
    if transfers:
        out["transfer_bytes"] = sum(
            int((e.get("args") or {}).get("bytes", 0))
            for e in transfers)
    return out


def phase_table(trace: dict) -> dict:
    """-> {wall_s, phases: [{cat, spans, busy_s, pct}], idle_s,
    idle_pct, top: [{name, count, total_s}]} for one Chrome trace;
    traces recorded with device telemetry on additionally carry a
    ``telemetry`` section (per-level table, predicted-vs-observed
    prune, compile/transfer accounting)."""
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X"]
    if not events:
        return {"wall_s": 0.0, "phases": [], "idle_s": 0.0,
                "idle_pct": None, "top": []}
    t0 = min(e["ts"] for e in events)
    t1 = max(e["ts"] + e.get("dur", 0) for e in events)
    wall_us = max(0.0, t1 - t0)

    by_cat: dict[str, list] = {}
    by_name: dict[str, list] = {}
    for e in events:
        by_cat.setdefault(e.get("cat") or "span", []).append(e)
        by_name.setdefault(e.get("name") or "?", []).append(e)

    phases = []
    work_ivs = []
    for cat in sorted(by_cat):
        ivs = [(e["ts"], e["ts"] + e.get("dur", 0)) for e in by_cat[cat]]
        busy = _union_us(ivs)
        if cat not in ENVELOPE_CATS:
            work_ivs.extend(ivs)
        phases.append({"cat": cat, "spans": len(ivs),
                       "busy_s": round(busy / 1e6, 4),
                       "pct": round(100 * busy / wall_us, 1)
                       if wall_us else None})
    phases.sort(key=lambda p: -p["busy_s"])
    idle_us = max(0.0, wall_us - _union_us(work_ivs))
    top = sorted(({"name": n,
                   "count": len(es),
                   "total_s": round(sum(e.get("dur", 0)
                                        for e in es) / 1e6, 4)}
                  for n, es in by_name.items()),
                 key=lambda r: -r["total_s"])[:12]
    out = {"wall_s": round(wall_us / 1e6, 4),
           "phases": phases,
           "idle_s": round(idle_us / 1e6, 4),
           "idle_pct": round(100 * idle_us / wall_us, 1)
           if wall_us else None,
           "top": top}
    t = _telemetry_table(events)
    if t is not None:
        out["telemetry"] = t
    return out


def render_report(rep: dict) -> str:
    """The human table the CLI prints."""
    lines = [f"wall: {rep['wall_s']}s   idle: {rep['idle_s']}s"
             + (f" ({rep['idle_pct']}%)"
                if rep.get("idle_pct") is not None else "")]
    if rep["phases"]:
        lines.append(f"{'phase':<12} {'spans':>6} {'busy_s':>10} "
                     f"{'% wall':>7}")
        for p in rep["phases"]:
            pct = "" if p["pct"] is None else f"{p['pct']:>6.1f}%"
            lines.append(f"{p['cat']:<12} {p['spans']:>6} "
                         f"{p['busy_s']:>10.4f} {pct:>7}")
    if rep["top"]:
        lines.append("")
        lines.append(f"{'span':<32} {'count':>6} {'total_s':>10}")
        for r in rep["top"]:
            lines.append(f"{r['name']:<32} {r['count']:>6} "
                         f"{r['total_s']:>10.4f}")
    t = rep.get("telemetry")
    if t:
        lines.append("")
        lines.append("device search telemetry")
        s = t.get("search")
        if s:
            obs_r = s.get("observed_prune_ratio")
            pred = s.get("predicted_prune_ratio")
            row = (f"prune ratio: observed "
                   f"{'n/a' if obs_r is None else obs_r}")
            if pred is not None:
                row += f"  predicted {pred}"
                if s.get("prune_ratio_delta") is not None:
                    row += f"  delta {s['prune_ratio_delta']}"
            if s.get("decided"):
                row += "  (decided statically — no device levels)"
            lines.append(row)
            lines.append(f"expanded {s['expanded']}  mask-killed "
                         f"{s['mask_killed']}  dedup-folds "
                         f"{s['dedup_folds']}  overflows "
                         f"{s['overflows']}")
        c = t.get("compiles")
        if c:
            lines.append(f"kernel compiles (cache misses): "
                         f"{c['count']} in {c['total_s']}s"
                         + ("  [persistent cache]"
                            if c.get("persistent_cache") else ""))
        if t.get("transfer_bytes"):
            lines.append(f"h2d transfer: {t['transfer_bytes']} bytes")
        rows = t.get("levels") or []
        if rows:
            lines.append(f"{'level':>5} {'occupancy':>9} "
                         f"{'expanded':>9} {'mask-kill%':>10} "
                         f"{'dedup%':>7} {'busy_s':>9}")

            def fmt(r):
                mk = r.get("mask_kill_pct")
                df = r.get("dedup_fold_pct")
                return (f"{r['level']:>5} {r['occupancy']:>9} "
                        f"{r['expanded']:>9} "
                        f"{'-' if mk is None else mk:>10} "
                        f"{'-' if df is None else df:>7} "
                        f"{r['busy_s']:>9.4f}")

            # head + tail, elided middle: a 500-level search must not
            # print 500 rows
            if len(rows) <= 24:
                lines.extend(fmt(r) for r in rows)
            else:
                lines.extend(fmt(r) for r in rows[:12])
                lines.append(f"  ... {len(rows) - 24} level(s) "
                             f"elided ...")
                lines.extend(fmt(r) for r in rows[-12:])
    return "\n".join(lines)
