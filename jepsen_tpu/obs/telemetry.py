"""Device-search telemetry — the aux counter block that opens the
device black box.

The BFS kernels (checker/linearizable.py: single-device, bucketed
batch, mesh-sharded; checker/pallas_level.py: the fused level loop)
run dozens-to-thousands of levels per bounded ``device.slice`` call,
and until this module the slice span was the *finest* observable unit:
total wall time, nothing about what the kernel did inside.  The
hb/dpor prune ratios that ``explain()`` *predicts* were therefore
never *observed*, and per-level frontier dynamics (the input every
remaining ROADMAP perf item needs) existed only as anecdotes.

The fix is GPUexplore's lesson (arXiv:1801.05857 — an accelerated
search is trustworthy when its progress is cheaply externally
checkable) applied to our own kernels, the way ScalaBFS
(arXiv:2105.11754) meters per-PE occupancy per level: each telemetry-
built kernel carries a small packed **aux counter block** — one int32
row per BFS level — through the slice loop and returns it next to the
search carry.  The block costs a handful of vector-sum ops per level
(near-zero against the mask/prune work) and NEVER feeds back into the
search: verdicts are byte-identical with telemetry on or off
(differential-fuzzed in tests/test_telemetry.py).

Aux block schema (``TELE_ROWS`` x ``TELE_COLS`` int32, row = one
level, additive — the final row aggregates any levels past the
buffer):

  col 0  occupancy     live frontier rows after the level's crash
                       closure (the width the det expansion actually
                       ran at — closure can merge crash successors in
                       above the entry count)
  col 1  expanded      valid candidate lanes (post-mask, post-closure)
  col 2  mask_killed   candidate lanes killed by the hb/dpor
                       must-order mask (0 when the search is unmasked)
  col 3  dedup_folds   successor states rewritten onto the dead-value
                       canonical token (0 when dedup is off)
  col 4  crash_rounds  crash-closure iterations the level ran
  col 5  next_count    rows surviving the dominance prune into the
                       next level
  col 6  overflow      1 iff this level newly overflowed (bailed
                       levels appear with overflow=1 and are re-run
                       wider — expect a duplicate row after escalation)
  col 7  goal          1 iff a goal configuration was found

Host side, :class:`SearchTelemetry` accumulates rows across slices,
emits ``device.level`` child spans under each ``device.slice`` (wall
time apportioned by occupancy — tracing-gated), feeds the
``jtpu_search_*`` registry metrics, and produces the
``search_telemetry`` result block whose ``observed_prune_ratio`` is
directly comparable against the prepass's *predicted* ``prune_ratio``
(``predicted_prune_ratio`` / ``prune_ratio_delta`` ride the block and
the ``search.telemetry`` span, which is what ``tools/trace_report.py``
and ``tools/obs_guard.py`` read out of ``BENCH_trace_*.json``).

Knob: ``JEPSEN_TPU_TELEMETRY`` (default ON; ``0``/``off`` disables,
the CLI's ``--no-telemetry``).  Off-mode kernels are the exact
pre-telemetry builds (the flag is part of every kernel cache key), so
off costs nothing beyond one cached flag check per drive.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from . import metrics as _metrics
from . import trace as _trace

#: aux block shape — one row per BFS level within a slice; levels past
#: the buffer fold additively into the last row (flagged by the host)
TELE_ROWS = 128
TELE_COLS = 8

#: column indices (see module doc for semantics)
C_OCC, C_EXP, C_KILL, C_DEDUP, C_ROUNDS, C_NEXT, C_OVF, C_GOAL = range(8)

COLUMNS = ("occupancy", "expanded", "mask_killed", "dedup_folds",
           "crash_rounds", "next_count", "overflow", "goal")

#: per-level detail cap on the result block (totals are exact; the
#: per_level list is a bounded sample so result dicts stay storable)
BLOCK_LEVEL_CAP = 512

_TRUTHY = ("1", "true", "on", "yes")

#: module override (tests, CLI); None = follow the env knob
_forced: bool | None = None
#: env knob read ONCE (the off-mode fast path must not pay an environ
#: lookup — or its string allocations — per search drive)
_env_on: bool | None = None
#: serializes the one-time env read against concurrent first callers
#: (fleet/stream threads drive searches too); the hot path stays
#: lock-free — double-checked locking under the GIL
_knob_lock = threading.Lock()


def enabled() -> bool:
    """Is device-search telemetry on?  Default ON; ``JEPSEN_TPU_
    TELEMETRY=0`` (the CLI's ``--no-telemetry``) or :func:`enable`
    turn it off."""
    global _env_on
    if _forced is not None:
        return _forced
    if _env_on is None:
        with _knob_lock:
            if _env_on is None:
                _env_on = os.environ.get(
                    "JEPSEN_TPU_TELEMETRY", "").strip().lower() \
                    not in ("0", "off", "false", "no")
    return _env_on


def enable(on: bool | None = True) -> None:
    """Force telemetry on/off for this process (``None`` reverts to
    the env knob, re-read on next use)."""
    global _forced, _env_on
    with _knob_lock:
        if on is None:
            # clear the cache BEFORE dropping the force: a concurrent
            # enabled() must not see the stale cached knob with the
            # force already gone
            _env_on = None
        _forced = on


# ---------------------------------------------------------------------------
# registry handles (declared in metrics._declare; re-obtained by name)
# ---------------------------------------------------------------------------

_M_LEVELS = _metrics.REGISTRY.counter(
    "jtpu_search_levels_total",
    "Device BFS levels executed (telemetry-observed)")
_M_EXP = _metrics.REGISTRY.counter(
    "jtpu_search_expanded_total",
    "Valid candidate lanes expanded by device BFS levels")
_M_KILL = _metrics.REGISTRY.counter(
    "jtpu_search_mask_killed_total",
    "Candidate lanes killed on-device by the hb/dpor must-order mask")
_M_DEDUP = _metrics.REGISTRY.counter(
    "jtpu_search_dedup_folds_total",
    "Successor states folded onto the dead-value canonical token")
_M_ROUNDS = _metrics.REGISTRY.counter(
    "jtpu_search_crash_rounds_total",
    "Crash-closure rounds executed inside device BFS levels")
_M_OVF = _metrics.REGISTRY.counter(
    "jtpu_search_overflows_total",
    "Device BFS levels that overflowed their frontier width")
_M_RATIO = _metrics.REGISTRY.gauge(
    "jtpu_search_observed_prune_ratio",
    "Observed surviving-lane fraction of the most recent device "
    "search (expanded / (expanded + mask_killed + dedup_folds); "
    "0 = decided without search)")
_M_OCC = _metrics.REGISTRY.histogram(
    "jtpu_search_level_occupancy",
    "Live frontier rows per device BFS level",
    buckets=(1, 8, 64, 512, 4096, 32768, 262144))
_M_DEV_S = _metrics.REGISTRY.counter(
    "jtpu_device_seconds_total",
    "Wall seconds spent inside device.slice executions")
_M_XFER = _metrics.REGISTRY.counter(
    "jtpu_device_transfer_bytes_total",
    "Host<->device bytes staged for search dispatch, by direction",
    ("direction",))
_M_DEVMEM = _metrics.REGISTRY.gauge(
    "jtpu_device_memory_bytes",
    "bytes_in_use reported by the primary device (0 where the "
    "backend has no memory_stats)")


# ---------------------------------------------------------------------------
# host-side unpack + accumulation
# ---------------------------------------------------------------------------


def unpack_levels(tele: np.ndarray) -> list[dict]:
    """Unpack one aux block ([TELE_ROWS, TELE_COLS] int32) into level
    dicts, dropping never-written rows (occupancy 0 — the kernel's
    ``cond`` requires a live frontier, so every executed level has
    occupancy >= 1)."""
    t = np.asarray(tele)
    if t.ndim != 2 or t.shape[1] != TELE_COLS:
        raise ValueError(f"aux block must be [rows, {TELE_COLS}], "
                         f"got {t.shape}")
    out = []
    for r in t:
        if int(r[C_OCC]) <= 0:
            continue
        out.append({name: int(r[i]) for i, name in enumerate(COLUMNS)})
    return out


def observed_prune_ratio(expanded: int, killed: int, folds: int):
    """Surviving-lane fraction — the observed twin of the prepass's
    predicted ``prune_ratio`` (both in (0, 1], smaller = more pruned;
    0 is reserved for statically decided searches).  ``None`` when
    nothing expanded and nothing was killed (no device work)."""
    den = expanded + killed + folds
    if den <= 0:
        return None
    return round(expanded / den, 6)


class SearchTelemetry:
    """Accumulates aux blocks across device slices for ONE search.

    ``add_slice`` ingests a 2-D block (optionally with the slice's
    wall window, for ``device.level`` span emission); ``add_totals``
    ingests batched/aggregated blocks where per-level alignment across
    keys is meaningless (the vmapped ladder) and only totals are kept.
    ``block()`` renders the ``search_telemetry`` result dict.
    """

    def __init__(self, engine: str = "device-bfs"):
        self.engine = engine
        self.levels: list[dict] = []
        self.totals = {name: 0 for name in COLUMNS}
        self.n_levels = 0
        self.max_occupancy = 0
        self.slices = 0
        self.truncated = False  # some slice folded levels into its
        #                         last row (lvl_cap > TELE_ROWS)

    def _tally(self, rows: list[dict]) -> None:
        for r in rows:
            for name in COLUMNS:
                self.totals[name] += r[name]
            self.max_occupancy = max(self.max_occupancy, r["occupancy"])
        self.n_levels += len(rows)

    def add_slice(self, tele: np.ndarray, t0: float | None = None,
                  t1: float | None = None,
                  frontier: int | None = None) -> None:
        """Ingest one slice's aux block.  ``t0``/``t1`` (perf_counter
        readings of the slice window) enable ``device.level`` child
        span emission, apportioned by occupancy — per-level cost is
        proportional to frontier width, so occupancy is the honest
        cheap estimator."""
        rows = unpack_levels(tele)
        self.slices += 1
        if not rows:
            return
        t = np.asarray(tele)
        if int(t[TELE_ROWS - 1, C_OCC]) > 0 and len(rows) == TELE_ROWS:
            # the last row is additive: with every row written it may
            # hold the fold of any levels past the buffer
            self.truncated = True
        base_level = self.n_levels
        self._tally(rows)
        self.levels.extend(rows)
        if t0 is not None and t1 is not None and _trace.enabled():
            rec = _trace.recorder(_trace.current_run())
            occ_sum = sum(r["occupancy"] for r in rows) or 1
            cur = t0
            span = max(0.0, t1 - t0)
            for i, r in enumerate(rows):
                frac = r["occupancy"] / occ_sum
                end = min(t1, cur + span * frac)
                args = {"level": base_level + i, **r}
                if frontier is not None:
                    args["frontier"] = frontier
                rec.record("device.level", "device", cur, end, args)
                cur = end

    def add_totals(self, tele: np.ndarray) -> None:
        """Ingest an aggregate block (e.g. a batch's lane-sum): totals
        and level count only — per-level rows across differently-paced
        keys do not align, so none are kept."""
        t = np.asarray(tele)
        if t.ndim == 3:
            t = t.sum(axis=0)
        rows = unpack_levels(t)
        self.slices += 1
        for r in rows:
            for name in COLUMNS:
                self.totals[name] += r[name]
            self.max_occupancy = max(self.max_occupancy, r["occupancy"])
        self.n_levels += len(rows)

    def block(self, predicted: float | None = None) -> dict:
        """The ``search_telemetry`` result block.  ``predicted`` is
        the prepass's prune_ratio (hb/dpor) when one was computed —
        recorded next to the observed ratio so the two can be diffed
        everywhere downstream.  Deterministic: counters only, no wall
        times (byte-identity across reruns of the same search)."""
        tt = self.totals
        obs_ratio = observed_prune_ratio(
            tt["expanded"], tt["mask_killed"], tt["dedup_folds"])
        out = {
            "levels": self.n_levels,
            "slices": self.slices,
            "max_occupancy": self.max_occupancy,
            "expanded": tt["expanded"],
            "mask_killed": tt["mask_killed"],
            "dedup_folds": tt["dedup_folds"],
            "crash_rounds": tt["crash_rounds"],
            "overflows": tt["overflow"],
            "goals": tt["goal"],
            "observed_prune_ratio": obs_ratio,
            "truncated": self.truncated,
        }
        if predicted is not None:
            out["predicted_prune_ratio"] = predicted
            if obs_ratio is not None:
                out["prune_ratio_delta"] = round(obs_ratio - predicted,
                                                 6)
        per = [[r[name] for name in COLUMNS]
               for r in self.levels[:BLOCK_LEVEL_CAP]]
        if per:
            out["per_level"] = per
            out["per_level_columns"] = list(COLUMNS)
            if self.n_levels > len(per):
                out["per_level_capped"] = True
        return out


def emit_shard_levels(tele: np.ndarray, n_used: int, n_shards: int,
                      t0: float, t1: float) -> None:
    """Per-shard ``device.level`` spans from one batched aux block.

    ``tele`` is the [B, TELE_ROWS, TELE_COLS] lane-stacked block a
    mesh-sharded batch slice returned; the lane axis partitions into
    ``n_shards`` contiguous device blocks (B divisible by the mesh —
    that is what the inert pad lanes guarantee).  Lanes at or past
    ``n_used`` are those mesh-divisibility pads and are EXCLUDED: pad
    lanes must not appear in observed occupancy.  Each shard's lane-sum
    unpacks into its own ``device.level`` spans (args carry
    ``shard=i``), apportioned over the slice window by occupancy — the
    per-shard twin of :meth:`SearchTelemetry.add_slice`'s emission, so
    a trace shows which shards carried the level work and which sat on
    pad-free but idle lanes.  Tracing-gated; totals are NOT tallied
    here (the caller's accumulator ingests the pad-stripped block)."""
    if not _trace.enabled():
        return
    t = np.asarray(tele)
    if t.ndim != 3 or n_shards <= 0 or t.shape[0] % n_shards:
        return
    per = t.shape[0] // n_shards
    rec = _trace.recorder(_trace.current_run())
    span = max(0.0, t1 - t0)
    for s in range(n_shards):
        lo = s * per
        used = min(max(0, n_used - lo), per)
        if used <= 0:
            continue  # all-pad shard: nothing real ran here
        rows = unpack_levels(t[lo:lo + used].sum(axis=0))
        if not rows:
            continue
        occ_sum = sum(r["occupancy"] for r in rows) or 1
        cur = t0
        for i, r in enumerate(rows):
            end = min(t1, cur + span * (r["occupancy"] / occ_sum))
            rec.record("device.level", "device", cur, end,
                       {"level": i, "shard": s, "lanes": used, **r})
            cur = end


def _predicted_ratio(result: dict | None, hbres=None):
    """The prepass's predicted prune_ratio for this search, if any —
    preferring the live hb stats (hbres), falling back to the result's
    attached ``hb`` block."""
    st = None
    if hbres is not None:
        st = getattr(hbres, "stats", None)
    if st is None and isinstance(result, dict):
        hb = result.get("hb")
        if isinstance(hb, dict):
            st = hb
    if isinstance(st, dict) and "prune_ratio" in st:
        try:
            return float(st["prune_ratio"])
        except (TypeError, ValueError):
            return None
    return None


def finalize_result(result: dict, acc: "SearchTelemetry | None", *,
                    hbres=None, attach: bool = True) -> dict:
    """Close out one search's telemetry: compute the block, attach it
    to the result (``attach=True``), bump the ``jtpu_search_*``
    registry, and emit the ``search.telemetry`` span (tracing-gated)
    so traces are self-contained — ``tools/trace_report.py`` and
    ``obs_guard`` read predicted-vs-observed from the span args."""
    if acc is None:
        return result
    predicted = _predicted_ratio(result, hbres)
    blk = acc.block(predicted=predicted)
    tt = acc.totals
    if acc.n_levels:
        _M_LEVELS.inc(acc.n_levels)
        _M_EXP.inc(tt["expanded"])
        _M_KILL.inc(tt["mask_killed"])
        _M_DEDUP.inc(tt["dedup_folds"])
        _M_ROUNDS.inc(tt["crash_rounds"])
        _M_OVF.inc(tt["overflow"])
        for r in acc.levels[:BLOCK_LEVEL_CAP]:
            _M_OCC.observe(r["occupancy"])
    if blk.get("observed_prune_ratio") is not None:
        _M_RATIO.set(blk["observed_prune_ratio"])
    update_device_memory()
    if attach:
        result["search_telemetry"] = blk
    _emit_span(blk)
    return result


def emit_decided(result: dict, hbres=None) -> dict:
    """Telemetry for a search the prepass decided WITHOUT device work:
    an all-zero block whose observed ratio is 0.0 (everything pruned),
    diffed against the predicted 0.0.  Span-only — decided results
    keep their certificate-centric shape (no ``search_telemetry``
    key), but traces still carry the predicted-vs-observed row (the
    10kuniq bench tier is exactly this case)."""
    if not enabled():
        return result
    predicted = _predicted_ratio(result, hbres)
    blk = {"levels": 0, "slices": 0, "max_occupancy": 0, "expanded": 0,
           "mask_killed": 0, "dedup_folds": 0, "crash_rounds": 0,
           "overflows": 0, "goals": 0, "observed_prune_ratio": 0.0,
           "decided": True, "truncated": False}
    blk["predicted_prune_ratio"] = predicted if predicted is not None \
        else 0.0
    blk["prune_ratio_delta"] = round(0.0 - blk["predicted_prune_ratio"],
                                     6)
    _M_RATIO.set(0.0)
    _emit_span(blk)
    return result


def _emit_span(blk: dict) -> None:
    if not _trace.enabled():
        return
    now = time.perf_counter()
    args = {k: v for k, v in blk.items()
            if k not in ("per_level", "per_level_columns")}
    _trace.recorder(_trace.current_run()).record(
        "search.telemetry", "telemetry", now, now, args)


# ---------------------------------------------------------------------------
# compile / transfer / memory accounting
# ---------------------------------------------------------------------------


def record_device_seconds(dt: float) -> None:
    """One device.slice execution's wall seconds — the numerator of
    the derived ``device_idle_fraction`` gauge (/api/stats)."""
    if dt > 0:
        _M_DEV_S.inc(dt)


def record_transfer(nbytes: int, direction: str = "h2d") -> None:
    """Byte-counted host->device staging, next to a ``device.
    transfer`` span when tracing is on."""
    if nbytes <= 0:
        return
    _M_XFER.inc(nbytes, direction=direction)
    if _trace.enabled():
        now = time.perf_counter()
        _trace.recorder(_trace.current_run()).record(
            "device.transfer", "device", now, now,
            {"bytes": int(nbytes), "direction": direction})


def transfer_bytes(arrays) -> int:
    """Total nbytes of a host-array tuple about to be staged."""
    total = 0
    for a in arrays:
        nb = getattr(a, "nbytes", None)
        if nb:
            total += int(nb)
    return total


def persistent_cache_configured() -> bool:
    """Whether a persistent XLA compile cache is configured — via the
    ``JEPSEN_TPU_COMPILE_CACHE_DIR`` env or jax's own
    ``jax_compilation_cache_dir`` knob.  Compile spans record it per
    miss and the fleet warm-boot gate (fleet/warmup.py) reports it per
    worker, so cold-start compile tax is attributable either way."""
    if os.environ.get("JEPSEN_TPU_COMPILE_CACHE_DIR"):
        return True
    try:
        import jax

        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:  # noqa: BLE001 — old jax without the knob
        return False


def compile_span(**attrs):
    """The ``device.compile`` span wrapping one kernel build+jit on a
    cache MISS (hits never enter it — the lookup is a dict get).  Args
    carry the cache verdict and whether a persistent XLA compile cache
    is configured, so cold-start compile tax is attributable from the
    trace alone (the fleet-warmup ROADMAP item's signal)."""
    from .. import obs

    return obs.span("device.compile", cat="device", cache="miss",
                    persistent_cache=persistent_cache_configured(),
                    **attrs)


def update_device_memory() -> None:
    """Refresh the device-memory gauge from the primary device's
    ``memory_stats`` (TPU/GPU report bytes_in_use; CPU backends have
    none and the gauge stays 0)."""
    try:
        import jax

        dev = jax.local_devices()[0]
        stats = dev.memory_stats() if hasattr(dev, "memory_stats") \
            else None
        if stats and "bytes_in_use" in stats:
            _M_DEVMEM.set(float(stats["bytes_in_use"]))
    except Exception:  # noqa: BLE001 — accounting must never raise
        pass
