"""Auto-reconnecting connection wrapper.

Reference: jepsen/src/jepsen/reconnect.clj — a read/write-lock guarded
wrapper around a connection: `with_conn` hands out the live connection;
on error the caller (or the wrapper) closes and reopens it
(reconnect.clj:16-129).  Used by database clients whose connections die
during partitions.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

log = logging.getLogger("jepsen")


def _note_exhausted() -> None:
    """One backoff schedule out of budget — a fleet-health signal (a
    campaign whose exhaustion counter climbs has nodes that stay dead
    through whole ramps) fed to the flight recorder's /metrics."""
    from .obs import metrics as _obs_metrics

    _obs_metrics.REGISTRY.counter(
        "jtpu_backoff_exhausted_total",
        "Reconnect backoff schedules that ran out of budget").inc()


@dataclass
class Backoff:
    """Capped exponential backoff with jitter and an attempts budget.

    The raw schedule is ``min(cap, base * factor**attempt)``; each delay
    is then shortened by up to ``jitter`` of itself (decorrelated
    retries: a fleet of clients reopening after the same crash must not
    reconnect in lockstep).  ``max_attempts`` bounds the whole loop — a
    reopen loop against a dead server terminates with the last error
    instead of spinning forever at a fixed interval.

    ``rng`` is injectable so the schedule is unit-testable."""

    base: float = 0.05
    cap: float = 2.0
    factor: float = 2.0
    max_attempts: int = 8
    jitter: float = 0.5
    rng: random.Random = field(default_factory=random.Random)
    #: stateful cursor for step()/exhausted() loops (health monitors);
    #: run() keeps its own per-call counter and ignores this
    attempt: int = field(default=0, init=False, compare=False)

    def raw_delay(self, attempt: int) -> float:
        """The un-jittered delay before retry ``attempt`` (0-based)."""
        return min(self.cap, self.base * self.factor ** attempt)

    def delay(self, attempt: int) -> float:
        raw = self.raw_delay(attempt)
        return raw * (1.0 - self.jitter * self.rng.random())

    def delays(self) -> list[float]:
        """The whole jittered schedule (one delay per retry; attempt 0
        runs immediately, so there are ``max_attempts - 1`` sleeps)."""
        return [self.delay(i) for i in range(max(0, self.max_attempts - 1))]

    def budget_s(self) -> float:
        """Worst-case total sleep time across the budget (no jitter)."""
        return sum(self.raw_delay(i)
                   for i in range(max(0, self.max_attempts - 1)))

    # -- the stateful schedule (continuous health loops) ---------------

    def step(self) -> float:
        """The next delay in the STATEFUL schedule; the cursor
        advances.  A monitor loop sleeps ``step()`` after each failed
        probe and calls :meth:`reset` after each success, so a node
        that recovers then re-fails starts from the base delay — not
        the capped one it had ratcheted to."""
        d = self.delay(self.attempt)
        self.attempt += 1
        budget = max(1, self.max_attempts) - 1
        if self.attempt == budget or (budget == 0
                                      and self.attempt == 1):
            # the cursor just crossed the budget (a zero-sleep budget
            # is born exhausted: its first step counts) — the same
            # event run() records on its final failure
            _note_exhausted()
        return d

    def exhausted(self) -> bool:
        """Has the stateful cursor spent the schedule's sleep budget
        (``max_attempts - 1`` sleeps — the same budget :meth:`run`
        spends across its ``max_attempts`` calls)?  A bounded loop
        checks this after each failed probe; :meth:`reset` re-arms.
        An exhausted-but-unreset Backoff makes later loops fail FAST
        (one probe, no re-ramp) until a success resets it — the
        self-healing campaign wants a permanently dead node to cost
        one probe per restart attempt, not a full ramp."""
        return self.attempt >= max(1, self.max_attempts) - 1

    def reset(self) -> None:
        """Re-arm the stateful schedule (successful health check)."""
        self.attempt = 0

    def clone(self) -> "Backoff":
        """A state-identical copy: same cursor AND the same rng stream
        position (``delay`` draws from the rng even at ``jitter=0``, so
        two schedules only stay in lockstep if the stream is copied).
        The model checker clones worlds mid-schedule; a shallow copy
        sharing the rng would let one branch advance another's."""
        b = Backoff(base=self.base, cap=self.cap, factor=self.factor,
                    max_attempts=self.max_attempts, jitter=self.jitter,
                    rng=random.Random())
        b.rng.setstate(self.rng.getstate())
        b.attempt = self.attempt
        return b

    def run(self, fn: Callable[[], Any], *, desc: str = "retry",
            sleep: Callable[[float], None] = time.sleep):
        """Call ``fn`` until it returns without raising; sleep the
        jittered schedule between attempts; after ``max_attempts``
        failures re-raise the last error."""
        last: Optional[BaseException] = None
        for attempt in range(max(1, self.max_attempts)):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — caller's fn decides
                last = e
                if attempt + 1 >= self.max_attempts:
                    _note_exhausted()
                    break
                d = self.delay(attempt)
                log.debug("%s failed (attempt %d/%d): %s; retrying in "
                          "%.3fs", desc, attempt + 1, self.max_attempts,
                          e, d)
                sleep(d)
        raise last  # type: ignore[misc]


class Wrapper:
    """reconnect.clj:16-56: open/close/name/log? policy functions."""

    def __init__(self, open: Callable[[], Any],
                 close: Callable[[Any], None] = lambda c: None,
                 name: str = "conn", log_errors: bool = True,
                 backoff: Optional[Backoff] = None):
        self._open = open
        self._close = close
        self.name = name
        self.log_errors = log_errors
        self.backoff = backoff
        self._lock = threading.RLock()
        self._conn: Optional[Any] = None
        self._closed = True

    def _open_retrying(self):
        """One open attempt, or the backoff-scheduled reopen loop when a
        :class:`Backoff` was given — capped exponential + jitter with an
        attempts budget, never a fixed-interval spin."""
        if self.backoff is None:
            return self._open()
        return self.backoff.run(self._open, desc=f"open {self.name}")

    def open(self) -> "Wrapper":
        """reconnect.clj:58-66."""
        with self._lock:
            if self._closed:
                self._conn = self._open_retrying()
                self._closed = False
        return self

    def conn(self):
        with self._lock:
            if self._closed:
                self.open()
            return self._conn

    def reopen(self) -> "Wrapper":
        """Close (ignoring errors) and open a fresh conn
        (reconnect.clj:77-90)."""
        with self._lock:
            try:
                if self._conn is not None:
                    self._close(self._conn)
            except Exception as e:
                if self.log_errors:
                    log.warning("error closing %s: %s", self.name, e)
            self._conn = self._open_retrying()
            self._closed = False
        return self

    def close(self) -> None:
        """reconnect.clj:103-112."""
        with self._lock:
            try:
                if self._conn is not None:
                    self._close(self._conn)
            finally:
                self._conn = None
                self._closed = True

    def with_conn(self, f: Callable[[Any], Any]):
        """Run f(conn); on error, reopen the conn and re-raise
        (reconnect.clj:92-101)."""
        c = self.conn()
        try:
            return f(c)
        except Exception as e:
            if self.log_errors:
                log.warning("error on %s: %s; reopening", self.name, e)
            try:
                self.reopen()
            except Exception as e2:
                if self.log_errors:
                    log.warning("error reopening %s: %s", self.name, e2)
            raise e


def wrapper(**kw) -> Wrapper:
    return Wrapper(**kw)
