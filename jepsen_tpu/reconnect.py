"""Auto-reconnecting connection wrapper.

Reference: jepsen/src/jepsen/reconnect.clj — a read/write-lock guarded
wrapper around a connection: `with_conn` hands out the live connection;
on error the caller (or the wrapper) closes and reopens it
(reconnect.clj:16-129).  Used by database clients whose connections die
during partitions.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional

log = logging.getLogger("jepsen")


class Wrapper:
    """reconnect.clj:16-56: open/close/name/log? policy functions."""

    def __init__(self, open: Callable[[], Any],
                 close: Callable[[Any], None] = lambda c: None,
                 name: str = "conn", log_errors: bool = True):
        self._open = open
        self._close = close
        self.name = name
        self.log_errors = log_errors
        self._lock = threading.RLock()
        self._conn: Optional[Any] = None
        self._closed = True

    def open(self) -> "Wrapper":
        """reconnect.clj:58-66."""
        with self._lock:
            if self._closed:
                self._conn = self._open()
                self._closed = False
        return self

    def conn(self):
        with self._lock:
            if self._closed:
                self.open()
            return self._conn

    def reopen(self) -> "Wrapper":
        """Close (ignoring errors) and open a fresh conn
        (reconnect.clj:77-90)."""
        with self._lock:
            try:
                if self._conn is not None:
                    self._close(self._conn)
            except Exception as e:
                if self.log_errors:
                    log.warning("error closing %s: %s", self.name, e)
            self._conn = self._open()
            self._closed = False
        return self

    def close(self) -> None:
        """reconnect.clj:103-112."""
        with self._lock:
            try:
                if self._conn is not None:
                    self._close(self._conn)
            finally:
                self._conn = None
                self._closed = True

    def with_conn(self, f: Callable[[Any], Any]):
        """Run f(conn); on error, reopen the conn and re-raise
        (reconnect.clj:92-101)."""
        c = self.conn()
        try:
            return f(c)
        except Exception as e:
            if self.log_errors:
                log.warning("error on %s: %s; reopening", self.name, e)
            try:
                self.reopen()
            except Exception as e2:
                if self.log_errors:
                    log.warning("error reopening %s: %s", self.name, e2)
            raise e


def wrapper(**kw) -> Wrapper:
    return Wrapper(**kw)
