"""Piecewise device microbenchmark for the search-kernel ops.

Times one full kernel level at several frontier widths, then each
pipeline stage in isolation (expand, hash, the dedup sort in both
variadic and packed forms, gathers in row-major and transposed layouts,
stream compaction), emitting one JSON line per measurement.  The point:
locate WHERE per-level cost explodes with width on a given backend — on
TPU the jump from F=1024 to F=8192 was measured at ~1600x (0.02 ->
32 ms/level) while CPU scales linearly, so some op hits a cliff that
linear reasoning cannot find.  Run this on the device, read the table,
then optimize the guilty op.

Usage:
    python tools/tpubench.py [--widths 1024,8192,65536] [--repeat 5]
    JAX_PLATFORMS=cpu python tools/tpubench.py   # CPU comparison
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # env alone does NOT stop this image's sitecustomize-registered TPU
    # plugin (verified: `JAX_PLATFORMS=cpu python -c "import jax;
    # jax.devices()"` hangs on the axon tunnel); the config pin must
    # land before first backend touch (tests/conftest.py:10-23)
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402


def bench_one(name: str, fn, *args, repeat: int = 5) -> dict:
    f = jax.jit(fn)
    t0 = time.perf_counter()
    out = f(*args)
    jax.block_until_ready(out)
    t_compile = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(repeat):
        out = f(*args)
    jax.block_until_ready(out)
    ms = (time.perf_counter() - t0) / repeat * 1000
    row = {"op": name, "ms": round(ms, 4),
           "compile_s": round(t_compile, 2)}
    print(json.dumps(row), flush=True)
    return row


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--widths", default="1024,8192,65536")
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--levels", type=int, default=64)
    args = ap.parse_args()
    widths = [int(w) for w in args.widths.split(",")]
    rep = args.repeat

    print(json.dumps({"backend": jax.default_backend(),
                      "device": str(jax.devices()[0])}), flush=True)

    import bench as hbench
    from jepsen_tpu.checker import linearizable as lin

    seq, model = hbench.make_seq("10k")
    es = lin.encode_search(seq)

    for F in widths:
        dims = lin.choose_dims(es, model, frontier=F)
        esp = lin.pad_search(es, dims.n_det_pad, dims.n_crash_pad)
        K, WORDS = dims.k, dims.words
        S = 4 * F
        rng = np.random.default_rng(0)

        # --- full kernel level, BOTH dominance prunes ------------------
        # the all-pairs prune exists to beat the sort pipeline's per-op
        # overhead floor at narrow widths; these paired rows are the
        # decisive on-chip measurement (skip all-pairs where its [M,M]
        # intermediates get silly — auto never picks it there either)
        kargs = lin.search_args(esp, es)
        lvls = jnp.int32(args.levels)
        modes = ["sort"] + (["allpairs"] if S <= lin._ALLPAIRS_MAX
                            else [])
        mode0 = lin._DOMINANCE_MODE
        for mode in modes:
            lin._DOMINANCE_MODE = mode
            try:
                # what the selector ACTUALLY chooses per site under
                # this mode (a forced "allpairs" can still fall back to
                # sort past the element budget — the row must say so)
                ap_cl = lin._use_allpairs(2 * F)
                ap_det = lin._use_allpairs(4 * F)
                fn = lin.get_kernel(model, dims)
                carry = tuple(jnp.asarray(c)
                              for c in lin._init_carry(dims, model))

                n_args = len(kargs)

                def level_fn(*a):
                    return fn(*a[:n_args], jnp.int32(10**9), lvls,
                              jnp.bool_(False), *a[n_args:])

                t0 = time.perf_counter()
                out = level_fn(*kargs, *carry)
                jax.block_until_ready(out)
                t_compile = time.perf_counter() - t0
                # repeat like every other row: a single-shot reading
                # straight after a ~30s tunnel compile has been observed
                # BELOW the ~14ms dispatch floor (r4, F=8192) — an
                # artifact, not physics
                dts = []
                for _ in range(rep):
                    t0 = time.perf_counter()
                    out = level_fn(*kargs, *carry)
                    jax.block_until_ready(out)
                    dts.append(time.perf_counter() - t0)
            finally:
                lin._DOMINANCE_MODE = mode0
            _fr, count, status, configs, max_depth, ovf = out
            # levels actually executed (each level linearizes one det
            # op); the while_loop exits early on frontier death /
            # verdict.  max_depth snapshots the ENTRY frontier of the
            # last body iteration (depth starts at 0), so L executed
            # levels report max_depth = L-1
            lvls_run = int(max_depth) + 1
            print(json.dumps({
                "op": f"kernel-{args.levels}-levels", "F": F, "K": K,
                "WORDS": WORDS, "dominance": mode,
                "allpairs_closure": ap_cl, "allpairs_det": ap_det,
                "ms_per_level": round(min(dts) / lvls_run * 1000, 4),
                "ms_per_level_mean": round(sum(dts) / len(dts)
                                           / lvls_run * 1000, 4),
                "levels_run": lvls_run,
                "carry": {"count": int(count), "status": int(status),
                          "configs": int(configs), "ovf": bool(ovf)},
                "compile_s": round(t_compile, 2)}), flush=True)

        # --- isolated pieces at the same shapes ------------------------
        keys32 = jnp.asarray(
            rng.integers(0, 2**31, S).astype(np.uint32))
        cfgs = jnp.asarray(
            rng.integers(0, 1000, (S, WORDS)).astype(np.int32))
        cfgsT = jnp.asarray(np.asarray(cfgs).T.copy())
        idx = jnp.asarray(rng.integers(0, S, S).astype(np.int32))
        mask = jnp.asarray(rng.random(S) < 0.2)
        frontier = jnp.asarray(
            rng.integers(0, 1000, (F, WORDS)).astype(np.int32))
        alive = jnp.ones(F, bool)

        pieces = lin._make_kernel_pieces(model, dims)

        def mask_fn(fr, al):
            base, sargs = lin._slice_tables(kargs, fr, al,
                                            w2p=pieces["w2p"])
            v, c, ns, g = pieces["expand_mask"](fr, al, base, *sargs)
            return v.sum(), c.sum(), ns.sum(), g.sum()

        bench_one(f"expand_mask F={F}", mask_fn, frontier, alive,
                  repeat=rep)

        def succ_fn(fr, al):
            v, c, ns, g = lin._level_mask(pieces, kargs, fr, al)
            cc, cv, n = lin._succ_block(pieces, fr,
                                        v.reshape(F * K), c, ns, S, K)
            return cc.sum(), cv.sum()

        bench_one(f"expand+succ(S) F={F}", succ_fn, frontier,
                  alive, repeat=rep)
        bench_one(f"hash S={S}",
                  lambda c: lin._hash_words(c.astype(jnp.uint32),
                                            0x9E3779B1).sum(),
                  cfgs, repeat=rep)
        # the production dominance sort is 3-operand / 2-key
        # (_sort_dominance); these two isolate the raw lax.sort cost at
        # the same row count for single- vs multi-operand forms
        bench_one(
            f"sort-variadic S={S}",
            lambda k: lax.sort((k, jnp.arange(S, dtype=jnp.int32)),
                               num_keys=1),
            keys32, repeat=rep)
        bench_one(f"sort-packed32 S={S}", lambda k: lax.sort(k),
                  keys32, repeat=rep)
        bench_one(f"gather-rows [S,{WORDS}] S={S}",
                  lambda c, i: jnp.take(c, i, axis=0).sum(), cfgs, idx,
                  repeat=rep)
        bench_one(f"gather-cols [{WORDS},S] S={S}",
                  lambda c, i: jnp.take(c, i, axis=1).sum(), cfgsT, idx,
                  repeat=rep)
        bench_one(f"compact_indices S={S}",
                  lambda m: lin._compact_indices(m, S // 4), mask,
                  repeat=rep)

        def dom_fn(c, m):
            pwh, popc = lin._pw_parts(c, dims)
            kept, sc, perm = lin._sort_dominance(pwh, popc, m, c, S,
                                                 dims)
            return kept.sum(), sc.sum()

        bench_one(f"sort_dominance S={S}", dom_fn, cfgs, mask,
                  repeat=rep)

        # 64 chained prunes in ONE dispatch: the standalone rows above
        # are floored by the ~14ms tunnel dispatch cost; these isolate
        # the true in-kernel per-application cost of each prune form
        # (the chain is data-dependent, so nothing hoists)
        def loop64(prune_fn):
            def run(c, m):
                def body(_i, carry):
                    cc, mm = carry
                    kept, sc = prune_fn(cc, mm)
                    # the output must differ from the input or XLA
                    # recognizes the loop body as identity and deletes
                    # the chain (observed: a 0.0005 ms "prune")
                    return sc + kept[:, None].astype(jnp.int32), mm
                return lax.fori_loop(0, 64, body, (c, m))[0].sum()
            return run

        def sort_prune(c, m):
            pwh, popc = lin._pw_parts(c, dims)
            kept, sc, _ = lin._sort_dominance(pwh, popc, m, c, S, dims)
            return kept, sc

        bench_one(f"sort_dominance-loop64 S={S}", loop64(sort_prune),
                  cfgs, mask, repeat=rep)
        if S <= lin._ALLPAIRS_MAX:
            def ap_prune(c, m):
                kept = lin._allpairs_dominance(c, m, dims)
                return kept, c

            bench_one(f"allpairs_dominance-loop64 S={S}",
                      loop64(ap_prune), cfgs, mask, repeat=rep)
        bench_one(f"neighbor-dedup S={S}",
                  lambda c: (jnp.all(c[1:] == c[:-1], axis=1)).sum(),
                  cfgs, repeat=rep)

    # --- engine-paired rows: pallas level-loop vs XLA step -----------
    # The pallas kernel (checker/pallas_level.py) fuses the whole level
    # loop into one device op to beat the ~1.3 ms/level op-count floor
    # (docs/perf-notes.md r4).  mutex2k is the eligibility-friendly
    # history (window 32); these rows are the decisive on-chip A/B.
    # A Mosaic lowering failure must emit a diagnostic row, not kill
    # the sweep — it would be the first hardware contact for the path.
    from jepsen_tpu.checker import pallas_level as plev

    seqm, modelm = hbench.make_seq("mutex2k")
    esm = lin.encode_search(seqm)
    for F in (16, 64):
        dimsm = lin.choose_dims(esm, modelm, frontier=F)
        if not plev.eligible(modelm, dimsm):
            print(json.dumps({"op": "engine-pair", "F": F,
                              "skipped": "ineligible dims",
                              "dims": str(dimsm)}), flush=True)
            continue
        espm = lin.pad_search(esm, dimsm.n_det_pad, dimsm.n_crash_pad)
        kargsm = lin.search_args(espm, esm)
        mode0 = lin._DOMINANCE_MODE
        for engine in ("xla", "pallas"):
            try:
                lin._DOMINANCE_MODE = "allpairs"
                if engine == "pallas":
                    step = jax.jit(plev.build_pallas_step_fn(
                        modelm, dimsm,
                        interpret=jax.default_backend() != "tpu"))
                else:
                    step = jax.jit(lin.build_search_step_fn(modelm,
                                                            dimsm))
                carry = tuple(jnp.asarray(c)
                              for c in lin._init_carry(dimsm, modelm))
                t0 = time.perf_counter()
                out = step(*kargsm, jnp.int32(10**9),
                           jnp.int32(args.levels), jnp.bool_(False),
                           *carry)
                jax.block_until_ready(out)
                t_compile = time.perf_counter() - t0
                dts = []
                for _ in range(rep):
                    t0 = time.perf_counter()
                    out = step(*kargsm, jnp.int32(10**9),
                               jnp.int32(args.levels), jnp.bool_(False),
                               *carry)
                    jax.block_until_ready(out)
                    dts.append(time.perf_counter() - t0)
                lvls_run = int(out[4]) + 1
                print(json.dumps({
                    "op": f"engine-{args.levels}-levels", "F": F,
                    "engine": engine, "history": "mutex2k",
                    "ms_per_level": round(min(dts) / lvls_run * 1000,
                                          4),
                    "levels_run": lvls_run,
                    "carry": {"count": int(out[1]),
                              "status": int(out[2]),
                              "configs": int(out[3]),
                              "ovf": bool(out[5])},
                    "compile_s": round(t_compile, 2)}), flush=True)
            except Exception as e:  # noqa: BLE001 — diagnostic row
                print(json.dumps({"op": f"engine-{args.levels}-levels",
                                  "F": F, "engine": engine,
                                  "error": repr(e)[:500]}), flush=True)
            finally:
                lin._DOMINANCE_MODE = mode0


if __name__ == "__main__":
    main()
