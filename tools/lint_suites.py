#!/usr/bin/env python
"""Standalone suite protocol lint — jepsen_tpu.analyze.suites as a CLI.

    python tools/lint_suites.py        # lint bundled suites AND live/
    python tools/lint_suites.py path/to/suite.py another_dir/
    python tools/lint_suites.py --json           # machine-readable

Files under a ``live/`` directory additionally get the B-code backend
lint (LiveBackend protocol conformance, crash-to-:fail swallowing,
fsync-before-rename journal ordering).

The default sweep (no paths) also runs the T-code thread/lock-
discipline lint over the service tiers (``jepsen_tpu/fleet/``,
``stream/``, ``obs/``, ``decompose/cache.py``, ``checker/bucket.py``)
— shared-state RMW without a lock, acquire without try/finally,
flock'd writes without fsync, spans without the ``run=`` pin.  Skip it
with ``--no-threads``; run it alone with ``--threads``.

The default sweep also runs the N-code knob-threading lint (every
``JEPSEN_TPU_*`` env knob the package reads must be CLI-reachable,
not frozen at import time when cli.py claims it, and documented) and
the O-code metrics-contract lint (every ``jtpu_*`` series a consumer
surface references must be registered; registered-but-unreferenced
orphans are flagged once, aggregated).  Skip with ``--no-knobs`` /
``--no-metrics``; run alone with ``--knobs`` / ``--metrics``.

The default sweep also runs the R-code retry-idempotency lint (a
mutation retried automatically — ``Backoff.run``, ``with_conn``, or
an attempt-shaped broad-except loop — must be able to complete
``:info`` on the ambiguous outcome; a bounded retry loop must not
swallow its final error).  Skip with ``--no-retry``; run alone with
``--retry``.  The model checker's MC201 certificate is the dynamic
twin of R001 (docs/analyze.md §12).

Exit code 0 when no ERROR-severity findings (warnings don't fail the
run), 1 otherwise.  The same check gates CI through
tests/test_suite_lint.py, so a new suite cannot merge with protocol
violations.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.analyze.suites import (  # noqa: E402
    SUITE_CODES,
    lint_knobs,
    lint_metrics,
    lint_paths,
    lint_retry,
    lint_thread_tier,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="AST protocol lint over jepsen suites, live "
                    "backends, and the threaded service tiers "
                    "(S-/B-/T-codes; see docs/analyze.md)")
    p.add_argument("paths", nargs="*",
                   help="suite files or directories (default: "
                        "jepsen_tpu/suites + jepsen_tpu/live)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--codes", action="store_true",
                   help="list the S-/B-/T-codes and exit")
    p.add_argument("--threads", action="store_true",
                   help="run ONLY the T-code thread/lock lint")
    p.add_argument("--no-threads", action="store_true",
                   help="skip the T-code lint in the default sweep")
    p.add_argument("--knobs", action="store_true",
                   help="run ONLY the N-code knob-threading lint")
    p.add_argument("--no-knobs", action="store_true",
                   help="skip the N-code lint in the default sweep")
    p.add_argument("--metrics", action="store_true",
                   help="run ONLY the O-code metrics-contract lint")
    p.add_argument("--no-metrics", action="store_true",
                   help="skip the O-code lint in the default sweep")
    p.add_argument("--retry", action="store_true",
                   help="run ONLY the R-code retry-idempotency lint")
    p.add_argument("--no-retry", action="store_true",
                   help="skip the R-code lint in the default sweep")
    opts = p.parse_args(argv)
    if opts.codes:
        for code, desc in sorted(SUITE_CODES.items()):
            print(f"{code}  {desc}")
        return 0

    only = opts.threads or opts.knobs or opts.metrics or opts.retry
    findings: dict = {}
    if not only:
        findings = lint_paths(opts.paths)
    # tier-wide passes: part of the default sweep (explicit paths mean
    # the caller scoped the run to specific suites, so leave them out
    # unless their --flag asked for them)
    sweep = not opts.paths and not only
    if opts.threads or (sweep and not opts.no_threads):
        for f, ds in lint_thread_tier().items():
            findings.setdefault(f, []).extend(ds)
    if opts.knobs or (sweep and not opts.no_knobs):
        for f, ds in lint_knobs().items():
            findings.setdefault(f, []).extend(ds)
    if opts.metrics or (sweep and not opts.no_metrics):
        for f, ds in lint_metrics().items():
            findings.setdefault(f, []).extend(ds)
    if opts.retry or (sweep and not opts.no_retry):
        for f, ds in lint_retry().items():
            findings.setdefault(f, []).extend(ds)
    n_err = sum(1 for ds in findings.values()
                for d in ds if d.severity == "error")
    n_warn = sum(1 for ds in findings.values()
                 for d in ds if d.severity == "warning")
    if opts.as_json:
        print(json.dumps({
            "errors": n_err,
            "warnings": n_warn,
            "files": {f: [d.to_dict() for d in ds]
                      for f, ds in findings.items()},
        }, indent=2))
    else:
        for _f, ds in sorted(findings.items()):
            for d in ds:
                print(f"{d.severity.upper()} {d.code} {d.message}")
        print(f"{n_err} error(s), {n_warn} warning(s)")
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
