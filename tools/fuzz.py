"""Differential fuzzer for the linearizability engines, with shrinking.

The reference's trust story for its checker is knossos `competition` —
racing two independent algorithms and taking the first answer
(jepsen/src/jepsen/checker.clj:122-126).  This goes further: generate
random histories (valid-by-construction, corrupted, and crash-heavy),
require the device BFS engine and the exact host DFS oracle
(checker/seq.py) to agree, and on ANY disagreement shrink the history to
a minimal counterexample before reporting — the artifact a human needs
to debug a checker divergence is the 6-op core, not the 400-op haystack.

Usage:
    python tools/fuzz.py --rounds 200 [--seed 0] [--n-ops 60]
                         [--model cas-register|register|mutex|
                                  unordered-queue|fifo-queue]
    python tools/fuzz.py --corpus [store/corpus]

``--corpus`` is the campaign->fuzz regression net (live/corpus.py):
every banked live-campaign history replays through ALL engine routes —
direct device BFS, decomposed, bucketed, streaming — with
verdict-parity assertions, a banked-expectation check, and the
certificate audit; queue (multiset) entries replay through
``total_queue``; engine entries additionally replay through the
dedup+DPOR route (analyze/dpor.py) forced on AND off as an extra
bit-identical-parity + audit leg.  Exit 1 on any parity break,
expectation mismatch, or W-code.

Exit code 0 = no divergence; 1 = divergence found (minimal repro printed
as JSON ops, replayable via --replay FILE).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # env alone does not stop the sitecustomize-registered TPU plugin;
    # pin via config before first backend touch (tests/conftest.py:10-23)
    import jax

    jax.config.update("jax_platforms", "cpu")

from jepsen_tpu.checker import linearizable as lin, seq as oracle  # noqa: E402
from jepsen_tpu.history import Op, encode_ops, info_op, invoke_op, ok_op  # noqa: E402
from jepsen_tpu.models import (  # noqa: E402
    cas_register, fifo_queue, mutex, register, unordered_queue,
)

MODELS = {
    "cas-register": cas_register,
    "register": lambda: register(0),
    "mutex": mutex,
    # capacity bounds the multiset; #enqueues never exceeds n-ops, and
    # the fuzzer caps queue histories at 32 ops (see gen_history)
    "unordered-queue": lambda: unordered_queue(33),
    "fifo-queue": lambda: fifo_queue(33),
}

#: queue configs carry a 33-lane state; keep their histories small
QUEUE_MAX_OPS = 32


def gen_history(rng: random.Random, model_name: str, n_ops: int,
                n_procs: int, crash_p: float) -> list[Op]:
    """Canonical simulators live in jepsen_tpu/synth.py (shared with the
    differential tests)."""
    from jepsen_tpu.synth import (
        sim_mutex_history, sim_queue_history, sim_register_history,
    )

    if model_name == "mutex":
        return sim_mutex_history(rng, n_ops, n_procs, crash_p=crash_p)
    if model_name in ("unordered-queue", "fifo-queue"):
        return sim_queue_history(rng, min(n_ops, QUEUE_MAX_OPS), n_procs,
                                 crash_p=crash_p,
                                 fifo=model_name == "fifo-queue")
    return sim_register_history(rng, n_procs, n_ops, crash_p=crash_p,
                                cas=(model_name == "cas-register"),
                                max_crashes=16)


def corrupt(rng: random.Random, h: list[Op]) -> list[Op]:
    from jepsen_tpu.synth import corrupt_dequeue, mutate

    if any(op.f == "dequeue" for op in h) and rng.random() < 0.5:
        # queue-specific corruptions: a from-thin-air dequeue, or a
        # service-order swap (mutate's flip_read arm is a no-op here)
        from jepsen_tpu.synth import swap_dequeues

        if rng.random() < 0.5:
            return swap_dequeues(rng, h)
        return corrupt_dequeue(rng, h)
    return mutate(rng, h)


#: per-engine work caps — mutated histories can explode combinatorially;
#: rounds where either engine gives up are skipped, not flagged
ORACLE_CAP = 40_000
DEVICE_BUDGET = 120_000


def results(h: list[Op], model):
    """Three-way full results: (WGL oracle, device BFS, linear host
    sweep) — or None on an encode error (the caller reads
    ``verdicts`` for that case).  The linear sweep runs with a witness
    cap so its valid verdicts carry auditable certificates."""
    from jepsen_tpu.checker.linear import check_opseq_linear

    s = encode_ops(h, model.f_codes)
    a = oracle.check_opseq(s, model, max_configs=ORACLE_CAP)
    b = lin.search_opseq(s, model, budget=DEVICE_BUDGET)
    c = check_opseq_linear(s, model, max_configs=ORACLE_CAP,
                           witness_cap=500_000)
    return s, (a, b, c)


def verdicts(h: list[Op], model) -> tuple:
    """Three-way: (WGL oracle, device BFS, linear host sweep)."""
    try:
        _s, (a, b, c) = results(h, model)
    except Exception as e:
        err = ("encode-error", str(e))
        return err, err, err
    return a["valid"], b["valid"], c["valid"]


def audit_results(s, model, rs) -> list:
    """Certificate audit over one round's three engine results:
    returns the W-code diagnostics found (empty = all certificates
    replay clean).  Fails loudly in --audit mode: a certificate its
    own engine cannot replay is an engine bug even when all three
    verdicts agree."""
    from jepsen_tpu.analyze.audit import audit

    bad = []
    for engine, r in zip(("oracle", "device", "linear"), rs):
        a = audit(s, model, r)
        if not a["ok"]:
            bad.extend((engine, d) for d in a["diagnostics"])
    return bad


def _diverge(vs) -> bool:
    vs = [v for v in vs if v != "unknown"]
    return len(set(vs)) > 1  # capped-out engines are not divergences


def diverges(h: list[Op], model) -> bool:
    return _diverge(verdicts(h, model))


def shrink(h: list[Op], model, *, max_passes: int = 8) -> list[Op]:
    """Greedy delta-debugging: repeatedly drop op *pairs* (invoke + its
    completion) and lone ops while the divergence persists."""
    from dataclasses import replace as _r  # noqa: F401

    cur = list(h)
    for _ in range(max_passes):
        changed = False
        # try dropping each process's whole op stream first (coarse)
        procs = sorted({op.process for op in cur})
        for p in procs:
            cand = [op for op in cur if op.process != p]
            if len(cand) < len(cur) and cand and diverges(cand, model):
                cur = cand
                changed = True
        # then drop invoke+completion pairs (fine)
        i = 0
        while i < len(cur):
            op = cur[i]
            if op.type == "invoke":
                js = [j for j in range(i + 1, len(cur))
                      if cur[j].process == op.process]
                drop = {i} | ({js[0]} if js else set())
            else:
                drop = {i}
            cand = [op for j, op in enumerate(cur) if j not in drop]
            if cand and diverges(cand, model):
                cur = cand
                changed = True
            else:
                i += 1
        if not changed:
            break
    return cur


def corpus_replay(pool_dir: str, *, audit: bool = True,
                  max_entries: int | None = None,
                  budget: int = DEVICE_BUDGET) -> int:
    """Replay the banked campaign corpus through every engine route.

    Engine entries (register/mutex models) run direct (device BFS),
    decomposed, bucketed, and streaming — plus the HB pre-pass
    (analyze/hb.py): every banked history replays through the static
    order-solver, and when it decides fast its verdict joins the
    parity set and its certificate (GK witness or HB-cycle) goes
    through the independent audit like any engine's.  All decided
    verdicts must be bit-identical to each other AND to the banked
    expectation (when one was recorded), and every certificate must
    audit clean.  Queue entries replay deterministically through
    ``total_queue`` against their banked verdict.  Returns 0 clean /
    1 on any failure."""
    from jepsen_tpu.analyze.audit import audit as audit_fn
    from jepsen_tpu.analyze.hb import hb_dispose
    from jepsen_tpu.decompose.engine import check_opseq_decomposed
    from jepsen_tpu.live import corpus as corpus_mod
    from jepsen_tpu.stream import StreamChecker

    entries = corpus_mod.load_pool(pool_dir)
    if max_entries is not None:
        entries = entries[:max_entries]
    if not entries:
        print(f"corpus: no entries under {pool_dir}")
        return 0
    t0 = time.time()
    failures = unknowns = hb_decided = 0
    for i, e in enumerate(entries):
        label = (f"{e.get('family')}×{e.get('nemesis')}"
                 f"{' seeded' if e.get('seeded') else ''} "
                 f"[{e['id'][:12]}]")
        ops = [Op.from_dict(d) for d in e["ops"]]
        banked = e.get("valid")
        try:
            if e.get("routes") == "queue":
                r = corpus_mod.replay_queue(ops)
                verdicts = {"total-queue": r["valid"]}
                # the static constraint compiler's event-level multiset
                # analysis joins the parity set: same verdict, with
                # W007-auditable evidence rows on invalid
                from jepsen_tpu.analyze.constraints import \
                    analyze_queue_events

                ca = analyze_queue_events(ops)
                verdicts["constraints"] = ca["valid"]
                if ca["valid"] is False and ca.get("evidence"):
                    from jepsen_tpu.analyze.audit import audit_events

                    a = audit_events(ops, {
                        "valid": False, "queue_evidence": ca["evidence"]})
                    if not a["ok"]:
                        print(f"CORPUS AUDIT FAILURE {label}: "
                              f"{[str(d) for d in a['diagnostics']]}",
                              file=sys.stderr)
                        failures += 1
                        continue
                results = []
            else:
                model = corpus_mod.entry_model(e)
                s = encode_ops(ops, model.f_codes)
                direct = lin.search_opseq(s, model, budget=budget)
                decomposed = check_opseq_decomposed(s, model,
                                                    witness=True)
                bucketed = lin.search_batch([s], model, bucket=True,
                                            budget=budget)[0]
                sc = StreamChecker(model)
                for op in ops:
                    sc.ingest(op)
                streamed = sc.finalize()
                verdicts = {"direct": direct["valid"],
                            "decomposed": decomposed["valid"],
                            "bucketed": bucketed["valid"],
                            "streaming": streamed["valid"]}
                results = [("direct", s, model, direct),
                           ("decomposed", s, model, decomposed),
                           ("bucketed", s, model, bucketed),
                           ("streaming", s, model, streamed)]
                hbr = hb_dispose(s, model)
                if hbr is not None:
                    # the static solver decided this banked history
                    # outright: its verdict must match every engine's,
                    # and its certificate must audit like theirs
                    hb_decided += 1
                    verdicts["hb"] = hbr["valid"]
                    results.append(("hb", s, model, hbr))
                # dpor parity leg: the dynamic layer (duplicate-op
                # edges, sleep sets, dead-value dedup, device mask
                # planes) must be verdict-transparent on every banked
                # history — replay the host DFS route with dpor forced
                # ON and OFF and require bit-identical verdicts; the
                # dpor-on certificate goes through the audit like any
                # engine's (regression teeth in tests/test_corpus.py)
                d_on = oracle.check_opseq(s, model,
                                          max_configs=ORACLE_CAP,
                                          dpor=True)
                d_off = oracle.check_opseq(s, model,
                                           max_configs=ORACLE_CAP,
                                           dpor=False)
                verdicts["dpor"] = d_on["valid"]
                verdicts["dpor-off"] = d_off["valid"]
                results.append(("dpor", s, model, d_on))
        except Exception as exc:  # noqa: BLE001 — report, keep going
            print(f"CORPUS FAILURE {label}: replay crashed: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            failures += 1
            continue
        decided = {k: v for k, v in verdicts.items()
                   if v not in ("unknown",)}
        unknowns += len(verdicts) - len(decided)
        if len(set(decided.values())) > 1:
            print(f"CORPUS DIVERGENCE {label}: {verdicts}",
                  file=sys.stderr)
            failures += 1
            continue
        if banked is not None and decided \
                and set(decided.values()) != {banked}:
            print(f"CORPUS REGRESSION {label}: banked verdict "
                  f"{banked}, engines now say {verdicts}",
                  file=sys.stderr)
            failures += 1
            continue
        mi = e.get("minimal")
        if mi:
            # bank-time ddmin contract: the stored minimal repro must
            # still reproduce the invalid verdict on its route — a
            # minimal core that stopped failing is a checker (or
            # shrinker) regression
            mops = [Op.from_dict(d) for d in mi["ops"]]
            try:
                if e.get("routes") == "queue":
                    mv = corpus_mod.replay_queue(mops)["valid"]
                else:
                    m2 = corpus_mod.entry_model(e)
                    ms = encode_ops(mops, m2.f_codes)
                    mv = oracle.check_opseq(
                        ms, m2, max_configs=ORACLE_CAP)["valid"]
            except Exception as exc:  # noqa: BLE001
                print(f"CORPUS MINIMAL FAILURE {label}: replay "
                      f"crashed: {type(exc).__name__}: {exc}",
                      file=sys.stderr)
                failures += 1
                continue
            if mv is not False:
                print(f"CORPUS MINIMAL FAILURE {label}: the banked "
                      f"{mi['n_ops']}-op minimal repro no longer "
                      f"reproduces invalid (got {mv!r})",
                      file=sys.stderr)
                failures += 1
                continue
        if audit:
            bad = []
            for engine, s_, m_, r_ in results:
                a = audit_fn(s_, m_, r_)
                if not a["ok"]:
                    bad.extend((engine, d) for d in a["diagnostics"])
            if bad:
                print(f"CORPUS AUDIT FAILURE {label}:",
                      file=sys.stderr)
                for engine, d in bad:
                    print(f"  [{engine}] {d}", file=sys.stderr)
                failures += 1
    status = "CLEAN" if failures == 0 else f"{failures} FAILURE(S)"
    print(f"corpus: {len(entries)} entr"
          f"{'y' if len(entries) == 1 else 'ies'} replayed through "
          f"all routes, {status}"
          + (f" ({hb_decided} decided fast by the HB pre-pass, "
             f"parity+audit checked)" if hb_decided else "")
          + (f" ({unknowns} route verdict(s) unknown under the "
             f"budget)" if unknowns else "")
          + f" ({time.time() - t0:.0f}s)")
    return 1 if failures else 0


def replay(path: str, model_name: str) -> int:
    model = MODELS[model_name]()
    ops = [Op.from_dict(d) for d in json.load(open(path))]
    a, b, c = verdicts(ops, model)
    div = len({v for v in (a, b, c) if v != "unknown"}) > 1
    print(f"oracle={a} device={b} linear={c} "
          f"({'DIVERGES' if div else 'agree'})")
    return 1 if div else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=100)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--n-ops", type=int, default=60)
    ap.add_argument("--n-procs", type=int, default=4)
    ap.add_argument("--model", default="cas-register",
                    choices=sorted(MODELS))
    ap.add_argument("--replay", metavar="FILE")
    ap.add_argument("--corpus", nargs="?", const="store/corpus",
                    default=None, metavar="DIR",
                    help="Replay the banked live-campaign corpus "
                         "(live/corpus.py) through all engine routes "
                         "with verdict-parity + audit assertions; "
                         "DIR defaults to store/corpus")
    ap.add_argument("--max-entries", type=int, default=None,
                    help="Bound the --corpus replay to the first N "
                         "pool entries")
    ap.add_argument("--out", default="fuzz-repro.json")
    ap.add_argument("--audit", action="store_true",
                    help="Also replay every engine's certificate "
                         "through jepsen_tpu.analyze.audit; any W-code "
                         "fails the run loudly (exit 1)")
    args = ap.parse_args()

    if args.corpus is not None:
        return corpus_replay(args.corpus,
                             max_entries=args.max_entries)

    if args.replay:
        return replay(args.replay, args.model)

    model = MODELS[args.model]()
    t0 = time.time()
    for i in range(args.rounds):
        rng = random.Random(args.seed + i)
        crash_p = rng.choice([0.0, 0.0, 0.1, 0.25])
        h = gen_history(rng, args.model, args.n_ops, args.n_procs,
                        crash_p)
        if rng.random() < 0.7:
            h = corrupt(rng, h)
        div = None
        if args.audit:
            # one engine pass serves both the audit and the divergence
            # test — the three searches dominate a round's cost
            try:
                s, rs = results(h, model)
            except Exception:
                div = False  # encode errors are the lint fuzzer's beat
            else:
                bad = audit_results(s, model, rs)
                if bad:
                    print(f"AUDIT FAILURE at round {i} "
                          f"(seed {args.seed + i}):", file=sys.stderr)
                    for engine, d in bad:
                        print(f"  [{engine}] {d}", file=sys.stderr)
                    json.dump([op.to_dict() for op in h],
                              open(args.out, "w"), indent=1)
                    print(f"history -> {args.out}")
                    return 1
                div = _diverge([r["valid"] for r in rs])
        if diverges(h, model) if div is None else div:
            a, b, c = verdicts(h, model)
            print(f"DIVERGENCE at round {i} (seed {args.seed + i}): "
                  f"oracle={a} device={b} linear={c}; shrinking...",
                  file=sys.stderr)
            small = shrink(h, model)
            a2, b2, c2 = verdicts(small, model)
            json.dump([op.to_dict() for op in small], open(args.out, "w"),
                      indent=1)
            print(f"minimal repro: {len(small)} ops (from {len(h)}) -> "
                  f"{args.out}; oracle={a2} device={b2} linear={c2}")
            for op in small:
                print(" ", op.to_dict())
            return 1
        if (i + 1) % 25 == 0:
            print(f"fuzz: {i + 1}/{args.rounds} rounds clean "
                  f"({time.time() - t0:.0f}s)", file=sys.stderr)
    print(f"fuzz: {args.rounds} rounds, no divergence "
          f"({time.time() - t0:.0f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
