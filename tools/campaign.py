#!/usr/bin/env python
"""Campaign front door — ``tools/campaign.py [--dry-run] ...``.

A thin wrapper over ``python -m jepsen_tpu.live`` so operators (and
CI) drive nemesis campaigns from the tools/ directory like the other
utilities; ``--dry-run`` prints the suite×nemesis matrix with per-cell
skip reasons without spawning a single process.

Self-healing knobs (see ``python -m jepsen_tpu.live --help``):
``--resume CAMPAIGN_ID`` continues an interrupted campaign without
re-running cells already recorded in its ``cells.jsonl``;
``--cell-budget S`` bounds each cell's wall clock (the watchdog
SIGKILLs wedged backend processes past it); ``--cell-retries N``
bounds retries on harness (not verdict) errors.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.live.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
