#!/usr/bin/env python
"""Campaign front door — ``tools/campaign.py [--dry-run] ...``.

A thin wrapper over ``python -m jepsen_tpu.live`` so operators (and
CI) drive nemesis campaigns from the tools/ directory like the other
utilities; ``--dry-run`` prints the suite×nemesis matrix with per-cell
skip reasons without spawning a single process.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.live.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
