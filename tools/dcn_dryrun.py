"""Two-process jax.distributed dry run — the DCN tier without hardware.

Validates the multi-HOST path (SURVEY.md §2.4/§5.8): two OS processes,
each owning 4 virtual CPU devices, bring up `jax.distributed`, build
`distributed.multihost_mesh()` (a hosts×chips = 2×4 mesh with the
independent-keys axis on DCN), and run `search_batch` with the key axis
sharded across BOTH processes.  This is the same SPMD program the real
multi-host TPU deployment runs — the reference's analog is its
control-node-centric SSH fan-out, which never needed this tier; the
checker's scale-out does.

Run with no arguments: forks the two ranks, waits, prints one OK line.
Exit code 0 = both ranks agreed on every verdict.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_PROCS = 2
DEVICES_PER_PROC = 4
N_KEYS = 8


def child(proc_id: int, port: int) -> None:
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={DEVICES_PER_PROC}")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu import distributed as dist

    ok = dist.init_from_env(coordinator=f"127.0.0.1:{port}",
                            num_processes=N_PROCS, process_id=proc_id)
    assert ok, "jax.distributed did not initialize"
    info = dist.process_info()
    assert info["process_count"] == N_PROCS, info
    assert info["global_devices"] == N_PROCS * DEVICES_PER_PROC, info

    mesh = dist.multihost_mesh()
    assert dict(mesh.shape) == {"keys": N_PROCS,
                                "shard": DEVICES_PER_PROC}, mesh.shape

    # identical batch on every rank (SPMD): half the keys corrupted so
    # they must ride the device kernel, half valid
    import random

    from jepsen_tpu.checker import linearizable as lin
    from jepsen_tpu.checker import seq as oracle
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    model = cas_register()
    seqs, want = [], []
    for k in range(N_KEYS):
        rng = random.Random(3000 + k)
        h = register_history(rng, n_ops=20, n_procs=3, overlap=3,
                             n_values=3)
        if k % 2 == 0:
            h = corrupt_read(rng, h, at=0.7)
        s = encode_ops(h, model.f_codes)
        seqs.append(s)
        want.append(oracle.check_opseq(s, model)["valid"])

    with mesh:
        results = lin.search_batch(seqs, model, budget=200_000,
                                   sharding=dist.keys_sharding(mesh))
    got = [r["valid"] for r in results]
    assert got == want, f"rank {proc_id}: {got} != {want}"
    if proc_id == 0:
        print(json.dumps({
            "ok": True, "phase": "dcn-2proc",
            "processes": N_PROCS,
            "devices_per_proc": DEVICES_PER_PROC,
            "mesh": dict(mesh.shape),
            "keys": N_KEYS,
            "verdicts": ["invalid" if v is False else "valid"
                         for v in got],
        }), flush=True)


def main() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(N_PROCS):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--proc-id", str(pid), "--port", str(port)],
            env=env,
            stdout=None if pid == 0 else subprocess.DEVNULL))
    rc = 0
    for pid, p in enumerate(procs):
        try:
            p.wait(timeout=600)
        except subprocess.TimeoutExpired:
            p.kill()
            print(f"dcn_dryrun: rank {pid} timed out", file=sys.stderr)
            rc = 1
            continue
        if p.returncode != 0:
            print(f"dcn_dryrun: rank {pid} rc={p.returncode}",
                  file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    if "--proc-id" in sys.argv:
        pid = int(sys.argv[sys.argv.index("--proc-id") + 1])
        port = int(sys.argv[sys.argv.index("--port") + 1])
        child(pid, port)
    else:
        sys.exit(main())
