#!/bin/bash
# One-shot TPU evidence collection — run the moment the axon tunnel is up.
#
#   tools/tpu_session.sh [outdir]
#
# Produces, in outdir (default /tmp/tpu_session):
#   probe.json        backend + device name
#   tpubench.jsonl    per-op microbenchmarks at the widths that matter
#   bench.json        the full bench (unpinned: tiers run on the TPU)
# and prints a summary.  Each step has its own timeout so a mid-session
# tunnel drop costs one artifact, not the session.
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-/tmp/tpu_session}
mkdir -p "$OUT"
# persistent XLA compile cache for tpubench.py and the probe (which
# set no cache dir of their own); bench.py's tier children pin the
# same directory in-process
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"

echo "== probe"
timeout 600 python - <<'PY' | tee "$OUT/probe.json"
import json
import jax
d = jax.devices()[0]
import jax.numpy as jnp
x = jnp.ones((256, 256)); (x @ x).block_until_ready()
print(json.dumps({"platform": d.platform, "device": str(d),
                  "n_devices": len(jax.devices())}))
PY
rc=$?
if [ $rc -ne 0 ]; then
  echo "probe failed rc=$rc — tunnel down?"; exit 1
fi

echo "== tpubench (microbenchmarks)"
# widths cover the round-4 policy range: narrow rungs (16-512, where
# dominance-pruned searches live), the downshift threshold, and the
# r2 width-cliff region (1024 fast / 8192 slow).  Highest-value widths
# FIRST so a timeout truncates the least interesting rows; timeout
# raised for the doubled compile count on a cold cache.
timeout 1500 python tools/tpubench.py --widths 8192,1024,16,64,256,4096 \
  --levels 64 --repeat 5 2>"$OUT/tpubench.err" | tee "$OUT/tpubench.jsonl"

echo "== full bench (unpinned)"
BENCH_BUDGET_S=1100 timeout 1200 python bench.py \
  2>"$OUT/bench.err" | tail -1 | tee "$OUT/bench.json"

echo "== summary"
python - "$OUT" <<'PY'
import json, sys, os
out = sys.argv[1]
try:
    b = json.load(open(os.path.join(out, "bench.json")))
    print("metric:", b.get("metric"))
    print("value:", b.get("value"), b.get("unit"),
          "vs_baseline:", b.get("vs_baseline"))
    print("backend:", (b.get("detail") or {}).get("backend"))
except Exception as e:
    print("no bench.json:", e)
PY
