#!/bin/bash
# Tunnel watcher — the axon tunnel has been observed to open for brief
# windows (~5 min, r4: up 00:59-01:04 then wedged), so waiting for a
# human-scheduled session loses them.  This loop probes with a short
# timeout; the moment the tunnel answers it spends the window on the
# highest-value missing artifact:
#
#   window 1: the full bench, unpinned, cheap tiers first  -> bench_tpu_*.json
#   window 2: the width-sweep microbench                   -> tpubench_*.jsonl
#   then exits.
#
#   nohup tools/tpu_watch.sh [outdir] &
#
# Artifacts land in outdir (default docs/tpu/r4 — inside the repo, so
# the end-of-round commit picks them up).
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-docs/tpu/r4}
mkdir -p "$OUT"
# persistent XLA compile cache: bench.py's children pin the same dir
# in-process; this export covers tpubench.py and the probe below,
# which set no cache dir of their own
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"

# nothing left to collect: exit immediately (a restarted watcher must
# not probe forever after both artifacts are banked)
if [ -f "$OUT/.bench_done" ] && [ -f "$OUT/.sweep_done" ]; then
  echo "$(date -u +%FT%TZ) both artifacts already banked; exiting" \
    >> "$OUT/watch.log"
  exit 0
fi

n=0
while true; do
  n=$((n + 1))
  up=$(timeout 75 python - 2>/dev/null <<'PY'
import jax
d = jax.devices()[0]
import jax.numpy as jnp
x = jnp.ones((128, 128)); (x @ x).block_until_ready()
print(d.platform)
PY
)
  if [ "$up" = "tpu" ]; then
    stamp=$(date -u +%H%M%S)
    if [ ! -f "$OUT/.bench_done" ]; then
      echo "$(date -u +%FT%TZ) tunnel UP (probe $n); bench -> bench_tpu_$stamp" \
        >> "$OUT/watch.log"
      BENCH_TIER_ORDER=1k,batch256,mutex2k,10k \
        BENCH_PROBE_S=90 BENCH_HOST_S=60 BENCH_BUDGET_S=900 \
        timeout 960 python bench.py \
        > "$OUT/bench_tpu_$stamp.json" 2> "$OUT/bench_tpu_$stamp.err"
      if python - "$OUT/bench_tpu_$stamp.json" <<'PY'
import json, sys
try:
    b = json.load(open(sys.argv[1]))
    ok = (b.get("detail") or {}).get("backend") == "tpu"
except Exception:
    ok = False
sys.exit(0 if ok else 1)
PY
      then
        touch "$OUT/.bench_done"
        echo "$(date -u +%FT%TZ) tpu-backed headline captured" >> "$OUT/watch.log"
      else
        echo "$(date -u +%FT%TZ) bench finished without a tpu headline" \
          >> "$OUT/watch.log"
      fi
    elif [ ! -f "$OUT/.sweep_done" ]; then
      # highest-value widths FIRST so a truncated sweep drops the least
      # interesting rows (the F=8192 row is the r4 artifact to recapture)
      echo "$(date -u +%FT%TZ) tunnel UP (probe $n); sweep -> tpubench_$stamp" \
        >> "$OUT/watch.log"
      WIDTHS=8192,1024,16,64,256,4096
      NW=$(echo "$WIDTHS" | tr ',' '\n' | wc -l)
      timeout 1500 python tools/tpubench.py \
        --widths "$WIDTHS" --levels 64 --repeat 5 \
        > "$OUT/tpubench_$stamp.jsonl" 2> "$OUT/tpubench_$stamp.err"
      # complete = every width produced its kernel row on the TPU
      # (a timeout-truncated sweep must be retried in a later window)
      if [ "$(grep -c '"op": "kernel' "$OUT/tpubench_$stamp.jsonl")" -ge "$NW" ] \
         && head -1 "$OUT/tpubench_$stamp.jsonl" | grep -q '"backend": "tpu"'; then
        touch "$OUT/.sweep_done"
        echo "$(date -u +%FT%TZ) tpu width sweep captured; exiting" \
          >> "$OUT/watch.log"
        exit 0
      fi
      echo "$(date -u +%FT%TZ) sweep incomplete; resuming watch" \
        >> "$OUT/watch.log"
    else
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down (probe $n)" >> "$OUT/watch.log"
  fi
  sleep 30
done
