#!/bin/bash
# Tunnel watcher — the axon tunnel has been observed to open for brief
# windows (~5 min, r4: up 00:59-01:04 then wedged), so waiting for a
# human-scheduled session loses them.  This loop probes with a short
# timeout; the moment the tunnel answers it runs the full bench
# UNPINNED, cheap tiers first, so even a short window banks TPU-backed
# artifacts (and populates .jax_cache so the next window — or the
# driver's end-of-round run — skips the compiles).
#
#   nohup tools/tpu_watch.sh [outdir] &
#
# Artifacts land in outdir (default docs/tpu/r4 — inside the repo, so
# the end-of-round commit picks them up).  Exits after a bench whose
# headline ran on the TPU; otherwise keeps watching.
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-docs/tpu/r4}
mkdir -p "$OUT"
n=0
while true; do
  n=$((n + 1))
  up=$(timeout 75 python - 2>/dev/null <<'PY'
import jax
d = jax.devices()[0]
import jax.numpy as jnp
x = jnp.ones((128, 128)); (x @ x).block_until_ready()
print(d.platform)
PY
)
  if [ "$up" = "tpu" ]; then
    stamp=$(date -u +%H%M%S)
    echo "$(date -u +%FT%TZ) tunnel UP (probe $n); bench -> bench_tpu_$stamp" \
      >> "$OUT/watch.log"
    BENCH_TIER_ORDER=1k,batch256,mutex2k,10k \
      BENCH_PROBE_S=90 BENCH_HOST_S=60 BENCH_BUDGET_S=900 \
      timeout 960 python bench.py \
      > "$OUT/bench_tpu_$stamp.json" 2> "$OUT/bench_tpu_$stamp.err"
    # while the tunnel is (maybe still) hot: the width-sweep microbench
    # table with honest levels_run accounting (VERDICT r3 item 3)
    timeout 900 python tools/tpubench.py \
      --widths 16,64,256,1024,4096,8192 --levels 64 --repeat 5 \
      > "$OUT/tpubench_$stamp.jsonl" 2>> "$OUT/bench_tpu_$stamp.err"
    if python - "$OUT/bench_tpu_$stamp.json" <<'PY'
import json, sys
try:
    b = json.load(open(sys.argv[1]))
    ok = (b.get("detail") or {}).get("backend") == "tpu"
except Exception:
    ok = False
sys.exit(0 if ok else 1)
PY
    then
      echo "$(date -u +%FT%TZ) tpu-backed headline captured; exiting" \
        >> "$OUT/watch.log"
      exit 0
    fi
    echo "$(date -u +%FT%TZ) bench finished without a tpu headline; resuming watch" \
      >> "$OUT/watch.log"
  else
    echo "$(date -u +%FT%TZ) tunnel down (probe $n)" >> "$OUT/watch.log"
  fi
  sleep 30
done
