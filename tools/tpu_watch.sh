#!/bin/bash
# Tunnel watcher — the axon tunnel opens for brief windows (~5-8 min
# observed r4: 00:59-01:04, 03:15-03:23, both ending in a wedge), so
# waiting for a human-scheduled session loses them.  This loop probes
# cheaply; the moment the tunnel answers it spends the window on the
# highest-value MISSING artifact, in order:
#
#   1. batch256 tier child on the chip      -> batch256_tpu_*.json
#   2. the 10k tier child, checkpointed     -> tenk_tpu_*.json
#      (slices persist to .bench_ckpt; a wedged window RESUMES next
#      window instead of restarting — the search accumulates until a
#      window finishes it)
#   3. one full bench, unpinned             -> bench_tpu_*.json
#      (bench.py now defers host comparators when the tunnel is open
#      and resumes tier checkpoints, so this is cheap once 1-2 landed)
#
#   nohup tools/tpu_watch.sh [outdir] &
#
# Artifacts land in outdir (default docs/tpu/r4 — inside the repo, so
# the end-of-round commit picks them up).
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-docs/tpu/r4}
mkdir -p "$OUT"
# persistent XLA compile cache: bench.py's children pin the same dir
# in-process; this export covers the probe
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
# per-slice trace on stderr: when a window wedges, the last trace line
# is the diagnosis (the r4 950s silent hang motivated this)
export JEPSEN_TPU_TRACE_SLICES=1

log() { echo "$(date -u +%FT%TZ) $*" >> "$OUT/watch.log"; }

if [ -f "$OUT/.batch_done" ] && [ -f "$OUT/.tenk_done" ] \
   && [ -f "$OUT/.bench_done" ] && [ -f "$OUT/.prune_done" ]; then
  log "all artifacts already banked; exiting"
  exit 0
fi

backend_of() {  # $1: tier-child json file; prints backend or nothing
  python - "$1" 2>/dev/null <<'PY'
import json, sys
try:
    print(json.load(open(sys.argv[1])).get("backend", ""))
except Exception:
    pass
PY
}

n=0
while true; do
  n=$((n + 1))
  up=$(timeout 75 python - 2>/dev/null <<'PY'
import jax
d = jax.devices()[0]
import jax.numpy as jnp
x = jnp.ones((128, 128)); (x @ x).block_until_ready()
print(d.platform)
PY
)
  if [ "$up" = "tpu" ]; then
    # the driver's end-of-round bench owns the chip when it runs: two
    # clients sharing the wedge-prone worker (and the same .bench_ckpt)
    # is how evidence gets corrupted — stand down while any other
    # bench.py is alive
    if pgrep -f "python.* bench\.py" > /dev/null 2>&1; then
      log "tunnel UP but another bench.py is running; standing down"
      sleep 120
      continue
    fi
    stamp=$(date -u +%H%M%S)
    if [ ! -f "$OUT/.batch_done" ]; then
      log "tunnel UP (probe $n); batch256 child -> batch256_tpu_$stamp"
      BENCH_TIER_S=180 timeout 420 python bench.py \
        --run-tier batch256 --budget 2000000 \
        > "$OUT/batch256_tpu_$stamp.json" \
        2> "$OUT/batch256_tpu_$stamp.err"
      if [ "$(backend_of "$OUT/batch256_tpu_$stamp.json")" = "tpu" ]; then
        touch "$OUT/.batch_done"
        log "batch256 on-chip banked"
        continue  # same window: go straight to the 10k
      fi
      log "batch256 child did not land on tpu; resuming watch"
    elif [ ! -f "$OUT/.tenk_done" ]; then
      log "tunnel UP (probe $n); 10k child (ckpt-resumed) -> tenk_tpu_$stamp"
      BENCH_TIER_S=420 timeout 600 python bench.py \
        --run-tier 10k --budget 100000000 \
        > "$OUT/tenk_tpu_$stamp.json" 2> "$OUT/tenk_tpu_$stamp.err"
      decided=$(python - "$OUT/tenk_tpu_$stamp.json" 2>/dev/null <<'PY'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
    print("yes" if d.get("valid") in (True, False)
          and d.get("backend") == "tpu" else "no")
except Exception:
    print("no")
PY
)
      if [ "$decided" = "yes" ]; then
        touch "$OUT/.tenk_done"
        log "10k DECIDED on-chip banked"
        continue  # same window: try the full bench
      fi
      log "10k undecided this window (progress checkpointed); resuming"
    elif [ ! -f "$OUT/.bench_done" ]; then
      log "tunnel UP (probe $n); full bench -> bench_tpu_$stamp"
      BENCH_PROBE_S=90 BENCH_BUDGET_S=900 timeout 960 python bench.py \
        > "$OUT/bench_tpu_$stamp.json" 2> "$OUT/bench_tpu_$stamp.err"
      if python - "$OUT/bench_tpu_$stamp.json" <<'PY'
import json, sys
try:
    b = json.load(open(sys.argv[1]))
    ok = (b.get("detail") or {}).get("backend") == "tpu"
except Exception:
    ok = False
sys.exit(0 if ok else 1)
PY
      then
        touch "$OUT/.bench_done"
        log "tpu-backed full bench banked"
        continue
      fi
      log "bench finished without a tpu headline; resuming watch"
    elif [ ! -f "$OUT/.prune_done" ]; then
      # the decisive sort-vs-allpairs on-chip comparison: paired kernel
      # rows + dispatch-amortized loop64 prune rows at the narrow rungs
      log "tunnel UP (probe $n); prune sweep -> prunebench_$stamp"
      timeout 900 python tools/tpubench.py \
        --widths 64,256,1024 --levels 64 --repeat 3 \
        > "$OUT/prunebench_$stamp.jsonl" \
        2> "$OUT/prunebench_$stamp.err"
      if [ "$(grep -c '"dominance": "allpairs"' \
              "$OUT/prunebench_$stamp.jsonl")" -ge 3 ] \
         && head -1 "$OUT/prunebench_$stamp.jsonl" \
            | grep -q '"backend": "tpu"'; then
        touch "$OUT/.prune_done"
        log "paired prune sweep banked; exiting"
        exit 0
      fi
      log "prune sweep incomplete; resuming watch"
    else
      exit 0
    fi
  else
    log "tunnel down (probe $n)"
  fi
  sleep 30
done
