#!/bin/bash
# Tunnel watcher — the axon tunnel opens for brief windows (~5-8 min
# observed r4: 00:59-01:04, 03:15-03:23, both ending in a wedge), so
# waiting for a human-scheduled session loses them.  This loop probes
# cheaply; the moment the tunnel answers it spends the window on the
# highest-value MISSING artifact, in order:
#
#   0. slice-cap validation: mutex2k child on-chip with per-slice
#      tracing                             -> slicecap_tpu_*.json
#      (VERDICT r5 item 7: the watchdog-aware slice caps landed AFTER
#      the r4 wedges and have never run on a real chip — validate them
#      on the cheapest decided tier before anything long runs)
#   1. batch256 tier child on the chip      -> batch256_tpu_*.json
#   2. the 10k tier child, checkpointed     -> tenk_tpu_*.json
#      (slices persist to .bench_ckpt; a wedged window RESUMES next
#      window instead of restarting — the search accumulates until a
#      window finishes it)
#   3. one full bench, unpinned             -> bench_tpu_*.json
#      (bench.py defers host comparators when the tunnel is open and
#      resumes tier checkpoints, so this is cheap once 1-2 landed)
#   4. paired sort-vs-allpairs prune sweep  -> prunebench_*.jsonl
#
#   nohup tools/tpu_watch.sh [outdir] &
#
# Artifacts land in outdir (default docs/tpu/r5 — inside the repo, so
# the end-of-round commit picks them up).
#
# Wedge-signature backoff (VERDICT r4 weak #6): r4's watcher probed a
# wedged worker every ~105 s for 11 hours.  The signature is a probe
# that HANGS while the tunnel's local TCP endpoint stays `open` (a dead
# worker behind a live listener).  There is no client-side reset for a
# wedged worker, so once the signature persists the watcher backs off
# (probe interval 105s -> 300s after 12 consecutive hung-open probes)
# and snaps back to fast probing the moment a probe either SUCCEEDS or
# the endpoint's TCP state CHANGES (a closed->open transition is a
# fresh tunnel).
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-docs/tpu/r5}
mkdir -p "$OUT"
# persistent XLA compile cache: bench.py's children pin the same dir
# in-process; this export covers the probe
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
# per-slice trace on stderr: when a window wedges, the last trace line
# is the diagnosis (the r4 950s silent hang motivated this)
export JEPSEN_TPU_TRACE_SLICES=1

log() { echo "$(date -u +%FT%TZ) $*" >> "$OUT/watch.log"; }

if [ -f "$OUT/.slicecap_done" ] && [ -f "$OUT/.batch_done" ] \
   && [ -f "$OUT/.tenk_done" ] && [ -f "$OUT/.bench_done" ] \
   && [ -f "$OUT/.prune_done" ]; then
  log "all artifacts already banked; exiting"
  exit 0
fi

backend_of() {  # $1: tier-child json file; prints backend or nothing
  python - "$1" 2>/dev/null <<'PY'
import json, sys
try:
    print(json.load(open(sys.argv[1])).get("backend", ""))
except Exception:
    pass
PY
}

tcp_state() {  # TCP state of the tunnel's local endpoint
  python - 2>/dev/null <<'PY'
import os, socket
port = int(os.environ.get("BENCH_TUNNEL_PORT", "2024"))
try:
    with socket.create_connection(("127.0.0.1", port), timeout=2):
        print("open")
except (TimeoutError, socket.timeout):
    print("timeout")
except OSError:
    print("closed")
PY
}

n=0
hung_open=0     # consecutive probes that hung while the endpoint was open
interval=30
last_tcp=""
while true; do
  n=$((n + 1))
  t_probe=$SECONDS
  up=$(timeout 75 python - 2>/dev/null <<'PY'
import jax
d = jax.devices()[0]
import jax.numpy as jnp
x = jnp.ones((128, 128)); (x @ x).block_until_ready()
print(d.platform)
PY
)
  probe_s=$((SECONDS - t_probe))
  tcp=$(tcp_state)
  if [ "$up" = "tpu" ]; then
    hung_open=0; interval=30
    # the driver's end-of-round bench owns the chip when it runs: two
    # clients sharing the wedge-prone worker (and the same .bench_ckpt)
    # is how evidence gets corrupted — stand down while any other
    # bench.py is alive
    if pgrep -f "python.* bench\.py" > /dev/null 2>&1; then
      log "tunnel UP but another bench.py is running; standing down"
      sleep 120
      continue
    fi
    stamp=$(date -u +%H%M%S)
    if [ ! -f "$OUT/.slicecap_done" ]; then
      # cheapest decided tier, hard 20s slice cap, full tracing: proves
      # every single execution stays under the worker watchdog before a
      # long run risks the window
      log "tunnel UP (probe $n); slice-cap validation -> slicecap_tpu_$stamp"
      BENCH_TIER_S=60 JEPSEN_TPU_SLICE_HARD_S=20 timeout 240 python bench.py \
        --run-tier mutex2k --budget 30000000 \
        > "$OUT/slicecap_tpu_$stamp.json" \
        2> "$OUT/slicecap_tpu_$stamp.err"
      if [ "$(backend_of "$OUT/slicecap_tpu_$stamp.json")" = "tpu" ]; then
        touch "$OUT/.slicecap_done"
        log "slice-cap validation banked (mutex2k on-chip)"
        continue  # same window: go straight to batch256
      fi
      log "slice-cap child did not land on tpu; resuming watch"
    elif [ ! -f "$OUT/.batch_done" ]; then
      log "tunnel UP (probe $n); batch256 child -> batch256_tpu_$stamp"
      BENCH_TIER_S=180 timeout 420 python bench.py \
        --run-tier batch256 --budget 2000000 \
        > "$OUT/batch256_tpu_$stamp.json" \
        2> "$OUT/batch256_tpu_$stamp.err"
      if [ "$(backend_of "$OUT/batch256_tpu_$stamp.json")" = "tpu" ]; then
        touch "$OUT/.batch_done"
        log "batch256 on-chip banked"
        continue  # same window: go straight to the 10k
      fi
      log "batch256 child did not land on tpu; resuming watch"
    elif [ ! -f "$OUT/.tenk_done" ]; then
      log "tunnel UP (probe $n); 10k child (ckpt-resumed) -> tenk_tpu_$stamp"
      BENCH_TIER_S=420 timeout 600 python bench.py \
        --run-tier 10k --budget 100000000 \
        > "$OUT/tenk_tpu_$stamp.json" 2> "$OUT/tenk_tpu_$stamp.err"
      decided=$(python - "$OUT/tenk_tpu_$stamp.json" 2>/dev/null <<'PY'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
    print("yes" if d.get("valid") in (True, False)
          and d.get("backend") == "tpu" else "no")
except Exception:
    print("no")
PY
)
      if [ "$decided" = "yes" ]; then
        touch "$OUT/.tenk_done"
        log "10k DECIDED on-chip banked"
        continue  # same window: try the full bench
      fi
      log "10k undecided this window (progress checkpointed); resuming"
    elif [ ! -f "$OUT/.bench_done" ]; then
      log "tunnel UP (probe $n); full bench -> bench_tpu_$stamp"
      BENCH_PROBE_S=90 BENCH_BUDGET_S=900 timeout 960 python bench.py \
        > "$OUT/bench_tpu_$stamp.json" 2> "$OUT/bench_tpu_$stamp.err"
      if python - "$OUT/bench_tpu_$stamp.json" <<'PY'
import json, sys
try:
    b = json.load(open(sys.argv[1]))
    ok = (b.get("detail") or {}).get("backend") == "tpu"
except Exception:
    ok = False
sys.exit(0 if ok else 1)
PY
      then
        touch "$OUT/.bench_done"
        log "tpu-backed full bench banked"
        continue
      fi
      log "bench finished without a tpu headline; resuming watch"
    elif [ ! -f "$OUT/.prune_done" ]; then
      # the decisive sort-vs-allpairs on-chip comparison: paired kernel
      # rows + dispatch-amortized loop64 prune rows at the narrow rungs
      log "tunnel UP (probe $n); prune sweep -> prunebench_$stamp"
      timeout 900 python tools/tpubench.py \
        --widths 64,256,1024 --levels 64 --repeat 3 \
        > "$OUT/prunebench_$stamp.jsonl" \
        2> "$OUT/prunebench_$stamp.err"
      if [ "$(grep -c '"dominance": "allpairs"' \
              "$OUT/prunebench_$stamp.jsonl")" -ge 3 ] \
         && head -1 "$OUT/prunebench_$stamp.jsonl" \
            | grep -q '"backend": "tpu"'; then
        touch "$OUT/.prune_done"
        log "paired prune sweep banked; exiting"
        exit 0
      fi
      log "prune sweep incomplete; resuming watch"
    else
      exit 0
    fi
  else
    # wedged-worker signature: a probe that actually HUNG (consumed
    # its 75s timeout) + endpoint still accepting.  A fast-failing
    # probe behind a live listener is NOT the signature — backing off
    # on those would cost minutes of a 5-8-min window when the worker
    # revives (a revival is only detectable by the next probe).
    if [ "$tcp" = "open" ] && [ "$probe_s" -ge 70 ]; then
      hung_open=$((hung_open + 1))
      if [ "$hung_open" -eq 12 ]; then
        log "wedged-worker signature persists (12 hung-open probes); backing off to 300s"
        interval=300
      fi
      log "tunnel down (probe $n, tcp=$tcp, hung ${probe_s}s, hung_open=$hung_open)"
    else
      # endpoint gone or changed: any future open is a fresh tunnel —
      # probe fast again
      if [ "$tcp" != "$last_tcp" ] && [ "$interval" -ne 30 ]; then
        log "endpoint tcp state changed ($last_tcp -> $tcp); fast probing resumes"
      fi
      hung_open=0; interval=30
      log "tunnel down (probe $n, tcp=$tcp)"
    fi
  fi
  last_tcp="$tcp"
  sleep "$interval"
done
