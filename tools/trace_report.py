#!/usr/bin/env python3
"""Summarize a flight-recorder trace.json into a phase-time table.

  python tools/trace_report.py store/my-test/latest/trace.json
  python tools/trace_report.py my-test            # latest run's trace
  python tools/trace_report.py trace.json --json  # machine-readable

The table answers "where did the wall-clock go": per-category busy
time (interval union — overlapped spans don't double-bill), device vs
host split, and the idle remainder that pipelining could still hide.
Thin wrapper over jepsen_tpu.obs.report so the web UI, the ``obs``
CLI, and this tool all fold traces identically.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.obs.__main__ import main  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:]
    sys.exit(main(["report"] + argv))
