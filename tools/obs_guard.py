#!/usr/bin/env python3
"""obs_guard — the executable bench contract.

ROADMAP perf claims ("the mask kills X% of lanes", "compiles are
cache-hits after warmup", "the device is busy, not idle") used to live
as prose next to BENCH_*.json numbers; nothing re-checked them when
the kernels changed.  This tool reads a checked-in threshold file and
fails LOUDLY when a committed bench trace (or a live /api/stats
snapshot) stops clearing it — wired as a tier-1 test
(tests/test_obs_guard.py), so a regression shows up as a red test,
not as a stale paragraph.

  python tools/obs_guard.py                      # obs_thresholds.json
  python tools/obs_guard.py --thresholds f.json --base /path/to/repo
  python tools/obs_guard.py --stats stats.json   # /api/stats snapshot

Threshold file schema (JSON)::

  {"traces": {"BENCH_trace_1k.json": {
       "require": ["telemetry", "prune_ratio_delta"],
       "max_device_idle_fraction": 0.9,   # 1 - device busy / wall
       "min_levels": 1,                   # observed BFS levels
       "min_observed_prune_ratio": 0.01,  # surviving-lane fraction
       "max_observed_prune_ratio": 1.0,
       "max_abs_prune_ratio_delta": 1.0,  # |observed - predicted|
       "max_compiles": 12,                # device.compile spans
       "min_transfer_bytes": 1}},
   "stats": {"min_kernel_cache_hit_ratio": 0.5,
             "min_verdict_cache_hit_ratio": 0.0,
             "min_bucket_padding_efficiency": 0.0,
             "max_device_idle_fraction": 1.0,
             "min_observed_prune_ratio": 0.0}}

Every key is optional; a trace listed with ``{}`` only asserts the
file exists and parses.  The ``stats`` block checks an ``/api/stats``
JSON snapshot (``--stats``) — derived gauges that are ``null``
(nothing recorded yet) fail ``min_*`` checks only when the metric is
in the block's ``require`` list.

A ``fleet`` block checks committed ``BENCH_fleet.json`` summaries
(the routed-tier contract: warm boots verify, steady state pays zero
compiles, routed verdicts match a single service, the knee doesn't
collapse)::

  {"fleet": {"BENCH_fleet.json": {
       "require": ["knee", "warmup_verified", "parity"],
       "min_knee_events_per_sec": 2000,
       "max_warmup_compiles": 24,
       "max_steady_state_compile_misses": 0,
       "max_shed_rate": 0.0,
       "min_workers": 2}}}

A ``shard`` block checks committed ``BENCH_shard.json`` summaries
(the bucket-then-shard contract: bucketed padding efficiency clears
the floor with the fused counterfactual recorded next to it, verdicts
match the fused route and the oracle, `explain_batch` predicts the
live stats exactly, and the measured laps paid zero compiles)::

  {"shard": {"BENCH_shard.json": {
       "require": ["bucketed", "fused_counterfactual", "parity",
                   "explain_match", "warmup_verified"],
       "min_padding_efficiency": 0.5,
       "min_efficiency_gain_vs_fused": 1.5,
       "max_steady_state_compile_misses": 0,
       "max_warmup_compiles": 0,
       "min_shards": 2,
       "min_sharded_warm_shapes": 1}}}
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.obs.report import phase_table  # noqa: E402

DEFAULT_THRESHOLDS = "obs_thresholds.json"


def _device_idle_fraction(rep: dict):
    """1 - device-busy / wall for one folded trace (the trace-local
    twin of metrics.derived_stats' process-lifetime gauge)."""
    wall = rep.get("wall_s") or 0.0
    if wall <= 0:
        return None
    busy = sum(p["busy_s"] for p in rep.get("phases", [])
               if p["cat"] == "device")
    return round(max(0.0, 1.0 - busy / wall), 4)


def check_trace(path: str, th: dict) -> list[str]:
    """-> failure strings for one trace file against its thresholds
    (empty = clears the contract)."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            rep = phase_table(json.load(f))
    except FileNotFoundError:
        return [f"{name}: trace file missing"]
    except (OSError, ValueError) as e:
        return [f"{name}: unreadable trace ({e})"]
    fails = []
    require = th.get("require", ())
    tele = rep.get("telemetry")
    if "telemetry" in require and tele is None:
        return [f"{name}: no telemetry in trace (recorded with "
                f"JEPSEN_TPU_TELEMETRY=0, or predates the aux "
                f"block?)"]
    tele = tele or {}
    search = tele.get("search") or {}

    idle = _device_idle_fraction(rep)
    mx = th.get("max_device_idle_fraction")
    if mx is not None:
        if idle is None:
            fails.append(f"{name}: device_idle_fraction "
                         f"unmeasurable (empty trace)")
        elif idle > mx:
            fails.append(f"{name}: device_idle_fraction {idle} "
                         f"> max {mx}")

    levels = len(tele.get("levels") or [])
    mn = th.get("min_levels")
    if mn is not None and levels < mn:
        fails.append(f"{name}: {levels} device level(s) "
                     f"< min {mn}")

    obs_r = search.get("observed_prune_ratio")
    for key, op, word in (("min_observed_prune_ratio",
                           lambda v, t: v < t, "<"),
                          ("max_observed_prune_ratio",
                           lambda v, t: v > t, ">")):
        t = th.get(key)
        if t is None:
            continue
        if obs_r is None:
            fails.append(f"{name}: no observed_prune_ratio in "
                         f"trace (needed for {key})")
        elif op(obs_r, t):
            fails.append(f"{name}: observed_prune_ratio {obs_r} "
                         f"{word} {key} {t}")

    delta = search.get("prune_ratio_delta")
    if "prune_ratio_delta" in require and delta is None:
        fails.append(f"{name}: no predicted-vs-observed "
                     f"prune_ratio_delta recorded")
    mx = th.get("max_abs_prune_ratio_delta")
    if mx is not None and delta is not None and abs(delta) > mx:
        fails.append(f"{name}: |prune_ratio_delta| {abs(delta)} "
                     f"> max {mx}")

    mx = th.get("max_compiles")
    if mx is not None:
        n = (tele.get("compiles") or {}).get("count", 0)
        if n > mx:
            fails.append(f"{name}: {n} kernel compile(s) "
                         f"> max {mx}")

    mn = th.get("min_transfer_bytes")
    if mn is not None and tele.get("transfer_bytes", 0) < mn:
        fails.append(f"{name}: transfer_bytes "
                     f"{tele.get('transfer_bytes', 0)} < min {mn}")
    return fails


def check_fleet(path: str, th: dict) -> list[str]:
    """-> failure strings for one committed BENCH_fleet.json summary
    against the fleet-tier thresholds (empty = contract holds)."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return [f"{name}: fleet bench file missing"]
    except (OSError, ValueError) as e:
        return [f"{name}: unreadable fleet bench ({e})"]
    fails = []
    require = th.get("require", ())
    warm = doc.get("warmup") or {}
    knee = doc.get("knee") or {}

    if "knee" in require and not knee:
        fails.append(f"{name}: no throughput knee recorded")
    if "warmup_verified" in require and warm.get("verified") \
            is not True:
        fails.append(f"{name}: warm boot did not verify "
                     f"(warmup={warm or None})")
    if "parity" in require and doc.get("parity") is not True:
        fails.append(f"{name}: routed verdicts diverged from the "
                     f"single-service oracle "
                     f"(parity={doc.get('parity')!r})")

    mn = th.get("min_knee_events_per_sec")
    if mn is not None:
        v = knee.get("events_per_sec")
        if v is None:
            fails.append(f"{name}: knee has no events_per_sec "
                         f"(needed for min_knee_events_per_sec)")
        elif v < mn:
            fails.append(f"{name}: knee {v} events/sec < min {mn}")

    mx = th.get("max_warmup_compiles")
    if mx is not None and warm.get("compiled", 0) > mx:
        fails.append(f"{name}: warm boot compiled "
                     f"{warm.get('compiled')} kernel(s) > max {mx}")

    mx = th.get("max_steady_state_compile_misses")
    if mx is not None:
        n = doc.get("steady_state_compile_misses")
        if n is None:
            fails.append(f"{name}: steady_state_compile_misses not "
                         f"recorded")
        elif n > mx:
            fails.append(f"{name}: {n} steady-state kernel compile "
                         f"miss(es) > max {mx} — warmup no longer "
                         f"covers the serving shapes")

    mx = th.get("max_shed_rate")
    if mx is not None:
        worst = max((r.get("shed_rate", 0.0)
                     for r in doc.get("ramp") or []), default=0.0)
        if worst > mx:
            fails.append(f"{name}: shed_rate {worst} under the ramp "
                         f"> max {mx}")

    mn = th.get("min_workers")
    if mn is not None and doc.get("workers", 0) < mn:
        fails.append(f"{name}: bench ran {doc.get('workers')} "
                     f"worker(s) < min {mn}")
    return fails


def check_shard(path: str, th: dict) -> list[str]:
    """-> failure strings for one committed BENCH_shard.json summary
    against the shard-tier thresholds (empty = contract holds)."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return [f"{name}: shard bench file missing"]
    except (OSError, ValueError) as e:
        return [f"{name}: unreadable shard bench ({e})"]
    fails = []
    require = th.get("require", ())
    warm = doc.get("warmup") or {}
    b = doc.get("bucketed") or {}
    fc = doc.get("fused_counterfactual") or {}
    b_eff = b.get("padding_efficiency")
    f_eff = fc.get("padding_efficiency")

    if "bucketed" in require and b_eff is None:
        fails.append(f"{name}: no bucketed padding efficiency "
                     f"recorded")
    if "fused_counterfactual" in require and f_eff is None:
        fails.append(f"{name}: no fused counterfactual recorded — "
                     f"the gain claim is unanchored")
    if "parity" in require and doc.get("parity") is not True:
        fails.append(f"{name}: bucketed-sharded verdicts diverged "
                     f"from the fused route / oracle "
                     f"(parity={doc.get('parity')!r})")
    if "explain_match" in require and doc.get("explain_match") \
            is not True:
        fails.append(f"{name}: explain_batch prediction no longer "
                     f"matches the live shard_batch stats "
                     f"(explain_diffs in the bench file)")
    if "warmup_verified" in require and warm.get("verified") \
            is not True:
        fails.append(f"{name}: trace-shape warm boot did not verify "
                     f"(warmup={warm or None})")

    mn = th.get("min_padding_efficiency")
    if mn is not None and b_eff is not None and b_eff < mn:
        fails.append(f"{name}: bucketed padding_efficiency {b_eff} "
                     f"< min {mn}")

    mn = th.get("min_efficiency_gain_vs_fused")
    if mn is not None:
        if b_eff is None or not f_eff:
            fails.append(f"{name}: efficiency gain unmeasurable "
                         f"(bucketed={b_eff}, fused={f_eff})")
        elif b_eff / f_eff < mn:
            fails.append(f"{name}: bucketed/fused efficiency gain "
                         f"{round(b_eff / f_eff, 3)} < min {mn}")

    mx = th.get("max_steady_state_compile_misses")
    if mx is not None:
        n = doc.get("steady_state_compile_misses")
        if n is None:
            fails.append(f"{name}: steady_state_compile_misses not "
                         f"recorded")
        elif n > mx:
            fails.append(f"{name}: {n} steady-state kernel compile "
                         f"miss(es) > max {mx} — the warm lap no "
                         f"longer covers the bucket shapes")

    mx = th.get("max_warmup_compiles")
    if mx is not None and warm.get("compiled", 0) > mx:
        fails.append(f"{name}: trace-shape warm boot compiled "
                     f"{warm.get('compiled')} fresh kernel(s) > max "
                     f"{mx} — shapes_from_trace no longer "
                     f"reconstructs the sharded kernel set")

    mn = th.get("min_shards")
    if mn is not None and doc.get("n_devices", 0) < mn:
        fails.append(f"{name}: bench ran on {doc.get('n_devices')} "
                     f"device(s) < min {mn}")

    mn = th.get("min_sharded_warm_shapes")
    if mn is not None:
        n = (doc.get("warmup_shapes") or {}).get("sharded", 0)
        if n < mn:
            fails.append(f"{name}: {n} sharded warm shape(s) in the "
                         f"trace manifest < min {mn}")
    return fails


def check_trace_spans(path: str) -> list[str]:
    """K007 over one committed trace: every ``device.compile`` span
    must carry the kernel cache-key coordinate set the static model in
    :mod:`jepsen_tpu.analyze.devlint` expects (older committed traces
    may carry a documented legacy generation; anything else means the
    compile-span instrumentation drifted from the kernel cache keys
    and warm-boot / zero-miss verification silently stops meaning
    anything)."""
    from jepsen_tpu.analyze.devlint import lint_trace_spans

    name = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return [f"{name}: trace file missing"]
    except (OSError, ValueError) as e:
        return [f"{name}: unreadable trace ({e})"]
    return [f"{d.code} {d.message}"
            for d in lint_trace_spans(doc, name=name)]


#: stats-block threshold key -> (derived gauge, direction)
_STATS_CHECKS = {
    "min_kernel_cache_hit_ratio": ("kernel_cache_hit_ratio", "min"),
    "min_verdict_cache_hit_ratio": ("verdict_cache_hit_ratio", "min"),
    "min_bucket_padding_efficiency": ("bucket_padding_efficiency",
                                      "min"),
    "min_shard_padding_efficiency": ("shard_padding_efficiency",
                                     "min"),
    "max_device_idle_fraction": ("device_idle_fraction", "max"),
    "min_observed_prune_ratio": ("observed_prune_ratio", "min"),
    "max_observed_prune_ratio": ("observed_prune_ratio", "max"),
}


def check_stats(snapshot: dict, th: dict) -> list[str]:
    """-> failure strings for one /api/stats snapshot's derived block
    against the ``stats`` thresholds."""
    derived = snapshot.get("derived") or {}
    require = th.get("require", ())
    fails = []
    for key, (gauge, direction) in _STATS_CHECKS.items():
        t = th.get(key)
        if t is None:
            continue
        v = derived.get(gauge)
        if v is None:
            if gauge in require:
                fails.append(f"stats: derived.{gauge} is null "
                             f"(required by {key})")
            continue
        if direction == "min" and v < t:
            fails.append(f"stats: derived.{gauge} {v} < {key} {t}")
        elif direction == "max" and v > t:
            fails.append(f"stats: derived.{gauge} {v} > {key} {t}")
    return fails


def run_guard(thresholds: dict, *, base: str = ".",
              stats_snapshot: dict | None = None) -> list[str]:
    """Every failure across the threshold file (empty = contract
    holds).  ``base`` anchors relative trace paths."""
    fails = []
    for rel, th in (thresholds.get("traces") or {}).items():
        fails.extend(check_trace(os.path.join(base, rel), th or {}))
    # K007 span-key verification covers EVERY committed trace next to
    # the thresholds, listed or not — a freshly recorded bench trace
    # with drifted compile-span keys must not slip past the guard just
    # because nobody added a thresholds entry for it yet
    for path in sorted(glob.glob(os.path.join(base,
                                              "BENCH_trace_*.json"))):
        fails.extend(check_trace_spans(path))
    for rel, th in (thresholds.get("fleet") or {}).items():
        fails.extend(check_fleet(os.path.join(base, rel), th or {}))
    for rel, th in (thresholds.get("shard") or {}).items():
        fails.extend(check_shard(os.path.join(base, rel), th or {}))
    st = thresholds.get("stats")
    if st:
        if stats_snapshot is None:
            # no snapshot supplied: check THIS process's registry —
            # meaningful when the caller ran searches first (tests)
            from jepsen_tpu.obs import metrics as _metrics

            stats_snapshot = _metrics.snapshot()
        fails.extend(check_stats(stats_snapshot, st))
    return fails


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools/obs_guard.py",
        description="Check committed bench traces (and optionally an "
                    "/api/stats snapshot) against the checked-in "
                    "observability thresholds; exit 1 loudly on any "
                    "miss.")
    p.add_argument("--thresholds", default=None,
                   help=f"threshold JSON (default: "
                        f"{DEFAULT_THRESHOLDS} next to the traces)")
    p.add_argument("--base", default=None,
                   help="directory the trace paths are relative to "
                        "(default: the thresholds file's directory)")
    p.add_argument("--stats", default=None,
                   help="an /api/stats JSON snapshot to check the "
                        "'stats' thresholds against (default: this "
                        "process's registry)")
    args = p.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tf = args.thresholds or os.path.join(repo, DEFAULT_THRESHOLDS)
    try:
        with open(tf) as f:
            thresholds = json.load(f)
    except (OSError, ValueError) as e:
        print(f"obs_guard: cannot read thresholds {tf}: {e}",
              file=sys.stderr)
        return 2
    base = args.base or os.path.dirname(os.path.abspath(tf))
    snap = None
    if args.stats:
        with open(args.stats) as f:
            snap = json.load(f)
    fails = run_guard(thresholds, base=base, stats_snapshot=snap)
    n_traces = len(thresholds.get("traces") or {})
    if fails:
        for f in fails:
            print(f"FAIL {f}", file=sys.stderr)
        print(f"obs_guard: {len(fails)} threshold(s) violated "
              f"across {n_traces} trace(s) — the bench contract is "
              f"BROKEN (re-record BENCH_trace_*.json via "
              f"`python bench.py --trace` and re-seed "
              f"{DEFAULT_THRESHOLDS} only if the regression is "
              f"intended)", file=sys.stderr)
        return 1
    print(f"obs_guard: ok — {n_traces} trace(s)"
          + (" + stats snapshot" if thresholds.get("stats") else "")
          + " within thresholds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
