"""Op-count proxy for the on-chip per-level floor.

docs/perf-notes.md (round 4): the measured ~1.3 ms/level floor at
narrow widths tracks the COUNT of executable computations in the
compiled level body (~5-10 us fixed overhead each on the axon TPU),
not the data volume.  This tool compiles the single-device search
kernel at a given width on the CPU backend, finds the LEVEL-LOOP body
computation in the optimized HLO, and prints its executable-op
histogram (fusions + non-trivial ops; tuple plumbing excluded) plus
every nested loop — the metric every depth-axis optimization is judged
by before a tunnel window can time it for real.

Usage: JAX_PLATFORMS=cpu python tools/fusioncount.py [--tier mutex2k]
       [--widths 16,64,256]
"""

import argparse
import collections
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: instructions that are data plumbing, not executable work
_CHEAP = {"tuple", "get-tuple-element", "parameter", "constant",
          "bitcast"}

#: one HLO instruction: `%name = <type> kind(...)` where <type> may be
#: a tuple `(s32[16]{0}, pred[])` (spaces inside — `\S+` never spans
#: it, which silently zeroed the while/fusion counts in the first
#: version of this tool)
_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?:\([^=]*?\)|\S+)\s+"
    r"([\w\-]+)\(")


def split_computations(txt: str) -> dict[str, list[str]]:
    """computation name -> its instruction lines."""
    comps: dict[str, list[str]] = {}
    cur = None
    for line in txt.splitlines():
        m = re.match(r"^(?:ENTRY\s+)?(%[\w.\-]+)\s*\(.*\{$",
                     line.strip())
        if m:
            cur = m.group(1)
            comps[cur] = []
        elif cur is not None and line.strip() == "}":
            cur = None
        elif cur is not None:
            comps[cur].append(line)
    return comps


def instr_kinds(lines: list[str]) -> collections.Counter:
    c: collections.Counter = collections.Counter()
    for ln in lines:
        m = _INSTR.match(ln)
        if m:
            c[m.group(1)] += 1
    return c


def body_stats(comps: dict, name: str, depth: int = 0, max_depth: int = 3):
    """Executable-op histogram of one computation + its nested whiles."""
    kinds = instr_kinds(comps[name])
    execu = sum(v for k, v in kinds.items() if k not in _CHEAP)
    nested = []
    if depth < max_depth:
        for ln in comps[name]:
            m = re.search(r"\bwhile\(.*?body=(%[\w.\-]+)", ln)
            if m and m.group(1) in comps:
                tc = re.search(r'known_trip_count..\{.n.:.(\d+)', ln)
                nested.append((m.group(1),
                               int(tc.group(1)) if tc else None,
                               body_stats(comps, m.group(1),
                                          depth + 1, max_depth)))
    return {"kinds": dict(kinds), "exec": execu, "nested": nested}


def find_level_body(comps: dict) -> str | None:
    """The outermost while body: the computation that contains the most
    instructions among bodies referenced by a while whose op_name ends
    in 'while' (the level loop)."""
    best = None
    for name, lines in comps.items():
        for ln in lines:
            m = re.search(r"\bwhile\(.*?body=(%[\w.\-]+)", ln)
            if not m or m.group(1) not in comps:
                continue
            op = re.search(r'op_name="([^"]*)"', ln)
            # the level loop is the while whose op_name has exactly one
            # /while segment (nested closure/searchsorted whiles have
            # deeper paths)
            if op and op.group(1).count("while") == 1:
                cand = m.group(1)
                if best is None or (len(comps[cand])
                                    > len(comps[best])):
                    best = cand
    return best


def _print_stats(label, st, indent="  "):
    top = sorted(((k, v) for k, v in st["kinds"].items()
                  if k not in _CHEAP), key=lambda kv: -kv[1])
    print(f"{indent}{label}: exec={st['exec']} "
          f"{dict(top[:8])}")
    for bname, trips, sub in st["nested"]:
        _print_stats(f"while body={bname} trips={trips}", sub,
                     indent + "  ")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tier", default="mutex2k")
    ap.add_argument("--widths", default="16,64,256")
    ap.add_argument("--dump", help="write full HLO text per width here")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    import bench
    from jepsen_tpu.checker import linearizable as lin

    seq, model = bench.make_seq(args.tier)
    es = lin.encode_search(seq)
    for f in (int(w) for w in args.widths.split(",")):
        dims = lin.choose_dims(es, model, frontier=f)
        esp = lin.pad_search(es, dims.n_det_pad, dims.n_crash_pad)
        fn = jax.jit(lin.build_search_step_fn(model, dims))
        carry = lin._init_carry(dims, model)
        a = (jnp.asarray(esp.det_f), jnp.asarray(esp.det_v1),
             jnp.asarray(esp.det_v2), jnp.asarray(esp.det_inv),
             jnp.asarray(esp.det_ret), jnp.asarray(esp.suffix_min_ret),
             jnp.asarray(esp.crash_f), jnp.asarray(esp.crash_v1),
             jnp.asarray(esp.crash_v2), jnp.asarray(esp.crash_inv),
             jnp.int32(es.n_det), jnp.int32(es.n_crash),
             jnp.int32(10 ** 9), jnp.int32(64), jnp.bool_(True))
        txt = fn.lower(*a, *carry).compile().as_text()
        comps = split_computations(txt)
        body = find_level_body(comps)
        print(f"F={f}: computations={len(comps)}")
        if body is None:
            print("  level-loop body not found")
        else:
            _print_stats(f"LEVEL body {body}", body_stats(comps, body))
        if args.dump:
            os.makedirs(args.dump, exist_ok=True)
            with open(os.path.join(args.dump,
                                   f"hlo_{args.tier}_F{f}.txt"),
                      "w") as fh:
                fh.write(txt)


if __name__ == "__main__":
    main()
