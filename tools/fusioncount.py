"""Op-count proxy for the on-chip per-level floor.

docs/perf-notes.md (round 4): the measured ~1.3 ms/level floor at
narrow widths tracks the COUNT of fused computations in the compiled
level body (~5-10 us fixed overhead each on the axon TPU), not the
data volume.  This tool compiles the single-device search kernel at a
given width on the CPU backend and prints computation counts from the
optimized HLO — the metric every depth-axis optimization is judged by
before a tunnel window can time it for real.

Usage: JAX_PLATFORMS=cpu python tools/fusioncount.py [--tier mutex2k]
       [--widths 16,64,256]
"""

import argparse
import collections
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def count_hlo(text: str) -> dict:
    """Computation-kind histogram of an optimized HLO module."""
    c: collections.Counter = collections.Counter()
    for m in re.finditer(r"^\s*%?([\w.-]+)\s*=", text, re.M):
        name = m.group(1)
        if name.startswith("fused_"):
            c["fusion"] += 1
    # fusion *calls* in the entry/while bodies are what execute per
    # iteration; count op kinds too
    for kind in ("fusion", "while", "sort", "custom-call", "gather",
                 "scatter", "dynamic-slice", "dynamic-update-slice",
                 "all-to-all", "reduce", "iota", "transpose", "copy",
                 "convert", "broadcast", "concatenate", "dot"):
        c[f"op:{kind}"] = len(re.findall(rf"=\s*\S+\s+{kind}\(", text))
    c["computations"] = len(re.findall(r"^%?\S+ \{$", text, re.M))
    return dict(c)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tier", default="mutex2k")
    ap.add_argument("--widths", default="16,64,256")
    ap.add_argument("--dump", help="write full HLO text per width here")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import bench
    from jepsen_tpu.checker import linearizable as lin

    seq, model = bench.make_seq(args.tier)
    es = lin.encode_search(seq)
    for f in (int(w) for w in args.widths.split(",")):
        dims = lin.choose_dims(es, model, frontier=f)
        esp = lin.pad_search(es, dims.n_det_pad, dims.n_crash_pad)
        fn = jax.jit(lin.build_search_step_fn(model, dims))
        import jax.numpy as jnp
        import numpy as np

        carry = lin._init_carry(dims, model)
        a = (jnp.asarray(esp.det_f), jnp.asarray(esp.det_v1),
             jnp.asarray(esp.det_v2), jnp.asarray(esp.det_inv),
             jnp.asarray(esp.det_ret), jnp.asarray(esp.suffix_min_ret),
             jnp.asarray(esp.crash_f), jnp.asarray(esp.crash_v1),
             jnp.asarray(esp.crash_v2), jnp.asarray(esp.crash_inv),
             jnp.int32(es.n_det), jnp.int32(es.n_crash),
             jnp.int64(10 ** 9), jnp.int32(64), jnp.bool_(True))
        lowered = fn.lower(*a, *carry)
        txt = lowered.compile().as_text()
        counts = count_hlo(txt)
        top = {k: v for k, v in sorted(counts.items(),
                                       key=lambda kv: -kv[1]) if v}
        print(f"F={f}: {top}")
        if args.dump:
            os.makedirs(args.dump, exist_ok=True)
            with open(os.path.join(args.dump,
                                   f"hlo_{args.tier}_F{f}.txt"),
                      "w") as fh:
                fh.write(txt)


if __name__ == "__main__":
    main()
