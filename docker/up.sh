#!/bin/bash
# Bring up the cluster (reference: docker/up.sh).  Generates a dev-only
# SSH keypair on first run, builds, and starts everything.
set -euo pipefail
cd "$(dirname "$0")"

if [ ! -f control/id_rsa ]; then
  echo "Generating dev SSH keypair..."
  ssh-keygen -t ed25519 -N "" -f control/id_rsa -C jepsen-dev
  cp control/id_rsa.pub node/authorized_keys
fi

docker compose build
docker compose up -d
echo
echo "Cluster up.  Run a test with:"
echo "  docker exec -it jepsen-control \\"
echo "    python -m jepsen_tpu.suites.etcdemo test -w register --time-limit 30"
