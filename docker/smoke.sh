#!/bin/bash
# Tier-3 smoke: bring up the 5-node cluster and run real suites against
# it over SSH — the analog of the reference's ssh-test tier
# (jepsen/test/jepsen/core_test.clj:32-86, which drives cd+echo over
# real SSH to n1..n5).
#
# Usage:  docker/smoke.sh [--keep]
#
# Steps:
#   1. build + start jepsen-control and n1..n5 (up.sh)
#   2. wait until every node answers SSH from the control container
#   3. run the atomdemo suite (in-process db; exercises the full
#      runner/checker/store pipeline inside the container)
#   4. run the etcdemo register workload against n1..n5 (real db
#      install over SSH, partition nemesis, TPU/CPU checker)
#   5. assert both runs produced results.json with "valid": true
#   6. docker compose down (unless --keep)
#
# Requires a docker daemon; this is the one tier that cannot run in the
# sandboxed build image (no docker, no sshd) — run it on any docker host.
set -euo pipefail
cd "$(dirname "$0")"

KEEP=${1:-}

./up.sh

cleanup() {
  if [ "$KEEP" != "--keep" ]; then
    docker compose down -v
  fi
}
trap cleanup EXIT

echo "== waiting for SSH on n1..n5"
for n in n1 n2 n3 n4 n5; do
  for i in $(seq 1 60); do
    if docker exec jepsen-control \
         ssh -o StrictHostKeyChecking=no -o ConnectTimeout=2 \
         root@"$n" true 2>/dev/null; then
      echo "  $n up"
      break
    fi
    [ "$i" = 60 ] && { echo "  $n NEVER came up"; exit 1; }
    sleep 2
  done
done

check_valid() {
  # $1: store glob inside the control container
  docker exec -i jepsen-control python - "$1" <<'PY'
import glob, json, sys
paths = sorted(glob.glob(sys.argv[1]))
assert paths, f"no results at {sys.argv[1]}"
r = json.load(open(paths[-1]))
assert r.get("valid") is True, f"run INVALID: {r}"
print("valid:", paths[-1])
PY
}

echo "== tier 3 (local): localnode — real daemons, kill -9 nemesis"
docker exec jepsen-control \
  python -m jepsen_tpu.suites.localnode test --time-limit 10
check_valid "store/localnode*/latest/results.json"

echo "== tier 2: atomdemo (in-process db, full pipeline)"
docker exec jepsen-control \
  python -m jepsen_tpu.suites.atomdemo test --time-limit 10 \
  --concurrency 5
check_valid "store/atom*/latest/results.json"

echo "== tier 3: etcdemo register over SSH against n1..n5"
docker exec jepsen-control \
  python -m jepsen_tpu.suites.etcdemo test -w register \
  --node n1 --node n2 --node n3 --node n4 --node n5 \
  --time-limit 60 --concurrency 5
check_valid "store/etcd*/latest/results.json"

# --- suite matrix: real servers, partition nemesis ------------------------
# Each suite installs its database on n1..n5 over SSH, drives a workload
# with the partition nemesis active, and must produce a valid
# results.json.  The control image ships the client drivers (kazoo,
# pika, pymysql).  Skip any suite with SMOKE_SKIP="zookeeper rabbitmq".
run_suite() {
  # $1 suite module, $2 store glob, rest: extra args
  local mod="$1" glob="$2"; shift 2
  case " ${SMOKE_SKIP:-} " in *" ${mod##*.} "*)
    echo "== skipping ${mod##*.} (SMOKE_SKIP)"; return 0;; esac
  echo "== tier 3: ${mod##*.} over SSH against n1..n5"
  docker exec jepsen-control \
    python -m "$mod" test \
    --node n1 --node n2 --node n3 --node n4 --node n5 \
    --concurrency 5 "$@"
  check_valid "$glob"
}

run_suite jepsen_tpu.suites.zookeeper "store/zookeeper*/latest/results.json" \
  --time-limit 60
run_suite jepsen_tpu.suites.rabbitmq "store/rabbitmq*/latest/results.json" \
  --time-limit 60
# galera's default dirty-reads workload runs nemesis-free by design;
# the set workload is the one that drives faults during writes
run_suite jepsen_tpu.suites.galera "store/galera*/latest/results.json" \
  --workload set --time-limit 90

echo "== smoke OK"
