"""Graceful drain: a checking service must be stoppable without
losing a verdict.

Contract (stream/service.py): a protocol ``{"drain": true}`` line (or
SIGTERM in ``--listen`` mode via :func:`drain_server`) flips the
service to draining — every open run finalizes and answers its
``final`` on its own connection, new run headers are refused with an
``overloaded: "draining"`` reply, and the process exits 0.  Rolling
restarts of fleet workers lose nothing.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from jepsen_tpu.models import register
from jepsen_tpu.stream.service import (
    StreamService,
    drain_server,
    make_server,
)


def _header(run="r1"):
    return json.dumps({"run": run, "model": "register", "init": 0})


def _op(run, process, typ, f, value):
    return json.dumps({"run": run,
                       "op": {"process": process, "type": typ,
                              "f": f, "value": value}})


def _ok_pair(run, process, f, value):
    return [_op(run, process, "invoke", f, value),
            _op(run, process, "ok", f, value)]


def test_protocol_drain_finalizes_and_refuses_new_runs():
    svc = StreamService(model=register(0))
    replies = []
    svc.handle_line(_header("a"), replies.append)
    for li in _ok_pair("a", 0, "write", 1):
        svc.handle_line(li, replies.append)
    svc.handle_line(json.dumps({"drain": True}), replies.append)
    finals = [r for r in replies if "final" in r]
    assert len(finals) == 1 and finals[0]["run"] == "a"
    assert finals[0]["final"]["valid"] is True
    assert finals[0]["final"]["finalized_by"] == "drain"
    # new runs are refused while draining
    svc.handle_line(_header("b"), replies.append)
    refused = [r for r in replies if r.get("overloaded")]
    assert refused and refused[-1]["overloaded"] == "draining"
    assert refused[-1]["run"] == "b"
    # and the headerless auto-open path is refused the same way
    svc2 = StreamService(model=register(0))
    svc2.drain(replies.append)
    svc2.handle_line(_op("c", 0, "invoke", "write", 1),
                     replies.append)
    assert replies[-1].get("overloaded") == "draining"


def test_drain_is_idempotent_and_preserves_prefix_verdict():
    svc = StreamService(model=register(0))
    replies = []
    svc.handle_line(_header("a"), replies.append)
    for li in _ok_pair("a", 0, "write", 2):
        svc.handle_line(li, replies.append)
    # a corrupted read would flip it invalid; drain before the end
    # yields the verdict of exactly the ingested prefix
    svc.handle_line(json.dumps({"drain": True}), replies.append)
    svc.handle_line(json.dumps({"drain": True}), replies.append)
    finals = [r for r in replies if "final" in r]
    assert len(finals) == 1  # second drain has nothing left


def test_drain_server_over_tcp_finalizes_on_the_connection():
    srv = make_server("127.0.0.1", 0, model=register(0))
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    port = srv.server_address[1]
    s = socket.create_connection(("127.0.0.1", port))
    w = s.makefile("w")
    r = s.makefile("r")
    w.write(_header("tcp-run") + "\n")
    for li in _ok_pair("tcp-run", 0, "write", 1):
        w.write(li + "\n")
    w.flush()
    time.sleep(0.3)  # let the handler ingest before draining
    drained = drain_server(srv)
    assert drained == 1
    # the final arrived on OUR connection, not nowhere
    s.settimeout(5)
    reply = json.loads(r.readline())
    assert reply["run"] == "tcp-run"
    assert reply["final"]["valid"] is True
    assert reply["final"]["finalized_by"] == "drain"
    t.join(timeout=5)
    assert not t.is_alive(), "serve_forever did not stop"
    s.close()
    srv.server_close()


def test_drained_server_refuses_new_runs_on_existing_connection():
    srv = make_server("127.0.0.1", 0, model=register(0))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    s = socket.create_connection(("127.0.0.1", port))
    w = s.makefile("w")
    r = s.makefile("r")
    w.write(_header("r0") + "\n")
    w.flush()
    time.sleep(0.3)
    srv.draining = True  # process-level flag (drain_parent chain)
    w.write(_header("r-new") + "\n")
    w.flush()
    s.settimeout(5)
    reply = json.loads(r.readline())
    assert reply == {"run": "r-new", "overloaded": "draining"}
    s.close()
    srv.shutdown()
    srv.server_close()


def test_sigterm_drains_and_exits_zero(tmp_path):
    """The process contract end to end: SIGTERM to a listening
    service finalizes its open runs (finals answered on the live
    connection) and the process exits 0."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.stream",
         "--listen", "127.0.0.1:0"],
        stderr=subprocess.PIPE, stdout=subprocess.DEVNULL,
        text=True, env=env)
    try:
        line = proc.stderr.readline()
        assert "listening on" in line, line
        port = int(line.rsplit(":", 1)[1])
        s = socket.create_connection(("127.0.0.1", port))
        w = s.makefile("w")
        r = s.makefile("r")
        w.write(_header("sig-run") + "\n")
        for li in _ok_pair("sig-run", 0, "write", 3):
            w.write(li + "\n")
        w.flush()
        time.sleep(0.5)
        proc.send_signal(signal.SIGTERM)
        s.settimeout(30)
        reply = json.loads(r.readline())
        assert reply["run"] == "sig-run"
        assert reply["final"]["valid"] is True
        assert reply["final"]["finalized_by"] == "drain"
        s.close()
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
