"""Nemesis tests: pure grudge topology properties (the analog of
nemesis_test.clj:18-60) and command-shape checks against the dummy
remote."""

import random
from dataclasses import replace

import pytest

from jepsen_tpu import faketime, nemesis, nemesis_time, net
from jepsen_tpu.control import DummyRemote, Session
from jepsen_tpu.history import info_op
from jepsen_tpu.util import majority

NODES = ["n1", "n2", "n3", "n4", "n5"]


def mk_test(responses=None):
    r = DummyRemote(responses or {"getent": (0, "10.0.0.9 STREAM x\n", "")})
    return {"nodes": list(NODES), "net": net.iptables,
            "sessions": {n: Session(node=n, remote=r) for n in NODES}}, r


# --- topology math --------------------------------------------------------


def test_bisect():
    assert nemesis.bisect([1, 2, 3, 4, 5]) == ([1, 2], [3, 4, 5])
    assert nemesis.bisect([]) == ([], [])


def test_split_one():
    loner, rest = nemesis.split_one(NODES, loner="n3")
    assert loner == ["n3"] and "n3" not in rest
    assert set(rest) | {"n3"} == set(NODES)


def test_complete_grudge():
    g = nemesis.complete_grudge(nemesis.bisect(NODES))
    assert g["n1"] == {"n3", "n4", "n5"}
    assert g["n4"] == {"n1", "n2"}
    # nobody grudges their own component
    for node, dropped in g.items():
        assert node not in dropped


def test_bridge():
    g = nemesis.bridge(NODES)
    # n3 is the bridge: appears in no grudge, has no grudge
    assert "n3" not in g
    for node, dropped in g.items():
        assert "n3" not in dropped
    assert g["n1"] == {"n4", "n5"}
    assert g["n4"] == {"n1", "n2"}


@pytest.mark.parametrize("n", [3, 5, 7, 9])
def test_majorities_ring_properties(n):
    """Every node sees a majority; no two nodes see the same majority
    (nemesis_test.clj:40-60)."""
    nodes = [f"m{i}" for i in range(n)]
    random.seed(n)
    g = nemesis.majorities_ring(nodes)
    m = majority(n)
    assert len(g) == n  # every node has an entry
    views = set()
    for node, dropped in g.items():
        visible = set(nodes) - set(dropped)
        assert node in visible
        assert len(visible) >= m, f"{node} sees a minority"
        views.add(frozenset(visible))
    assert len(views) == n, "two nodes see the same majority"


# --- partitioner ----------------------------------------------------------


def test_partitioner_start_stop():
    test, r = mk_test()
    p = nemesis.partition_halves().setup(test)
    out = p.invoke(test, info_op("nemesis", "start"))
    assert out.type == "info" and out.value[0] == "isolated"
    drops = [e for e in r.log if "iptables -A INPUT" in e[2]]
    assert len(drops) == len(NODES)  # one batched rule per node
    out2 = p.invoke(test, info_op("nemesis", "stop"))
    assert out2.value == "network-healed"
    assert any("iptables -F" in e[2] for e in r.log)


# --- compose --------------------------------------------------------------


def test_compose_routes_and_renames():
    class Recorder(nemesis.Nemesis):
        def __init__(self):
            self.seen = []

        def invoke(self, test, op):
            self.seen.append(op.f)
            return replace(op, type="info")

    a, b = Recorder(), Recorder()
    comp = nemesis.compose([
        (frozenset({"start", "stop"}), a),
        ({"kill-start": "start", "kill-stop": "stop"}, b),
    ])
    test, _ = mk_test()
    out = comp.invoke(test, info_op("nemesis", "start"))
    assert a.seen == ["start"] and out.f == "start"
    out2 = comp.invoke(test, info_op("nemesis", "kill-start"))
    assert b.seen == ["start"], "inner nemesis sees renamed f"
    assert out2.f == "kill-start", "outer f restored on the completion"
    with pytest.raises(ValueError, match="no nemesis"):
        comp.invoke(test, info_op("nemesis", "what"))


# --- node start/stop + hammer-time ----------------------------------------


def test_hammer_time_commands_and_state():
    test, r = mk_test()
    h = nemesis.hammer_time("mongod", targeter=lambda ns: ns[0])
    out = h.invoke(test, info_op("nemesis", "start"))
    assert out.value == {"n1": ["paused", "mongod"]}
    assert any("killall -s STOP mongod" in e[2] for e in r.log
               if e[0] == "n1")
    # double start: refuses while already disrupting
    out2 = h.invoke(test, info_op("nemesis", "start"))
    assert "already disrupting" in str(out2.value)
    out3 = h.invoke(test, info_op("nemesis", "stop"))
    assert out3.value == {"n1": ["resumed", "mongod"]}
    assert any("killall -s CONT mongod" in e[2] for e in r.log)
    # stop again: not started
    assert h.invoke(test, info_op("nemesis", "stop")).value == "not-started"


def test_truncate_file():
    test, r = mk_test()
    op = info_op("nemesis", "truncate",
                 {"n2": {"file": "/var/lib/db/wal", "drop": 64}})
    nemesis.truncate_file().invoke(test, op)
    assert any("truncate -c -s -64 /var/lib/db/wal" in e[2]
               for e in r.log if e[0] == "n2")


# --- clock nemesis --------------------------------------------------------


def test_clock_nemesis_ops():
    test, r = mk_test()
    cn = nemesis_time.clock_nemesis()
    cn.invoke(test, info_op("nemesis", "bump", {"n1": 8000, "n3": -4000}))
    assert any("/opt/jepsen/bump-time 8000" in e[2] for e in r.log
               if e[0] == "n1")
    assert any("/opt/jepsen/bump-time -4000" in e[2] for e in r.log
               if e[0] == "n3")
    cn.invoke(test, info_op("nemesis", "strobe",
                            {"n2": {"delta": 100, "period": 5,
                                    "duration": 10}}))
    assert any("/opt/jepsen/strobe-time 100 5 10" in e[2] for e in r.log
               if e[0] == "n2")
    cn.invoke(test, info_op("nemesis", "reset", ["n4"]))
    assert any("ntpdate -b pool.ntp.org" in e[2] for e in r.log
               if e[0] == "n4")
    out = cn.invoke(test, info_op("nemesis", "strobe-pin",
                                  {"n5": {"delta": 200, "period": 10,
                                          "duration": 5}}))
    assert any("/opt/jepsen/strobe-time-experiment 200 10 5" in e[2]
               for e in r.log if e[0] == "n5")
    # the adjustment count (the experiment's observable) rides the op
    assert "adjustments" in out.value["n5"]


def test_clock_gens():
    test = {"nodes": NODES}
    random.seed(1)
    op = nemesis_time.bump_gen(test, "nemesis")
    assert op["f"] == "bump" and op["value"]
    for delta in op["value"].values():
        assert 4 <= abs(delta) <= 2**18
    op2 = nemesis_time.strobe_gen(test, "nemesis")
    for s in op2["value"].values():
        assert s["period"] >= 1 and 0 <= s["duration"] <= 32


# --- faketime -------------------------------------------------------------


def test_faketime_script_and_wrap():
    s = faketime.script("/usr/bin/etcd", -30, 1.5)
    assert s.startswith("#!/bin/bash")
    assert 'faketime -m -f "-30s x1.5" /usr/bin/etcd "$@"' in s

    r = DummyRemote({"stat": (1, "", "nope")})
    sess = Session(node="n1", remote=r)
    faketime.wrap(sess, "/usr/bin/etcd", 10, 2.0)
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any(c.startswith("mv /usr/bin/etcd /usr/bin/etcd.no-faketime")
               for c in cmds)
    assert any("chmod a+x /usr/bin/etcd" in c for c in cmds)


# --- native clock binaries -------------------------------------------------


import shutil
import subprocess


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_clock_binaries_compile_and_pin_runs(tmp_path):
    """All three clock binaries build with the flags nemesis_time uses
    on nodes; the offset-pinning strobe runs end to end with delta=0 (a
    harmless pin to the current offset) and reports its tick count."""
    import os

    native = nemesis_time.NATIVE_DIR
    for src in ("bump_time.cc", "strobe_time.cc",
                "strobe_time_experiment.cc"):
        out = tmp_path / src.replace(".cc", "")
        subprocess.run(["g++", "-O2", "-o", str(out),
                        os.path.join(native, src)], check=True)
    r = subprocess.run([str(tmp_path / "strobe_time_experiment"),
                        "0", "50", "1"], capture_output=True, text=True,
                       check=True, timeout=30)
    assert int(r.stdout.strip()) >= 10  # ~20 ticks at 50ms over 1s
