"""Device-search telemetry (jepsen_tpu/obs/telemetry.py) — the aux
counter block that opens the device black box.

Contract under test:

  * **verdict byte-identity** — telemetry ON vs OFF returns byte-for-
    byte identical verdicts (everything except the attached
    ``search_telemetry`` block itself) across every engine route:
    host DFS, host linear, device BFS, batched, bucketed, mesh-
    sharded, decomposed, streamed — audits on (the acceptance
    criterion's differential fuzz);
  * **the aux block is honest** — schema/unpack unit-tested; the
    observed counters line up with what the search reports (configs
    expanded, goal found), and mask-kill / dedup-fold columns move
    exactly when the must-order mask / dead-value dedup are active;
  * **compile/transfer accounting** — a kernel-cache miss records a
    ``device.compile`` span (hits never do) tagged with whether a
    persistent XLA cache (util.enable_compilation_cache) is
    configured, and argument staging records byte-counted
    ``device.transfer`` spans;
  * **knobs** — JEPSEN_TPU_TELEMETRY / --no-telemetry / enable()
    gate everything; the off path builds the exact pre-telemetry
    kernels (separate cache key) and attaches nothing.
"""

import json
import random

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from jepsen_tpu import obs
from jepsen_tpu.checker import linearizable as lin
from jepsen_tpu.checker import seq as oracle
from jepsen_tpu.checker.linear import check_opseq_linear
from jepsen_tpu.history import encode_ops, invoke_op, ok_op
from jepsen_tpu.models import cas_register, register
from jepsen_tpu.obs import telemetry as tele
from jepsen_tpu.obs.metrics import REGISTRY
from jepsen_tpu.synth import corrupt_read, register_history

# test_linearizable.py's shared generous dims: one compiled kernel
# serves every differential case here too
DIMS = lin.SearchDims(n_det_pad=128, n_crash_pad=32, window=96, k=16,
                      state_width=1, frontier=256)


@pytest.fixture(autouse=True)
def _telemetry_default():
    """Each test starts from the env-default knob state."""
    tele.enable(None)
    yield
    tele.enable(None)


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs a multi-device mesh")
    return Mesh(np.array(devs), ("shard",))


#: stat fields that differ RUN-to-run regardless of the telemetry
#: knob — wall-clock timings and process-global cache warmth
#: (bucket_batch's kernel_cache deltas, verdict-cache hit/miss
#: counters: the ON pass warms the caches the OFF pass then hits) —
#: not verdict content
_VOLATILE = ("seconds", "probe_seconds", "t_dev", "phase_s",
             "kernel_cache", "cache_hits", "cache_misses",
             "cache_inserts", "hits", "misses", "inserts")


def _canon(v):
    if isinstance(v, dict):
        return {k: _canon(x) for k, x in v.items()
                if k not in _VOLATILE and k != "search_telemetry"}
    if isinstance(v, list):
        return [_canon(x) for x in v]
    return v


def _strip(r: dict) -> str:
    """Canonical verdict bytes: everything except the telemetry
    block itself and wall-clock timing stats."""
    return json.dumps(_canon(r), sort_keys=True, default=str)


# ---------------------------------------------------------------------------
# Unit: knob
# ---------------------------------------------------------------------------


def test_knob_default_on_and_env_off(monkeypatch):
    assert tele.enabled() is True  # default ON
    monkeypatch.setenv("JEPSEN_TPU_TELEMETRY", "0")
    tele.enable(None)  # drop the cached env read
    assert tele.enabled() is False
    monkeypatch.setenv("JEPSEN_TPU_TELEMETRY", "off")
    tele.enable(None)
    assert tele.enabled() is False
    monkeypatch.setenv("JEPSEN_TPU_TELEMETRY", "1")
    tele.enable(None)
    assert tele.enabled() is True


def test_enable_overrides_env(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_TELEMETRY", "0")
    tele.enable(True)
    assert tele.enabled() is True
    tele.enable(False)
    assert tele.enabled() is False
    tele.enable(None)
    assert tele.enabled() is False  # back to the env knob


def test_cli_no_telemetry_sets_env_and_disables(monkeypatch):
    import argparse
    import os

    from jepsen_tpu import cli

    monkeypatch.delenv("JEPSEN_TPU_TELEMETRY", raising=False)
    p = argparse.ArgumentParser()
    cli.add_test_opts(p)
    ns = p.parse_args(["--no-telemetry"])
    assert ns.no_telemetry is True
    try:
        opts = cli.test_opt_fn(ns)
        assert opts.get("no_telemetry") is True
        assert os.environ.get("JEPSEN_TPU_TELEMETRY") == "0"
        assert tele.enabled() is False
    finally:
        # plain pop, NOT monkeypatch.delenv: test_opt_fn set the var
        # outside monkeypatch's ledger, so a second delenv would
        # record "0" as the value to RESTORE at teardown and leak
        # telemetry-off into every later test
        os.environ.pop("JEPSEN_TPU_TELEMETRY", None)
        tele.enable(None)


# ---------------------------------------------------------------------------
# Unit: aux-block schema and unpack
# ---------------------------------------------------------------------------


def test_unpack_levels_schema_and_zero_rows():
    blk = np.zeros((tele.TELE_ROWS, tele.TELE_COLS), np.int32)
    blk[0] = (4, 10, 2, 1, 3, 6, 0, 0)
    blk[1] = (6, 12, 0, 0, 1, 2, 1, 1)
    # row 5 never written (occupancy 0) -> dropped
    blk[5, tele.C_EXP] = 99
    rows = tele.unpack_levels(blk)
    assert len(rows) == 2
    assert rows[0] == {"occupancy": 4, "expanded": 10,
                      "mask_killed": 2, "dedup_folds": 1,
                      "crash_rounds": 3, "next_count": 6,
                      "overflow": 0, "goal": 0}
    assert rows[1]["goal"] == 1 and rows[1]["overflow"] == 1
    with pytest.raises(ValueError):
        tele.unpack_levels(np.zeros((4, 3), np.int32))
    with pytest.raises(ValueError):
        tele.unpack_levels(np.zeros(tele.TELE_COLS, np.int32))


def test_observed_prune_ratio_math():
    assert tele.observed_prune_ratio(0, 0, 0) is None
    assert tele.observed_prune_ratio(10, 0, 0) == 1.0
    assert tele.observed_prune_ratio(1, 3, 0) == 0.25
    assert tele.observed_prune_ratio(1, 1, 2) == 0.25


def test_accumulator_totals_truncation_and_block():
    acc = tele.SearchTelemetry()
    blk = np.zeros((tele.TELE_ROWS, tele.TELE_COLS), np.int32)
    for i in range(tele.TELE_ROWS):
        blk[i] = (2, 4, 1, 0, 0, 2, 0, 0)
    acc.add_slice(blk)
    # every row written incl. the additive last one -> truncated
    assert acc.truncated is True
    assert acc.n_levels == tele.TELE_ROWS
    assert acc.totals["expanded"] == 4 * tele.TELE_ROWS
    out = acc.block(predicted=0.5)
    assert out["observed_prune_ratio"] == pytest.approx(4 / 5)
    assert out["predicted_prune_ratio"] == 0.5
    assert out["prune_ratio_delta"] == pytest.approx(0.3)
    assert out["truncated"] is True
    assert out["per_level_columns"] == list(tele.COLUMNS)


def test_accumulator_per_level_cap():
    acc = tele.SearchTelemetry()
    blk = np.zeros((tele.TELE_ROWS, tele.TELE_COLS), np.int32)
    blk[:, tele.C_OCC] = 1
    for _ in range(8):  # 8 x 128 levels > BLOCK_LEVEL_CAP
        acc.add_slice(blk)
    out = acc.block()
    assert len(out["per_level"]) == tele.BLOCK_LEVEL_CAP
    assert out["per_level_capped"] is True
    assert out["levels"] == 8 * tele.TELE_ROWS


def test_add_totals_folds_batched_blocks():
    acc = tele.SearchTelemetry()
    blk = np.zeros((3, tele.TELE_ROWS, tele.TELE_COLS), np.int32)
    blk[:, 0] = (5, 7, 1, 0, 0, 5, 0, 1)
    acc.add_totals(blk)  # 3-D: lane-sum first
    assert acc.totals["occupancy"] == 15
    assert acc.totals["expanded"] == 21
    assert acc.levels == []  # totals-only: no per-level rows kept


# ---------------------------------------------------------------------------
# The block rides device results and the counters move
# ---------------------------------------------------------------------------


def _crashy_seq(seed: int, model, n_ops: int = 50):
    """A crash-heavy simulated history: the class where the greedy
    witness / hb prepass usually fail to decide and the device BFS
    actually runs."""
    from jepsen_tpu.synth import sim_register_history

    rng = random.Random(seed)
    h = sim_register_history(rng, 4, n_ops, crash_p=0.15,
                             max_crashes=8)
    return encode_ops(h, model.f_codes)


def _device_searched(r: dict) -> bool:
    return str(r.get("engine", "")).startswith("device")


def _first_device_search(model, seeds=range(40)):
    for seed in seeds:
        s = _crashy_seq(seed, model)
        r = lin.search_opseq(s, model, dims=DIMS)
        if _device_searched(r) and "search_telemetry" in r:
            return s, r
    pytest.fail("no seed reached the device kernel")


def test_search_telemetry_block_on_device_result():
    model = cas_register()
    levels_before = REGISTRY.get(
        "jtpu_search_levels_total").total()
    s, r = _first_device_search(model)
    st = r["search_telemetry"]
    for k in ("levels", "slices", "max_occupancy", "expanded",
              "mask_killed", "dedup_folds", "crash_rounds",
              "overflows", "goals", "observed_prune_ratio",
              "truncated"):
        assert k in st, k
    assert st["levels"] > 0 and st["slices"] >= 1
    assert st["expanded"] > 0
    ratio = st["observed_prune_ratio"]
    assert ratio is not None and 0 < ratio <= 1.0
    # predicted (hb/dpor prepass) rides next to observed when computed
    if "predicted_prune_ratio" in st:
        assert st["prune_ratio_delta"] == pytest.approx(
            ratio - st["predicted_prune_ratio"], abs=1e-5)
    # per-level rows align with the totals
    per = st["per_level"]
    cols = st["per_level_columns"]
    exp_i = cols.index("expanded")
    if not st.get("per_level_capped"):
        assert sum(r2[exp_i] for r2 in per) == st["expanded"]
    # registry counters moved
    assert REGISTRY.get("jtpu_search_levels_total").total() \
        > levels_before
    assert REGISTRY.get(
        "jtpu_search_observed_prune_ratio").value() == ratio


def test_telemetry_off_attaches_nothing():
    model = cas_register()
    s, _ = _first_device_search(model)
    tele.enable(False)
    r = lin.search_opseq(s, model, dims=DIMS)
    assert "search_telemetry" not in r
    assert _device_searched(r)


def test_device_level_spans_under_tracing():
    model = cas_register()
    s, _ = _first_device_search(model)
    obs.enable(True)
    run = "t-tele-spans"
    obs.set_run(run)
    try:
        r = lin.search_opseq(s, model, dims=DIMS)
        spans = obs.recorder(run).spans()
    finally:
        obs.set_run(None)
        obs.drop_recorder(run)
        obs.enable(None)
    lvl = [s2 for s2 in spans if s2["name"] == "device.level"]
    slc = [s2 for s2 in spans if s2["name"] == "device.slice"]
    ts = [s2 for s2 in spans if s2["name"] == "search.telemetry"]
    assert slc and lvl and ts
    st = r["search_telemetry"]
    assert len(lvl) == min(st["levels"],
                           tele.TELE_ROWS * st["slices"])
    # child spans sit inside their slice's window and carry the
    # schema's args
    a = lvl[0]["args"]
    for k in ("level", "occupancy", "expanded", "mask_killed",
              "dedup_folds", "frontier"):
        assert k in a, k
    # level spans are apportioned inside the driver's t0..t1 window,
    # which opens a hair before the slice span object itself records
    assert lvl[0]["ts"] >= min(x["ts"] for x in slc) - 5000.0
    # the search.telemetry span carries the result block (minus the
    # per-level rows) — traces are self-contained for obs_guard
    assert ts[-1]["args"]["observed_prune_ratio"] == \
        st["observed_prune_ratio"]


def test_decided_search_emits_prune_span_without_block():
    """A statically decided search (hb prepass) has no device work:
    result keeps its certificate shape (no search_telemetry key) but
    a traced run still records observed=0 vs predicted=0."""
    model = register(0)
    h = []
    for p in range(3):  # unique writes, quiescent: hb decides
        h += [invoke_op(p, "write", 10 + p), ok_op(p, "write", 10 + p)]
    h += [invoke_op(0, "read", None), ok_op(0, "read", 12)]
    s = encode_ops(h, model.f_codes)
    obs.enable(True)
    run = "t-tele-decided"
    obs.set_run(run)
    try:
        r = lin.search_opseq(s, model, dims=DIMS)
        spans = obs.recorder(run).spans()
    finally:
        obs.set_run(None)
        obs.drop_recorder(run)
        obs.enable(None)
    assert (r.get("hb") or {}).get("decided") is not None \
        or r.get("engine") in ("hb-decide", "greedy-witness")
    ts = [s2 for s2 in spans if s2["name"] == "search.telemetry"]
    if (r.get("hb") or {}).get("decided") is not None:
        assert "search_telemetry" not in r
        assert ts and ts[-1]["args"].get("decided") is True
        assert ts[-1]["args"]["observed_prune_ratio"] == 0.0
        assert "prune_ratio_delta" in ts[-1]["args"]


def test_mask_and_dedup_columns_fire_when_reductions_do():
    """Crash-heavy cas histories build masked (+dedup) kernels: the
    aux block's mask-kill / dedup-fold columns must actually move —
    the observed twin of the dpor layer's predicted reductions."""
    model = cas_register()
    killed = folded = False
    for seed in range(60):
        s = _crashy_seq(seed, model)
        # dpor pinned on: the reductions must not depend on what env
        # state earlier test files left behind
        r = lin.search_opseq(s, model, dims=DIMS, dpor=True)
        st = r.get("search_telemetry")
        if not st:
            continue
        killed = killed or st["mask_killed"] > 0
        folded = folded or st["dedup_folds"] > 0
        if killed and folded:
            break
    assert killed, "no seed produced device mask kills"
    assert folded, "no seed produced device dedup folds"


# ---------------------------------------------------------------------------
# Compile / transfer accounting
# ---------------------------------------------------------------------------


def test_compile_span_on_miss_never_on_hit():
    model = cas_register()
    # dims unique to this test so the first get_kernel is a real miss
    dims = lin.SearchDims(n_det_pad=96, n_crash_pad=32, window=64,
                          k=16, state_width=1, frontier=128)
    for k in [k for k in list(lin._KERNEL_CACHE) if dims in k]:
        lin._KERNEL_CACHE.pop(k, None)
    obs.enable(True)
    run = "t-compile-span"
    obs.set_run(run)
    try:
        lin.get_kernel(model, dims, telemetry=tele.enabled())
        first = [s for s in obs.recorder(run).spans()
                 if s["name"] == "device.compile"]
        lin.get_kernel(model, dims, telemetry=tele.enabled())
        second = [s for s in obs.recorder(run).spans()
                  if s["name"] == "device.compile"]
    finally:
        obs.set_run(None)
        obs.drop_recorder(run)
        obs.enable(None)
    assert len(first) == 1, "cache miss must record device.compile"
    a = first[0]["args"]
    assert a["cache"] == "miss"
    assert a["engine"] in ("xla", "pallas")
    assert "persistent_cache" in a
    assert len(second) == 1, "cache hit must NOT record a compile"


def test_compile_span_detects_persistent_cache(tmp_path,
                                               monkeypatch):
    from jepsen_tpu import util

    monkeypatch.delenv("JEPSEN_TPU_COMPILE_CACHE_DIR", raising=False)
    prior = jax.config.jax_compilation_cache_dir
    applied = util.enable_compilation_cache(str(tmp_path))
    assert applied == str(tmp_path)
    obs.enable(True)
    run = "t-compile-pcache"
    obs.set_run(run)
    try:
        with tele.compile_span(engine="xla"):
            pass
        span = [s for s in obs.recorder(run).spans()
                if s["name"] == "device.compile"][0]
        assert span["args"]["persistent_cache"] is True
        jax.config.update("jax_compilation_cache_dir", prior)
        with tele.compile_span(engine="xla"):
            pass
        span2 = [s for s in obs.recorder(run).spans()
                 if s["name"] == "device.compile"][-1]
        assert span2["args"]["persistent_cache"] is False
    finally:
        jax.config.update("jax_compilation_cache_dir", prior)
        obs.set_run(None)
        obs.drop_recorder(run)
        obs.enable(None)


def test_transfer_accounting_counts_bytes():
    m = REGISTRY.get("jtpu_device_transfer_bytes_total")
    before = m.value(direction="h2d")
    arrs = (np.zeros(10, np.int32), np.zeros((4, 4), np.int32))
    nb = tele.transfer_bytes(arrs)
    assert nb == 40 + 64
    tele.record_transfer(nb)
    assert m.value(direction="h2d") == before + nb
    tele.record_transfer(0)  # no-op, no crash
    assert m.value(direction="h2d") == before + nb


def test_device_seconds_and_idle_fraction_derived():
    from jepsen_tpu.obs.metrics import derived_stats

    tele.record_device_seconds(0.25)
    d = derived_stats(REGISTRY)
    assert "device_idle_fraction" in d
    assert 0.0 <= d["device_idle_fraction"] <= 1.0
    assert "observed_prune_ratio" in d


# ---------------------------------------------------------------------------
# Differential fuzz: byte-identical verdicts on/off, all routes
# ---------------------------------------------------------------------------


def _routes(s, model, mesh=None):
    from jepsen_tpu.decompose.engine import check_opseq_decomposed
    from jepsen_tpu.stream import StreamChecker

    out = {
        "dfs": oracle.check_opseq(s, model),
        "linear": check_opseq_linear(s, model, witness_cap=200_000),
        "direct": lin.search_opseq(s, model, budget=300_000,
                                   dims=DIMS),
        "decomposed": check_opseq_decomposed(s, model, witness=True),
        "batched": lin.search_batch([s, s], model,
                                    budget=300_000)[0],
        "bucketed": lin.search_batch([s], model, bucket=True,
                                     budget=300_000)[0],
    }
    if mesh is not None:
        out["sharded"] = lin.search_opseq_sharded(
            s, model, mesh, budget=300_000)
    return out


@pytest.mark.parametrize("group", range(3))
def test_differential_fuzz_identical_verdicts(group, mesh):
    """Telemetry ON vs OFF: every route's verdict bytes (minus the
    block itself) must be identical, audits clean, across valid,
    corrupted, and crash-heavy histories + a streamed leg."""
    from jepsen_tpu.analyze.audit import audit as audit_fn
    from jepsen_tpu.stream import StreamChecker

    n_checked = 0
    for i in range(8):
        seed = group * 100 + i
        rng = random.Random(seed)
        model = cas_register()
        h = register_history(rng, n_ops=30, n_procs=4, overlap=4,
                             crash_p=(0.0, 0.1, 0.25)[group])
        if i % 2:
            h = corrupt_read(rng, h, at=0.8)
        s = encode_ops(h, model.f_codes)

        tele.enable(True)
        on = _routes(s, model, mesh)
        sc = StreamChecker(model)
        for op in h:
            sc.ingest(op)
        on["streamed"] = sc.finalize()

        tele.enable(False)
        off = _routes(s, model, mesh)
        sc = StreamChecker(model)
        for op in h:
            sc.ingest(op)
        off["streamed"] = sc.finalize()
        tele.enable(None)

        for route in on:
            assert _strip(on[route]) == _strip(off[route]), \
                f"seed {seed} route {route} verdict bytes differ"
            if on[route]["valid"] != "unknown" \
                    and route != "streamed":
                a = audit_fn(s, model, on[route])
                assert a["ok"], f"seed {seed} route {route} audit"
        n_checked += 1
    assert n_checked == 8


def test_explain_plan_carries_telemetry_block():
    """The static plan states where its predicted prune ratios become
    observations — and that they won't, when the knob is off."""
    from jepsen_tpu.analyze.plan import explain, render_plan

    model = cas_register()
    s = _crashy_seq(0, model)
    plan = explain(s, model)
    assert plan["telemetry"]["enabled"] is True
    assert "observed" in plan["telemetry"]["observed_at"]
    assert "telemetry: on" in render_plan(plan)
    tele.enable(False)
    try:
        plan = explain(s, model)
        assert plan["telemetry"]["enabled"] is False
        assert "telemetry: off" in render_plan(plan)
    finally:
        tele.enable(None)


def test_sharded_route_telemetry_block(mesh):
    """The mesh-sharded driver aggregates per-shard blocks; its
    telemetry must ride the result like the single-device path."""
    model = cas_register()
    for seed in range(30):
        s = _crashy_seq(seed, model)
        r = lin.search_opseq_sharded(s, model, mesh, budget=300_000)
        st = r.get("search_telemetry")
        if st and st["levels"] > 0:
            assert st["expanded"] > 0
            assert st["observed_prune_ratio"] is not None
            return
    pytest.fail("no sharded search produced device telemetry")
