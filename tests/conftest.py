"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Real TPU hardware is single-chip (or absent) in CI; multi-chip sharding is
validated on a host-platform device mesh, per the build contract.  Must run
before the first `import jax` anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize registers a TPU PJRT plugin and imports jax
# before any conftest runs, so the env vars above are not enough on their
# own — pin the platform via config too (backends are not yet initialized
# when conftest loads, so this still takes effect).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running (excluded from the tier-1 "
                   "'not slow' gate)")
