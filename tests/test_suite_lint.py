"""Suite protocol lint (jepsen_tpu/analyze/suites.py) — the CI gate.

``test_bundled_suites_have_no_protocol_errors`` is the tier-1 guard: a
new suite cannot merge with an ERROR-severity protocol violation (broad
except converting crashes to determinate completions, invoke paths that
return None, nemesis completions that aren't :info).  The rest pins the
rules themselves on fixture sources, and regression-tests the defects
the lint actually found in the bundled suites.
"""

import json
import os
import subprocess
import sys
import urllib.error

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.analyze.suites import (  # noqa: E402
    SUITE_CODES,
    lint_live_source,
    lint_paths,
    lint_source,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(diags, severity=None):
    return {d.code for d in diags
            if severity is None or d.severity == severity}


# ---------------------------------------------------------------------------
# the CI gate: bundled suites must be protocol-clean
# ---------------------------------------------------------------------------


def test_bundled_suites_have_no_protocol_errors():
    findings = lint_paths()
    errors = [(f, d) for f, ds in findings.items() for d in ds
              if d.severity == "error"]
    assert errors == [], "suite protocol errors:\n" + "\n".join(
        f"  {d.message}" for _f, d in errors)


def test_lint_suites_cli_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_suites.py"),
         "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["errors"] == 0
    assert set(payload) == {"errors", "warnings", "files"}


def test_lint_suites_cli_flags_errors(tmp_path):
    bad = tmp_path / "bad_suite.py"
    bad.write_text(
        "class FooClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        try:\n"
        "            return replace(op, type='ok')\n"
        "        except Exception:\n"
        "            return replace(op, type='ok')\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_suites.py"),
         str(bad)],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1
    assert "S002" in out.stdout


# ---------------------------------------------------------------------------
# the rules, on fixture sources
# ---------------------------------------------------------------------------


def test_s001_invoke_returns_none_and_falls_off():
    src = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        if op.f == 'read':\n"
        "            return None\n")
    diags = lint_source(src, "fix.py")
    assert codes(diags, "error") == {"S001"}
    assert len(diags) == 2  # the None return AND the fall-through


def test_s001_return_op_unchanged():
    src = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        return op\n")
    assert "S001" in codes(lint_source(src, "f.py"), "error")
    # reassigned op is a completion — not flagged
    src_ok = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        op = replace(op, type='ok')\n"
        "        return op\n")
    assert lint_source(src_ok, "f.py") == []


def test_s001_clean_shapes_accepted():
    src = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        try:\n"
        "            if op.f == 'read':\n"
        "                return replace(op, type='ok', value=1)\n"
        "            raise ValueError(op.f)\n"
        "        except OSError as e:\n"
        "            return replace(op, type='info', error=str(e))\n")
    assert lint_source(src, "f.py") == []


def test_s002_broad_except_to_ok():
    src = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        try:\n"
        "            return replace(op, type='ok')\n"
        "        except Exception:\n"
        "            return replace(op, type='ok')\n")
    assert "S002" in codes(lint_source(src, "f.py"), "error")


def test_s003_broad_except_unconditional_fail():
    src = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        try:\n"
        "            return replace(op, type='ok')\n"
        "        except Exception as e:\n"
        "            return replace(op, type='fail', error=str(e))\n")
    assert "S003" in codes(lint_source(src, "f.py"), "error")


def test_s003_guarded_or_conditional_fail_is_clean():
    # the idiomatic forms stay clean: a type conditioned on op.f, a
    # fail return guarded by an exception test with re-raise
    src = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        try:\n"
        "            return replace(op, type='ok')\n"
        "        except Exception as e:\n"
        "            if 'conflict' in str(e):\n"
        "                return replace(op, type='fail')\n"
        "            raise\n")
    assert lint_source(src, "f.py") == []
    src2 = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        try:\n"
        "            return replace(op, type='ok')\n"
        "        except Exception as e:\n"
        "            return replace(op, type='fail' if op.f == 'read'"
        " else 'info', error=str(e))\n")
    assert lint_source(src2, "f.py") == []


def test_s004_db_pairing():
    src = (
        "class FooDB(db_mod.DB):\n"
        "    def setup(self, test, node):\n"
        "        pass\n")
    diags = lint_source(src, "f.py")
    assert codes(diags) == {"S004"}
    assert all(d.severity == "warning" for d in diags)


def test_s005_nemesis_completion_type():
    src = (
        "class FooNemesis(nemesis_mod.Nemesis):\n"
        "    def invoke(self, test, op):\n"
        "        return replace(op, type='ok')\n")
    assert "S005" in codes(lint_source(src, "f.py"), "error")
    src_ok = src.replace("'ok'", "'info'")
    assert lint_source(src_ok, "f.py") == []


def test_suppression_comment():
    src = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        try:\n"
        "            return replace(op, type='ok')\n"
        "        except Exception as e:\n"
        "            return replace(op, type='fail')  # suite-lint: ok\n")
    assert lint_source(src, "f.py") == []


def test_codes_documented():
    for code in ("S001", "S002", "S003", "S004", "S005",
                 "B001", "B002", "B003"):
        assert code in SUITE_CODES


# ---------------------------------------------------------------------------
# B-codes: live backend protocol (jepsen_tpu/live/)
# ---------------------------------------------------------------------------


def test_b001_concrete_backend_missing_protocol_member():
    src = (
        "class BrokenBackend(LiveBackend):\n"
        "    name = 'broken'\n"
        "    def workload(self, opts):\n"
        "        return {}\n")
    assert "B001" in codes(lint_live_source(src, "f.py"), "error")


def test_b001_abstract_intermediate_is_exempt():
    # the replicated consensus core pattern: no `name`, protocol left
    # to concrete families — and the family inheriting through it is
    # clean when the chain provides everything
    src = (
        "class ConsensusBackend(LiveBackend):\n"
        "    def health_check(self, test, node):\n"
        "        pass\n"
        "class FamBackend(ConsensusBackend):\n"
        "    name = 'fam'\n"
        "    def server_argv(self, test, node):\n"
        "        return []\n"
        "    def workload(self, opts):\n"
        "        return {}\n")
    assert lint_live_source(src, "f.py") == []


def test_b001_annotated_name_and_async_members_recognized():
    # review regression: `name: str = 'fam'` (AnnAssign) and async
    # protocol members must count as provided
    src = (
        "class FamBackend(LiveBackend):\n"
        "    name: str = 'fam'\n"
        "    def server_argv(self, test, node):\n"
        "        return []\n"
        "    async def workload(self, opts):\n"
        "        return {}\n")
    assert lint_live_source(src, "f.py") == []
    # a bare annotation with no value is NOT a name assignment
    src2 = (
        "class ShyBackend(LiveBackend):\n"
        "    name: str\n"
        "    def server_argv(self, test, node):\n"
        "        return []\n"
        "    def workload(self, opts):\n"
        "        return {}\n")
    assert "B001" in codes(lint_live_source(src2, "f.py"), "error")


def test_b001_unnamed_but_complete_backend_flagged():
    src = (
        "class ShyBackend(LiveBackend):\n"
        "    def server_argv(self, test, node):\n"
        "        return []\n"
        "    def workload(self, opts):\n"
        "        return {}\n")
    diags = lint_live_source(src, "f.py")
    assert "B001" in codes(diags, "error")
    assert "name" in diags[0].message


def test_b002_live_helper_swallows_crash_to_fail():
    src = (
        "class Shim:\n"
        "    def fetch(self, op):\n"
        "        try:\n"
        "            return do(op)\n"
        "        except Exception:\n"
        "            return replace(op, type='fail')\n")
    assert "B002" in codes(lint_live_source(src, "f.py"), "error")
    # a guarded / re-raising handler stays clean
    src_ok = src.replace("            return replace(op, type='fail')\n",
                         "            if op.f == 'read':\n"
                         "                return replace(op, "
                         "type='fail')\n"
                         "            raise\n")
    assert lint_live_source(src_ok, "f.py") == []


def test_b002_does_not_double_report_client_invoke():
    # *Client.invoke is S003's beat (lint_source); the live lint must
    # not duplicate the finding
    src = (
        "class FooClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        try:\n"
        "            return replace(op, type='ok')\n"
        "        except Exception:\n"
        "            return replace(op, type='fail')\n")
    assert "B002" not in codes(lint_live_source(src, "f.py"))
    assert "S003" in codes(lint_source(src, "f.py"), "error")


def test_b003_rename_without_fsync():
    src = (
        "import os\n"
        "def save(path, data):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(data)\n"
        "    os.replace(tmp, path)\n")
    assert "B003" in codes(lint_live_source(src, "f.py"), "error")
    src_ok = (
        "import os\n"
        "def save(path, data):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(data)\n"
        "        f.flush()\n"
        "        os.fsync(f.fileno())\n"
        "    os.replace(tmp, path)\n")
    assert lint_live_source(src_ok, "f.py") == []
    # read-only opens next to a rename are not journal writes
    src_ro = (
        "import os\n"
        "def rotate(path):\n"
        "    with open(path, 'r') as f:\n"
        "        f.read()\n"
        "    os.rename(path, path + '.old')\n")
    assert lint_live_source(src_ro, "f.py") == []


def test_bundled_live_backends_are_clean():
    findings = lint_paths([os.path.join(REPO, "jepsen_tpu", "live")])
    errors = [d for ds in findings.values() for d in ds
              if d.severity == "error"]
    assert errors == [], "\n".join(d.message for d in errors)


# ---------------------------------------------------------------------------
# regression tests for the defects the lint found (satellite 2)
# ---------------------------------------------------------------------------


def test_chronos_crashed_addjob_is_indeterminate(monkeypatch):
    """chronos.py used to convert EVERY invoke crash to :fail — but a
    crashed add-job POST may have been applied, and a silently-scheduled
    job would then run without the checker expecting it.  Crashed
    add-jobs must complete :info; crashed reads (effect-free) stay
    :fail."""
    from jepsen_tpu.history import Op
    from jepsen_tpu.suites import chronos

    def boom(*a, **kw):
        raise urllib.error.URLError("connection refused")

    monkeypatch.setattr(chronos.urllib.request, "urlopen", boom)
    client = chronos.ChronosClient(node="n1")
    job = {"name": "j1", "start": 10.0, "count": 5, "interval": 60,
           "epsilon": 15, "duration": 5}
    out = client.invoke({}, Op(process=0, type="invoke", f="add-job",
                               value=job))
    assert out.type == "info"

    def read_boom(_test):
        raise OSError("ssh down")

    monkeypatch.setattr(chronos, "read_runs", read_boom)
    out = client.invoke({}, Op(process=0, type="invoke", f="read",
                               value=None))
    assert out.type == "fail"


def test_robustirc_close_deletes_server_session():
    """robustirc's SetClient opened a server-side session per open()
    and never deleted it — the worker reopens clients after every
    crash, so sessions accumulated on the server for the whole run.
    close() must issue the DELETE (and survive a dead server)."""
    from jepsen_tpu.suites import robustirc

    calls = []

    class FakeSession:
        def quit(self, message="x"):
            calls.append("quit")

    c = robustirc.SetClient("n1")
    c.session = FakeSession()
    c.close({})
    assert calls == ["quit"]
    assert c.session is None

    class DeadSession:
        def quit(self, message="x"):
            raise OSError("server gone")

    c2 = robustirc.SetClient("n1")
    c2.session = DeadSession()
    c2.close({})  # must not raise
    assert c2.session is None


def test_robustirc_session_quit_issues_delete(monkeypatch):
    from jepsen_tpu.suites import robustirc

    reqs = []

    class R:
        def __init__(self):
            self.fp = None

        def read(self):
            return b"{}"

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def close(self):
            pass

    def fake_urlopen(req, timeout=None, context=None):
        reqs.append((req.get_method(), req.full_url))
        return R()

    monkeypatch.setattr(robustirc.urllib.request, "urlopen",
                        fake_urlopen)
    monkeypatch.setattr(
        robustirc.IRCSession, "__init__",
        lambda self, node, timeout=10.0: (
            setattr(self, "node", str(node)),
            setattr(self, "timeout", timeout),
            setattr(self, "ctx", None),
            setattr(self, "session_id", "sess42"),
            setattr(self, "session_auth", "auth"),
        ) and None)
    s = robustirc.IRCSession("n1")
    s.quit()
    assert reqs and reqs[-1][0] == "DELETE"
    assert "/robustirc/v1/sess42" in reqs[-1][1]


@pytest.mark.parametrize("fname", ["chronos.py", "robustirc.py"])
def test_fixed_suites_stay_clean(fname):
    findings = lint_paths([os.path.join(
        REPO, "jepsen_tpu", "suites", fname)])
    errors = [d for ds in findings.values() for d in ds
              if d.severity == "error"]
    assert errors == []


# ---------------------------------------------------------------------------
# tools/lint_suites.py --json exit-code coverage (B fixtures): the CLI
# contract CI and scripts depend on — 1 on any error-severity finding,
# 0 on warning-only/clean, with the finding visible in the JSON payload
# ---------------------------------------------------------------------------


def _run_lint_json(*paths):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_suites.py"),
         "--json", *map(str, paths)],
        capture_output=True, text=True, cwd=REPO)
    return out.returncode, json.loads(out.stdout)


def test_lint_suites_json_exit_1_on_b_code_fixture(tmp_path):
    live = tmp_path / "live"
    live.mkdir()
    bad = live / "bad_backend.py"
    bad.write_text(
        "import os\n"
        "def journal(path, line):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(line)\n"
        "    os.replace(tmp, path)\n")
    rc, payload = _run_lint_json(bad)
    assert rc == 1
    assert payload["errors"] >= 1
    found = {d["code"] for ds in payload["files"].values() for d in ds}
    assert "B003" in found


def test_lint_suites_json_exit_0_on_clean_live_fixture(tmp_path):
    live = tmp_path / "live"
    live.mkdir()
    clean = live / "ok_backend.py"
    clean.write_text(
        "import os\n"
        "def journal(path, line):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(line)\n"
        "        f.flush()\n"
        "        os.fsync(f.fileno())\n"
        "    os.replace(tmp, path)\n")
    rc, payload = _run_lint_json(clean)
    assert rc == 0
    assert payload["errors"] == 0


def test_lint_suites_json_exit_1_on_b002_fixture(tmp_path):
    live = tmp_path / "live"
    live.mkdir()
    bad = live / "swallow_backend.py"
    bad.write_text(
        "from dataclasses import replace\n"
        "def probe(op):\n"
        "    try:\n"
        "        return do(op)\n"
        "    except Exception:\n"
        "        return replace(op, type='fail')\n")
    rc, payload = _run_lint_json(bad)
    assert rc == 1
    found = {d["code"] for ds in payload["files"].values() for d in ds}
    assert "B002" in found


# ---------------------------------------------------------------------------
# N-codes — JEPSEN_TPU_* knob threading
# ---------------------------------------------------------------------------

from jepsen_tpu.analyze.suites import (  # noqa: E402
    lint_knobs,
    lint_metrics,
    registered_metrics,
)


def _knob_pkg(tmp_path, src):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(src)
    return pkg


def _all(diags_by_file):
    return [d for ds in diags_by_file.values() for d in ds]


def test_n001_toggle_without_cli_flag(tmp_path):
    pkg = _knob_pkg(tmp_path, (
        "import os\n"
        "def foo_enabled():\n"
        "    return os.environ.get('JEPSEN_TPU_FOO', '') != '0'\n"))
    out = _all(lint_knobs(pkg, cli_text="",
                          docs_text="JEPSEN_TPU_FOO"))
    assert {d.code for d in out} == {"N001"}
    # a cli.py mention clears it
    out = _all(lint_knobs(pkg, cli_text="JEPSEN_TPU_FOO",
                          docs_text="JEPSEN_TPU_FOO"))
    assert out == []


def test_n001_needs_the_enabled_idiom(tmp_path):
    # a plain function read is not a toggle: no N001
    pkg = _knob_pkg(tmp_path, (
        "import os\n"
        "def depth():\n"
        "    return int(os.environ.get('JEPSEN_TPU_DEPTH', '4'))\n"))
    out = _all(lint_knobs(pkg, cli_text="",
                          docs_text="JEPSEN_TPU_DEPTH"))
    assert out == []


def test_n002_import_time_read_of_cli_claimed_knob(tmp_path):
    pkg = _knob_pkg(tmp_path, (
        "import os\n"
        "MODE = os.environ.get('JEPSEN_TPU_BAR', 'auto')\n"))
    out = _all(lint_knobs(pkg, cli_text="JEPSEN_TPU_BAR",
                          docs_text="JEPSEN_TPU_BAR"))
    assert {d.code for d in out} == {"N002"}
    # env-only tuning constants (no cli.py claim) are exempt
    out = _all(lint_knobs(pkg, cli_text="",
                          docs_text="JEPSEN_TPU_BAR"))
    assert out == []


def test_n003_undocumented_knob_and_internal_exemption(tmp_path):
    pkg = _knob_pkg(tmp_path, (
        "import os\n"
        "def f():\n"
        "    a = os.environ['JEPSEN_TPU_MYSTERY']\n"
        "    b = os.environ.get('JEPSEN_TPU_PROC_ID')\n"
        "    return a, b\n"))
    out = _all(lint_knobs(pkg, cli_text="", docs_text=""))
    assert [(d.code, d.severity) for d in out] == [("N003", "warning")]
    assert "JEPSEN_TPU_MYSTERY" in out[0].message


def test_n003_membership_test_counts_as_read(tmp_path):
    pkg = _knob_pkg(tmp_path, (
        "import os\n"
        "def f():\n"
        "    return 'JEPSEN_TPU_GHOST' in os.environ\n"))
    out = _all(lint_knobs(pkg, cli_text="", docs_text=""))
    assert {d.code for d in out} == {"N003"}


def test_knoblint_suppression(tmp_path):
    pkg = _knob_pkg(tmp_path, (
        "import os\n"
        "def foo_enabled():\n"
        "    return os.environ.get('JEPSEN_TPU_FOO') == '1'"
        "  # knoblint: ok\n"))
    out = _all(lint_knobs(pkg, cli_text="", docs_text=""))
    assert out == []


def test_package_knobs_are_threaded():
    """The CI gate: every knob the package reads has its CLI flag, no
    cli-claimed knob freezes at import, everything is documented."""
    out = _all(lint_knobs())
    assert [str(d) for d in out if d.severity == "error"] == []
    assert [str(d) for d in out if d.severity == "warning"] == []


# ---------------------------------------------------------------------------
# O-codes — jtpu_* metrics contract
# ---------------------------------------------------------------------------

def _metrics_pkg(tmp_path, *names):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    body = "from x import REGISTRY\n" + "".join(
        f"M{i} = REGISTRY.counter('{n}', 'h')\n"
        for i, n in enumerate(names))
    (pkg / "m.py").write_text(body)
    return pkg


def test_o001_consumer_references_unregistered_series(tmp_path):
    pkg = _metrics_pkg(tmp_path, "jtpu_real_total")
    web = tmp_path / "web.py"
    web.write_text("PANEL = ['jtpu_real_total', 'jtpu_ghost_total']\n")
    out = _all(lint_metrics(pkg, consumers=[web]))
    o001 = [d for d in out if d.code == "O001"]
    assert len(o001) == 1 and "jtpu_ghost_total" in o001[0].message


def test_o001_histogram_suffixes_resolve_to_family(tmp_path):
    pkg = _metrics_pkg(tmp_path, "jtpu_lat_seconds")
    web = tmp_path / "web.py"
    web.write_text("Q = 'jtpu_lat_seconds_bucket'\n")
    out = [d for d in _all(lint_metrics(pkg, consumers=[web]))
           if d.code == "O001"]
    assert out == []


def test_o002_orphans_aggregate_into_one_warning(tmp_path):
    pkg = _metrics_pkg(tmp_path, "jtpu_used_total",
                       "jtpu_orphan_a_total", "jtpu_orphan_b_total")
    web = tmp_path / "web.py"
    web.write_text("P = 'jtpu_used_total'\n")
    out = _all(lint_metrics(pkg, consumers=[web]))
    o002 = [d for d in out if d.code == "O002"]
    assert len(o002) == 1 and o002[0].severity == "warning"
    assert "jtpu_orphan_a_total" in o002[0].message
    assert "jtpu_orphan_b_total" in o002[0].message


def test_metriclint_suppression(tmp_path):
    pkg = _metrics_pkg(tmp_path, "jtpu_real_total")
    web = tmp_path / "web.py"
    web.write_text("G = 'jtpu_ghost_total'  # metriclint: ok\n")
    out = [d for d in _all(lint_metrics(pkg, consumers=[web]))
           if d.code == "O001"]
    assert out == []


def test_package_metrics_contract_holds():
    """The CI gate: every series a consumer surface references is
    registered (O001 clean); the mc layer's own series are present."""
    out = _all(lint_metrics())
    assert [str(d) for d in out if d.severity == "error"] == []
    reg = registered_metrics()
    for name in ("jtpu_mc_states_total", "jtpu_mc_schedules_total",
                 "jtpu_mc_violations_total", "jtpu_mc_prune_ratio"):
        assert name in reg, name


def test_new_codes_registered_and_cli_runs(capsys):
    for code in ("N001", "N002", "N003", "O001", "O002"):
        assert code in SUITE_CODES
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_suites_cli", os.path.join(REPO, "tools",
                                        "lint_suites.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--knobs", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["errors"] == 0


# ---------------------------------------------------------------------------
# R-codes — retry idempotency
# ---------------------------------------------------------------------------

from jepsen_tpu.analyze.suites import (  # noqa: E402
    lint_retry,
    lint_retry_source,
)


def test_r001_backoff_run_of_mutation_without_info():
    src = (
        "class AClient(Client):\n"
        "    def invoke(self, test, op):\n"
        "        self.backoff.run(lambda: self.conn.put(op.value))\n"
        "        return replace(op, type='ok')\n")
    assert codes(lint_retry_source(src, "fix.py"),
                 "error") == {"R001"}
    # same construct with an :info completion path is the idiom — clean
    ok = src + (
        "\n"
        "    def invoke2(self, test, op):\n"
        "        try:\n"
        "            self.backoff.run(lambda: self.conn.put(op.value))\n"
        "            return replace(op, type='ok')\n"
        "        except Exception:\n"
        "            return replace(op, type='info')\n")
    diags = lint_retry_source(ok, "fix.py")
    assert [d for d in diags if "invoke2" in d.message] == []


def test_r001_with_conn_of_mutation():
    src = (
        "def invoke(self, test, op):\n"
        "    self.wrapper.with_conn(lambda c: c.write(op.value))\n"
        "    return replace(op, type='ok')\n")
    assert codes(lint_retry_source(src, "fix.py"),
                 "error") == {"R001"}
    # reads through the same wrapper are idempotent — clean
    read = src.replace("c.write", "c.read")
    assert lint_retry_source(read, "fix.py") == []


def test_r001_attempt_loop_mutation_and_r002_swallow():
    src = (
        "def do(conn, op):\n"
        "    for attempt in range(3):\n"
        "        try:\n"
        "            conn.enqueue(op)\n"
        "            return 'ok'\n"
        "        except Exception:\n"
        "            continue\n")
    got = codes(lint_retry_source(src, "fix.py"), "error")
    assert got == {"R001", "R002"}


def test_r002_only_when_loop_is_retry_shaped():
    # a per-item scan skipping bad items is NOT a retry loop
    scan = (
        "def sweep(files):\n"
        "    for f in files:\n"
        "        try:\n"
        "            load(f)\n"
        "        except Exception:\n"
        "            continue\n")
    assert lint_retry_source(scan, "fix.py") == []
    # kept-last-error used after the loop is the legitimate exit
    kept = (
        "def do(conn, op):\n"
        "    last = None\n"
        "    for attempt in range(3):\n"
        "        try:\n"
        "            return conn.read(op)\n"
        "        except Exception as e:\n"
        "            last = e\n"
        "    return replace(op, type='fail', error=str(last))\n")
    assert lint_retry_source(kept, "fix.py") == []
    # re-raise after the loop is Backoff.run semantics — clean
    rr = (
        "def do(conn, op):\n"
        "    for attempt in range(3):\n"
        "        try:\n"
        "            return conn.read(op)\n"
        "        except Exception:\n"
        "            continue\n"
        "    raise RuntimeError('budget')\n")
    assert lint_retry_source(rr, "fix.py") == []


def test_r_probe_loops_and_backoff_run_itself_are_clean():
    probe = (
        "def wait(self):\n"
        "    while not self.bo.exhausted():\n"
        "        try:\n"
        "            self.health_check()\n"
        "            return\n"
        "        except Exception:\n"
        "            sleep(self.bo.step())\n"
        "    raise RuntimeError('dead')\n")
    assert lint_retry_source(probe, "fix.py") == []


def test_retrylint_suppression():
    src = (
        "def do(conn, op):\n"
        "    for attempt in range(3):\n"
        "        try:\n"
        "            conn.enqueue(op)  # server dedups; retrylint: ok\n"
        "            return 'ok'\n"
        "        except Exception:\n"
        "            continue\n"
        "    raise RuntimeError('budget')\n")
    assert lint_retry_source(src, "fix.py") == []


def test_package_retry_discipline_holds():
    """The CI gate: no automatically retried mutation in the package
    without :info handling (the reconnect layer, health probes, and
    queue clients must all classify clean)."""
    out = _all(lint_retry())
    assert [str(d) for d in out
            if d.severity == "error"] == []


def test_lint_suites_cli_retry_flag(capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_suites_cli_r", os.path.join(REPO, "tools",
                                          "lint_suites.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--retry", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["errors"] == 0
