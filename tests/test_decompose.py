"""P-compositional decomposition engine (jepsen_tpu/decompose/).

The subsystem's contract is absolute: ``decompose=True`` must be
verdict-identical to the direct engines on every history — valid,
invalid, crashed-op-laden, multi-key — while doing exponentially less
work where a split applies and ZERO search work on a canonical-hash
cache hit.  The differential fuzz here (>= 300 histories, :info ops
included) is the enforcement; the targeted tests pin the individual
decomposition theorems (value-block exactness incl. the naive-
projection counterexample, quiescence threading, locality) and the
cache/scheduler plumbing.
"""

from __future__ import annotations

import os
import random
from dataclasses import replace

import numpy as np
import pytest

from jepsen_tpu.history import (encode_ops, info_op, invoke_op, ok_op)
from jepsen_tpu.models import (cas_register, multi_register, mutex,
                               register)
from jepsen_tpu.synth import (flip_read, register_history,
                              sim_mutex_history, sim_register_history)


def _direct(seq, model):
    from jepsen_tpu.checker.seq import check_opseq

    return check_opseq(seq, model)


def _decomposed(seq, model, **kw):
    from jepsen_tpu.decompose.engine import check_opseq_decomposed

    return check_opseq_decomposed(
        seq, model, direct=lambda s: _direct(s, model), **kw)


def sim_multireg_history(rng, width=3, n_procs=4, n_ops=30,
                         crash_p=0.05):
    """Valid-by-construction multi-register history ((key, value) ops);
    crashed writes apply with probability .5."""
    state = {k: 0 for k in range(width)}
    h, pending, crashed = [], {}, set()
    done = 0
    while done < n_ops or pending:
        live = [p for p in range(n_procs) if p not in crashed]
        if not live:
            break
        p = rng.choice(live)
        if p in pending:
            f, k, v = pending.pop(p)
            if crash_p and rng.random() < crash_p:
                if rng.random() < 0.5 and f == "write":
                    state[k] = v
                crashed.add(p)
                h.append(info_op(p, f, (k, v if f == "write" else None)))
                continue
            if f == "read":
                h.append(ok_op(p, f, (k, state[k])))
            else:
                state[k] = v
                h.append(ok_op(p, f, (k, v)))
        elif done < n_ops:
            f = rng.choice(["read", "write"])
            k = rng.randrange(width)
            v = None if f == "read" else rng.randrange(5)
            h.append(invoke_op(p, f, (k, v)))
            pending[p] = (f, k, v)
            done += 1
    return h


def _flip_mr_read(rng, h):
    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "read"]
    if not idx:
        return h
    h = list(h)
    i = rng.choice(idx)
    k, v = h[i].value
    h[i] = replace(h[i], value=(k, (v or 0) + 7))
    return h


# ---------------------------------------------------------------------------
# differential fuzz: >= 300 histories, zero verdict divergences
# ---------------------------------------------------------------------------


def _fuzz_cases():
    """(label, model, seq) for 320 histories: cas-register with :info
    ops and corruptions, unique-write registers (the value-block class),
    low-overlap registers (the quiescence class), mutex with crashes,
    and multi-register (the locality class)."""
    cases = []
    for i in range(110):  # cas-register, crashes, 1/3 corrupted
        rng = random.Random(i)
        m = cas_register()
        h = sim_register_history(rng, n_procs=4, n_ops=24, crash_p=0.1,
                                 cas=(i % 2 == 0))
        if i % 3 == 0:
            h = flip_read(rng, h)
        cases.append(("cas", m, encode_ops(h, m.f_codes)))
    for i in range(70):  # unique writes: the value-block fast path
        rng = random.Random(1000 + i)
        m = register(0)
        h = register_history(rng, n_ops=36, n_procs=6, overlap=5,
                             crash_p=0.0, n_values=10**6, cas=False)
        if i % 2 == 0:
            h = flip_read(rng, h)
        cases.append(("uniq", m, encode_ops(h, m.f_codes)))
    for i in range(40):  # low overlap: the quiescence-cut path
        rng = random.Random(2000 + i)
        m = cas_register()
        h = register_history(rng, n_ops=40, n_procs=3, overlap=1,
                             crash_p=0.02, max_crashes=2, n_values=4)
        if i % 2 == 0:
            h = flip_read(rng, h)
        cases.append(("quiesce", m, encode_ops(h, m.f_codes)))
    for i in range(50):  # mutex with crashed acquires/releases
        rng = random.Random(3000 + i)
        m = mutex()
        h = sim_mutex_history(rng, n_ops=26, n_procs=4, crash_p=0.06)
        cases.append(("mutex", m, encode_ops(h, m.f_codes)))
    for i in range(50):  # multi-register: the locality path
        rng = random.Random(4000 + i)
        m = multi_register(3)
        h = sim_multireg_history(rng)
        if i % 3 == 0:
            h = _flip_mr_read(rng, h)
        cases.append(("multireg", m, encode_ops(h, m.f_codes)))
    assert len(cases) >= 300
    return cases


def test_differential_fuzz_decomposed_vs_direct():
    divergences = []
    used_methods = set()
    for label, m, seq in _fuzz_cases():
        d = _direct(seq, m)["valid"]
        r = _decomposed(seq, m)
        used_methods.update(r["decompose"]["methods"])
        if r["valid"] != d:
            divergences.append((label, d, r["valid"], r["decompose"]))
    assert not divergences, divergences[:5]
    # the fuzz must actually exercise every decomposition, or the
    # parity claim is vacuous
    assert {"value-blocks", "quiescence",
            "key-partition"} <= used_methods, used_methods


def test_differential_fuzz_witnessed_verdicts_pass_audit():
    """ISSUE 4: the decomposed funnel with witness=True emits
    proof-carrying verdicts on every path — stitched cell witnesses,
    value-block constructions, quiescence chains — and the independent
    audit pass replays each with zero W-codes.  Invalid verdicts carry
    a parent-row frontier (or say why not)."""
    from jepsen_tpu.analyze.audit import audit

    # every 5th case: all labels, less wall — and a stride coprime to
    # the generators' i%2 / i%3 corruption cadence, so valid AND
    # invalid cases of every label survive the sampling
    cases = _fuzz_cases()[::5]
    witnessed = 0
    stitched = 0
    for label, m, seq in cases:
        r = _decomposed(seq, m, witness=True, audit=True)
        assert r["valid"] == _direct(seq, m)["valid"], (label, r)
        if r["valid"] is True:
            assert "linearization" in r or "witness_dropped" in r, r
            if "linearization" in r:
                witnessed += 1
        elif r["valid"] is False:
            assert "final_ops" in r or "frontier_dropped" in r, r
        a = audit(seq, m, r)
        assert a["ok"], (label, a["diagnostics"])
        if r["decompose"].get("stitched"):
            stitched += 1
    assert witnessed > len(cases) // 4, witnessed
    assert stitched > 0


def test_wired_entry_points_are_verdict_identical():
    from jepsen_tpu.checker.linear import check_opseq_linear
    from jepsen_tpu.checker.seq import check_opseq

    m = cas_register()
    for i in range(25):
        rng = random.Random(50 + i)
        h = sim_register_history(rng, n_procs=4, n_ops=24, crash_p=0.08)
        if i % 3 == 0:
            h = flip_read(rng, h)
        seq = encode_ops(h, m.f_codes)
        a = check_opseq(seq, m)["valid"]
        assert check_opseq(seq, m, decompose=True)["valid"] == a
        assert check_opseq_linear(seq, m, decompose=True)["valid"] == a


def test_linearizable_checker_decompose_option():
    from jepsen_tpu.checker.linearizable import Linearizable

    m = cas_register()
    rng = random.Random(9)
    h = sim_register_history(rng, n_procs=4, n_ops=60, crash_p=0.05)
    plain = Linearizable(m, algorithm="linear").check({"name": ""}, h)
    dec = Linearizable(m, algorithm="linear",
                       decompose=True).check({"name": ""}, h)
    assert dec["valid"] == plain["valid"]
    assert dec["engine"].startswith("decompose(")
    assert dec["decompose"]["cells"] >= 1


# ---------------------------------------------------------------------------
# value blocks: exactness and the naive-projection counterexample
# ---------------------------------------------------------------------------


def test_value_blocks_reject_naive_projection_counterexample():
    """w(1)[0,10] w(2)[0,10] r->1[1,2] r->2[3,4] r->1[5,6]: each
    per-value projection is linearizable on its own, but the value
    sequence 1,2,1 needs two writes of 1 — the cross-block cycle test
    is what makes the decomposition exact."""
    from jepsen_tpu.decompose.partition import value_block_verdict

    h = [invoke_op(0, "write", 1), invoke_op(1, "write", 2),
         invoke_op(2, "read", None), ok_op(2, "read", 1),
         invoke_op(3, "read", None), ok_op(3, "read", 2),
         invoke_op(4, "read", None), ok_op(4, "read", 1),
         ok_op(0, "write", 1), ok_op(1, "write", 2)]
    m = register(0)
    seq = encode_ops(h, m.f_codes)
    assert _direct(seq, m)["valid"] is False
    assert value_block_verdict(seq, m) is False
    assert _decomposed(seq, m)["valid"] is False


def test_value_blocks_gate_ineligible_histories():
    from jepsen_tpu.decompose.partition import value_block_verdict

    m = cas_register(0)
    # CAS ops: not this decomposition
    h = [invoke_op(0, "cas", (0, 1)), ok_op(0, "cas", (0, 1))]
    assert value_block_verdict(encode_ops(h, m.f_codes), m) is None
    # duplicate writes of one value: ineligible
    h = [invoke_op(0, "write", 3), ok_op(0, "write", 3),
         invoke_op(0, "write", 3), ok_op(0, "write", 3)]
    assert value_block_verdict(encode_ops(h, m.f_codes), m) is None
    # crashed ops: ineligible
    h = [invoke_op(0, "write", 3), info_op(0, "write", 3)]
    assert value_block_verdict(encode_ops(h, m.f_codes), m) is None
    # read of a value nothing wrote: immediately invalid
    h = [invoke_op(0, "read", None), ok_op(0, "read", 42)]
    assert value_block_verdict(encode_ops(h, m.f_codes), m) is False
    # reads of the initial value are fine (pinned-first pseudo-block)
    h = [invoke_op(0, "read", None), ok_op(0, "read", 0),
         invoke_op(0, "write", 5), ok_op(0, "write", 5),
         invoke_op(0, "read", None), ok_op(0, "read", 5)]
    assert value_block_verdict(encode_ops(h, m.f_codes), m) is True


# ---------------------------------------------------------------------------
# quiescence cutting
# ---------------------------------------------------------------------------


def test_quiescence_segments_partition_and_crash_placement():
    from jepsen_tpu.decompose.partition import quiescence_segments

    m = cas_register()
    rng = random.Random(11)
    h = register_history(rng, n_ops=50, n_procs=3, overlap=1,
                         crash_p=0.05, max_crashes=3, n_values=4)
    seq = encode_ops(h, m.f_codes)
    segs = quiescence_segments(seq)
    # segments partition the rows in order
    assert np.array_equal(np.concatenate(segs), np.arange(len(seq)))
    # crash rows (ret = +inf) may appear in the FINAL segment only
    ok = np.asarray(seq.ok)
    for s in segs[:-1]:
        assert ok[s].all(), "crash row escaped a non-final segment"
    # an actually-quiescent generator must actually split
    assert len(segs) > 1


def test_quiescence_threading_runs_and_agrees():
    """Histories that split must go through the state-set composition
    path (methods includes 'quiescence') and still agree exactly."""
    m = cas_register()
    hit = 0
    for i in range(30):
        rng = random.Random(600 + i)
        h = register_history(rng, n_ops=44, n_procs=3, overlap=1,
                             crash_p=0.03, max_crashes=2, n_values=3)
        if i % 2 == 0:
            h = flip_read(rng, h)
        seq = encode_ops(h, m.f_codes)
        r = _decomposed(seq, m)
        if "quiescence" in r["decompose"]["methods"]:
            hit += 1
        assert r["valid"] == _direct(seq, m)["valid"]
    assert hit > 0


# ---------------------------------------------------------------------------
# canonicalization + verdict cache
# ---------------------------------------------------------------------------


def test_canonical_key_invariances():
    from jepsen_tpu.decompose.canonical import canonical_key

    m = cas_register()
    rng = random.Random(21)
    h = sim_register_history(rng, n_procs=4, n_ops=24, crash_p=0.1)
    seq = encode_ops(h, m.f_codes)
    k0 = canonical_key(seq, m)
    # process renaming: invisible
    h2 = [replace(op, process=op.process + 100) for op in h]
    assert canonical_key(encode_ops(h2, m.f_codes), m) == k0
    # event-index erasure: a dropped :fail op at the front shifts
    # every raw event index but not the ranks
    h3 = [invoke_op(99, "write", 7),
          replace(ok_op(99, "write", 7), type="fail"), *h]
    assert canonical_key(encode_ops(h3, m.f_codes), m) == k0

    # value renaming (register family): a value bijection is invisible
    def shift(v):
        if isinstance(v, int):
            return v + 50
        if isinstance(v, (tuple, list)):  # cas (expected, new)
            return tuple(shift(x) for x in v)
        return v

    h4 = [replace(op, value=shift(op.value)) for op in h]
    assert canonical_key(encode_ops(h4, m.f_codes), m) == k0
    # ...but the model's identity is not
    assert canonical_key(seq, cas_register(7)) != k0
    assert canonical_key(seq, register(0)) != k0


def test_cache_hit_does_zero_search_work(tmp_path):
    from jepsen_tpu.decompose.cache import VerdictCache

    m = cas_register()
    rng = random.Random(42)
    h = sim_register_history(rng, n_procs=4, n_ops=30, crash_p=0.1)
    seq = encode_ops(h, m.f_codes)
    path = str(tmp_path / "verdicts.jsonl")
    cache = VerdictCache(path)
    r1 = _decomposed(seq, m, cache=cache)
    assert r1["configs"] > 0
    # the same canonical shape — processes renamed — from a COLD cache
    # object (disk round-trip): zero search work
    h2 = [replace(op, process=op.process + 10) for op in h]
    seq2 = encode_ops(h2, m.f_codes)
    r2 = _decomposed(seq2, m, cache=VerdictCache(path))
    assert r2["valid"] == r1["valid"]
    assert r2["configs"] == 0
    assert r2["decompose"]["cache_hits"] >= 1
    assert r2["decompose"]["methods"] == ["cache"]


def test_cache_never_stores_unknown(tmp_path):
    from jepsen_tpu.decompose.cache import VerdictCache

    c = VerdictCache(str(tmp_path / "v.jsonl"))
    c.put_verdict("k1", "unknown")
    c.put_verdict("k2", True)
    assert len(VerdictCache(str(tmp_path / "v.jsonl"))) == 1


def test_segment_cache_reuses_state_sets(tmp_path):
    """A multi-segment cell checked twice: the second pass must hit the
    per-segment entries (input-state set in the key, reachable states
    as the value) and do no sweep work."""
    from jepsen_tpu.decompose.cache import VerdictCache

    m = cas_register()
    rng = random.Random(77)
    h = register_history(rng, n_ops=44, n_procs=3, overlap=1,
                         crash_p=0.0, n_values=3)
    seq = encode_ops(h, m.f_codes)
    path = str(tmp_path / "v.jsonl")
    r1 = _decomposed(seq, m, cache=VerdictCache(path))
    assert "quiescence" in r1["decompose"]["methods"]
    r2 = _decomposed(seq, m, cache=VerdictCache(path))
    assert r2["configs"] == 0 and r2["valid"] == r1["valid"]


# ---------------------------------------------------------------------------
# batch + scheduler integration
# ---------------------------------------------------------------------------


def test_search_batch_decompose_dedup_and_parity():
    from jepsen_tpu.checker.linearizable import search_batch

    m = cas_register()
    seqs = []
    for k in range(12):  # 4 distinct shapes, 3 copies each
        rng = random.Random(k % 4)
        h = sim_register_history(rng, n_procs=4, n_ops=24, crash_p=0.0)
        seqs.append(encode_ops(h, m.f_codes))
    direct = search_batch(seqs, m, budget=200_000)
    dec = search_batch(seqs, m, budget=200_000, decompose=True)
    assert [r["valid"] for r in dec] == [r["valid"] for r in direct]
    stats = dec[0]["decompose_batch"]
    assert stats["searched"] == 4 and stats["deduped"] == 8
    # dedup'd keys report zero configs — no search happened for them
    assert sum(1 for r in dec if r["configs"] == 0) == 8


def test_pool_scheduler_parity():
    from jepsen_tpu.decompose.engine import check_opseq_decomposed

    rng = random.Random(5)
    m = multi_register(4)
    h = sim_multireg_history(rng, width=4, n_ops=50, n_procs=6)
    seq = encode_ops(h, m.f_codes)
    r = check_opseq_decomposed(seq, m, scheduler="pool", n_procs=2)
    assert r["valid"] == _direct(seq, m)["valid"]
    assert r["decompose"]["cells"] > 1
    assert "pool" in r["decompose"]["methods"]


def test_model_descriptor_roundtrip():
    from jepsen_tpu.decompose.schedule import (model_descriptor,
                                               model_from_descriptor)
    from jepsen_tpu.models import fifo_queue, noop, unordered_queue

    for m in (register(3), cas_register(), mutex(), noop(),
              multi_register(5, 2), unordered_queue(8), fifo_queue(4)):
        m2 = model_from_descriptor(model_descriptor(m))
        assert m2.name == m.name
        assert m2.init == m.init
        assert m2.state_width == m.state_width


def test_env_knob_reaches_suite_constructed_checkers(monkeypatch):
    """--lin-decompose travels via JEPSEN_TPU_LIN_DECOMPOSE, the same
    fleet-wide channel as the algorithm selector, because suites build
    their own Linearizable checkers."""
    from jepsen_tpu.checker.linearizable import Linearizable

    monkeypatch.delenv("JEPSEN_TPU_LIN_DECOMPOSE", raising=False)
    assert Linearizable(cas_register()).decompose is False
    monkeypatch.setenv("JEPSEN_TPU_LIN_DECOMPOSE", "1")
    assert Linearizable(cas_register()).decompose is True
    m = cas_register()
    rng = random.Random(4)
    h = sim_register_history(rng, n_procs=3, n_ops=20)
    r = Linearizable(m, algorithm="linear").check({"name": ""}, h)
    assert r["engine"].startswith("decompose")


def test_cli_flag_sets_env_knob(monkeypatch):
    import argparse

    from jepsen_tpu import cli

    # setenv-then-delenv (not bare delenv of an absent var, which
    # records nothing): the cli sets the var OUTSIDE monkeypatch, so
    # teardown must know to remove it or it leaks into later tests
    monkeypatch.setenv("JEPSEN_TPU_LIN_DECOMPOSE", "placeholder")
    monkeypatch.delenv("JEPSEN_TPU_LIN_DECOMPOSE")
    p = argparse.ArgumentParser()
    cli.add_test_opts(p)
    opts = cli.test_opt_fn(p.parse_args(["--lin-decompose", "--dummy"]))
    assert opts["lin_decompose"] is True
    assert os.environ.get("JEPSEN_TPU_LIN_DECOMPOSE") == "1"
