"""Shape-bucketed device batching (checker/bucket.py, ISSUE 2).

The scheduler's contract: bucketed `search_batch` is VERDICT-IDENTICAL
to the single fused batch on any mix of key shapes (sizes, :info crash
ops, duplicates, corruptions), while reporting strictly less padded
work on heterogeneous batches.  The satellites ride along: the full
per-cell result dicts from `device_batch_cells`, the pool's final
queue drain, the portfolio's decomposed leg, and the persistent
compilation-cache wiring (env knob + CLI flag).
"""

import os
import queue
import random
import threading

from jepsen_tpu.checker import linearizable as lin
from jepsen_tpu.checker.bucket import (bucket_key, bucketing_enabled,
                                       plan_buckets,
                                       search_batch_bucketed)
from jepsen_tpu.history import encode_ops
from jepsen_tpu.models import cas_register
from jepsen_tpu.synth import (flip_read, register_history,
                              sim_register_history)


def _mixed_batch():
    """Mixed-size batch: narrow keys with :info crash ops, DUPLICATE
    keys (two copies per shape), medium keys, and one corrupted WIDE
    key that must ride the device (a valid wide key would be disposed
    of host-side by the greedy witness and never pad anything)."""
    m = cas_register()
    seqs = []
    for k in range(6):
        rng = random.Random(k % 3)
        h = sim_register_history(rng, n_procs=3, n_ops=18, crash_p=0.1)
        if k % 3 == 0:
            h = flip_read(random.Random(k), h)
        seqs.append(encode_ops(h, m.f_codes))
    for k in range(3):
        rng = random.Random(100 + k)
        h = register_history(rng, n_ops=64, n_procs=6, overlap=4,
                             crash_p=0.02, max_crashes=2, n_values=4)
        if k == 1:
            h = flip_read(rng, h)
        seqs.append(encode_ops(h, m.f_codes))
    rng = random.Random(999)
    h = register_history(rng, n_ops=200, n_procs=8, overlap=12,
                         crash_p=0.02, max_crashes=2, n_values=5)
    seqs.append(encode_ops(flip_read(rng, h), m.f_codes))
    return seqs, m


# ---------------------------------------------------------------------------
# differential parity: bucketed vs unbucketed
# ---------------------------------------------------------------------------


def test_differential_bucketed_vs_unbucketed_mixed_sizes():
    seqs, m = _mixed_batch()
    fused = lin.search_batch(seqs, m, budget=300_000, bucket=False)
    buck = lin.search_batch(seqs, m, budget=300_000, bucket=True,
                            audit=True)
    assert [r["valid"] for r in buck] == [r["valid"] for r in fused]
    # per-key accounting stays honest: every result names a real
    # engine, and device-ridden keys bill configs
    for r in buck:
        assert r.get("engine")
    # invalid keys exist in this batch (corruptions) and agree
    assert False in [r["valid"] for r in buck]
    # ISSUE 4: every per-key verdict is a certified one — greedy keys
    # carry real witnesses (surviving bucket padding/reordering: the
    # rows index each key's OWN OpSeq), device keys explicit drop
    # reasons — and the independent audit replays all of them clean
    from jepsen_tpu.analyze.audit import audit

    greedy_wit = 0
    for s, r in zip(seqs, buck):
        if r["valid"] is True:
            assert "linearization" in r or "witness_dropped" in r, r
        elif r["valid"] is False:
            assert "final_ops" in r or "frontier_dropped" in r, r
        assert audit(s, m, r)["ok"], r
        if r.get("engine") == "greedy-witness":
            assert r.get("linearization"), r
            greedy_wit += 1
    assert greedy_wit > 0


def test_differential_bucketed_vs_unbucketed_reordered():
    """Same keys, shuffled: verdicts follow the keys, not the order
    (the bucketed path scatters/gathers through bucket plans)."""
    seqs, m = _mixed_batch()
    rng = random.Random(7)
    perm = list(range(len(seqs)))
    rng.shuffle(perm)
    shuffled = [seqs[i] for i in perm]
    base = lin.search_batch(seqs, m, budget=300_000, bucket=False)
    buck = lin.search_batch(shuffled, m, budget=300_000, bucket=True)
    assert [buck[perm.index(i)]["valid"] for i in range(len(seqs))] == \
        [r["valid"] for r in base]


def test_differential_fuzz_random_batches():
    """Randomized rounds: batch composition (sizes, corruption, crash
    ops, duplicate keys) varies per round; verdicts must match the
    fused path exactly every time.  Shapes draw from a small dims pool
    so compiled kernels cache across rounds."""
    m = cas_register()
    for round_ in range(3):
        rng = random.Random(7000 + round_)
        seqs = []
        for _ in range(rng.randrange(4, 9)):
            size = rng.choice([14, 18, 40, 64])
            seed = rng.randrange(4)
            h = sim_register_history(random.Random(seed), n_procs=3,
                                     n_ops=size, crash_p=0.08)
            if rng.random() < 0.4:
                h = flip_read(random.Random(seed + 50), h)
            seqs.append(encode_ops(h, m.f_codes))
        seqs += seqs[:2]  # duplicate keys
        fused = lin.search_batch(seqs, m, budget=200_000, bucket=False)
        buck = lin.search_batch(seqs, m, budget=200_000, bucket=True)
        assert [r["valid"] for r in buck] == \
            [r["valid"] for r in fused], f"round {round_}"


def test_bucketed_handles_all_greedy_and_empty():
    m = cas_register()
    rng = random.Random(3)
    h = register_history(rng, n_ops=24, n_procs=3, overlap=2,
                         n_values=3)
    seqs = [encode_ops(h, m.f_codes)] * 3  # valid: greedy disposes all
    out = search_batch_bucketed(seqs, m, budget=100_000)
    assert [r["valid"] for r in out] == [True] * 3
    assert all(r["engine"] == "greedy-witness" for r in out)
    assert search_batch_bucketed([], m) == []


# ---------------------------------------------------------------------------
# bucket planning
# ---------------------------------------------------------------------------


def test_wide_plus_narrow_lands_in_two_buckets():
    """ISSUE 2 satellite: a 1-wide-key + N-narrow-key batch must land
    in >= 2 buckets."""
    seqs, m = _mixed_batch()
    keys = [bucket_key(lin.encode_search(s)) for s in seqs]
    plans = plan_buckets(keys, 8)
    assert len(plans) >= 2
    out = search_batch_bucketed(seqs, m, budget=300_000)
    st = out[0]["bucket_batch"]
    assert st["n_buckets"] >= 2
    # the wide key's bucket pads to ITS dims, not the narrow keys'
    dims = [b["dims"] for b in st["buckets"] if b["dims"]]
    assert len({tuple(d) for d in dims}) >= 2


def test_plan_buckets_cap_merges_and_covers():
    keys = [(64, 32, 32), (128, 32, 32), (256, 64, 32), (512, 96, 64),
            (64, 64, 32), (1024, 32, 32), (64, 32, 32)]
    plans = plan_buckets(keys, 2)
    assert len(plans) == 2
    covered = sorted(i for grp in plans for i in grp)
    assert covered == list(range(len(keys)))
    # no cap: one bucket per distinct dims tuple
    assert len(plan_buckets(keys, 99)) == len(set(keys))


def test_bucket_key_matches_single_key_dims():
    seqs, m = _mixed_batch()
    for s in seqs:
        es = lin.encode_search(s)
        d = lin.choose_dims(es, m)
        assert bucket_key(es) == (d.n_det_pad, d.window, d.n_crash_pad)


def test_mixed_batch_padding_efficiency_beats_fused():
    """The acceptance criterion's shape: on a mixed-size batch the
    bucketed path reports strictly higher useful/padded than the
    single-fused-batch counterfactual."""
    seqs, m = _mixed_batch()
    out = search_batch_bucketed(seqs, m, budget=300_000)
    st = out[0]["bucket_batch"]
    assert st["padded_ops"] < st["fused_padded_ops"]
    assert st["padding_efficiency"] > st["fused_padding_efficiency"]
    assert "kernel_cache" in st and st["kernel_cache"]["misses"] >= 0


def test_env_knob_disables_bucketing(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_BATCH_BUCKETS", "0")
    assert bucketing_enabled() is False
    seqs, m = _mixed_batch()
    out = lin.search_batch(seqs[:4], m, budget=100_000)
    assert all("bucket_batch" not in r for r in out)
    monkeypatch.setenv("JEPSEN_TPU_BATCH_BUCKETS", "4")
    assert bucketing_enabled() is True
    # "1" is a single fused bucket — counts as disabled
    monkeypatch.setenv("JEPSEN_TPU_BATCH_BUCKETS", "1")
    assert bucketing_enabled() is False


# ---------------------------------------------------------------------------
# scheduler satellites
# ---------------------------------------------------------------------------


def test_device_batch_cells_returns_full_dicts():
    from jepsen_tpu.decompose.schedule import device_batch_cells

    m = cas_register()
    cells = []
    for k in range(4):
        rng = random.Random(40 + k)
        h = sim_register_history(rng, n_procs=3, n_ops=16, crash_p=0.0)
        if k % 2 == 0:
            h = flip_read(rng, h)
        cells.append(encode_ops(h, m.f_codes))
    out = device_batch_cells(cells, m, budget=100_000)
    assert len(out) == 4
    for r in out:
        assert isinstance(r, dict)
        assert r["valid"] in (True, False, "unknown")
        assert "configs" in r and "engine" in r
    # verdicts agree with the direct oracle per cell
    from jepsen_tpu.checker.seq import check_opseq

    for cell, r in zip(cells, out):
        assert r["valid"] == check_opseq(cell, m)["valid"]


def test_pool_drain_collects_raced_verdicts():
    from jepsen_tpu.decompose.schedule import _drain_queue

    q: "queue.Queue" = queue.Queue()
    q.put((0, True, 10))
    q.put((2, False, 5))
    out: dict = {1: (True, 3)}
    _drain_queue(q, out)
    assert out == {0: (True, 10), 1: (True, 3), 2: (False, 5)}
    _drain_queue(q, out)  # empty queue: no-op
    assert out == {0: (True, 10), 1: (True, 3), 2: (False, 5)}


def _invalid_builder():
    # overlap=1: quiescence-rich, so the decomposed leg has a real cut
    # to work with (on an undecomposable history it now concedes
    # "unknown" instead of duplicating the linear leg)
    m = cas_register()
    rng = random.Random(5)
    h = register_history(rng, n_ops=60, n_procs=4, overlap=1, n_values=3)
    from jepsen_tpu.synth import corrupt_read

    h = corrupt_read(rng, h, at=0.7)
    return encode_ops(h, m.f_codes), m


def test_portfolio_worker_decompose_leg_runs_inprocess():
    """The new leg's worker path, driven directly (no spawn): the
    decomposed engine decides and labels the leg 'decompose'."""
    from jepsen_tpu.checker.parallel import _portfolio_worker

    ready, go = threading.Event(), threading.Event()
    go.set()
    q: "queue.Queue" = queue.Queue()
    _portfolio_worker(_invalid_builder, (), "decompose", 0, 1_000_000,
                      False, ready, go, q)
    algo, seed, r = q.get_nowait()
    assert algo == "decompose"
    assert r["valid"] is False
    assert r["engine"].startswith("decompose")


def test_portfolio_worker_decompose_leg_concedes_undecomposable():
    """No cutter applies (duplicate writes, no quiescent point, single
    register): the leg must concede "unknown" instead of duplicating
    the sibling linear leg's whole-history sweep."""
    from jepsen_tpu.checker.parallel import _portfolio_worker
    from jepsen_tpu.history import invoke_op, ok_op

    m = cas_register()
    h = [invoke_op(0, "write", 1), invoke_op(1, "write", 1),
         ok_op(0, "write", 1), invoke_op(2, "read", None),
         ok_op(1, "write", 1), invoke_op(0, "read", None),
         ok_op(2, "read", 1), ok_op(0, "read", 1)]
    ready, go = threading.Event(), threading.Event()
    go.set()
    q: "queue.Queue" = queue.Queue()
    _portfolio_worker(lambda: (encode_ops(h, m.f_codes), m), (),
                      "decompose", 0, 1_000_000, False, ready, go, q)
    _algo, _seed, r = q.get_nowait()
    assert r["valid"] == "unknown"
    assert r.get("info") == "nothing decomposes"


def test_linearizable_decompose_cache_object_memoized(tmp_path):
    """A path/True verdict_cache is constructed ONCE per checker —
    re-parsing the whole jsonl on every check() was O(n^2) across a
    suite run."""
    from jepsen_tpu.checker.linearizable import Linearizable

    m = cas_register()
    rng = random.Random(9)
    h = sim_register_history(rng, n_procs=3, n_ops=20)
    chk = Linearizable(m, algorithm="linear", decompose=True,
                       verdict_cache=str(tmp_path / "v.jsonl"))
    r1 = chk.check({"name": ""}, h)
    c1 = chk._cache_obj
    r2 = chk.check({"name": ""}, h)
    assert chk._cache_obj is c1
    assert r2["valid"] == r1["valid"]
    assert r2["decompose"]["cache_hits"] >= 1


def test_portfolio_races_decomposed_leg():
    """n_procs >= 3 adds the dedicated decomposed leg; the race still
    returns the right verdict whichever leg wins."""
    from jepsen_tpu.checker.parallel import portfolio_check

    out = portfolio_check(_invalid_builder, n_procs=3, deadline_s=120)
    assert out["valid"] is False
    assert out["engine"].startswith("host3(")


# ---------------------------------------------------------------------------
# compilation-cache wiring
# ---------------------------------------------------------------------------


def test_enable_compilation_cache(tmp_path, monkeypatch):
    import jax

    from jepsen_tpu.util import enable_compilation_cache

    old = jax.config.jax_compilation_cache_dir
    try:
        assert enable_compilation_cache(str(tmp_path)) == str(tmp_path)
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
        # env fallback
        monkeypatch.setenv("JEPSEN_TPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "env"))
        assert enable_compilation_cache() == str(tmp_path / "env")
        monkeypatch.delenv("JEPSEN_TPU_COMPILE_CACHE_DIR")
        assert enable_compilation_cache() is None
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_cli_compile_cache_flag(tmp_path, monkeypatch):
    import argparse

    import jax

    from jepsen_tpu import cli

    # the cli sets the env var OUTSIDE monkeypatch; register it so
    # teardown removes it (same trick as test_cli_flag_sets_env_knob)
    monkeypatch.setenv("JEPSEN_TPU_COMPILE_CACHE_DIR", "placeholder")
    monkeypatch.delenv("JEPSEN_TPU_COMPILE_CACHE_DIR")
    old = jax.config.jax_compilation_cache_dir
    try:
        p = argparse.ArgumentParser()
        cli.add_test_opts(p)
        opts = cli.test_opt_fn(p.parse_args(
            ["--compile-cache-dir", str(tmp_path), "--dummy"]))
        assert opts["compile_cache_dir"] == str(tmp_path)
        assert os.environ["JEPSEN_TPU_COMPILE_CACHE_DIR"] == \
            str(tmp_path)
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
