"""LIVE wire-protocol client tests — real sockets, real encodings.

VERDICT r3 missing #4: several suite clients had only ever run against
DummyRemote command fixtures.  No database binaries or driver wheels
exist in this image, but these clients speak hand-rolled stdlib
protocols — so each test here stands up an in-process server speaking
the REAL protocol (memcache text, RESP, hazelcast REST, etcd v3 JSON
gateway) on a loopback socket and drives the actual client.invoke()
through it: the full encode -> TCP -> parse -> op-type mapping path,
both happy and error cases.
"""

from __future__ import annotations

import base64
import json
import socket
import socketserver
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from jepsen_tpu.history import invoke_op
from jepsen_tpu.suites import etcdemo, hazelcast, raftis


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# memcache text protocol (hazelcast MemcacheIdClient)
# ---------------------------------------------------------------------------


class _MemcacheHandler(socketserver.StreamRequestHandler):
    def handle(self):
        while True:
            line = self.rfile.readline()
            if not line:
                return
            parts = line.decode().split()
            store = self.server.store
            with self.server.lock:
                if parts and parts[0] == "add":
                    data = self.rfile.readline().strip().decode()
                    if parts[1] in store:
                        self.wfile.write(b"NOT_STORED\r\n")
                    else:
                        store[parts[1]] = int(data)
                        self.wfile.write(b"STORED\r\n")
                elif parts and parts[0] == "incr":
                    k, by = parts[1], int(parts[2])
                    if k not in store:
                        self.wfile.write(b"NOT_FOUND\r\n")
                    else:
                        store[k] += by
                        self.wfile.write(f"{store[k]}\r\n".encode())
                else:
                    self.wfile.write(b"ERROR\r\n")
            self.wfile.flush()


def test_hazelcast_memcache_ids_live(monkeypatch):
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0),
                                          _MemcacheHandler)
    srv.store, srv.lock = {}, threading.Lock()
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    monkeypatch.setattr(hazelcast, "PORT", srv.server_address[1])
    try:
        c = hazelcast.MemcacheIdClient().open({}, "127.0.0.1")
        got = [c.invoke({}, invoke_op(0, "generate", None))
               for _ in range(5)]
        assert all(op.type == "ok" for op in got)
        vals = [op.value for op in got]
        assert vals == sorted(vals) and len(set(vals)) == 5  # unique ids
        c.close({})
        # error mapping: dead server -> :info (id may have been claimed)
        srv.shutdown()
        srv.server_close()
        c2 = hazelcast.MemcacheIdClient().open({}, "127.0.0.1")
        op = c2.invoke({}, invoke_op(0, "generate", None))
        assert op.type == "info"
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# hazelcast REST queues (RestQueueClient)
# ---------------------------------------------------------------------------


class _RestQueueHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n).decode()
        with self.server.lock:
            self.server.q.append(int(body))
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):  # noqa: N802
        with self.server.lock:
            v = self.server.q.pop(0) if self.server.q else None
        if v is None:
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()
        else:
            body = str(v).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)


def test_hazelcast_rest_queue_live(monkeypatch):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _RestQueueHandler)
    srv.q, srv.lock = [], threading.Lock()
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    monkeypatch.setattr(hazelcast, "PORT", srv.server_address[1])
    try:
        c = hazelcast.RestQueueClient().open({}, "127.0.0.1")
        assert c.invoke({}, invoke_op(0, "enqueue", 7)).type == "ok"
        assert c.invoke({}, invoke_op(0, "enqueue", 8)).type == "ok"
        op = c.invoke({}, invoke_op(0, "dequeue", None))
        assert (op.type, op.value) == ("ok", 7)  # FIFO through the wire
        # drain pulls the rest then sees two empty polls
        op = c.invoke({}, invoke_op(0, "drain", None))
        assert op.type == "ok" and op.value == [8]
        # empty dequeue is a determinate :fail
        op = c.invoke({}, invoke_op(0, "dequeue", None))
        assert op.type == "fail"
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# RESP (raftis RegisterClient over disque.RespConn)
# ---------------------------------------------------------------------------


class _RespHandler(socketserver.StreamRequestHandler):
    def _read_cmd(self):
        line = self.rfile.readline()
        if not line or not line.startswith(b"*"):
            return None
        n = int(line[1:].strip())
        args = []
        for _ in range(n):
            ln = int(self.rfile.readline()[1:].strip())
            args.append(self.rfile.read(ln + 2)[:-2].decode())
        return args

    def handle(self):
        while True:
            cmd = self._read_cmd()
            if cmd is None:
                return
            store, lock = self.server.store, self.server.lock
            with lock:
                if cmd[0] == "SET":
                    if self.server.leaderless:
                        self.wfile.write(b"-ERR no leader\r\n")
                    else:
                        store[cmd[1]] = cmd[2]
                        self.wfile.write(b"+OK\r\n")
                elif cmd[0] == "GET":
                    v = store.get(cmd[1])
                    if v is None:
                        self.wfile.write(b"$-1\r\n")
                    else:
                        b = v.encode()
                        self.wfile.write(
                            b"$%d\r\n%s\r\n" % (len(b), b))
                else:
                    self.wfile.write(b"-ERR unknown\r\n")
            self.wfile.flush()


def test_raftis_register_live(monkeypatch):
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _RespHandler)
    srv.store, srv.lock, srv.leaderless = {}, threading.Lock(), False
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    monkeypatch.setattr(raftis, "REDIS_PORT", srv.server_address[1])
    try:
        c = raftis.RegisterClient().open({}, "127.0.0.1")
        op = c.invoke({}, invoke_op(0, "read", None))
        assert (op.type, op.value) == ("ok", None)  # unset register
        assert c.invoke({}, invoke_op(0, "write", 42)).type == "ok"
        op = c.invoke({}, invoke_op(0, "read", None))
        assert (op.type, op.value) == ("ok", 42)
        # raftis's "no leader" error is a determinate :fail
        srv.leaderless = True
        op = c.invoke({}, invoke_op(0, "write", 1))
        assert op.type == "fail" and "no leader" in op.error
        c.close({})
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# etcd v3 JSON gateway (etcdemo EtcdClient)
# ---------------------------------------------------------------------------


class _EtcdHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        kv, lock = self.server.kv, self.server.lock

        def b64d(s):
            return base64.b64decode(s).decode()

        def b64e(s):
            return base64.b64encode(s.encode()).decode()

        with lock:
            if self.path.endswith("/kv/put"):
                kv[b64d(body["key"])] = b64d(body["value"])
                out = {}
            elif self.path.endswith("/kv/range"):
                k = b64d(body["key"])
                out = {}
                if k in kv:
                    out["kvs"] = [{"key": body["key"],
                                   "value": b64e(kv[k])}]
            elif self.path.endswith("/kv/txn"):
                cmp_ = body["compare"][0]
                k = b64d(cmp_["key"])
                ok = kv.get(k) == b64d(cmp_["value"])
                if ok:
                    put = body["success"][0]["requestPut"]
                    kv[b64d(put["key"])] = b64d(put["value"])
                out = {"succeeded": ok}
            else:
                self.send_response(404)
                self.end_headers()
                return
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


def test_etcd_v3_gateway_live(monkeypatch):
    from jepsen_tpu import independent

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _EtcdHandler)
    srv.kv, srv.lock = {}, threading.Lock()
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    monkeypatch.setattr(etcdemo, "client_url",
                        lambda node: f"http://{node}:{port}")
    try:
        c = etcdemo.EtcdClient().open({}, "127.0.0.1")
        kv = independent.tuple_
        op = c.invoke({}, invoke_op(0, "read", kv(5, None)))
        assert op.type == "ok" and op.value.value is None
        assert c.invoke({}, invoke_op(0, "write", kv(5, 3))).type == "ok"
        op = c.invoke({}, invoke_op(0, "read", kv(5, None)))
        assert op.type == "ok" and op.value.value == 3
        # cas hit and miss, through real txn JSON
        assert c.invoke({}, invoke_op(0, "cas", kv(5, (3, 4)))).type \
            == "ok"
        assert c.invoke({}, invoke_op(0, "cas", kv(5, (9, 1)))).type \
            == "fail"
        op = c.invoke({}, invoke_op(0, "read", kv(5, None)))
        assert op.value.value == 4
    finally:
        srv.shutdown()
        srv.server_close()


def test_etcd_client_down_maps_to_info_or_fail(monkeypatch):
    """Connection refused: reads :fail, writes :info (etcdemo.clj
    error mapping)."""
    from jepsen_tpu import independent

    port = _free_port()  # nothing listens here
    monkeypatch.setattr(etcdemo, "client_url",
                        lambda node: f"http://{node}:{port}")
    c = etcdemo.EtcdClient().open({}, "127.0.0.1")
    kv = independent.tuple_
    assert c.invoke({}, invoke_op(0, "read", kv(1, None))).type == "fail"
    assert c.invoke({}, invoke_op(0, "write", kv(1, 2))).type == "info"


# ---------------------------------------------------------------------------
# postgres wire protocol (cockroach SQLClient family)
# ---------------------------------------------------------------------------


def test_cockroach_sql_register_live():
    """The SQL txn machinery (suites/cockroach.py:101-162) executed
    LIVE over real pg-wire v3 frames: happy paths, cas hit/miss, a
    server-reported txn conflict (read -> :fail, write -> :info), and
    loss of the server mid-session (indeterminate)."""
    from jepsen_tpu import independent
    from jepsen_tpu.suites import cockroach, pgwire

    srv, port = pgwire.MiniPGServer.start()
    t = {"sql_port": port}
    kv = independent.tuple_
    try:
        c = cockroach.RegisterClient().open(t, "127.0.0.1")
        c.setup(t)  # CREATE TABLE over the wire
        assert c.invoke(t, invoke_op(0, "write", kv(1, 5))).type == "ok"
        op = c.invoke(t, invoke_op(0, "read", kv(1, None)))
        assert op.type == "ok" and op.value.value == 5
        op = c.invoke(t, invoke_op(0, "read", kv(2, None)))
        assert op.type == "ok" and op.value.value is None
        assert c.invoke(t, invoke_op(0, "cas", kv(1, (5, 7)))).type \
            == "ok"
        assert c.invoke(t, invoke_op(0, "cas", kv(1, (5, 9)))).type \
            == "fail"
        op = c.invoke(t, invoke_op(0, "read", kv(1, None)))
        assert op.type == "ok" and op.value.value == 7
        # server-reported conflict: the client's error mapping
        # (client.clj:retryable semantics) runs live
        srv.engine.fail_next(1)
        assert c.invoke(t, invoke_op(0, "read", kv(1, None))).type \
            == "fail"
        srv.engine.fail_next(1)
        assert c.invoke(t, invoke_op(0, "write", kv(1, 8))).type \
            == "info"
        # the rollback path left the connection usable
        op = c.invoke(t, invoke_op(0, "read", kv(1, None)))
        assert op.type == "ok" and op.value.value == 7
        # in-flight loss of the connection (server drops mid-statement):
        # writes indeterminate, reads definite
        srv.engine.die_next(1)
        op = c.invoke(t, invoke_op(0, "write", kv(3, 1)))
        assert op.type == "info"
        op = c.invoke(t, invoke_op(0, "read", kv(3, None)))
        assert op.type == "fail"  # connection is dead now
        c.close(t)
    finally:
        srv.shutdown()
        srv.server_close()


def test_pgwire_shim_is_the_fallback_driver():
    from jepsen_tpu.suites import cockroach, pgwire

    try:
        import psycopg2  # noqa: F401
    except ImportError:
        assert cockroach.pg_driver() is pgwire


def test_cockroach_bank_live_concurrent_transfers():
    """The bank workload (tests/bank.clj shape) LIVE over pg-wire:
    multi-statement transactions (implicit BEGIN -> SELECT + two
    UPDATEs -> COMMIT) from concurrent clients against the serializing
    engine.  Total preservation is the workload's invariant; a dying
    connection mid-transaction must roll back, never leak a
    half-applied transfer."""
    import random as rnd

    from jepsen_tpu.suites import cockroach, pgwire

    srv, port = pgwire.MiniPGServer.start()
    t = {"sql_port": port, "accounts": list(range(4)),
         "total_amount": 100}
    try:
        c0 = cockroach.BankClient().open(t, "127.0.0.1")
        c0.setup(t)

        def worker(seed, n_ops, results):
            c = cockroach.BankClient().open(t, "127.0.0.1")
            r = rnd.Random(seed)
            for _ in range(n_ops):
                a, b = r.sample(t["accounts"], 2)
                op = invoke_op(0, "transfer",
                               {"from": a, "to": b,
                                "amount": 1 + r.randrange(5)})
                results.append(c.invoke(t, op).type)
            c.close(t)

        results: list = []
        ts = [threading.Thread(target=worker, args=(s, 25, results))
              for s in range(4)]
        for th in ts:
            th.start()
        for th in ts:
            th.join(timeout=60)
        assert all(not th.is_alive() for th in ts)
        assert results and set(results) <= {"ok", "fail"}
        # the invariant the bank checker exists for: total preserved
        op = c0.invoke(t, invoke_op(0, "read", None))
        assert op.type == "ok"
        assert sum(op.value.values()) == 100, op.value
        # insufficient funds -> :fail (the SELECT-then-check txn path)
        op = c0.invoke(t, invoke_op(0, "transfer",
                                    {"from": 0, "to": 1,
                                     "amount": 10**6}))
        assert op.type == "fail"
        # a connection dying MID-TRANSACTION with a WRITE ALREADY
        # APPLIED: the transfer runs SELECT (1), the debit UPDATE (2,
        # applied — the undo log now holds the old balance), and dies
        # on the credit UPDATE (3).  The engine's abort hook must
        # replay the undo log — restoring the debited account — and
        # release the txn lock.
        #
        # The concurrent transfers above may have DRAINED account 0;
        # an insufficient-funds transfer bails after the SELECT (one
        # statement, not three), comes back :fail, and leaves the die
        # counter partially consumed — the ~40% flake.  Seed account 0
        # with a known positive balance BEFORE arming the counter.
        balances = c0.invoke(t, invoke_op(0, "read", None)).value
        if balances[0] < 1:
            rich = max(balances, key=balances.get)
            op = c0.invoke(t, invoke_op(0, "transfer",
                                        {"from": rich, "to": 0,
                                         "amount": 1}))
            assert op.type == "ok", op
        before = c0.invoke(t, invoke_op(0, "read", None)).value
        assert before[0] >= 1
        cdie = cockroach.BankClient().open(t, "127.0.0.1")
        srv.engine.die_next(3)
        try:
            op = cdie.invoke(t, invoke_op(0, "transfer",
                                          {"from": 0, "to": 1,
                                           "amount": 1}))
            assert op.type == "info"  # indeterminate to the client...
            after = c0.invoke(t, invoke_op(0, "read", None)).value
            assert after == before  # ...but rolled back on the server
        finally:
            # a partially-consumed counter (e.g. an assertion above
            # fired) must not leak into the teardown's statements
            srv.engine.disarm()
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# pg-wire shim unit behavior (param quoting, injection-counter scoping)
# ---------------------------------------------------------------------------


def test_pgwire_param_interpolation_quotes_and_escapes():
    from decimal import Decimal

    from jepsen_tpu.suites import pgwire

    f = pgwire._interpolate
    assert f("SELECT %s", (7,)) == "SELECT 7"
    assert f("SELECT %s", (None,)) == "SELECT NULL"
    assert f("SELECT %s", ("it's",)) == "SELECT 'it''s'"
    assert f("SELECT %s", (Decimal("1.50"),)) == "SELECT 1.50"
    assert f("SELECT %s", (True,)) == "SELECT TRUE"
    # psycopg2's %% -> literal %
    assert f("LIKE 'a%%' AND x=%s", (1,)) == "LIKE 'a%' AND x=1"
    with pytest.raises(pgwire.Error, match="unsupported format"):
        f("SELECT %d", (1,))
    with pytest.raises(pgwire.Error, match="not enough parameters"):
        f("%s %s", (1,))
    with pytest.raises(pgwire.Error, match="more parameters"):
        f("%s", (1, 2))
    with pytest.raises(pgwire.Error, match="can't adapt"):
        f("%s", (object(),))


def test_pgwire_injection_counters_scope_to_consuming_connection():
    """A die counter partially consumed by one connection's statements
    must neither fire on another connection nor survive the consumer's
    death; fail counters scope the same way."""
    from jepsen_tpu.suites import pgwire

    eng = pgwire.RegisterEngine()
    eng.execute("UPSERT INTO registers (id, value) VALUES (1, 5)")
    eng.die_next(3)
    results: list = []

    def other_conn():
        # a different thread = a different connection in this engine:
        # its statement must pass through the armed counter untouched
        results.append(
            eng.execute("SELECT value FROM registers WHERE id=1"))

    # this thread claims the counter with its first statement
    eng.execute("SELECT value FROM registers WHERE id=1")
    th = threading.Thread(target=other_conn)
    th.start()
    th.join(timeout=10)
    assert results and results[0][0] == [(5,)]
    # the claimant consumed 1 of 3; its death must clear the rest
    eng.abort_connection()
    for _ in range(4):  # would have died on the 3rd statement
        eng.execute("SELECT value FROM registers WHERE id=1")
    # disarm() clears a freshly-armed counter too
    eng.fail_next(2)
    eng.disarm()
    eng.execute("SELECT value FROM registers WHERE id=1")
