"""Streaming service backpressure: op budgets + bounded ingest queues.

ROADMAP streaming phase 2: thousands of concurrent connections must
degrade predictably.  The contract under test — past a per-run op
budget, ops are SHED with an explicit ``overloaded`` reply (and the
run still finalizes on the admitted prefix); a connection whose
checker falls behind its bounded ingest queue sheds lines the same
way instead of stalling the socket or buffering without bound.
"""

import json

from jepsen_tpu.models import register
from jepsen_tpu.stream.service import StreamService, serve_lines


def _header(run="r1"):
    return json.dumps({"run": run, "model": "register", "init": 0})


def _op(run, process, typ, f, value):
    return json.dumps({"run": run,
                       "op": {"process": process, "type": typ,
                              "f": f, "value": value}})


def _ok_pair(run, process, f, value):
    return [_op(run, process, "invoke", f, value),
            _op(run, process, "ok", f, value)]


def test_op_budget_sheds_with_overloaded_reply():
    svc = StreamService(model=register(0), op_budget=6)
    replies = []
    lines = [_header()]
    for i in range(8):  # 16 ops; budget admits 6
        lines += _ok_pair("r1", 0, "write", i % 3)
    for li in lines:
        svc.handle_line(li, replies.append)
    over = [r for r in replies if r.get("overloaded")]
    assert over, "no overloaded reply despite blowing the budget"
    assert over[0]["overloaded"] == "op-budget"
    assert over[0]["budget"] == 6
    # the run still finalizes: verdict of exactly the admitted prefix,
    # with the shed count reported
    svc.end_run("r1", replies.append)
    finals = [r for r in replies if "final" in r]
    assert len(finals) == 1
    assert finals[0]["final"]["valid"] is True
    assert finals[0]["final"]["shed"] == 16 - 6


def test_budget_is_per_run_not_global():
    svc = StreamService(model=register(0), op_budget=4)
    replies = []
    for run in ("a", "b"):
        svc.handle_line(_header(run), replies.append)
    for i in range(4):
        for run in ("a", "b"):
            for li in _ok_pair(run, 0, "write", 1):
                svc.handle_line(li, replies.append)
    # each run admitted exactly its own 4 ops, shed its own overflow
    for run in ("a", "b"):
        svc.end_run(run, replies.append)
    finals = {r["run"]: r["final"] for r in replies if "final" in r}
    assert finals["a"]["shed"] == 4
    assert finals["b"]["shed"] == 4
    assert finals["a"]["valid"] is True


def test_no_budget_admits_everything():
    svc = StreamService(model=register(0))
    replies = []
    svc.handle_line(_header(), replies.append)
    for i in range(50):
        for li in _ok_pair("r1", 0, "write", i % 4):
            svc.handle_line(li, replies.append)
    svc.end_run("r1", replies.append)
    final = [r for r in replies if "final" in r][0]["final"]
    assert "shed" not in final
    assert final["valid"] is True
    assert not any(r.get("overloaded") for r in replies)


def test_serve_lines_inline_mode_processes_all():
    svc = StreamService(model=register(0))
    replies = []
    lines = [_header()] + _ok_pair("r1", 0, "write", 2)
    shed = serve_lines(svc, iter(lines), replies.append, ingest_max=0)
    assert shed == 0
    finals = [r for r in replies if "final" in r]
    assert finals and finals[0]["final"]["valid"] is True


def test_serve_lines_bounded_queue_sheds_when_swamped():
    """A checker that can't keep up (artificially slowed) behind a
    2-line queue: a fast producer's flood is shed with overloaded
    replies, memory stays bounded, and EOF still finalizes whatever
    was admitted."""
    import time

    svc = StreamService(model=register(0))
    real = svc.handle_line

    def slow_handle(line, emit):
        time.sleep(0.01)
        real(line, emit)

    svc.handle_line = slow_handle
    replies = []
    lines = [_header()]
    for i in range(100):
        lines += _ok_pair("r1", 0, "write", i % 3)
    shed = serve_lines(svc, iter(lines), replies.append, ingest_max=2)
    assert shed > 0, "a 10ms/line checker behind a 2-line queue " \
                     "must shed a 201-line burst"
    over = [r for r in replies if r.get("overloaded") == "ingest-queue"]
    assert over and over[0]["queue"] == 2
    finals = [r for r in replies if "final" in r]
    assert len(finals) == 1  # EOF finalized the admitted prefix


# ---------------------------------------------------------------------------
# dropped connections + the idle-run reaper (the self-healing service)
# ---------------------------------------------------------------------------


def test_dropped_connection_persists_prefix_verdict(tmp_path):
    """A TCP connection that dies mid-history (the reader raises)
    must not leak its runs open: every open run is finalized silently
    and — with ``persist_dir`` — its final verdict lands on disk."""
    import pytest

    pdir = str(tmp_path / "runs")
    svc = StreamService(model=register(0), persist_dir=pdir)
    replies = []

    def lines():
        yield _header("r1")
        for li in _ok_pair("r1", 0, "write", 2):
            yield li
        raise ConnectionResetError("client vanished mid-history")

    with pytest.raises(ConnectionResetError):
        serve_lines(svc, lines(), replies.append, ingest_max=0)
    # the run was salvaged, not leaked: nothing open, and no final was
    # EMITTED (the client is gone) — it was persisted instead
    assert not svc._runs
    assert not [r for r in replies if "final" in r]
    with open(f"{pdir}/r1.json") as f:
        snap = json.load(f)
    assert snap["final"]["valid"] is True
    assert snap["rows"] == 1


def test_dropped_emit_in_queued_mode_still_salvages(tmp_path):
    """Same contract on the bounded-queue path: the worker's emit
    blowing up (broken pipe) re-raises after the join, with every
    open run finalized first."""
    import pytest

    pdir = str(tmp_path / "runs")
    svc = StreamService(model=register(0), persist_dir=pdir)

    calls = {"n": 0}

    def dying_emit(d):
        calls["n"] += 1
        raise BrokenPipeError("peer reset")

    lines = [_header("r9")] + _ok_pair("r9", 0, "write", 1)
    # the header line emits nothing; the first status change tries to
    # emit and dies — connection-fatal
    with pytest.raises(BrokenPipeError):
        serve_lines(svc, iter(lines), dying_emit, ingest_max=2)
    assert not svc._runs
    with open(f"{pdir}/r9.json") as f:
        assert json.load(f)["final"]["valid"] is True


def test_idle_run_reaper_finalizes_silent_runs():
    """The idle-timeout knob: a run silent past the timeout is
    finalized (prefix verdict emitted, labelled by the reaper); a
    fresh run is left alone."""
    import time

    svc = StreamService(model=register(0), idle_timeout=10.0)
    replies = []
    svc.handle_line(_header("old"), replies.append)
    for li in _ok_pair("old", 0, "write", 1):
        svc.handle_line(li, replies.append)
    svc.handle_line(_header("fresh"), replies.append)
    # age only the old run
    svc._last["old"] = time.monotonic() - 60.0
    reaped = svc.reap_idle(replies.append)
    assert reaped == ["old"]
    finals = [r for r in replies if "final" in r]
    assert len(finals) == 1 and finals[0]["run"] == "old"
    assert finals[0]["final"]["valid"] is True
    assert finals[0]["final"]["finalized_by"] == "idle-reaper"
    assert "fresh" in svc._runs
    # reaping again finds nothing new
    assert svc.reap_idle(replies.append) == []


def test_reaper_thread_runs_inside_serve_lines():
    """With ``idle_timeout`` set, serve_lines keeps a reaper ticking
    while the connection idles: a run that goes silent mid-connection
    is finalized without the client ever sending `end`."""
    import threading
    import time

    svc = StreamService(model=register(0), idle_timeout=0.15)
    replies = []
    fed = threading.Event()

    def lines():
        yield _header("r1")
        for li in _ok_pair("r1", 0, "write", 1):
            yield li
        fed.set()
        # the connection now idles (reader blocked) long past the
        # idle timeout, then closes cleanly
        time.sleep(0.8)

    serve_lines(svc, lines(), replies.append, ingest_max=0)
    finals = [r for r in replies if "final" in r]
    assert len(finals) == 1
    assert finals[0]["final"].get("finalized_by") == "idle-reaper"
