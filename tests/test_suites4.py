"""Fourth suite tranche: etcd(v2), logcabin (SSH TreeOps client),
raftis, robustirc, percona, mysql-cluster, postgres-rds, dgraph."""

import json
import random

from jepsen_tpu.history import Op

from test_suites import dummy_test


def mkop(**kw):
    base = dict(index=0, type="ok", f="read", value=None, process=0,
                time=0)
    base.update(kw)
    return Op(**base)


# --- etcd (v2) ------------------------------------------------------------


def test_etcd_v2_urls():
    from jepsen_tpu.suites import etcd

    assert etcd.peer_url("n1") == "http://n1:2380"
    assert etcd.initial_cluster({"nodes": ["n1", "n2"]}) == \
        "n1=http://n1:2380,n2=http://n2:2380"


def test_etcd_v2_db_commands():
    from jepsen_tpu.suites import etcd

    test, r = dummy_test(nodes=("n1",))
    r.responses["stat /"] = (1, "", "no")
    r.responses["ls -A"] = (0, "etcd-v2.1.1-linux-amd64\n", "")
    r.responses["dirname"] = (0, "/opt", "")
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        etcd.db("v2.1.1").setup(test, "n1")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("--initial-cluster n1=http://n1:2380" in c for c in cmds)
    assert any("start-stop-daemon" in c for c in cmds)


# --- logcabin -------------------------------------------------------------


def test_logcabin_addrs():
    from jepsen_tpu.suites import logcabin

    assert logcabin.server_id("n3") == "3"
    assert logcabin.server_addr("n1") == "n1:5254"
    assert logcabin.server_addrs({"nodes": ["n1", "n2"]}) == \
        "n1:5254,n2:5254"


def test_logcabin_db_commands():
    from jepsen_tpu.suites import logcabin

    test, r = dummy_test(nodes=("n1",))
    test["barrier"] = "no-barrier"
    r.responses["stat /logcabin"] = (1, "", "no")
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        logcabin.db().setup(test, "n1")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("git clone" in c for c in cmds)
    assert any("scons" in c for c in cmds)
    assert any("--bootstrap" in c for c in cmds), "primary bootstraps"
    assert any("Reconfigure" in c for c in cmds)


def test_logcabin_cas_client_over_ssh():
    from jepsen_tpu.suites import logcabin

    test, r = dummy_test(nodes=("n1",))
    c = logcabin.CASClient().open(test, "n1")
    # reads shell to TreeOps and parse JSON from stdout
    r.responses["read /jepsen"] = (0, json.dumps(4), "")
    out = c.invoke(test, mkop(type="invoke", f="read"))
    assert out.type == "ok" and out.value == 4
    # cas failure pattern -> :fail
    r.responses["write /jepsen"] = (
        1, "", "Exiting due to LogCabin::Client::Exception: Path "
        "'/jepsen' has value '3', not '4' as required")
    out = c.invoke(test, mkop(type="invoke", f="cas", value=(4, 5)))
    assert out.type == "fail"


# --- raftis ---------------------------------------------------------------


def test_raftis_cluster_and_db():
    from jepsen_tpu.suites import raftis

    assert raftis.initial_cluster({"nodes": ["n1", "n2"]}) == \
        "n1:8901,n2:8901"
    test, r = dummy_test(nodes=("n1",))
    r.responses["stat /"] = (1, "", "no")
    r.responses["ls -A"] = (0, "raftis\n", "")
    r.responses["dirname"] = (0, "/opt", "")
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        raftis.db("v2.0.4").setup(test, "n1")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("n1:8901" in c and "start-stop-daemon" in c
               for c in cmds)


def test_raftis_test_constructs():
    from jepsen_tpu.suites import raftis

    t = raftis.raftis_test({"nodes": ["n1"], "time_limit": 1})
    assert t["model"].name == "register"


# --- robustirc ------------------------------------------------------------


def test_robustirc_topic_parsing():
    from jepsen_tpu.suites import robustirc

    assert robustirc.parse_topic(
        {"Data": ":n1!j@x TOPIC #jepsen :42"}) == 42
    assert robustirc.parse_topic({"Data": "PRIVMSG #jepsen :42"}) is None
    assert robustirc.parse_topic({"Data": "PING"}) is None


def test_robustirc_daemon_cmd():
    from jepsen_tpu.suites import robustirc

    cmd = robustirc.daemon_cmd("n1", singlenode=True)
    assert "-singlenode" in cmd and "-listen=n1:13001" in cmd
    cmd2 = robustirc.daemon_cmd("n2", join="n1")
    assert "-join=n1:13001" in cmd2


def test_robustirc_message_id_deterministic_tail():
    from jepsen_tpu.suites import robustirc

    a = robustirc.message_id("TOPIC #jepsen :1")
    b = robustirc.message_id("TOPIC #jepsen :1")
    import hashlib

    tail = int(hashlib.md5(b"TOPIC #jepsen :1").hexdigest()[17:], 16)
    assert a & tail == tail and b & tail == tail


# --- percona --------------------------------------------------------------


def test_percona_cluster_address():
    from jepsen_tpu.suites import percona

    test = {"nodes": ["n1", "n2", "n3"]}
    assert percona.cluster_address(test, "n1") == "gcomm://"
    assert percona.cluster_address(test, "n2") == "gcomm://n1,n2,n3"


def test_percona_db_commands():
    from jepsen_tpu.suites import percona

    test, r = dummy_test(nodes=("n1", "n2"))
    test["barrier"] = "no-barrier"
    r.responses["dpkg-query"] = (1, "", "not installed")
    r.responses["apt-get install"] = (0, "", "")
    percona.db("5.6.25-25.12-1.jessie").setup(test, "n1")
    cmds = [e[2] for e in r.log if e[0] == "n1" and e[1] == "exec"]
    assert any("debconf-set-selections" in c for c in cmds)
    assert any("service mysql start bootstrap-pxc" in c for c in cmds)
    assert any("create database if not exists jepsen" in c
               for c in cmds)
    # joiner does a plain start
    test2, r2 = dummy_test(nodes=("n1", "n2"))
    test2["barrier"] = "no-barrier"
    r2.responses["dpkg-query"] = (1, "", "not installed")
    percona.db("5.6.25-25.12-1.jessie").setup(test2, "n2")
    cmds2 = [e[2] for e in r2.log if e[1] == "exec"]
    assert any("service mysql start" in c and "bootstrap" not in c
               for c in cmds2)


def test_percona_bank_test_lock_types():
    from jepsen_tpu.suites import percona

    t = percona.bank_test({"lock_type": "share", "nodes": ["n1"]})
    assert "share-lock" in t["name"]
    assert t["client"].lock_type == " LOCK IN SHARE MODE"
    assert t["total_amount"] == 50


# --- mysql-cluster --------------------------------------------------------


def test_mysql_cluster_node_ids_and_conf():
    from jepsen_tpu.suites import mysql_cluster as mc

    test = {"nodes": ["n1", "n2", "n3", "n4", "n5"]}
    assert mc.mgmd_node_id(test, "n1") == 1
    assert mc.ndbd_node_id(test, "n2") == 12
    assert mc.mysqld_node_id(test, "n5") == 25
    assert mc.ndbd_nodes(test) == ["n1", "n2", "n3", "n4"]
    conf = mc.nodes_conf(test)
    assert conf.count("[ndb_mgmd]") == 5
    assert conf.count("[ndbd]") == 4  # storage on first four only
    assert conf.count("[mysqld]") == 5
    cnf = mc.my_cnf(test, "n2")
    assert "ndb-nodeid=22" in cnf
    assert "ndb-connectstring=n1,n2,n3,n4,n5" in cnf


def test_mysql_cluster_start_order():
    from jepsen_tpu.suites import mysql_cluster as mc

    test, r = dummy_test(nodes=("n1",))
    test["barrier"] = "no-barrier"
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        mc.db("7.4.6").setup(test, "n1")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    mgmd = [i for i, c in enumerate(cmds) if "ndb_mgmd" in c]
    ndbd = [i for i, c in enumerate(cmds)
            if "/bin/ndbd" in c]
    mysqld = [i for i, c in enumerate(cmds) if "mysqld_safe" in c]
    assert mgmd and ndbd and mysqld
    assert mgmd[0] < ndbd[0] < mysqld[0]


# --- postgres-rds ---------------------------------------------------------


def test_postgres_rds_test_shape():
    from jepsen_tpu import nemesis as nemesis_mod
    from jepsen_tpu.suites import postgres_rds

    t = postgres_rds.bank_test({"nodes": ["rds.example.com"],
                                "time_limit": 1})
    # managed service: no db automation, no-op nemesis
    assert t["nemesis"] is nemesis_mod.noop
    assert t["total_amount"] == 50
    assert t["client"].n == 5


# --- dgraph ---------------------------------------------------------------


def test_dgraph_db_commands():
    from jepsen_tpu.suites import dgraph

    test, r = dummy_test(nodes=("n1", "n2"))
    test["barrier"] = "no-barrier"
    r.responses["stat /"] = (1, "", "no")
    r.responses["ls -A"] = (0, "dgraph\n", "")
    r.responses["dirname"] = (0, "/opt", "")
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        dgraph.db().setup(test, "n2")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    zero = [c for c in cmds if " zero " in c or c.endswith(" zero")]
    assert any("--peer n1:5080" in c for c in cmds), "n2 joins primary"
    assert any("server" in c and "--zero n2:5080" in c for c in cmds)


def test_dgraph_upsert_checker():
    from jepsen_tpu.suites import dgraph

    ch = dgraph.upsert_checker()
    good = [mkop(index=0, f="upsert", value="0x1"),
            mkop(index=1, f="read", value=["0x1"])]
    assert ch.check({}, good)["valid"] is True
    two_ok = good + [mkop(index=2, f="upsert", value="0x2")]
    assert ch.check({}, two_ok)["valid"] is False
    multi_read = good + [mkop(index=3, f="read",
                              value=["0x1", "0x2"])]
    assert ch.check({}, multi_read)["valid"] is False


def test_dgraph_delete_checker():
    from jepsen_tpu.suites import dgraph

    ch = dgraph.delete_checker()
    ok = [mkop(index=0, value=[5]), mkop(index=1, value=[])]
    assert ch.check({}, ok)["valid"] is True
    bad = ok + [mkop(index=2, value=[5, 5])]
    assert ch.check({}, bad)["valid"] is False


def test_dgraph_workloads_construct():
    from jepsen_tpu.suites import dgraph

    for wl in dgraph.WORKLOADS:
        t = dgraph.dgraph_test({"workload": wl, "nodes": ["n1"],
                                "time_limit": 1})
        assert wl in t["name"]
        assert t["checker"] is not None
