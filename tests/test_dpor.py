"""Dynamic partial-order reduction (analyze/dpor.py) + the device
must-order mask and the dead-value frontier dedup — phase 2 of
state-space reduction.

Contract under test:

  * **verdict identity** — with the dynamic layer ON, every route
    (host DFS, host linear sweep, device BFS, decomposed, bucketed,
    streamed) returns exactly the verdict the unreduced oracle
    returns, on valid, corrupted, and crash-heavy histories (the
    acceptance criterion's 300+-history all-route differential fuzz,
    audits included);
  * **off-mode guard** — JEPSEN_TPU_DPOR=0 / dpor=False leaves every
    engine byte-identical to its unreduced behavior: no dpor stats
    attached, no masked kernels built, configs counts unchanged (the
    PR-10 off-mode-guard pattern, tier-1-gated);
  * **the reductions actually fire** — sleep sets prune, dead states
    rewrite and collapse, device lanes get masked — measured through
    the result stats and the jtpu_dpor_* counters, not assumed.
"""

import random

import pytest

from jepsen_tpu.analyze import dpor as dpor_mod
from jepsen_tpu.checker import linearizable as lin
from jepsen_tpu.checker import seq as oracle
from jepsen_tpu.checker.linear import check_opseq_linear
from jepsen_tpu.history import (Op, encode_ops, info_op, invoke_op,
                                ok_op)
from jepsen_tpu.models import cas_register, mutex, register
from jepsen_tpu.obs.metrics import REGISTRY
from jepsen_tpu.synth import (corrupt_read, register_history,
                              sim_mutex_history)

# ---------------------------------------------------------------------------
# Unit: duplicate-op canonical edges
# ---------------------------------------------------------------------------


def test_duplicate_op_edges_staircase_only():
    """Identical rows chain by invocation when returns do not invert;
    rt-implied pairs are skipped; different content never chains."""
    model = register(0)
    h = [
        invoke_op(0, "write", 5), ok_op(0, "write", 5),      # rows 0-1
        invoke_op(1, "write", 5),                             # row 2
        invoke_op(2, "write", 7),                             # row 3
        ok_op(1, "write", 5), ok_op(2, "write", 7),
    ]
    s = encode_ops(h, model.f_codes)
    edges = dpor_mod.duplicate_op_edges(s)
    # row0 (w5, returns before row1 invokes) -> rt-implied: skipped;
    # the overlapping duplicate pair must NOT edge to the w7 row
    for (src, dst, kind) in edges:
        assert kind == "dup"
        assert int(s.v1[src]) == int(s.v1[dst])


def test_duplicate_op_edges_prune_preserves_verdict():
    """A history of duplicate overlapping writes (hb-tainted: no
    unique-writes algebra) still decides identically with the dup-edge
    mask on, and the mask genuinely prunes the sweep."""
    model = register(0)
    h = []
    # 4 concurrent identical writes + interleaved reads, then a second
    # wave — symmetric interleavings galore
    for p in range(4):
        h.append(invoke_op(p, "write", 1))
    for p in range(4):
        h.append(ok_op(p, "write", 1))
    h.append(invoke_op(0, "read", None))
    h.append(ok_op(0, "read", 1))
    for p in range(4):
        h.append(invoke_op(p, "write", 2))
    for p in range(4):
        h.append(ok_op(p, "write", 2))
    s = encode_ops(h, model.f_codes)
    on = check_opseq_linear(s, model, dpor=True)
    off = check_opseq_linear(s, model, dpor=False)
    assert on["valid"] is True and off["valid"] is True
    assert on["configs"] <= off["configs"]
    edges = dpor_mod.duplicate_op_edges(s)
    assert edges, "duplicate writes must produce dup edges"


# ---------------------------------------------------------------------------
# Unit: sleep sets and the dead-value quotient
# ---------------------------------------------------------------------------


def test_sleep_sets_prune_and_preserve_verdict():
    rng = random.Random(5)
    model = cas_register()
    pruned_somewhere = False
    for seed in range(10):
        rng = random.Random(seed)
        h = register_history(rng, n_ops=40, n_procs=4, overlap=4,
                             crash_p=0.1)
        if seed % 2:
            h = corrupt_read(rng, h, at=0.8)
        s = encode_ops(h, model.f_codes)
        on = oracle.check_opseq(s, model, dpor=True)
        off = oracle.check_opseq(s, model, dpor=False)
        assert on["valid"] == off["valid"], seed
        st = on.get("dpor") or {}
        pruned_somewhere = pruned_somewhere or st.get("sleep_prunes")
    assert pruned_somewhere, "sleep sets never fired across 10 seeds"


def test_dead_value_rewrite_collapses_frontier():
    """Unread writes die immediately: configurations differing only in
    which dead value they left behind must merge.  The linear sweep
    reports the rewrites/hits it performed."""
    model = register(0)
    h = []
    # 3 concurrent writes of values nobody ever reads
    for p in range(3):
        h.append(invoke_op(p, "write", 10 + p))
    for p in range(3):
        h.append(ok_op(p, "write", 10 + p))
    # a later concurrent wave, still unread
    for p in range(3):
        h.append(invoke_op(p, "write", 20 + p))
    for p in range(3):
        h.append(ok_op(p, "write", 20 + p))
    s = encode_ops(h, model.f_codes)
    # hb=False: the interval pass would decide this unique-writes
    # history without any sweep — the point here is the sweep's dedup
    on = check_opseq_linear(s, model, dpor=True, hb=False)
    off = check_opseq_linear(s, model, dpor=False, hb=False)
    assert on["valid"] is True and off["valid"] is True
    st = on["dpor"]
    assert st["dedup_rewrites"] > 0
    assert on["configs"] < off["configs"], \
        "dead-value collapse should shrink the level sweep"


def test_dead_value_respects_live_reads():
    """A value still read later must NOT fold — the read's legality
    depends on it."""
    from jepsen_tpu.decompose.canonical import dead_value_cutoffs

    model = register(0)
    h = [invoke_op(0, "write", 3), ok_op(0, "write", 3),
         invoke_op(1, "write", 4), ok_op(1, "write", 4),
         invoke_op(0, "read", None), ok_op(0, "read", 4)]
    s = encode_ops(h, model.f_codes)
    dv = dead_value_cutoffs(s, model)
    assert dv is not None
    # value 4 is read at det position 5 -> dead only past it (values
    # encode as themselves: ValueEncoder identity_ints)
    assert dv.cutoffs.get(4, 0) > 0
    assert dv.cutoffs.get(3, 1) == 0  # never read: dead from the start
    on = check_opseq_linear(s, model, dpor=True)
    off = check_opseq_linear(s, model, dpor=False)
    assert on["valid"] == off["valid"] is True


def test_crash_compared_values_never_die():
    """A crashed read of v pins v live forever (the crashed comparison
    may linearize at any future point)."""
    from jepsen_tpu.decompose.canonical import NEVER_DEAD, \
        dead_value_cutoffs

    model = cas_register()
    h = [invoke_op(0, "write", 3), ok_op(0, "write", 3),
         invoke_op(1, "read", 3), info_op(1, "read", 3),
         invoke_op(2, "write", 9), ok_op(2, "write", 9)]
    s = encode_ops(h, model.f_codes)
    dv = dead_value_cutoffs(s, model)
    assert dv is not None
    enc3 = int(s.v1[0])  # encoded value of the crashed-read target
    assert dv.cutoffs[enc3] == NEVER_DEAD


# ---------------------------------------------------------------------------
# Device mask
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_device_mask_parity_and_prune(seed):
    rng = random.Random(1000 + seed)
    model = cas_register()
    h = register_history(rng, n_ops=48, n_procs=5, overlap=4,
                         crash_p=0.12, max_crashes=4)
    if seed % 2:
        h = corrupt_read(rng, h, at=0.85)
    s = encode_ops(h, model.f_codes)
    want = oracle.check_opseq(s, model, dpor=False)["valid"]
    on = lin.search_opseq(s, model, budget=2_000_000, dpor=True)
    off = lin.search_opseq(s, model, budget=2_000_000, dpor=False)
    assert on["valid"] == off["valid"] == want, seed
    if str(on.get("engine", "")).startswith("device") \
            and str(off.get("engine", "")).startswith("device"):
        # reductions can only shrink the explored configuration count
        assert on["configs"] <= off["configs"], seed


def test_attach_reductions_builds_planes():
    model = cas_register()
    h = [invoke_op(0, "write", 1), invoke_op(1, "write", 1),
         ok_op(0, "write", 1), ok_op(1, "write", 1),
         invoke_op(2, "read", None), info_op(2, "read")]
    s = encode_ops(h, model.f_codes)
    es = lin.encode_search(s)
    edges = dpor_mod.duplicate_op_edges(s)
    must = {}
    for (src, dst, _k) in edges:
        must.setdefault(dst, []).append(src)
    must = {d: tuple(v) for d, v in must.items()}
    lin.attach_reductions(es, s, model, must, dedup=True)
    assert es.masked
    esp = lin.pad_search(es, 64, 32)
    assert esp.det_mpred.shape == (64, lin.MASK_PREDS)
    assert esp.det_cpredw.shape == (64, 1)
    assert esp.dead_from.shape[0] >= 8
    assert esp.masked and esp.dedup == es.dedup


def test_crash_pred_bit63_no_overflow():
    """A must-order edge whose source is crash index 63 (MAX_CRASH-1)
    sets bit 63 of the packed crash-pred mask — it must fit the
    unsigned plane, not overflow a signed int64 (regression)."""
    model = cas_register()
    h = []
    t = 0
    for i in range(lin.MAX_CRASH):
        h.append(invoke_op(i % 8, "write", i + 1, index=len(h), time=t))
        t += 1
        h.append(info_op(i % 8, "write", i + 1, index=len(h), time=t))
        t += 1
    # a read observing the LAST crashed write forces an edge from
    # crash index 63
    h.append(invoke_op(0, "read", None, index=len(h), time=t))
    t += 1
    h.append(ok_op(0, "read", lin.MAX_CRASH, index=len(h), time=t))
    s = encode_ops(h, model.f_codes)
    on = lin.search_opseq(s, model, budget=500_000, dpor=True)
    off = lin.search_opseq(s, model, budget=500_000, dpor=False)
    assert on["valid"] == off["valid"]


def test_sharded_parity_with_dpor():
    import numpy as np

    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = Mesh(np.array(devs), ("shard",))
    model = cas_register()
    rng = random.Random(77)
    h = register_history(rng, n_ops=60, n_procs=6, overlap=4,
                         crash_p=0.1)
    h = corrupt_read(rng, h, at=0.9)
    s = encode_ops(h, model.f_codes)
    want = oracle.check_opseq(s, model, dpor=False)["valid"]
    on = lin.search_opseq_sharded(s, model, mesh,
                                  frontier_per_device=128, hb=False,
                                  dpor=True)
    off = lin.search_opseq_sharded(s, model, mesh,
                                   frontier_per_device=128, hb=False,
                                   dpor=False)
    assert on["valid"] == off["valid"] == want


# ---------------------------------------------------------------------------
# Off-mode guard (the tier-1 satellite: dpor off => byte-identical
# results and a dormant layer)
# ---------------------------------------------------------------------------


def test_off_mode_is_byte_identical_and_dormant(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_DPOR", "0")
    assert not dpor_mod.dpor_enabled()
    model = cas_register()
    for seed in range(6):
        rng = random.Random(50 + seed)
        h = register_history(rng, n_ops=36, n_procs=4, overlap=3,
                             crash_p=0.1)
        if seed % 2:
            h = corrupt_read(rng, h, at=0.8)
        s = encode_ops(h, model.f_codes)
        a = oracle.check_opseq(s, model)
        b = oracle.check_opseq(s, model, dpor=False)
        # env-off and explicit-off are the SAME search, byte-identical
        assert a == b, seed
        assert "dpor" not in a
        c = check_opseq_linear(s, model)
        assert "dpor" not in c
        d = lin.search_opseq(s, model, budget=500_000)
        assert "dpor" not in d


def test_off_mode_overhead_is_bounded():
    """dpor=False must not pay the dynamic layer's costs: the DFS with
    the layer off explores exactly its pre-phase-2 config count (the
    run above asserts equality), and a same-history timing ratio stays
    sane.  Loose bound — this is a smoke guard, not a benchmark."""
    import time

    model = cas_register()
    rng = random.Random(99)
    h = register_history(rng, n_ops=60, n_procs=4, overlap=4,
                         crash_p=0.0)
    s = encode_ops(h, model.f_codes)
    t0 = time.perf_counter()
    for _ in range(3):
        oracle.check_opseq(s, model, dpor=False, hb=False)
    t_off = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(3):
        oracle.check_opseq(s, model, dpor=False, hb=False)
    t_off2 = time.perf_counter() - t0
    # two identical off-mode runs bound each other (noise guard): the
    # real assertion is above — off-mode results are byte-identical
    assert t_off2 < 20 * t_off + 1.0


# ---------------------------------------------------------------------------
# All-route differential fuzz (acceptance: 300+ histories, :info
# crashes included, audits passing)
# ---------------------------------------------------------------------------


def _routes(s, model):
    """Every engine route with the dynamic layer ON (env default)."""
    from jepsen_tpu.decompose.engine import check_opseq_decomposed
    from jepsen_tpu.stream import StreamChecker

    out = {
        "dfs": oracle.check_opseq(s, model, dpor=True),
        "linear": check_opseq_linear(s, model, dpor=True,
                                     witness_cap=200_000),
        "direct": lin.search_opseq(s, model, budget=300_000,
                                   dpor=True),
        "decomposed": check_opseq_decomposed(s, model, witness=True,
                                             dpor=True),
        "bucketed": lin.search_batch([s], model, bucket=True,
                                     budget=300_000, dpor=True)[0],
    }
    return out


@pytest.mark.parametrize("group", range(8))
def test_all_route_differential_fuzz(group):
    """40 histories per group x 8 groups = 320 histories: valid,
    corrupted, crash-heavy, mutex, duplicate-heavy — every route with
    dpor ON must match the dpor-OFF WGL oracle bit-for-bit on
    verdicts, and every certificate must audit clean."""
    from jepsen_tpu.analyze.audit import audit as audit_fn
    from jepsen_tpu.stream import StreamChecker

    n_checked = 0
    for i in range(40):
        seed = group * 1000 + i
        rng = random.Random(seed)
        if group == 6:
            model = mutex()
            h = sim_mutex_history(rng, n_ops=26, n_procs=3,
                                  crash_p=0.15, max_crashes=3)
        elif group == 7:
            # duplicate-heavy register histories (hb-tainted class):
            # the dup-edge + dedup sweet spot
            model = register(0)
            h = register_history(rng, n_ops=28, n_procs=4, overlap=4,
                                 crash_p=0.1, n_values=2, cas=False)
            if i % 2:
                h = corrupt_read(rng, h, at=0.7)
        else:
            model = cas_register()
            h = register_history(rng, n_ops=30, n_procs=4, overlap=4,
                                 crash_p=(0.0, 0.1, 0.25, 0.1)[group % 4])
            if group % 2:
                h = corrupt_read(rng, h, at=0.8)
        s = encode_ops(h, model.f_codes)
        want = oracle.check_opseq(s, model, dpor=False,
                                  max_configs=200_000)["valid"]
        if want == "unknown":
            continue
        rs = _routes(s, model)
        sc = StreamChecker(model, dpor=True)
        for op in h:
            sc.ingest(op)
        rs["streamed"] = sc.finalize()
        for route, r in rs.items():
            if r["valid"] == "unknown":
                continue
            assert r["valid"] == want, \
                f"seed {seed} route {route}: {r['valid']} != {want}"
            a = audit_fn(s, model, r)
            assert a["ok"], (f"seed {seed} route {route} audit: "
                             f"{[str(d) for d in a['diagnostics']]}")
        n_checked += 1
    assert n_checked >= 30  # the group really exercised the net


# ---------------------------------------------------------------------------
# Metrics, plan, and knobs
# ---------------------------------------------------------------------------


def test_dpor_metrics_registered_and_fire():
    for name in ("jtpu_dpor_sleep_prunes_total",
                 "jtpu_dpor_dedup_total",
                 "jtpu_dpor_mask_total",
                 "jtpu_dpor_dup_edges_total"):
        assert REGISTRY.get(name) is not None, name
    # a dedup-heavy run must move the counters
    model = register(0)
    h = []
    for p in range(3):
        h.append(invoke_op(p, "write", 30 + p))
    for p in range(3):
        h.append(ok_op(p, "write", 30 + p))
    s = encode_ops(h, model.f_codes)
    m = REGISTRY.get("jtpu_dpor_dedup_total")
    before = m.value(site="host-linear", event="rewrite")
    check_opseq_linear(s, model, dpor=True, hb=False)
    assert m.value(site="host-linear", event="rewrite") > before
    # exposition renders them (the /metrics surface)
    from jepsen_tpu.obs.metrics import render

    assert "jtpu_dpor_dedup_total" in render()


def test_explain_dpor_block_and_batch_mirror():
    from jepsen_tpu.analyze.plan import explain, explain_batch

    model = cas_register()
    rng = random.Random(3)
    h = register_history(rng, n_ops=30, n_procs=4, overlap=4,
                         crash_p=0.1)
    s = encode_ops(h, model.f_codes)
    plan = explain(s, model)
    dp = plan["dpor"]
    for k in ("enabled", "dup_edges", "mask_coverage", "masked_rows",
              "dedup", "sleep_set_bound", "pruned_upper_bound",
              "prune_ratio"):
        assert k in dp, k
    bp = explain_batch([s, s], model)
    bdp = bp["dpor"]
    for k in ("enabled", "keys", "masked_keys", "dedup_keys",
              "dup_edges", "mask_coverage",
              "dedup_hit_rate_prediction", "sleep_set_bound"):
        assert k in bdp, k
    # render both without blowing up, mentioning the block
    from jepsen_tpu.analyze.plan import render_plan

    assert "dpor" in render_plan(plan)
    assert "dpor" in render_plan(bp, batch=True)


def test_knob_family_resolution(monkeypatch):
    assert dpor_mod.resolve_dpor(None) == dpor_mod.dpor_enabled()
    assert dpor_mod.resolve_dpor(True) is True
    assert dpor_mod.resolve_dpor(False) is False
    monkeypatch.setenv("JEPSEN_TPU_DPOR", "off")
    assert dpor_mod.dpor_enabled() is False
    monkeypatch.setenv("JEPSEN_TPU_DPOR", "1")
    assert dpor_mod.dpor_enabled() is True


def test_cli_no_dpor_sets_env(monkeypatch):
    import argparse
    import os

    from jepsen_tpu import cli

    monkeypatch.delenv("JEPSEN_TPU_DPOR", raising=False)
    p = argparse.ArgumentParser()
    cli.add_test_opts(p)
    ns = p.parse_args(["--no-dpor"])
    assert ns.no_dpor is True
    cli.test_opt_fn(ns)
    assert os.environ.get("JEPSEN_TPU_DPOR") == "0"
    # plain pop, NOT monkeypatch.delenv: test_opt_fn set the var
    # outside monkeypatch's ledger, so a second delenv records "0" as
    # the value to RESTORE at teardown — leaking dpor-off into every
    # test file that runs after this one
    os.environ.pop("JEPSEN_TPU_DPOR", None)
