"""Differential tests: device frontier search vs the exact host oracle.

The reference establishes confidence in its checker by racing two knossos
algorithms (`competition`, jepsen/src/jepsen/checker.clj:122-126); here we
run the vectorized device engine and the host DFS on the same random
histories and require identical verdicts.  Histories come from a
simulator that is valid-by-construction (ops take effect at their
completion — a legal linearization point), plus corrupted and
crash-heavy variants that are frequently invalid.
"""

import random

import jax

import pytest

from jepsen_tpu.history import (
    encode_ops, fail_op, info_op, invoke_op, ok_op,
)
from jepsen_tpu.checker import seq as oracle
from jepsen_tpu.checker import linearizable as lin
from jepsen_tpu.models import cas_register, mutex, register

# Shared generous dims so all differential cases reuse one compiled kernel.
DIMS = lin.SearchDims(n_det_pad=128, n_crash_pad=32, window=96, k=16,
                      state_width=1, frontier=256)


def random_register_history(rng: random.Random, n_procs=4, n_ops=40, *,
                            crash_p=0.0, cas=True):
    """Simulate processes against a real register (canonical simulator:
    jepsen_tpu/synth.py; shared with tools/fuzz.py)."""
    from jepsen_tpu.synth import sim_register_history

    return sim_register_history(rng, n_procs, n_ops, crash_p=crash_p,
                                cas=cas, max_crashes=8)


def corrupt(rng: random.Random, h):
    """Flip one ok read's value (canonical: synth.flip_read)."""
    from jepsen_tpu.synth import flip_read

    return flip_read(rng, h)


def both_verdicts(h, model):
    s = encode_ops(h, model.f_codes)
    a = oracle.check_opseq(s, model)
    es = lin.encode_search(s)
    assert es.window <= DIMS.window, "test dims too small"
    assert es.concurrency <= DIMS.k, "test dims too small"
    b = lin.search_opseq(s, model, dims=DIMS)
    return a, b


@pytest.mark.parametrize("seed", range(12))
def test_differential_valid_histories(seed):
    rng = random.Random(seed)
    h = random_register_history(rng, n_procs=4, n_ops=40)
    a, b = both_verdicts(h, cas_register())
    assert a["valid"] is True, f"simulator produced invalid history? {a}"
    assert b["valid"] is True, f"device disagrees: {b}"


@pytest.mark.parametrize("seed", range(12))
def test_differential_corrupted_histories(seed):
    rng = random.Random(1000 + seed)
    h = corrupt(rng, random_register_history(rng, n_procs=4, n_ops=40))
    a, b = both_verdicts(h, cas_register())
    assert a["valid"] in (True, False)
    assert b["valid"] == a["valid"], f"oracle={a} device={b}"


@pytest.mark.parametrize("seed", range(12))
def test_differential_crashy_histories(seed):
    rng = random.Random(2000 + seed)
    h = random_register_history(rng, n_procs=4, n_ops=30, crash_p=0.25)
    a, b = both_verdicts(h, cas_register())
    assert a["valid"] is True, f"simulator produced invalid history? {a}"
    assert b["valid"] is True, f"device disagrees: {b}"


@pytest.mark.parametrize("seed", range(8))
def test_differential_crashy_corrupted(seed):
    rng = random.Random(3000 + seed)
    h = corrupt(rng, random_register_history(rng, n_procs=4, n_ops=30,
                                             crash_p=0.25))
    a, b = both_verdicts(h, cas_register())
    assert b["valid"] == a["valid"], f"oracle={a} device={b}"


def test_mutex_history():
    # hazelcast-style lock workload (hazelcast.clj:379-386): acquire and
    # release must alternate globally.
    m = mutex()
    h = [invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
         invoke_op(1, "acquire", None),  # blocks...
         invoke_op(0, "release", None), ok_op(0, "release", None),
         ok_op(1, "acquire", None),
         invoke_op(1, "release", None), ok_op(1, "release", None)]
    a = oracle.check_opseq(encode_ops(h, m.f_codes), m)
    assert a["valid"] is True
    s = encode_ops(h, m.f_codes)
    b = lin.search_opseq(s, m, dims=lin.SearchDims(
        n_det_pad=64, n_crash_pad=32, window=32, k=4, state_width=1,
        frontier=64))
    assert b["valid"] is True

    # double acquire with no release: invalid
    h2 = [invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
          invoke_op(1, "acquire", None), ok_op(1, "acquire", None)]
    s2 = encode_ops(h2, m.f_codes)
    assert oracle.check_opseq(s2, m)["valid"] is False
    b2 = lin.search_opseq(s2, m, dims=lin.SearchDims(
        n_det_pad=64, n_crash_pad=32, window=32, k=4, state_width=1,
        frontier=64))
    assert b2["valid"] is False


def test_checker_wrapper_small_and_large():
    rng = random.Random(7)
    model = cas_register()
    chk = lin.linearizable(model, host_threshold=10)
    h = random_register_history(rng, n_procs=4, n_ops=6)
    out = chk.check({}, h)
    assert out["valid"] is True and out["engine"] == "host-oracle"

    h2 = random_register_history(rng, n_procs=4, n_ops=60)
    out2 = chk.check({}, h2)
    assert out2["valid"] is True

    h3 = corrupt(rng, h2)
    out3 = chk.check({}, h3)
    ref = oracle.check_opseq(encode_ops(h3, model.f_codes), model)
    assert out3["valid"] == ref["valid"]
    if out3["valid"] is False:
        # invalid verdicts come back host-confirmed with a witness frontier
        assert "final_ops" in out3


def test_larger_history_smoke():
    rng = random.Random(99)
    h = random_register_history(rng, n_procs=8, n_ops=300)
    model = cas_register()
    s = encode_ops(h, model.f_codes)
    out = lin.search_opseq(s, model)
    assert out["valid"] is True


def test_truncate_to_failure_soundness():
    """The witness prefix must agree with the full-history verdict on
    corrupted histories (the cut is closed, so prefix-invalid implies
    full-invalid)."""
    model = cas_register()
    for seed in range(6):
        rng = random.Random(400 + seed)
        from jepsen_tpu.synth import corrupt_read, register_history

        h = register_history(rng, n_ops=200, n_procs=6, overlap=3,
                             crash_p=0.02)
        h = corrupt_read(rng, h, at=0.3)  # fail early: big truncation win
        s = encode_ops(h, model.f_codes)
        full = oracle.check_opseq(s, model)
        if full["valid"] is not False:
            continue
        out = lin.search_opseq(s, model)
        assert out["valid"] is False
        trunc = lin.truncate_to_failure(s, out["max_depth"], out["window"])
        if trunc is not None:
            assert len(trunc) < len(s)
            assert oracle.check_opseq(trunc, model)["valid"] is False


def test_wrapper_witness_prefix():
    from jepsen_tpu.synth import corrupt_read, register_history

    model = cas_register()
    rng = random.Random(77)
    h = register_history(rng, n_ops=300, n_procs=6, overlap=3)
    h = corrupt_read(rng, h, at=0.2)
    chk = lin.linearizable(model, host_threshold=10)
    out = chk.check({}, h)
    ref = oracle.check_opseq(encode_ops(h, model.f_codes), model)
    assert out["valid"] == ref["valid"]
    if out["valid"] is False and "witness_prefix_ops" in out:
        assert out["witness_prefix_ops"] < 300


def test_slicing_equivalence(monkeypatch):
    """Tiny slices (1 level per device call) must give the same verdict
    as big ones — the slice boundary is invisible to the search."""
    monkeypatch.setattr(lin, "_SLICE_LEVELS0", 1)
    monkeypatch.setattr(lin, "_adapt_lvl_cap", lambda cap, dt, **kw: cap)
    rng = random.Random(77)
    h = corrupt(rng, random_register_history(rng, n_procs=4, n_ops=40))
    model = cas_register()
    s = encode_ops(h, model.f_codes)
    a = oracle.check_opseq(s, model)
    slices = []
    b = lin.search_opseq(s, model, dims=DIMS,
                         on_slice=lambda c, d: slices.append(True))
    assert b["valid"] == a["valid"], f"oracle={a} device={b}"
    assert len(slices) > 1, "expected multiple 1-level slices"


def test_checkpoint_resume(tmp_path, monkeypatch):
    """Stop a search mid-flight, persist the carry, resume in a 'new'
    driver, and get the same verdict as an uninterrupted run."""
    monkeypatch.setattr(lin, "_SLICE_LEVELS0", 2)
    monkeypatch.setattr(lin, "_adapt_lvl_cap", lambda cap, dt, **kw: cap)
    rng = random.Random(78)
    h = corrupt(rng, random_register_history(rng, n_procs=4, n_ops=40))
    model = cas_register()
    s = encode_ops(h, model.f_codes)
    # hb=False: the static prepass would decide this corrupt history
    # outright with zero device slices — this test targets the
    # checkpoint machinery, which needs real slices to snapshot
    want = lin.search_opseq(s, model, dims=DIMS, hb=False)["valid"]

    ckpt = str(tmp_path / "search.npz")

    class Stop(Exception):
        pass

    n = [0]

    def save_then_stop(carry, dims):
        n[0] += 1
        lin.save_checkpoint(ckpt, carry, dims, model, budget=20_000_000,
                            seq=s)
        if n[0] >= 2:
            raise Stop

    try:
        lin.search_opseq(s, model, dims=DIMS, on_slice=save_then_stop,
                         hb=False)
    except Stop:
        pass
    carry, dims2, name, budget, digest, _pallas = \
        lin.load_checkpoint(ckpt)
    # the adaptive driver may have moved frontier width along the grid;
    # everything else must round-trip exactly
    assert {**dims2.__dict__, "frontier": 0} == \
        {**DIMS.__dict__, "frontier": 0}
    assert dims2.frontier == lin._grid_width(dims2.frontier)
    assert name == model.name
    assert digest == lin.history_digest(s, model)
    out = lin.resume_opseq(s, model, ckpt)
    assert out["valid"] == want
    assert out["engine"].startswith("device")

    # resuming against a different history must be refused
    h2 = corrupt(random.Random(99),
                 random_register_history(random.Random(99), n_procs=4,
                                         n_ops=40))
    s2 = encode_ops(h2, model.f_codes)
    with pytest.raises(ValueError, match="digest"):
        lin.resume_opseq(s2, model, ckpt)


@pytest.mark.parametrize("seed", range(6))
def test_escalation_resumes_not_restarts(seed, monkeypatch):
    """Force frontier overflow with a tiny initial frontier: the ladder
    must widen and RESUME from the pre-overflow carry, producing the
    oracle's verdict."""
    monkeypatch.setattr(lin, "_SLICE_LEVELS0", 4)
    monkeypatch.setattr(lin, "_adapt_lvl_cap", lambda cap, dt, **kw: cap)
    rng = random.Random(4000 + seed)
    h = corrupt(rng, random_register_history(rng, n_procs=4, n_ops=40))
    model = cas_register()
    s = encode_ops(h, model.f_codes)
    a = oracle.check_opseq(s, model)
    tiny = lin.SearchDims(n_det_pad=128, n_crash_pad=32, window=96,
                          k=16, state_width=1, frontier=8)
    b = lin.search_opseq(s, model, dims=tiny)
    assert b["valid"] == a["valid"], f"oracle={a} device={b}"
    # ladder must actually have escalated for a nontrivial search
    if a["configs"] > 64:
        assert b["frontier"] > 8, f"no escalation happened: {b}"


def test_fuzzer_smoke(monkeypatch):
    """tools/fuzz.py end to end: a handful of clean rounds, plus shrink
    on a hand-planted divergence stand-in (the shrinker must reduce a
    corrupted history to a small core that still diverges under a fake
    'engine')."""
    import os

    monkeypatch.syspath_prepend(
        os.path.join(os.path.dirname(__file__), "..", "tools"))
    import fuzz

    model = cas_register()
    for i in range(4):
        h = fuzz.gen_history(random.Random(i), "cas-register", 20, 3,
                             0.0)
        assert fuzz.diverges(h, model) is False

    # shrink with a stand-in divergence predicate ("oracle says
    # invalid") — exercises the pair-dropping logic without needing a
    # real engine bug.  Search a few seeds for an invalid corruption
    # rather than pinning one (randrange/choice sequences are not
    # guaranteed stable across CPython versions).
    from jepsen_tpu.history import encode_ops as enc

    def invalid(hh, m):
        try:
            s = enc(hh, m.f_codes)
        except Exception:
            return False
        return oracle.check_opseq(
            s, m, max_configs=fuzz.ORACLE_CAP)["valid"] is False

    h = None
    for seed in range(30):
        rng = random.Random(seed)
        cand = fuzz.corrupt(rng, fuzz.gen_history(rng, "cas-register",
                                                  30, 3, 0.0))
        if invalid(cand, model):
            h = cand
            break
    assert h is not None, "no invalid corruption in 30 seeds?!"
    monkeypatch.setattr(fuzz, "diverges", lambda hh, m: invalid(hh, m))
    small = fuzz.shrink(h, model)
    assert invalid(small, model)
    assert len(small) < len(h), "shrinker must actually reduce"
    assert len(small) <= 12, f"expected a small core, got {len(small)}"


# ---------------------------------------------------------------------------
# competition mode (checker.clj:122-126's :competition selector)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bad", [False, True])
def test_competition_agrees_with_oracle(bad):
    rng = random.Random(31 if bad else 13)
    h = random_register_history(rng, n_procs=4, n_ops=60)
    if bad:
        h = corrupt(rng, h)
    model = cas_register()
    s = encode_ops(h, model.f_codes)
    want = oracle.check_opseq(s, model)["valid"]
    out = lin.check_competition(s, model)
    assert out["valid"] == want
    assert out["engine"].startswith("competition(")


def test_competition_host_wins_when_device_stalls(monkeypatch):
    """With a zero device budget the host oracle must carry the race."""
    rng = random.Random(5)
    h = corrupt(rng, random_register_history(rng, n_procs=4, n_ops=50))
    model = cas_register()
    s = encode_ops(h, model.f_codes)
    out = lin.check_competition(s, model, budget=1)
    assert out["valid"] is False
    assert out["engine"] in ("competition(host-wgl)",
                             "competition(host-linear)")


def test_linearizable_algorithm_selection():
    rng = random.Random(77)
    h = corrupt(rng, random_register_history(rng, n_procs=4, n_ops=60))
    model = cas_register()
    test = {"name": "alg", "start_time": 0}
    for alg in ("auto", "host", "wgl", "device", "linear", "competition"):
        chk = lin.linearizable(model, algorithm=alg)
        assert chk.check(test, h, {})["valid"] is False, alg
    with pytest.raises(ValueError):
        lin.linearizable(model, algorithm="quantum")


# ---------------------------------------------------------------------------
# unordered-queue model on device (sorted-array multiset encoding)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(8))
def test_differential_queue_histories(seed):
    from jepsen_tpu.models import unordered_queue
    from jepsen_tpu.synth import corrupt_dequeue, sim_queue_history

    rng = random.Random(500 + seed)
    h = sim_queue_history(rng, 30, 4,
                          crash_p=(0.1 if seed % 2 else 0.0))
    n_enq = sum(1 for o in h if o.f == "enqueue" and o.type == "invoke")
    # fixed capacity so every seed shares ONE compiled kernel (the cache
    # keys on model.name, which embeds capacity)
    model = unordered_queue(31)
    assert n_enq < 31
    s = encode_ops(h, model.f_codes)
    a = oracle.check_opseq(s, model)
    b = lin.search_opseq(s, model)
    assert a["valid"] is True, f"simulator produced invalid queue? {a}"
    assert b["valid"] is True, f"device disagrees: {b}"

    hb = corrupt_dequeue(random.Random(seed), h)
    if hb is not h:
        sb = encode_ops(hb, model.f_codes)
        ab = oracle.check_opseq(sb, model)
        bb = lin.search_opseq(sb, model)
        assert bb["valid"] == ab["valid"], f"oracle={ab} device={bb}"


def test_queue_duplicate_values_dedup():
    """Two enqueues of the same value: the multiset must hold both, and
    dequeuing it twice is legal while a third dequeue is not."""
    from jepsen_tpu.models import unordered_queue

    model = unordered_queue(4)
    h = [invoke_op(0, "enqueue", 7), ok_op(0, "enqueue", 7),
         invoke_op(0, "enqueue", 7), ok_op(0, "enqueue", 7),
         invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 7),
         invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 7)]
    s = encode_ops(h, model.f_codes)
    assert oracle.check_opseq(s, model)["valid"] is True
    assert lin.search_opseq(s, model)["valid"] is True

    h_bad = h + [invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 7)]
    s_bad = encode_ops(h_bad, model.f_codes)
    assert oracle.check_opseq(s_bad, model)["valid"] is False
    assert lin.search_opseq(s_bad, model)["valid"] is False


def test_search_batch_mixed_difficulty_compaction():
    """Keys of very different sizes in one batch: the compacting driver
    must retire easy keys early and still return correct verdicts for
    every key in input order."""
    from jepsen_tpu.synth import corrupt_read, register_history

    model = cas_register()
    seqs, want = [], []
    for k in range(13):  # odd count: exercises grid padding
        rng = random.Random(9000 + k)
        n = 12 if k % 3 else 120  # most keys tiny, a few long-tail
        h = register_history(rng, n_ops=n, n_procs=4, overlap=3)
        if k % 2 == 0:
            h = corrupt_read(rng, h, at=0.7)
        s = encode_ops(h, model.f_codes)
        seqs.append(s)
        want.append(oracle.check_opseq(s, model)["valid"])
    # defeat the greedy-witness host path for valid keys? no — mixed
    # batches exercise exactly the production flow (greedy disposes of
    # well-behaved keys, the device batch gets the rest)
    got = lin.search_batch(seqs, model, budget=500_000)
    assert [r["valid"] for r in got] == want
    assert all(r["engine"] in
               ("device-batch", "device-batch(pallas)",
                "greedy-witness", "hb-decide", "device-bfs",
                "device-bfs(pallas)", "trivial")
               for r in got)
    # at least the corrupted keys must have ridden the device
    assert sum(r["engine"].startswith("device-batch")
               for r in got) >= 6


@pytest.mark.parametrize("seed", range(8))
def test_differential_fifo_queue_histories(seed):
    from jepsen_tpu.models import fifo_queue
    from jepsen_tpu.synth import sim_queue_history, swap_dequeues

    rng = random.Random(600 + seed)
    h = sim_queue_history(rng, 28, 4, fifo=True,
                          crash_p=(0.1 if seed % 2 else 0.0))
    model = fifo_queue(29)
    s = encode_ops(h, model.f_codes)
    a = oracle.check_opseq(s, model)
    b = lin.search_opseq(s, model)
    assert a["valid"] is True, f"simulator produced invalid fifo? {a}"
    assert b["valid"] is True, f"device disagrees: {b}"

    hb = swap_dequeues(random.Random(seed), h)
    if hb is not h:
        sb = encode_ops(hb, model.f_codes)
        ab = oracle.check_opseq(sb, model)
        bb = lin.search_opseq(sb, model)
        assert bb["valid"] == ab["valid"], f"oracle={ab} device={bb}"


def test_fifo_rejects_out_of_order_service():
    from jepsen_tpu.models import fifo_queue, unordered_queue

    h = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
         invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2),
         invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 2),
         invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1)]
    fifo, uq = fifo_queue(4), unordered_queue(4)
    s_f = encode_ops(h, fifo.f_codes)
    s_u = encode_ops(h, uq.f_codes)
    # LIFO service order: fine for a multiset, fatal for FIFO
    assert oracle.check_opseq(s_u, uq)["valid"] is True
    assert lin.search_opseq(s_u, uq)["valid"] is True
    assert oracle.check_opseq(s_f, fifo)["valid"] is False
    assert lin.search_opseq(s_f, fifo)["valid"] is False


def test_width_floor_backend_policy(monkeypatch):
    """The narrowest rung is backend-dependent: 16 on CPU (narrow
    valleys are cheap there), 64 on TPU (on-chip per-level cost is
    flat below F~64 while every rung costs a compile — see
    docs/tpu/r4/tpubench.jsonl), env-overridable either way."""
    monkeypatch.setattr(lin, "_WIDTH_FLOOR", None)
    monkeypatch.delenv("JEPSEN_TPU_WIDTH_FLOOR", raising=False)
    assert lin._width_floor() == (
        64 if jax.default_backend() == "tpu" else 16)
    monkeypatch.setattr(lin, "_WIDTH_FLOOR", None)
    monkeypatch.setenv("JEPSEN_TPU_WIDTH_FLOOR", "128")
    assert lin._grid_width(1) == 128
    assert lin._grid_width(129) == 256
    # reset so later tests see the real policy
    monkeypatch.setattr(lin, "_WIDTH_FLOOR", None)
