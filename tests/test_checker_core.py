"""Checker protocol / combinator tests (checker.clj merge-valid semantics)."""

from jepsen_tpu.checker import CheckerFn, check_safe, compose, merge_valid


def test_merge_valid_ordering():
    assert merge_valid([True, True]) is True
    assert merge_valid([True, "unknown"]) == "unknown"
    assert merge_valid([False, "unknown"]) is False
    assert merge_valid([]) is True
    # a checker that produced no verdict must not read as a pass
    assert merge_valid([True, None]) == "unknown"


def test_check_safe_catches():
    def boom(test, history, opts):
        raise RuntimeError("kaboom")
    r = check_safe(CheckerFn(boom), {}, [])
    assert r["valid"] == "unknown"
    assert "kaboom" in r["error"]


def test_compose_merges():
    ok = CheckerFn(lambda t, h, o: {"valid": True, "n": len(h)})
    bad = CheckerFn(lambda t, h, o: {"valid": False})
    broken = CheckerFn(lambda t, h, o: {})
    r = compose({"ok": ok, "bad": bad}).check({}, [1, 2], {})
    assert r["valid"] is False
    assert r["ok"]["n"] == 2
    r2 = compose({"ok": ok, "broken": broken}).check({}, [], {})
    assert r2["valid"] == "unknown"
