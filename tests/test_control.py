"""Control-plane tests: command construction against the dummy remote
(the analog of control.clj's *dummy* mode, control.clj:16,288-300) and
real execution via LocalRemote."""

import pytest

from jepsen_tpu import control, control_util as cu, net, reconnect
from jepsen_tpu.control import (DummyRemote, LocalRemote, RemoteError,
                                Session, SSHRemote, lit)
from jepsen_tpu.os import debian


def dummy_session(responses=None):
    r = DummyRemote(responses)
    return Session(node="n1", remote=r), r


def test_exec_escaping_and_output():
    s, r = dummy_session({"echo": (0, "  hello\n", "")})
    out = s.exec("echo", "hello world")
    assert out == "hello"
    assert r.log == [("n1", "exec", "echo 'hello world'")]


def test_exec_nonzero_raises():
    s, r = dummy_session({"false": (1, "", "boom")})
    with pytest.raises(RemoteError, match="boom"):
        s.exec("false")


def test_sudo_and_cd_wrapping():
    s, r = dummy_session()
    s.su().exec("whoami")
    assert r.log[-1][2] == "sudo -S -u root sh -c whoami"
    s.cd("/tmp").exec("ls")
    assert r.log[-1][2] == "cd /tmp && ls"
    s.su("admin").cd("/x").exec("ls")
    assert r.log[-1][2] == "sudo -S -u admin sh -c 'cd /x && ls'"


def test_lit_unescaped():
    s, r = dummy_session()
    s.exec("ls", lit("|"), "wc")
    assert r.log[-1][2] == "ls | wc"


def test_local_remote_real_commands(tmp_path):
    s = Session(node="local", remote=LocalRemote())
    assert s.exec("echo", "hi") == "hi"
    p = tmp_path / "f.txt"
    s.exec("sh", "-c", f"echo data > {p}")
    assert cu.exists(s, str(p))
    assert not cu.exists(s, str(tmp_path / "nope"))
    assert "f.txt" in cu.ls(s, str(tmp_path))


def test_on_nodes_parallel_fanout():
    r = DummyRemote()
    test = {"nodes": ["n1", "n2", "n3"],
            "sessions": {n: Session(node=n, remote=r)
                         for n in ["n1", "n2", "n3"]}}
    out = control.on_nodes(
        test, lambda t, n: control.session(n, t).exec("hostname"))
    assert set(out) == {"n1", "n2", "n3"}
    assert {e[0] for e in r.log} == {"n1", "n2", "n3"}


def test_cached_wget_key_is_base64():
    s, r = dummy_session({"stat": (1, "", "no such file")})
    path = cu.cached_wget(s, "https://x.example/v1.2/foo.tar")
    assert path.startswith(cu.WGET_CACHE_DIR + "/")
    # base64 of the URL, not the basename — versioned URLs can't alias
    import base64

    assert base64.b64decode(
        path.rsplit("/", 1)[1]).decode() == "https://x.example/v1.2/foo.tar"
    assert any("wget" in e[2] for e in r.log if e[1] == "exec")


def test_start_stop_daemon_command_shape():
    s, r = dummy_session()
    cu.start_daemon(s, "/opt/etcd/etcd", "--name", "n1",
                    logfile="/var/log/etcd.log", pidfile="/var/run/etcd.pid",
                    chdir="/opt/etcd")
    cmd = r.log[-1][2]
    assert "start-stop-daemon --start" in cmd
    assert "--background" in cmd and "--make-pidfile" in cmd
    assert "--exec /opt/etcd/etcd" in cmd
    assert ">> /var/log/etcd.log 2>&1" in cmd

    r.responses["stat"] = (0, "", "")
    r.responses["cat"] = (0, "1234", "")
    cu.stop_daemon(s, "/var/run/etcd.pid")
    assert any("kill -9 1234" in e[2] for e in r.log)


def test_grepkill():
    s, r = dummy_session()
    cu.grepkill(s, "etcd")
    cmd = r.log[-1][2]
    assert "ps aux | grep etcd | grep -v grep" in cmd
    assert "xargs kill -9" in cmd


def test_iptables_net_commands():
    r = DummyRemote({"getent": (0, "192.168.1.2  STREAM n2\n", "")})
    nodes = ["n1", "n2"]
    test = {"nodes": nodes, "net": net.iptables,
            "sessions": {n: Session(node=n, remote=r) for n in nodes}}
    net.iptables.drop(test, "n2", "n1")
    assert any("iptables -A INPUT -s 192.168.1.2 -j DROP -w" in e[2]
               for e in r.log if e[0] == "n1")
    net.iptables.heal(test)
    assert any("iptables -F -w" in e[2] for e in r.log)
    net.iptables.slow(test)
    assert any("netem delay 50ms 10ms distribution normal" in e[2]
               for e in r.log)
    net.iptables.flaky(test)
    assert any("loss 20% 75%" in e[2] for e in r.log)

    # batch grudge fast path: one rule with joined IPs per victim
    r.log.clear()
    net.drop_all(test, {"n1": ["n2"]})
    rules = [e for e in r.log if "iptables -A INPUT" in e[2]]
    assert len(rules) == 1 and rules[0][0] == "n1"


def test_reconnect_wrapper():
    opens = []

    class Conn:
        def __init__(self):
            self.closed = False
            opens.append(self)

    w = reconnect.Wrapper(open=Conn, close=lambda c: setattr(
        c, "closed", True), log_errors=False)
    c1 = w.conn()
    assert w.with_conn(lambda c: c) is c1

    def boom(c):
        raise RuntimeError("conn died")

    with pytest.raises(RuntimeError):
        w.with_conn(boom)
    c2 = w.conn()
    assert c2 is not c1 and c1.closed
    w.close()
    assert c2.closed and len(opens) == 2


def test_debian_install_only_missing():
    listing = ("ii  wget  1.21  amd64  retrieves files\n"
               "ii  curl  7.88  amd64  transfers data\n")
    s, r = dummy_session({"dpkg": (0, listing, "")})
    debian.install(s, ["wget", "curl", "vim"])
    installs = [e[2] for e in r.log if "apt-get install" in e[2]]
    assert len(installs) == 1 and "vim" in installs[0]
    assert "wget" not in installs[0]


def test_debian_install_pinned_versions():
    s, r = dummy_session({"apt-cache": (0, "  Installed: 1.0\n", "")})
    debian.install(s, {"etcd": "3.1.5", "wget": "1.0"})
    installs = [e[2] for e in r.log if "apt-get install" in e[2]]
    assert len(installs) == 1 and "etcd=3.1.5" in installs[0]


def test_ssh_remote_command_construction():
    ssh = SSHRemote(control.SSHConfig(username="admin", port=2222,
                                      private_key_path="/k"))
    args = ssh._base("n1")
    assert args[0] == "ssh"
    assert "admin@n1" in args
    assert "-p" in args and "2222" in args[args.index("-p") + 1]
    assert "-i" in args and "/k" in args
    assert any("ControlMaster" in a for a in args)


def test_command_trace_logs(caplog):
    import logging

    r = DummyRemote()
    sess = Session(node="n1", remote=r)
    with caplog.at_level(logging.INFO, logger="jepsen"):
        sess.exec("echo", "untraced")
        with control.trace():
            sess.exec("echo", "traced-cmd")
        sess.exec("echo", "after")
    traced = [rec.message for rec in caplog.records
              if "trace" in rec.message]
    assert any("traced-cmd" in m and "n1>" in m for m in traced)
    assert not any("untraced" in m for m in traced)
    assert not any("after" in m for m in traced)


def test_trace_is_thread_scoped():
    import threading

    r = DummyRemote()
    seen = []

    def other():
        seen.append(control._TRACE.on)

    with control.trace():
        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert control._TRACE.on is True
    assert seen == [False]
    assert control._TRACE.on is False


def test_tcpdump_capture_commands():
    r = DummyRemote()
    sess = Session(node="n1", remote=r)
    cu.start_tcpdump(sess, "/tmp/jepsen.pcap", port=26257)
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("tcpdump" in c and "-w /tmp/jepsen.pcap" in c
               and "port 26257" in c for c in cmds)
    cu.stop_tcpdump(sess)
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("kill" in c or "pkill" in c or "grep" in c for c in cmds)
