"""Failure-analysis rendering (checker/linear_report.py) — the
knossos linear.svg analog (checker.clj:128-139)."""

import os

from jepsen_tpu.checker import linear_report, seq as oracle
from jepsen_tpu.checker.linearizable import Linearizable
from jepsen_tpu.history import encode_ops, invoke_op, ok_op
from jepsen_tpu.models import cas_register


def _invalid_history():
    # read 3 can never be right: only 1 was ever written
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         invoke_op(1, "read", None), ok_op(1, "read", 3),
         invoke_op(0, "read", None), ok_op(0, "read", 1)]
    return encode_ops(h, cas_register().f_codes)


def test_oracle_returns_final_paths():
    # hb=False: this pins the DFS's own reporting contract (deepest
    # partial linearizations); the HB pre-pass legitimately decides
    # this history first and carries its own certificate instead
    s = _invalid_history()
    out = oracle.check_opseq(s, cas_register(), hb=False)
    assert out["valid"] is False
    assert out["final_paths"]
    assert len(out["final_paths"]) <= 10
    for p in out["final_paths"]:
        assert len(p["linearized"]) == out["max_depth"]


def test_render_linear_html_contains_svg_and_paths():
    s = _invalid_history()
    out = oracle.check_opseq(s, cas_register())
    doc = linear_report.render_linear_html(s, out)
    assert "<svg" in doc
    assert "could not be linearized" in doc
    assert "read" in doc


def test_checker_writes_linear_html(tmp_path):
    s = _invalid_history()
    test = {"name": "report-test", "store_base": str(tmp_path)}
    out = Linearizable(cas_register()).check(test, s)
    assert out["valid"] is False
    assert "report_file" in out
    assert os.path.exists(out["report_file"])
    assert out["report_file"].endswith("linear.html")
    with open(out["report_file"]) as f:
        assert "<svg" in f.read()


def test_valid_history_writes_nothing(tmp_path):
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         invoke_op(1, "read", None), ok_op(1, "read", 1)]
    s = encode_ops(h, cas_register().f_codes)
    test = {"name": "report-test-valid", "store_base": str(tmp_path)}
    out = Linearizable(cas_register()).check(test, s)
    assert out["valid"] is True
    assert "report_file" not in out
