"""Device-contract lint (jepsen_tpu/analyze/devlint.py) + the
thread/lock-discipline T-codes — the CI gates and the per-code rules.

``test_shipped_routes_stage_clean`` is the tier-1 guard for the
tentpole: every registered kernel route (single-XLA, bucketed-batch,
mesh-sharded, pallas-fused) must stage abstractly at representative
dims with zero K-code errors.  ``test_thread_tier_is_clean`` is its
T-code twin over the service tiers.  The fixture tests pin each
K001-K007 / T001-T004 rule on a minimal positive case plus a
suppressed (or corrected) negative, so the lint itself cannot rot
silently.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from jepsen_tpu.analyze.devlint import (  # noqa: E402
    DEVLINT_CODES,
    check_donation,
    check_span_args,
    lint_jaxpr,
    lint_trace_spans,
    representative_dims,
    run_devlint,
    span_kind_for_args,
    stage_route,
)
from jepsen_tpu.analyze.suites import (  # noqa: E402
    SUITE_CODES,
    lint_thread_tier,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(diags):
    return {d.code for d in diags}


# ---------------------------------------------------------------------------
# the CI gates
# ---------------------------------------------------------------------------


def test_shipped_routes_stage_clean():
    """Every registered kernel route stages abstractly with zero
    K-code errors (non-live: no compilation, milliseconds per route)."""
    rep = run_devlint(live=False)
    assert sorted(rep["routes"]) == [
        "bucketed-batch", "mesh-sharded", "pallas-fused", "single-xla"]
    errs = [d for d in rep["diagnostics"] if d["severity"] == "error"]
    assert errs == [], "device-contract errors:\n" + "\n".join(
        f"  {d['code']} {d['message']}" for d in errs)


def test_thread_tier_is_clean():
    findings = lint_thread_tier()
    errs = [(f, d) for f, ds in findings.items() for d in ds
            if d.severity == "error"]
    assert errs == [], "thread-discipline errors:\n" + "\n".join(
        f"  {d.message}" for _f, d in errs)


def test_committed_traces_satisfy_k007():
    """Every committed BENCH_trace_*.json compile span carries a
    documented cache-key coordinate generation."""
    import glob

    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_trace_*.json")))
    assert paths, "no committed bench traces found"
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        diags = lint_trace_spans(doc, name=os.path.basename(p))
        assert diags == [], f"{p}:\n" + "\n".join(
            f"  {d.message}" for d in diags)


def test_devlint_cli_exit_codes():
    out = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu.analyze", "--devlint",
         "--json"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["errors"] == 0
    assert len(payload["routes"]) == 4


def test_lint_suites_cli_includes_thread_tier():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_suites.py"),
         "--threads", "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert set(payload) == {"errors", "warnings", "files"}
    assert payload["errors"] == 0


def test_codes_are_documented():
    for code in DEVLINT_CODES:
        assert code.startswith("K")
    for code in ("T001", "T002", "T003", "T004"):
        assert code in SUITE_CODES


# ---------------------------------------------------------------------------
# K-code fixtures (staged toy kernels)
# ---------------------------------------------------------------------------


def _cb(v):
    return np.asarray(v, np.int32)


def test_k001_host_callback_in_loop():
    def f(x):
        def body(c, _):
            y = jax.pure_callback(
                _cb, jax.ShapeDtypeStruct((), jnp.int32), c)
            return c + y, None
        out, _ = jax.lax.scan(body, x, None, length=4)
        return out

    jaxpr = jax.make_jaxpr(f)(jnp.int32(1))
    assert "K001" in codes(lint_jaxpr(jaxpr, route_name="fix"))


def test_k001_suppressed_on_line():
    def f(x):
        def body(c, _):
            y = jax.pure_callback(  # devlint: ok — fixture
                _cb, jax.ShapeDtypeStruct((), jnp.int32), c)
            return c + y, None
        out, _ = jax.lax.scan(body, x, None, length=4)
        return out

    jaxpr = jax.make_jaxpr(f)(jnp.int32(1))
    assert "K001" not in codes(lint_jaxpr(jaxpr, route_name="fix"))


def test_k002_float_in_int_only_route():
    def f(x):
        return x.astype(jnp.float32) * jnp.float32(2)

    jaxpr = jax.make_jaxpr(f)(jnp.arange(4, dtype=jnp.int32))
    diags = lint_jaxpr(jaxpr, route_name="fix", int_only=True)
    assert "K002" in codes(diags)
    # a float-carrying route (pallas MXU matmuls) only bans 64-bit
    diags = lint_jaxpr(jaxpr, route_name="fix", int_only=False)
    assert "K002" not in codes(diags)


def test_k003_weak_type_invar():
    jaxpr = jax.make_jaxpr(lambda x, y: x + y)(
        jnp.arange(4, dtype=jnp.int32), 3)  # python scalar operand
    assert "K003" in codes(lint_jaxpr(jaxpr, route_name="fix"))
    jaxpr = jax.make_jaxpr(lambda x, y: x + y)(
        jnp.arange(4, dtype=jnp.int32),
        jnp.asarray(3, dtype=jnp.int32))
    assert "K003" not in codes(lint_jaxpr(jaxpr, route_name="fix"))


_DONATING = textwrap.dedent("""\
    import jax

    def get_kernel(model, dims):
        return jax.jit(step, donate_argnums=(6,))
""")

_DONATING_OK = textwrap.dedent("""\
    import jax

    def get_kernel(model, dims):
        return jax.jit(step, donate_argnums=(6,))  # devlint: ok
""")

_NON_DONATING = textwrap.dedent("""\
    import jax

    def get_kernel(model, dims):
        return jax.jit(step)
""")


def test_k004_donation_policy_both_directions():
    # jit donates, route says don't: the slice driver re-feeds the
    # pre-overflow carry after a frontier escalation
    diags = check_donation(_DONATING, "get_kernel",
                           donate_carry=False, route_name="fix")
    assert "K004" in codes(diags)
    # declared donation the jit never performs
    diags = check_donation(_NON_DONATING, "get_kernel",
                           donate_carry=True, route_name="fix")
    assert "K004" in codes(diags)
    # matching policy in both directions is clean
    assert check_donation(_DONATING, "get_kernel",
                          donate_carry=True, route_name="fix") == []
    assert check_donation(_NON_DONATING, "get_kernel",
                          donate_carry=False, route_name="fix") == []


def test_k004_suppressed_on_jit_line():
    diags = check_donation(_DONATING_OK, "get_kernel",
                           donate_carry=False, route_name="fix")
    assert "K004" not in codes(diags)


def test_k004_missing_getter_is_warning():
    diags = check_donation(_NON_DONATING, "get_missing",
                           donate_carry=False, route_name="fix")
    assert [d.severity for d in diags] == ["warning"]


def test_k005_dynamic_shape_fails_staging():
    import types

    def f(x):
        return jnp.nonzero(x)[0]  # data-dependent output shape

    route = types.SimpleNamespace(
        name="fix", build=lambda model, dims: (
            f, (jnp.arange(8, dtype=jnp.int32),)))
    model, dims = representative_dims()
    jaxpr, diags = stage_route(route, model, dims)
    assert jaxpr is None
    assert codes(diags) == {"K005"}


def test_k006_transfer_in_scan_body():
    def f(x):
        def body(c, _):
            jax.debug.print("level {}", c)
            return c + 1, None
        out, _ = jax.lax.scan(body, x, None, length=4)
        return out

    jaxpr = jax.make_jaxpr(f)(jnp.int32(0))
    assert "K006" in codes(lint_jaxpr(jaxpr, route_name="fix"))


# ---------------------------------------------------------------------------
# K007 — the static cache-key model
# ---------------------------------------------------------------------------


def _full_solo_args(**over):
    args = {"engine": "xla", "frontier": 8, "n_det_pad": 64,
            "n_crash_pad": 32, "window": 32, "k": 2,
            "masked": False, "masked_crash": False, "dedup": False,
            "vt": 8, "model": "register", "model_init": 0,
            "model_width": 1}
    args.update(over)
    return args


def test_k007_full_coordinate_set_passes_strict():
    assert check_span_args(_full_solo_args()) == []
    batch = _full_solo_args(batch=256)
    assert span_kind_for_args(batch) == "batch"
    assert check_span_args(batch) == []
    sharded = _full_solo_args(batch=32, sharded=True, shards=8)
    assert span_kind_for_args(sharded) == "batch-sharded"
    assert check_span_args(sharded) == []


def test_k007_missing_coord_fails_strict():
    args = _full_solo_args()
    del args["masked_crash"]
    fails = check_span_args(args)
    assert fails and "masked_crash" in fails[0]


def test_k007_legacy_generation_needs_non_strict():
    legacy = {"engine": "xla", "frontier": 8, "n_det_pad": 64}
    assert check_span_args(legacy, strict=True)
    assert check_span_args(legacy, strict=False) == []


def test_k007_domain_violation_fails_even_with_full_keys():
    fails = check_span_args(_full_solo_args(window=17))
    assert any("window" in f for f in fails)
    fails = check_span_args(_full_solo_args(engine="cuda"))
    assert any("engine" in f for f in fails)


def test_k007_runtime_coords_are_excluded():
    args = _full_solo_args(cache="miss", persistent_cache=False)
    assert check_span_args(args) == []


# ---------------------------------------------------------------------------
# warmup loader reports K007 instead of silently defaulting
# ---------------------------------------------------------------------------


def test_warmup_trace_loader_reports_k007(tmp_path):
    from jepsen_tpu.fleet.warmup import load_shapes

    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "device.compile", "args": {
            "frontier": 8}},  # fits no documented generation
    ]}))
    with pytest.raises(ValueError, match="K007"):
        load_shapes(str(trace))
    diags = []
    shapes = load_shapes(str(trace), diagnostics=diags)
    assert shapes == []
    assert codes(diags) == {"K007"}


def test_warmup_manifest_validates_against_static_model(tmp_path):
    from jepsen_tpu.fleet.warmup import load_shapes

    man = tmp_path / "shapes.json"
    man.write_text(json.dumps({"shapes": [
        {"n_det_pad": 64, "frontier": 8, "window": 17}]}))
    with pytest.raises(ValueError, match="window"):
        load_shapes(str(man))


def test_warm_boot_refuses_drifted_shapes():
    from jepsen_tpu.fleet.warmup import WarmShape, warm_boot

    rep = warm_boot([WarmShape(n_det_pad=64, frontier=8, window=17)])
    assert rep["verified"] is False
    assert rep["shapes"] == 0
    assert rep["k007"]


# ---------------------------------------------------------------------------
# T-code fixtures (lint_thread_tier over a tmp file)
# ---------------------------------------------------------------------------


def _tlint(tmp_path, source):
    p = tmp_path / "fixture.py"
    p.write_text(textwrap.dedent(source))
    findings = lint_thread_tier([p])
    return [d for ds in findings.values() for d in ds]


def test_t001_unlocked_rmw_from_thread(tmp_path):
    diags = _tlint(tmp_path, """\
        import threading

        COUNT = 0

        def worker():
            global COUNT
            COUNT += 1

        def start():
            threading.Thread(target=worker).start()
    """)
    assert codes(diags) == {"T001"}


def test_t001_lock_or_suppression_clears_it(tmp_path):
    diags = _tlint(tmp_path, """\
        import threading

        COUNT = 0
        LOCK = threading.Lock()

        def worker():
            global COUNT
            with LOCK:
                COUNT += 1

        def start():
            threading.Thread(target=worker).start()
    """)
    assert diags == []
    diags = _tlint(tmp_path, """\
        import threading

        COUNT = 0

        def worker():
            global COUNT
            COUNT += 1  # threadlint: ok — fixture

        def start():
            threading.Thread(target=worker).start()
    """)
    assert diags == []


def test_t001_check_then_act(tmp_path):
    diags = _tlint(tmp_path, """\
        import threading

        class Box:
            def worker(self):
                if self.slot is None:
                    self.slot = 1

            def start(self):
                threading.Thread(target=self.worker).start()
    """)
    assert codes(diags) == {"T001"}


def test_t002_bare_acquire_without_finally(tmp_path):
    diags = _tlint(tmp_path, """\
        import threading

        LOCK = threading.Lock()

        def worker():
            LOCK.acquire()
            step()
            LOCK.release()

        def start():
            threading.Thread(target=worker).start()
    """)
    assert "T002" in codes(diags)
    diags = _tlint(tmp_path, """\
        import threading

        LOCK = threading.Lock()

        def worker():
            LOCK.acquire()
            try:
                step()
            finally:
                LOCK.release()

        def start():
            threading.Thread(target=worker).start()
    """)
    assert "T002" not in codes(diags)


def test_t003_flock_write_without_fsync(tmp_path):
    diags = _tlint(tmp_path, """\
        import threading

        def worker(fh):
            with _locked():
                fh.write("entry")

        def start():
            threading.Thread(target=worker).start()
    """)
    assert "T003" in codes(diags)
    diags = _tlint(tmp_path, """\
        import os
        import threading

        def worker(fh):
            with _locked():
                fh.write("entry")
                os.fsync(fh.fileno())

        def start():
            threading.Thread(target=worker).start()
    """)
    assert "T003" not in codes(diags)


def test_t004_span_without_run_pin(tmp_path):
    diags = _tlint(tmp_path, """\
        import threading

        def worker():
            with obs.span("prep", keys=3):
                step()

        def start():
            threading.Thread(target=worker).start()
    """)
    assert "T004" in codes(diags)
    diags = _tlint(tmp_path, """\
        import threading

        def worker(run_pin):
            with obs.span("prep", run=run_pin, keys=3):
                step()

        def start():
            threading.Thread(target=worker).start()
    """)
    assert "T004" not in codes(diags)


def test_caller_holds_lock_fixpoint(tmp_path):
    """A helper whose every in-tier call site holds a lock is as
    protected as one taking the lock itself (stream/service.py's
    _handle pattern)."""
    diags = _tlint(tmp_path, """\
        import threading

        class Svc:
            def _apply(self):
                self.n += 1

            def handle_line(self):
                with self._lock:
                    self._apply()

            def start(self):
                threading.Thread(target=self.handle_line).start()
    """)
    assert diags == []


# ---------------------------------------------------------------------------
# regression pins for the defects the lints actually found
# ---------------------------------------------------------------------------


def test_admission_decide_is_serialized():
    """fleet/admission.py T001 fix: decide() runs on router handler
    threads; the scale-signal max-updates and the spawn damper
    check-then-act must hold the controller lock."""
    import threading

    from jepsen_tpu.fleet.admission import AdmissionController

    ctl = AdmissionController()
    assert isinstance(ctl._lock, type(threading.Lock()))
    n = 64
    sigs = [{"ops_total": float(i), "shed_total": 0.0}
            for i in range(n)]
    threads = [threading.Thread(
        target=lambda s=s: [ctl.decide(s) for _ in range(10)])
        for s in sigs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # the max-update under contention must equal the true max, and
    # every decision must have been counted
    assert ctl._last_ops == float(n - 1)
    assert sum(ctl.decisions.values()) == n * 10


def test_env_knob_cache_clears_before_force_drop(monkeypatch):
    """obs trace/telemetry T001 fix: enable(None) must leave no stale
    cached env read visible after the force is gone."""
    from jepsen_tpu.obs import trace

    monkeypatch.delenv("JEPSEN_TPU_TRACE", raising=False)
    trace.enable(True)
    assert trace.enabled() is True
    trace.enable(None)
    assert trace.enabled() is False  # re-read, not stale cache
