"""Streaming incremental checker (jepsen_tpu/stream/).

The subsystem's contract is absolute: a history streamed op-by-op must
reach EXACTLY the post-hoc verdict — same valid flag, audit-clean
certificate — while surfacing invalidity before the stream ends
whenever the violation is not in the final segment.  The differential
fuzz here (200+ histories, :info crashes, never-quiescing workloads,
mid-stream invalidations, multi-register cells) is the enforcement;
the targeted tests pin the online-cut semantics, the device fold, the
cache reuse, the runner/abort wiring, the plan gate, and the service
mode.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import urllib.request
from dataclasses import replace

import pytest

from jepsen_tpu.history import encode_ops, info_op, invoke_op, ok_op
from jepsen_tpu.models import (cas_register, multi_register, mutex,
                               register)
from jepsen_tpu.stream import StreamChecker
from jepsen_tpu.synth import (corrupt_read, flip_read, register_history,
                              sim_mutex_history, sim_register_history)


def _direct(seq, model):
    from jepsen_tpu.checker.seq import check_opseq

    return check_opseq(seq, model)


def _stream(h, model, **kw):
    """Stream op-by-op; returns (final result, event index of the first
    mid-stream invalid status, checker)."""
    sc = StreamChecker(model, **kw)
    invalid_at = None
    for i, op in enumerate(h):
        sc.ingest(op)
        if invalid_at is None and sc.verdict()["status"] == "invalid":
            invalid_at = i
    return sc.finalize(), invalid_at, sc


def sim_multireg_history(rng, width=3, n_procs=4, n_ops=30,
                         crash_p=0.05):
    state = {k: 0 for k in range(width)}
    h, pending, crashed = [], {}, set()
    done = 0
    while done < n_ops or pending:
        live = [p for p in range(n_procs) if p not in crashed]
        if not live:
            break
        p = rng.choice(live)
        if p in pending:
            f, k, v = pending.pop(p)
            if crash_p and rng.random() < crash_p:
                if rng.random() < 0.5 and f == "write":
                    state[k] = v
                crashed.add(p)
                h.append(info_op(p, f, (k, v if f == "write" else None)))
                continue
            if f == "read":
                h.append(ok_op(p, f, (k, state[k])))
            else:
                state[k] = v
                h.append(ok_op(p, f, (k, v)))
        elif done < n_ops:
            f = rng.choice(["read", "write"])
            k = rng.randrange(width)
            v = None if f == "read" else rng.randrange(5)
            h.append(invoke_op(p, f, (k, v)))
            pending[p] = (f, k, v)
            done += 1
    return h


def _flip_mr_read(rng, h):
    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "read"]
    if not idx:
        return h
    h = list(h)
    i = rng.choice(idx)
    k, v = h[i].value
    h[i] = replace(h[i], value=(k, (v or 0) + 7))
    return h


# ---------------------------------------------------------------------------
# differential fuzz: 200+ histories streamed vs checked post-hoc
# ---------------------------------------------------------------------------


def _fuzz_cases():
    """(label, model, history) for 215 event-level histories: crashed
    (:info) ops, never-quiescing overlap, quiescent bursts with
    mid-stream invalidations, mutex, and multi-register cells."""
    cases = []
    for i in range(70):  # cas-register with crashes, 1/3 corrupted
        rng = random.Random(i)
        m = cas_register()
        h = sim_register_history(rng, n_procs=4, n_ops=24, crash_p=0.1,
                                 cas=(i % 2 == 0))
        if i % 3 == 0:
            h = flip_read(rng, h)
        cases.append(("cas", m, h))
    for i in range(45):  # quiescent bursts: the online-cut fast path
        rng = random.Random(2000 + i)
        m = cas_register()
        h = register_history(rng, n_ops=36, n_procs=4, overlap=3,
                             quiesce_every=6, crash_p=0.03,
                             max_crashes=2, n_values=4, cas=False)
        if i % 2 == 0:
            h = flip_read(rng, h)
        cases.append(("burst", m, h))
    for i in range(30):  # never-quiescing: everything lands in the tail
        rng = random.Random(5000 + i)
        m = cas_register()
        h = sim_register_history(rng, n_procs=6, n_ops=20, crash_p=0.05)
        if i % 3 == 0:
            h = flip_read(rng, h)
        cases.append(("tail", m, h))
    for i in range(35):  # mutex with crashed acquires/releases
        rng = random.Random(3000 + i)
        m = mutex()
        h = sim_mutex_history(rng, n_ops=24, n_procs=4, crash_p=0.06)
        cases.append(("mutex", m, h))
    for i in range(35):  # multi-register: the locality path
        rng = random.Random(4000 + i)
        m = multi_register(3)
        h = sim_multireg_history(rng)
        if i % 3 == 0:
            h = _flip_mr_read(rng, h)
        cases.append(("multireg", m, h))
    assert len(cases) >= 200
    return cases


def test_differential_fuzz_streamed_vs_posthoc():
    """Every streamed final verdict equals the direct engine's, every
    certificate audits clean, and a mid-stream invalid status is never
    a false alarm."""
    from jepsen_tpu.analyze.audit import audit

    divergences = []
    early = 0
    methods: set = set()
    for label, m, h in _fuzz_cases():
        seq = encode_ops(h, m.f_codes)
        d = _direct(seq, m)["valid"]
        r, invalid_at, sc = _stream(h, m)
        methods.update(r["stream"]["methods"])
        if r["valid"] != d:
            divergences.append((label, d, r["valid"], r["stream"]))
            continue
        a = audit(sc.seq(), m, r)
        if not a["ok"]:
            divergences.append((label, "audit", a["codes"],
                                [str(x) for x in a["diagnostics"][:2]]))
        if invalid_at is not None:
            # an online invalid is FINAL: it must match the verdict
            assert r["valid"] is False, (label, invalid_at, r)
            if invalid_at < len(h) - 1:
                early += 1
    assert not divergences, divergences[:5]
    # the fuzz must actually exercise the streaming machinery, and
    # invalid verdicts must actually surface before streams end
    assert {"quiescence", "sub-search", "key-partition"} <= methods, \
        methods
    assert early >= 10, early


def test_streamed_equals_decomposed_engine():
    """Bit-identical to ``check_opseq_decomposed`` (the acceptance
    criterion's reference engine) on a stride of the corpus."""
    from jepsen_tpu.decompose.engine import check_opseq_decomposed

    for label, m, h in _fuzz_cases()[::7]:
        seq = encode_ops(h, m.f_codes)
        dec = check_opseq_decomposed(
            seq, m, direct=lambda s, m=m: _direct(s, m))
        r, _at, _sc = _stream(h, m)
        assert r["valid"] == dec["valid"], (label, dec["valid"], r)


def test_early_invalid_surfaces_before_stream_end():
    """A violation at op k << n flips the live verdict to ``invalid``
    before ingest of op n completes (the acceptance criterion), with
    the bulk of the stream still to come."""
    rng = random.Random(42)
    m = register(0)
    h = register_history(rng, n_ops=300, n_procs=5, overlap=4,
                         quiesce_every=8, n_values=5, cas=False)
    h = corrupt_read(rng, h, at=0.1)
    seq = encode_ops(h, m.f_codes)
    assert _direct(seq, m)["valid"] is False
    r, invalid_at, _sc = _stream(h, m)
    assert r["valid"] is False
    assert invalid_at is not None and invalid_at < len(h) - 1
    # the violation sits ~10% in; the invalid verdict must not wait for
    # the tail of the stream
    assert invalid_at < len(h) // 2, (invalid_at, len(h))
    assert r["stream"]["invalid_event"] == invalid_at


def test_never_quiescing_stream_stays_open_then_decides():
    """High-overlap workloads never cut: the provisional verdict stays
    ``open`` the whole stream and finalize still decides exactly."""
    rng = random.Random(7)
    m = cas_register()
    # overlap 4 is refilled after every completion, so the pending set
    # never empties mid-stream: no quiescent point ever exists
    h = register_history(rng, n_ops=24, n_procs=6, overlap=4,
                         n_values=4)
    sc = StreamChecker(m)
    for op in h:
        sc.ingest(op)
        assert sc.verdict()["status"] == "open"
    r = sc.finalize()
    assert r["valid"] == _direct(encode_ops(h, m.f_codes), m)["valid"]
    assert r["stream"]["segments"] == 1


def test_provisional_status_progression():
    rng = random.Random(9)
    m = cas_register()
    h = register_history(rng, n_ops=30, n_procs=3, overlap=2,
                         quiesce_every=5, crash_p=0.0, n_values=3,
                         cas=False)
    sc = StreamChecker(m)
    seen = []
    for op in h:
        sc.ingest(op)
        s = sc.verdict()["status"]
        if not seen or seen[-1] != s:
            seen.append(s)
    assert seen[0] == "open"
    assert "valid-so-far" in seen
    r = sc.finalize()
    assert r["valid"] is True


# ---------------------------------------------------------------------------
# independent [k v] workloads (the atomdemo / jepsen.independent shape)
# ---------------------------------------------------------------------------


def sim_indep_history(rng, n_keys=3, n_procs=4, n_ops=40, crash_p=0.05):
    """Valid-by-construction independent CAS registers, KV-wrapped as
    ``independent.concurrent_generator`` emits them."""
    from jepsen_tpu import independent
    from jepsen_tpu.history import fail_op

    state = {k: 0 for k in range(n_keys)}
    h, pending, crashed = [], {}, set()
    done = 0
    while done < n_ops or pending:
        live = [p for p in range(n_procs) if p not in crashed]
        if not live:
            break
        p = rng.choice(live)
        if p in pending:
            f, k, v = pending.pop(p)
            if crash_p and rng.random() < crash_p:
                if rng.random() < 0.5:
                    if f == "write":
                        state[k] = v
                    elif f == "cas" and state[k] == v[0]:
                        state[k] = v[1]
                crashed.add(p)
                h.append(info_op(p, f, independent.tuple_(
                    k, v if f != "read" else None)))
                continue
            if f == "read":
                h.append(ok_op(p, f, independent.tuple_(k, state[k])))
            elif f == "write":
                state[k] = v
                h.append(ok_op(p, f, independent.tuple_(k, v)))
            elif state[k] == v[0]:
                state[k] = v[1]
                h.append(ok_op(p, f, independent.tuple_(k, v)))
            else:
                h.append(fail_op(p, f, independent.tuple_(k, v)))
        elif done < n_ops:
            f = rng.choice(["read", "write", "cas"])
            k = rng.randrange(n_keys)
            v = (None if f == "read" else rng.randrange(5)
                 if f == "write" else (rng.randrange(5),
                                       rng.randrange(5)))
            h.append(invoke_op(p, f, independent.tuple_(k, v)))
            pending[p] = (f, k, v)
            done += 1
    return h


def _flip_kv_read(rng, h):
    from jepsen_tpu import independent

    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "read"]
    if not idx:
        return h
    h = list(h)
    i = rng.choice(idx)
    kv = h[i].value
    h[i] = replace(h[i], value=independent.tuple_(kv.key,
                                                  (kv.value or 0) + 7))
    return h


def test_independent_streams_match_posthoc_per_key():
    """An independent [k v] history (the atomdemo shape) demuxes into
    per-key cells under the test model: the streamed overall verdict
    AND every per-key verdict match independent.checker's post-hoc
    split, per-key certificates audit clean, and corrupted keys flip
    the live verdict mid-stream."""
    from jepsen_tpu import independent
    from jepsen_tpu.analyze.audit import audit

    m = cas_register(0)
    early = 0
    for i in range(40):
        rng = random.Random(9000 + i)
        h = sim_indep_history(rng)
        if i % 3 == 0:
            h = _flip_kv_read(rng, h)
        ks = independent.history_keys(h)
        ref = {k: _direct(encode_ops(independent.subhistory(k, h),
                                     m.f_codes), m)["valid"]
               for k in ks}
        r, invalid_at, sc = _stream(h, m)
        assert r["valid"] == (False if False in ref.values()
                              else True), (i, ref, r)
        assert "independent" in r["stream"]["methods"]
        if invalid_at is not None and invalid_at < len(h) - 1:
            early += 1
        for k in ks:
            cr = sc.cell_results[k]
            assert cr["valid"] == ref[k], (i, k, ref)
            cert = {"valid": cr["valid"]}
            if cr["linearization"] is not None:
                cert["linearization"] = cr["linearization"]
            elif cr["final_ops"] is not None:
                cert["final_ops"] = cr["final_ops"]
            else:
                cert["witness_dropped"] = cert["frontier_dropped"] = \
                    "per-key drop"
            a = audit(sc.cell_seq(k), m, cert)
            assert a["ok"], (i, k, a["codes"])
        # the global result keeps the certificate contract (per-key
        # evidence under `independent`, explicit drops at the top)
        assert audit(sc.seq(), m, r)["ok"]
        assert set(r["independent"]) == {str(k) for k in ks}
    assert early >= 5, early


def test_independent_stream_in_core_run(monkeypatch, tmp_path):
    """The flagship atomdemo suite shape end-to-end through core.run
    with streaming on: streamed verdict agrees with the independent
    post-hoc checker."""
    import threading as _t

    from jepsen_tpu import (core, fixtures, generator as gen,
                            independent)
    from jepsen_tpu.checker import linearizable as lin

    monkeypatch.setenv("JEPSEN_TPU_STREAM", "1")
    registers: dict = {}
    lock = _t.Lock()

    from jepsen_tpu import client as client_mod

    class MapClient(client_mod.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op):
            k, v = op.value.key, op.value.value
            with lock:
                reg = registers.setdefault(k, fixtures.AtomRegister(0))
            if op.f == "write":
                reg.write(v)
                return replace(op, type="ok")
            return replace(op, type="ok",
                           value=independent.tuple_(k, reg.read()))

    test = fixtures.noop_test() | {
        "name": None,
        "client": MapClient(),
        "model": cas_register(0),
        "checker": independent.checker(lin.linearizable()),
        "concurrency": 4,
        "generator": gen.clients(independent.concurrent_generator(
            2, range(4),
            lambda k: gen.limit(10, gen.mix([
                {"type": "invoke", "f": "read", "value": None},
                lambda t, p: {"type": "invoke", "f": "write",
                              "value": random.randrange(5)},
            ])))),
    }
    test = core.run(test)
    assert test["results"]["valid"] is True
    assert test["results"]["stream"]["valid"] is True
    st = test["results"]["stream"]["stream"]
    assert "independent" in st["methods"]
    assert st["cells"] == 4


# ---------------------------------------------------------------------------
# online cuts vs post-hoc cuts
# ---------------------------------------------------------------------------


def test_online_cuts_match_posthoc_on_failfree_histories():
    """Without :fail ops an online cut exists exactly where the offline
    cutter puts one, so streamed segment counts equal the plan's
    prediction (with fails, online cuts are a sound coarsening)."""
    from jepsen_tpu.analyze.plan import stream_plan

    m = register(0)
    for i in range(10):
        rng = random.Random(600 + i)
        h = register_history(rng, n_ops=40, n_procs=4, overlap=3,
                             quiesce_every=7, crash_p=0.0, n_values=4,
                             cas=False)  # cas=False: no :fail ops
        seq = encode_ops(h, m.f_codes)
        plan = stream_plan(seq, m)
        r, _at, _sc = _stream(h, m)
        assert r["stream"]["segments"] == plan["segments"], (i, plan)
        assert plan["applies"] is True


# ---------------------------------------------------------------------------
# cache reuse across streams (satellite: counters measured, not inferred)
# ---------------------------------------------------------------------------


def test_cache_reuse_across_streamed_runs(tmp_path):
    from jepsen_tpu.decompose.cache import VerdictCache

    m = cas_register()
    rng = random.Random(77)
    h = register_history(rng, n_ops=44, n_procs=3, overlap=1,
                         crash_p=0.0, n_values=3)
    path = str(tmp_path / "v.jsonl")
    r1, _a, _s = _stream(h, m, cache=VerdictCache(path))
    assert r1["stream"]["cache_inserts"] > 0
    # same canonical shapes (processes renamed), cold cache object:
    # zero search work, every segment a hit
    h2 = [replace(op, process=op.process + 10) for op in h]
    r2, _a, _s = _stream(h2, m, cache=VerdictCache(path))
    assert r2["valid"] == r1["valid"]
    assert r2["configs"] == 0
    assert r2["stream"]["cache_hits"] >= r1["stream"]["cache_inserts"] - 2


def test_shared_cache_counters_are_per_run():
    """Concurrent streams share one VerdictCache (the service / fleet
    mode): constructing or running a second checker must neither zero
    nor inflate the first one's per-run counters."""
    from jepsen_tpu.decompose.cache import VerdictCache

    m = cas_register()
    rng = random.Random(31)
    h = register_history(rng, n_ops=30, n_procs=3, overlap=1,
                         crash_p=0.0, n_values=3)
    cache = VerdictCache()
    sc1 = StreamChecker(m, cache=cache)
    for op in h[:len(h) // 2]:
        sc1.ingest(op)
    # a second run opens mid-stream on the SAME cache object
    sc2 = StreamChecker(m, cache=cache)
    for op in h[len(h) // 2:]:
        sc1.ingest(op)
    r1 = sc1.finalize()
    # run 2's CONSTRUCTION happened mid-stream: run 1 still reports
    # its own FULL profile — exactly one cache lookup per segment
    # (folds + non-empty finals), nothing reset, nothing leaked in
    # (intra-run hits on repeated tiny segments are run 1's own)
    assert r1["stream"]["cache_hits"] + r1["stream"]["cache_misses"] \
        == r1["stream"]["segments"]
    assert r1["stream"]["cache_inserts"] > 0
    # run 2 streams the same content warm: every lookup hits, zero
    # search work — and its counters are its own, not the union
    for op in h:
        sc2.ingest(op)
    r2 = sc2.finalize()
    assert r2["valid"] == r1["valid"]
    assert r2["configs"] == 0
    assert r2["stream"]["cache_misses"] == 0
    assert r2["stream"]["cache_hits"] == r2["stream"]["segments"]


def test_engine_results_carry_cache_insert_counters(tmp_path):
    """The decomposed engine's results now expose hit/miss/insert
    counters per run (satellite: reuse measured, not inferred)."""
    from jepsen_tpu.decompose.cache import VerdictCache
    from jepsen_tpu.decompose.engine import check_opseq_decomposed

    m = cas_register()
    rng = random.Random(5)
    h = sim_register_history(rng, n_procs=3, n_ops=20)
    seq = encode_ops(h, m.f_codes)
    cache = VerdictCache(str(tmp_path / "v.jsonl"))
    r = check_opseq_decomposed(seq, m, cache=cache,
                               direct=lambda s: _direct(s, m))
    assert r["decompose"]["cache_inserts"] == cache.inserts > 0
    assert "cache_hits" in r["decompose"]


def test_segment_and_final_cache_keys_do_not_collide(tmp_path):
    """A mid-stream fold's state-set entry and a final segment's
    verdict entry for the SAME canonical content must not overwrite
    each other (the _skey kind namespace)."""
    from jepsen_tpu.decompose.engine import _skey

    assert _skey(b"x") != _skey(b"x", b"fin")


# ---------------------------------------------------------------------------
# device fold
# ---------------------------------------------------------------------------


def test_device_fold_states_matches_host_fold():
    from jepsen_tpu.decompose.engine import segment_states
    from jepsen_tpu.decompose.partition import (quiescence_segments,
                                                subseq)
    from jepsen_tpu.stream.device import device_fold_states

    m = register(0)
    rng = random.Random(5)
    h = register_history(rng, n_ops=48, n_procs=6, overlap=5,
                         quiesce_every=8, unique_writes=True, cas=False)
    seq = encode_ops(h, m.f_codes)
    segs = quiescence_segments(seq)
    assert len(segs) >= 3
    states = {tuple(m.init)}
    checked = 0
    for rows in segs[:-1]:
        ss = subseq(seq, rows)
        host = segment_states(ss, m, states)
        dev = device_fold_states(ss, m, states)
        if dev is not None:
            assert dev[0] == host
            checked += 1
        states = host
    assert checked >= 2


def test_forced_device_routing_is_verdict_identical():
    m = register(0)
    rng = random.Random(6)
    h = register_history(rng, n_ops=40, n_procs=5, overlap=4,
                         quiesce_every=8, n_values=6, cas=False)
    seq = encode_ops(h, m.f_codes)
    d = _direct(seq, m)["valid"]
    # host_fold_max=0 routes every eligible fold to the device batch
    r, _at, _sc = _stream(h, m, host_fold_max=0)
    assert r["valid"] == d
    assert r["stream"]["routes"]["device"] >= 1
    assert "device" in r["stream"]["methods"]
    # device-folded segments drop chains, never fabricate them
    if r["valid"] is True:
        assert "linearization" in r or "witness_dropped" in r


def test_async_folds_reach_the_same_verdict():
    m = cas_register()
    for i in range(6):
        rng = random.Random(800 + i)
        h = register_history(rng, n_ops=36, n_procs=4, overlap=2,
                             quiesce_every=6, crash_p=0.05,
                             max_crashes=2, n_values=4)
        if i % 2 == 0:
            h = flip_read(rng, h)
        seq = encode_ops(h, m.f_codes)
        sc = StreamChecker(m, async_folds=True)
        for op in h:
            sc.ingest(op)
        r = sc.finalize()
        assert r["valid"] == _direct(seq, m)["valid"], i


# ---------------------------------------------------------------------------
# the plan gate (satellite: predictor and engine share one rule)
# ---------------------------------------------------------------------------


def test_stream_plan_in_explain_and_route_rule():
    from jepsen_tpu.analyze.plan import (STREAM_HOST_FOLD_MAX, explain,
                                         segment_fold_route)

    m = register(0)
    rng = random.Random(3)
    h = register_history(rng, n_ops=40, n_procs=4, overlap=3,
                         quiesce_every=6, cas=False)
    plan = explain(h, m)
    st = plan["streaming"]
    assert st["applies"] is True and st["closed_segments"] >= 2
    assert st["ttfv_rows"] is not None
    assert st["device_eligible"] is True
    # the routing rule: device only for register families past the cap
    assert segment_fold_route(8, 4, m) == "host"
    assert segment_fold_route(8, 4, m, host_fold_max=0) == "device"
    assert segment_fold_route(10**6, 30, mutex()) == "host"
    assert segment_fold_route(10**6, 30, m) == "device"
    assert STREAM_HOST_FOLD_MAX > 0


def test_stream_plan_never_quiescing():
    from jepsen_tpu.analyze.plan import stream_plan

    m = cas_register()
    rng = random.Random(8)
    h = register_history(rng, n_ops=24, n_procs=6, overlap=4,
                         n_values=4)
    st = stream_plan(encode_ops(h, m.f_codes), m)
    assert st["closed_segments"] == 0 and st["applies"] is False


# ---------------------------------------------------------------------------
# runner wiring + the abort-path fix
# ---------------------------------------------------------------------------


def _cas_test(state, store_base=None, **over):
    from jepsen_tpu import fixtures, generator as gen
    from jepsen_tpu.checker import linearizable as lin

    return __import__("jepsen_tpu.fixtures", fromlist=["noop_test"]) \
        .noop_test() | {
        "name": None,
        "db": fixtures.atom_db(state),
        "client": fixtures.atom_client(state),
        "model": cas_register(0),
        "checker": lin.linearizable(),
        "generator": gen.clients(gen.limit(
            30, {"type": "invoke", "f": "read", "value": None})),
        "concurrency": 3,
    } | over


def test_core_run_streams_and_threads_results(monkeypatch):
    from jepsen_tpu import core, fixtures

    monkeypatch.setenv("JEPSEN_TPU_STREAM", "1")
    state = fixtures.AtomRegister()
    test = core.run(_cas_test(state))
    assert test["results"]["valid"] is True
    s = test["results"]["stream"]
    assert s["valid"] is True
    assert s["stream"]["events"] == len(test["history"])
    assert test["stream_results"]["valid"] is True


def test_core_run_abort_still_yields_prefix_verdict(tmp_path,
                                                    monkeypatch):
    """Satellite fix: a crashed run must flush + finalize the op sink —
    the prefix it recorded still gets a verdict, persisted to the
    store and attached to the raised error."""
    from jepsen_tpu import core, fixtures, generator as gen

    monkeypatch.setenv("JEPSEN_TPU_STREAM", "1")

    class ExplodingGen(gen.Generator):
        def __init__(self, n):
            self.n = n
            self.lock = threading.Lock()

        def op(self, test, process):
            with self.lock:
                self.n -= 1
                if self.n < 0:
                    raise RuntimeError("generator exploded!")
            return {"type": "invoke", "f": "read", "value": None}

    state = fixtures.AtomRegister()
    test = _cas_test(state, name="abort-stream",
                     store_base=str(tmp_path / "store"),
                     generator=gen.clients(ExplodingGen(9)))
    test["name"] = "abort-stream"
    test["store_base"] = str(tmp_path / "store")
    with pytest.raises(RuntimeError, match="generator exploded") as ei:
        core.run(test)
    sr = ei.value.stream_results
    assert sr["aborted"] is True
    assert sr["valid"] in (True, False)
    assert sr["stream"]["stream"]["events"] > 0
    # and it reached the store, happy path or not
    import glob

    paths = glob.glob(str(tmp_path / "store" / "abort-stream" / "*"
                          / "results.json"))
    assert paths, "aborted run wrote no results.json"
    on_disk = json.load(open(paths[0]))
    assert on_disk["aborted"] is True
    assert on_disk["valid"] == sr["valid"]


def test_cli_stream_flag_sets_env(monkeypatch):
    import argparse

    from jepsen_tpu import cli

    monkeypatch.setenv("JEPSEN_TPU_STREAM", "placeholder")
    monkeypatch.delenv("JEPSEN_TPU_STREAM")
    p = argparse.ArgumentParser()
    cli.add_test_opts(p)
    opts = cli.test_opt_fn(p.parse_args(["--stream", "--dummy"]))
    assert opts["stream"] is True
    assert os.environ.get("JEPSEN_TPU_STREAM") == "1"


# ---------------------------------------------------------------------------
# web: /api/live + panels
# ---------------------------------------------------------------------------


def test_web_live_endpoint_and_panels(tmp_path):
    from jepsen_tpu import store, web

    base = str(tmp_path / "store")
    test = {"name": "livedemo", "start_time": "20260803T120000",
            "store_base": base}
    store.save_1(test, [])
    store.save_2(test, {
        "valid": True,
        "stream": {"valid": True, "engine": "stream(quiescence)",
                   "stream": {"segments": 3, "events": 40,
                              "first_verdict_event": 4,
                              "cache_hits": 2, "cache_misses": 1,
                              "cache_inserts": 3}}})
    d = os.path.join(base, "livedemo", "20260803T120000")
    with open(os.path.join(d, "live.json"), "w") as f:
        json.dump({"status": "valid-so-far", "events": 40, "rows": 20,
                   "segments_closed": 3, "checked_rows": 12}, f)

    srv = web.make_server(host="127.0.0.1", port=0, base=base)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        api = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/live/livedemo/20260803T120000"
        ).read())
        assert api["status"] == "valid-so-far"
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/files/livedemo/20260803T120000/"
        ).read().decode()
        assert "Live verdict" in page  # the polling panel
        assert "streamed" in page  # the result-panel stream row
        assert "verdict cache" in page  # hit/miss/insert counters
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/live/nosuch/run")
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# service mode (tier-1-gated smoke)
# ---------------------------------------------------------------------------


def test_service_mode_smoke():
    """``python -m jepsen_tpu.stream``: two interleaved runs over
    stdin, one valid and one invalid, final verdicts + audit clean."""
    rng = random.Random(1)
    h_ok = sim_register_history(rng, n_procs=3, n_ops=14)
    h_bad = flip_read(rng, sim_register_history(rng, n_procs=3,
                                                n_ops=14))
    lines = [json.dumps({"run": "a", "model": "cas-register"}),
             json.dumps({"run": "b", "model": "cas-register"})]
    for i in range(max(len(h_ok), len(h_bad))):
        if i < len(h_ok):
            lines.append(json.dumps({"run": "a",
                                     "op": h_ok[i].to_dict()}))
        if i < len(h_bad):
            lines.append(json.dumps({"run": "b",
                                     "op": h_bad[i].to_dict()}))
    lines += [json.dumps({"run": "a", "end": True}),
              json.dumps({"run": "b", "end": True})]
    out = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu.stream", "--audit"],
        input="\n".join(lines) + "\n", capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    finals = {}
    for ln in out.stdout.splitlines():
        d = json.loads(ln)
        assert "error" not in d, d
        if "final" in d:
            finals[d["run"]] = d["final"]
    assert finals["a"]["valid"] is True
    assert finals["b"]["valid"] is False
    for f in finals.values():
        assert f["audit"]["ok"] is True


def test_service_in_process_multiplexing_and_eof_finalize():
    """EOF finalizes every open run — the in-process twin of the
    subprocess smoke, exercising bare-op shorthand + default model."""
    from jepsen_tpu.models import cas_register as _cr
    from jepsen_tpu.stream.service import StreamService, serve_stdio

    rng = random.Random(2)
    h = sim_register_history(rng, n_procs=3, n_ops=12)
    lines = [json.dumps(op.to_dict()) for op in h]  # bare-op shorthand

    import io

    out = io.StringIO()
    serve_stdio(StreamService(model=_cr()), iter(ln + "\n"
                                                 for ln in lines), out)
    msgs = [json.loads(x) for x in out.getvalue().splitlines()]
    finals = [m for m in msgs if "final" in m]
    assert len(finals) == 1 and finals[0]["run"] == "default"
    assert finals[0]["final"]["valid"] in (True, False)


# ---------------------------------------------------------------------------
# bounded `:info` lookahead: mid-stream crash-fault detection
# ---------------------------------------------------------------------------


def _kill_shaped_history(corrupt: bool, n_tail: int = 60):
    """An acked write, a crashed (:info) write, then a long read tail —
    the campaign's kill-cell shape.  ``corrupt`` makes one tail read
    return a value no fork of the crashed op can explain."""
    h = [invoke_op(0, "write", 3), ok_op(0, "write", 3),
         invoke_op(1, "write", 4), info_op(1, "write", 4)]
    for i in range(n_tail):
        p = 2 + (i % 3)
        v = 2 if (corrupt and i == 12) else 3
        h += [invoke_op(p, "read", None), ok_op(p, "read", v)]
    return h


def test_info_lookahead_flips_verdict_mid_stream():
    """The tentpole behavior: a violation that only a crashed op's
    fork can decide flips the LIVE verdict mid-stream (bounded
    lookahead), where finalize-only mode stays silent until the end —
    and both reach the identical final verdict."""
    m = register(0)
    h = _kill_shaped_history(corrupt=True)
    r_la, at_la, _ = _stream(h, m, info_lookahead=8)
    r_off, at_off, _ = _stream(h, m, info_lookahead=0)
    assert r_la["valid"] is False and r_off["valid"] is False
    assert at_la is not None and at_la < len(h) - 1, \
        "lookahead never flipped the live verdict mid-stream"
    assert at_off is None, \
        "finalize-only mode flipped mid-stream without any cut?"
    assert r_la["stream"]["lookahead_checks"] >= 1
    assert r_off["stream"]["lookahead_checks"] == 0
    # the violating read sits at ~event 30; detection must not wait
    # for the tail
    assert at_la < len(h) - 20, (at_la, len(h))


def test_info_lookahead_no_false_alarm_on_valid_crash_history():
    """A crashed op that CAN linearize must not trip the fork check:
    the live verdict stays non-final and finalize says valid."""
    m = register(0)
    h = _kill_shaped_history(corrupt=False)
    # the tail reads 3 forever; make a later segment read the crashed
    # value 4 so the :info op must be PRESENT in one fork
    h += [invoke_op(1, "read", None), ok_op(1, "read", 4)]
    r, at, _ = _stream(h, m, info_lookahead=8)
    assert r["valid"] is True, r
    assert at is None
    assert r["stream"]["lookahead_checks"] >= 1


def test_info_lookahead_fuzz_parity_with_finalize_only():
    """The satellite fuzz: across the crash-bearing corpus, an
    aggressive lookahead horizon reaches EXACTLY the final verdicts of
    finalize-only mode (and of the direct engine), audits clean, and
    the speculative checks actually fire."""
    from jepsen_tpu.analyze.audit import audit

    fired = 0
    early_la = 0
    for label, m, h in _fuzz_cases():
        if not any(op.type == "info" for op in h):
            continue
        r_la, at_la, sc = _stream(h, m, info_lookahead=4)
        r_off, _at, _sc = _stream(h, m, info_lookahead=0)
        d = _direct(encode_ops(h, m.f_codes), m)["valid"]
        assert r_la["valid"] == r_off["valid"] == d, \
            (label, d, r_la["valid"], r_off["valid"])
        a = audit(sc.seq(), m, r_la)
        assert a["ok"], (label, a["codes"])
        fired += r_la["stream"]["lookahead_checks"]
        if at_la is not None and r_la["valid"] is False:
            early_la += 1
    assert fired >= 10, \
        f"the lookahead fuzz never exercised the fork check ({fired})"
    assert early_la >= 1


def _crashed_writer_history(n_infos, n_reads):
    """One complete write, n_infos crashed writers, n_reads reads with
    one corrupt value — invalid regardless of how the infos fork."""
    h = [invoke_op(0, "write", 3), ok_op(0, "write", 3)]
    for j in range(n_infos):
        p = 10 + j
        h += [invoke_op(p, "write", 4), info_op(p, "write", 4)]
    for i in range(n_reads):
        p = 2 + (i % 3)
        h += [invoke_op(p, "read", None),
              ok_op(p, "read", 2 if i == 5 else 3)]
    return h


def test_info_lookahead_respects_fork_budget():
    """The speculative fork check is gated by a COST budget (pending
    :info count x open-segment rows, analyze.plan.info_fork_budget),
    not a flat info cap: past the budget the check is skipped and the
    verdict still lands at finalize; under it, a narrow segment
    affords more pending infos than the old flat cap of 6."""
    from jepsen_tpu.analyze.plan import (STREAM_INFO_FORK_BUDGET,
                                         STREAM_INFO_FORK_MAX,
                                         info_fork_cost)

    m = register(0)
    # 20 crashed writers: the cost at the first lookahead trigger
    # (20 infos over a ~28-row open segment) already blows the budget
    n_infos = 20
    assert info_fork_cost(n_infos, n_infos + 8) \
        > STREAM_INFO_FORK_BUDGET
    h = _crashed_writer_history(n_infos, 40)
    r, _at, _ = _stream(h, m, info_lookahead=8)
    assert r["stream"]["lookahead_checks"] == 0
    assert r["valid"] is False  # finalize still decides exactly
    d = _direct(encode_ops(h, m.f_codes), m)["valid"]
    assert d is False

    # one past the old flat cap, but the narrow open segment keeps the
    # cost under budget: the fork now RUNS where it used to be capped
    h = _crashed_writer_history(STREAM_INFO_FORK_MAX + 1, 40)
    r, _at, _ = _stream(h, m, info_lookahead=8)
    assert r["stream"]["lookahead_checks"] >= 1
    assert r["valid"] is False
    assert _direct(encode_ops(h, m.f_codes), m)["valid"] is False


def test_stream_plan_reports_info_lookahead_gate():
    """analyze.plan.stream_plan predicts the lookahead route with the
    same primitives the checker executes: horizon, fork cap, crashed
    cells, and the speculative-check cadence."""
    from jepsen_tpu.analyze.plan import (STREAM_INFO_FORK_BUDGET,
                                         STREAM_INFO_FORK_HARD_MAX,
                                         STREAM_INFO_FORK_MAX,
                                         STREAM_INFO_LOOKAHEAD,
                                         info_fork_budget,
                                         info_fork_gate, stream_plan)

    assert info_fork_gate(1) and info_fork_gate(STREAM_INFO_FORK_MAX)
    assert not info_fork_gate(0)
    assert not info_fork_gate(STREAM_INFO_FORK_MAX + 1)

    # the cost budget: width-scaled, flat-cap-compatible at the
    # 64-row characteristic width, hard-capped on infos alone
    assert info_fork_budget(1, 10)
    assert not info_fork_budget(0, 10)
    assert info_fork_budget(STREAM_INFO_FORK_MAX, 63)
    assert not info_fork_budget(STREAM_INFO_FORK_MAX + 1,
                                STREAM_INFO_FORK_BUDGET)
    assert info_fork_budget(STREAM_INFO_FORK_MAX + 4, 8)  # narrow
    assert not info_fork_budget(STREAM_INFO_FORK_HARD_MAX + 1, 0)

    m = register(0)
    h = _kill_shaped_history(corrupt=False)
    seq = encode_ops(h, m.f_codes)
    sp = stream_plan(seq, m)
    la = sp["info_lookahead"]
    assert la["horizon"] == STREAM_INFO_LOOKAHEAD
    assert la["fork_max"] == STREAM_INFO_FORK_MAX
    assert la["fork_budget"] == STREAM_INFO_FORK_BUDGET
    assert la["fork_cost_max"] >= 1
    assert la["crashed_cells"] == 1
    assert la["info_rows"] == 1
    assert la["forkable"] is True
    assert la["speculative_checks"] \
        == 61 // STREAM_INFO_LOOKAHEAD
    # a crash-free history predicts no speculative work
    h2 = [op for op in _kill_shaped_history(corrupt=False)
          if op.process != 1]
    sp2 = stream_plan(encode_ops(h2, m.f_codes), m, info_lookahead=8)
    assert sp2["info_lookahead"]["crashed_cells"] == 0
    assert sp2["info_lookahead"]["speculative_checks"] == 0
    assert sp2["info_lookahead"]["horizon"] == 8
