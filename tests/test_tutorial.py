"""Doc-sync tests: the tutorial's code paths must actually run.

Each test mirrors a docs/tutorial chapter's snippets against the real
APIs (same calls, same argument shapes) so the tutorial cannot drift
from the framework.  Kept fast: dummy transport, in-process fixtures,
tiny op counts.
"""

import random

from jepsen_tpu import (checker as checker_mod, cli, core, fixtures,
                        generator as gen, independent)
from jepsen_tpu.checker import linearizable as lin, timeline
from jepsen_tpu.models import cas_register


def test_ch1_scaffold_noop_runs(tmp_path):
    """Chapter 1: the do-nothing test runs end to end under --dummy."""
    def my_test(opts):
        return fixtures.noop_test() | dict(opts) | {
            "name": "my-first-test",
            "store_base": str(tmp_path / "store"),
        }

    rc = cli.run(cli.single_test_cmd(my_test),
                 ["test", "--node", "n1", "--node", "n2",
                  "--time-limit", "1", "--dummy"])
    assert rc == 0


def test_ch3_generators_compose():
    """Chapter 3: mix/stagger/time-limit produce invocation dicts."""
    def r(test, process):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, process):
        return {"type": "invoke", "f": "write",
                "value": random.randrange(5)}

    g = gen.time_limit(30, gen.stagger(0.0, gen.mix([r, w])))
    test = {"concurrency": 2, "nodes": ["n1"]}
    with gen.with_threads([0, 1]):
        op = gen.gen_op(g, test, 0)
    assert op["type"] == "invoke" and op["f"] in ("read", "write")


def test_ch4_atom_lin_flow(tmp_path):
    """Chapter 4: the cluster-free atom fixture checked by the device
    engine, exactly as the tutorial wires it."""
    state = fixtures.AtomRegister()

    def r(test, process):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, process):
        return {"type": "invoke", "f": "write",
                "value": random.randrange(5)}

    # the tutorial's two gotchas, observed: the model's initial state
    # must match atom_db's reset-to-0, and client generators must be
    # scoped with gen.clients or the nemesis consumes them
    test = fixtures.noop_test() | {
        "name": "atom-lin",
        "store_base": str(tmp_path / "store"),
        "db": fixtures.atom_db(state),
        "client": fixtures.atom_client(state),
        "model": cas_register(0),
        "checker": lin.linearizable(),
        "generator": gen.clients(gen.limit(20, gen.mix([r, w]))),
        "concurrency": 3,
        "time_limit": 5,
    }
    out = core.run(test)
    assert out["results"]["valid"] is True


def test_ch6_independent_wiring(tmp_path):
    """Chapter 6: concurrent_generator + independent.checker over the
    atom fixture, with the composed per-key checkers."""
    state = fixtures.AtomRegister()

    def r(test, process):
        return {"type": "invoke", "f": "read", "value": None}

    def naturals():
        k = 0
        while True:
            yield k
            k += 1

    test = fixtures.noop_test() | {
        "name": "tutorial-independent",
        "store_base": str(tmp_path / "store"),
        "db": fixtures.atom_db(state),
        "client": fixtures.atom_client(state),
        "model": cas_register(0),
        "checker": independent.checker(checker_mod.compose({
            "linear": lin.linearizable(),
            "timeline": timeline.timeline(),
        })),
        # an infinite key stream needs the time limit (real suites wrap
        # this exactly so, e.g. etcdemo/atomdemo)
        "generator": gen.time_limit(3, gen.clients(
            independent.concurrent_generator(
                2, naturals(), lambda k: gen.limit(6, r)))),
        "concurrency": 4,
        "time_limit": 5,
    }
    out = core.run(test)
    assert out["results"]["valid"] is True


def test_ch7_store_reload(tmp_path):
    """Chapter 7: repl.last_test and store.read_history reload a run."""
    from jepsen_tpu import repl

    state = fixtures.AtomRegister()
    test = fixtures.noop_test() | {
        "name": "tutorial-store",
        "store_base": str(tmp_path / "store"),
        "db": fixtures.atom_db(state),
        "client": fixtures.atom_client(state),
        "model": cas_register(0),
        "checker": lin.linearizable(),
        "generator": gen.clients(gen.limit(
            4, lambda t, p: {"type": "invoke", "f": "read",
                             "value": None})),
        "concurrency": 2,
        "time_limit": 5,
    }
    core.run(test)
    last = repl.last_test(str(tmp_path / "store"))
    assert last["name"] == "tutorial-store"
