"""Tests for checker/linear.py — the memoized, dominance-pruned host
checker (the knossos `linear` analog; reference selector at
jepsen/src/jepsen/checker.clj:122-126)."""

import random

import pytest

from jepsen_tpu import synth
from jepsen_tpu.checker import seq as seqmod
from jepsen_tpu.checker.linear import check_opseq_linear
from jepsen_tpu.checker.linearizable import Linearizable, check_competition
from jepsen_tpu.history import encode_ops, info_op, invoke_op, ok_op
from jepsen_tpu.models import (cas_register, fifo_queue, mutex, register,
                               unordered_queue)


def enc(h, model):
    return encode_ops(h, model.f_codes)


# ---------------------------------------------------------------------------
# fixed cases
# ---------------------------------------------------------------------------


def test_empty_history_valid():
    model = register()
    out = check_opseq_linear(enc([], model), model)
    assert out["valid"] is True


def test_simple_valid_register():
    model = register()
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         invoke_op(0, "read", 1), ok_op(0, "read", 1)]
    out = check_opseq_linear(enc(h, model), model)
    assert out["valid"] is True


def test_simple_invalid_register():
    model = register(initial=0)
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         invoke_op(0, "read", 2), ok_op(0, "read", 2)]
    out = check_opseq_linear(enc(h, model), model)
    assert out["valid"] is False
    assert out["final_ops"]  # the blocked read is reported


def test_crashed_write_may_linearize():
    # read of 1 is only explainable if the crashed write linearized
    model = register(initial=0)
    h = [invoke_op(1, "write", 1), info_op(1, "write", 1),
         invoke_op(0, "read", 1), ok_op(0, "read", 1)]
    out = check_opseq_linear(enc(h, model), model)
    assert out["valid"] is True


def test_crashed_write_is_optional():
    # read of 0 is fine even though a crashed write of 1 is pending
    model = register(initial=0)
    h = [invoke_op(1, "write", 1), info_op(1, "write", 1),
         invoke_op(0, "read", 0), ok_op(0, "read", 0)]
    out = check_opseq_linear(enc(h, model), model)
    assert out["valid"] is True


def test_crash_cannot_linearize_before_invocation():
    # the crashed write is invoked AFTER the read returns: the read of 1
    # cannot be explained by it
    model = register(initial=0)
    h = [invoke_op(0, "read", 1), ok_op(0, "read", 1),
         invoke_op(1, "write", 1), info_op(1, "write", 1)]
    out = check_opseq_linear(enc(h, model), model)
    assert out["valid"] is False


def test_mutex_double_acquire_invalid():
    model = mutex()
    h = [invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
         invoke_op(1, "acquire", None), ok_op(1, "acquire", None)]
    out = check_opseq_linear(enc(h, model), model)
    assert out["valid"] is False


def test_crashed_release_unlocks_once():
    # acquire, crashed release, acquire — OK; a third acquire is not
    model = mutex()
    h = [invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
         invoke_op(0, "release", None), info_op(0, "release", None),
         invoke_op(1, "acquire", None), ok_op(1, "acquire", None)]
    assert check_opseq_linear(enc(h, model), model)["valid"] is True
    h2 = h + [invoke_op(2, "acquire", None), ok_op(2, "acquire", None)]
    assert check_opseq_linear(enc(h2, model), model)["valid"] is False


def test_budget_yields_unknown():
    model = cas_register()
    rng = random.Random(7)
    h = synth.register_history(rng, n_ops=200, n_procs=8, overlap=8,
                               crash_p=0.05, max_crashes=6, n_values=3)
    out = check_opseq_linear(enc(h, model), model, max_configs=10)
    assert out["valid"] == "unknown"


# ---------------------------------------------------------------------------
# differential vs the WGL oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("trial", range(60))
def test_differential_register_family(trial):
    rng = random.Random(1000 + trial)
    model = cas_register() if trial % 2 else register()
    h = synth.register_history(rng, n_ops=rng.randint(8, 120),
                               n_procs=rng.randint(2, 6),
                               overlap=rng.randint(1, 6),
                               crash_p=0.08, max_crashes=6, n_values=3)
    if trial % 2 == 0:
        h = [op for op in h if op.f != "cas"]
    if rng.random() < 0.5:
        h = synth.corrupt_read(rng, h, at=rng.uniform(0.3, 0.95))
    seq = enc(h, model)
    a = check_opseq_linear(seq, model, max_configs=2_000_000)
    b = seqmod.check_opseq(seq, model, max_configs=2_000_000)
    if "unknown" not in (a["valid"], b["valid"]):
        assert a["valid"] == b["valid"]


@pytest.mark.parametrize("trial", range(30))
def test_differential_mutex_and_queues(trial):
    rng = random.Random(2000 + trial)
    if trial % 2 == 0:
        model = mutex()
        h = synth.sim_mutex_history(rng, n_ops=rng.randint(8, 100),
                                    n_procs=rng.randint(2, 5),
                                    crash_p=0.1, max_crashes=6)
        if rng.random() < 0.3:
            h = h + [invoke_op(97, "acquire", None),
                     ok_op(97, "acquire", None),
                     invoke_op(98, "acquire", None),
                     ok_op(98, "acquire", None)]
    else:
        model = unordered_queue(16) if rng.random() < 0.5 \
            else fifo_queue(16)
        h = synth.sim_queue_history(rng, n_ops=rng.randint(8, 60),
                                    n_procs=rng.randint(2, 4))
        if rng.random() < 0.4:
            h = synth.corrupt_dequeue(rng, h)
        elif rng.random() < 0.4:
            h = synth.swap_dequeues(rng, h)
    seq = enc(h, model)
    a = check_opseq_linear(seq, model, max_configs=2_000_000)
    b = seqmod.check_opseq(seq, model, max_configs=2_000_000)
    if "unknown" not in (a["valid"], b["valid"]):
        assert a["valid"] == b["valid"]


# ---------------------------------------------------------------------------
# wiring: algorithm menu + competition
# ---------------------------------------------------------------------------


def test_linearizable_algorithm_linear(tmp_path):
    model = cas_register()
    rng = random.Random(3)
    h = synth.register_history(rng, n_ops=120, n_procs=4, overlap=4,
                               n_values=3)
    h = synth.corrupt_read(rng, h, at=0.7)
    chk = Linearizable(model, algorithm="linear")
    out = chk.check({"name": "t", "start-time": "now",
                     "store-base": str(tmp_path)}, h)
    assert out["valid"] is False
    assert out["engine"] == "host-linear"


def test_competition_includes_linear_leg():
    # a history past the device encoding limits (too many crashes) is
    # now decided by the host legs instead of a single capped DFS
    model = register()
    h = []
    for i in range(70):  # 70 crashed writes > MAX_CRASH=64
        h += [invoke_op(100 + i, "write", 1), info_op(100 + i, "write", 1)]
    h += [invoke_op(0, "read", 0), ok_op(0, "read", 0)]
    seq = enc(h, model)
    out = check_competition(seq, model)
    assert out["valid"] is True
    assert "competition" in out["engine"]


def test_competition_decides_invalid():
    model = cas_register()
    rng = random.Random(11)
    h = synth.register_history(rng, n_ops=160, n_procs=6, overlap=6,
                               crash_p=0.03, max_crashes=4, n_values=3)
    h = synth.corrupt_read(rng, h, at=0.8)
    seq = enc(h, model)
    out = check_competition(seq, model)
    assert out["valid"] is False


def test_algorithm_env_override(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_LIN_ALGORITHM", "linear")
    chk = Linearizable(cas_register())
    assert chk.algorithm == "linear"
    # an explicit algorithm beats the env override
    chk2 = Linearizable(cas_register(), algorithm="wgl")
    assert chk2.algorithm == "host"
    monkeypatch.setenv("JEPSEN_TPU_LIN_ALGORITHM", "bogus")
    with pytest.raises(ValueError):
        Linearizable(cas_register())


def test_valid_witness_linearization():
    """A valid verdict carries a replayable witness: applying the ops in
    linearization order must be model-legal and cover every ok op."""
    model = cas_register()
    rng = random.Random(21)
    h = synth.register_history(rng, n_ops=80, n_procs=4, overlap=4,
                               crash_p=0.1, max_crashes=5, n_values=3)
    s = enc(h, model)
    out = check_opseq_linear(s, model, witness_cap=2_000_000)
    assert out["valid"] is True
    lin = out.get("linearization")
    assert lin is not None
    # replay
    state = model.init
    for row in lin:
        state = model.pystep(state, int(s.f[row]), int(s.v1[row]),
                             int(s.v2[row]))
        assert state is not None, f"illegal step at row {row}"
    ok_rows = {i for i in range(len(s)) if bool(s.ok[i])}
    assert ok_rows.issubset(set(lin)), "witness missing ok ops"


def test_witness_cap_disables_gracefully():
    model = cas_register()
    rng = random.Random(22)
    h = synth.register_history(rng, n_ops=60, n_procs=4, overlap=4,
                               n_values=3)
    s = enc(h, model)
    out = check_opseq_linear(s, model, witness_cap=0)
    assert out["valid"] is True
    assert "linearization" not in out


def test_checkpoint_resume_roundtrip(tmp_path):
    """A snapshot taken mid-run resumes to the same verdict; a snapshot
    bound to a different history refuses to load."""
    model = cas_register()
    rng = random.Random(41)
    h = synth.register_history(rng, n_ops=200, n_procs=6, overlap=6,
                               crash_p=0.05, max_crashes=5, n_values=3)
    h = synth.corrupt_read(rng, h, at=0.85)
    s = enc(h, model)
    want = check_opseq_linear(s, model)
    ckpt = str(tmp_path / "lin.ckpt")
    out = check_opseq_linear(s, model, checkpoint_path=ckpt,
                             checkpoint_every=5)
    assert out["valid"] == want["valid"]
    import os
    assert os.path.exists(ckpt)
    resumed = check_opseq_linear(s, model, resume_from=ckpt)
    assert resumed["valid"] == want["valid"]
    # determinism: snapshot + replayed remainder lands exactly where the
    # uninterrupted run did, and the snapshot really was mid-run
    assert resumed["configs"] == want["configs"]
    assert resumed["max_depth"] == want["max_depth"]
    import json
    assert json.load(open(ckpt))["depth"] > 0

    h2 = h + [invoke_op(90, "write", 2), ok_op(90, "write", 2)]
    s2 = enc(h2, model)
    with pytest.raises(ValueError, match="digest"):
        check_opseq_linear(s2, model, resume_from=ckpt)
