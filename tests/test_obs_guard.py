"""tools/obs_guard.py — the executable bench contract.

The tier-1 gate here is the acceptance criterion itself: the
committed ``obs_thresholds.json`` must hold against the committed
``BENCH_trace_*.json`` recordings (including the predicted-vs-
observed prune-ratio delta rows for the 10k and 10kuniq tiers), and
the checker's failure modes must actually fire — a contract that
cannot fail is prose, not a guard.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools import obs_guard  # noqa: E402


# ---------------------------------------------------------------------------
# The tier-1 gate: committed thresholds hold against committed traces
# ---------------------------------------------------------------------------


def _thresholds():
    with open(os.path.join(REPO, "obs_thresholds.json")) as f:
        return json.load(f)


def test_committed_bench_contract_holds():
    th = _thresholds()
    fails = obs_guard.run_guard({"traces": th["traces"]}, base=REPO)
    assert fails == [], "the committed bench contract is broken:\n" \
        + "\n".join(fails)


def test_committed_fleet_contract_holds():
    """Acceptance: BENCH_fleet.json records a throughput knee, a
    verified warm boot, zero steady-state compile misses, and routed/
    single-service parity — and the committed thresholds require all
    of it (a fleet block that doesn't is prose, not a gate)."""
    th = _thresholds()
    fleet = th.get("fleet") or {}
    assert "BENCH_fleet.json" in fleet
    req = fleet["BENCH_fleet.json"].get("require", ())
    for key in ("knee", "warmup_verified", "parity"):
        assert key in req, f"fleet contract does not require {key}"
    assert fleet["BENCH_fleet.json"][
        "max_steady_state_compile_misses"] == 0
    fails = obs_guard.run_guard({"fleet": fleet}, base=REPO)
    assert fails == [], "the committed fleet contract is broken:\n" \
        + "\n".join(fails)


def test_committed_thresholds_cover_prune_delta_tiers():
    """Acceptance: a recorded predicted-vs-observed prune-ratio delta
    for at least the 10k and 10kuniq tiers — both the requirement in
    the threshold file AND the recording in the traces."""
    th = _thresholds()["traces"]
    for tier in ("BENCH_trace_10k.json", "BENCH_trace_10kuniq.json"):
        assert "prune_ratio_delta" in th[tier].get("require", ()), tier
        with open(os.path.join(REPO, tier)) as f:
            trace = json.load(f)
        spans = [e for e in trace["traceEvents"]
                 if e.get("name") == "search.telemetry"]
        assert spans, f"{tier}: no search.telemetry span recorded"
        assert spans[-1]["args"].get("prune_ratio_delta") is not None


def test_guard_cli_exit_codes(capsys):
    assert obs_guard.main([]) == 0
    out = capsys.readouterr().out
    assert "ok" in out
    assert obs_guard.main(["--thresholds", "/nonexistent.json"]) == 2


# ---------------------------------------------------------------------------
# Unit: the failure modes fire
# ---------------------------------------------------------------------------


def test_check_trace_missing_file():
    fails = obs_guard.check_trace("/nonexistent_trace.json",
                                  {"min_levels": 1})
    assert fails and "missing" in fails[0]


def _mini_trace(tmp_path, *, with_tele=True, idle=False):
    """A tiny synthetic trace: one device.slice, two device.level
    rows, one search.telemetry span."""
    evs = [{"name": "device.slice", "cat": "device", "ph": "X",
            "ts": 0.0, "dur": 10.0 if idle else 1_000_000.0,
            "pid": 1, "tid": 1, "args": {}}]
    if with_tele:
        evs += [
            {"name": "device.level", "cat": "device", "ph": "X",
             "ts": 0.0, "dur": 500_000.0, "pid": 1, "tid": 1,
             "args": {"level": 0, "occupancy": 4, "expanded": 6,
                      "mask_killed": 2, "dedup_folds": 0}},
            {"name": "device.level", "cat": "device", "ph": "X",
             "ts": 500_000.0, "dur": 500_000.0, "pid": 1, "tid": 1,
             "args": {"level": 1, "occupancy": 8, "expanded": 10,
                      "mask_killed": 6, "dedup_folds": 0}},
            {"name": "search.telemetry", "cat": "telemetry",
             "ph": "X", "ts": 1_000_000.0, "dur": 0.0, "pid": 1,
             "tid": 1,
             "args": {"levels": 2, "expanded": 16, "mask_killed": 8,
                      "dedup_folds": 0, "overflows": 0,
                      "observed_prune_ratio": 0.666667,
                      "predicted_prune_ratio": 1.0,
                      "prune_ratio_delta": -0.333333}},
            {"name": "device.compile", "cat": "device", "ph": "X",
             "ts": 0.0, "dur": 1000.0, "pid": 1, "tid": 1,
             "args": {"cache": "miss", "persistent_cache": False}},
            {"name": "device.transfer", "cat": "device", "ph": "X",
             "ts": 0.0, "dur": 0.0, "pid": 1, "tid": 1,
             "args": {"bytes": 1024, "direction": "h2d"}},
        ]
    # a padding host span so wall > device busy in the idle case
    evs.append({"name": "host.pad", "cat": "host", "ph": "X",
                "ts": 0.0, "dur": 2_000_000.0, "pid": 1, "tid": 2,
                "args": {}})
    p = tmp_path / "trace.json"
    p.write_text(json.dumps({"traceEvents": evs}))
    return str(p)


def test_check_trace_clean_pass(tmp_path):
    p = _mini_trace(tmp_path)
    th = {"require": ["telemetry", "prune_ratio_delta"],
          "max_device_idle_fraction": 0.6, "min_levels": 2,
          "min_observed_prune_ratio": 0.5,
          "max_observed_prune_ratio": 1.0,
          "max_abs_prune_ratio_delta": 0.5,
          "max_compiles": 1, "min_transfer_bytes": 1024}
    assert obs_guard.check_trace(p, th) == []


def test_check_trace_requires_telemetry(tmp_path):
    p = _mini_trace(tmp_path, with_tele=False)
    fails = obs_guard.check_trace(p, {"require": ["telemetry"]})
    assert fails and "no telemetry" in fails[0]
    # without the require, a bare trace passes an empty contract
    assert obs_guard.check_trace(p, {}) == []


def test_check_trace_threshold_violations(tmp_path):
    p = _mini_trace(tmp_path, idle=True)
    th = {"max_device_idle_fraction": 0.1,
          "min_levels": 3,
          "min_observed_prune_ratio": 0.9,
          "max_abs_prune_ratio_delta": 0.1,
          "max_compiles": 0,
          "min_transfer_bytes": 4096}
    fails = obs_guard.check_trace(p, th)
    text = "\n".join(fails)
    for needle in ("device_idle_fraction", "level(s)",
                   "observed_prune_ratio", "prune_ratio_delta",
                   "compile(s)", "transfer_bytes"):
        assert needle in text, f"{needle} check never fired:\n{text}"


def test_check_stats_directions_and_null_handling():
    snap = {"derived": {"kernel_cache_hit_ratio": 0.4,
                        "device_idle_fraction": 0.95,
                        "observed_prune_ratio": None}}
    th = {"min_kernel_cache_hit_ratio": 0.5,
          "max_device_idle_fraction": 0.9,
          "min_observed_prune_ratio": 0.1}
    fails = obs_guard.check_stats(snap, th)
    text = "\n".join(fails)
    assert "kernel_cache_hit_ratio" in text
    assert "device_idle_fraction" in text
    # null derived gauge is skipped unless required
    assert "observed_prune_ratio" not in text
    th["require"] = ["observed_prune_ratio"]
    fails = obs_guard.check_stats(snap, th)
    assert any("observed_prune_ratio" in f for f in fails)


def _fleet_doc(**over):
    doc = {"workers": 2,
           "warmup": {"shapes": 4, "compiled": 4, "verified": True},
           "steady_state_compile_misses": 0,
           "ramp": [{"clients": 1, "shed_rate": 0.0},
                    {"clients": 2, "shed_rate": 0.0}],
           "knee": {"clients": 2, "events_per_sec": 5000.0},
           "parity": True}
    doc.update(over)
    return doc


_FLEET_TH = {"require": ["knee", "warmup_verified", "parity"],
             "min_knee_events_per_sec": 1000,
             "max_warmup_compiles": 8,
             "max_steady_state_compile_misses": 0,
             "max_shed_rate": 0.0,
             "min_workers": 2}


def _write_fleet(tmp_path, doc):
    p = tmp_path / "BENCH_fleet.json"
    p.write_text(json.dumps(doc))
    return str(p)


def test_check_fleet_clean_pass(tmp_path):
    p = _write_fleet(tmp_path, _fleet_doc())
    assert obs_guard.check_fleet(p, _FLEET_TH) == []


def test_check_fleet_missing_file():
    fails = obs_guard.check_fleet("/nonexistent_fleet.json",
                                  {"require": ["knee"]})
    assert fails and "missing" in fails[0]


def test_check_fleet_failure_modes(tmp_path):
    p = _write_fleet(tmp_path, _fleet_doc(
        warmup={"shapes": 4, "compiled": 20, "verified": False},
        steady_state_compile_misses=3,
        ramp=[{"clients": 1, "shed_rate": 0.4}],
        knee={"clients": 1, "events_per_sec": 10.0},
        parity=False,
        workers=1))
    fails = obs_guard.check_fleet(p, _FLEET_TH)
    text = "\n".join(fails)
    for needle in ("did not verify", "diverged", "events/sec",
                   "warm boot compiled", "compile miss", "shed_rate",
                   "worker(s)"):
        assert needle in text, f"{needle} check never fired:\n{text}"


def test_check_fleet_missing_knee_and_misses(tmp_path):
    doc = _fleet_doc()
    doc.pop("knee")
    doc.pop("steady_state_compile_misses")
    p = _write_fleet(tmp_path, doc)
    fails = obs_guard.check_fleet(p, _FLEET_TH)
    text = "\n".join(fails)
    assert "no throughput knee" in text
    assert "not recorded" in text


def test_run_guard_stats_against_live_registry():
    """With no snapshot supplied the guard reads this process's
    registry — the in-process smoke path."""
    fails = obs_guard.run_guard(
        {"stats": {"max_device_idle_fraction": 1.0}}, base=REPO)
    assert fails == []


# ---------------------------------------------------------------------------
# shard tier (BENCH_shard.json)
# ---------------------------------------------------------------------------


def _shard_doc(**over):
    doc = {"n_devices": 8,
           "warmup": {"shapes": 2, "compiled": 0, "verified": True},
           "warmup_shapes": {"total": 2, "sharded": 2},
           "steady_state_compile_misses": 0,
           "bucketed": {"padding_efficiency": 0.58},
           "fused_counterfactual": {"padding_efficiency": 0.29},
           "parity": True,
           "explain_match": True}
    doc.update(over)
    return doc


_SHARD_TH = {"require": ["bucketed", "fused_counterfactual", "parity",
                         "explain_match", "warmup_verified"],
             "min_padding_efficiency": 0.5,
             "min_efficiency_gain_vs_fused": 1.2,
             "max_steady_state_compile_misses": 0,
             "max_warmup_compiles": 0,
             "min_shards": 2,
             "min_sharded_warm_shapes": 1}


def _write_shard(tmp_path, doc):
    p = tmp_path / "BENCH_shard.json"
    p.write_text(json.dumps(doc))
    return str(p)


def test_check_shard_clean_pass(tmp_path):
    p = _write_shard(tmp_path, _shard_doc())
    assert obs_guard.check_shard(p, _SHARD_TH) == []


def test_check_shard_missing_file():
    fails = obs_guard.check_shard("/nonexistent_shard.json",
                                  {"require": ["parity"]})
    assert fails and "missing" in fails[0]


def test_check_shard_failure_modes(tmp_path):
    p = _write_shard(tmp_path, _shard_doc(
        bucketed={"padding_efficiency": 0.3},
        fused_counterfactual={"padding_efficiency": 0.29},
        parity=False,
        explain_match=False,
        warmup={"shapes": 2, "compiled": 2, "verified": False},
        warmup_shapes={"total": 2, "sharded": 0},
        steady_state_compile_misses=2,
        n_devices=1))
    fails = obs_guard.check_shard(p, _SHARD_TH)
    text = "\n".join(fails)
    for needle in ("verdicts diverged", "no longer matches",
                   "did not verify", "padding_efficiency 0.3",
                   "efficiency gain", "steady-state kernel compile",
                   "warm boot compiled", "device(s) < min",
                   "sharded warm shape"):
        assert needle in text, f"{needle} check never fired:\n{text}"


def test_check_shard_missing_blocks(tmp_path):
    doc = _shard_doc()
    doc.pop("bucketed")
    doc.pop("fused_counterfactual")
    doc.pop("steady_state_compile_misses")
    p = _write_shard(tmp_path, doc)
    fails = obs_guard.check_shard(p, _SHARD_TH)
    text = "\n".join(fails)
    assert "no bucketed padding efficiency" in text
    assert "no fused counterfactual" in text
    assert "not recorded" in text


def test_committed_shard_contract_holds():
    """Acceptance: the committed BENCH_shard.json clears the committed
    'shard' thresholds — bucketed padding efficiency over the floor
    with the fused counterfactual recorded, verdict parity, the
    explain_batch cost-model match, a verified zero-compile warm boot,
    and zero steady-state compile misses."""
    th = _thresholds()
    shard = th.get("shard") or {}
    assert "BENCH_shard.json" in shard
    block = shard["BENCH_shard.json"]
    req = block.get("require", ())
    for key in ("bucketed", "fused_counterfactual", "parity",
                "explain_match", "warmup_verified"):
        assert key in req, f"shard contract does not require {key}"
    assert block["max_steady_state_compile_misses"] == 0
    assert block["max_warmup_compiles"] == 0
    assert block["min_padding_efficiency"] >= 0.5
    fails = obs_guard.run_guard({"shard": shard}, base=REPO)
    assert fails == [], "the committed shard contract is broken:\n" \
        + "\n".join(fails)
