"""Shell-layer model checking (analyze/simnet.py + the MC2xx codes).

The shell-lifting contract under test: the *actual dispatch code* of
the live daemons (``kv_server.dispatch``, ``queue_server.dispatch``,
``replicated_queue.dispatch_resp``,
``replicated_server.handle_client_request``) runs under the bounded
scheduler on a simulated transport.  Four tiers of guarantees:

* **Parity** — a fault-free simnet schedule produces the SAME
  client-visible history as the real TCP daemon serving the same op
  program, for all four families.  This is what makes a shell
  certificate evidence about the shipped server, not about a model.
* **Reduction soundness** — the (code, state) violation set is
  bit-identical with DPOR on and off at the same scope (the MC1xx
  invariant, re-proven over the transport worlds).
* **Seeded-bug acceptance** — each seeded shell mode is caught at the
  default scope with a replaying, shrunk certificate whose rendered
  history the engine re-confirms INVALID (MC203's loop certificate is
  confirmed by replay — an amplification has no client history to
  hand the engine).
* **Clean-shell verdicts** — un-seeded modes clear the scope with a
  complete search and a nonzero prune ratio.

Wire-level regressions for the two shell bugs the checker's modes
encode ride along: the queue handler releasing a claim whose reply
died (MC204's fix) and the kv reqId reply-dedup (MC202's fix).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.analyze import __main__ as analyze_cli  # noqa: E402
from jepsen_tpu.analyze import modelcheck as mc  # noqa: E402
from jepsen_tpu.analyze import simnet  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def triples(history):
    return [(op.type, op.f, op.value) for op in history]


def violation_set(result):
    return {(v["code"], v["state"]) for v in result["violations"]}


def run_cli_inproc(capsys, *args):
    rc = analyze_cli.main(list(args))
    return rc, capsys.readouterr().out


# ---------------------------------------------------------------------------
# fault-free drivers
# ---------------------------------------------------------------------------


def _fault_free_transport(family, ops):
    """Drive a transport world with no faults enabled: every request
    and reply delivered in order.  crashes=0/partitions=0 leaves only
    send/deliver enabled, so evs[0] is deterministic."""
    scope = mc.Scope(nodes=3, ops=tuple(ops), crashes=0, partitions=0,
                     max_events=99)
    w = mc.make_world(family, "clean", scope)
    while True:
        evs = w.enabled()
        if not evs:
            break
        v = w.execute(evs[0])
        assert v is None, f"fault-free schedule violated: {v}"
    return w


def _fault_free_repl(ops, via_node):
    """shell-replicated has no message soup: each op resolves
    atomically at the chosen entry node (proxy hop included)."""
    scope = mc.Scope(nodes=3, ops=tuple(ops), crashes=0, partitions=0,
                     max_events=99)
    w = mc.make_world("shell-replicated", "clean", scope)
    for _ in ops:
        v = w.execute(("op", via_node))
        assert v is None, f"fault-free schedule violated: {v}"
    return w


def _wait_port(port, host="127.0.0.1", deadline_s=15.0):
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return socket.create_connection((host, port), timeout=1.0)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def _spawn(module, port, data, *extra):
    p = subprocess.Popen(
        [sys.executable, "-m", module, str(port), data, *extra],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    _wait_port(port).close()
    return p


def _http(method, url, body=None, timeout=5):
    req = urllib.request.Request(url, data=body, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


# ---------------------------------------------------------------------------
# parity: fault-free simnet history == real-TCP daemon history
# ---------------------------------------------------------------------------

#: the shared kv op program: CAS hit, read, blind write, CAS miss, read
KV_OPS = (("cas", 1, 2), ("r",), ("w", 5), ("cas", 9, 7), ("r",))


def _kv_client_history(port):
    """The op program against a real kv_server, completions rendered
    by the same rules ShellKVWorld._complete applies."""
    base = f"http://127.0.0.1:{port}/v2/keys/{simnet.KEY}"
    hist = []
    for i, verb in enumerate(KV_OPS):
        if verb[0] == "r":
            hist.append(("invoke", "read", None))
            st, b = _http("GET", base)
            hist.append(("ok", "read",
                         int(b["node"]["value"]) if st == 200
                         else simnet.ABSENT))
            continue
        if verb[0] == "cas":
            f, value = "cas", [verb[1], verb[2]]
            qs = f"prevValue={verb[1]}&reqId=op{i}"
            new = verb[2]
        else:
            f, value = "write", verb[1]
            qs = f"reqId=op{i}"
            new = verb[1]
        hist.append(("invoke", f, value))
        st, _b = _http("PUT", f"{base}?{qs}",
                       urllib.parse.urlencode({"value": new}).encode())
        hist.append(("ok" if st == 200 else "fail", f, value))
    return hist


def test_parity_shell_kv(tmp_path):
    sim = triples(_fault_free_transport("shell-kv", KV_OPS).history)
    port, data = 18470, str(tmp_path / "kv")
    p = _spawn("jepsen_tpu.live.kv_server", port, data)
    try:
        # seed the register the sim world starts with
        _http("PUT", f"http://127.0.0.1:{port}/v2/keys/{simnet.KEY}",
              b"value=1")
        real = _kv_client_history(port)
    finally:
        p.kill()
        p.wait(timeout=5)
    assert sim == real


QUEUE_OPS = (("add", 7), ("add", 8), ("get",), ("get",), ("get",))


def _queue_client_history(conn):
    hist = []
    for i, verb in enumerate(QUEUE_OPS):
        if verb[0] == "add":
            hist.append(("invoke", "enqueue", verb[1]))
            jid = conn.command("ADDJOB", "jepsen", str(verb[1]), 0,
                               "REQID", f"op{i}")
            hist.append(("ok" if jid else "fail", "enqueue", verb[1]))
        else:
            hist.append(("invoke", "dequeue", None))
            got = conn.command("GETJOB", "TIMEOUT", 0, "COUNT", 1,
                               "FROM", "jepsen")
            if got is None:
                hist.append(("fail", "dequeue", None))
            else:
                hist.append(("ok", "dequeue", int(got[0][2])))
    return hist


def test_parity_shell_queue(tmp_path):
    from jepsen_tpu.suites.disque import RespConn

    sim = triples(
        _fault_free_transport("shell-queue", QUEUE_OPS).history)
    port, data = 18471, str(tmp_path / "q")
    p = _spawn("jepsen_tpu.live.queue_server", port, data)
    try:
        real = _queue_client_history(
            RespConn("127.0.0.1", port, timeout=5))
    finally:
        p.kill()
        p.wait(timeout=5)
    assert sim == real


def test_parity_shell_rqueue(tmp_path):
    """Same program through the replicated queue's JPROXY relay: the
    sim client enters at the follower (every command proxied); the
    real client connects to a non-leader node."""
    from jepsen_tpu.suites.disque import RespConn, RespError

    sim = triples(
        _fault_free_transport("shell-rqueue", QUEUE_OPS).history)

    ports = [18474, 18475, 18476]
    base = str(tmp_path)
    procs = []

    def rq_spawn(i, *extra):
        peers = ",".join(f"127.0.1.{j + 1}:{p}"
                         for j, p in enumerate(ports))
        p = subprocess.Popen(
            [sys.executable, "-m", "jepsen_tpu.live.replicated_queue",
             str(ports[i]), os.path.join(base, f"n{i}"),
             "--id", str(i), "--peers", peers,
             "--host", f"127.0.1.{i + 1}",
             "--oplog", os.path.join(base, "shared", "oplog"),
             "--lease-ms", "350", *extra],
            cwd=REPO, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        _wait_port(ports[i], host=f"127.0.1.{i + 1}").close()
        return p

    def rq_leader(deadline_s=25.0):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            leaders = []
            for i in range(3):
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.1.{i + 1}:{ports[i] + 500}"
                            f"/_repl/status", timeout=1) as r:
                        if json.loads(r.read())["role"] == "leader":
                            leaders.append(i)
                except OSError:
                    pass
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.1)
        raise AssertionError("no single leader")

    try:
        procs = [rq_spawn(i) for i in range(3)]
        leader = rq_leader()
        follower = (leader + 1) % 3
        # settle: the follower must know the leader before the first
        # proxied command, or it answers -NOLEADER (a fault the
        # fault-free schedule doesn't model)
        deadline = time.monotonic() + 25
        conn = None
        while True:
            try:
                conn = RespConn(f"127.0.1.{follower + 1}",
                                ports[follower], timeout=5)
                probe = conn.command("GETJOB", "TIMEOUT", 0,
                                     "COUNT", 1, "FROM", "jepsen")
                assert probe is None
                break
            except (RespError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.15)
        real = _queue_client_history(conn)
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=5)
    assert sim == real


REPL_OPS = (("w", 1), ("r",), ("w", 2), ("r",))


def test_parity_shell_replicated(tmp_path):
    """Writes and reads through a FOLLOWER — every request rides the
    handle_client_request proxy decision, in the sim and on the real
    cluster alike."""
    w = _fault_free_repl(REPL_OPS, via_node=1)
    sim = triples(w.history)

    ports = [18477, 18478, 18479]
    base = str(tmp_path)

    def repl_spawn(i):
        p = subprocess.Popen(
            [sys.executable, "-m",
             "jepsen_tpu.live.replicated_server",
             str(ports[i]), os.path.join(base, f"n{i}"),
             "--id", str(i), "--peers", ",".join(map(str, ports)),
             "--oplog", os.path.join(base, "shared", "oplog"),
             "--lease-ms", "350"],
            cwd=REPO, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        _wait_port(ports[i]).close()
        return p

    def wait_leader(deadline_s=25.0):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            leaders = []
            for i in range(3):
                try:
                    st, b = _http(
                        "GET",
                        f"http://127.0.0.1:{ports[i]}/_repl/status",
                        timeout=1)
                    if b.get("role") == "leader":
                        leaders.append(i)
                except OSError:
                    pass
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.1)
        raise AssertionError("no single leader")

    procs = []
    try:
        procs = [repl_spawn(i) for i in range(3)]
        leader = wait_leader()
        follower = (leader + 1) % 3
        url = (f"http://127.0.0.1:{ports[follower]}"
               f"/v2/keys/{simnet.KEY}")

        def put_ok(val, deadline_s=25.0):
            deadline = time.monotonic() + deadline_s
            while True:
                try:
                    st, _b = _http(
                        "PUT", url,
                        urllib.parse.urlencode(
                            {"value": val}).encode())
                    if st == 200:
                        return
                except OSError:
                    pass
                if time.monotonic() > deadline:
                    raise AssertionError(f"write {val} never acked")
                time.sleep(0.15)

        real = []
        for verb in REPL_OPS:
            if verb[0] == "w":
                real.append(("invoke", "write", verb[1]))
                put_ok(verb[1])
                real.append(("ok", "write", verb[1]))
            else:
                real.append(("invoke", "read", None))
                st, b = _http("GET", url)
                assert st == 200, (st, b)
                real.append(("ok", "read", int(b["node"]["value"])))
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=5)
    assert sim == real


# ---------------------------------------------------------------------------
# reduction soundness over the transport worlds
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family,mode", [
    ("shell-kv", "volatile"),
    ("shell-queue", "volatile"),
    ("shell-replicated", "stale-proxy"),
])
def test_dpor_soundness_shell_seeded(family, mode):
    scope = mc.default_scope(family, mode)
    on = mc.explore(family, mode, scope, dpor=True,
                    max_violations=10_000)
    off = mc.explore(family, mode, scope, dpor=False,
                     max_violations=10_000)
    assert on["explored"]["complete"] and off["explored"]["complete"]
    assert violation_set(on) == violation_set(off)
    assert on["violations"], f"{family}/{mode}: seeded bug not found"
    assert on["explored"]["sleep_prunes"] > 0
    assert on["explored"]["events"] <= off["explored"]["events"]


@pytest.mark.parametrize("family", mc.SHELL_FAMILIES)
def test_clean_shell_passes_with_reduction_biting(family):
    r = mc.run_mc(family, "clean", dpor=True)
    assert r["ok"], r["violations"][:1]
    ex = r["explored"]
    assert ex["complete"]
    assert ex["states"] > 0
    assert ex["prune_ratio"] > 0, \
        f"{family}/clean: the reduction did not bite"


# ---------------------------------------------------------------------------
# seeded-bug acceptance: certificate lifecycle per MC2xx code
# ---------------------------------------------------------------------------


def _accept(family, mode, want_code, tmp_path, route, banked=True):
    r = mc.run_mc(family, mode, dpor=True,
                  bank_base=str(tmp_path / "corpus"))
    assert not r["ok"]
    codes = {v["code"] for v in r["violations"]}
    assert want_code in codes, (codes, r["violations"][:1])
    v = next(v for v in r["violations"] if v["code"] == want_code)
    assert v["replayed"]
    assert v["shrunk"]["n_to"] <= v["shrunk"]["n_from"]
    assert len(v["schedule"]) == v["shrunk"]["n_to"]
    c = v["confirm"]
    assert c["route"] == route
    assert c["engine_valid"] is False
    assert c["audit_ok"] is True and c["audit_checked"]
    if banked:
        assert v["banked"]["banked"] >= 1
        assert (tmp_path / "corpus").exists()
    return v


def test_seeded_mc202_kv_acked_reply_lost_then_lied(tmp_path):
    v = _accept("shell-kv", "volatile", "MC202", tmp_path, "engine")
    # the probe read is what exhibits the committed-but-failed write
    fs = [op["f"] for op in v["history"]]
    assert "read" in fs and "cas" in fs


def test_seeded_mc201_queue_retry_double_commit(tmp_path):
    v = _accept("shell-queue", "volatile", "MC201", tmp_path,
                "engine")
    fs = [op["f"] for op in v["history"]]
    assert "enqueue" in fs and "drain" in fs


def test_seeded_mc201_rqueue_proxy_retry_double_commit(tmp_path):
    v = _accept("shell-rqueue", "volatile", "MC201", tmp_path,
                "engine")
    fs = [op["f"] for op in v["history"]]
    assert "enqueue" in fs and "drain" in fs


def test_seeded_mc204_queue_session_leak(tmp_path):
    v = _accept("shell-queue", "session-leak", "MC204", tmp_path,
                "queue")
    fs = [op["f"] for op in v["history"]]
    # the leaked claim is invisible: the drain must NOT see it
    assert "drain" in fs


def test_seeded_mc205_stale_leader_proxy(tmp_path):
    _accept("shell-replicated", "stale-proxy", "MC205", tmp_path,
            "engine")


def test_seeded_mc203_proxy_loop(tmp_path):
    """MC203 has no invalid client history to hand the engine — the
    amplification IS the bug — so the confirm route is the replay
    itself and nothing banks."""
    v = _accept("shell-replicated", "proxy-loop", "MC203", tmp_path,
                "loop", banked=False)
    assert v["confirm"]["audit_checked"] == "loop-replay"
    assert v["banked"]["banked"] == 0


def test_shell_certificate_replays_via_module_api():
    r = mc.run_mc("shell-queue", "volatile", dpor=True)
    v = next(x for x in r["violations"] if x["code"] == "MC201")
    rep = mc.replay_certificate(v)
    assert rep["reproduced"] and rep["code"] == v["code"]
    broken = dict(v, schedule=v["schedule"][:1])
    assert not mc.replay_certificate(broken)["reproduced"]


# ---------------------------------------------------------------------------
# CLI: --mc-scope and the shell families/modes
# ---------------------------------------------------------------------------


def test_cli_shell_clean_pair_exits_0(capsys):
    rc, _ = run_cli_inproc(capsys, "--mc", "--mc-family", "shell-kv",
                           "--mc-mode", "clean")
    assert rc == 0


def test_cli_shell_seeded_pair_exits_1(capsys):
    rc, out = run_cli_inproc(
        capsys, "--mc", "--mc-family", "shell-queue",
        "--mc-mode", "volatile", "--json")
    assert rc == 1
    payload = json.loads(out)
    assert payload["ok"] is False
    codes = {v["code"] for r in payload["runs"]
             for v in r["violations"]}
    assert "MC201" in codes


def test_cli_shell_bad_pair_exits_254(capsys):
    # shell-kv has no split-brain mode
    rc, _ = run_cli_inproc(capsys, "--mc", "--mc-family", "shell-kv",
                           "--mc-mode", "split-brain")
    assert rc == 254


def test_cli_shell_scope_explain(capsys):
    rc, out = run_cli_inproc(capsys, "--mc", "--mc-scope", "shell",
                             "--explain", "--json")
    assert rc == 0
    plan = json.loads(out)["mc_plan"]
    assert {(b["family"], b["mode"]) for b in plan} == {
        (f, m) for f in mc.SHELL_FAMILIES
        for m in mc.SHELL_MODES[f]}
    # transport families advertise the transport event vocabulary
    kv = next(b for b in plan if b["family"] == "shell-kv")
    assert "retry" in kv["events"] and "reset" in kv["events"]


def test_cli_default_scope_stays_core(capsys):
    rc, out = run_cli_inproc(capsys, "--mc", "--explain", "--json")
    assert rc == 0
    plan = json.loads(out)["mc_plan"]
    fams = {b["family"] for b in plan}
    assert fams == set(mc.FAMILIES)


@pytest.mark.slow
def test_cli_shell_scope_sweep_exits_0():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu.analyze", "--mc",
         "--mc-scope", "shell", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    out = json.loads(p.stdout)
    assert out["ok"] is True
    assert len(out["runs"]) == sum(
        len(m) for m in mc.SHELL_MODES.values())


@pytest.mark.slow
def test_cli_all_scope_sweep_exits_0():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu.analyze", "--mc",
         "--mc-scope", "all", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    out = json.loads(p.stdout)
    assert out["ok"] is True
    assert len(out["runs"]) == sum(
        len(m) for m in mc.ALL_MODES.values())


def test_sweep_api_shell_families():
    s = mc.run_mc_sweep(mc.SHELL_FAMILIES)
    assert s["ok"], [(r["family"], r["mode"], r["ok"])
                     for r in s["runs"]]
    assert {r["family"] for r in s["runs"]} == set(mc.SHELL_FAMILIES)


# ---------------------------------------------------------------------------
# deeper shell matrix (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("family", mc.SHELL_FAMILIES)
def test_slow_clean_shell_matrix_deeper(family):
    scope = mc.scope_from_args(family, "clean", max_events=7)
    r = mc.run_mc(family, "clean", scope=scope, dpor=True)
    assert r["ok"], r["violations"][:1]
    assert r["explored"]["complete"]


@pytest.mark.slow
@pytest.mark.parametrize("family,mode", [
    (f, m) for f in mc.SHELL_FAMILIES
    for m in mc.SHELL_MODES[f] if m != "clean"])
def test_slow_seeded_shell_matrix_deeper(family, mode):
    base = mc.default_scope(family, mode)
    deeper = max(7, base.max_events)
    scope = mc.scope_from_args(family, mode, max_events=deeper)
    r = mc.run_mc(family, mode, scope=scope, dpor=True,
                  shrink=False, confirm=False)
    assert not r["ok"]
    assert all(v["replayed"] for v in r["violations"])


# ---------------------------------------------------------------------------
# wire-level regressions for the shell bugs the seeded modes encode
# ---------------------------------------------------------------------------


def test_queue_reply_failure_releases_claim(tmp_path):
    """MC204's fix at the wire: a GETJOB whose reply dies on the
    socket must return its claim to pending — a reconnecting consumer
    sees the job instead of a leak until the retry window."""
    from jepsen_tpu.live import queue_server
    from jepsen_tpu.suites.disque import RespConn

    class FlakyHandler(queue_server.Handler):
        def _send(self, payload):
            # drop exactly one job reply (RESP arrays start with '*';
            # the empty reply *-1 and ADDJOB's +id pass through)
            if payload.startswith(b"*") \
                    and not payload.startswith(b"*-1") \
                    and not getattr(self.server, "dropped", False):
                self.server.dropped = True
                raise OSError("injected reply-send failure")
            super()._send(payload)

    srv = queue_server.Server(("127.0.0.1", 0), FlakyHandler)
    srv.store = queue_server.Store(str(tmp_path / "q"))
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        c = RespConn("127.0.0.1", port, timeout=5)
        jid = c.command("ADDJOB", "jepsen", "7", 0, "RETRY", 600)
        assert jid
        try:
            c.command("GETJOB", "TIMEOUT", 0, "COUNT", 1,
                      "FROM", "jepsen")
        except Exception:  # noqa: BLE001 — the connection just died
            pass
        # RETRY 600 means redelivery-by-timeout can't save us inside
        # the test: only the release-on-reply-failure path can
        c2 = RespConn("127.0.0.1", port, timeout=5)
        got = c2.command("GETJOB", "TIMEOUT", 0, "COUNT", 1,
                         "FROM", "jepsen")
        assert got is not None and got[0][2] == "7", \
            "claim leaked on reply-send failure (the MC204 bug)"
    finally:
        srv.shutdown()
        srv.server_close()


def test_kv_reqid_dedup_on_the_wire(tmp_path):
    """MC202's fix at the wire: a retransmitted PUT carrying the same
    reqId gets the SAME reply instead of re-running the CAS (which
    would answer 412 for a write that committed)."""
    port, data = 18473, str(tmp_path / "kv")
    p = _spawn("jepsen_tpu.live.kv_server", port, data)
    base = f"http://127.0.0.1:{port}/v2/keys/x"
    try:
        st, _ = _http("PUT", base, b"value=1")
        assert st == 200
        url = f"{base}?prevValue=1&reqId=opA"
        st1, b1 = _http("PUT", url, b"value=2")
        st2, b2 = _http("PUT", url, b"value=2")
        assert (st1, st2) == (200, 200)
        assert b1 == b2, "retransmission got a different reply"
        # the same CAS without the idempotency key re-runs and fails
        st3, _ = _http("PUT", f"{base}?prevValue=1", b"value=2")
        assert st3 == 412
    finally:
        p.kill()
        p.wait(timeout=5)


def test_replicated_proxy_forward_error_classes():
    """The proxy decision's error contract (the MC205/MC203 boundary):
    a refused forward falls back to the local 503 (the op definitely
    didn't happen); any other socket error is 504 — never a 503 that
    lets the client record :fail for a write the leader may have
    applied.  Runs the REAL handle_client_request."""
    from jepsen_tpu.live.replicated_server import (
        PREFIX,
        handle_client_request,
    )

    class Follower:
        id = 1
        lock = threading.Lock()
        leader_id = 0

        def put(self, key, value, prev=None):
            return 503, {"errorCode": 300, "message": "not leader"}

        def get(self, key):
            return 503, {"errorCode": 300, "message": "not leader"}

    def refused(lid, m, p, b):
        raise ConnectionRefusedError("leader down")

    def torn(lid, m, p, b):
        raise OSError("connection reset mid-reply")

    def looping(lid, m, p, b):
        raise AssertionError("a proxied request must not re-forward")

    st, _ = handle_client_request(Follower(), "PUT", PREFIX + "x",
                                  b"value=1", proxied=False,
                                  forward=refused)
    assert st == 503
    st, _ = handle_client_request(Follower(), "PUT", PREFIX + "x",
                                  b"value=1", proxied=False,
                                  forward=torn)
    assert st == 504
    # a proxied request answers locally even when it is not leader
    st, _ = handle_client_request(Follower(), "PUT", PREFIX + "x",
                                  b"value=1", proxied=True,
                                  forward=looping)
    assert st == 503
