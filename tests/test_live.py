"""Live fault-injection campaigns (jepsen_tpu/live/).

Tier-1 here: the dry-run planner (spawns nothing), the per-family
server recovery invariants under real kill -9 (acked state survives,
un-acked may vanish — never the reverse; volatile modes stage the
seeded bugs), faketime wrap!/unwrap idempotence, and the campaign
smoke cell (register × kill-restart, tiny history, audit on) the
acceptance criteria name.  The full ≥3-family × ≥4-nemesis matrix and
the seeded-bug detection run under ``-m slow``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# planner / CLI — no processes spawned
# ---------------------------------------------------------------------------


def test_plan_covers_full_matrix_with_skip_reasons():
    from jepsen_tpu.live.backend import FAMILIES
    from jepsen_tpu.live.campaign import plan, render_plan
    from jepsen_tpu.live.matrix import standard_matrix

    cells = plan()
    fams, nems = set(FAMILIES), set(standard_matrix())
    assert len(fams) >= 3 and len(nems) >= 4  # the acceptance floor
    base = [c for c in cells if not c["seeded"]]
    assert {(c["family"], c["nemesis"]) for c in base} \
        == {(f, n) for f in fams for n in nems}
    # every cell either runs or carries a human-readable reason
    for c in cells:
        assert c["skip"] is None or isinstance(c["skip"], str)
    # kill-restart needs nothing exotic: runnable everywhere
    assert all(c["skip"] is None for c in base
               if c["nemesis"] == "kill-restart")
    out = render_plan(cells)
    for f in fams:
        assert f in out
    for n in nems:
        assert n in out


def test_campaign_cli_dry_run_spawns_nothing():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "campaign.py"),
         "--dry-run", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    cells = json.loads(r.stdout)
    assert isinstance(cells, list) and len(cells) >= 12
    assert {"family", "nemesis", "skip"} <= set(cells[0])
    # the human rendering of the same plan (in-process: the CLI text
    # path is plain render_plan)
    from jepsen_tpu.live.campaign import render_plan

    out = render_plan(cells)
    assert "register" in out and "kill-restart" in out


def test_unknown_nemesis_probe_reason_rendering():
    from jepsen_tpu.live.campaign import plan

    cells = plan(families=["kv"], nemeses=["clock-skew"], seeded=False)
    assert len(cells) == 1
    import shutil

    if shutil.which("faketime") is None:
        assert "faketime" in cells[0]["skip"]
    else:
        assert cells[0]["skip"] is None


# ---------------------------------------------------------------------------
# server recovery invariants under real kill -9
# ---------------------------------------------------------------------------


def _wait_port(port, deadline_s=15.0):
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return socket.create_connection(("127.0.0.1", port),
                                            timeout=1.0)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def _spawn(module, port, data, *extra):
    p = subprocess.Popen(
        [sys.executable, "-m", module, str(port), data, *extra],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    _wait_port(port).close()
    return p


def test_kv_server_kill9_loses_only_unacked(tmp_path):
    """Acked PUTs fsync before the reply: after a kill -9 mid-write
    the recovered value is either the last ACKED write or the un-acked
    in-flight one — never anything older."""
    import urllib.error
    import urllib.parse
    import urllib.request

    port, data = 18410, str(tmp_path / "kv")
    p = _spawn("jepsen_tpu.live.kv_server", port, data)
    try:
        def put(v):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v2/keys/r",
                data=urllib.parse.urlencode({"value": v}).encode(),
                method="PUT")
            urllib.request.urlopen(req, timeout=2).close()

        for v in ("1", "2", "3"):
            put(v)  # acked
        # in-flight: bytes on the wire, reply never read, server shot
        s = socket.create_connection(("127.0.0.1", port), timeout=2)
        body = urllib.parse.urlencode({"value": "99"}).encode()
        s.sendall(b"PUT /v2/keys/r HTTP/1.1\r\n"
                  b"Host: x\r\nContent-Type: "
                  b"application/x-www-form-urlencoded\r\n"
                  + f"Content-Length: {len(body)}\r\n\r\n".encode()
                  + body)
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=5)
        s.close()
        p = _spawn("jepsen_tpu.live.kv_server", port, data)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v2/keys/r", timeout=2) as r:
            v = json.loads(r.read())["node"]["value"]
        assert v in ("3", "99"), \
            f"recovered {v!r}: an ACKED write was lost"
    finally:
        p.kill()
        p.wait(timeout=5)


def test_queue_server_kill9_keeps_acked_adds_drops_acked_jobs(tmp_path):
    """ADDJOBs acked before the crash must survive; ACKJOBed jobs must
    stay retired (no resurrection from a stale oplog replay)."""
    from jepsen_tpu.suites.disque import RespConn

    port, data = 18412, str(tmp_path / "q")
    p = _spawn("jepsen_tpu.live.queue_server", port, data)
    try:
        c = RespConn("127.0.0.1", port, timeout=5)
        c.command("ADDJOB", "jepsen", "7", 100, "RETRY", 5)
        jid2 = c.command("ADDJOB", "jepsen", "8", 100, "RETRY", 5)
        got = c.command("GETJOB", "TIMEOUT", 500, "COUNT", 1,
                        "FROM", "jepsen")
        assert got[0][2] == "7"
        c.command("ACKJOB", got[0][1])  # 7 retired durably
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=5)
        p = _spawn("jepsen_tpu.live.queue_server", port, data)
        c2 = RespConn("127.0.0.1", port, timeout=5)
        survived = []
        while True:
            got = c2.command("GETJOB", "TIMEOUT", 300, "COUNT", 1,
                             "FROM", "jepsen")
            if got is None:
                break
            survived.append(got[0][2])
            c2.command("ACKJOB", got[0][1])
        assert survived == ["8"], \
            f"expected exactly the acked-but-unconsumed job: {survived}"
        assert jid2 is not None
    finally:
        p.kill()
        p.wait(timeout=5)


def test_localnode_kill9_midwrite_loses_only_unacked(tmp_path):
    """The register family's crash contract on the localnode backend:
    a kill -9 landing mid-write loses at most the un-acked op — the
    recovered value is the last ACKED write or the in-flight one the
    harness would record :info, never anything older."""
    def rt(sock, line):
        sock.sendall((line + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            buf += sock.recv(4096)
        return buf.decode().strip()

    port, data = 18416, str(tmp_path / "ln")
    p = _spawn("jepsen_tpu.suites.localnode_server", port, data)
    try:
        s = _wait_port(port)
        for v in (1, 2, 3):
            assert rt(s, f"W a {v}") == "OK"  # acked = fsynced
        # in-flight: the write is on the wire, the reply never read —
        # exactly the op the harness records :info
        s.sendall(b"W a 99\n")
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=5)
        s.close()
        p = _spawn("jepsen_tpu.suites.localnode_server", port, data)
        s2 = _wait_port(port)
        out = rt(s2, "R a")
        assert out in ("OK 3", "OK 99"), \
            f"recovered {out!r}: an ACKED write was lost"
    finally:
        p.kill()
        p.wait(timeout=5)


def test_volatile_lock_forgets_holder_on_kill9(tmp_path):
    """The seeded-bug mechanism, deterministically at the wire level:
    a volatile lock server double-grants across a kill -9; the durable
    one must refuse the second grant."""
    def rt(sock, line):
        sock.sendall((line + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            buf += sock.recv(4096)
        return buf.decode().strip()

    for mode, expect_regrant in (("volatile", True), ("durable", False)):
        port = 18414 if mode == "volatile" else 18415
        data = str(tmp_path / mode)
        extra = ("volatile",) if mode == "volatile" else ()
        p = _spawn("jepsen_tpu.suites.localnode_server", port, data,
                   *extra)
        try:
            s = _wait_port(port)
            assert rt(s, "LOCK o1") == "OK"
            os.kill(p.pid, signal.SIGKILL)
            p.wait(timeout=5)
            p = _spawn("jepsen_tpu.suites.localnode_server", port,
                       data, *extra)
            s2 = _wait_port(port)
            out = rt(s2, "LOCK o2")
            if expect_regrant:
                assert out == "OK", \
                    "volatile server remembered its holder?"
            else:
                assert out == "BUSY", \
                    "durable server forgot a FSYNCED grant"
        finally:
            p.kill()
            p.wait(timeout=5)


# ---------------------------------------------------------------------------
# faketime wrap!/unwrap idempotence
# ---------------------------------------------------------------------------


def test_faketime_wrap_unwrap_idempotent(tmp_path):
    from jepsen_tpu import control, faketime

    sess = control.Session(node="n1", remote=control.LocalRemote())
    cmd = str(tmp_path / "server.sh")
    with open(cmd, "w") as f:
        f.write("#!/bin/sh\necho original\n")
    os.chmod(cmd, 0o755)

    faketime.wrap(sess, cmd, 120, 1.5)
    assert faketime.wrapped(sess, cmd)
    with open(cmd) as f:
        w1 = f.read()
    assert "faketime" in w1 and f"{cmd}.no-faketime" in w1
    # the original is preserved verbatim
    with open(f"{cmd}.no-faketime") as f:
        assert f.read() == "#!/bin/sh\necho original\n"
    # wrap again: idempotent (rewrites the wrapper, never wraps the
    # wrapper — the faketime.clj:20-31 contract)
    faketime.wrap(sess, cmd, 240, 2.0)
    with open(f"{cmd}.no-faketime") as f:
        assert f.read() == "#!/bin/sh\necho original\n"
    with open(cmd) as f:
        assert "x2" in f.read()
    # unwrap restores the original...
    assert faketime.unwrap(sess, cmd) is True
    assert not faketime.wrapped(sess, cmd)
    with open(cmd) as f:
        assert f.read() == "#!/bin/sh\necho original\n"
    # ...and unwrapping again is a no-op, not an error
    assert faketime.unwrap(sess, cmd) is False
    with open(cmd) as f:
        assert f.read() == "#!/bin/sh\necho original\n"


# ---------------------------------------------------------------------------
# the tier-1 campaign smoke cell (register × kill-restart, audit on)
# ---------------------------------------------------------------------------


def test_campaign_smoke_register_kill_restart(tmp_path):
    from jepsen_tpu.live.campaign import run_campaign

    record = run_campaign(
        {"time_limit": 2.5, "rate": 12, "ops_per_key": 8,
         "group_size": 2, "nodes": ["n1", "n2"], "kill_every": 1.0,
         "store_base": str(tmp_path / "store"),
         "data_root": str(tmp_path / "nodes"),
         "base_port": 18420},
        families=["register"], nemeses=["kill-restart"], seeded=False)
    assert record["summary"].get("ok") == 1, record
    [cell] = record["cells"]
    assert cell["status"] == "ok"
    assert cell["valid"] is True, cell
    # a real proof-carrying verdict: certificates audited ok
    assert cell["audit"] and cell["audit"]["ok"] is True, cell
    assert cell["audit"]["certificates"] >= 1
    # real faults were injected (kills only — heals don't count) and
    # the workload came back
    assert cell["faults"] >= 1
    assert cell["ops"] > 20
    # the campaign store holds the grid + the per-cell stream
    d = os.path.join(str(tmp_path / "store"), "campaigns",
                     record["id"])
    assert os.path.isfile(os.path.join(d, "campaign.json"))
    with open(os.path.join(d, "cells.jsonl")) as f:
        lines = [json.loads(x) for x in f if x.strip()]
    assert len(lines) == 1 and lines[0]["family"] == "register"
    # the cell's own run dir persisted results.json
    assert os.path.isfile(os.path.join(cell["store"], "results.json"))


# ---------------------------------------------------------------------------
# the campaign grid web pages
# ---------------------------------------------------------------------------


def test_web_campaign_grid(tmp_path):
    import threading
    import urllib.request

    from jepsen_tpu import web

    base = str(tmp_path / "store")
    d = os.path.join(base, "campaigns", "20260804T000000")
    os.makedirs(d)
    record = {
        "id": "20260804T000000",
        "summary": {"ok": 2, "skipped": 1, "failed": 0, "detected": 1,
                    "audited_ok": 2},
        "cells": [
            {"family": "register", "nemesis": "kill-restart",
             "seeded": False, "status": "ok", "valid": True,
             "store": base + "/live-register/20260804T000001"},
            {"family": "lock", "nemesis": "kill-restart",
             "seeded": True, "status": "ok", "valid": False,
             "detection": {"latency_s": 1.5},
             "store": base + "/live-lock/20260804T000002"},
            {"family": "lock", "nemesis": "clock-skew",
             "seeded": False, "status": "skipped",
             "reason": "no `faketime` binary on PATH"},
        ],
    }
    with open(os.path.join(d, "campaign.json"), "w") as f:
        json.dump(record, f)

    srv = web.make_server(host="127.0.0.1", port=0, base=base)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        home = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/").read().decode()
        assert "/campaigns" in home
        idx = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/campaigns").read().decode()
        assert "20260804T000000" in idx
        grid = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/campaigns/20260804T000000"
        ).read().decode()
        assert "kill-restart" in grid and "clock-skew" in grid
        assert "valid-true" in grid and "valid-false" in grid
        assert "detected in 1.5s" in grid
        assert "faketime" in grid  # the skip reason, inline
        assert "seeded" in grid
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# slow: the full matrix + the seeded-bug detection
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_matrix_campaign(tmp_path):
    """The acceptance criterion end to end: ≥3 families × ≥4 nemeses
    on a plain CPU box — every executed cell yields an audited verdict
    from a real process history, unsupported cells skip with reasons,
    and the seeded volatile-lock cell is detected by the streaming
    checker with recorded detection latency."""
    from jepsen_tpu.live.campaign import run_campaign

    record = run_campaign(
        {"time_limit": 4, "rate": 15, "ops_per_key": 10,
         "store_base": str(tmp_path / "store"),
         "data_root": str(tmp_path / "nodes"),
         "base_port": 18430},
        seeded=True)
    assert len(record["families"]) >= 3
    assert len(record["nemeses"]) >= 4
    by_status: dict = {}
    for cell in record["cells"]:
        by_status.setdefault(cell["status"], []).append(cell)
        if cell["status"] == "ok" and not cell.get("seeded"):
            assert cell["valid"] in (True, "unknown"), cell
            if cell["valid"] is True and cell.get("audit"):
                assert cell["audit"]["ok"], cell
        elif cell["status"] == "skipped":
            assert cell["reason"], cell
    assert len(by_status.get("ok", [])) >= 4
    assert not by_status.get("failed"), by_status.get("failed")
    seeded = [c for c in record["cells"] if c.get("seeded")]
    assert seeded, "the seeded volatile-lock cell never ran"
    [sc] = seeded
    if sc["status"] == "ok" and sc["valid"] is False:
        # the streamed checker caught it, with the latency recorded
        assert sc["stream_valid"] is False
        assert sc["detection"] is not None
        assert sc["detection"].get("latency_events", 0) >= 0
    else:
        # timing starvation on a loaded host can miss the stage —
        # tolerated exactly like test_localnode's volatile test
        assert sc["valid"] is not None
