"""Live fault-injection campaigns (jepsen_tpu/live/).

Tier-1 here: the dry-run planner (spawns nothing), the per-family
server recovery invariants under real kill -9 (acked state survives,
un-acked may vanish — never the reverse; volatile modes stage the
seeded bugs), faketime wrap!/unwrap idempotence, and the campaign
smoke cell (register × kill-restart, tiny history, audit on) the
acceptance criteria name.  The full ≥3-family × ≥4-nemesis matrix and
the seeded-bug detection run under ``-m slow``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# planner / CLI — no processes spawned
# ---------------------------------------------------------------------------


def test_plan_covers_full_matrix_with_skip_reasons():
    from jepsen_tpu.live.backend import FAMILIES
    from jepsen_tpu.live.campaign import plan, render_plan
    from jepsen_tpu.live.matrix import standard_matrix

    cells = plan()
    fams, nems = set(FAMILIES), set(standard_matrix())
    assert len(fams) >= 3 and len(nems) >= 4  # the acceptance floor
    base = [c for c in cells if not c["seeded"]]
    assert {(c["family"], c["nemesis"]) for c in base} \
        == {(f, n) for f in fams for n in nems}
    # every cell either runs or carries a human-readable reason
    for c in cells:
        assert c["skip"] is None or isinstance(c["skip"], str)
    # kill-restart needs nothing exotic: runnable everywhere
    assert all(c["skip"] is None for c in base
               if c["nemesis"] == "kill-restart")
    out = render_plan(cells)
    for f in fams:
        assert f in out
    for n in nems:
        assert n in out


def test_campaign_cli_dry_run_spawns_nothing():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "campaign.py"),
         "--dry-run", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    cells = json.loads(r.stdout)
    assert isinstance(cells, list) and len(cells) >= 12
    assert {"family", "nemesis", "skip"} <= set(cells[0])
    # the human rendering of the same plan (in-process: the CLI text
    # path is plain render_plan)
    from jepsen_tpu.live.campaign import render_plan

    out = render_plan(cells)
    assert "register" in out and "kill-restart" in out


def test_unknown_nemesis_probe_reason_rendering():
    from jepsen_tpu.live.campaign import plan

    cells = plan(families=["kv"], nemeses=["clock-skew"], seeded=False)
    assert len(cells) == 1
    import shutil

    if shutil.which("faketime") is None:
        assert "faketime" in cells[0]["skip"]
    else:
        assert cells[0]["skip"] is None


# ---------------------------------------------------------------------------
# server recovery invariants under real kill -9
# ---------------------------------------------------------------------------


def _wait_port(port, deadline_s=15.0):
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return socket.create_connection(("127.0.0.1", port),
                                            timeout=1.0)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def _spawn(module, port, data, *extra):
    p = subprocess.Popen(
        [sys.executable, "-m", module, str(port), data, *extra],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    _wait_port(port).close()
    return p


def test_kv_server_kill9_loses_only_unacked(tmp_path):
    """Acked PUTs fsync before the reply: after a kill -9 mid-write
    the recovered value is either the last ACKED write or the un-acked
    in-flight one — never anything older."""
    import urllib.error
    import urllib.parse
    import urllib.request

    port, data = 18410, str(tmp_path / "kv")
    p = _spawn("jepsen_tpu.live.kv_server", port, data)
    try:
        def put(v):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v2/keys/r",
                data=urllib.parse.urlencode({"value": v}).encode(),
                method="PUT")
            urllib.request.urlopen(req, timeout=2).close()

        for v in ("1", "2", "3"):
            put(v)  # acked
        # in-flight: bytes on the wire, reply never read, server shot
        s = socket.create_connection(("127.0.0.1", port), timeout=2)
        body = urllib.parse.urlencode({"value": "99"}).encode()
        s.sendall(b"PUT /v2/keys/r HTTP/1.1\r\n"
                  b"Host: x\r\nContent-Type: "
                  b"application/x-www-form-urlencoded\r\n"
                  + f"Content-Length: {len(body)}\r\n\r\n".encode()
                  + body)
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=5)
        s.close()
        p = _spawn("jepsen_tpu.live.kv_server", port, data)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v2/keys/r", timeout=2) as r:
            v = json.loads(r.read())["node"]["value"]
        assert v in ("3", "99"), \
            f"recovered {v!r}: an ACKED write was lost"
    finally:
        p.kill()
        p.wait(timeout=5)


def test_queue_server_kill9_keeps_acked_adds_drops_acked_jobs(tmp_path):
    """ADDJOBs acked before the crash must survive; ACKJOBed jobs must
    stay retired (no resurrection from a stale oplog replay)."""
    from jepsen_tpu.suites.disque import RespConn

    port, data = 18412, str(tmp_path / "q")
    p = _spawn("jepsen_tpu.live.queue_server", port, data)
    try:
        c = RespConn("127.0.0.1", port, timeout=5)
        c.command("ADDJOB", "jepsen", "7", 100, "RETRY", 5)
        jid2 = c.command("ADDJOB", "jepsen", "8", 100, "RETRY", 5)
        got = c.command("GETJOB", "TIMEOUT", 500, "COUNT", 1,
                        "FROM", "jepsen")
        assert got[0][2] == "7"
        c.command("ACKJOB", got[0][1])  # 7 retired durably
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=5)
        p = _spawn("jepsen_tpu.live.queue_server", port, data)
        c2 = RespConn("127.0.0.1", port, timeout=5)
        survived = []
        while True:
            got = c2.command("GETJOB", "TIMEOUT", 300, "COUNT", 1,
                             "FROM", "jepsen")
            if got is None:
                break
            survived.append(got[0][2])
            c2.command("ACKJOB", got[0][1])
        assert survived == ["8"], \
            f"expected exactly the acked-but-unconsumed job: {survived}"
        assert jid2 is not None
    finally:
        p.kill()
        p.wait(timeout=5)


def test_localnode_kill9_midwrite_loses_only_unacked(tmp_path):
    """The register family's crash contract on the localnode backend:
    a kill -9 landing mid-write loses at most the un-acked op — the
    recovered value is the last ACKED write or the in-flight one the
    harness would record :info, never anything older."""
    def rt(sock, line):
        sock.sendall((line + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            buf += sock.recv(4096)
        return buf.decode().strip()

    port, data = 18416, str(tmp_path / "ln")
    p = _spawn("jepsen_tpu.suites.localnode_server", port, data)
    try:
        s = _wait_port(port)
        for v in (1, 2, 3):
            assert rt(s, f"W a {v}") == "OK"  # acked = fsynced
        # in-flight: the write is on the wire, the reply never read —
        # exactly the op the harness records :info
        s.sendall(b"W a 99\n")
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=5)
        s.close()
        p = _spawn("jepsen_tpu.suites.localnode_server", port, data)
        s2 = _wait_port(port)
        out = rt(s2, "R a")
        assert out in ("OK 3", "OK 99"), \
            f"recovered {out!r}: an ACKED write was lost"
    finally:
        p.kill()
        p.wait(timeout=5)


def test_volatile_lock_forgets_holder_on_kill9(tmp_path):
    """The seeded-bug mechanism, deterministically at the wire level:
    a volatile lock server double-grants across a kill -9; the durable
    one must refuse the second grant."""
    def rt(sock, line):
        sock.sendall((line + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            buf += sock.recv(4096)
        return buf.decode().strip()

    for mode, expect_regrant in (("volatile", True), ("durable", False)):
        port = 18414 if mode == "volatile" else 18415
        data = str(tmp_path / mode)
        extra = ("volatile",) if mode == "volatile" else ()
        p = _spawn("jepsen_tpu.suites.localnode_server", port, data,
                   *extra)
        try:
            s = _wait_port(port)
            assert rt(s, "LOCK o1") == "OK"
            os.kill(p.pid, signal.SIGKILL)
            p.wait(timeout=5)
            p = _spawn("jepsen_tpu.suites.localnode_server", port,
                       data, *extra)
            s2 = _wait_port(port)
            out = rt(s2, "LOCK o2")
            if expect_regrant:
                assert out == "OK", \
                    "volatile server remembered its holder?"
            else:
                assert out == "BUSY", \
                    "durable server forgot a FSYNCED grant"
        finally:
            p.kill()
            p.wait(timeout=5)


# ---------------------------------------------------------------------------
# faketime wrap!/unwrap idempotence
# ---------------------------------------------------------------------------


def test_faketime_wrap_unwrap_idempotent(tmp_path):
    from jepsen_tpu import control, faketime

    sess = control.Session(node="n1", remote=control.LocalRemote())
    cmd = str(tmp_path / "server.sh")
    with open(cmd, "w") as f:
        f.write("#!/bin/sh\necho original\n")
    os.chmod(cmd, 0o755)

    faketime.wrap(sess, cmd, 120, 1.5)
    assert faketime.wrapped(sess, cmd)
    with open(cmd) as f:
        w1 = f.read()
    assert "faketime" in w1 and f"{cmd}.no-faketime" in w1
    # the original is preserved verbatim
    with open(f"{cmd}.no-faketime") as f:
        assert f.read() == "#!/bin/sh\necho original\n"
    # wrap again: idempotent (rewrites the wrapper, never wraps the
    # wrapper — the faketime.clj:20-31 contract)
    faketime.wrap(sess, cmd, 240, 2.0)
    with open(f"{cmd}.no-faketime") as f:
        assert f.read() == "#!/bin/sh\necho original\n"
    with open(cmd) as f:
        assert "x2" in f.read()
    # unwrap restores the original...
    assert faketime.unwrap(sess, cmd) is True
    assert not faketime.wrapped(sess, cmd)
    with open(cmd) as f:
        assert f.read() == "#!/bin/sh\necho original\n"
    # ...and unwrapping again is a no-op, not an error
    assert faketime.unwrap(sess, cmd) is False
    with open(cmd) as f:
        assert f.read() == "#!/bin/sh\necho original\n"


# ---------------------------------------------------------------------------
# the tier-1 campaign smoke cell (register × kill-restart, audit on)
# ---------------------------------------------------------------------------


def test_campaign_smoke_register_kill_restart(tmp_path):
    from jepsen_tpu.live.campaign import run_campaign

    record = run_campaign(
        {"time_limit": 2.5, "rate": 12, "ops_per_key": 8,
         "group_size": 2, "nodes": ["n1", "n2"], "kill_every": 1.0,
         "store_base": str(tmp_path / "store"),
         "data_root": str(tmp_path / "nodes"),
         "base_port": 18420},
        families=["register"], nemeses=["kill-restart"], seeded=False)
    assert record["summary"].get("ok") == 1, record
    [cell] = record["cells"]
    assert cell["status"] == "ok"
    assert cell["valid"] is True, cell
    # a real proof-carrying verdict: certificates audited ok
    assert cell["audit"] and cell["audit"]["ok"] is True, cell
    assert cell["audit"]["certificates"] >= 1
    # real faults were injected (kills only — heals don't count) and
    # the workload came back
    assert cell["faults"] >= 1
    assert cell["ops"] > 20
    # the campaign store holds the grid + the per-cell stream
    d = os.path.join(str(tmp_path / "store"), "campaigns",
                     record["id"])
    assert os.path.isfile(os.path.join(d, "campaign.json"))
    with open(os.path.join(d, "cells.jsonl")) as f:
        lines = [json.loads(x) for x in f if x.strip()]
    assert len(lines) == 1 and lines[0]["family"] == "register"
    # the cell's own run dir persisted results.json
    assert os.path.isfile(os.path.join(cell["store"], "results.json"))


# ---------------------------------------------------------------------------
# the campaign grid web pages
# ---------------------------------------------------------------------------


def test_web_campaign_grid(tmp_path):
    import threading
    import urllib.request

    from jepsen_tpu import web

    base = str(tmp_path / "store")
    d = os.path.join(base, "campaigns", "20260804T000000")
    os.makedirs(d)
    record = {
        "id": "20260804T000000",
        "summary": {"ok": 2, "skipped": 1, "failed": 0, "detected": 1,
                    "audited_ok": 2},
        "cells": [
            {"family": "register", "nemesis": "kill-restart",
             "seeded": False, "status": "ok", "valid": True,
             "store": base + "/live-register/20260804T000001"},
            {"family": "lock", "nemesis": "kill-restart",
             "seeded": True, "status": "ok", "valid": False,
             "detection": {"latency_s": 1.5},
             "store": base + "/live-lock/20260804T000002"},
            {"family": "lock", "nemesis": "clock-skew",
             "seeded": False, "status": "skipped",
             "reason": "no `faketime` binary on PATH"},
        ],
    }
    with open(os.path.join(d, "campaign.json"), "w") as f:
        json.dump(record, f)

    srv = web.make_server(host="127.0.0.1", port=0, base=base)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        home = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/").read().decode()
        assert "/campaigns" in home
        idx = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/campaigns").read().decode()
        assert "20260804T000000" in idx
        grid = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/campaigns/20260804T000000"
        ).read().decode()
        assert "kill-restart" in grid and "clock-skew" in grid
        assert "valid-true" in grid and "valid-false" in grid
        assert "detected in 1.5s" in grid
        assert "faketime" in grid  # the skip reason, inline
        assert "seeded" in grid
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# slow: the full matrix + the seeded-bug detection
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_matrix_campaign(tmp_path):
    """The acceptance criterion end to end: ≥3 families × ≥4 nemeses
    on a plain CPU box — every executed cell yields an audited verdict
    from a real process history, unsupported cells skip with reasons,
    and the seeded volatile-lock cell is detected by the streaming
    checker with recorded detection latency."""
    from jepsen_tpu.live.campaign import run_campaign

    record = run_campaign(
        {"time_limit": 4, "rate": 15, "ops_per_key": 10,
         "store_base": str(tmp_path / "store"),
         "data_root": str(tmp_path / "nodes"),
         "base_port": 18430},
        seeded=True)
    assert len(record["families"]) >= 3
    assert len(record["nemeses"]) >= 4
    by_status: dict = {}
    for cell in record["cells"]:
        by_status.setdefault(cell["status"], []).append(cell)
        if cell["status"] == "ok" and not cell.get("seeded"):
            assert cell["valid"] in (True, "unknown"), cell
            if cell["valid"] is True and cell.get("audit"):
                assert cell["audit"]["ok"], cell
        elif cell["status"] == "skipped":
            assert cell["reason"], cell
    assert len(by_status.get("ok", [])) >= 4
    assert not by_status.get("failed"), by_status.get("failed")
    seeded = [c for c in record["cells"] if c.get("seeded")]
    assert seeded, "no seeded cell ever ran"
    # the volatile-lock cell always plans on kill-restart; the
    # replicated seeded cells join it (partition only where iptables
    # exists)
    assert {(c["family"], c["nemesis"]) for c in seeded} \
        >= {("lock", "kill-restart"), ("replicated", "kill-restart")}
    for sc in seeded:
        if sc["status"] == "ok" and sc["valid"] is False:
            # the checker caught it, with detection latency recorded
            # (model-less queue cells stream through the total-queue
            # fold route and grade like everyone else)
            if "stream_valid" in sc:
                assert sc["stream_valid"] is False
            assert sc["detection"] is not None
            assert sc["detection"].get("latency_events", 0) >= 0
            if (sc["family"], sc["nemesis"]) == ("replicated",
                                                 "kill-restart"):
                # the bounded :info lookahead flips the volatile
                # cluster's amnesia MID-STREAM, not at finalize
                assert sc["detection"]["at"] == "streamed", sc
        else:
            # timing starvation on a loaded host can miss the stage —
            # tolerated exactly like test_localnode's volatile test
            assert sc["valid"] is not None


# ---------------------------------------------------------------------------
# replicated family: consensus recovery invariants at the wire level
# ---------------------------------------------------------------------------


def _repl_spawn(i, ports, base, *extra):
    p = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.live.replicated_server",
         str(ports[i]), os.path.join(base, f"n{i}"),
         "--id", str(i), "--peers", ",".join(map(str, ports)),
         "--oplog", os.path.join(base, "shared", "oplog"),
         "--lease-ms", "350", *extra],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    _wait_port(ports[i]).close()
    return p


def _repl_status(ports, i):
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{ports[i]}/_repl/status", timeout=1) as r:
        return json.loads(r.read())


def _repl_put(ports, i, k, v, timeout=3):
    import urllib.error
    import urllib.parse
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{ports[i]}/v2/keys/{k}",
        data=urllib.parse.urlencode({"value": v}).encode(),
        method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _repl_get(ports, i, k, timeout=3):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ports[i]}/v2/keys/{k}",
                timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _repl_wait_leader(ports, alive, deadline_s=25.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        leaders = []
        for i in alive:
            try:
                s = _repl_status(ports, i)
                if s["role"] == "leader":
                    leaders.append(i)
            except OSError:
                pass
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.1)
    raise AssertionError(f"no single leader among {alive}")


def _repl_put_retry(ports, i, k, v, deadline_s=25.0):
    """PUT until acked (elections in progress return 5xx briefly; the
    generous deadline covers a loaded CI box where process churn
    stretches election rounds)."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            st, body = _repl_put(ports, i, k, v)
            if st == 200:
                return body
        except OSError:
            pass
        if time.monotonic() > deadline:
            raise AssertionError(f"write {k}={v} never acked via {i}")
        time.sleep(0.15)


def test_replicated_majority_accepts_and_healed_minority_converges(
        tmp_path):
    """The consensus contract under a minority outage: with one node
    (follower OR leader) frozen, the majority keeps accepting ACKED
    writes; when the minority heals it converges to the majority's
    state — served reads through it return the latest value, never a
    stale one."""
    ports = [18440, 18441, 18442]
    base = str(tmp_path)
    procs = [_repl_spawn(i, ports, base) for i in range(3)]
    try:
        leader = _repl_wait_leader(ports, range(3))
        _repl_put_retry(ports, leader, "r", "v1")
        # freeze a FOLLOWER: majority (leader + 1) still acks
        follower = next(i for i in range(3) if i != leader)
        os.kill(procs[follower].pid, signal.SIGSTOP)
        _repl_put_retry(ports, leader, "r", "v2")
        os.kill(procs[follower].pid, signal.SIGCONT)
        # freeze the LEADER: the surviving majority elects and acks
        os.kill(procs[leader].pid, signal.SIGSTOP)
        alive = [i for i in range(3) if i != leader]
        new_leader = _repl_wait_leader(ports, alive)
        _repl_put_retry(ports, new_leader, "r", "v3")
        # heal the minority: the thawed ex-leader must converge — a
        # read through it (proxy or local after catch-up) shows v3,
        # and its replica state catches up to the leader's seq
        os.kill(procs[leader].pid, signal.SIGCONT)
        deadline = time.monotonic() + 10
        seen = None
        while time.monotonic() < deadline:
            try:
                st, body = _repl_get(ports, leader, "r")
                seen = body.get("node", {}).get("value")
                if seen == "v3":
                    break
            except OSError:
                pass
            time.sleep(0.15)
        assert seen == "v3", f"healed minority served {seen!r}"
        assert seen != "v2", "healed minority served a STALE read"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if _repl_status(ports, leader)["seq"] \
                    >= _repl_status(ports, new_leader)["seq"]:
                break
            time.sleep(0.15)
        assert _repl_status(ports, leader)["seq"] \
            >= _repl_status(ports, new_leader)["seq"], \
            "healed minority never caught up from the shared oplog"
    finally:
        for p in procs:
            try:
                os.kill(p.pid, signal.SIGCONT)
            except OSError:
                pass
            p.kill()
            p.wait(timeout=5)


def test_replicated_leader_kill9_loses_only_unacked(tmp_path):
    """kill -9 of the LEADER: every acked write survives (majority
    memory + the shared oplog); the restarted ex-leader catches up
    rather than resurrecting stale state."""
    ports = [18444, 18445, 18446]
    base = str(tmp_path)
    procs = [_repl_spawn(i, ports, base) for i in range(3)]
    try:
        leader = _repl_wait_leader(ports, range(3))
        for v in ("1", "2", "3"):
            _repl_put_retry(ports, leader, "r", v)
        os.kill(procs[leader].pid, signal.SIGKILL)
        procs[leader].wait(timeout=5)
        alive = [i for i in range(3) if i != leader]
        new_leader = _repl_wait_leader(ports, alive)
        st, body = _repl_get(ports, new_leader, "r")
        assert body.get("node", {}).get("value") == "3", \
            f"an ACKED write was lost across leader kill -9: {body}"
        # restart the old leader; it rejoins as a follower and reads
        # through it reach the current state
        procs[leader] = _repl_spawn(leader, ports, base)
        deadline = time.monotonic() + 10
        val = None
        while time.monotonic() < deadline:
            try:
                st, body = _repl_get(ports, leader, "r")
                val = body.get("node", {}).get("value")
                if val == "3":
                    break
            except OSError:
                pass
            time.sleep(0.15)
        assert val == "3", f"restarted ex-leader served {val!r}"
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=5)


def test_replicated_volatile_forgets_acked_on_total_crash(tmp_path):
    """The kill-seeded bug, deterministically at the wire level: a
    VOLATILE cluster (no durable oplog, completeness-free elections)
    that loses every node forgets acked writes — exactly what the
    campaign's replicated×kill-restart seeded cell stages and the
    streaming checker's `:info` lookahead must flip mid-stream."""
    ports = [18447, 18448, 18449]
    base = str(tmp_path)
    procs = [_repl_spawn(i, ports, base, "volatile") for i in range(3)]
    try:
        leader = _repl_wait_leader(ports, range(3))
        _repl_put_retry(ports, leader, "r", "7")
        for p in procs:
            os.kill(p.pid, signal.SIGKILL)
            p.wait(timeout=5)
        procs = [_repl_spawn(i, ports, base, "volatile")
                 for i in range(3)]
        leader = _repl_wait_leader(ports, range(3))
        st, body = _repl_get(ports, leader, "r")
        assert st == 404, \
            f"volatile cluster remembered an acked write: {body}"
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=5)


def test_replicated_split_brain_mode_serves_stale_reads(tmp_path):
    """The partition-seeded bug at the wire level: a split-brain
    leader paused past its lease neither steps down nor adopts its
    successor's writes — after the thaw, reads through it regress to
    the pre-partition value while the new leader serves the fresh
    one (two leaders, client-visible staleness)."""
    ports = [18450, 18451, 18452]
    base = str(tmp_path)
    procs = [_repl_spawn(i, ports, base, "split-brain")
             for i in range(3)]
    try:
        leader = _repl_wait_leader(ports, range(3))
        _repl_put_retry(ports, leader, "r", "old")
        os.kill(procs[leader].pid, signal.SIGSTOP)
        alive = [i for i in range(3) if i != leader]
        new_leader = _repl_wait_leader(ports, alive)
        _repl_put_retry(ports, new_leader, "r", "new")
        os.kill(procs[leader].pid, signal.SIGCONT)
        st, body = _repl_get(ports, leader, "r")
        assert body.get("node", {}).get("value") == "old", \
            f"expected the stale read, got {body}"
        st2, body2 = _repl_get(ports, new_leader, "r")
        assert body2["node"]["value"] == "new"
    finally:
        for p in procs:
            try:
                os.kill(p.pid, signal.SIGCONT)
            except OSError:
                pass
            p.kill()
            p.wait(timeout=5)


# ---------------------------------------------------------------------------
# the self-healing campaign runner: --resume, retries, watchdog
# ---------------------------------------------------------------------------


def test_campaign_resume_skips_completed_cells(tmp_path, monkeypatch):
    """Kill a campaign mid-matrix, resume it: completed cells are NOT
    re-run, the rest execute, and campaign.json ends up complete."""
    from jepsen_tpu.live import campaign as camp

    executed = []
    arm = {"die_at": 2}

    def fake_run_cell(cell, opts):
        executed.append((cell["family"], cell["nemesis"]))
        if len(executed) == arm["die_at"]:
            # the campaign dies mid-matrix AFTER cell 1 was recorded
            raise KeyboardInterrupt("campaign killed")
        return {**cell, "status": "ok", "valid": True, "ops": 1}

    monkeypatch.setattr(camp, "run_cell", fake_run_cell)
    opts = {"store_base": str(tmp_path), "campaign_id": "c1",
            "cell_retries": 0}
    import pytest as _pytest

    with _pytest.raises(KeyboardInterrupt):
        camp.run_campaign(opts, families=["register", "kv", "lock"],
                          nemeses=["kill-restart"], seeded=False)
    d = os.path.join(str(tmp_path), "campaigns", "c1")
    with open(os.path.join(d, "cells.jsonl")) as f:
        recorded = [json.loads(x) for x in f if x.strip()]
    assert len(recorded) == 1  # only the completed cell survived
    assert len(executed) == 2

    executed.clear()
    arm["die_at"] = -1  # disarmed: the resumed campaign completes
    record = camp.run_campaign(opts, families=["register", "kv",
                                               "lock"],
                               nemeses=["kill-restart"], seeded=False,
                               resume=True)
    # the completed cell was NOT re-executed; the other two were
    assert len(executed) == 2
    assert recorded[0]["family"] not in {f for f, _ in executed}
    assert record["resumed_cells"] == 1
    assert len(record["cells"]) == 3
    assert all(c["status"] == "ok" for c in record["cells"])
    resumed = [c for c in record["cells"] if c.get("resumed")]
    assert len(resumed) == 1
    with open(os.path.join(d, "cells.jsonl")) as f:
        assert len([x for x in f if x.strip()]) == 3

    # a recorded RETRYABLE harness failure does not count as
    # completed: resume re-runs that cell (the resume skip-set and
    # the retry policy agree on what is terminal)
    with open(os.path.join(d, "cells.jsonl"), "a") as f:
        f.write(json.dumps({"family": "register",
                            "nemesis": "kill-restart",
                            "seeded": False, "skip": None,
                            "status": "failed",
                            "reason": "RuntimeError: transient"})
                + "\n")
    executed.clear()
    record2 = camp.run_campaign(opts, families=["register", "kv",
                                                "lock"],
                                nemeses=["kill-restart"], seeded=False,
                                resume=True)
    assert ("register", "kill-restart") in executed
    assert len(executed) == 1  # kv and lock resumed from their lines
    assert record2["resumed_cells"] == 2
    reg2 = next(c for c in record2["cells"]
                if c["family"] == "register")
    assert reg2["status"] == "ok" and not reg2.get("resumed")


def test_campaign_retries_harness_errors_not_verdicts(tmp_path,
                                                      monkeypatch):
    """A cell failing on a HARNESS error is retried (bounded); a cell
    with a real verdict — even invalid — is never re-run."""
    from jepsen_tpu.live import campaign as camp

    calls = {"register": 0, "kv": 0}

    def fake_run_cell(cell, opts):
        calls[cell["family"]] += 1
        if cell["family"] == "register" and calls["register"] == 1:
            return {**cell, "status": "failed",
                    "reason": "RuntimeError: transient"}
        if cell["family"] == "kv":
            return {**cell, "status": "ok", "valid": False}
        return {**cell, "status": "ok", "valid": True}

    monkeypatch.setattr(camp, "run_cell", fake_run_cell)
    record = camp.run_campaign(
        {"store_base": str(tmp_path), "cell_retries": 2},
        families=["register", "kv"], nemeses=["kill-restart"],
        seeded=False)
    assert calls["register"] == 2  # failed once, retried, succeeded
    assert calls["kv"] == 1        # invalid verdict: never retried
    reg = next(c for c in record["cells"]
               if c["family"] == "register")
    assert reg["status"] == "ok" and reg["attempts"] == 2
    kv = next(c for c in record["cells"] if c["family"] == "kv")
    assert kv["attempts"] == 1 and kv["valid"] is False


def test_watchdog_escalates_on_wedged_backend(tmp_path):
    """The per-cell watchdog: a backend process wedged (SIGSTOP, so
    even SIGTERM alone wouldn't land cleanly) past the budget is
    thawed, terminated, and — if needed — SIGKILLed; the sweep records
    what it killed."""
    from jepsen_tpu.live.campaign import _Watchdog

    d = tmp_path / "nodes" / "n1"
    d.mkdir(parents=True)
    p = subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(600)"])
    (d / "server.pid").write_text(str(p.pid))
    os.kill(p.pid, signal.SIGSTOP)  # wedged: frozen mid-flight
    try:
        wd = _Watchdog(0.2, str(tmp_path / "nodes"),
                       grace_s=0.3, resweep_s=0.2).start()
        deadline = time.monotonic() + 15
        while p.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        wd.stop()
        assert p.poll() is not None, "watchdog never killed the pid"
        assert wd.fired
        assert p.pid in wd.killed
    finally:
        try:
            os.kill(p.pid, signal.SIGKILL)
        except OSError:
            pass
        p.wait(timeout=5)


def test_cell_budget_scales_with_time_limit():
    from jepsen_tpu.live.campaign import cell_budget

    assert cell_budget({"cell_budget": 42}) == 42.0
    assert cell_budget({"time_limit": 8}) == max(120.0, 8 * 10 + 90.0)
    assert cell_budget({"time_limit": 60}) == 690.0


# ---------------------------------------------------------------------------
# tier-1 smoke: replicated × partition (skipped-with-reason sans iptables)
# ---------------------------------------------------------------------------


def test_campaign_smoke_replicated_partition(tmp_path):
    """The replicated×partition cell end to end where the host can
    inject loopback partitions; a human-readable capability skip
    everywhere else — the degradation contract, pinned in tier-1."""
    from jepsen_tpu.live.campaign import run_campaign
    from jepsen_tpu.live.matrix import probe_iptables

    record = run_campaign(
        {"time_limit": 4, "rate": 12, "lease_ms": 400,
         "part_every": 1.5,
         "store_base": str(tmp_path / "store"),
         "data_root": str(tmp_path / "nodes"),
         "base_port": 18460},
        families=["replicated"], nemeses=["partition"], seeded=False)
    [cell] = [c for c in record["cells"] if not c.get("seeded")]
    reason = probe_iptables()
    if reason is not None:
        assert cell["status"] == "skipped"
        assert cell["reason"] == reason
        assert ("iptables" in cell["reason"]
                or "NET_ADMIN" in cell["reason"])
    else:
        assert cell["status"] == "ok", cell
        # consensus under partition: the cell completes with an
        # audited verdict (valid unless the partition outlasted the
        # checker's patience — then unknown is honest)
        assert cell["valid"] in (True, "unknown"), cell
        if cell["valid"] is True and cell.get("audit"):
            assert cell["audit"]["ok"], cell


# ---------------------------------------------------------------------------
# replicated queue: consensus redelivery invariants at the wire level
# ---------------------------------------------------------------------------


def _rq_spawn(i, ports, base, *extra):
    peers = ",".join(f"127.0.1.{j + 1}:{p}"
                     for j, p in enumerate(ports))
    p = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.live.replicated_queue",
         str(ports[i]), os.path.join(base, f"n{i}"),
         "--id", str(i), "--peers", peers,
         "--host", f"127.0.1.{i + 1}",
         "--oplog", os.path.join(base, "shared", "oplog"),
         "--lease-ms", "350", *extra],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    deadline = time.monotonic() + 15
    while True:
        try:
            socket.create_connection(
                (f"127.0.1.{i + 1}", ports[i]), timeout=1.0).close()
            return p
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def _rq_leader(ports, alive, deadline_s=25.0):
    import urllib.request

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        leaders = []
        for i in alive:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.1.{i + 1}:{ports[i] + 500}"
                        f"/_repl/status", timeout=1) as r:
                    if json.loads(r.read())["role"] == "leader":
                        leaders.append(i)
            except OSError:
                pass
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.1)
    raise AssertionError(f"no single leader among {alive}")


def _rq_conn(ports, i):
    from jepsen_tpu.suites.disque import RespConn

    return RespConn(f"127.0.1.{i + 1}", ports[i], timeout=5)


def _rq_add_retry(ports, i, body, deadline_s=25.0):
    from jepsen_tpu.suites.disque import RespError

    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return _rq_conn(ports, i).command(
                "ADDJOB", "jepsen", body, 100, "RETRY", 1)
        except (RespError, OSError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.15)


def test_replicated_queue_redelivers_unacked_across_leader_kill(
        tmp_path):
    """The redelivery contract the single-node queue family could
    never stage: a job CLAIMED but un-acked on a leader that dies is
    redelivered by the new leader (claims are leader-local; pending is
    replicated) — at-least-once, never silent loss.  ACKJOB is a
    majority commit, so acked jobs stay retired across a restart."""
    ports = [18480, 18481, 18482]
    base = str(tmp_path)
    procs = [_rq_spawn(i, ports, base) for i in range(3)]
    try:
        leader = _rq_leader(ports, range(3))
        # enqueue VIA A FOLLOWER: the proxy path is the wire contract
        follower = next(i for i in range(3) if i != leader)
        jid = _rq_add_retry(ports, follower, "41")
        assert jid and jid.startswith("D-")
        got = _rq_conn(ports, leader).command(
            "GETJOB", "TIMEOUT", 2000, "COUNT", 1, "FROM", "jepsen")
        assert got[0][2] == "41"
        # claimed, NOT acked — shoot the leader
        os.kill(procs[leader].pid, signal.SIGKILL)
        procs[leader].wait(timeout=5)
        alive = [i for i in range(3) if i != leader]
        nl = _rq_leader(ports, alive)
        c = _rq_conn(ports, nl)
        got2 = c.command("GETJOB", "TIMEOUT", 4000, "COUNT", 1,
                         "FROM", "jepsen")
        assert got2 and got2[0][2] == "41", \
            "un-acked claim was not redelivered after leader kill -9"
        assert c.command("ACKJOB", got2[0][1]) == 1
        # restart the dead node; the ACKED job must stay retired
        procs[leader] = _rq_spawn(leader, ports, base)
        time.sleep(1.0)
        got3 = c.command("GETJOB", "TIMEOUT", 2500, "COUNT", 1,
                         "FROM", "jepsen")
        assert got3 is None, f"acked job resurrected: {got3}"
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=5)


def test_replicated_queue_volatile_forgets_acked_adds(tmp_path):
    """The seeded redelivery bug at the wire level: a VOLATILE cluster
    that loses every node forgets acked ADDJOBs — what the
    replicated-queue × link-bridge seeded cell stages (there via an
    election through the bridge instead of a full crash)."""
    ports = [18484, 18485, 18486]
    base = str(tmp_path)
    procs = [_rq_spawn(i, ports, base, "volatile") for i in range(3)]
    try:
        leader = _rq_leader(ports, range(3))
        assert _rq_add_retry(ports, leader, "7")
        for p in procs:
            os.kill(p.pid, signal.SIGKILL)
            p.wait(timeout=5)
        procs = [_rq_spawn(i, ports, base, "volatile")
                 for i in range(3)]
        leader = _rq_leader(ports, range(3))
        got = _rq_conn(ports, leader).command(
            "GETJOB", "TIMEOUT", 1500, "COUNT", 1, "FROM", "jepsen")
        assert got is None, \
            f"volatile cluster remembered an acked ADDJOB: {got}"
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=5)


# ---------------------------------------------------------------------------
# pgwire: durability + the campaign row it was missing
# ---------------------------------------------------------------------------


def test_pgwire_server_kill9_loses_only_unacked(tmp_path):
    """The live pgwire daemon's crash contract: COMMITs are fsync'd
    before the reply (live/pgwire_server.py), so kill -9 loses at most
    the in-flight transaction."""
    from jepsen_tpu.suites import pgwire

    port, data = 18492, str(tmp_path / "pg")

    def spawn():
        p = subprocess.Popen(
            [sys.executable, "-m", "jepsen_tpu.live.pgwire_server",
             str(port), data],
            cwd=REPO, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        _wait_port(port).close()
        return p

    p = spawn()
    try:
        conn = pgwire.connect("127.0.0.1", port)
        conn.autocommit = False
        for v in (1, 2, 3):
            with conn:
                with conn.cursor() as cur:
                    cur.execute("UPSERT INTO registers (id, value) "
                                "VALUES (%s, %s)", (0, v))
        # open a transaction, write, DON'T commit — then shoot it
        with conn.cursor() as cur:
            cur.execute("UPSERT INTO registers (id, value) "
                        "VALUES (%s, %s)", (0, 99))
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=5)
        p = spawn()
        conn2 = pgwire.connect("127.0.0.1", port)
        conn2.autocommit = False
        with conn2:
            with conn2.cursor() as cur:
                cur.execute("SELECT value FROM registers WHERE id=%s",
                            (0,))
                row = cur.fetchone()
        assert row == (3,), \
            f"recovered {row!r}: committed write lost (or an " \
            f"UNcommitted one survived)"
    finally:
        p.kill()
        p.wait(timeout=5)


def test_campaign_smoke_pgwire_kill_restart(tmp_path):
    """The pgwire family through the campaign runner — the matrix row
    it never had: a real kill-restart cell over the durable pg-wire
    daemon, audited, with the SQL client's txn machinery on the wire."""
    from jepsen_tpu.live.campaign import run_campaign

    record = run_campaign(
        {"time_limit": 2.5, "rate": 12, "ops_per_key": 8,
         "group_size": 2, "kill_every": 1.0,
         "store_base": str(tmp_path / "store"),
         "data_root": str(tmp_path / "nodes"),
         "base_port": 18494},
        families=["pgwire"], nemeses=["kill-restart"], seeded=False)
    assert record["summary"].get("ok") == 1, record
    [cell] = record["cells"]
    assert cell["valid"] is True, cell
    assert cell["audit"] and cell["audit"]["ok"] is True, cell
    assert cell["faults"] >= 1
    assert cell["ops"] > 10


# ---------------------------------------------------------------------------
# per-peer-link cells: smoke + the sweep-verified no-leak contract
# ---------------------------------------------------------------------------


def test_campaign_smoke_replicated_link_split_one(tmp_path):
    """A link-partition cell end to end where the host has a rule
    engine (iptables or tc); a human-readable capability skip
    elsewhere.  Either way: after the cell, NO partition rule remains
    installed (journal empty — the sweep-verified heal contract)."""
    from jepsen_tpu.live import links
    from jepsen_tpu.live.campaign import run_campaign

    data_root = str(tmp_path / "nodes")
    record = run_campaign(
        {"time_limit": 4, "rate": 12, "lease_ms": 400,
         "part_every": 1.5,
         "store_base": str(tmp_path / "store"),
         "data_root": data_root, "base_port": 18496},
        families=["replicated"], nemeses=["link-split-one"],
        seeded=False)
    [cell] = [c for c in record["cells"] if not c.get("seeded")]
    reason = links.probe_links()
    if reason is not None:
        assert cell["status"] == "skipped"
        assert cell["reason"] == reason
    else:
        assert cell["status"] == "ok", cell
        assert cell["valid"] in (True, "unknown"), cell
        assert cell["faults"] >= 1
        # the cell banked its history into the regression corpus
        from jepsen_tpu.live import corpus as corpus_mod

        assert cell.get("corpus"), cell
        assert cell["corpus"]["pool"] >= 1
        assert corpus_mod.load_pool(corpus_mod.corpus_dir(
            str(tmp_path / "store")))
    # sweep verified: no journaled rule outlives the cell
    assert links.journal_rules(data_root) == []


@pytest.mark.slow
def test_seeded_split_brain_link_isolate_leader(tmp_path):
    """Acceptance: the split-brain cell — replicated × isolate-leader
    ASYMMETRIC grudge.  The one-way cut drops only the leader's
    outbound peer links; the majority elects a successor while the
    seeded split-brain leader keeps serving its (uncut) clients stale
    reads — detected invalid with recorded streamed-vs-finalize
    detection latency, corpus banked, and zero rules left installed."""
    from jepsen_tpu.live import corpus, links
    from jepsen_tpu.live.campaign import run_campaign

    if links.probe_links() is not None:
        pytest.skip(f"no link rule engine: {links.probe_links()}")
    data_root = str(tmp_path / "nodes")
    record = run_campaign(
        {"store_base": str(tmp_path / "store"),
         "data_root": data_root, "base_port": 18520},
        families=["replicated"], nemeses=["link-isolate-leader"],
        seeded=True)
    [sc] = [c for c in record["cells"] if c.get("seeded")]
    assert sc["status"] == "ok", sc
    assert links.journal_rules(data_root) == []  # sweep verified
    if sc["valid"] is False:
        det = sc["detection"]
        assert det is not None
        assert det["at"] in ("streamed", "finalize")
        assert det.get("latency_events", -1) >= 0
        # the history was banked into the corpus...
        pool = corpus.load_pool(
            corpus.corpus_dir(str(tmp_path / "store")))
        assert any(e["family"] == "replicated" and e.get("seeded")
                   for e in pool)
        # ...and replays through ALL engine routes, parity + audit
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import fuzz as fuzz_tool

        assert fuzz_tool.corpus_replay(
            corpus.corpus_dir(str(tmp_path / "store"))) == 0
    else:
        # election timing on a starved host can outrun the grudge —
        # tolerated like the other seeded cells
        assert sc["valid"] is not None


@pytest.mark.slow
def test_seeded_redelivery_link_bridge(tmp_path):
    """Acceptance: the redelivery cell — replicated-queue × bridge
    grudge.  Volatile replicas under the majority-with-overlap cut
    lose acked ADDJOBs to an election through the bridge node; the
    final drain comes up short — detected invalid with recorded
    detection latency, banked, replayed, and no rules left."""
    from jepsen_tpu.live import corpus, links
    from jepsen_tpu.live.campaign import run_campaign

    if links.probe_links() is not None:
        pytest.skip(f"no link rule engine: {links.probe_links()}")
    data_root = str(tmp_path / "nodes")
    record = run_campaign(
        {"store_base": str(tmp_path / "store"),
         "data_root": data_root, "base_port": 18530},
        families=["replicated-queue"], nemeses=["link-bridge"],
        seeded=True)
    [sc] = [c for c in record["cells"] if c.get("seeded")]
    assert sc["status"] == "ok", sc
    assert links.journal_rules(data_root) == []  # sweep verified
    if sc["valid"] is False:
        det = sc["detection"]
        # the total-queue fold route: the live verdict flips AT the
        # short final drain — streamed grading with recorded latency,
        # final verdict bit-identical to the post-hoc multiset
        # checker, W007 evidence passing the independent audit
        assert det is not None and det["at"] == "streamed", det
        assert det.get("fold") == "total-queue"
        assert det.get("latency_events", -1) >= 0
        assert sc.get("stream_valid") is False
        if sc.get("stream_audit") is not None:
            assert sc["stream_audit"]["ok"], sc["stream_audit"]
        pool = corpus.load_pool(
            corpus.corpus_dir(str(tmp_path / "store")))
        assert any(e["routes"] == "queue" for e in pool)
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import fuzz as fuzz_tool

        assert fuzz_tool.corpus_replay(
            corpus.corpus_dir(str(tmp_path / "store"))) == 0
    else:
        assert sc["valid"] is not None


@pytest.mark.slow
def test_seeded_replicated_kill_restart_streamed_detection(tmp_path):
    """The PR's acceptance criterion end to end: the volatile
    replicated cluster under whole-cluster kill -9 loses acked writes,
    the streaming checker's `:info` lookahead flips the verdict
    MID-STREAM (detection labelled "streamed", not "finalize"), and
    the campaign records it."""
    from jepsen_tpu.live.campaign import run_campaign

    record = run_campaign(
        {"store_base": str(tmp_path / "store"),
         "data_root": str(tmp_path / "nodes"),
         "base_port": 18470},
        families=["replicated"], nemeses=["kill-restart"], seeded=True)
    [sc] = [c for c in record["cells"] if c.get("seeded")]
    assert sc["status"] == "ok", sc
    if sc["valid"] is False:
        assert sc["stream_valid"] is False
        det = sc["detection"]
        assert det is not None and det["at"] == "streamed", det
        assert det.get("latency_events", -1) >= 0
        # persisted in the campaign store
        d = os.path.join(str(tmp_path / "store"), "campaigns",
                         record["id"])
        with open(os.path.join(d, "cells.jsonl")) as f:
            [line] = [json.loads(x) for x in f if x.strip()
                      if json.loads(x).get("seeded")]
        assert line["detection"]["at"] == "streamed"
    else:
        # timing starvation on a loaded host (elections outracing the
        # kill cadence) — tolerated like the other seeded cells
        assert sc["valid"] is not None
