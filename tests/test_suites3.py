"""Third suite tranche: aerospike (roster workflow + capped kill
nemesis), crate (version-divergence/lost-updates checkers), rethinkdb
(reconfigure grudge math), tidb (three-daemon orchestration)."""

import random

from jepsen_tpu.history import Op

from test_suites import dummy_test


def mkop(**kw):
    base = dict(index=0, type="ok", f="read", value=None, process=0,
                time=0)
    base.update(kw)
    return Op(**base)


# --- aerospike ------------------------------------------------------------


def test_aerospike_parse_kv_and_roster():
    from jepsen_tpu.suites import aerospike

    kv = aerospike.parse_kv("migrate_allowed=true;migrate_partitions_"
                            "remaining=0")
    assert kv["migrate_allowed"] == "true"

    test, r = dummy_test()
    resp = ("roster=A,B,C:pending_roster=A,B,C:"
            "observed_nodes=A,B,C")
    r.responses["asinfo -v roster:namespace=jepsen"] = (0, resp, "")
    from jepsen_tpu.control import Session

    sess = Session(node="n1", remote=r)
    ro = aerospike.roster(sess)
    assert ro["roster"] == ["A", "B", "C"]
    assert ro["observed_nodes"] == ["A", "B", "C"]


def test_aerospike_config_template():
    from jepsen_tpu.suites import aerospike

    conf = aerospike.config_template(
        "10.0.0.1", "10.0.0.9", replication_factor=3,
        heartbeat_interval=150, commit_to_device=False)
    assert "mesh-seed-address-port 10.0.0.9 3002" in conf
    assert "replication-factor 3" in conf
    assert "strong-consistency true" in conf
    assert "storage-engine memory" in conf
    conf2 = aerospike.config_template(
        "a", "b", replication_factor=2, heartbeat_interval=150,
        commit_to_device=True)
    assert "commit-to-device true" in conf2


def test_aerospike_capped_kill():
    from jepsen_tpu.suites import aerospike

    assert aerospike.capped_conj({"a"}, "b", 1) == {"a"}
    assert aerospike.capped_conj({"a"}, "b", 2) == {"a", "b"}
    assert aerospike.capped_conj({"a"}, "a", 1) == {"a"}

    test, r = dummy_test()
    nem = aerospike.KillNemesis(max_dead=1)
    op = mkop(type="info", f="kill", value=["n1", "n2"], process="nemesis")
    out = nem.invoke(test, op)
    vals = sorted(out.value.values())
    # cap 1: exactly one node actually killed
    assert vals.count("killed") == 1 and vals.count("still-alive") == 1
    killed = [n for n, v in out.value.items() if v == "killed"][0]
    out2 = nem.invoke(test, mkop(type="info", f="restart",
                                 value=[killed], process="nemesis"))
    assert out2.value[killed] == "started"
    assert nem.dead == set()


def test_aerospike_db_setup_commands():
    from jepsen_tpu.suites import aerospike

    test, r = dummy_test(nodes=("n1",))
    test["barrier"] = "no-barrier"
    roster_resp = ("roster=n1:pending_roster=n1:observed_nodes=n1")
    r.responses["ls /tmp/packages"] = (
        0, "aerospike-server.deb\naerospike-tools.deb\n", "")
    r.responses["asinfo -v roster:namespace=jepsen"] = (0, roster_resp, "")
    r.responses["asinfo -v statistics"] = (
        0, "migrate_allowed=true;migrate_partitions_remaining=0", "")
    r.responses["getent ahosts n1"] = (0, "10.0.0.1 STREAM n1\n", "")
    aerospike.db().setup(test, "n1")
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("dpkg -i --force-confnew" in c for c in cmds)
    assert any("service aerospike start" in c for c in cmds)
    assert any("roster-set:namespace=jepsen" in c for c in cmds)
    assert any("asadm" in c and "recluster" in c for c in cmds)


def test_aerospike_workloads_construct():
    from jepsen_tpu.suites import aerospike

    for wl in aerospike.WORKLOADS:
        t = aerospike.aerospike_test({"workload": wl, "nodes": ["n1"],
                                      "time_limit": 1})
        assert t["client"] is not None
        assert t["generator"] is not None
        assert wl in t["name"]


def test_aerospike_tla_spec_exists():
    import os

    p = os.path.join(os.path.dirname(__file__), "..", "native", "spec",
                     "aerospike_cp.tla")
    src = open(p).read()
    assert "NoSplitBrain" in src and "Revive" in src


# --- crate ----------------------------------------------------------------


def test_crate_config_yml():
    from jepsen_tpu.suites import crate

    yml = crate.config_yml({"nodes": ["n1", "n2", "n3"]}, "n2")
    assert "node.name: n2" in yml
    assert 'discovery.zen.minimum_master_nodes: 2' in yml
    assert '"n3:44300"' in yml


def test_crate_multiversion_checker():
    from jepsen_tpu.suites import crate

    ch = crate.multiversion_checker()
    good = [
        mkop(index=0, value={"value": 1, "_version": 1}),
        mkop(index=1, value={"value": 1, "_version": 1}),
        mkop(index=2, value={"value": 2, "_version": 2}),
    ]
    assert ch.check({}, good)["valid"] is True

    bad = good + [mkop(index=3, value={"value": 9, "_version": 2})]
    out = ch.check({}, bad)
    assert out["valid"] is False
    assert 2 in out["multis"]


def test_crate_tests_construct():
    from jepsen_tpu.suites import crate

    for wl in crate.TESTS:
        t = crate.crate_test({"workload": wl, "nodes": ["n1"],
                              "time_limit": 1})
        assert wl in t["name"]
        assert t["checker"] is not None


# --- rethinkdb ------------------------------------------------------------


def test_rethinkdb_config():
    from jepsen_tpu.suites import rethinkdb

    conf = rethinkdb.config({"nodes": ["n1", "n2"]}, "n1")
    assert "join=n1:29015" in conf and "join=n2:29015" in conf
    assert "server-name=n1" in conf


def test_rethinkdb_random_topology_and_grudge():
    from jepsen_tpu.suites import rethinkdb

    random.seed(5)
    nodes = ["n1", "n2", "n3", "n4", "n5"]
    for _ in range(20):
        primary, replicas = rethinkdb.random_topology(nodes)
        assert primary in replicas
        assert set(replicas) <= set(nodes)
        assert len(set(replicas)) == len(replicas)

    saw_empty = saw_grudge = False
    for _ in range(50):
        g = rethinkdb.reconfigure_grudge(nodes, "n1")
        if not g:
            saw_empty = True
            continue
        saw_grudge = True
        # complete grudge over a bisection: every node blocks the other
        # half
        assert set(g.keys()) == set(nodes)
        for dst, srcs in g.items():
            assert dst not in srcs
            assert 0 < len(srcs) < len(nodes)
    assert saw_empty and saw_grudge


def test_rethinkdb_db_commands():
    from jepsen_tpu.suites import rethinkdb

    test, r = dummy_test(nodes=("n1",))
    r.responses["apt-get install"] = (0, "", "")
    rethinkdb.db("2.3.5~0jessie").setup(test, "n1")
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("apt-key add" in c for c in cmds)
    assert any("/etc/rethinkdb/instances.d/jepsen.conf" in c
               for c in cmds)
    assert any("service rethinkdb start" in c for c in cmds)


def test_rethinkdb_test_constructs():
    from jepsen_tpu.suites import rethinkdb

    for nem in rethinkdb.NEMESES:
        t = rethinkdb.document_cas_test(
            {"nemesis": nem, "write_acks": "single",
             "read_mode": "outdated", "nodes": ["n1"], "time_limit": 1})
        assert "w=single" in t["name"] and "r=outdated" in t["name"]


# --- tidb -----------------------------------------------------------------


def test_tidb_cluster_strings():
    from jepsen_tpu.suites import tidb

    test = {"nodes": ["n1", "n2"]}
    assert tidb.initial_cluster(test) == \
        "pd-n1=http://n1:2380,pd-n2=http://n2:2380"
    assert tidb.pd_endpoints(test) == "n1:2379,n2:2379"


def test_tidb_db_commands():
    from jepsen_tpu.suites import tidb

    test, r = dummy_test(nodes=("n1",))
    test["barrier"] = "no-barrier"
    r.responses["stat /"] = (1, "", "no")
    r.responses["ls -A"] = (0, "tidb-latest-linux-amd64\n", "")
    r.responses["dirname"] = (0, "/opt", "")
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        tidb.db("file:///tmp/tidb.tar.gz").setup(test, "n1")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    pd = [i for i, c in enumerate(cmds) if "pd-server" in c
          and "start-stop-daemon" in c]
    kv = [i for i, c in enumerate(cmds) if "tikv-server" in c
          and "start-stop-daemon" in c]
    db_ = [i for i, c in enumerate(cmds) if "tidb-server" in c
           and "start-stop-daemon" in c]
    assert pd and kv and db_, "all three daemons must start"
    assert pd[0] < kv[0] < db_[0], "dependency order: pd -> tikv -> tidb"
    assert any("--initial-cluster pd-n1=http://n1:2380" in c
               for c in cmds)


def test_tidb_workloads_construct():
    from jepsen_tpu.suites import tidb

    for wl in tidb.WORKLOADS:
        for nem in tidb.NEMESES:
            t = tidb.tidb_test({"workload": wl, "nemesis": nem,
                                "nodes": ["n1"], "time_limit": 1})
            assert wl in t["name"]
    t = tidb.tidb_test({"workload": "bank", "nodes": ["n1"]})
    assert t["total_amount"] == 50
