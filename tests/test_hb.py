"""Happens-before constraint analysis (jepsen_tpu/analyze/hb.py).

The verdict-identity acceptance: a 300+-history differential fuzz —
crashes, cas ops, mutations, multi-register — through the host engines
with the pre-pass on vs off, a stride through the batch/decomposed/
streaming routes, audit on everywhere.  Plus the decide-fast
certificates (GK witness, HB-cycle) validated and tamper-tested
(W006), the fold fast-path against ``segment_states``, the must-order
prune's measured config reduction, and the plan/metrics surfaces.
"""

import os
import random
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu import synth  # noqa: E402
from jepsen_tpu.analyze.audit import AuditError, audit, maybe_audit  # noqa: E402
from jepsen_tpu.analyze.hb import (  # noqa: E402
    analyze_hb,
    hb_dispose,
    hb_fold_states,
    maybe_hb,
)
from jepsen_tpu.checker.linear import check_opseq_linear  # noqa: E402
from jepsen_tpu.checker.linearizable import search_batch  # noqa: E402
from jepsen_tpu.checker.seq import check_opseq  # noqa: E402
from jepsen_tpu.history import (  # noqa: E402
    Op,
    encode_ops,
    info_op,
    invoke_op,
    ok_op,
)
from jepsen_tpu.models import (  # noqa: E402
    cas_register,
    multi_register,
    register,
)

# ---------------------------------------------------------------------------
# decide-fast
# ---------------------------------------------------------------------------


def test_decides_valid_unique_writes_with_audited_witness():
    rng = random.Random(1)
    m = register(0)
    h = synth.register_history(rng, n_ops=80, n_procs=4, overlap=6,
                               crash_p=0.0, cas=False,
                               unique_writes=True)
    s = encode_ops(h, m.f_codes)
    hb = analyze_hb(s, m)
    assert hb.decided is not None and hb.decided["valid"] is True
    assert hb.decided["configs"] == 0
    assert hb.stats["reason"] == "gk-interval"
    a = audit(s, m, hb.decided)
    assert a["ok"] and a["checked"] == "linearization"
    # the engines agree and return the same decision with zero search
    r = check_opseq(s, m)
    assert r["valid"] is True and r["configs"] == 0
    assert r["engine"] == "hb-decide"


def test_decides_invalid_block_order_with_cycle_certificate():
    rng = random.Random(2)
    m = register(0)
    h = synth.register_history(rng, n_ops=80, n_procs=4, overlap=6,
                               crash_p=0.0, cas=False,
                               unique_writes=True)
    h = synth.swap_read_values(rng, h)
    s = encode_ops(h, m.f_codes)
    hb = analyze_hb(s, m)
    assert hb.decided is not None and hb.decided["valid"] is False
    cyc = hb.decided["hb_cycle"]
    assert len(cyc) >= 2
    # op-level chain: consecutive edges share the op, and it closes
    for i, e in enumerate(cyc):
        assert e["dst"] == cyc[(i + 1) % len(cyc)]["src"]
        assert e["kind"] in ("rt", "rf", "ww", "init")
    a = audit(s, m, hb.decided)
    assert a["ok"] and a["checked"] == "hb_cycle"
    assert check_opseq(s, m, hb=False)["valid"] is False


def test_decides_impossible_read_with_frontier():
    m = register(0)
    h = [invoke_op(0, "write", 5), ok_op(0, "write", 5),
         invoke_op(1, "read", 9), ok_op(1, "read", 9)]
    s = encode_ops(h, m.f_codes)
    hb = analyze_hb(s, m)
    assert hb.decided is not None and hb.decided["valid"] is False
    assert hb.decided["final_ops"] == [1]
    assert audit(s, m, hb.decided)["ok"]


def test_crash_cycle_decided_with_info_rows():
    """A forced-order cycle through a CRASHED write still decides: the
    :ok read anchors the crashed write's block, the rf edge is forced,
    and the read returned before the write invoked."""
    m = register(0)
    h = [invoke_op(1, "read", 7), ok_op(1, "read", 7),
         invoke_op(0, "write", 7), info_op(0, "write", 7)]
    s = encode_ops(h, m.f_codes)
    hb = analyze_hb(s, m)
    assert hb.decided is not None and hb.decided["valid"] is False
    kinds = [e["kind"] for e in hb.decided["hb_cycle"]]
    assert kinds == ["rf", "rt"]
    assert audit(s, m, hb.decided)["ok"]
    assert check_opseq(s, m, hb=False)["valid"] is False


def test_multi_register_decides_per_key_and_stitches():
    m = multi_register(3)
    h = []
    v = 1
    for p in range(3):
        for _ in range(5):
            h.append(invoke_op(p, "write", (p, v)))
            h.append(ok_op(p, "write", (p, v)))
            v += 1
    s = encode_ops(h, m.f_codes)
    hb = analyze_hb(s, m)
    assert hb.decided is not None and hb.decided["valid"] is True
    assert len(hb.decided["linearization"]) == len(s)
    assert audit(s, m, hb.decided)["ok"]


def test_cas_and_foreign_models_are_out_of_scope():
    from jepsen_tpu.models import mutex

    m = cas_register()
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         invoke_op(0, "cas", (1, 2)), ok_op(0, "cas", (1, 2))]
    s = encode_ops(h, m.f_codes)
    hb = analyze_hb(s, m)
    # cas histories never decide fast, but the canonical read-order
    # prune still applies (reads are state-transparent under cas too)
    assert hb.decided is None and hb.applies
    assert "cas" in hb.stats["reason"]
    assert hb.stats["edges"]["rf"] == hb.stats["edges"]["ww"] == 0
    mm = mutex()
    h2 = [invoke_op(0, "acquire"), ok_op(0, "acquire")]
    s2 = encode_ops(h2, mm.f_codes)
    assert not analyze_hb(s2, mm).applies


# ---------------------------------------------------------------------------
# tampered certificates fail the independent audit (W006)
# ---------------------------------------------------------------------------


def _cycle_case():
    rng = random.Random(5)
    m = register(0)
    h = synth.swap_read_values(rng, synth.register_history(
        rng, n_ops=60, n_procs=4, overlap=5, crash_p=0.0, cas=False,
        unique_writes=True))
    s = encode_ops(h, m.f_codes)
    hb = analyze_hb(s, m)
    assert hb.decided is not None and "hb_cycle" in hb.decided
    return s, m, hb.decided


def test_tampered_cycle_fails_audit():
    s, m, res = _cycle_case()
    # 1: break the chain
    bad = dict(res)
    bad["hb_cycle"] = [dict(e) for e in res["hb_cycle"]]
    bad["hb_cycle"][0] = {**bad["hb_cycle"][0],
                          "dst": (bad["hb_cycle"][0]["dst"] + 1)
                          % len(s)}
    a = audit(s, m, bad)
    assert not a["ok"] and "W006" in a["codes"]
    # 2: out-of-range row
    bad2 = dict(res)
    bad2["hb_cycle"] = [{**res["hb_cycle"][0], "src": len(s) + 5},
                        *res["hb_cycle"][1:]]
    a2 = audit(s, m, bad2)
    assert not a2["ok"] and "W001" in a2["codes"]
    # 3: claim an unjustified rt edge
    bad3 = dict(res)
    bad3["hb_cycle"] = [{**e, "kind": "rt"} for e in res["hb_cycle"]]
    a3 = audit(s, m, bad3)
    assert not a3["ok"] and "W006" in a3["codes"]
    # maybe_audit raises loudly on the tamper
    with pytest.raises(AuditError):
        maybe_audit(s, m, bad3, True)


def test_cycle_certificate_rejected_when_preconditions_fail():
    """A structurally-plausible cycle over a history with DUPLICATE
    writes must not audit: the block algebra's unique-writes
    precondition is re-checked independently."""
    m = register(0)
    h = [invoke_op(0, "write", 5), ok_op(0, "write", 5),
         invoke_op(1, "write", 5), ok_op(1, "write", 5),
         invoke_op(0, "read", 5), ok_op(0, "read", 5)]
    s = encode_ops(h, m.f_codes)
    fake = {"valid": False, "configs": 0,
            "hb_cycle": [{"src": 0, "dst": 2, "kind": "rf"},
                         {"src": 2, "dst": 0, "kind": "rt"}]}
    a = audit(s, m, fake)
    assert not a["ok"] and "W006" in a["codes"]


# ---------------------------------------------------------------------------
# the acceptance fuzz: 300+ histories, every route, audit on
# ---------------------------------------------------------------------------


def _fuzz_histories(n):
    """(model, history) spanning the decidable class and well outside
    it: crashes, cas, duplicate values, mutations, multi-register."""
    out = []
    i = 0
    while len(out) < n:
        rng = random.Random(100_000 + i)
        i += 1
        kind = rng.randrange(4)
        if kind == 3:
            m = multi_register(3)
            h = []
            state = {k: 0 for k in range(3)}
            nxt = 1
            open_ops = {}
            for _ in range(rng.randrange(8, 30)):
                p = rng.randrange(3)
                if p in open_ops:
                    op = open_ops.pop(p)
                    h.append((info_op if rng.random() < 0.08 else
                              ok_op)(p, op.f, op.value))
                else:
                    k = rng.randrange(3)
                    if rng.random() < 0.5:
                        v = nxt if rng.random() < 0.8 \
                            else rng.randrange(3)
                        nxt += 1
                        op = invoke_op(p, "write", (k, v))
                        state[k] = v
                    else:
                        v = state[k] if rng.random() < 0.8 \
                            else rng.randrange(5)
                        op = invoke_op(p, "read", (k, v))
                    h.append(op)
                    open_ops[p] = op
            for p, op in open_ops.items():
                h.append(ok_op(p, op.f, op.value))
            out.append((m, h))
            continue
        m = register(0) if kind == 0 else cas_register()
        h = synth.register_history(
            rng, n_ops=rng.randrange(8, 40),
            n_procs=rng.randrange(2, 6), overlap=rng.randrange(1, 6),
            crash_p=rng.choice([0.0, 0.0, 0.1, 0.3]),
            cas=(kind == 1 and rng.random() < 0.5), max_crashes=8,
            unique_writes=rng.random() < 0.5,
            n_values=rng.choice([2, 3, 8]))
        if rng.random() < 0.5:
            h = synth.mutate(rng, h)
        out.append((m, h))
    return out


def test_differential_fuzz_all_routes_verdict_identical():
    from jepsen_tpu.decompose.engine import check_opseq_decomposed
    from jepsen_tpu.stream import StreamChecker

    cases = _fuzz_histories(310)
    decided = masked = routed = 0
    for idx, (m, h) in enumerate(cases):
        try:
            s = encode_ops(h, m.f_codes)
        except Exception:  # noqa: BLE001 — encode errors: lint's beat
            continue
        on = check_opseq(s, m, max_configs=250_000, lint=False,
                         hb=True)
        off = check_opseq(s, m, max_configs=250_000, lint=False,
                          hb=False)
        lin_on = check_opseq_linear(s, m, max_configs=250_000,
                                    lint=False, hb=True,
                                    witness_cap=200_000)
        lin_off = check_opseq_linear(s, m, max_configs=250_000,
                                     lint=False, hb=False,
                                     witness_cap=200_000)
        rs = [on, off, lin_on, lin_off]
        if idx % 6 == 0:
            rs.append(search_batch([s], m, budget=250_000, lint=False,
                                   bucket=True)[0])
            rs.append(check_opseq_decomposed(s, m,
                                             sub_max_configs=250_000,
                                             lint=False, witness=True))
            sc = StreamChecker(m)
            for op in h:
                sc.ingest(op)
            rs.append(sc.finalize())
            routed += 1
        vs = {r["valid"] for r in rs if r["valid"] != "unknown"}
        assert len(vs) <= 1, (idx, [r["valid"] for r in rs],
                              [op.to_dict() for op in h])
        for r in (on, lin_on):
            a = audit(s, m, r)
            assert a["ok"], (idx, a["diagnostics"], r)
        if on.get("engine") == "hb-decide":
            decided += 1
        if (on.get("hb") or {}).get("must_edges"):
            masked += 1
    # the fuzz must actually exercise the machinery
    assert decided >= 60, decided
    assert masked >= 40, masked
    assert routed >= 50, routed


# ---------------------------------------------------------------------------
# segment-fold fast path
# ---------------------------------------------------------------------------


def test_fold_states_match_segment_sweep():
    from jepsen_tpu.decompose.engine import segment_states

    rounds = checked = 0
    for i in range(120):
        rng = random.Random(40_000 + i)
        m = register(rng.randrange(0, 3))
        h = synth.register_history(
            rng, n_ops=rng.randrange(4, 22),
            n_procs=rng.randrange(2, 5), overlap=rng.randrange(1, 5),
            crash_p=0.0, cas=False, unique_writes=rng.random() < 0.7)
        if rng.random() < 0.4:
            h = synth.mutate(rng, h)
        try:
            s = encode_ops(h, m.f_codes)
        except Exception:  # noqa: BLE001
            continue
        if len(s) == 0 or not bool(np.asarray(s.ok).all()):
            continue
        insts = [tuple(m.init)]
        if rng.random() < 0.5:
            insts.append((rng.randrange(0, 4),))
        rounds += 1
        out = hb_fold_states(s, m, insts, witness=rng.random() < 0.5)
        if out is None:
            continue
        states = out[0] if isinstance(out, tuple) else out
        ref = segment_states(s, m, insts, max_configs=3_000_000)
        assert states == ref, (i, states, ref)
        if isinstance(out, tuple) and out[1] is not None:
            # every reachable out-state carries a chain from a real
            # instate (exactness guard)
            assert set(out[1]) == states
        checked += 1
    assert checked >= 12, (rounds, checked)


def test_fold_cedes_rather_than_truncating_states():
    """Review regression: a segment with MORE reachable out-states
    than the witness cap must cede to the generic fold, never return
    a truncated state set (a wrong frontier would also poison the
    shared segment cache)."""
    from jepsen_tpu.decompose.engine import segment_states
    from jepsen_tpu.stream import StreamChecker

    m = register(0)
    h = []
    for v in range(1, 13):  # 12 fully-concurrent writes: 12 out-states
        h.append(invoke_op(v, "write", v))
    for v in range(1, 13):
        h.append(ok_op(v, "write", v))
    seg = encode_ops(h, m.f_codes)
    out = hb_fold_states(seg, m, [(0,)], witness=True)
    ref = segment_states(seg, m, [(0,)])
    assert len(ref) == 12
    assert out is None or out[0] == ref
    # end to end: the streamed verdict must match the direct engine
    h2 = list(h) + [invoke_op(0, "read", 9), ok_op(0, "read", 9)]
    sc = StreamChecker(m)
    for op in h2:
        sc.ingest(op)
    r = sc.finalize()
    assert r["valid"] is True
    assert check_opseq(encode_ops(h2, m.f_codes), m,
                       hb=False)["valid"] is True


def test_hb_false_reaches_decomposed_folds():
    """Review regression: the per-call opt-out must travel through the
    decomposed route — hb=False may not ride the env default into the
    engine's segment folds."""
    rng = random.Random(11)
    m = register(0)
    h = synth.register_history(rng, n_ops=40, n_procs=3, overlap=2,
                               quiesce_every=5, crash_p=0.0, cas=False,
                               unique_writes=True)
    s = encode_ops(h, m.f_codes)
    on = check_opseq_linear(s, m, decompose=True, hb=True, lint=False)
    off = check_opseq_linear(s, m, decompose=True, hb=False,
                             lint=False)
    assert on["valid"] == off["valid"]
    assert "hb-fold" not in off["decompose"]["methods"]


def test_stream_fold_rides_hb_route():
    from jepsen_tpu.stream import StreamChecker

    rng = random.Random(77)
    m = register(0)
    h = synth.register_history(rng, n_ops=60, n_procs=3, overlap=2,
                               quiesce_every=6, crash_p=0.0, cas=False,
                               unique_writes=True)
    sc = StreamChecker(m)
    for op in h:
        sc.ingest(op)
    r = sc.finalize()
    assert r["valid"] == check_opseq(encode_ops(h, m.f_codes), m,
                                     hb=False)["valid"]
    assert r["stream"]["routes"]["hb"] >= 1
    assert "hb-fold" in r["stream"]["methods"]
    # off switch: no hb route, same verdict
    sc2 = StreamChecker(m, hb=False)
    for op in h:
        sc2.ingest(op)
    r2 = sc2.finalize()
    assert r2["valid"] == r["valid"]
    assert r2["stream"]["routes"]["hb"] == 0


# ---------------------------------------------------------------------------
# the prune
# ---------------------------------------------------------------------------


def _read_storm(n_readers=8, reads_each=4):
    """Concurrent same-value reads around sequential writes: the
    read-permutation blowup the canonical-order chains collapse.  A
    final impossible-tail keeps the greedy witness and the decide-fast
    class out (duplicate writes), so the sweep really runs."""
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    # duplicate write of 1 -> outside the unique-writes decide class
    h += [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    for r in range(reads_each):
        for p in range(1, n_readers + 1):
            h.append(invoke_op(p, "read", 1))
        for p in range(1, n_readers + 1):
            h.append(ok_op(p, "read", 1))
    h += [invoke_op(0, "write", 2), ok_op(0, "write", 2),
          invoke_op(1, "read", 1), ok_op(1, "read", 1)]  # stale: invalid
    return h


def test_must_order_prune_reduces_explored_configs():
    m = register(0)
    h = _read_storm()
    s = encode_ops(h, m.f_codes)
    on = check_opseq_linear(s, m, lint=False, hb=True)
    off = check_opseq_linear(s, m, lint=False, hb=False)
    assert on["valid"] == off["valid"] is False
    assert on["hb"]["must_edges"] > 0
    assert on["configs"] < off["configs"], (on["configs"],
                                            off["configs"])
    # the DFS oracle masks too
    d_on = check_opseq(s, m, lint=False, hb=True)
    d_off = check_opseq(s, m, lint=False, hb=False)
    assert d_on["valid"] == d_off["valid"] is False
    assert d_on["configs"] <= d_off["configs"]


def test_plan_reports_pruned_bound_and_decidability():
    from jepsen_tpu.analyze.plan import explain, render_plan

    m = register(0)
    s = encode_ops(_read_storm(), m.f_codes)
    plan = explain(s, m)
    hb = plan["hb"]
    assert hb["applies"] and hb["decided"] is None
    assert hb["must_edges"] > 0
    assert hb["pruned_upper_bound"] < plan["config_upper_bound"]
    assert 0 < hb["prune_ratio"] < 1
    assert "happens-before" in render_plan(plan)

    rng = random.Random(9)
    h2 = synth.register_history(rng, n_ops=40, n_procs=3, overlap=3,
                                crash_p=0.0, cas=False,
                                unique_writes=True)
    plan2 = explain(encode_ops(h2, m.f_codes), m)
    assert plan2["hb"]["decided"] is True
    assert plan2["hb"]["pruned_upper_bound"] == 0
    assert plan2["hb"]["prune_ratio"] == 0.0


# ---------------------------------------------------------------------------
# batch disposal + knobs + metrics
# ---------------------------------------------------------------------------


def test_search_batch_disposes_decided_keys():
    from jepsen_tpu.analyze.plan import explain_batch

    m = register(0)
    seqs = []
    # invalid unique-writes keys (greedy fails, hb decides) + storm
    # keys that must actually search
    for i in range(4):
        rng = random.Random(200 + i)
        h = synth.swap_read_values(rng, synth.register_history(
            rng, n_ops=24, n_procs=3, overlap=4, crash_p=0.0,
            cas=False, unique_writes=True))
        seqs.append(encode_ops(h, m.f_codes))
    seqs.append(encode_ops(_read_storm(4, 2), m.f_codes))
    res = search_batch(seqs, m, budget=200_000, bucket=True)
    assert [r["valid"] for r in res[:4]] == [False] * 4
    assert all(r["engine"] == "hb-decide" for r in res[:4])
    stats = next((r.get("bucket_batch") for r in res
                  if r.get("bucket_batch")), None)
    if stats is not None:
        plan = explain_batch(seqs, m)
        assert plan["hb_decided"] == stats["hb_decided"] == 4
    # audit rides the batch exit for hb-decided keys too
    res2 = search_batch(seqs, m, budget=200_000, bucket=True,
                        audit=True)
    assert [r["valid"] for r in res2[:4]] == [False] * 4


def test_env_knob_disables_prepass(monkeypatch):
    m = register(0)
    rng = random.Random(3)
    h = synth.register_history(rng, n_ops=30, n_procs=3, overlap=3,
                               crash_p=0.0, cas=False,
                               unique_writes=True)
    s = encode_ops(h, m.f_codes)
    monkeypatch.setenv("JEPSEN_TPU_HB", "0")
    assert maybe_hb(s, m, None) is None
    r = check_opseq(s, m)
    assert r.get("engine") != "hb-decide"
    monkeypatch.setenv("JEPSEN_TPU_HB", "1")
    assert maybe_hb(s, m, None) is not None
    assert check_opseq(s, m)["engine"] == "hb-decide"


def test_hb_metrics_exported():
    from jepsen_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.REGISTRY
    before = reg.get("jtpu_hb_prepass_total").total()
    m = register(0)
    rng = random.Random(4)
    h = synth.register_history(rng, n_ops=24, n_procs=3, overlap=3,
                               crash_p=0.0, cas=False,
                               unique_writes=True)
    s = encode_ops(h, m.f_codes)
    assert hb_dispose(s, m) is not None
    assert reg.get("jtpu_hb_prepass_total").total() == before + 1
    assert reg.get("jtpu_hb_prepass_total").value(
        outcome="decided_valid") >= 1
    # prune ratio gauge: decided -> 0; the family shows on /metrics
    assert reg.get("jtpu_hb_prune_ratio").value() == 0.0
    text = obs_metrics.render()
    assert "jtpu_hb_prepass_total" in text
    assert "jtpu_hb_prune_ratio" in text


def test_result_panel_renders_hb_evidence():
    from jepsen_tpu.web import result_block

    s, m, res = _cycle_case()
    html = result_block(res)
    assert "HB cycle" in html
    assert "hb-decide" in html
