"""Chronos schedule checker (checker/schedule.py) — constraint
satisfaction of repeating job targets by observed runs."""

from jepsen_tpu.checker import schedule
from jepsen_tpu.history import invoke_op, ok_op


def test_job_targets_cutoff():
    job = {"name": "1", "start": 100.0, "interval": 60, "count": 5,
           "epsilon": 10, "duration": 5}
    # read at t=250: targets at 100, 160, 220 must have begun
    # (cutoff = 250 - 10 - 5 = 235; 280 > 235 excluded)
    ts = schedule.job_targets(250.0, job)
    assert [t0 for t0, _ in ts] == [100.0, 160.0, 220.0]
    assert ts[0][1] == 100.0 + 10 + schedule.EPSILON_FORGIVENESS


def test_job_targets_respects_count():
    job = {"name": "1", "start": 0.0, "interval": 10, "count": 2,
           "epsilon": 1, "duration": 0}
    assert len(schedule.job_targets(1e9, job)) == 2


def _run(name, start, end="auto"):
    return {"name": name, "start": start,
            "end": start + 1 if end == "auto" else end}


def test_job_solution_satisfied():
    job = {"name": "1", "start": 100.0, "interval": 60, "count": 3,
           "epsilon": 10, "duration": 5}
    runs = [_run("1", 101), _run("1", 165), _run("1", 228)]
    s = schedule.job_solution(400.0, job, runs)
    assert s["valid"] is True
    assert all(r is not None for _, r in s["solution"])
    assert s["extra"] == []


def test_job_solution_missing_run():
    job = {"name": "1", "start": 100.0, "interval": 60, "count": 3,
           "epsilon": 10, "duration": 5}
    runs = [_run("1", 101), _run("1", 228)]  # 160-target missed
    s = schedule.job_solution(400.0, job, runs)
    assert s["valid"] is False
    missed = [t for t, r in s["solution"] if r is None]
    assert missed == [(160.0, 175.0)]


def test_job_solution_incomplete_runs_dont_count():
    job = {"name": "1", "start": 100.0, "interval": 60, "count": 1,
           "epsilon": 10, "duration": 5}
    runs = [_run("1", 101, end=None)]  # began but never finished
    s = schedule.job_solution(400.0, job, runs)
    assert s["valid"] is False
    assert s["incomplete"] and not s["complete"]


def test_job_solution_duplicate_runs_are_extra():
    job = {"name": "1", "start": 100.0, "interval": 60, "count": 1,
           "epsilon": 10, "duration": 5}
    runs = [_run("1", 101), _run("1", 103)]
    s = schedule.job_solution(400.0, job, runs)
    assert s["valid"] is True
    assert len(s["extra"]) == 1


def test_solution_multi_job():
    jobs = [{"name": "1", "start": 100.0, "interval": 60, "count": 1,
             "epsilon": 10, "duration": 5},
            {"name": "2", "start": 100.0, "interval": 60, "count": 1,
             "epsilon": 10, "duration": 5}]
    runs = [_run("1", 101)]  # job 2 never ran
    out = schedule.solution(400.0, jobs, runs)
    assert out["valid"] is False
    assert out["jobs"]["1"]["valid"] is True
    assert out["jobs"]["2"]["valid"] is False


def test_schedule_checker_over_history(tmp_path):
    job = {"name": "1", "start": 100.0, "interval": 60, "count": 1,
           "epsilon": 10, "duration": 5}
    runs = [_run("1", 101)]
    h = [invoke_op(0, "add-job", job), ok_op(0, "add-job", job),
         invoke_op(0, "read", None, time=int(400e9)),
         ok_op(0, "read", runs, time=int(400e9))]
    test = {"name": "chronos-test", "start_wall_time": 0,
            "store_base": str(tmp_path)}
    out = schedule.schedule_checker().check(test, h)
    assert out["valid"] is True


def test_schedule_checker_no_read():
    out = schedule.schedule_checker(plot=False).check({}, [])
    assert out["valid"] == "unknown"
