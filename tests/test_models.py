"""Model semantics + differential tests: pystep vs JAX jstep must agree."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jepsen_tpu.history import NIL
from jepsen_tpu.models import (
    cas_register, fifo_queue, multi_register, mutex, noop, register,
    unordered_queue,
)


def jstep_eval(model, state, fname, v1, v2):
    code = model.f_codes[fname]
    s = jnp.asarray(state, dtype=jnp.int32)
    s2, legal = jax.jit(model.jstep)(
        s, jnp.int32(code), jnp.int32(v1), jnp.int32(v2))
    return tuple(int(x) for x in s2), bool(legal)


# --- register ---------------------------------------------------------------

def test_register_read_write():
    m = register(0)
    assert m.step((0,), "read", 0) == (0,)
    assert m.step((0,), "read", 1) is None
    assert m.step((0,), "read", None) == (0,)   # unknown read always legal
    assert m.step((0,), "write", 7) == (7,)


# --- cas-register -----------------------------------------------------------

def test_cas_register_semantics():
    m = cas_register(0)
    assert m.step((0,), "cas", (0, 5)) == (5,)
    assert m.step((0,), "cas", (1, 5)) is None
    assert m.step((3,), "write", 9) == (9,)
    assert m.step((3,), "read", 3) == (3,)
    assert m.step((3,), "read", 4) is None


def test_cas_register_nil_initial():
    m = cas_register()
    assert m.init == (NIL,)
    assert m.step(m.init, "read", None) == m.init
    assert m.step(m.init, "read", 0) is None


# --- mutex ------------------------------------------------------------------

def test_mutex_semantics():
    m = mutex()
    assert m.step((0,), "acquire", None) == (1,)
    assert m.step((1,), "acquire", None) is None
    assert m.step((1,), "release", None) == (0,)
    assert m.step((0,), "release", None) is None


# --- multi-register ---------------------------------------------------------

def test_multi_register():
    m = multi_register(3)
    s = m.init
    assert s == (0, 0, 0)
    s2 = m.step(s, "write", (1, 9))
    assert s2 == (0, 9, 0)
    assert m.step(s2, "read", (1, 9)) == s2
    assert m.step(s2, "read", (1, 8)) is None
    assert m.step(s2, "read", (5, 0)) is None  # out of range


# --- differential: pystep vs jstep ------------------------------------------

CASES = {
    "register": (register(0), [
        ("read", 0, NIL), ("read", 1, NIL), ("read", NIL, NIL),
        ("write", 3, NIL), ("write", -1, NIL),
    ]),
    "cas-register": (cas_register(0), [
        ("read", 0, NIL), ("read", 2, NIL), ("read", NIL, NIL),
        ("write", 4, NIL), ("cas", 0, 9), ("cas", 7, 9),
    ]),
    "mutex": (mutex(), [
        ("acquire", NIL, NIL), ("release", NIL, NIL),
    ]),
    "multi-register": (multi_register(4, 0), [
        ("read", 0, 0), ("read", 2, 1), ("read", 1, NIL),
        ("write", 3, 7), ("write", 0, -2),
    ]),
    "fifo-queue": (fifo_queue(4), [
        ("enqueue", 1, NIL), ("enqueue", 2, NIL), ("enqueue", NIL, NIL),
        ("dequeue", 1, NIL), ("dequeue", 2, NIL), ("dequeue", 9, NIL),
        ("dequeue", NIL, NIL),
    ]),
    "unordered-queue": (unordered_queue(4), [
        ("enqueue", 1, NIL), ("enqueue", 2, NIL), ("enqueue", 2, NIL),
        ("enqueue", NIL, NIL),
        ("dequeue", 1, NIL), ("dequeue", 2, NIL), ("dequeue", 7, NIL),
        ("dequeue", NIL, NIL),
    ]),
}


@pytest.mark.parametrize("name", list(CASES))
def test_pystep_jstep_agree(name):
    model, ops = CASES[name]
    rng = np.random.default_rng(0)
    # random walk: apply random legal ops, compare both impls at each step
    states = [model.init]
    for _ in range(50):
        state = states[rng.integers(len(states))]
        fname, v1, v2 = ops[rng.integers(len(ops))]
        code = model.f_codes[fname]
        py = model.pystep(state, code, v1, v2)
        js, legal = jstep_eval(model, state, fname, v1, v2)
        if py is None:
            assert not legal, (name, state, fname, v1, v2)
        else:
            assert legal, (name, state, fname, v1, v2)
            assert js == py, (name, state, fname, v1, v2)
            states.append(py)


def test_noop_accepts_everything():
    m = noop()
    assert m.pystep((0,), 0, 1, 2) == (0,)


def test_noop_accepts_any_f_through_encode_ops():
    from jepsen_tpu.history import encode_ops, invoke_op, ok_op
    from jepsen_tpu.models import noop

    m = noop()
    h = [invoke_op(0, "frobnicate", 1), ok_op(0, "frobnicate", 1)]
    s = encode_ops(h, m.f_codes)
    assert len(s) == 1
    assert m.pystep(m.init, 0, 1, 1) == m.init


def test_multi_register_illegal_write_leaves_state():
    import jax.numpy as jnp
    from jepsen_tpu.models import R_WRITE, multi_register

    m = multi_register(3)
    st = jnp.zeros(3, dtype=jnp.int32)
    new, legal = m.jstep(st, jnp.int32(R_WRITE), jnp.int32(5), jnp.int32(9))
    assert not bool(legal)
    assert (new == st).all()
