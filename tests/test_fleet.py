"""Fleet tier: routing stability, the multi-writer cache store,
warm-boot admission, dead-worker salvage, and the routed-vs-single
parity smoke.

The load-bearing claims under test, in the order a fleet needs them:

  * rendezvous routing moves ~1/N of runs on a join and ONLY the dead
    worker's runs on a leave (a moved run is a re-checked prefix — the
    hash discipline is a correctness-cost bound, not aesthetics);
  * concurrent workers writing the shared verdict store never lose an
    insert, and a restarted worker sees everything the fleet decided;
  * a cold worker is refused admission until its warm-boot report
    verifies (zero kernel-cache misses on re-probe);
  * a killed worker's open runs finalize through the persist-dir
    salvage path and the run's suffix re-routes to a survivor;
  * verdicts through the routed fleet are bit-identical (minus cache
    counters) to one service checking the same histories.
"""

import json
import random
import socket
import threading
import time

from jepsen_tpu.fleet.admission import (
    AdmissionController,
    AdmissionPolicy,
    scale_signal,
)
from jepsen_tpu.fleet.cachestore import FleetCacheStore
from jepsen_tpu.fleet.router import (
    FleetRouter,
    WorkerSpec,
    make_router_server,
    merge_metrics_texts,
    merge_snapshots,
    route_run,
)
from jepsen_tpu.reconnect import Backoff
from jepsen_tpu.stream.service import make_server
from jepsen_tpu.synth import register_history


def _specs(n, port=1):
    return [WorkerSpec(f"w{i}", "127.0.0.1", port) for i in range(n)]


def _mk_history(seed, n_ops=80):
    rng = random.Random(seed)
    return register_history(rng, n_ops=n_ops, n_procs=4, overlap=3,
                            quiesce_every=8, n_values=5, cas=False)


def _op_lines(run_id, h):
    lines = [json.dumps({"run": run_id, "model": "register"})]
    lines += [json.dumps({"run": run_id, "op": op.to_dict()})
              for op in h]
    lines.append(json.dumps({"run": run_id, "end": True}))
    return lines


def _strip_cache(summary):
    out = dict(summary)
    stream = dict(out.get("stream") or {})
    for k in list(stream):
        if k.startswith("cache_"):
            stream.pop(k)
    out["stream"] = stream
    out.pop("finalized_by", None)
    return out


# ---------------------------------------------------------------------------
# rendezvous routing
# ---------------------------------------------------------------------------


def test_rendezvous_routing_is_deterministic_and_balanced():
    workers = _specs(4)
    runs = [f"run-{i}" for i in range(400)]
    placed = {r: route_run(r, workers).wid for r in runs}
    assert placed == {r: route_run(r, workers).wid for r in runs}
    counts = {w.wid: 0 for w in workers}
    for wid in placed.values():
        counts[wid] += 1
    # balanced within a loose bound (hash, not perfection): every
    # worker holds something, none holds a majority
    assert all(c > 0 for c in counts.values())
    assert max(counts.values()) < len(runs) // 2


def test_worker_join_moves_a_bounded_fraction():
    runs = [f"run-{i}" for i in range(500)]
    before = {r: route_run(r, _specs(4)).wid for r in runs}
    after = {r: route_run(r, _specs(5)).wid for r in runs}
    moved = [r for r in runs if before[r] != after[r]]
    # rendezvous: a join steals ~1/5 of the keyspace; everything that
    # moved must have moved TO the new worker
    assert len(moved) < len(runs) * 0.35
    assert all(after[r] == "w4" for r in moved)


def test_worker_leave_moves_only_its_own_runs():
    runs = [f"run-{i}" for i in range(500)]
    full = _specs(4)
    before = {r: route_run(r, full).wid for r in runs}
    survivors = [w for w in full if w.wid != "w2"]
    after = {r: route_run(r, survivors).wid for r in runs}
    for r in runs:
        if before[r] != "w2":
            assert after[r] == before[r], \
                "a survivor's run moved on an unrelated leave"
        else:
            assert after[r] != "w2"


# ---------------------------------------------------------------------------
# the multi-writer cache store
# ---------------------------------------------------------------------------


def test_cachestore_per_worker_segments_do_not_clobber(tmp_path):
    root = str(tmp_path / "store")
    a = FleetCacheStore(root, worker_id="w1", compact_bytes=0)
    b = FleetCacheStore(root, worker_id="w2", compact_bytes=0)
    n = 150
    done = threading.Event()

    def writer():
        for i in range(n):
            b.put_verdict(f"b{i}", i % 2 == 0)
        done.set()

    def spiller():
        # post-test loop condition: always complete at least one
        # insert+spill even if the writer already finished (the test
        # asserts a0 reached the base)
        i = 0
        while True:
            a.put_verdict(f"a{i}", True)
            a.compact()  # spill merges EVERY segment into the base
            i += 1
            if done.is_set():
                break

    threads = [threading.Thread(target=writer),
               threading.Thread(target=spiller)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b.compact()
    # a restarted worker (fresh load: base + segments) sees every
    # insert from both writers — the hit ratio survives restarts
    fresh = FleetCacheStore(root, worker_id="w3")
    missing = [i for i in range(n) if fresh.get(f"b{i}") is None]
    assert missing == [], \
        f"spill race lost {len(missing)} concurrent insert(s)"
    assert fresh.get("a0")["v"] is True


def test_cachestore_spill_truncates_only_own_segment(tmp_path):
    import os

    root = str(tmp_path / "store")
    a = FleetCacheStore(root, worker_id="w1", compact_bytes=0)
    b = FleetCacheStore(root, worker_id="w2", compact_bytes=0)
    a.put_verdict("ka", True)
    b.put_verdict("kb", False)
    a.compact()
    seg = lambda wid: os.path.join(root, "segments", f"{wid}.jsonl")  # noqa: E731
    assert os.path.getsize(seg("w1")) == 0  # spilled
    assert os.path.getsize(seg("w2")) > 0   # untouched
    # both entries live in the base now / still reachable
    fresh = FleetCacheStore(root, worker_id="w9")
    assert fresh.get("ka")["v"] is True
    assert fresh.get("kb")["v"] is False


def test_cachestore_refresh_picks_up_peer_verdicts(tmp_path):
    root = str(tmp_path / "store")
    a = FleetCacheStore(root, worker_id="w1", compact_bytes=0)
    b = FleetCacheStore(root, worker_id="w2", compact_bytes=0)
    b.put_verdict("peer-key", True)
    assert a.get("peer-key") is None  # loaded before the peer wrote
    assert a.refresh() == 1
    assert a.get("peer-key")["v"] is True


# ---------------------------------------------------------------------------
# warm boot + admission
# ---------------------------------------------------------------------------


def test_warm_boot_compiles_then_verifies_zero_miss(tmp_path):
    from jepsen_tpu.fleet.warmup import WarmShape, warm_boot

    shape = WarmShape(n_det_pad=64, frontier=8)
    rep = warm_boot([shape])
    assert rep["shapes"] == 1
    assert rep["verified"] is True
    assert rep["wall_s"] > 0
    # a second boot of the same shape is all hits, still verified
    rep2 = warm_boot([shape])
    assert rep2["compiled"] == 0
    assert rep2["verified"] is True


def test_load_shapes_from_manifest_and_trace(tmp_path):
    from jepsen_tpu.fleet.warmup import load_shapes

    man = tmp_path / "shapes.json"
    man.write_text(json.dumps({"shapes": [
        {"model": ["register", 0, 1], "n_det_pad": 256,
         "frontier": 64}]}))
    shapes = load_shapes(str(man))
    assert len(shapes) == 1
    assert shapes[0].n_det_pad == 256 and shapes[0].window == 32
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "device.compile", "args": {
            "n_det_pad": 1024, "frontier": 128, "window": 64,
            "n_crash_pad": 32, "k": 4}},
        {"name": "device.compile", "args": {
            "n_det_pad": 1024, "frontier": 128, "window": 64,
            "n_crash_pad": 32, "k": 4}},  # duplicate span: dedup
        {"name": "device.slice", "args": {"frontier": 128}},
    ]}))
    shapes = load_shapes(str(trace))
    assert len(shapes) == 1
    assert shapes[0].n_det_pad == 1024 and shapes[0].window == 64


def test_admission_requires_verified_warmup():
    router = FleetRouter(require_warmup=True)
    cold = WorkerSpec("cold", "127.0.0.1", 1)
    assert not router.admit_worker(cold)
    assert not router.admit_worker(
        cold, warmup_report={"verified": False})
    assert router.admit_worker(
        cold, warmup_report={"verified": True, "shapes": 3})
    assert router.is_live("cold")


def test_admission_controller_decisions():
    t = {"now": 0.0}
    ctl = AdmissionController(
        AdmissionPolicy(max_open_runs=100, spawn_open_runs=10,
                        max_shed_rate=0.5, spawn_shed_rate=0.1,
                        min_spawn_interval_s=100.0),
        clock=lambda: t["now"])
    accept = {"open_runs": 1, "fold_backlog": 0,
              "shed_total": 0, "ops_total": 100}
    assert ctl.decide(accept) == "accept"
    assert ctl.decide({**accept, "open_runs": 500}) == "shed"
    # soft ceiling -> spawn signal, damped on repeat
    assert ctl.decide({**accept, "open_runs": 50}) == "spawn-worker"
    assert ctl.decide({**accept, "open_runs": 50}) == "accept"
    t["now"] = 200.0  # damping window passed
    assert ctl.decide({**accept, "open_runs": 50}) == "spawn-worker"
    # shed-rate path: the DELTA since the last sample decides
    ctl2 = AdmissionController(
        AdmissionPolicy(max_shed_rate=0.3, spawn_shed_rate=2.0))
    ctl2.decide({"open_runs": 0, "fold_backlog": 0,
                 "shed_total": 0, "ops_total": 100})
    assert ctl2.decide({"open_runs": 0, "fold_backlog": 0,
                        "shed_total": 80, "ops_total": 150}) == "shed"


def test_scale_signal_sums_labelled_metrics():
    sig = scale_signal({"values": {
        "jtpu_stream_runs_open": {"type": "gauge",
                                  "values": 3},
        "jtpu_shed_total": {"op-budget": 2.0, "draining": 1.0},
        "jtpu_stream_ops_ingested_total": 500.0,
    }})
    assert sig["open_runs"] == 3.0
    assert sig["shed_total"] == 3.0
    assert sig["ops_total"] == 500.0


# ---------------------------------------------------------------------------
# scrape merging
# ---------------------------------------------------------------------------


def test_merge_metrics_texts_adds_worker_label():
    merged = merge_metrics_texts({
        "w0": "# HELP jtpu_x things\n# TYPE jtpu_x counter\n"
              "jtpu_x 3\njtpu_y{reason=\"a\"} 1\n",
        "w1": "# HELP jtpu_x things\n# TYPE jtpu_x counter\n"
              "jtpu_x 4\n",
    })
    lines = merged.splitlines()
    assert lines.count("# HELP jtpu_x things") == 1  # deduped
    assert 'jtpu_x{worker="w0"} 3' in lines
    assert 'jtpu_x{worker="w1"} 4' in lines
    assert 'jtpu_y{worker="w0",reason="a"} 1' in lines


def test_merge_snapshots_sums_values_and_keeps_workers():
    merged = merge_snapshots({
        "w0": {"jtpu_a": {"type": "counter", "help": "h",
                          "values": 2},
               "jtpu_b": {"type": "counter", "help": "h",
                          "values": {"x": 1}},
               "derived": {"ratio": 0.5}},
        "w1": {"jtpu_a": {"type": "counter", "help": "h",
                          "values": 5},
               "jtpu_b": {"type": "counter", "help": "h",
                          "values": {"x": 2, "y": 7}}},
    })
    assert merged["n_workers"] == 2
    assert merged["jtpu_a"]["values"] == 7
    assert merged["jtpu_b"]["values"] == {"x": 3, "y": 7}
    assert "derived" not in merged
    assert merged["workers"]["w1"]["jtpu_a"]["values"] == 5


# ---------------------------------------------------------------------------
# the live fleet: routing, salvage, parity (tier-1 smoke)
# ---------------------------------------------------------------------------


def _boot_fleet(n=2, persist=None, probe_interval=0.05):
    servers, specs = [], []
    for i in range(n):
        srv = make_server("127.0.0.1", 0, persist_dir=persist)
        threading.Thread(target=srv.serve_forever,
                         daemon=True).start()
        servers.append(srv)
        specs.append(WorkerSpec(f"w{i}", "127.0.0.1",
                                srv.server_address[1], persist))
    router = FleetRouter(
        specs, probe_interval=probe_interval,
        backoff_factory=lambda: Backoff(base=0.01, cap=0.05,
                                        max_attempts=3, jitter=0.0))
    router.start_probes()
    rsrv = make_router_server("127.0.0.1", 0, router)
    threading.Thread(target=rsrv.serve_forever, daemon=True).start()
    return servers, specs, router, rsrv


def _teardown(servers, router, rsrv):
    router.stop_probes()
    rsrv.shutdown()
    rsrv.server_close()
    for srv in servers:
        try:
            srv.shutdown()
            srv.server_close()
        except OSError:
            pass


def _client(port, lines):
    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    w = s.makefile("w")
    r = s.makefile("r")
    for li in lines:
        w.write(li + "\n")
    w.flush()
    s.shutdown(socket.SHUT_WR)
    out = [json.loads(x) for x in r if x.strip()]
    s.close()
    return out


def test_fleet_smoke_routed_verdicts_match_single_service():
    """2 workers + router + 8 concurrent clients: every run's final
    through the fleet equals the single-service verdict for the same
    history (cache counters aside)."""
    from jepsen_tpu.stream.service import StreamService

    servers, specs, router, rsrv = _boot_fleet(2)
    rport = rsrv.server_address[1]
    hists = {f"run-{i}": _mk_history(300 + i) for i in range(8)}
    finals = {}
    lock = threading.Lock()

    def go(rid, h):
        out = _client(rport, _op_lines(rid, h))
        fin = [d for d in out if "final" in d]
        assert len(fin) == 1, f"{rid}: {out}"
        with lock:
            finals[rid] = fin[0]["final"]

    threads = [threading.Thread(target=go, args=(rid, h))
               for rid, h in hists.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert set(finals) == set(hists)
    # both workers actually took runs (rendezvous spread)
    placed = {router.route(rid).wid for rid in hists}
    assert placed == {"w0", "w1"}
    # parity: one service, fresh cache, same histories
    for rid, h in hists.items():
        svc = StreamService()
        replies = []
        for li in _op_lines(rid, h):
            svc.handle_line(li, replies.append)
        single = [d for d in replies if "final" in d][-1]["final"]
        assert _strip_cache(finals[rid]) == _strip_cache(single), \
            f"routed verdict diverged from single service on {rid}"
    _teardown(servers, router, rsrv)


def test_fleet_dead_worker_salvages_and_reroutes(tmp_path):
    """Kill the worker holding an open run: the router detects death
    by probe, salvages the persisted final (the worker's abandon path
    flushed it), answers the client, and re-routes the suffix to the
    survivor."""
    persist = str(tmp_path / "persist")
    servers, specs, router, rsrv = _boot_fleet(2, persist=persist)
    rport = rsrv.server_address[1]
    rid = "salvage-me"
    victim = router.route(rid)
    s = socket.create_connection(("127.0.0.1", rport))
    w = s.makefile("w")
    r = s.makefile("r")
    w.write(json.dumps({"run": rid, "model": "register"}) + "\n")
    for op in ({"process": 0, "type": "invoke", "f": "write",
                "value": 7},
               {"process": 0, "type": "ok", "f": "write",
                "value": 7}):
        w.write(json.dumps({"run": rid, "op": op}) + "\n")
    w.flush()
    time.sleep(0.4)
    for srv, spec in zip(servers, specs):
        if spec.wid == victim.wid:
            srv.shutdown()
            srv.server_close()
    deadline = time.time() + 10
    while router.is_live(victim.wid) and time.time() < deadline:
        time.sleep(0.05)
    assert not router.is_live(victim.wid), "probes never declared death"
    for op in ({"process": 1, "type": "invoke", "f": "read",
                "value": None},
               {"process": 1, "type": "ok", "f": "read", "value": 7}):
        w.write(json.dumps({"run": rid, "op": op}) + "\n")
    w.write(json.dumps({"run": rid, "end": True}) + "\n")
    w.flush()
    s.shutdown(socket.SHUT_WR)
    replies = [json.loads(x) for x in r if x.strip()]
    s.close()
    finals = [d["final"] for d in replies if "final" in d]
    assert any(f.get("finalized_by") == "salvage" for f in finals), \
        f"no salvaged final in {replies}"
    # the salvaged prefix verdict is the true one for what was ingested
    salvaged = next(f for f in finals
                    if f.get("finalized_by") == "salvage")
    assert salvaged["valid"] is True
    # and the suffix re-routed: the survivor answered an end for the
    # re-opened run (its own final for the suffix)
    assert len(finals) >= 2, "suffix never finalized on the survivor"
    _teardown(servers, router, rsrv)


def test_fleet_aggregated_scrape_merges_workers():
    import urllib.request

    servers, specs, router, rsrv = _boot_fleet(2)
    rport = rsrv.server_address[1]
    # push one run through so worker counters move
    _client(rport, _op_lines("scrape-run", _mk_history(42, 40)))
    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{rport}/api/stats", timeout=10).read())
    assert stats["n_workers"] == 3  # w0 + w1 + the router itself
    assert "jtpu_stream_ops_ingested_total" in stats
    assert "jtpu_fleet_routed_total" in stats
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{rport}/metrics", timeout=10)\
        .read().decode()
    assert 'worker="router"' in text
    assert "jtpu_fleet_workers" in text
    _teardown(servers, router, rsrv)


def test_router_sheds_on_admission_decision():
    servers, specs, router, rsrv = _boot_fleet(2)
    # a policy that sheds everything: open_runs ceiling of 0
    router.admission = AdmissionController(
        AdmissionPolicy(max_open_runs=0))
    rport = rsrv.server_address[1]
    out = _client(rport, _op_lines("shed-me", _mk_history(9, 20)))
    assert any(d.get("overloaded") == "admission" for d in out)
    assert not any("final" in d for d in out)
    _teardown(servers, router, rsrv)


# ---------------------------------------------------------------------------
# verdict-cache spawn damping
# ---------------------------------------------------------------------------


def test_scale_signal_extracts_cache_hit_miss_labels():
    """The FleetCacheStore hit/miss labels ride into the signal; a
    worker that never fired the counter reports bare 0 -> 0.0."""
    sig = scale_signal({"values": {
        "jtpu_verdict_cache_total": {"hit": 40.0, "miss": 160.0,
                                     "insert": 12.0},
    }})
    assert sig["cache_hits"] == 40.0
    assert sig["cache_misses"] == 160.0
    assert scale_signal({"values": {
        "jtpu_verdict_cache_total": 0}})["cache_hits"] == 0.0


def test_admission_cold_cache_damps_spawn():
    """Spawn conditions met, but the fleet verdict cache is cold past
    the minimum-lookups floor: the controller admits instead of
    forking a worker that would boot colder still.  A warm cache (or
    too few lookups to mean anything) leaves spawn undamped."""
    t = {"now": 0.0}

    def ctl():
        return AdmissionController(
            AdmissionPolicy(spawn_open_runs=10,
                            min_spawn_interval_s=0.0,
                            spawn_min_cache_hit_ratio=0.2,
                            cache_signal_min_lookups=256),
            clock=lambda: t["now"])

    busy = {"open_runs": 50, "fold_backlog": 0,
            "shed_total": 0, "ops_total": 100}
    # cold cache, enough lookups: damped to accept
    cold = {**busy, "cache_hits": 30.0, "cache_misses": 470.0}
    c = ctl()
    assert c.cache_hit_ratio(cold) == 0.06
    assert c.decide(cold) == "accept"
    # warm cache: spawn goes through
    warm = {**busy, "cache_hits": 400.0, "cache_misses": 100.0}
    assert ctl().decide(warm) == "spawn-worker"
    # cold but below the lookup floor: ratio means nothing -> spawn
    sparse = {**busy, "cache_hits": 1.0, "cache_misses": 40.0}
    c = ctl()
    assert c.cache_hit_ratio(sparse) is None
    assert c.decide(sparse) == "spawn-worker"
    # no cache keys at all (legacy signal): unaffected
    assert ctl().decide(busy) == "spawn-worker"


def test_trace_shapes_carry_model_and_shard_coords(tmp_path):
    """A sharded device.compile span round-trips model descriptor,
    per-shard lanes and shard count into a WarmShape the warm boot can
    hand straight to get_sharded_batch_kernel."""
    from jepsen_tpu.fleet.warmup import load_shapes

    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "device.compile", "args": {
            "engine": "xla", "n_det_pad": 64, "n_crash_pad": 32,
            "window": 32, "k": 4, "frontier": 64, "sharded": True,
            "shards": 8, "batch": 2, "masked": True,
            "masked_crash": False, "dedup": True, "vt": 8,
            "model": "cas-register", "model_init": -2147483648,
            "model_width": 1}},
    ]}))
    shapes = load_shapes(str(trace))
    assert len(shapes) == 1
    s = shapes[0]
    assert s.model == ("cas-register", -2147483648, 1)
    assert s.shards == 8
    assert s.batch == 16  # per-shard lanes x shard count
    assert s.masked and s.dedup
