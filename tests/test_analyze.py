"""Static analysis (jepsen_tpu/analyze/): linter + plan explainer.

Three contracts:

  * every error code drives a fatal diagnostic through EVERY wired
    engine entry point (check_opseq, check_opseq_linear, Linearizable,
    search_batch, the decompose engine) — and ``lint=False`` /
    JEPSEN_TPU_LINT=0 restores the old permissive behavior;
  * differential fuzz: the linter NEVER alters a verdict on well-formed
    histories (>= 200 synthetic histories, :info ops included);
  * the plan explainer predicts the same SearchDims / bucket /
    decomposition choices the live engines make on the BENCH configs
    (explain output compared to recorded run stats).
"""

import os
import random
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu import synth  # noqa: E402
from jepsen_tpu.analyze import (  # noqa: E402
    HistoryLintError,
    analyze,
    explain,
    explain_batch,
    lint_history,
    lint_opseq,
)
from jepsen_tpu.analyze.lint import scan_events  # noqa: E402
from jepsen_tpu.checker.linear import check_opseq_linear  # noqa: E402
from jepsen_tpu.checker.linearizable import (  # noqa: E402
    Linearizable,
    search_batch,
    search_opseq,
)
from jepsen_tpu.checker.seq import check_opseq  # noqa: E402
from jepsen_tpu.decompose.engine import check_opseq_decomposed  # noqa: E402
from jepsen_tpu.history import (  # noqa: E402
    Op,
    complete,
    encode_ops,
    fail_op,
    invoke_op,
    ok_op,
    pair_index,
)
from jepsen_tpu.models import cas_register, multi_register, register  # noqa: E402


def codes(diags):
    return {d.code for d in diags}


def err_codes(diags):
    return {d.code for d in diags if d.severity == "error"}


# ---------------------------------------------------------------------------
# event-level linter: every code
# ---------------------------------------------------------------------------


def test_clean_history_no_diagnostics():
    rng = random.Random(11)
    h = synth.sim_register_history(rng, n_ops=60, crash_p=0.1)
    assert lint_history(h, cas_register()) == []


def test_nemesis_events_are_exempt():
    # the nemesis journals :info for both invocation and completion
    # (core.py NemesisWorker); that must not read as orphan completions
    h = [Op(process="nemesis", type="info", f="start"),
         Op(process="nemesis", type="info", f="start"),
         invoke_op(0, "write", 1), ok_op(0, "write", 1),
         Op(process="nemesis", type="info", f="stop"),
         Op(process="nemesis", type="info", f="stop")]
    assert lint_history(h, cas_register()) == []


def test_h001_double_invoke():
    h = [invoke_op(0, "write", 1), invoke_op(0, "write", 2),
         ok_op(0, "write", 2)]
    diags = lint_history(h)
    assert err_codes(diags) == {"H001"}
    assert diags[0].index == 1 and diags[0].process == 0


def test_h002_orphan_completion():
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         ok_op(0, "write", 1)]
    assert err_codes(lint_history(h)) == {"H002"}


def test_h003_bad_completion_type():
    h = [invoke_op(0, "write", 1),
         Op(process=0, type="oops", f="write", value=1)]
    diags = lint_history(h)
    assert "H003" in err_codes(diags)


def test_h004_nonmonotone_indices_warn_only():
    h = [Op(process=0, type="invoke", f="write", value=1, index=5),
         Op(process=0, type="ok", f="write", value=1, index=3)]
    diags = lint_history(h)
    assert codes(diags) == {"H004"}
    assert all(d.severity == "warning" for d in diags)


def test_h005_unencodable_value():
    h = [invoke_op(0, "write", [1, 2, 3]), ok_op(0, "write", [1, 2, 3])]
    assert "H005" in err_codes(lint_history(h))


def test_h005_skips_dropped_fail_rows():
    # encode_ops drops :fail rows before encoding their value; the lint
    # must mirror that (a defect on a dropped row is a non-event)
    h = [invoke_op(0, "write", [1, 2, 3]),
         fail_op(0, "write", [1, 2, 3])]
    assert lint_history(h, cas_register()) == []


def test_h006_value_drift_warning():
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 2)]
    diags = lint_history(h)
    assert codes(diags) == {"H006"}
    assert all(d.severity == "warning" for d in diags)


def test_h006_nil_lane_refinement_is_clean():
    # multi-register reads invoke with (key, nil); the completion fills
    # the nil lane — complete()'s documented contract, not drift
    h = [invoke_op(0, "read", (3, None)), ok_op(0, "read", (3, 7))]
    assert lint_history(h, multi_register(8)) == []


def test_m001_unknown_f():
    h = [invoke_op(0, "frobnicate", 1), ok_op(0, "frobnicate", 1)]
    diags = lint_history(h, cas_register())
    assert "M001" in err_codes(diags)
    # without a model the check cannot run
    assert "M001" not in codes(lint_history(h))


def test_m001_skips_failed_rows():
    h = [invoke_op(0, "frobnicate", 1), fail_op(0, "frobnicate", 1)]
    assert lint_history(h, cas_register()) == []


def test_scan_facts():
    rng = random.Random(5)
    h = synth.register_history(rng, n_ops=50, n_procs=6, overlap=4,
                               crash_p=0.1, max_crashes=3)
    sc = scan_events(h, cas_register())
    assert sc.diagnostics == []
    assert sc.n_invoke == sum(1 for op in h if op.type == "invoke")
    assert sc.n_info == sum(1 for op in h if op.type == "info")
    assert sc.concurrency >= 1
    assert sc.pairs == pair_index(h)


# ---------------------------------------------------------------------------
# OpSeq-level linter
# ---------------------------------------------------------------------------


def _valid_seq(seed=3, n=40, crash_p=0.1):
    rng = random.Random(seed)
    h = synth.sim_register_history(rng, n_ops=n, crash_p=crash_p)
    return encode_ops(h, cas_register().f_codes)


def test_opseq_clean():
    assert lint_opseq(_valid_seq(), cas_register()) == []


def test_opseq_nonmonotone_inv():
    seq = _valid_seq()
    seq.inv = seq.inv[::-1].copy()
    assert "H004" in err_codes(lint_opseq(seq))


def test_opseq_ret_before_inv():
    seq = _valid_seq()
    seq.ret = np.asarray(seq.ret).copy()
    seq.ret[0] = int(seq.inv[0])  # returns at its own invocation rank
    assert "H004" in err_codes(lint_opseq(seq))


def test_opseq_ok_never_returns():
    from jepsen_tpu.history import INF_RET

    seq = _valid_seq()
    rows = np.nonzero(np.asarray(seq.ok))[0]
    seq.ret = np.asarray(seq.ret).copy()
    seq.ret[rows[0]] = INF_RET
    assert "H002" in err_codes(lint_opseq(seq))


def test_opseq_unknown_f_code():
    seq = _valid_seq()
    seq.f = np.asarray(seq.f).copy()
    seq.f[0] = 99
    assert "M001" in err_codes(lint_opseq(seq, cas_register()))


def test_opseq_column_shape_mismatch():
    seq = _valid_seq()
    seq.v1 = np.asarray(seq.v1)[:-1].copy()
    assert "H007" in err_codes(lint_opseq(seq))


# ---------------------------------------------------------------------------
# engine wiring: fatal on errors, off-switches honored
# ---------------------------------------------------------------------------

ENGINES = [
    pytest.param(lambda s, m: check_opseq(s, m), id="check_opseq"),
    pytest.param(lambda s, m: check_opseq_linear(s, m),
                 id="check_opseq_linear"),
    pytest.param(lambda s, m: search_opseq(s, m, budget=10_000),
                 id="search_opseq"),
    pytest.param(lambda s, m: search_batch([s], m, budget=10_000),
                 id="search_batch"),
    pytest.param(lambda s, m: check_opseq_decomposed(
        s, m, sub_max_configs=100_000), id="decompose"),
]

#: the off-switch variants run HOST engines only — the point is the
#: permissive contract, and a garbage encoding fed to the device BFS can
#: cost arbitrary search time (exactly why the linter exists)
ENGINES_OFF = [
    pytest.param(lambda s, m: check_opseq(s, m, max_configs=100_000,
                                          lint=False),
                 id="check_opseq"),
    pytest.param(lambda s, m: check_opseq_linear(
        s, m, max_configs=100_000, lint=False),
                 id="check_opseq_linear"),
    pytest.param(lambda s, m: check_opseq_decomposed(
        s, m, sub_max_configs=100_000, lint=False), id="decompose"),
]


def _malformed_seq():
    seq = _valid_seq(seed=9, n=12, crash_p=0.0)
    seq.inv = seq.inv[::-1].copy()
    return seq


@pytest.mark.parametrize("engine", ENGINES)
def test_engines_raise_on_malformed(engine):
    with pytest.raises(HistoryLintError) as ei:
        engine(_malformed_seq(), cas_register())
    assert any(d.code == "H004" for d in ei.value.diagnostics)


@pytest.mark.parametrize("engine", ENGINES_OFF)
def test_engines_permissive_with_lint_off(engine):
    # lint=False restores the seed's silent tolerance: the engine runs
    # (whatever it concludes) instead of raising
    out = engine(_malformed_seq(), cas_register())
    if isinstance(out, list):
        out = out[0]
    assert out["valid"] in (True, False, "unknown")


def test_device_engines_permissive_with_lint_off():
    # a mildly-corrupted seq (ok row that never returns — H002 at the
    # opseq level) stays cheap to search, so the device entries can
    # demonstrate the same off-switch without unbounded work
    from jepsen_tpu.history import INF_RET

    seq = _valid_seq(seed=13, n=12, crash_p=0.0)
    rows = np.nonzero(np.asarray(seq.ok))[0]
    seq.ret = np.asarray(seq.ret).copy()
    seq.ret[rows[-1]] = INF_RET
    with pytest.raises(HistoryLintError):
        search_opseq(seq, cas_register(), budget=10_000)
    r1 = search_opseq(seq, cas_register(), budget=100_000, lint=False)
    r2 = search_batch([seq], cas_register(), budget=100_000,
                      lint=False)[0]
    assert r1["valid"] in (True, False, "unknown")
    assert r2["valid"] in (True, False, "unknown")


def test_env_knob_disables_lint(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_LINT", "0")
    out = check_opseq(_malformed_seq(), cas_register())
    assert out["valid"] in (True, False, "unknown")


def test_search_batch_names_offending_key():
    good = _valid_seq(seed=1, n=20, crash_p=0.0)
    with pytest.raises(HistoryLintError) as ei:
        search_batch([good, _malformed_seq()], cas_register(),
                     budget=10_000)
    errs = [d for d in ei.value.diagnostics if d.severity == "error"]
    assert all("batch key 1" in d.message for d in errs)


def test_linearizable_raises_on_event_level_defects():
    chk = Linearizable(cas_register(), algorithm="linear")
    bad = [invoke_op(0, "write", 1), invoke_op(0, "write", 2),
           ok_op(0, "write", 2)]
    with pytest.raises(HistoryLintError):
        chk.check({"name": ""}, bad)
    # the per-checker off switch keeps the seed behavior
    out = Linearizable(cas_register(), algorithm="linear",
                       lint=False).check({"name": ""}, bad)
    assert out["valid"] in (True, False, "unknown")


def test_linearizable_surfaces_warnings():
    chk = Linearizable(cas_register(), algorithm="linear")
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 2)]  # H006 drift
    out = chk.check({"name": ""}, h)
    assert out["valid"] in (True, False)
    warns = out.get("lint_warnings", [])
    assert any(w["code"] == "H006" for w in warns)


def test_check_safe_degrades_lint_error_to_unknown():
    # a malformed history inside a real run must degrade the composed
    # verdict to unknown (with the diagnostic), never crash the run
    from jepsen_tpu.checker.core import check_safe

    chk = Linearizable(cas_register(), algorithm="linear")
    bad = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
           ok_op(0, "write", 1)]
    out = check_safe(chk, {"name": ""}, bad)
    assert out["valid"] == "unknown"
    assert "H002" in str(out.get("error", ""))


# ---------------------------------------------------------------------------
# strict pair_index / complete (satellite 1)
# ---------------------------------------------------------------------------


def test_pair_index_strict_and_permissive():
    dbl = [invoke_op(0, "write", 1), invoke_op(0, "write", 2),
           ok_op(0, "write", 2)]
    orphan = [ok_op(0, "write", 1)]
    # permissive (default): double-invoke overwrites, orphan dropped
    assert pair_index(dbl) == {1: 2, 2: 1}
    assert pair_index(orphan) == {}
    with pytest.raises(HistoryLintError) as ei:
        pair_index(dbl, strict=True)
    assert ei.value.diagnostics[0].code == "H001"
    with pytest.raises(HistoryLintError) as ei:
        pair_index(orphan, strict=True)
    assert ei.value.diagnostics[0].code == "H002"


def test_complete_strict_and_permissive():
    orphan = [invoke_op(0, "read", None), ok_op(0, "read", 5),
              ok_op(1, "read", 6)]
    done = complete(orphan)
    assert done[0].value == 5  # permissive fill-in still works
    with pytest.raises(HistoryLintError):
        complete(orphan, strict=True)
    # well-formed histories pass strict mode untouched
    rng = random.Random(2)
    h = synth.sim_register_history(rng, n_ops=30, crash_p=0.1)
    assert complete(h, strict=True) == complete(h)


# ---------------------------------------------------------------------------
# differential fuzz: lint never alters verdicts on well-formed histories
# ---------------------------------------------------------------------------


def _fuzz_histories(n=200):
    """Well-formed histories: valid and invalid, :info ops included,
    register + mutex + queue shapes."""
    out = []
    for i in range(n):
        rng = random.Random(1000 + i)
        kind = i % 4
        if kind == 0:
            h = synth.sim_register_history(rng, n_ops=30,
                                           crash_p=0.15)
            m = cas_register()
        elif kind == 1:
            h = synth.sim_register_history(rng, n_ops=30, crash_p=0.1)
            h = synth.flip_read(rng, h)  # (almost always) invalid
            m = cas_register()
        elif kind == 2:
            from jepsen_tpu.models import mutex

            h = synth.sim_mutex_history(rng, n_ops=24, crash_p=0.1)
            m = mutex()
        else:
            h = synth.register_history(rng, n_ops=30, n_procs=4,
                                       overlap=3, crash_p=0.1,
                                       max_crashes=4,
                                       unique_writes=True, cas=False)
            if i % 8 == 3:
                h = synth.swap_read_values(rng, h)
            m = register(0)
        out.append((h, m))
    return out


def test_differential_fuzz_linter_verdict_neutral():
    checked = 0
    for h, m in _fuzz_histories(200):
        seq = encode_ops(h, m.f_codes)
        assert lint_opseq(seq, m) == [], "fuzz history must be clean"
        on = check_opseq_linear(seq, m, lint=True)
        off = check_opseq_linear(seq, m, lint=False)
        assert on["valid"] == off["valid"]
        assert on["configs"] == off["configs"]
        checked += 1
    assert checked >= 200


def test_differential_fuzz_wgl_and_batch():
    # a slice of the corpus through the other engines (the linear sweep
    # above covers volume; these cover the wiring)
    corpus = _fuzz_histories(24)
    seqs, models = [], []
    for h, m in corpus:
        seq = encode_ops(h, m.f_codes)
        on = check_opseq(seq, m, max_configs=200_000, lint=True)
        off = check_opseq(seq, m, max_configs=200_000, lint=False)
        assert on["valid"] == off["valid"]
        if m.name == "cas-register":
            seqs.append(seq)
    on_b = search_batch(seqs, cas_register(), budget=300_000, lint=True)
    off_b = search_batch(seqs, cas_register(), budget=300_000,
                         lint=False)
    assert [r["valid"] for r in on_b] == [r["valid"] for r in off_b]


# ---------------------------------------------------------------------------
# plan explainer vs the live engines (BENCH configs)
# ---------------------------------------------------------------------------


def test_explain_matches_engine_facts_bench_batch_key():
    """BENCH config #3's key shape: the plan's window/concurrency/dims
    must equal what the device engine reports after actually running."""
    import bench

    seq, model = bench.make_batch_key(0)  # valid key (k%4 != 0 pattern)
    plan = explain(seq, model)
    r = search_opseq(seq, model, budget=500_000)
    assert r["valid"] in (True, False)
    if "window" in r:  # device path reports its encoding facts
        assert plan["window"] == r["window"]
        assert plan["concurrency"] == r["concurrency"]
        assert plan["engine"] == "device-bfs"
    else:
        assert plan["engine"] == r["engine"]


def test_explain_engine_route_greedy_and_fallback():
    # greedy: a valid low-contention history is disposed host-side
    rng = random.Random(3)
    h = synth.register_history(rng, n_ops=60, n_procs=4, overlap=2,
                               crash_p=0.0)
    m = cas_register()
    seq = encode_ops(h, m.f_codes)
    plan = explain(seq, m)
    r = search_opseq(seq, m)
    assert plan["engine"] == r["engine"] == "greedy-witness"

    # fallback: crash count past MAX_CRASH forces the host sweep
    rng = random.Random(4)
    h2 = synth.register_history(rng, n_ops=400, n_procs=80, overlap=70,
                                crash_p=0.9, max_crashes=70)
    h2 = synth.corrupt_read(rng, h2, at=0.5)
    seq2 = encode_ops(h2, m.f_codes)
    from jepsen_tpu.checker.linearizable import MAX_CRASH, MAX_WINDOW

    es_facts = explain(seq2, m)
    if es_facts["n_crash"] > MAX_CRASH or es_facts["window"] > MAX_WINDOW:
        assert es_facts["engine"] == "host-linear(fallback)"
        assert not es_facts["device_eligible"]
        import time

        # the label (not the verdict) is what's under test, and it is
        # set on every exit path — a tight deadline keeps this cheap
        r2 = search_opseq(seq2, m, budget=50_000,
                          deadline=time.perf_counter() + 5.0)
        assert r2["engine"] in ("host-linear(fallback)",
                                "greedy-witness")


def test_explain_batch_matches_bucketed_run_stats():
    """The bucket plan (count + per-bucket dims + greedy/hard split)
    must equal the bucket_batch stats the live scheduler records."""
    import bench

    seqs, model = [], None
    for k in range(12):
        s, model = bench.make_batch_key(k)
        seqs.append(s)
    # one wide outlier so bucketing has real work to do (kept modest:
    # this test is about PLAN equality, not search throughput)
    rng = random.Random(77)
    wide = synth.register_history(rng, n_ops=256, n_procs=16,
                                  overlap=12, crash_p=0.0)
    wide = synth.corrupt_read(rng, wide, at=0.9)
    seqs.append(encode_ops(wide, model.f_codes))

    plan = explain_batch(seqs, model)
    # small budget: the PLAN equality under test is decided host-side;
    # invalid keys may exhaust it ("unknown"), which costs nothing here
    results = search_batch(seqs, model, budget=50_000, bucket=True)
    stats = results[0].get("bucket_batch")
    assert stats is not None, "bucketed run must record stats"
    assert plan["n_keys"] == stats["n_keys"]
    assert plan["n_buckets"] == stats["n_buckets"]
    assert plan["greedy"] == stats["greedy"]
    assert plan["hard"] == stats["hard"]
    # per-bucket: same sizes and the same tight dims, in the same
    # largest-cost-first order
    assert [b["n_keys"] for b in plan["buckets"]] == \
        [b["n_keys"] for b in stats["buckets"]]
    assert [b["dims"] for b in plan["buckets"]] == \
        [b["dims"] for b in stats["buckets"]]
    assert [b["padding_efficiency"] for b in plan["buckets"]] == \
        [b["padding_efficiency"] for b in stats["buckets"]]


def test_explain_decompositions_match_engine_methods():
    m = register(0)
    # unique-writes, no quiescence pressure -> value blocks apply
    rng = random.Random(21)
    h = synth.register_history(rng, n_ops=80, n_procs=6, overlap=5,
                               crash_p=0.0, unique_writes=True,
                               cas=False)
    seq = encode_ops(h, m.f_codes)
    plan = explain(seq, m)
    assert plan["decompositions"]["value_blocks"]["applies"]
    r = check_opseq_decomposed(seq, m, sub_max_configs=500_000)
    assert "value-blocks" in r["decompose"]["methods"]

    # reused values + permanent overlap -> nothing applies, engine goes
    # direct (the 10k64 "applies: false" case, scaled down)
    rng = random.Random(22)
    h2 = synth.register_history(rng, n_ops=80, n_procs=8, overlap=8,
                                crash_p=0.0, n_values=3, cas=False)
    seq2 = encode_ops(h2, m.f_codes)
    plan2 = explain(seq2, m)
    assert not plan2["decompositions"]["value_blocks"]["applies"]
    r2 = check_opseq_decomposed(seq2, m, sub_max_configs=500_000)
    expect = {"direct", "sub-search"}
    if plan2["decompositions"]["quiescence"]["applies"]:
        expect.add("quiescence")
    assert set(r2["decompose"]["methods"]) <= expect | {"cache"}

    # quiescent history -> cuts predicted and used
    rng = random.Random(23)
    h3 = synth.register_history(rng, n_ops=40, n_procs=3, overlap=1,
                                crash_p=0.0, n_values=3, cas=False)
    seq3 = encode_ops(h3, m.f_codes)
    plan3 = explain(seq3, m)
    r3 = check_opseq_decomposed(seq3, m, sub_max_configs=500_000)
    if plan3["decompositions"]["quiescence"]["applies"]:
        assert r3["decompose"]["segments"] == \
            plan3["decompositions"]["quiescence"]["segments"]


def test_explain_multi_register_key_partition():
    m = multi_register(4)
    rng = random.Random(31)
    h = []
    for p in range(3):
        for i in range(6):
            k = rng.randrange(4)
            h.append(invoke_op(p, "write", (k, p * 100 + i)))
            h.append(ok_op(p, "write", (k, p * 100 + i)))
    seq = encode_ops(h, m.f_codes)
    plan = explain(seq, m)
    kp = plan["decompositions"]["key_partition"]
    assert kp["applies"]
    r = check_opseq_decomposed(seq, m, sub_max_configs=500_000)
    assert r["decompose"]["cells"] == kp["cells"]


# ---------------------------------------------------------------------------
# plan gates for the live families added since PR 7 (replicated,
# replicated-queue, pgwire) — regression pins so explain() routes them
# instead of falling through to defaults
# ---------------------------------------------------------------------------


def _v2_style_history(keyed=False, with_cas=False):
    """A replicated/pgwire-shaped history: cas_register(MISSING=-1)
    semantics — reads of a missing row return -1, unique writes."""
    h = [invoke_op(0, "read", (7, None) if keyed else -1),
         ok_op(0, "read", (7, -1) if keyed else -1),
         invoke_op(1, "write", (7, 5) if keyed else 5),
         ok_op(1, "write", (7, 5) if keyed else 5),
         invoke_op(0, "read", (7, 5) if keyed else 5),
         ok_op(0, "read", (7, 5) if keyed else 5),
         invoke_op(2, "write", (9, 8) if keyed else 8),
         ok_op(2, "write", (9, 8) if keyed else 8)]
    if with_cas:
        h += [invoke_op(1, "cas", (8, 11)), ok_op(1, "cas", (8, 11))]
    return h


def test_plan_routes_replicated_family():
    """cas_register(-1) — the replicated/pgwire model with MISSING
    reads.  Unique-writes all-:ok histories must hit the value-block
    AND hb decide-fast gates (not fall through to a raw search), and
    the prediction must match a real engine run."""
    m = cas_register(-1)
    seq = encode_ops(_v2_style_history(), m.f_codes)
    plan = explain(seq, m)
    assert not plan["independent"]["detected"]
    assert plan["decompositions"]["value_blocks"]["applies"]
    assert plan["hb"]["applies"]
    assert plan["hb"]["decided"] is True
    assert plan["hb"]["reason"] == "gk-interval"
    st = plan["streaming"]
    assert st["device_eligible"] is True  # register family state-pins
    r = check_opseq(seq, m)
    assert r["valid"] is True

    # cas rows take the history out of the unique-writes algebra: the
    # hb gate must say so (decide-fast off, canonical read-order only)
    seq2 = encode_ops(_v2_style_history(with_cas=True), m.f_codes)
    plan2 = explain(seq2, m)
    assert plan2["hb"]["decided"] is None
    assert "cas" in plan2["hb"]["reason"]
    assert plan2["hb"]["edges"]["rf"] == 0


def test_plan_routes_pgwire_independent_composite():
    """The pgwire/kv campaign records jepsen.independent [k v]
    histories; under the register model the whole-history plan used to
    mis-read key lanes as values.  explain() must flag the composite
    and name the per-key demux route."""
    m = cas_register(-1)
    seq = encode_ops(_v2_style_history(keyed=True), m.f_codes)
    plan = explain(seq, m)
    ind = plan["independent"]
    assert ind["detected"] is True
    assert ind["keys"] == 2  # keys 7 and 9
    assert "demux" in ind["route"]
    from jepsen_tpu.analyze.plan import render_plan

    assert "KEYED COMPOSITE" in render_plan(plan)
    # an un-keyed history must not trip the gate
    plain = explain(encode_ops(_v2_style_history(), m.f_codes), m)
    assert plain["independent"] == {"detected": False}


def test_plan_routes_replicated_queue_family():
    """unordered-queue (the replicated-queue/disque multiset model):
    every register-only gate must decline WITH a reason, the hb pass
    must report itself out of scope, and segment folds must never
    predict the device state-pinning route."""
    from jepsen_tpu.analyze.plan import segment_fold_route
    from jepsen_tpu.models import unordered_queue

    m = unordered_queue(8)
    h = []
    for i in range(4):
        h += [invoke_op(i % 2, "enqueue", i + 1),
              ok_op(i % 2, "enqueue", i + 1)]
    for i in range(4):
        h += [invoke_op(i % 2, "dequeue", i + 1),
              ok_op(i % 2, "dequeue", i + 1)]
    seq = encode_ops(h, m.f_codes)
    plan = explain(seq, m)
    dec = plan["decompositions"]
    assert dec["key_partition"]["applies"] is False
    assert "multi-register" in dec["key_partition"]["reason"]
    assert dec["value_blocks"]["applies"] is False
    assert "single register" in dec["value_blocks"]["reason"]
    assert plan["hb"]["applies"] is False
    assert "out of scope" in plan["hb"]["reason"]
    st = plan["streaming"]
    assert st["device_eligible"] is False
    assert st["routes"]["device"] == 0
    # the fold router must pin queue folds to host at ANY size: the
    # pseudo-op state pinning trick needs a single-value register
    assert segment_fold_route(10_000, 40, m) == "host"
    assert segment_fold_route(10_000, 40, m, host_fold_max=0) == "host"
    r = check_opseq(seq, m)
    assert r["valid"] is True


def test_analyze_end_to_end_and_render():
    from jepsen_tpu.analyze.plan import render_plan

    rng = random.Random(41)
    h = synth.sim_register_history(rng, n_ops=40, crash_p=0.1)
    rep = analyze(h, cas_register())
    assert rep["errors"] == 0
    assert rep["plan"] is not None
    text = render_plan(rep["plan"])
    assert "SearchDims" in text and "decompositions" in text
    # malformed history: no plan, errors reported
    bad = [invoke_op(0, "write", 1), invoke_op(0, "write", 2),
           ok_op(0, "write", 2)]
    rep2 = analyze(bad, cas_register())
    assert rep2["errors"] >= 1 and rep2["plan"] is None


def test_analyze_cli_module(tmp_path):
    from jepsen_tpu import store
    from jepsen_tpu.analyze.__main__ import main

    rng = random.Random(51)
    h = synth.sim_register_history(rng, n_ops=30, crash_p=0.1)
    p = tmp_path / "history.jsonl"
    import json

    with open(p, "w") as f:
        for op in h:
            f.write(json.dumps(op.to_dict()) + "\n")
    assert store.read_history(str(p))  # format sanity
    assert main([str(p), "--model", "cas-register", "--explain"]) == 0
    assert main([str(p), "--json"]) == 0
    # lint errors exit 1
    bad = tmp_path / "bad.jsonl"
    with open(bad, "w") as f:
        f.write(json.dumps({"process": 0, "type": "ok", "f": "write",
                            "value": 1}) + "\n")
    assert main([str(bad)]) == 1


# ---------------------------------------------------------------------------
# Q-codes: the queue-history lint family (analyze/lint.py + the
# multiset checkers' on-by-default wiring in checker/basic.py)
# ---------------------------------------------------------------------------


def _qops(*specs):
    from jepsen_tpu.history import info_op, invoke_op, ok_op

    mk = {"invoke": invoke_op, "ok": ok_op, "info": info_op}
    return [mk[t](p, f, v) for t, p, f, v in specs]


def test_q001_ack_without_claim_is_an_error():
    h = _qops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
              ("invoke", 1, "ack", 1), ("ok", 1, "ack", 1))
    codes = [d.code for d in scan_events(h).diagnostics]
    assert "Q001" in codes
    # wired on by default: the multiset checker raises
    from jepsen_tpu.analyze.lint import HistoryLintError
    from jepsen_tpu.checker import basic

    with pytest.raises(HistoryLintError):
        basic.total_queue().check({}, h)


def test_q002_double_ack_is_an_error():
    h = _qops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
              ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1),
              ("invoke", 1, "ack", 1), ("ok", 1, "ack", 1),
              ("invoke", 1, "ack", 1), ("ok", 1, "ack", 1))
    diags = scan_events(h).diagnostics
    assert [d.code for d in diags].count("Q002") == 1
    assert all(d.code != "Q001" for d in diags)  # claimed first: legal


def test_q003_unexpected_dequeue_warns_but_checker_judges():
    from jepsen_tpu.checker import basic

    h = _qops(("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 7))
    diags = scan_events(h).diagnostics
    q3 = [d for d in diags if d.code == "Q003"]
    assert q3 and q3[0].severity == "warning"
    # the checker still returns its own verdict, warnings attached
    out = basic.total_queue().check({}, h)
    assert out["valid"] is False
    assert any(d["code"] == "Q003" for d in out["lint_warnings"])
    out2 = basic.queue().check({}, h)
    assert out2["valid"] is False
    assert any(d["code"] == "Q003" for d in out2["lint_warnings"])


def test_q003_drained_element_never_enqueued_warns():
    h = _qops(("invoke", 0, "drain", None), ("ok", 0, "drain", [5]))
    codes = [d.code for d in scan_events(h).diagnostics]
    assert "Q003" in codes


def test_q_codes_respect_the_lint_knob(monkeypatch):
    from jepsen_tpu.checker import basic

    h = _qops(("invoke", 1, "ack", 9), ("ok", 1, "ack", 9))
    monkeypatch.setenv("JEPSEN_TPU_LINT", "0")
    out = basic.total_queue().check({}, h)  # must not raise
    assert "lint_warnings" not in out


def test_clean_queue_history_has_no_q_codes():
    h = _qops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
              ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1),
              ("invoke", 1, "ack", 1), ("ok", 1, "ack", 1),
              ("invoke", 2, "drain", None), ("ok", 2, "drain", []))
    assert not [d for d in scan_events(h).diagnostics
                if d.code.startswith("Q")]
    from jepsen_tpu.checker import basic

    assert "lint_warnings" not in basic.total_queue().check({}, h)


def test_q_codes_documented():
    from jepsen_tpu.analyze.lint import ERROR_CODES, QUEUE_CODES

    for code in QUEUE_CODES:
        assert code in ERROR_CODES
