"""CLI + web + codec + report tests (cli exit-code contract
cli.clj:103-114; web surface web.clj)."""

import json
import threading
import urllib.request

import pytest

from jepsen_tpu import cli, codec, fixtures, repl, report, store, web
from jepsen_tpu import generator as gen
from jepsen_tpu.checker import linearizable as lin
from jepsen_tpu.models import cas_register


def test_parse_concurrency():
    opts = {"concurrency": "3n", "nodes": ["a", "b", "c"]}
    assert cli.parse_concurrency(opts)["concurrency"] == 9
    opts = {"concurrency": "7", "nodes": ["a"]}
    assert cli.parse_concurrency(opts)["concurrency"] == 7
    with pytest.raises(ValueError):
        cli.parse_concurrency({"concurrency": "x2", "nodes": []})


def test_parse_nodes_file(tmp_path):
    f = tmp_path / "nodes"
    f.write_text("h1\nh2\n\n")
    opts = cli.parse_nodes({"nodes_file": str(f), "nodes": ["ignored"]})
    assert opts["nodes"] == ["h1", "h2"]
    assert cli.parse_nodes({"nodes": None,
                            "nodes_file": None})["nodes"] == \
        cli.DEFAULT_NODES


def test_rename_ssh_options():
    opts = cli.rename_ssh_options({"username": "admin", "password": "pw",
                                   "strict_host_key_checking": True,
                                   "ssh_private_key": "/k"})
    assert opts["ssh"] == {"username": "admin", "password": "pw",
                           "strict_host_key_checking": True,
                           "private_key_path": "/k"}


def make_test_fn(state_box, store_base):
    def test_fn(opts):
        state = fixtures.AtomRegister()
        state_box.append(state)
        return fixtures.noop_test() | {
            "name": "cli-demo",
            "store_base": store_base,
            "nodes": opts["nodes"],
            "concurrency": min(opts["concurrency"], 4),
            "db": fixtures.atom_db(state),
            "client": fixtures.atom_client(state),
            "model": cas_register(0),
            "checker": lin.linearizable(),
            "generator": gen.clients(gen.limit(
                20, {"type": "invoke", "f": "read", "value": None})),
        }
    return test_fn


def test_cli_end_to_end_exit_codes(tmp_path):
    boxes = []
    cmds = cli.single_test_cmd(make_test_fn(boxes, str(tmp_path / "store")))
    rc = cli.run(cmds, ["test", "-n", "a", "-n", "b", "--concurrency", "2n",
                        "--dummy"])
    assert rc == cli.EXIT_OK
    assert len(boxes) == 1

    rc = cli.run(cmds, ["bogus-subcommand"])
    assert rc == cli.EXIT_BAD_ARGS
    rc = cli.run(cmds, [])
    assert rc == cli.EXIT_BAD_ARGS


def test_web_serves_store(tmp_path):
    base = str(tmp_path / "store")
    test = {"name": "webdemo", "start_time": "20260729T120000",
            "store_base": base}
    store.save_1(test, [])
    store.save_2(test, {"valid": True})

    srv = web.make_server(host="127.0.0.1", port=0, base=base)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        home = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/").read().decode()
        assert "webdemo" in home and "valid-true" in home
        d = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/files/webdemo/20260729T120000/"
        ).read().decode()
        assert "results.json" in d
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/files/webdemo/20260729T120000/"
            f"results.json").read()
        assert json.loads(r)["valid"] is True
        z = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/files/webdemo/20260729T120000/?zip"
        ).read()
        assert z[:2] == b"PK"
        # path traversal is refused
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/files/../../etc/passwd")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req)
    finally:
        srv.shutdown()


def test_result_block_renders_search_telemetry():
    """The run result panel shows the device telemetry block: the
    observed prune ratio diffed against the predicted one, and a
    depth/occupancy sparkline from the per-level rows."""
    st = {"levels": 3, "slices": 1, "max_occupancy": 9,
          "expanded": 40, "mask_killed": 10, "dedup_folds": 2,
          "crash_rounds": 4, "overflows": 0, "goals": 1,
          "observed_prune_ratio": 0.769231, "truncated": False,
          "predicted_prune_ratio": 1.0,
          "prune_ratio_delta": -0.230769,
          "per_level": [[3, 12, 4, 0, 1, 6, 0, 0],
                        [6, 18, 4, 1, 2, 9, 0, 0],
                        [9, 10, 2, 1, 1, 0, 0, 1]],
          "per_level_columns": ["occupancy", "expanded",
                                "mask_killed", "dedup_folds",
                                "crash_rounds", "next_count",
                                "overflow", "goal"]}
    html = web.result_block({"valid": True, "engine": "device-bfs",
                             "configs": 40,
                             "search_telemetry": st})
    assert "device telemetry" in html
    assert "observed prune ratio 0.769231" in html
    assert "vs predicted 1.0" in html
    assert "depth/occupancy" in html
    assert "peak 9" in html
    # sparkline math: peak occupancy maps to the tallest block
    spark = web._occupancy_sparkline(st)
    assert spark and web._SPARK[-1] in spark
    # a result without the block renders exactly as before
    plain = web.result_block({"valid": True, "engine": "device-bfs",
                              "configs": 40})
    assert "device telemetry" not in plain
    assert "depth/occupancy" not in plain


def test_api_stats_derived_device_gauges(tmp_path):
    """/api/stats carries the fleet strip's derived
    device_idle_fraction and observed_prune_ratio gauges, and the
    /campaigns page polls them."""
    import os
    import urllib.request as rq

    from jepsen_tpu.obs import telemetry as tele

    tele.record_device_seconds(0.01)  # make the idle gauge non-null
    base = str(tmp_path / "store")
    os.makedirs(os.path.join(base, "campaigns"), exist_ok=True)
    srv = web.make_server(host="127.0.0.1", port=0, base=base)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        s = json.loads(rq.urlopen(
            f"http://127.0.0.1:{port}/api/stats").read())
        d = s["derived"]
        assert "device_idle_fraction" in d
        assert 0.0 <= d["device_idle_fraction"] <= 1.0
        assert "observed_prune_ratio" in d
        page = rq.urlopen(
            f"http://127.0.0.1:{port}/campaigns").read().decode()
        assert "device idle" in page
        assert "observed prune" in page
    finally:
        srv.shutdown()


def test_codec_roundtrip():
    for v in [None, 42, "hi", [1, 2, {"a": True}], {"k": [1, None]}]:
        assert codec.decode(codec.encode(v)) == v


def test_report_to(tmp_path, capsys):
    p = tmp_path / "out.txt"
    with report.to(str(p)):
        print("hello report")
    assert "hello report" in p.read_text()
    assert "hello report" in capsys.readouterr().out


def test_repl_last_test(tmp_path):
    base = str(tmp_path / "store")
    test = {"name": "t1", "start_time": "20260729T110000",
            "store_base": base}
    store.save_1(test, [])
    store.save_2(test, {"valid": False})
    out = repl.last_test(base)
    assert out["results"]["valid"] is False


def test_web_mc_panel(tmp_path, monkeypatch):
    """/mc renders the model-checker matrix; the sweep is stubbed so
    the page test doesn't pay for a real bounded search."""
    fake = {"ok": True, "runs": [
        {"family": "lock", "mode": "clean", "ok": True,
         "violations": [],
         "explored": {"states": 42, "schedules": 7, "events": 99,
                      "sleep_prunes": 3, "dedup": 1,
                      "prune_ratio": 0.03, "complete": True}},
        {"family": "lock", "mode": "volatile", "ok": False,
         "violations": [{
             "code": "MC106", "detail": "double grant",
             "schedule": [["op", 0], ["crash", 0], ["restart", 0],
                          ["op", 1]],
             "shrunk": {"n_from": 6, "n_to": 4, "checks": 9,
                        "minimal": True},
             "replayed": True,
             "confirm": {"route": "engine", "engine_valid": False,
                         "audit_ok": True, "audit_checked": 1}}],
         "explored": {"states": 50, "schedules": 9, "events": 120,
                      "sleep_prunes": 12, "dedup": 2,
                      "prune_ratio": 0.09, "complete": True}},
    ]}
    from jepsen_tpu.analyze import modelcheck
    monkeypatch.setattr(modelcheck, "run_mc_sweep", lambda: fake)
    monkeypatch.setattr(web, "_MC_CACHE", None)
    page = web.mc_html()
    assert "MC106" in page and "caught MC106" in page
    assert "as expected" in page and "UNEXPECTED" not in page
    assert "op(0) → crash(0) → restart(0) → op(1)" in page
    assert "engine valid=False" in page and "audit ok=True" in page
    # the home page links the panel
    monkeypatch.setattr(web, "_MC_CACHE", fake)
    srv = web.make_server(host="127.0.0.1", port=0,
                          base=str(tmp_path))
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        home = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/").read().decode()
        assert '<a href="/mc">' in home
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/mc").read().decode()
        assert "Bounded model checker" in page
    finally:
        srv.shutdown()
