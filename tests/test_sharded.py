"""Mesh-sharded frontier search: verdicts must match the host oracle;
exploration must be deterministic and (with dominance pruning) explore
at most the oracle's configuration space.  Exactness of the all_to_all
routing is guarded indirectly: a config lost in routing flips a VALID
history's verdict (the witness path dies out), and the differential
cases here include uncorrupted, valid histories for exactly that
reason.  Runs on the virtual 8-device CPU mesh (conftest)."""

import random

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from jepsen_tpu.checker import linearizable as lin
from jepsen_tpu.checker import seq as oracle
from jepsen_tpu.history import encode_ops
from jepsen_tpu.models import cas_register
from jepsen_tpu.synth import corrupt_read, register_history


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs a multi-device mesh")
    return Mesh(np.array(devs), ("shard",))


@pytest.mark.parametrize("seed", range(6))
def test_sharded_agrees_with_oracle(mesh, seed):
    rng = random.Random(seed)
    model = cas_register()
    h = register_history(rng, n_ops=50, n_procs=6, overlap=4, crash_p=0.1)
    if seed % 2:
        h = corrupt_read(rng, h, at=0.9)
    s = encode_ops(h, model.f_codes)
    want = oracle.check_opseq(s, model)["valid"]
    got = lin.search_opseq_sharded(s, model, mesh, frontier_per_device=128)
    assert got["valid"] == want, f"oracle={want} sharded={got}"


def test_sharded_exact_and_deterministic(mesh):
    rng = random.Random(42)
    model = cas_register()
    h = register_history(rng, n_ops=220, n_procs=16, overlap=6,
                         crash_p=0.01, max_crashes=4)
    h = corrupt_read(rng, h, at=0.95)
    s = encode_ops(h, model.f_codes)
    ref = oracle.check_opseq(s, model)
    counts = set()
    for _ in range(3):
        out = lin.search_opseq_sharded(s, model, mesh,
                                       frontier_per_device=256)
        assert out["valid"] == ref["valid"]
        counts.add(out["configs"])
    # deterministic across runs; dominance pruning means the sharded
    # engine explores AT MOST the oracle's configuration space (the
    # crash-subset dimension collapses to minimal antichains)
    assert len(counts) == 1, f"nondeterministic: {counts}"
    c = counts.pop()
    assert c <= ref["configs"], \
        f"sharded explored {c}, oracle {ref['configs']}"


def test_sharded_escalates_on_overflow(mesh):
    rng = random.Random(7)
    model = cas_register()
    h = register_history(rng, n_ops=120, n_procs=12, overlap=8)
    h = corrupt_read(rng, h, at=0.9)
    s = encode_ops(h, model.f_codes)
    ref = oracle.check_opseq(s, model)
    # start absurdly narrow; the ladder must still converge to the truth
    out = lin.search_opseq_sharded(s, model, mesh, frontier_per_device=64)
    assert out["valid"] == ref["valid"]


def test_sharded_escalation_resumes(mesh, monkeypatch):
    """Tiny per-device frontier forces the sharded ladder to widen; the
    verdict must still match the oracle (resume-from-carry soundness on
    the mesh path)."""
    import random

    from jepsen_tpu.checker import linearizable as lin, seq as oracle
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register

    monkeypatch.setattr(lin, "_SLICE_LEVELS0", 4)
    monkeypatch.setattr(lin, "_adapt_lvl_cap", lambda cap, dt: cap)
    from test_linearizable import corrupt, random_register_history

    rng = random.Random(911)
    h = corrupt(rng, random_register_history(rng, n_procs=4, n_ops=40))
    model = cas_register()
    s = encode_ops(h, model.f_codes)
    want = oracle.check_opseq(s, model)["valid"]
    out = lin.search_opseq_sharded(s, model, mesh,
                                   frontier_per_device=8,
                                   budget=500_000)
    assert out["valid"] == want, f"oracle={want} sharded={out}"


# ---------------------------------------------------------------------------
# multi-host plumbing (jepsen_tpu.distributed) — standalone degradation:
# process_count == 1 means the DCN ("keys") axis has size 1, the whole
# batch stays on this host, and verdicts must be unchanged.
# ---------------------------------------------------------------------------


def test_distributed_standalone_degrades():
    from jepsen_tpu import distributed as dist

    assert dist.init_from_env() is False  # no cluster configured
    info = dist.process_info()
    assert info["process_index"] == 0 and info["process_count"] == 1
    mesh = dist.multihost_mesh()
    assert mesh.shape["keys"] == 1
    assert mesh.shape["shard"] == len(jax.devices())
    sh = dist.keys_sharding(mesh)
    # a batch checked under the degraded sharding still gives exact
    # verdicts (single-host path)
    import random

    from jepsen_tpu.checker import linearizable as lin, seq as oracle
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    model = cas_register()
    seqs, want = [], []
    for k in range(8):
        rng = random.Random(4200 + k)
        h = register_history(rng, n_ops=24, n_procs=3, overlap=3)
        if k % 2 == 0:
            h = corrupt_read(rng, h, at=0.7)
        s = encode_ops(h, model.f_codes)
        seqs.append(s)
        want.append(oracle.check_opseq(s, model)["valid"])
    with mesh:
        got = lin.search_batch(seqs, model, budget=100_000, sharding=sh)
    assert [r["valid"] for r in got] == want


def test_sharded_batch_certificate_and_audit(mesh):
    """The mesh-sharded batch path's certificate/audit contract —
    ROADMAP noted it had 'never been exercised'.  Every per-key result
    coming back through the mesh route must either carry real evidence
    (greedy/hb witnesses) or state exactly why it cannot
    (witness_dropped / frontier_dropped), and the independent audit
    pass must replay every certificate clean (CPU mesh fallback)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from jepsen_tpu.analyze.audit import audit as audit_fn

    model = cas_register()
    seqs, want = [], []
    for k in range(8):
        rng = random.Random(8800 + k)
        h = register_history(rng, n_ops=28, n_procs=4, overlap=3,
                             crash_p=0.1 if k % 3 == 0 else 0.0)
        if k % 2 == 0:
            h = corrupt_read(rng, h, at=0.75)
        s = encode_ops(h, model.f_codes)
        seqs.append(s)
        want.append(oracle.check_opseq(s, model, dpor=False)["valid"])
    sh = NamedSharding(mesh, PartitionSpec("shard"))
    got = lin.search_batch(seqs, model, budget=300_000, sharding=sh,
                           audit=True)
    assert [r["valid"] for r in got] == want
    for k, (s, r) in enumerate(zip(seqs, got)):
        if r["valid"] is True:
            assert "linearization" in r or "witness_dropped" in r, \
                (k, r)
        elif r["valid"] is False:
            assert ("final_ops" in r or "hb_cycle" in r
                    or "frontier_dropped" in r), (k, r)
        a = audit_fn(s, model, r)
        assert a["ok"], (k, [str(d) for d in a["diagnostics"]])


def test_sharded_single_history_certificate_and_audit(mesh):
    """search_opseq_sharded's own certificate: a whole-history mesh
    verdict states its witness/frontier drop reason and audits clean —
    for a valid, an invalid, and an hb-decided history."""
    from jepsen_tpu.analyze.audit import audit as audit_fn

    model = cas_register()
    rng = random.Random(4242)
    h_ok = register_history(rng, n_ops=40, n_procs=4, overlap=4)
    h_bad = corrupt_read(rng, register_history(
        random.Random(4243), n_ops=40, n_procs=4, overlap=4), at=0.8)
    for h in (h_ok, h_bad):
        s = encode_ops(h, model.f_codes)
        want = oracle.check_opseq(s, model, dpor=False)["valid"]
        # hb=False exercises the real mesh kernels (the prepass would
        # decide these statically); a second call with the prepass ON
        # must return the same verdict with an hb certificate
        out = lin.search_opseq_sharded(s, model, mesh,
                                       frontier_per_device=128,
                                       hb=False)
        assert out["valid"] == want
        if out["valid"] is True:
            assert "linearization" in out or "witness_dropped" in out
        elif out["valid"] is False:
            assert "final_ops" in out or "frontier_dropped" in out
        a = audit_fn(s, model, out)
        assert a["ok"], [str(d) for d in a["diagnostics"]]
        dec = lin.search_opseq_sharded(s, model, mesh,
                                       frontier_per_device=128,
                                       audit=True)
        assert dec["valid"] == want


def test_sharded_deadline_and_slice_hook(mesh):
    """The sharded drive honors a deadline (verdict unknown, not a
    hang) and delivers every slice's carry + dims to on_slice — the
    scale-out analog of the single-device checkpoint hook."""
    import time

    rng = random.Random(99)
    model = cas_register()
    h = register_history(rng, n_ops=120, n_procs=8, overlap=6,
                         crash_p=0.1)
    h = corrupt_read(rng, h, at=0.9)
    s = encode_ops(h, model.f_codes)
    seen = []
    out = lin.search_opseq_sharded(
        s, model, mesh, frontier_per_device=64,
        deadline=time.perf_counter() - 1.0,  # already past: one slice
        on_slice=lambda carry, dims: seen.append(
            (np.asarray(carry[0]).shape, dims.frontier)))
    assert out["valid"] in (True, False, "unknown")
    assert seen, "on_slice never fired"
    shape, f = seen[0]
    assert shape[0] == f * mesh.shape["shard"]


# ---------------------------------------------------------------------------
# bucket-then-shard scheduler (checker/bucket.search_batch_sharded_bucketed)
# ---------------------------------------------------------------------------


def _mixed_batch(seed0, *, n=12):
    """The differential-fuzz key mix: small/medium/big op counts,
    :info crashes, corrupt (invalid) and clean (valid) histories, plus
    non-CAS register keys whose corrupt reads the hb/constraint
    prepass decides statically — those must dispose BEFORE sharding."""
    model = cas_register()
    seqs = []
    for k in range(n):
        rng = random.Random(seed0 + k)
        n_ops = (28, 50, 90)[k % 3]
        cas = k % 4 != 3
        h = register_history(rng, n_ops=n_ops, n_procs=5, overlap=4,
                             crash_p=0.1 if k % 3 == 0 else 0.0,
                             cas=cas)
        if k % 2 == 0 or not cas:
            h = corrupt_read(rng, h, at=0.8)
        seqs.append(encode_ops(h, model.f_codes))
    return seqs, model


@pytest.mark.parametrize("seed0", [5200, 6300])
def test_bucketed_sharded_differential_fuzz(mesh, seed0):
    """Bucketed-sharded vs fused-sharded vs single-device vs oracle:
    verdict-identical key-for-key on a mixed-size batch, with every
    certificate audited on all three engine routes."""
    from jax.sharding import NamedSharding, PartitionSpec

    from jepsen_tpu.analyze.audit import audit as audit_fn

    seqs, model = _mixed_batch(seed0)
    want = [oracle.check_opseq(s, model, dpor=False)["valid"]
            for s in seqs]
    sh = NamedSharding(mesh, PartitionSpec("shard"))
    got_b = lin.search_batch(seqs, model, budget=400_000, sharding=sh,
                             audit=True)
    got_f = lin.search_batch(seqs, model, budget=400_000, sharding=sh,
                             bucket=False, audit=True)
    got_1 = lin.search_batch(seqs, model, budget=400_000, audit=True)
    assert [r["valid"] for r in got_b] == want
    assert [r["valid"] for r in got_f] == want
    assert [r["valid"] for r in got_1] == want
    for k, (s, rb) in enumerate(zip(seqs, got_b)):
        a = audit_fn(s, model, rb)
        assert a["ok"], (k, [str(d) for d in a["diagnostics"]])
    sb = got_b[0].get("shard_batch")
    assert sb, "bucketed-sharded stats block missing"
    assert sb["n_devices"] == mesh.shape["shard"]
    disposed = sb["greedy"] + sb["hb_decided"] \
        + sb["constraint_decided"] + sb["hard"]
    searched = sum(b["searched"] for b in sb["buckets"])
    assert disposed + searched == len(seqs)
    # non-CAS corrupt keys must never reach a device bucket
    assert sb["hb_decided"] + sb["constraint_decided"] > 0


def test_bucketed_sharded_explain_match(mesh):
    """explain_batch(n_devices=...)'s prediction matches the live
    shard_batch stats field-for-field on bench-config keys — the
    cost-model contract the shard tier gates on."""
    from jax.sharding import NamedSharding, PartitionSpec

    from jepsen_tpu.analyze.plan import explain_batch
    from jepsen_tpu.checker.shard_bench import _stats_match_plan

    model = cas_register()
    seqs = []
    for k in range(10):
        rng = random.Random(31000 + k)
        h = register_history(rng, n_ops=74 if k < 8 else 120,
                             n_procs=6, overlap=4)
        h = corrupt_read(rng, h, at=0.85)
        seqs.append(encode_ops(h, model.f_codes))
    sh = NamedSharding(mesh, PartitionSpec("shard"))
    got = lin.search_batch(seqs, model, budget=400_000, sharding=sh,
                           audit=False)
    sb = got[0].get("shard_batch")
    assert sb
    n_dev = mesh.shape["shard"]
    plan = explain_batch(seqs, model, n_devices=n_dev)
    match, diffs = _stats_match_plan(sb, plan)
    assert match, diffs
    assert plan["padding_efficiency"] == sb["padding_efficiency"]
    assert plan["fused_padded_ops"] == sb["fused_padded_ops"]


def test_sharded_pad_lanes_inert(mesh):
    """Mesh-divisibility pad lanes must not bill configs or occupancy:
    the same keys at the same dims, sharded (5 pad lanes on 8 devices)
    vs unsharded (no pads), produce identical per-key configs AND an
    identical telemetry block."""
    from jax.sharding import NamedSharding, PartitionSpec

    from jepsen_tpu.obs import telemetry as _tele

    model = cas_register()
    seqs = []
    for k in range(3):
        rng = random.Random(7100 + k)
        h = register_history(rng, n_ops=40, n_procs=5, overlap=4)
        h = corrupt_read(rng, h, at=0.85)
        seqs.append(encode_ops(h, model.f_codes))
    ess = [lin.encode_search(s) for s in seqs]
    dims = lin.batch_dims(ess, model, frontier=64)
    sh = NamedSharding(mesh, PartitionSpec("shard"))
    _tele.enable(True)
    try:
        got_s = lin.search_batch(seqs, model, budget=400_000, dims=dims,
                                 sharding=sh, audit=False)
        got_1 = lin.search_batch(seqs, model, budget=400_000, dims=dims,
                                 audit=False)
    finally:
        _tele.enable(None)
    assert [r["valid"] for r in got_s] == [r["valid"] for r in got_1]
    assert [r.get("configs") for r in got_s] \
        == [r.get("configs") for r in got_1]
    ts = got_s[0].get("search_telemetry")
    t1 = got_1[0].get("search_telemetry")
    assert ts is not None and t1 is not None
    for f in ("expanded", "mask_killed", "dedup_folds", "goals",
              "max_occupancy"):
        assert ts[f] == t1[f], \
            (f, ts[f], t1[f], "pad lanes leaked into telemetry")
