"""Perf graph + timeline rendering tests (the analog of perf_test.clj:
fixed history, exercise rendering, assert artifacts exist)."""

import os
import random

from jepsen_tpu.checker import perf, timeline
from jepsen_tpu.history import index as index_history
from jepsen_tpu.history import info_op, invoke_op, ok_op
from jepsen_tpu.synth import register_history


def fixed_history():
    rng = random.Random(0)
    h = register_history(rng, n_ops=60, n_procs=4, overlap=3, crash_p=0.05)
    # nemesis window mid-test
    h.insert(len(h) // 3, info_op("nemesis", "start", "partition!"))
    h.insert(2 * len(h) // 3, info_op("nemesis", "stop", "healed"))
    # timestamps: 0.5s apart
    out = []
    for i, op in enumerate(h):
        from dataclasses import replace

        out.append(replace(op, time=int(i * 0.5e9)))
    return index_history(out)


def test_quantiles():
    assert perf.quantiles([0.5, 1.0], [1, 2, 3, 4]) == {0.5: 3, 1.0: 4}
    assert perf.quantiles([0.5], []) == {}


def test_latencies_to_quantiles():
    pts = [(0.0, 10.0), (1.0, 20.0), (11.0, 5.0)]
    out = perf.latencies_to_quantiles(10.0, [1.0], pts)
    assert out[1.0] == [(5.0, 20.0), (15.0, 5.0)]


def test_nemesis_regions():
    h = fixed_history()
    regions = perf.nemesis_regions(h)
    assert len(regions) == 1
    t0, t1 = regions[0]
    assert t0 < t1


def test_graphs_render(tmp_path):
    test = {"name": "perfdemo", "store_base": str(tmp_path),
            "start_time": "20260729T000000"}
    h = fixed_history()
    out = perf.perf().check(test, h, {})
    assert out["valid"] is True
    d = os.path.join(str(tmp_path), "perfdemo", "20260729T000000")
    assert os.path.exists(os.path.join(d, "latency-raw.png"))
    assert os.path.exists(os.path.join(d, "latency-quantiles.png"))
    assert os.path.exists(os.path.join(d, "rate.png"))


def test_timeline_pairs():
    h = [invoke_op(0, "read", None), invoke_op(1, "write", 1),
         ok_op(1, "write", 1), info_op(0, "read", None),
         info_op("nemesis", "start", None)]
    ps = timeline.pairs(h)
    assert len(ps) == 3
    # invoke+info pair for process 0; lone nemesis info
    assert any(a.process == 0 and b is not None and b.type == "info"
               for a, b in ps)
    assert any(a.process == "nemesis" and b is None for a, b in ps)


def test_timeline_html(tmp_path):
    test = {"name": "tldemo", "store_base": str(tmp_path),
            "start_time": "20260729T000000"}
    h = fixed_history()
    out = timeline.timeline().check(test, h, {})
    assert out["valid"] is True
    p = os.path.join(str(tmp_path), "tldemo", "20260729T000000",
                     "timeline.html")
    content = open(p).read()
    assert "op ok" in content and "class=\"ops\"" in content
