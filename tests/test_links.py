"""Per-peer-link partitions (jepsen_tpu/live/links.py + the grudge
math in jepsen_tpu/nemesis.py).

Tier-1 here: the pure grudge-topology math (split-one / bridge /
isolate-leader / one-way / random-halves produce the expected
(src, dst) rule sets — no iptables anywhere near these), the address
scheme, the crash-safe rule journal and its sweep contract (fake rule
engine — installs/removals recorded, never executed), the
LinkPartitionNemesis start/heal cycle over the journal, and the
``--dry-run`` validation of the full family × nemesis × grudge matrix
(spawns nothing).  A real-engine install/sweep round trip runs where
the host can actually stage links (iptables or tc), and skips with the
probe's own reason elsewhere.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NODES = ["n1", "n2", "n3"]


# ---------------------------------------------------------------------------
# grudge-topology math — pure functions
# ---------------------------------------------------------------------------


def test_grudge_links_directed_semantics():
    from jepsen_tpu import nemesis

    # node n dropping traffic FROM s is the link (s, n)
    grudge = {"n1": {"n3"}, "n3": {"n1"}}
    assert nemesis.grudge_links(grudge) == {("n3", "n1"), ("n1", "n3")}
    assert nemesis.grudge_links({}) == set()


def test_split_one_links_full_symmetric_cut():
    from jepsen_tpu import nemesis

    links = nemesis.split_one_links(NODES, "n2")
    assert links == {("n2", "n1"), ("n2", "n3"),
                     ("n1", "n2"), ("n3", "n2")}
    # loner chosen at random still cuts exactly one node fully
    links = nemesis.split_one_links(NODES)
    cut = {a for a, _ in links} & {b for _, b in links}
    [loner] = [n for n in NODES
               if all(n in (a, b) for a, b in links)]
    assert len(links) == 4
    assert loner in cut


def test_bridge_links_majority_with_overlap():
    from jepsen_tpu import nemesis

    # bisect([n1,n2,n3]) -> [n1] | [n2,n3], bridge n2: only n1<->n3 cut
    links = nemesis.bridge_links(NODES)
    assert links == {("n1", "n3"), ("n3", "n1")}
    # 5 nodes: halves [a,b] | [c,d,e], bridge c — every cross-half pair
    # except those touching the bridge
    links5 = nemesis.bridge_links(["a", "b", "c", "d", "e"])
    expected = set()
    for x in ("a", "b"):
        for y in ("d", "e"):
            expected |= {(x, y), (y, x)}
    assert links5 == expected


def test_isolate_links_one_way_asymmetry():
    from jepsen_tpu import nemesis

    # outbound-only: peers drop traffic FROM the victim; the reverse
    # direction stays up — the asymmetric split-brain stager
    out = nemesis.isolate_links(NODES, "n1", inbound=False,
                                outbound=True)
    assert out == {("n1", "n2"), ("n1", "n3")}
    inb = nemesis.isolate_links(NODES, "n1", inbound=True,
                                outbound=False)
    assert inb == {("n2", "n1"), ("n3", "n1")}
    assert nemesis.isolate_links(NODES, "n1") == out | inb
    # one-way sets are disjoint from their reverses (truly asymmetric)
    assert not out & {(b, a) for a, b in out}


def test_random_halves_links_symmetric_partition():
    from jepsen_tpu import nemesis

    links = nemesis.random_halves_links(["a", "b", "c", "d"])
    # 2|2 halves: 4 directed cross links in each direction
    assert len(links) == 8
    assert links == {(b, a) for a, b in links}  # symmetric
    # every node keeps at least one peer it still talks to
    for n in ("a", "b", "c", "d"):
        cut_from_n = {d for s, d in links if s == n}
        assert len(cut_from_n) == 2


def test_all_peer_links_and_bidirectional():
    from jepsen_tpu import nemesis

    assert nemesis.all_peer_links(["x", "y"]) == {("x", "y"),
                                                  ("y", "x")}
    assert nemesis.bidirectional({("a", "b")}) == {("a", "b"),
                                                   ("b", "a")}


def test_node_addr_scheme():
    from jepsen_tpu.live import links

    test = {"nodes": NODES}
    assert [links.node_addr(test, n) for n in NODES] == \
        ["127.0.1.1", "127.0.1.2", "127.0.1.3"]
    assert links.node_addr({"nodes": NODES,
                            "addr_base": "127.0.2."}, "n2") \
        == "127.0.2.2"


# ---------------------------------------------------------------------------
# the rule journal — crash-safe, swept
# ---------------------------------------------------------------------------


def test_journal_append_read_clear_and_torn_tail(tmp_path):
    from jepsen_tpu.live import links

    root = str(tmp_path)
    assert links.journal_rules(root) == []
    r1 = {"kind": "link", "src": "127.0.1.1", "dst": "127.0.1.2",
          "mode": "drop", "engine": "iptables"}
    r2 = {"kind": "port", "port": 18100, "engine": "iptables"}
    links.journal_append(root, r1)
    links.journal_append(root, r2)
    assert links.journal_rules(root) == [r1, r2]
    # a torn final line (SIGKILL mid-append) is dropped, not crashed on
    with open(links.journal_path(root), "a") as f:
        f.write('{"kind": "link", "src": "127.0')
    assert links.journal_rules(root) == [r1, r2]
    links.journal_clear(root)
    assert links.journal_rules(root) == []


class FakeEngine:
    """Records installs/removals; never touches the host."""

    name = "iptables"

    def __init__(self, fail_remove=False):
        self.installed = []
        self.removed = []
        self.swept = 0
        self.fail_remove = fail_remove

    def supports(self, mode):
        return None

    def install(self, rule):
        self.installed.append(dict(rule))

    def remove(self, rule):
        self.removed.append(dict(rule))
        return not self.fail_remove

    def sweep_engine(self):
        self.swept += 1


def test_sweep_removes_journaled_rules_and_counts(tmp_path):
    from jepsen_tpu.live import links
    from jepsen_tpu.obs import metrics as obs_metrics

    root = str(tmp_path)
    eng = FakeEngine()
    rules = [{"kind": "link", "src": "127.0.1.1", "dst": "127.0.1.2",
              "mode": "drop", "engine": "iptables"},
             {"kind": "port", "port": 18100, "engine": "iptables"}]
    for r in rules:
        links.journal_append(root, r)
    before = obs_metrics.REGISTRY.get(
        "jtpu_link_rules_swept_total").total()
    assert links.sweep(root, engine=eng) == 2
    assert eng.removed == rules
    assert eng.swept == 1
    assert links.journal_rules(root) == []  # journal cleared
    assert links.sweep(root, engine=eng) == 0  # idempotent
    after = obs_metrics.REGISTRY.get(
        "jtpu_link_rules_swept_total").total()
    assert after - before == 2


def test_sweep_tree_finds_nested_journals(tmp_path):
    from jepsen_tpu.live import links

    eng_rules = {"kind": "link", "src": "127.0.1.1",
                 "dst": "127.0.1.3", "mode": "drop",
                 "engine": "iptables"}
    roots = [str(tmp_path / "cell-a"), str(tmp_path / "cell-b")]
    for r in roots:
        links.journal_append(r, eng_rules)
    # the removal itself shells out to a missing binary and fails —
    # the sweep still clears the journals (rules can't exist when the
    # engine doesn't)
    assert links.sweep_tree(str(tmp_path)) == 2
    for r in roots:
        assert links.journal_rules(r) == []


def test_link_partition_nemesis_start_journal_heal(tmp_path):
    from jepsen_tpu.history import Op
    from jepsen_tpu.live import links
    from jepsen_tpu.live.backend import FAMILIES

    backend = FAMILIES["replicated"]
    test = {"nodes": NODES, "data_root": str(tmp_path)}
    eng = FakeEngine()
    nem = links.LinkPartitionNemesis(backend, "bridge", engine=eng)
    op = Op(process="nemesis", type="info", f="start", value=None)
    out = nem.invoke(test, op)
    assert out.type == "info"
    assert out.value[0] == "links-drop"
    assert out.value[1] == "bridge"
    # bridge over [n1,n2,n3]: exactly n1<->n3, both directions, by addr
    assert sorted((r["src"], r["dst"]) for r in eng.installed) == \
        [("127.0.1.1", "127.0.1.3"), ("127.0.1.3", "127.0.1.1")]
    # every installed rule was journaled BEFORE install
    assert len(links.journal_rules(str(tmp_path))) == 2
    # second start is a no-op
    assert nem.invoke(test, op).value == "already-partitioned"
    # stop heals through the journal sweep
    out = nem.invoke(test, Op(process="nemesis", type="info",
                              f="stop", value=None))
    assert out.value == "links-healed"
    assert len(eng.removed) == 2
    assert links.journal_rules(str(tmp_path)) == []


def test_isolate_leader_grudge_targets_backend_leader(tmp_path):
    from jepsen_tpu.history import Op
    from jepsen_tpu.live import links

    class FakeBackend:
        name = "fake"
        peer_linked = True

        def leader(self, test):
            return "n3"

    eng = FakeEngine()
    nem = links.LinkPartitionNemesis(FakeBackend(), "isolate-leader",
                                     engine=eng)
    test = {"nodes": NODES, "data_root": str(tmp_path)}
    nem.invoke(test, Op(process="nemesis", type="info", f="start",
                        value=None))
    # one-way: peers drop traffic FROM the leader only
    assert sorted((r["src"], r["dst"]) for r in eng.installed) == \
        [("127.0.1.3", "127.0.1.1"), ("127.0.1.3", "127.0.1.2")]
    nem.teardown(test)
    assert links.journal_rules(str(tmp_path)) == []


def test_degrade_grudge_uses_degrade_mode(tmp_path):
    from jepsen_tpu.history import Op
    from jepsen_tpu.live import links
    from jepsen_tpu.live.backend import FAMILIES

    eng = FakeEngine()
    nem = links.LinkPartitionNemesis(FAMILIES["replicated"], "degrade",
                                     engine=eng)
    test = {"nodes": NODES, "data_root": str(tmp_path)}
    nem.invoke(test, Op(process="nemesis", type="info", f="start",
                        value=None))
    assert len(eng.installed) == 6  # every ordered peer pair
    assert all(r["mode"] == "degrade" for r in eng.installed)
    nem.teardown(test)


# ---------------------------------------------------------------------------
# real engine round trip — only where the host can stage links
# ---------------------------------------------------------------------------


def test_real_engine_install_block_sweep_heal():
    import socket
    import threading

    from jepsen_tpu.live import links

    reason = links.probe_links()
    if reason is not None:
        pytest.skip(f"no link rule engine here: {reason}")
    eng, _ = links.pick_engine()
    root = "/tmp/jepsen-links-test"
    links.journal_clear(root)
    rule = {"kind": "link", "src": "127.0.1.1", "dst": "127.0.1.2",
            "mode": "drop", "engine": eng.name}
    srv = socket.socket()
    srv.bind(("127.0.1.2", 0))
    srv.listen(2)
    port = srv.getsockname()[1]

    def accept_loop():
        while True:
            try:
                c, _a = srv.accept()
                c.close()
            except OSError:
                return

    threading.Thread(target=accept_loop, daemon=True).start()
    try:
        links.journal_append(root, rule)
        eng.install(rule)
        # the cut (src, dst) direction is dead...
        s = socket.socket()
        s.bind(("127.0.1.1", 0))
        s.settimeout(1.0)
        with pytest.raises(OSError):
            s.connect(("127.0.1.2", port))
        s.close()
        # ...while the client direction (default source) still works
        socket.create_connection(("127.0.1.2", port),
                                 timeout=1.0).close()
        # sweep restores connectivity and clears the journal
        assert links.sweep(root, engine=eng) == 1
        s2 = socket.socket()
        s2.bind(("127.0.1.1", 0))
        s2.settimeout(2.0)
        s2.connect(("127.0.1.2", port))
        s2.close()
        assert links.journal_rules(root) == []
    finally:
        links.sweep(root)
        srv.close()


# ---------------------------------------------------------------------------
# the full family × nemesis × grudge matrix — dry-run, spawns nothing
# ---------------------------------------------------------------------------


def test_dry_run_validates_family_nemesis_grudge_matrix():
    from jepsen_tpu.live import links
    from jepsen_tpu.live.backend import FAMILIES
    from jepsen_tpu.live.campaign import SEEDED
    from jepsen_tpu.live.matrix import standard_matrix

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "campaign.py"),
         "--dry-run", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    cells = json.loads(r.stdout)
    matrix = standard_matrix()
    base = [c for c in cells if not c["seeded"]]
    # the full cross product, exactly once per coordinate
    assert {(c["family"], c["nemesis"]) for c in base} == \
        {(f, n) for f in FAMILIES for n in matrix}
    assert len(base) == len(FAMILIES) * len(matrix)
    # one matrix row per grudge
    link_rows = [n for n in matrix if n.startswith("link-")]
    assert set(link_rows) == {f"link-{g}" for g in links.GRUDGES}
    assert len(link_rows) >= 5
    by_coord = {(c["family"], c["nemesis"]): c for c in base}
    engine_reason = links.probe_links()
    for fname, fam in FAMILIES.items():
        for n in link_rows:
            cell = by_coord[(fname, n)]
            if not fam.peer_linked:
                # families without inter-node links skip with a reason
                # naming the gap, not a crash and not a silent run
                assert cell["skip"] and "no inter-node links" \
                    in cell["skip"], cell
            elif engine_reason is not None:
                assert cell["skip"], cell
            elif n == "link-degrade":
                # mode-aware engine pick: degrade can run on tc even
                # where iptables (drop-only) would win the drop pick
                assert (cell["skip"] is None) == \
                    (links.probe_degrade() is None)
            else:
                assert cell["skip"] is None, cell
    # seeded link cells appear exactly where an engine exists
    seeded = {(c["family"], c["nemesis"]) for c in cells
              if c["seeded"]}
    for coord in (("replicated", "link-isolate-leader"),
                  ("replicated-queue", "link-bridge")):
        assert coord in SEEDED
        assert (coord in seeded) == (engine_reason is None)
    # kill-restart still needs nothing exotic, for every family
    assert all(by_coord[(f, "kill-restart")]["skip"] is None
               for f in FAMILIES)


def test_render_plan_covers_grudge_columns():
    from jepsen_tpu.live.campaign import plan, render_plan

    cells = plan()
    out = render_plan(cells)
    assert "link-bridge" in out
    assert "link-isolate-leader" in out
    assert "replicated-queue" in out and "pgwire" in out
