"""History substrate tests (reference tier-1: checker_test/util_test style)."""

import numpy as np

from jepsen_tpu.history import (
    INF_RET, NIL, Op, ValueEncoder, complete, encode_ops, index,
    invoke_op, max_concurrency, ok_op, fail_op, info_op, pair_index,
)
from jepsen_tpu.models import cas_register

FC = cas_register().f_codes


def h(*ops):
    return list(ops)


def test_index_assigns_sequential():
    hist = index(h(invoke_op(0, "read"), ok_op(0, "read", 1)))
    assert [op.index for op in hist] == [0, 1]


def test_pair_index_matches_invoke_completion():
    hist = h(
        invoke_op(0, "write", 1),   # 0
        invoke_op(1, "read"),       # 1
        ok_op(1, "read", None),     # 2
        ok_op(0, "write", 1),       # 3
    )
    pairs = pair_index(hist)
    assert pairs[0] == 3 and pairs[3] == 0
    assert pairs[1] == 2 and pairs[2] == 1


def test_complete_fills_read_values():
    hist = complete(h(invoke_op(0, "read"), ok_op(0, "read", 42)))
    assert hist[0].value == 42


def test_encode_drops_fail_keeps_info():
    hist = h(
        invoke_op(0, "write", 1),
        fail_op(0, "write", 1),     # definitely didn't happen -> dropped
        invoke_op(1, "write", 2),
        info_op(1, "write", 2),     # indeterminate -> kept, ret=inf
        invoke_op(2, "write", 3),   # crashed without completion -> kept
    )
    seq = encode_ops(hist, FC)
    assert len(seq) == 2
    assert list(seq.ret) == [INF_RET, INF_RET]
    assert list(seq.ok) == [False, False]
    assert seq.n_must == 0


def test_encode_cas_value_lanes():
    hist = h(invoke_op(0, "cas", (1, 2)), ok_op(0, "cas", (1, 2)))
    seq = encode_ops(hist, FC)
    assert seq.v1[0] == 1 and seq.v2[0] == 2


def test_encode_nil_read():
    hist = h(invoke_op(0, "read"), info_op(0, "read"))
    seq = encode_ops(hist, FC)
    assert seq.v1[0] == NIL


def test_encode_sorted_by_invocation():
    hist = h(
        invoke_op(0, "write", 1),
        invoke_op(1, "write", 2),
        ok_op(1, "write", 2),
        ok_op(0, "write", 1),
    )
    seq = encode_ops(hist, FC)
    assert list(seq.inv) == [0, 1]
    assert list(seq.ret) == [3, 2]
    # real-time: neither precedes the other (overlapping)
    assert seq.ret[0] > seq.inv[1] and seq.ret[1] > seq.inv[0]


def test_nemesis_ops_excluded():
    hist = h(
        Op("nemesis", "info", "start-partition", "all"),
        invoke_op(0, "read"),
        ok_op(0, "read", None),
        Op("nemesis", "info", "stop-partition", None),
    )
    seq = encode_ops(hist, FC)
    assert len(seq) == 1


def test_value_encoder_interns_non_ints():
    enc = ValueEncoder()
    a = enc.encode("foo")
    b = enc.encode("bar")
    assert a != b
    assert enc.encode("foo") == a
    assert enc.decode(a) == "foo"
    assert enc.encode(5) == 5
    assert enc.decode(NIL) is None


def test_max_concurrency():
    hist = h(
        invoke_op(0, "write", 1),   # 0 opens
        invoke_op(1, "write", 2),   # 1 opens -> 2 concurrent
        ok_op(0, "write", 1),
        ok_op(1, "write", 2),
        invoke_op(2, "write", 3),   # crashed: stays open forever
        invoke_op(0, "write", 4),
        ok_op(0, "write", 4),
    )
    seq = encode_ops(hist, FC)
    assert max_concurrency(seq) == 2


def test_chunked_history_writer_roundtrip(tmp_path, monkeypatch):
    """>16k ops take the chunked path; bytes must be identical to a
    1-op-per-chunk write and order exact (util.clj:156-178 parity)."""
    from jepsen_tpu import store
    from jepsen_tpu.history import invoke_op, ok_op

    ops = []
    for i in range(20_000):
        ops.append(invoke_op(i % 7, "write", i))
        ops.append(ok_op(i % 7, "write", i))
    test = {"name": "pwriter", "start_time": "t1",
            "store_root": str(tmp_path)}
    p = store.write_history(test, ops)
    chunked_bytes = open(p, "rb").read()
    assert chunked_bytes.count(b"\n") == len(ops)

    monkeypatch.setattr(store, "HISTORY_CHUNK", 1)
    test2 = {"name": "pwriter", "start_time": "t2",
             "store_root": str(tmp_path)}
    p2 = store.write_history(test2, ops)
    assert open(p2, "rb").read() == chunked_bytes

    back = store.read_history(p)
    assert len(back) == len(ops)
    assert back[0].f == "write" and back[-1].value == 19_999
