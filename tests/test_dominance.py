"""All-pairs dominance prune (`_allpairs_dominance`) — exactness
properties and whole-engine equivalence against the windowed sorted
prune (`_sort_dominance`).

The sorted prune trades exactness for sort-pipeline locality: its
window (R=8) + run-first reach may KEEP dominated rows.  The all-pairs
form is exact.  Both must agree on everything that matters:

  * soundness — every pruned row is covered by a kept dominator;
  * minimality (all-pairs only) — no kept row dominates another;
  * verdicts — the device search decides identically under either.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from jepsen_tpu.checker import linearizable as lin

DIMS = lin.SearchDims(n_det_pad=64, n_crash_pad=32, window=32, k=4,
                      state_width=1, frontier=32)


def random_cfgs(rng, m, dims, dup_bias=True):
    """Random config rows shaped like kernel rows: [p | win | crash |
    state].  With dup_bias, rows cluster on few (p, win, state) homes so
    dominance/dup relations actually occur."""
    p = rng.integers(0, 3 if dup_bias else 1000, (m, 1))
    win = rng.integers(0, 4 if dup_bias else 2**31, (m, dims.win_words))
    crash = rng.integers(0, 16, (m, dims.crash_words))
    state = rng.integers(0, 3 if dup_bias else 2**31,
                         (m, dims.state_width))
    return np.concatenate([p, win, crash, state], axis=1).astype(np.int32)


def dominates(a, b, dims):
    """Row a dominates row b: equal (p, win, state), crash(a) ⊆
    crash(b)."""
    lo = 1 + dims.win_words
    hi = lo + dims.crash_words
    pwa = np.concatenate([a[:lo], a[hi:]])
    pwb = np.concatenate([b[:lo], b[hi:]])
    if not np.array_equal(pwa, pwb):
        return False
    ca = a[lo:hi].astype(np.uint32)
    cb = b[lo:hi].astype(np.uint32)
    return bool(np.all((ca & ~cb) == 0))


@pytest.mark.parametrize("seed", range(6))
def test_allpairs_exactness_properties(seed):
    rng = np.random.default_rng(seed)
    m = 64
    cfgs = random_cfgs(rng, m, DIMS)
    valid = rng.random(m) < 0.8
    kept = np.asarray(lin._allpairs_dominance(
        jnp.asarray(cfgs), jnp.asarray(valid), DIMS))
    assert not np.any(kept & ~valid)
    kept_idx = np.flatnonzero(kept)
    # soundness: every valid row is dominated-or-equal by a kept row
    for i in np.flatnonzero(valid):
        assert any(dominates(cfgs[j], cfgs[i], DIMS) for j in kept_idx), i
    # minimality: no kept row is strictly dominated by (or duplicates)
    # another kept row
    for i in kept_idx:
        for j in kept_idx:
            if i == j:
                continue
            if np.array_equal(cfgs[i], cfgs[j]):
                pytest.fail(f"duplicate rows {i}, {j} both kept")
            if dominates(cfgs[j], cfgs[i], DIMS):
                pytest.fail(f"kept row {i} dominated by kept row {j}")


@pytest.mark.parametrize("seed", range(6))
def test_allpairs_keeps_subset_of_sort_distinct_values(seed):
    """The exact prune keeps a subset of the windowed prune's surviving
    VALUES (the sort prune may keep dominated extras, never fewer
    minimal ones)."""
    rng = np.random.default_rng(100 + seed)
    m = 64
    cfgs = random_cfgs(rng, m, DIMS)
    valid = np.ones(m, bool)
    kept_ap = np.asarray(lin._allpairs_dominance(
        jnp.asarray(cfgs), jnp.asarray(valid), DIMS))
    pwh, popc = lin._pw_parts(jnp.asarray(cfgs), DIMS)
    kept_s, scfgs, _perm = lin._sort_dominance(
        pwh, popc, jnp.asarray(valid), jnp.asarray(cfgs), m, DIMS)
    ap_vals = {tuple(r) for r in cfgs[kept_ap]}
    s_vals = {tuple(r) for r in np.asarray(scfgs)[np.asarray(kept_s)]}
    assert ap_vals <= s_vals


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("k_out,n", [(8, 64), (64, 256), (256, 100)])
def test_matrix_compact_matches_search_compact(seed, k_out, n,
                                               monkeypatch):
    """Both compaction forms return identical in-range rows and the
    same count (rows past the count are arbitrary in-bounds indices)."""
    rng = np.random.default_rng(seed)
    mask = jnp.asarray(rng.random(n) < 0.3)
    monkeypatch.setattr(lin, "_COMPACT_MODE", "search")
    idx_s, cnt_s = lin._compact_indices(mask, k_out)
    monkeypatch.setattr(lin, "_COMPACT_MODE", "matrix")
    idx_m, cnt_m = lin._compact_indices(mask, k_out)
    assert int(cnt_s) == int(cnt_m)
    c = min(int(cnt_s), k_out)
    np.testing.assert_array_equal(np.asarray(idx_s)[:c],
                                  np.asarray(idx_m)[:c])
    # every returned index in-bounds either way (callers gather first,
    # mask later)
    assert np.all((np.asarray(idx_m) >= 0) & (np.asarray(idx_m) < n))


def _fuzz_history(seed, n_ops=40, n_procs=4, crash_p=0.15):
    import random

    from jepsen_tpu.synth import corrupt_read, register_history

    rng = random.Random(seed)
    h = register_history(rng, n_ops=n_ops, n_procs=n_procs,
                         overlap=4, crash_p=crash_p)
    if seed % 2:  # alternate valid-by-construction / corrupted-invalid
        h = corrupt_read(rng, h, at=0.7)
    return h


@pytest.mark.parametrize("seed", range(8))
def test_engine_verdicts_match_across_prunes(seed, monkeypatch):
    """search_opseq decides identically with either prune (the all-pairs
    path forced on CPU, where auto would pick sort)."""
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register

    model = cas_register()
    h = _fuzz_history(2000 + seed)
    seq = encode_ops(h, model.f_codes)
    out_sort = lin.search_opseq(seq, model, budget=2_000_000)
    monkeypatch.setattr(lin, "_DOMINANCE_MODE", "allpairs")
    out_ap = lin.search_opseq(seq, model, budget=2_000_000)
    assert out_sort["valid"] == out_ap["valid"], (
        f"seed {seed}: sort={out_sort} allpairs={out_ap}")
    # the exact prune can only explore the same or fewer configs
    if (str(out_sort.get("engine", "")).startswith("device-bfs")
            and str(out_ap.get("engine", "")).startswith("device-bfs")):
        assert out_ap["configs"] <= out_sort["configs"]
