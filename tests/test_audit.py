"""Proof-carrying verdicts (ISSUE 4): certificates, audit, shrink.

The contract under test: EVERY engine route's decided verdict carries a
certificate — ``linearization`` (or an explicit ``witness_dropped``
reason) on valid, ``final_ops`` (or ``frontier_dropped``) on invalid —
and the independent audit pass (analyze/audit.py) replays it with zero
W-codes.  The differential fuzz here spans the direct WGL oracle, the
`linear` host sweep, the device solo/batch/bucketed engines, and the
decomposed funnel (value blocks, quiescence chains, the P-compositional
cell stitch), :info ops included.  Tampered certificates must trip the
matching W-code — an audit that can't fail proves nothing.

The shrinker satellites ride along: seeded-invalid histories reduce
below 10 ops, the brute-force permutation checker independently
confirms the core, and shrinking a minimum is a no-op.  So do the live
generator-stream lint (H001/H002 at emit time) and the linear witness
fixes (explicit drop reasons; chains surviving checkpoints).
"""

from __future__ import annotations

import json
import random

import pytest

from jepsen_tpu.analyze.audit import AuditError, audit, maybe_audit
from jepsen_tpu.analyze.shrink import (brute_force_check, shrink_invalid,
                                       shrink_summary)
from jepsen_tpu.history import encode_ops, info_op, invoke_op, ok_op
from jepsen_tpu.models import cas_register, multi_register, mutex, register
from jepsen_tpu.synth import (corrupt_read, flip_read, register_history,
                              sim_mutex_history, sim_register_history)


def _assert_certified(seq, model, r, where=""):
    """The certificate contract + a clean audit, for one result."""
    v = r.get("valid")
    if v is True:
        assert "linearization" in r or "witness_dropped" in r, (where, r)
    elif v is False:
        assert "final_ops" in r or "frontier_dropped" in r, (where, r)
    a = audit(seq, model, r)
    assert a["ok"], (where, a["diagnostics"], r)
    return a


# ---------------------------------------------------------------------------
# differential fuzz: every route, >= 200 histories, zero W-codes
# ---------------------------------------------------------------------------


def _histories():
    """(label, model, seq) mix: cas-register with :info ops and
    corruptions, unique-writes registers (value blocks), low-overlap
    (quiescence), mutex crashes, multi-register (cell stitch)."""
    cases = []
    for i in range(60):
        rng = random.Random(i)
        m = cas_register()
        h = sim_register_history(rng, n_procs=4, n_ops=22, crash_p=0.1,
                                 cas=(i % 2 == 0))
        if i % 3 == 0:
            h = flip_read(rng, h)
        cases.append(("cas", m, encode_ops(h, m.f_codes)))
    for i in range(40):
        rng = random.Random(1000 + i)
        m = register(0)
        h = register_history(rng, n_ops=30, n_procs=5, overlap=4,
                             crash_p=0.0, n_values=10**6, cas=False)
        if i % 2 == 0:
            h = flip_read(rng, h)
        cases.append(("uniq", m, encode_ops(h, m.f_codes)))
    for i in range(30):
        rng = random.Random(2000 + i)
        m = cas_register()
        h = register_history(rng, n_ops=36, n_procs=3, overlap=1,
                             crash_p=0.02, max_crashes=2, n_values=4)
        if i % 2 == 0:
            h = flip_read(rng, h)
        cases.append(("quiesce", m, encode_ops(h, m.f_codes)))
    for i in range(30):
        rng = random.Random(3000 + i)
        m = mutex()
        h = sim_mutex_history(rng, n_ops=22, n_procs=4, crash_p=0.06)
        cases.append(("mutex", m, encode_ops(h, m.f_codes)))
    for i in range(45):
        rng = random.Random(4000 + i)
        m = multi_register(3)
        h = _sim_multireg(rng)
        if i % 3 == 0:
            h = _flip_mr_read(rng, h)
        cases.append(("multireg", m, encode_ops(h, m.f_codes)))
    assert len(cases) >= 200
    return cases


def _sim_multireg(rng, width=3, n_procs=4, n_ops=26, crash_p=0.05):
    state = {k: 0 for k in range(width)}
    h, pending, crashed = [], {}, set()
    done = 0
    while done < n_ops or pending:
        live = [p for p in range(n_procs) if p not in crashed]
        if not live:
            break
        p = rng.choice(live)
        if p in pending:
            f, k, v = pending.pop(p)
            if crash_p and rng.random() < crash_p:
                if rng.random() < 0.5 and f == "write":
                    state[k] = v
                crashed.add(p)
                h.append(info_op(p, f, (k, v if f == "write" else None)))
                continue
            if f == "read":
                h.append(ok_op(p, f, (k, state[k])))
            else:
                state[k] = v
                h.append(ok_op(p, f, (k, v)))
        elif done < n_ops:
            f = rng.choice(["read", "write"])
            k = rng.randrange(width)
            v = None if f == "read" else rng.randrange(5)
            h.append(invoke_op(p, f, (k, v)))
            pending[p] = (f, k, v)
            done += 1
    return h


def _flip_mr_read(rng, h):
    from dataclasses import replace

    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "read"]
    if not idx:
        return h
    h = list(h)
    i = rng.choice(idx)
    k, v = h[i].value
    h[i] = replace(h[i], value=(k, (v or 0) + 7))
    return h


def test_fuzz_host_routes_carry_auditable_certificates():
    """WGL oracle, linear sweep (witnessed), and the decomposed funnel
    (witness=True) all emit certificates that replay clean, and the
    witnesses are REAL on every route (non-vacuous coverage)."""
    from jepsen_tpu.checker.linear import check_opseq_linear
    from jepsen_tpu.checker.seq import check_opseq
    from jepsen_tpu.decompose.engine import check_opseq_decomposed

    witnessed = {"wgl": 0, "linear": 0, "decomposed": 0}
    stitched = 0
    for label, m, seq in _histories():
        where = (label, len(seq))
        a = check_opseq(seq, m)
        _assert_certified(seq, m, a, where)
        if a["valid"] is True:
            witnessed["wgl"] += 1
        b = check_opseq_linear(seq, m, witness_cap=500_000)
        assert b["valid"] == a["valid"], where
        _assert_certified(seq, m, b, where)
        if b.get("linearization") is not None:
            witnessed["linear"] += 1
        d = check_opseq_decomposed(
            seq, m, witness=True,
            direct=lambda s, m=m: check_opseq(s, m, lint=False))
        assert d["valid"] == a["valid"], (where, d)
        _assert_certified(seq, m, d, where)
        if d.get("linearization") is not None:
            witnessed["decomposed"] += 1
        if d["decompose"].get("stitched"):
            stitched += 1
    # every route must produce real witnesses, and the multi-cell
    # stitch must actually run, or the parity claim is vacuous
    assert all(n > 20 for n in witnessed.values()), witnessed
    assert stitched > 10, stitched


def test_fuzz_device_batch_routes_carry_certificates():
    """Bucketed and fused device batches: every per-key verdict is
    certified (greedy keys with real witnesses — surviving bucket
    reordering — device keys with explicit drop reasons)."""
    from jepsen_tpu.checker import linearizable as lin

    m = cas_register()
    seqs = []
    for k in range(10):
        rng = random.Random(k % 5)
        h = sim_register_history(rng, n_procs=3, n_ops=16 + 8 * (k % 3),
                                 crash_p=0.08)
        if k % 3 == 0:
            h = flip_read(random.Random(k), h)
        seqs.append(encode_ops(h, m.f_codes))
    for bucket in (False, True):
        out = lin.search_batch(seqs, m, budget=150_000, bucket=bucket,
                               audit=True)
        greedy_wit = 0
        for s, r in zip(seqs, out):
            _assert_certified(s, m, r, f"bucket={bucket}")
            if r.get("engine") == "greedy-witness":
                assert r.get("linearization"), r
                greedy_wit += 1
        assert greedy_wit > 0


def test_search_opseq_device_verdicts_state_their_drops():
    from jepsen_tpu.checker import linearizable as lin

    m = cas_register()
    rng = random.Random(999)
    h = flip_read(rng, register_history(rng, n_ops=60, n_procs=6,
                                        overlap=6, n_values=5))
    seq = encode_ops(h, m.f_codes)
    r = lin.search_opseq(seq, m, budget=150_000, audit=True)
    if r["valid"] is False and "device" in r.get("engine", ""):
        assert r["frontier_dropped"]
    _assert_certified(seq, m, r)


# ---------------------------------------------------------------------------
# tampered certificates must trip the matching W-code
# ---------------------------------------------------------------------------


def _valid_case():
    m = cas_register()
    rng = random.Random(11)
    h = sim_register_history(rng, n_procs=4, n_ops=24, crash_p=0.0)
    seq = encode_ops(h, m.f_codes)
    from jepsen_tpu.checker.seq import check_opseq

    r = check_opseq(seq, m)
    assert r["valid"] is True and r["linearization"]
    return m, seq, r


def test_audit_flags_tampered_certificates():
    m, seq, r = _valid_case()

    def codes(**mut):
        bad = {**r, **mut}
        return audit(seq, m, bad)["codes"]

    lin = r["linearization"]
    # W001: out-of-range row
    assert "W001" in codes(linearization=lin[:-1] + [len(seq) + 5])
    # W002: duplicated row / missing ok row
    assert "W002" in codes(linearization=lin + [lin[0]])
    assert "W002" in codes(linearization=lin[:-1])
    # W003: real-time order broken (move the first op last; with ops
    # invoked over time the first returns before some later invoke)
    assert "W003" in codes(linearization=lin[1:] + [lin[0]])
    # W004: model-illegal order (reverse usually breaks replay; if not,
    # swapping two ops of different values will)
    rev = audit(seq, m, {**r, "linearization": list(reversed(lin))})
    assert not rev["ok"]
    # contract: a decided verdict with NO certificate and NO reason
    bare = dict(r)
    del bare["linearization"]
    assert "W002" in audit(seq, m, bare)["codes"]
    assert audit(seq, m, {"valid": False})["codes"] == ["W002"]
    # explicit drop reasons are accepted
    assert audit(seq, m, {"valid": True,
                          "witness_dropped": "x"})["ok"]
    assert audit(seq, m, {"valid": False,
                          "frontier_dropped": "x"})["ok"]


def test_audit_w005_flags_cross_cell_stitch_violations():
    """A stitched multi-register witness that breaks cross-cell
    precedence reads as W005 (same-cell breaks stay W003)."""
    m = multi_register(2)
    # key 0: write then (after it returns) key 1: write — real-time
    # orders them across cells
    h = [invoke_op(0, "write", (0, 5)), ok_op(0, "write", (0, 5)),
         invoke_op(1, "write", (1, 6)), ok_op(1, "write", (1, 6))]
    seq = encode_ops(h, m.f_codes)
    good = {"valid": True, "linearization": [0, 1],
            "decompose": {"stitched": True}}
    assert audit(seq, m, good)["ok"]
    bad = {"valid": True, "linearization": [1, 0],
           "decompose": {"stitched": True}}
    assert "W005" in audit(seq, m, bad)["codes"]
    # without the stitch marker the same defect is plain W003
    assert "W003" in audit(seq, m, {"valid": True,
                                    "linearization": [1, 0]})["codes"]


def test_maybe_audit_raises_loudly_and_attaches_summary():
    m, seq, r = _valid_case()
    out = maybe_audit(seq, m, dict(r), True)
    assert out["audit"]["ok"] and out["audit"]["checked"] == \
        "linearization"
    with pytest.raises(AuditError):
        maybe_audit(seq, m, {**r, "linearization":
                             r["linearization"][:-1]}, True)
    # off by default: no audit key, no raise
    out2 = maybe_audit(seq, m, {**r, "linearization": []}, None)
    assert "audit" not in out2


def test_audit_env_knob(monkeypatch):
    from jepsen_tpu.analyze.audit import audit_enabled

    monkeypatch.delenv("JEPSEN_TPU_AUDIT", raising=False)
    assert audit_enabled() is False
    monkeypatch.setenv("JEPSEN_TPU_AUDIT", "1")
    assert audit_enabled() is True
    m, seq, r = _valid_case()
    from jepsen_tpu.checker.seq import check_opseq

    out = check_opseq(seq, m)  # audit=None follows the env knob
    assert out["audit"]["ok"]


def test_cli_audit_flag_sets_env_knob(monkeypatch):
    import argparse

    from jepsen_tpu import cli

    monkeypatch.setenv("JEPSEN_TPU_AUDIT", "placeholder")
    monkeypatch.delenv("JEPSEN_TPU_AUDIT")
    p = argparse.ArgumentParser()
    cli.add_test_opts(p)
    opts = cli.test_opt_fn(p.parse_args(["--audit", "--dummy"]))
    assert opts["audit"] is True
    import os

    assert os.environ.get("JEPSEN_TPU_AUDIT") == "1"


# ---------------------------------------------------------------------------
# counterexample minimization
# ---------------------------------------------------------------------------


def test_shrinker_reduces_seeded_invalid_below_10_ops():
    """The bench-config shape (register_history + corrupt_read): the
    shrinker must reduce the counterexample below 10 ops and the
    brute-force permutation checker must independently confirm it."""
    m = cas_register()
    rng = random.Random(7)
    h = register_history(rng, n_ops=120, n_procs=6, overlap=4,
                         n_values=8)
    h = corrupt_read(rng, h, at=0.5)
    seq = encode_ops(h, m.f_codes)
    out = shrink_invalid(seq, m)
    assert out["n_to"] < 10, out
    assert out["minimal"] is True
    assert out["brute_force"] is False  # independently confirmed invalid
    summ = shrink_summary(seq, out)
    assert len(summ["ops"]) == out["n_to"]


def test_shrinking_is_idempotent():
    from jepsen_tpu.decompose.partition import subseq

    m = cas_register()
    for i in range(5):
        rng = random.Random(40 + i)
        h = flip_read(rng, sim_register_history(rng, n_procs=4,
                                                n_ops=40, crash_p=0.05))
        seq = encode_ops(h, m.f_codes)
        out = shrink_invalid(seq, m)
        if not out["minimal"]:
            continue
        core = subseq(seq, out["rows"])
        again = shrink_invalid(core, m)
        assert again["rows"] == list(range(len(core))), (i, again)
        assert again["n_to"] == out["n_to"]


def test_brute_force_agrees_with_oracle_on_small_histories():
    from jepsen_tpu.checker.seq import check_opseq

    m = cas_register()
    for i in range(40):
        rng = random.Random(70 + i)
        h = sim_register_history(rng, n_procs=3, n_ops=7, crash_p=0.1)
        if i % 2 == 0:
            h = flip_read(rng, h)
        seq = encode_ops(h, m.f_codes)
        bf = brute_force_check(seq, m)
        assert bf == check_opseq(seq, m)["valid"], i
    # size gate: None past max_ops
    rng = random.Random(1)
    big = encode_ops(sim_register_history(rng, n_procs=3, n_ops=30),
                     m.f_codes)
    assert brute_force_check(big, m, max_ops=16) is None


def test_shrink_is_wired_into_failure_reports():
    from jepsen_tpu.checker.linearizable import Linearizable

    m = cas_register()
    rng = random.Random(9)
    h = flip_read(rng, sim_register_history(rng, n_procs=4, n_ops=40,
                                            crash_p=0.05))
    res = Linearizable(m, algorithm="linear").check(
        {"name": "shrinktest", "start_time": "t0"}, h)
    assert res["valid"] is False
    sh = res.get("shrink")
    assert sh and sh["n_to"] < 10 and sh["brute_force"] is False
    assert sh["ops"]  # the rendered story
    with open(res["report_file"]) as f:
        page = f.read()
    assert "Minimal failing subhistory" in page


# ---------------------------------------------------------------------------
# linear.py witness satellites: explicit drops + snapshot survival
# ---------------------------------------------------------------------------


def test_linear_witness_dropped_reasons():
    from jepsen_tpu.checker.linear import check_opseq_linear

    m = cas_register()
    rng = random.Random(3)
    h = sim_register_history(rng, n_procs=4, n_ops=24, crash_p=0.05)
    seq = encode_ops(h, m.f_codes)
    # cap 0: tracking disabled, and it says so
    r0 = check_opseq_linear(seq, m)
    assert r0["valid"] is True and "linearization" not in r0
    assert "witness_cap=0" in r0["witness_dropped"]
    # tiny cap: table blows the cap, verdict unaffected, reason explicit
    r1 = check_opseq_linear(seq, m, witness_cap=4)
    assert r1["valid"] is True and "linearization" not in r1
    assert "exceeded witness_cap=4" in r1["witness_dropped"]
    # ample cap: a real, auditable witness
    r2 = check_opseq_linear(seq, m, witness_cap=500_000)
    assert audit(seq, m, r2)["ok"] and r2["linearization"]


def test_linear_witness_survives_checkpoint_resume(tmp_path):
    from jepsen_tpu.checker.linear import check_opseq_linear

    m = cas_register()
    rng = random.Random(5)
    h = sim_register_history(rng, n_procs=4, n_ops=30, crash_p=0.05)
    seq = encode_ops(h, m.f_codes)
    ck = str(tmp_path / "linear.ck")
    base = check_opseq_linear(seq, m, witness_cap=500_000,
                              checkpoint_path=ck, checkpoint_every=3)
    assert base["valid"] is True and base["linearization"]
    # resume WITH a witness cap: the serialized pre-snapshot chains
    # seed the walk, so the resumed verdict still carries a full,
    # auditable witness
    r = check_opseq_linear(seq, m, witness_cap=500_000, resume_from=ck)
    assert r["valid"] is True
    assert r["linearization"], r.get("witness_dropped")
    assert audit(seq, m, r)["ok"]
    # resume without a cap: explicit drop, not silence
    r2 = check_opseq_linear(seq, m, resume_from=ck)
    assert r2["valid"] is True and "witness_cap=0" in \
        r2["witness_dropped"]


def test_linear_witnessless_checkpoint_resume_says_so(tmp_path):
    from jepsen_tpu.checker.linear import check_opseq_linear

    m = cas_register()
    rng = random.Random(6)
    h = sim_register_history(rng, n_procs=4, n_ops=30, crash_p=0.05)
    seq = encode_ops(h, m.f_codes)
    ck = str(tmp_path / "nolin.ck")
    check_opseq_linear(seq, m, checkpoint_path=ck, checkpoint_every=3)
    r = check_opseq_linear(seq, m, witness_cap=500_000, resume_from=ck)
    assert r["valid"] is True and "witnessless checkpoint" in \
        r["witness_dropped"]


# ---------------------------------------------------------------------------
# live generator-stream lint (H001/H002 at emit time)
# ---------------------------------------------------------------------------


class _DoubleInvoker:
    """Deliberately broken: never waits for completions."""

    def op(self, test, process):
        return {"type": "invoke", "f": "read", "value": None}


class _OrphanCompleter:
    def op(self, test, process):
        return {"type": "ok", "f": "read", "value": 1}


def test_stream_lint_raises_at_emission():
    from jepsen_tpu.analyze.lint import HistoryLintError
    from jepsen_tpu.generator import StreamLinter, op_and_validate

    test = {"__stream_lint__": StreamLinter(), "concurrency": 2}
    g = _DoubleInvoker()
    op_and_validate(g, test, 0)  # first invoke is fine
    op_and_validate(g, test, 1)  # other processes unaffected
    with pytest.raises(HistoryLintError) as ei:
        op_and_validate(g, test, 0)
    d = ei.value.diagnostics[0]
    assert d.code == "H001" and "_DoubleInvoker" in d.message
    with pytest.raises(HistoryLintError) as ei:
        op_and_validate(_OrphanCompleter(),
                        {"__stream_lint__": StreamLinter()}, 3)
    assert ei.value.diagnostics[0].code == "H002"


def test_stream_lint_tolerates_wellformed_flow_and_nemesis():
    from jepsen_tpu.generator import StreamLinter, op_and_validate

    sl = StreamLinter()
    test = {"__stream_lint__": sl}
    g = _DoubleInvoker()
    for p in (0, 1):
        op_and_validate(g, test, p)
        sl.on_complete(p)  # the worker closes the op
        op_and_validate(g, test, p)  # next invoke is legal again
        sl.on_complete(p)
    # nemesis emissions are exempt (they journal :info freely)
    for _ in range(3):
        op_and_validate({"type": "info", "f": "start"}, test, "nemesis")


def test_stream_lint_installed_behind_lint_knob(monkeypatch):
    from jepsen_tpu import core

    monkeypatch.delenv("JEPSEN_TPU_LINT", raising=False)
    t = core.prepare_test({"name": "x", "nodes": []})
    assert "__stream_lint__" in t
    monkeypatch.setenv("JEPSEN_TPU_LINT", "0")
    t2 = core.prepare_test({"name": "x", "nodes": []})
    assert "__stream_lint__" not in t2


# ---------------------------------------------------------------------------
# standalone tooling: python -m jepsen_tpu.analyze --audit, fuzz --audit
# ---------------------------------------------------------------------------


def test_analyze_main_audit_mode(tmp_path):
    from jepsen_tpu import store
    from jepsen_tpu.analyze.__main__ import main
    from jepsen_tpu.checker.seq import check_opseq

    m = cas_register()
    rng = random.Random(13)
    h = sim_register_history(rng, n_procs=3, n_ops=16)
    hist = [op for op in h]
    hp = str(tmp_path / "history.jsonl")
    with open(hp, "w") as f:
        for op in hist:
            f.write(json.dumps(op.to_dict()) + "\n")
    seq = encode_ops(store.read_history(hp), m.f_codes)
    r = check_opseq(seq, m)
    rp = str(tmp_path / "result.json")
    with open(rp, "w") as f:
        json.dump({"valid": r["valid"],
                   "linearization": r["linearization"]}, f)
    assert main([hp, "--model", "cas-register", "--audit", rp]) == 0
    # a tampered stored certificate fails loudly (exit 1)
    with open(rp, "w") as f:
        json.dump({"valid": r["valid"],
                   "linearization": r["linearization"][:-1]}, f)
    assert main([hp, "--model", "cas-register", "--audit", rp]) == 1
    # --audit without --model is a usage error
    assert main([hp, "--audit", rp]) == 254


def test_fuzz_audit_mode_bounded_seeds(monkeypatch, capsys):
    """tools/fuzz.py --audit: a bounded-seed pass stays clean — the
    tier-1 gate for the certificate fuzz mode."""
    import importlib.util
    import os
    import sys

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "fuzz.py")
    spec = importlib.util.spec_from_file_location("_fuzz_audit", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(sys, "argv",
                        ["fuzz.py", "--rounds", "4", "--n-ops", "20",
                         "--audit"])
    assert mod.main() == 0


# ---------------------------------------------------------------------------
# web result page
# ---------------------------------------------------------------------------


def test_web_result_page_renders_plan_audit_and_shrink(tmp_path):
    from jepsen_tpu import web
    from jepsen_tpu.analyze.plan import explain

    m = cas_register()
    rng = random.Random(2)
    h = sim_register_history(rng, n_procs=3, n_ops=16)
    seq = encode_ops(h, m.f_codes)
    run = tmp_path / "t" / "20260803T000000"
    run.mkdir(parents=True)
    result = {
        "valid": False, "engine": "host-oracle", "configs": 123,
        "final_ops": [1, 2],
        "explain": explain(seq, m),
        "audit": {"ok": True, "checked": "final_ops", "codes": []},
        "shrink": {"n_from": 16, "n_to": 3, "rows": [0, 1, 2],
                   "checks": 9, "minimal": True, "brute_force": False,
                   "ops": [{"process": 0, "f": "write", "value": 1}]},
    }
    with open(run / "results.json", "w") as f:
        json.dump(result, f)
    page = web.dir_html(str(tmp_path), "t/20260803T000000")
    assert "Search plan" in page and "SearchDims" in page
    assert "audit" in page and "final_ops" in page
    assert "Minimal failing subhistory" in page
    assert "blocking frontier" in page
    # a run dir without results.json renders the plain browser
    (tmp_path / "t2").mkdir()
    assert "Search plan" not in web.dir_html(str(tmp_path), "t2")
