"""faultfs tests: C++ syntax check against the mock fuse3 header, a live
control-plane round trip (control server + ctl client compiled for real,
no FUSE needed), and driver command shapes via the dummy remote."""

import os
import shutil
import subprocess
import time

import pytest

from jepsen_tpu import faultfs
from jepsen_tpu.control import DummyRemote, Session

NATIVE = faultfs.NATIVE_DIR

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def test_faultfs_syntax_against_mock_fuse():
    subprocess.run(
        ["g++", "-std=c++17", "-DFAULTFS_SYNTAX_TEST", "-fsyntax-only",
         "-Wall", "-Werror", "-I.", "faultfs.cc"],
        cwd=NATIVE, check=True)


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    d = tmp_path_factory.mktemp("faultfs-build")
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-DFAULTFS_SYNTAX_TEST", "-I", NATIVE,
         "-o", str(d / "faultfs"), os.path.join(NATIVE, "faultfs.cc"),
         "-lpthread"],
        check=True)
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-o", str(d / "faultfsctl"),
         os.path.join(NATIVE, "faultfsctl.cc")],
        check=True)
    return d


def test_control_plane_round_trip(built, tmp_path):
    """Start the control server (no FUSE), drive it with faultfsctl."""
    real = tmp_path / "real"
    real.mkdir()
    env = dict(os.environ, FAULTFS_CONTROL_ONLY="1")
    proc = subprocess.Popen([str(built / "faultfs"), str(real), "/dev/null"],
                            env=env)
    sock = str(real / ".faultfs.sock")
    try:
        for _ in range(100):
            if os.path.exists(sock):
                break
            time.sleep(0.05)
        assert os.path.exists(sock), "control socket never appeared"

        def ctl(*args):
            out = subprocess.run([str(built / "faultfsctl"), sock, *args],
                                 capture_output=True, text=True, timeout=10)
            assert out.returncode == 0, out.stderr
            return out.stdout

        assert "active=0" in ctl("status")
        assert "ok set" in ctl("set", "errno=EIO", "p=1.0")
        st = ctl("status")
        assert "active=1" in st and "errno=5" in st and "p=1" in st
        assert "ok set" in ctl("set", "errno=ENOSPC", "p=0.01",
                               "methods=write,fsync")
        st = ctl("status")
        assert "errno=28" in st and "p=0.01" in st
        assert "ok cleared" in ctl("clear")
        assert "active=0" in ctl("status")
        assert "err unknown" in ctl("frobnicate")
    finally:
        proc.kill()
        proc.wait()


def test_driver_command_shapes():
    r = DummyRemote({"stat /": (1, "", "no"),
                     "dpkg": (0, "", ""),
                     "apt-get": (0, "", "")})
    nodes = ["n1"]
    test = {"nodes": nodes,
            "sessions": {n: Session(node=n, remote=r) for n in nodes}}
    sess = Session(node="n1", remote=r)
    faultfs.break_all(sess)
    faultfs.break_one_percent(sess)
    faultfs.clear(sess)
    faultfs.break_methods(sess, ["write", "fsync"], err="ENOSPC", p=0.5)
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    ctl = faultfs.CTL
    assert any(f"{ctl} {faultfs.SOCK} set errno=EIO p=1.0" in c
               for c in cmds)
    assert any("p=0.01" in c for c in cmds)
    assert any(" clear" in c for c in cmds)
    assert any("methods=write,fsync" in c and "errno=ENOSPC" in c
               for c in cmds)

    # nemesis surface
    from jepsen_tpu.history import info_op

    r.log.clear()
    nem = faultfs.nemesis()
    out = nem.invoke(test, info_op("nemesis", "break-all", None))
    assert out.type == "info"
    assert any("set errno=EIO p=1.0" in e[2] for e in r.log)
    with pytest.raises(ValueError):
        nem.invoke(test, info_op("nemesis", "what", None))


def test_install_commands():
    r = DummyRemote({"stat /": (1, "", "no"), "dpkg": (0, "", "")})
    sess = Session(node="n1", remote=r)
    faultfs.install(sess)
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    ups = [e for e in r.log if e[1] == "upload"]
    assert any("libfuse3-dev" in c for c in cmds)
    assert {os.path.basename(u[2][0]) for u in ups} == set(faultfs.SOURCES)
    assert any("cmake -B build" in c for c in cmds)
    # neither binary "exists" on the dummy node -> raw-frontend mount
    # via start-stop-daemon, then a /proc/mounts wait
    assert any("start-stop-daemon" in c and faultfs.RAW_BIN in c
               and "/real /faulty" in c for c in cmds)
    assert any("/proc/mounts" in c for c in cmds)


# ---------------------------------------------------------------------------
# Tier-3: the raw /dev/fuse frontend against a REAL kernel mount.
# The charybdefs validation recipe (charybdefs/test/jepsen/charybdefs/
# remote_test.clj:7-21): mount, break, observe EIO through the kernel,
# clear, observe recovery.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def raw_built(tmp_path_factory):
    d = tmp_path_factory.mktemp("faultfs-raw-build")
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-I", NATIVE, "-o",
         str(d / "faultfs_raw"), os.path.join(NATIVE, "faultfs_raw.cc"),
         "-lpthread"],
        check=True)
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-o", str(d / "faultfsctl"),
         os.path.join(NATIVE, "faultfsctl.cc")],
        check=True)
    return d


@pytest.mark.skipif(not os.path.exists("/dev/fuse"),
                    reason="no /dev/fuse in this image")
@pytest.mark.skipif(os.geteuid() != 0,
                    reason="raw frontend mounts /dev/fuse itself (root)")
def test_raw_mount_kernel_errno_injection(raw_built, tmp_path):
    real = tmp_path / "real"
    mnt = tmp_path / "mnt"
    real.mkdir()
    mnt.mkdir()
    proc = subprocess.Popen(
        [str(raw_built / "faultfs_raw"), str(real), str(mnt)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        # wait for the kernel mount to appear (the daemon prints MOUNTED
        # after mount(2) succeeds)
        mounted = False
        for _ in range(100):
            if proc.poll() is not None:
                pytest.skip("mount failed (sandboxed?): "
                            + (proc.stderr.read() or ""))
            with open("/proc/mounts") as f:
                if any(str(mnt) in line and "faultfs" in line
                       for line in f):
                    mounted = True
                    break
            time.sleep(0.05)
        assert mounted, "faultfs_raw never mounted"
        sock = str(real / ".faultfs.sock")
        for _ in range(100):
            if os.path.exists(sock):
                break
            time.sleep(0.05)

        def ctl(*args):
            out = subprocess.run(
                [str(raw_built / "faultfsctl"), sock, *args],
                capture_output=True, text=True, timeout=10)
            assert out.returncode == 0, out.stderr
            return out.stdout

        # passthrough: data written via the mount lands in the real dir
        f = mnt / "data.txt"
        f.write_text("payload-1\n")
        assert f.read_text() == "payload-1\n"
        assert (real / "data.txt").read_text() == "payload-1\n"
        assert "data.txt" in os.listdir(mnt)

        # break-all: every op fails with EIO *through the kernel*
        assert "ok set" in ctl("set", "errno=EIO", "p=1.0")
        with pytest.raises(OSError) as ei:
            f.read_text()
        assert ei.value.errno == 5  # EIO

        # clear: reads work again
        assert "ok cleared" in ctl("clear")
        assert f.read_text() == "payload-1\n"

        # targeted: only writes fail, with ENOSPC
        assert "ok set" in ctl("set", "errno=ENOSPC", "p=1.0",
                               "methods=write")
        assert f.read_text() == "payload-1\n"
        fd = os.open(f, os.O_WRONLY | os.O_APPEND)
        try:
            with pytest.raises(OSError) as ei:
                os.write(fd, b"more\n")
            assert ei.value.errno == 28  # ENOSPC
        finally:
            os.close(fd)
        assert "ok cleared" in ctl("clear")
        with open(f, "a") as fh:
            fh.write("recovered\n")
        assert f.read_text() == "payload-1\nrecovered\n"
    finally:
        proc.terminate()  # SIGTERM handler unmounts + exits
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
        subprocess.run(["umount", "-l", str(mnt)],
                       capture_output=True)  # belt and braces
