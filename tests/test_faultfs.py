"""faultfs tests: C++ syntax check against the mock fuse3 header, a live
control-plane round trip (control server + ctl client compiled for real,
no FUSE needed), and driver command shapes via the dummy remote."""

import os
import shutil
import subprocess
import time

import pytest

from jepsen_tpu import faultfs
from jepsen_tpu.control import DummyRemote, Session

NATIVE = faultfs.NATIVE_DIR

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def test_faultfs_syntax_against_mock_fuse():
    subprocess.run(
        ["g++", "-std=c++17", "-DFAULTFS_SYNTAX_TEST", "-fsyntax-only",
         "-Wall", "-Werror", "-I.", "faultfs.cc"],
        cwd=NATIVE, check=True)


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    d = tmp_path_factory.mktemp("faultfs-build")
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-DFAULTFS_SYNTAX_TEST", "-I", NATIVE,
         "-o", str(d / "faultfs"), os.path.join(NATIVE, "faultfs.cc"),
         "-lpthread"],
        check=True)
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-o", str(d / "faultfsctl"),
         os.path.join(NATIVE, "faultfsctl.cc")],
        check=True)
    return d


def test_control_plane_round_trip(built, tmp_path):
    """Start the control server (no FUSE), drive it with faultfsctl."""
    real = tmp_path / "real"
    real.mkdir()
    env = dict(os.environ, FAULTFS_CONTROL_ONLY="1")
    proc = subprocess.Popen([str(built / "faultfs"), str(real), "/dev/null"],
                            env=env)
    sock = str(real / ".faultfs.sock")
    try:
        for _ in range(100):
            if os.path.exists(sock):
                break
            time.sleep(0.05)
        assert os.path.exists(sock), "control socket never appeared"

        def ctl(*args):
            out = subprocess.run([str(built / "faultfsctl"), sock, *args],
                                 capture_output=True, text=True, timeout=10)
            assert out.returncode == 0, out.stderr
            return out.stdout

        assert "active=0" in ctl("status")
        assert "ok set" in ctl("set", "errno=EIO", "p=1.0")
        st = ctl("status")
        assert "active=1" in st and "errno=5" in st and "p=1" in st
        assert "ok set" in ctl("set", "errno=ENOSPC", "p=0.01",
                               "methods=write,fsync")
        st = ctl("status")
        assert "errno=28" in st and "p=0.01" in st
        assert "ok cleared" in ctl("clear")
        assert "active=0" in ctl("status")
        assert "err unknown" in ctl("frobnicate")
    finally:
        proc.kill()
        proc.wait()


def test_driver_command_shapes():
    r = DummyRemote({"stat /": (1, "", "no"),
                     "dpkg": (0, "", ""),
                     "apt-get": (0, "", "")})
    nodes = ["n1"]
    test = {"nodes": nodes,
            "sessions": {n: Session(node=n, remote=r) for n in nodes}}
    sess = Session(node="n1", remote=r)
    faultfs.break_all(sess)
    faultfs.break_one_percent(sess)
    faultfs.clear(sess)
    faultfs.break_methods(sess, ["write", "fsync"], err="ENOSPC", p=0.5)
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    ctl = faultfs.CTL
    assert any(f"{ctl} {faultfs.SOCK} set errno=EIO p=1.0" in c
               for c in cmds)
    assert any("p=0.01" in c for c in cmds)
    assert any(" clear" in c for c in cmds)
    assert any("methods=write,fsync" in c and "errno=ENOSPC" in c
               for c in cmds)

    # nemesis surface
    from jepsen_tpu.history import info_op

    r.log.clear()
    nem = faultfs.nemesis()
    out = nem.invoke(test, info_op("nemesis", "break-all", None))
    assert out.type == "info"
    assert any("set errno=EIO p=1.0" in e[2] for e in r.log)
    with pytest.raises(ValueError):
        nem.invoke(test, info_op("nemesis", "what", None))


def test_install_commands():
    r = DummyRemote({"stat /": (1, "", "no"), "dpkg": (0, "", "")})
    sess = Session(node="n1", remote=r)
    faultfs.install(sess)
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    ups = [e for e in r.log if e[1] == "upload"]
    assert any("libfuse3-dev" in c for c in cmds)
    assert {os.path.basename(u[2][0]) for u in ups} == \
        {"faultfs.cc", "faultfsctl.cc", "CMakeLists.txt"}
    assert any("cmake -B build" in c for c in cmds)
    assert any(f"{faultfs.BIN} /real /faulty -o allow_other" in c
               for c in cmds)
