"""Tier-1 tests for the O(n) checkers, mirroring the reference's
checker_test.clj cases (valid, invalid, pathological)."""

import pytest

from jepsen_tpu.checker import basic
from jepsen_tpu.history import fail_op, info_op, invoke_op, ok_op
from jepsen_tpu import independent


def ops(*specs):
    """(type, process, f, value) shorthand."""
    mk = {"invoke": invoke_op, "ok": ok_op, "fail": fail_op,
          "info": info_op}
    return [mk[t](p, f, v) for t, p, f, v in specs]


# --- queue ----------------------------------------------------------------


def test_queue_valid():
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    assert basic.queue().check({}, h)["valid"] is True


def test_queue_dequeue_from_nowhere():
    h = ops(("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 7))
    out = basic.queue().check({}, h)
    assert out["valid"] is False
    assert "7" in out["error"]


def test_queue_unordered_ok():
    # enqueue 1 2, dequeue 2 1 — fine for an unordered queue
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 2),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    assert basic.queue().check({}, h)["valid"] is True


def test_queue_counts_indeterminate_enqueue():
    # an enqueue that crashed still counts (invoke taken), so the dequeue
    # is legal
    h = ops(("invoke", 0, "enqueue", 5), ("info", 0, "enqueue", 5),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 5))
    assert basic.queue().check({}, h)["valid"] is True


def test_fifo_queue_order():
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 2))
    out = basic.queue(basic.FIFOQueue()).check({}, h)
    assert out["valid"] is False


# --- set ------------------------------------------------------------------


def test_set_never_read():
    h = ops(("invoke", 0, "add", 1), ("ok", 0, "add", 1))
    assert basic.set_checker().check({}, h)["valid"] == "unknown"


def test_set_valid_with_recovered():
    h = ops(("invoke", 0, "add", 1), ("ok", 0, "add", 1),
            ("invoke", 0, "add", 2), ("info", 0, "add", 2),  # indeterminate
            ("invoke", 1, "read", None), ("ok", 1, "read", [1, 2]))
    out = basic.set_checker().check({}, h)
    assert out["valid"] is True
    assert out["recovered"] == "#{2}"


def test_set_lost_and_unexpected():
    h = ops(("invoke", 0, "add", 1), ("ok", 0, "add", 1),
            ("invoke", 1, "read", None), ("ok", 1, "read", [99]))
    out = basic.set_checker().check({}, h)
    assert out["valid"] is False
    assert out["lost"] == "#{1}"
    assert out["unexpected"] == "#{99}"


# --- total-queue ----------------------------------------------------------


def test_total_queue_valid_with_drain():
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
            ("invoke", 1, "drain", None), ("ok", 1, "drain", [1, 2]))
    out = basic.total_queue().check({}, h)
    assert out["valid"] is True


def test_total_queue_pathological():
    # duplicated and unexpected dequeues (checker_test.clj:57-81 analog)
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 9))
    out = basic.total_queue().check({}, h)
    assert out["valid"] is False
    assert out["duplicated"] == {1: 1}
    assert out["unexpected"] == {9: 1}


def test_total_queue_lost():
    h = ops(("invoke", 0, "enqueue", 3), ("ok", 0, "enqueue", 3),
            ("invoke", 1, "drain", None), ("ok", 1, "drain", []))
    out = basic.total_queue().check({}, h)
    assert out["valid"] is False
    assert out["lost"] == {3: 1}


# --- unique-ids -----------------------------------------------------------


def test_unique_ids():
    h = ops(("invoke", 0, "generate", None), ("ok", 0, "generate", 10),
            ("invoke", 0, "generate", None), ("ok", 0, "generate", 11))
    out = basic.unique_ids().check({}, h)
    assert out["valid"] is True and out["range"] == [10, 11]

    h2 = h + ops(("invoke", 1, "generate", None), ("ok", 1, "generate", 10))
    out2 = basic.unique_ids().check({}, h2)
    assert out2["valid"] is False
    assert out2["duplicated"] == {10: 2}


# --- counter --------------------------------------------------------------


def test_counter_valid_concurrent_read():
    h = ops(("invoke", 0, "add", 5), ("invoke", 1, "read", None),
            ("ok", 0, "add", 5), ("ok", 1, "read", 3))
    # read of 3 is within [0, 5]
    assert basic.counter().check({}, h)["valid"] is True


def test_counter_read_too_high():
    h = ops(("invoke", 0, "add", 5), ("ok", 0, "add", 5),
            ("invoke", 1, "read", None), ("ok", 1, "read", 9))
    out = basic.counter().check({}, h)
    assert out["valid"] is False
    assert out["errors"] == [[5, 9, 5]]


# --- bank -----------------------------------------------------------------


def test_bank():
    test = {"total_amount": 100}
    good = ops(("invoke", 0, "read", None),
               ("ok", 0, "read", {0: 60, 1: 40}))
    assert basic.bank().check(test, good)["valid"] is True

    bad = ops(("invoke", 0, "read", None),
              ("ok", 0, "read", {0: 70, 1: 40}))
    out = basic.bank().check(test, bad)
    assert out["valid"] is False
    assert out["bad_reads"][0]["type"] == "wrong-total"

    neg = ops(("invoke", 0, "read", None),
              ("ok", 0, "read", {0: 150, 1: -50}))
    out = basic.bank().check(test, neg)
    assert out["valid"] is False
    assert out["bad_reads"][0]["type"] == "negative-value"


# --- G2 -------------------------------------------------------------------


def test_g2():
    h = ops(("invoke", 0, "insert", (0, (1, None))),
            ("ok", 0, "insert", (0, (1, None))),
            ("invoke", 1, "insert", (0, (None, 2))),
            ("fail", 1, "insert", (0, (None, 2))))
    assert basic.g2().check({}, h)["valid"] is True

    h2 = ops(("invoke", 0, "insert", (0, (1, None))),
             ("ok", 0, "insert", (0, (1, None))),
             ("invoke", 1, "insert", (0, (None, 2))),
             ("ok", 1, "insert", (0, (None, 2))))
    out = basic.g2().check({}, h2)
    assert out["valid"] is False and out["illegal"] == {0: 2}


# --- independent lift -----------------------------------------------------


def test_independent_subhistory_and_keys():
    kv = independent.tuple_
    h = [invoke_op(0, "write", kv("a", 1)), ok_op(0, "write", kv("a", 1)),
         invoke_op(1, "write", kv("b", 2)), ok_op(1, "write", kv("b", 2)),
         info_op("nemesis", "partition", None)]
    assert independent.history_keys(h) == ["a", "b"]
    sub = independent.subhistory("a", h)
    assert [op.value for op in sub] == [1, 1, None]
    assert sub[2].process == "nemesis"  # un-keyed ops kept


def test_independent_checker_host_path():
    from jepsen_tpu.checker import linearizable as lin
    from jepsen_tpu.models import cas_register

    kv = independent.tuple_
    model = cas_register()
    h = []
    # key a: valid; key b: invalid read
    h += [invoke_op(0, "write", kv("a", 1)), ok_op(0, "write", kv("a", 1)),
          invoke_op(0, "read", kv("a", None)), ok_op(0, "read", kv("a", 1))]
    h += [invoke_op(1, "write", kv("b", 1)), ok_op(1, "write", kv("b", 1)),
          invoke_op(1, "read", kv("b", None)), ok_op(1, "read", kv("b", 9))]
    chk = independent.checker(lin.linearizable(model))
    out = chk.check({}, h)
    assert out["valid"] is False
    assert out["failures"] == ["b"]
    assert out["results"]["a"]["valid"] is True


def test_independent_checker_device_batch():
    import random

    from jepsen_tpu.checker import linearizable as lin
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    kv = independent.tuple_
    model = cas_register()
    rng = random.Random(3)
    h = []
    bad_keys = set()
    for k in range(6):
        sub = register_history(rng, n_ops=30, n_procs=3, overlap=2)
        if k % 3 == 0:
            sub = corrupt_read(rng, sub, at=0.9)
            bad_keys.add(k)
        for op in sub:
            h.append(
                __import__("dataclasses").replace(
                    op, process=op.process + 3 * k, value=kv(k, op.value)))
    chk = independent.checker(lin.linearizable(model, host_threshold=5))
    out = chk.check({}, h)
    assert out["valid"] is False
    assert set(out["failures"]) == bad_keys
    for k in range(6):
        assert out["results"][k]["valid"] is (k not in bad_keys)


# --- sequential + monotonic (cockroach suite checkers) --------------------


def test_trailing_nil():
    from jepsen_tpu.checker import extra

    assert not extra.trailing_nil([None, None, 1, 2])
    assert extra.trailing_nil([1, None])
    assert extra.trailing_nil([None, 1, None])
    assert not extra.trailing_nil([])


def test_sequential_checker():
    from jepsen_tpu.checker import extra

    test = {"key_count": 2}
    # read vectors are in reverse insert order: later subkey first
    good = ops(("invoke", 0, "read", None),
               ("ok", 0, "read", ("k", [None, "k_0"])),  # y missing, x seen? -> wait
               )
    # y=None then x="k_0" means later insert invisible, earlier visible: fine
    out = extra.sequential().check(test, good)
    assert out["valid"] is True

    bad = ops(("invoke", 0, "read", None),
              ("ok", 0, "read", ("k", ["k_1", None])))  # y seen, x missing
    out = extra.sequential().check(test, bad)
    assert out["valid"] is False and out["bad_count"] == 1

    full = ops(("invoke", 0, "read", None),
               ("ok", 0, "read", ("k", ["k_1", "k_0"])))
    out = extra.sequential().check(test, full)
    assert out["valid"] is True and out["all_count"] == 1


def test_monotonic_checker():
    from jepsen_tpu.checker import extra

    def row(v, sts, proc=0, node="n1", tb=0):
        return {"val": v, "sts": sts, "proc": proc, "node": node, "tb": tb}

    h = ops(("invoke", 0, "add", {"val": 0}), ("ok", 0, "add", {"val": 0}),
            ("invoke", 0, "add", {"val": 1}), ("ok", 0, "add", {"val": 1}),
            ("invoke", 1, "read", None),
            ("ok", 1, "read", [row(0, 10), row(1, 20)]))
    assert extra.monotonic().check({}, h)["valid"] is True

    # reversed values: off-order
    h2 = ops(("invoke", 0, "add", {"val": 0}), ("ok", 0, "add", {"val": 0}),
             ("invoke", 0, "add", {"val": 1}), ("ok", 0, "add", {"val": 1}),
             ("invoke", 1, "read", None),
             ("ok", 1, "read", [row(1, 10), row(0, 20)]))
    out = extra.monotonic().check({}, h2)
    assert out["valid"] is False and out["off_order_vals"]

    # lost element
    h3 = ops(("invoke", 0, "add", {"val": 0}), ("ok", 0, "add", {"val": 0}),
             ("invoke", 0, "add", {"val": 1}), ("ok", 0, "add", {"val": 1}),
             ("invoke", 1, "read", None), ("ok", 1, "read", [row(0, 10)]))
    out = extra.monotonic().check({}, h3)
    assert out["valid"] is False and out["lost"] == [1]

    # never read -> unknown
    h4 = ops(("invoke", 0, "add", {"val": 0}), ("ok", 0, "add", {"val": 0}))
    assert extra.monotonic().check({}, h4)["valid"] == "unknown"


def test_concurrency_limit():
    from jepsen_tpu.checker import core as ccore

    calls = []

    class Slow(ccore.Checker):
        def check(self, test, history, opts=None):
            calls.append(1)
            return {"valid": True}

    chk = ccore.concurrency_limit(2, Slow())
    out = chk.check({}, [])
    assert out["valid"] is True and calls == [1]


def test_queue_linearizable_checker():
    """Full linearizability over queue semantics — stronger than the
    model-reduce: a from-thin-air element or an unjustifiable FIFO
    service order must fail; drains become windowed concurrent dequeues
    (NOT the reference's zero-width expansion, which is only sound for
    order-insensitive reduces)."""
    from jepsen_tpu.checker import basic
    from jepsen_tpu.history import info_op, invoke_op, ok_op

    h = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
         invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2),
         invoke_op(0, "drain", None), ok_op(0, "drain", [2, 1])]
    # multiset semantics: drain order is free
    assert basic.queue_linearizable().check({}, h, {})["valid"] is True
    # FIFO: the drain's list carries a service ORDER the interval
    # encoding cannot express — any element-removing drain -> unknown
    assert basic.queue_linearizable(fifo=True).check(
        {}, h, {})["valid"] == "unknown"

    # sequential (non-drain) LIFO service order: invalid under FIFO
    h_lifo = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
              invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2),
              invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 2),
              invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1)]
    assert basic.queue_linearizable(fifo=True).check(
        {}, h_lifo, {})["valid"] is False
    assert basic.queue_linearizable().check(
        {}, h_lifo, {})["valid"] is True

    # the windowed-drain soundness case (multiset): a dequeue strictly
    # inside the drain window serviced between the drained element's
    # enqueue and the drain's completion — valid, where the zero-width
    # expansion would wrongly impose the drain's completion as the
    # dequeue's instant
    h_win = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
             invoke_op(0, "drain", None),
             invoke_op(1, "enqueue", 2), ok_op(1, "enqueue", 2),
             invoke_op(1, "dequeue", None), ok_op(1, "dequeue", 2),
             ok_op(0, "drain", [1])]
    assert basic.queue_linearizable().check(
        {}, h_win, {})["valid"] is True
    # an EMPTY drain removed nothing: fifo stays checkable through it
    h_empty = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
               invoke_op(1, "drain", None), ok_op(1, "drain", []),
               invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1)]
    assert basic.queue_linearizable(fifo=True).check(
        {}, h_empty, {})["valid"] is True

    # from-thin-air dequeue fails under both
    h2 = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
          invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 99)]
    assert basic.queue_linearizable().check({}, h2, {})["valid"] is False

    # count-valued (disque-style) and crashed drains: no constraint
    # for the multiset; FIFO cannot be checked soundly -> unknown
    h3 = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
          invoke_op(0, "drain", None), ok_op(0, "drain", 1),
          invoke_op(1, "drain", None), info_op(1, "drain", None)]
    assert basic.queue_linearizable().check({}, h3, {})["valid"] is True
    out_l = basic.queue_linearizable(fifo=True).check({}, h3, {})
    assert out_l["valid"] == "unknown" and "stale head" in out_l["info"]
    # a FAILED drain removed nothing: fifo stays checkable
    h4 = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
          invoke_op(0, "drain", None), fail_op(0, "drain", None),
          invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1)]
    assert basic.queue_linearizable(fifo=True).check(
        {}, h4, {})["valid"] is True
    # a DANGLING drain invoke (no completion ever) is a crashed drain:
    # lossy for fifo, no-constraint for the multiset
    h5 = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
          invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2),
          invoke_op(1, "drain", None),
          invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 2)]
    assert basic.queue_linearizable(fifo=True).check(
        {}, h5, {})["valid"] == "unknown"
    assert basic.queue_linearizable().check({}, h5, {})["valid"] is True

    # over the gate: unknown, not an hours-long search
    big = []
    for i in range(60):
        big += [invoke_op(0, "enqueue", i), ok_op(0, "enqueue", i)]
    out3 = basic.queue_linearizable(max_ops=50).check({}, big, {})
    assert out3["valid"] == "unknown"


@pytest.mark.parametrize("seed", range(4))
def test_queue_linear_drain_window_property(seed):
    """Simulated (valid-by-construction) queue traffic plus a final
    drain of the leftovers must always check valid — the windowed drain
    expansion may never invent a real-time constraint the run didn't
    have."""
    import random

    from jepsen_tpu.checker import basic
    from jepsen_tpu.history import invoke_op, ok_op
    from jepsen_tpu.synth import sim_queue_history

    rng = random.Random(7100 + seed)
    h = sim_queue_history(rng, 30, 4, fifo=bool(seed % 2))
    enq = [o.value for o in h if o.type == "ok" and o.f == "enqueue"]
    for o in h:
        if o.type == "ok" and o.f == "dequeue":
            enq.remove(o.value)
    h = h + [invoke_op(9, "drain", None), ok_op(9, "drain", enq)]
    # multiset check: always valid.  (fifo histories are also valid
    # multiset histories; fifo+element-removing-drain is "unknown" by
    # design, covered in test_queue_linearizable_checker.)
    chk = basic.queue_linearizable()
    assert chk.check({}, h, {})["valid"] is True, seed
