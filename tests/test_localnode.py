"""Executed Tier-3: the localnode suite — real OS processes as nodes.

The reference proves its stack against real remote processes
(core_test.clj:32-86 ssh-test; docker/smoke.sh).  This image has no
sshd/docker, so the executable analog is the localnode suite: real
daemons via start-stop-daemon, real TCP clients, real kill -9 crashes,
full runner -> nemesis -> checker -> store pipeline.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from jepsen_tpu.suites import localnode, localnode_server

SERVER = os.path.abspath(localnode_server.__file__)


def _connect(port, deadline_s=10.0):
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return socket.create_connection(("127.0.0.1", port),
                                            timeout=1.0)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def _rt(sock, line):
    sock.sendall((line + "\n").encode())
    buf = b""
    while not buf.endswith(b"\n"):
        buf += sock.recv(4096)
    return buf.decode().strip()


def test_server_survives_kill_minus_9(tmp_path):
    """Acked writes are fsynced before the reply, so they survive a
    SIGKILL and reappear after restart (oplog replay)."""
    port = 17990
    data = str(tmp_path / "data")

    def start():
        return subprocess.Popen([sys.executable, SERVER, str(port), data],
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)

    proc = start()
    try:
        s = _connect(port)
        assert _rt(s, "W a 3") == "OK"
        assert _rt(s, "CAS a 3 4") == "OK"
        assert _rt(s, "CAS a 9 7") == "FAIL"
        assert _rt(s, "R a") == "OK 4"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=5)
        proc = start()
        s2 = _connect(port)
        assert _rt(s2, "R a") == "OK 4"  # durable across the crash
        assert _rt(s2, "R nope") == "OK nil"
    finally:
        proc.kill()
        proc.wait(timeout=5)


def test_lock_workload_live_durable_valid(tmp_path):
    """BASELINE config #4 executed end to end: real lock-server
    process, real TCP acquire/release, kill -9 / restart nemesis,
    mutex verdict through the full runner.  The durable server fsyncs
    the holder before granting, so every verdict must be valid."""
    from jepsen_tpu import core
    from jepsen_tpu.suites import localnode

    test = localnode.locknode_test({
        "base_port": 17960,
        "data_root": str(tmp_path / "nodes"),
        "store_base": str(tmp_path / "store"),
        "time_limit": 6,
        "kill_every": 2,
        "concurrency": 4,
    })
    test = core.run(test)
    res = test["results"]
    assert res.get("valid") is True, res
    hist = test["history"]
    assert any(op.process == "nemesis" and op.f == "kill"
               for op in hist), "nemesis never killed the lock server"
    oks = [op for op in hist if isinstance(op.process, int)
           and op.type == "ok"]
    assert len(oks) > 10, f"too few completed lock ops: {len(oks)}"


def test_lock_volatile_double_grant_detected(tmp_path):
    """The reference's hazelcast finding reproduced live: a lock
    server that forgets its holder on kill -9 double-grants, and the
    mutex checker must CATCH it (hazelcast.clj analysis; the checker
    path is BASELINE config #4's whole point)."""
    from jepsen_tpu import core
    from jepsen_tpu.suites import localnode

    # the construction must leave the checker NO :info release to
    # explain the gap with (a symmetric acquire/release workload always
    # has one: the dead holder's own release discovers the kill on its
    # send and records :info, which legally linearizes as the unlock).
    # So: one HOLDER process (acquire, hold 2 s, release) and one
    # acquire-ONLY process that never releases.  The kill lands inside
    # the hold; the restarted volatile server forgets the holder and
    # grants the acquirer while the holder still sleeps; the holder's
    # release is then INVOKED strictly after that grant returned, so
    # real-time order pins its linearization point after both grants —
    # two ok acquires with no possible unlock between.  hazelcast.clj's
    # double-grant finding, reproduced live through the full runner.
    import itertools

    from jepsen_tpu import generator as gen
    from jepsen_tpu.suites.localnode import lock_gen

    # hold must outlast kill + restart latency (the restart's daemon
    # start + readiness poll takes ~2 s on a loaded host): the second
    # grant has to COMPLETE while the holder still sleeps, or the
    # holder's pending release alone explains the gap.  The latency
    # varies wildly with host load, so CALIBRATE it: time one real
    # setup/kill/restart cycle and size the hold from it.
    from jepsen_tpu import control
    from jepsen_tpu.suites.localnode import LocalNodeDB, _kill

    cal = {"nodes": ["n1"], "base_port": 17969,
           "data_root": str(tmp_path / "cal"), "lock_volatile": True,
           "remote": control.LocalRemote(), "ssh": {}}
    db = LocalNodeDB()
    db.setup(cal, "n1")
    _kill(control.session("n1", cal), cal, "n1")
    t0 = time.monotonic()
    db.setup(cal, "n1")
    restart_s = time.monotonic() - t0
    db.teardown(cal, "n1")
    hold = max(5.0, 3.0 * restart_s + 2.0)
    kill_at = 1.5
    tl = int(kill_at + hold + restart_s + 5)

    for attempt in range(3):
        test = localnode.locknode_test({
            "base_port": 17970 + attempt,
            "data_root": str(tmp_path / f"nodes{attempt}"),
            "store_base": str(tmp_path / f"store{attempt}"),
            "time_limit": tl,
            "concurrency": 2,
            "lock_volatile": True,
        })
        holder = gen.stagger(0.01, lock_gen(hold=hold))
        acquirer = gen.stagger(0.05, gen.each(
            lambda: gen.seq(itertools.cycle(
                [{"type": "invoke", "f": "acquire", "value": None}]))))
        nem = gen.seq(itertools.cycle(
            [gen.sleep(kill_at), {"type": "info", "f": "kill"},
             gen.sleep(0.3), {"type": "info", "f": "restart"}]))
        test["generator"] = gen.phases(
            gen.time_limit(tl, gen.nemesis(
                nem, gen.reserve(1, holder, acquirer))),
            gen.nemesis(gen.once({"type": "info", "f": "restart"})),
            gen.sleep(0.5))
        test = core.run(test)
        res = test["results"]
        assert res.get("valid") in (True, False)
        if res.get("valid") is False:
            # the double grant was real and the checker caught it —
            # through real sockets, a real kill -9, the full runner
            return
        # valid verdict: only acceptable if the double grant was never
        # STAGED (kill/restart timing missed the hold window).  If the
        # history shows an acquirer grant completing inside a holder's
        # open hold — before the holder even invoked its release — no
        # linearization exists, and a valid verdict is a CHECKER
        # REGRESSION, not bad luck.
        open_hold = False
        for op in test["history"]:
            if not isinstance(op.process, int):
                continue
            holder_side = op.process % 2 == 0  # reserve(1,...): thread 0
            if holder_side and op.f == "acquire" and op.type == "ok":
                open_hold = True
            elif holder_side and op.f == "release" \
                    and op.type == "invoke":
                open_hold = False
            elif (not holder_side and op.f == "acquire"
                    and op.type == "ok" and open_hold):
                pytest.fail(
                    "history stages an inexplicable double grant (an "
                    "acquirer ok inside a holder's un-released hold) "
                    f"but the checker said valid: {res}")
        # never staged: timing starvation on a loaded host, not a
        # checker problem
    pytest.skip(f"double grant not staged in 3 runs (hold {hold:.1f}s, "
                f"calibrated restart {restart_s:.1f}s — host load "
                "shifted timing); verdicts matched the histories")


def test_full_stack_real_processes(tmp_path):
    """core.run end to end: real server daemons per node, a kill -9 /
    restart nemesis, linearizable verdict, store artifacts."""
    from jepsen_tpu import core

    test = localnode.localnode_test({
        "nodes": ["n1", "n2", "n3"],
        "base_port": 17920,
        "data_root": str(tmp_path / "nodes"),
        "store_base": str(tmp_path / "store"),
        "time_limit": 6,
        "rate": 20,
        "concurrency": 6,
        "ops_per_key": 25,
    })
    test = core.run(test)
    res = test["results"]
    assert res.get("valid") is True, res
    hist = test["history"]
    assert any(op.process == "nemesis" and op.f == "kill"
               for op in hist), "nemesis never killed a server"
    assert any(op.process == "nemesis" and op.f == "restart"
               for op in hist)
    client_ops = [op for op in hist if isinstance(op.process, int)]
    assert len(client_ops) > 40, f"too few ops: {len(client_ops)}"
    # store artifacts on disk
    from jepsen_tpu import store

    d = os.path.dirname(store.path(test, "x"))
    assert os.path.isfile(os.path.join(d, "results.json"))
    r = json.load(open(os.path.join(d, "results.json")))
    assert r.get("valid") is True
    # every server process is gone after teardown
    for i in range(3):
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", 17920 + i),
                                     timeout=0.3).close()
