"""Verdict-cache jsonl compaction — long campaigns must not grow the
append-only store without bound.

Contract: compaction rewrites exactly the live entry set (newest per
key), drops superseded duplicate lines, survives concurrent writers'
appends made since load (they are merged from a fresh read), replaces
the file atomically, and auto-arms past the size threshold.
"""

import json
import os

from jepsen_tpu.decompose.cache import VerdictCache


def _lines(path):
    with open(path) as f:
        return [json.loads(x) for x in f if x.strip()]


def test_compact_drops_superseded_lines(tmp_path):
    p = str(tmp_path / "v.jsonl")
    c = VerdictCache(p, compact_bytes=0)  # manual compaction only
    for _ in range(5):
        c.put_verdict("k1", True)
        c.put_verdict("k2", False)
        c.put_states("k3", [[1, 2], [3, 4]])
    assert len(_lines(p)) == 15
    dropped = c.compact()
    assert dropped == 12
    live = _lines(p)
    assert len(live) == 3
    assert {e["k"] for e in live} == {"k1", "k2", "k3"}
    # semantics intact after compaction + reload
    c2 = VerdictCache(p)
    assert c2.get("k1") == {"k": "k1", "v": True}
    assert c2.get("k2") == {"k": "k2", "v": False}
    assert c2.get("k3")["out"] == [[1, 2], [3, 4]]
    assert c.compactions == 1
    assert c.compacted_away == 12


def test_compact_then_append_lands_in_new_file(tmp_path):
    p = str(tmp_path / "v.jsonl")
    c = VerdictCache(p, compact_bytes=0)
    for _ in range(3):
        c.put_verdict("a", True)
    c.compact()
    c.put_verdict("b", False)  # append handle must follow the replace
    assert {e["k"] for e in _lines(p)} == {"a", "b"}
    assert len(_lines(p)) == 2


def test_compact_merges_other_writers_entries(tmp_path):
    """A second process appended since our load: compaction must carry
    its entries into the rewrite, not forget them."""
    p = str(tmp_path / "v.jsonl")
    c1 = VerdictCache(p, compact_bytes=0)
    c1.put_verdict("mine", True)
    c2 = VerdictCache(p, compact_bytes=0)
    c2.put_verdict("theirs", False)
    c1.compact()
    keys = {e["k"] for e in _lines(p)}
    assert keys == {"mine", "theirs"}
    # and a fresh loader sees both
    c3 = VerdictCache(p)
    assert c3.get("mine")["v"] is True
    assert c3.get("theirs")["v"] is False


def test_auto_compaction_triggers_past_threshold(tmp_path):
    p = str(tmp_path / "v.jsonl")
    c = VerdictCache(p, compact_bytes=2000)
    # hammer one hot key: the file grows while the live set stays at 1
    for i in range(3000):
        c.put_verdict("hot", True)
    assert c.compactions >= 1, "size-triggered compaction never fired"
    assert os.path.getsize(p) < 2000 + 4096  # bounded, not ~90KB
    assert len(_lines(p)) < 300
    c.close()
    assert VerdictCache(p).get("hot")["v"] is True


def test_compaction_disabled_with_zero_threshold(tmp_path):
    p = str(tmp_path / "v.jsonl")
    c = VerdictCache(p, compact_bytes=0)
    for _ in range(600):
        c.put_verdict("hot", True)
    assert c.compactions == 0
    assert len(_lines(p)) == 600


def test_in_memory_cache_compact_is_noop():
    c = VerdictCache(None)
    c.put_verdict("x", True)
    assert c.compact() == 0


# ---------------------------------------------------------------------------
# concurrent writers vs compaction (the interprocess lock)
# ---------------------------------------------------------------------------


def test_concurrent_writer_appends_survive_compaction_race(tmp_path):
    """The regression the fleet cache tier depends on: a second
    writer appending WHILE the first compacts must never lose an
    insert — the lock serializes each append against the
    merge-read -> replace window, and the per-append inode re-check
    re-points a handle whose file was just replaced."""
    import threading

    p = str(tmp_path / "v.jsonl")
    a = VerdictCache(p, compact_bytes=0)
    b = VerdictCache(p, compact_bytes=0)
    n = 200
    stop = threading.Event()

    def writer():
        for i in range(n):
            b.put_verdict(f"b{i}", i % 2 == 0)
        stop.set()

    def compactor():
        # loop body must run at least once even if the writer wins the
        # scheduling race and sets `stop` before this thread starts —
        # the "hot" assertion below depends on one insert happening
        while True:
            a.put_verdict("hot", True)
            a.compact()
            if stop.is_set():
                break

    threads = [threading.Thread(target=writer),
               threading.Thread(target=compactor)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    a.compact()  # final merge picks up b's tail
    fresh = VerdictCache(p)
    missing = [i for i in range(n) if fresh.get(f"b{i}") is None]
    assert missing == [], \
        f"compaction race lost {len(missing)} concurrent insert(s)"
    assert fresh.get("hot")["v"] is True


def test_reader_mid_scan_sees_complete_old_view(tmp_path):
    """A loader that opened the file before a compaction keeps reading
    a complete (stale) view — the replace is atomic and the old inode
    stays readable; no torn line, no mixed old/new interleaving."""
    p = str(tmp_path / "v.jsonl")
    c = VerdictCache(p, compact_bytes=0)
    for i in range(50):
        c.put_verdict(f"k{i}", True)
        c.put_verdict(f"k{i}", False)  # superseded duplicates
    with open(p) as f:
        head = [json.loads(f.readline()) for _ in range(10)]
        c.compact()  # replaces the file under the open handle
        tail = [json.loads(x) for x in f if x.strip()]
    # the reader drained the OLD file: every pre-compaction line, in
    # order, with the superseded duplicates still present
    assert len(head) + len(tail) == 100
    assert [e["k"] for e in head] == [f"k{i // 2}" for i in range(10)]
    # and a fresh loader sees the compacted view
    assert len(_lines(p)) == 50
