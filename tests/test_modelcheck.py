"""Bounded model checker (jepsen_tpu/analyze/modelcheck.py) — the CI
gate for the MC1xx layer.

Three tiers of guarantees:

* **Soundness of the reduction** — sleep sets prune *transitions*,
  never reachable states, so the (code, state-fingerprint) violation
  set must be bit-identical with DPOR on and off at the same scope.
* **Seeded-bug acceptance** — each seeded live mode (``volatile``,
  ``split-brain``, ``rqueue_volatile``-style queue volatility, lock
  volatility) is caught at the default bounded scope with a schedule
  certificate that replays deterministically, shrinks to a small core,
  renders as a jepsen history the linearizability engine re-confirms
  INVALID (audit passing), and banks into a corpus.
* **Clean-backend verdicts** — the un-seeded modes clear the same
  scope with zero violations, a complete search, and a nonzero
  sleep-set prune ratio (the reduction must actually bite).

The fast tests run the default scopes (sub-second each); ``-m slow``
widens the budgets (deeper schedules, extra crash) for the full
matrix.
"""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.analyze import modelcheck as mc  # noqa: E402
from jepsen_tpu.analyze import __main__ as analyze_cli  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def violation_set(result: dict) -> set:
    return {(v["code"], v["state"]) for v in result["violations"]}


def run_cli(*args, env=None):
    e = dict(os.environ, JAX_PLATFORMS="cpu")
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, "-m", "jepsen_tpu.analyze", *args],
        capture_output=True, text=True, cwd=REPO, env=e)


def run_cli_inproc(capsys, *args):
    # same entry point as the subprocess path, minus the interpreter
    # + jax import tax; keeps tier-1 wall time down
    rc = analyze_cli.main(list(args))
    return rc, capsys.readouterr().out


# ---------------------------------------------------------------------------
# reduction soundness: sleep sets prune transitions, never states
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family,mode", [
    ("replicated", "volatile"),
    ("lock", "volatile"),
    ("rqueue", "volatile"),
])
def test_dpor_soundness_seeded(family, mode):
    scope = mc.default_scope(family, mode)
    on = mc.explore(family, mode, scope, dpor=True,
                    max_violations=10_000)
    off = mc.explore(family, mode, scope, dpor=False,
                     max_violations=10_000)
    assert on["explored"]["complete"] and off["explored"]["complete"]
    assert violation_set(on) == violation_set(off)
    assert on["violations"], f"{family}/{mode}: seeded bug not found"
    # the reduction must have actually pruned something
    assert on["explored"]["sleep_prunes"] > 0
    assert on["explored"]["events"] <= off["explored"]["events"]


@pytest.mark.parametrize("family", mc.FAMILIES)
def test_dpor_soundness_clean(family):
    scope = mc.default_scope(family, "clean")
    on = mc.explore(family, "clean", scope, dpor=True)
    off = mc.explore(family, "clean", scope, dpor=False)
    assert not on["violations"] and not off["violations"]
    assert on["explored"]["complete"] and off["explored"]["complete"]


# ---------------------------------------------------------------------------
# clean backends clear the bounded scope
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", mc.FAMILIES)
def test_clean_mode_passes_with_reduction_biting(family):
    r = mc.run_mc(family, "clean", dpor=True)
    assert r["ok"], r
    assert r["explored"]["complete"]
    assert r["explored"]["prune_ratio"] > 0
    assert r["explored"]["states"] > 10


# ---------------------------------------------------------------------------
# seeded-bug acceptance: catch -> shrink -> replay -> confirm -> bank
# ---------------------------------------------------------------------------

def _accept(family, mode, want_code, tmp_path, route):
    r = mc.run_mc(family, mode, dpor=True,
                  bank_base=str(tmp_path / "corpus"))
    assert not r["ok"]
    codes = {v["code"] for v in r["violations"]}
    assert want_code in codes, (codes, r["violations"][:1])
    v = next(v for v in r["violations"] if v["code"] == want_code)
    # the shrunk schedule still replays deterministically
    assert v["replayed"]
    assert v["shrunk"]["n_to"] <= v["shrunk"]["n_from"]
    assert len(v["schedule"]) == v["shrunk"]["n_to"]
    # the rendered history is engine-confirmed INVALID, audit passing
    c = v["confirm"]
    assert c["route"] == route
    assert c["engine_valid"] is False
    assert c["audit_ok"] is True and c["audit_checked"]
    # and it banked into the corpus
    assert v["banked"]["banked"] >= 1
    assert (tmp_path / "corpus").exists()
    return v


def test_seeded_kv_volatile_caught(tmp_path):
    v = _accept("replicated", "volatile", "MC102", tmp_path, "engine")
    # lost-write histories need at least a write and the probe read
    fs = [op["f"] for op in v["history"]]
    assert "read" in fs


def test_seeded_kv_split_brain_caught(tmp_path):
    _accept("replicated", "split-brain", "MC101", tmp_path, "engine")


def test_seeded_rqueue_volatile_caught(tmp_path):
    v = _accept("rqueue", "volatile", "MC104", tmp_path, "queue")
    fs = [op["f"] for op in v["history"]]
    assert "enqueue" in fs and "drain" in fs


def test_seeded_lock_volatile_caught(tmp_path):
    _accept("lock", "volatile", "MC106", tmp_path, "engine")


def test_certificate_replays_via_module_api(tmp_path):
    r = mc.run_mc("lock", "volatile", dpor=True)
    v = r["violations"][0]
    rep = mc.replay_certificate(v)
    assert rep["reproduced"] and rep["code"] == v["code"]
    # a truncated schedule must NOT claim reproduction
    broken = dict(v, schedule=v["schedule"][:1])
    assert not mc.replay_certificate(broken)["reproduced"]


@pytest.mark.slow
def test_sweep_expectation_matrix():
    # per-cell coverage rides tier-1 (clean modes + every seeded
    # acceptance test above); the whole-matrix sweep is the slow tier
    s = mc.run_mc_sweep()
    assert s["ok"], [(r["family"], r["mode"], r["ok"])
                     for r in s["runs"]]


# ---------------------------------------------------------------------------
# CLI exit codes (`python -m jepsen_tpu.analyze --mc`)
# ---------------------------------------------------------------------------

def test_cli_seeded_pair_exits_1_and_replay_round_trips(
        tmp_path, capsys):
    # the seeded half stays a real subprocess: it pins the actual
    # process exit code of `python -m jepsen_tpu.analyze --mc`
    p = run_cli("--mc", "--mc-family", "lock", "--mc-mode",
                "volatile", "--json")
    assert p.returncode == 1, p.stderr
    out = json.loads(p.stdout)
    assert out["ok"] is False
    cert = out["runs"][0]["violations"][0]
    cert_path = tmp_path / "cert.json"
    cert_path.write_text(json.dumps(cert))
    rc, rep_out = run_cli_inproc(capsys, "--mc", "--replay",
                                 str(cert_path))
    assert rc == 0, rep_out
    assert "reproduced" in rep_out


def test_cli_clean_pair_exits_0(capsys):
    rc, out = run_cli_inproc(
        capsys, "--mc", "--mc-family", "lock", "--mc-mode", "clean")
    assert rc == 0, out


def test_cli_bad_args(capsys):
    # lock has no split-brain mode: the pair matches nothing
    rc, _ = run_cli_inproc(capsys, "--mc", "--mc-family", "lock",
                           "--mc-mode", "split-brain")
    assert rc == 254
    rc, _ = run_cli_inproc(capsys, "--mc", "--replay",
                           "/nonexistent/cert.json")
    assert rc == 254


def test_cli_explain_prints_scope_plan(capsys):
    rc, out = run_cli_inproc(capsys, "--mc", "--explain", "--json")
    assert rc == 0
    plan = json.loads(out)["mc_plan"]
    assert {(b["family"], b["mode"]) for b in plan} == {
        (f, m) for f in mc.FAMILIES for m in mc.MODES[f]}


@pytest.mark.slow
def test_cli_full_sweep_exits_0():
    p = run_cli("--mc", "--json")
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    out = json.loads(p.stdout)
    assert out["ok"] is True
    assert len(out["runs"]) == sum(len(m) for m in mc.MODES.values())


# ---------------------------------------------------------------------------
# full matrix at widened scope (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("family", mc.FAMILIES)
def test_slow_clean_matrix_deeper(family):
    scope = mc.scope_from_args(family, "clean", max_events=7)
    r = mc.run_mc(family, "clean", scope=scope, dpor=True)
    assert r["ok"], r["violations"][:1]
    assert r["explored"]["complete"]


@pytest.mark.slow
@pytest.mark.parametrize("family,mode", [
    (f, m) for f in mc.FAMILIES for m in mc.MODES[f] if m != "clean"])
def test_slow_seeded_matrix_deeper(family, mode):
    scope = mc.scope_from_args(family, mode, max_events=7)
    r = mc.run_mc(family, mode, scope=scope, dpor=True,
                  shrink=False, confirm=False)
    assert not r["ok"]
    assert all(v["replayed"] for v in r["violations"])


@pytest.mark.slow
def test_slow_dpor_soundness_deeper():
    scope = mc.scope_from_args("replicated", "volatile", max_events=7)
    on = mc.explore("replicated", "volatile", scope, dpor=True,
                    max_violations=100_000)
    off = mc.explore("replicated", "volatile", scope, dpor=False,
                     max_violations=100_000)
    assert violation_set(on) == violation_set(off)
