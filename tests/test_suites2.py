"""Second suite tranche: mongodb (replica sets + write-concern matrix),
disque (RESP client + cluster meet), chronos (mesos + schedule)."""

import json
import socket
import threading

from jepsen_tpu.util import AbortableBarrier

from test_suites import dummy_test


# --- mongodb --------------------------------------------------------------


def _rs_status_ok(nodes):
    return json.dumps({"members": [
        {"name": f"{n}:27017", "stateStr":
         "PRIMARY" if i == 0 else "SECONDARY"}
        for i, n in enumerate(nodes)]})


def test_mongo_replica_set_config():
    from jepsen_tpu.suites import mongodb

    cfg = mongodb.target_replica_set_config(
        {"nodes": ["n1", "n2", "n3"]})
    assert cfg["_id"] == "jepsen"
    assert cfg["members"][2] == {"_id": 2, "host": "n3:27017"}


def test_mongo_db_setup_commands():
    from jepsen_tpu.suites import mongodb

    nodes = ["n1", "n2", "n3"]
    test, r = dummy_test(responses={
        "rs.status()": (0, _rs_status_ok(nodes), ""),
        "pkgin list": (0, "", "")})
    test["barrier"] = "no-barrier"
    mongodb.db("3.0.4").setup(test, "n1")
    cmds = [e[2] for e in r.log if e[0] == "n1" and e[1] == "exec"]
    assert any("pkgin -y install mongodb-3.0.4" in c for c in cmds)
    assert any("replSetName: jepsen" in c for c in cmds)
    assert any("svcadm enable -r mongodb" in c for c in cmds)
    assert any("rs.initiate" in c for c in cmds)  # n1 is jepsen primary


def test_mongo_await_join_parses_members():
    from jepsen_tpu.suites import mongodb
    from jepsen_tpu.control import DummyRemote, Session

    r = DummyRemote({"rs.status()": (0, _rs_status_ok(["n1", "n2"]), "")})
    sess = Session(node="n1", remote=r)
    mongodb.await_join({"nodes": ["n1", "n2"]}, sess, timeout_s=2)
    mongodb.await_primary(sess, timeout_s=2)


def test_mongo_workloads_and_write_concern_matrix():
    from jepsen_tpu.suites import mongodb

    for wc in mongodb.WRITE_CONCERNS:
        t = mongodb.doc_cas_test({"write_concern": wc,
                                  "nodes": ["n1"], "time_limit": 1})
        assert wc in t["name"]
        assert isinstance(t["client"], mongodb.DocumentCASClient)
    t = mongodb.doc_cas_test({"no_reads": True, "nodes": ["n1"]})
    assert "no-read" in t["name"]
    t = mongodb.transfer_test({"nodes": ["n1"]})
    assert isinstance(t["client"], mongodb.TransferClient)


# --- disque ---------------------------------------------------------------


def test_disque_db_commands():
    from jepsen_tpu.suites import disque

    test, r = dummy_test(responses={
        "stat /opt/disque": (1, "", "no"),
        "getent ahosts n1": (0, "10.0.0.1 STREAM n1\n", ""),
        "cluster meet": (0, "OK", "")})
    test["barrier"] = "no-barrier"
    disque.db("abc123").setup(test, "n2")
    cmds = [e[2] for e in r.log if e[0] == "n2" and e[1] == "exec"]
    assert any("git clone" in c for c in cmds)
    assert any("git reset --hard abc123" in c for c in cmds)
    assert any("start-stop-daemon --start" in c and "disque-server" in c
               for c in cmds)
    assert any("cluster meet 10.0.0.1 7711" in c for c in cmds)


class FakeDisque(threading.Thread):
    """Tiny RESP server: ADDJOB queues, GETJOB pops, ACKJOB acks."""

    def __init__(self):
        super().__init__(daemon=True)
        self.server = socket.create_server(("127.0.0.1", 0))
        self.port = self.server.getsockname()[1]
        self.jobs: list = []
        self.acked: list = []

    def run(self):
        while True:
            try:
                conn, _ = self.server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        buf = conn.makefile("rb")
        while True:
            line = buf.readline()
            if not line:
                return
            n = int(line[1:])
            args = []
            for _ in range(n):
                ln = int(buf.readline()[1:])
                args.append(buf.read(ln + 2)[:-2].decode())
            cmd = args[0].upper()
            if cmd == "ADDJOB":
                self.jobs.append(args[2])
                conn.sendall(b"+D-jobid1\r\n")
            elif cmd == "GETJOB":
                if not self.jobs:
                    conn.sendall(b"*-1\r\n")
                else:
                    body = self.jobs.pop(0)
                    reply = (f"*1\r\n*3\r\n$6\r\njepsen\r\n$5\r\njob-1"
                             f"\r\n${len(body)}\r\n{body}\r\n")
                    conn.sendall(reply.encode())
            elif cmd == "ACKJOB":
                self.acked.append(args[1])
                conn.sendall(b":1\r\n")
            else:
                conn.sendall(b"-ERR unknown\r\n")


def test_disque_client_roundtrip():
    from dataclasses import dataclass as dc

    from jepsen_tpu.suites import disque

    srv = FakeDisque()
    srv.start()

    @dc
    class Op:
        f: str
        type: str = "invoke"
        value: object = None
        process: int = 0

    c = disque.DisqueClient().open({"nodes": ["127.0.0.1"]}, "127.0.0.1")
    import jepsen_tpu.suites.disque as dmod

    orig = dmod.PORT
    try:
        dmod.PORT = srv.port
        c.conn = dmod.RespConn("127.0.0.1", srv.port)
        out = c.invoke({}, Op(f="enqueue", value=42))
        assert out.type == "ok"
        out = c.invoke({}, Op(f="dequeue"))
        assert out.type == "ok" and out.value == 42
        assert srv.acked == ["job-1"]
        out = c.invoke({}, Op(f="dequeue"))
        assert out.type == "fail"  # empty queue
        c.invoke({}, Op(f="enqueue", value=7))
        out = c.invoke({}, Op(f="drain"))
        # the ok value is the drained ELEMENT LIST — what
        # expand_queue_drain_ops turns into dequeue invoke/ok pairs
        # (a bare count crashed the total-queue checker the first time
        # this client ran against a live server)
        assert out.type == "ok" and out.value == [7]
    finally:
        dmod.PORT = orig
        c.close({})
        srv.server.close()


# --- chronos --------------------------------------------------------------


def test_chronos_job_json_and_interval():
    from jepsen_tpu.suites import chronos

    job = {"name": "3", "start": 0.0, "count": 5, "duration": 2,
           "epsilon": 11, "interval": 30}
    assert chronos.interval_str(job) == "R5/1970-01-01T00:00:00Z/PT30S"
    j = chronos.job_json(job)
    assert j["epsilon"] == "PT11S"
    assert "sleep 2" in j["command"]
    assert chronos.JOB_DIR in j["command"]


def test_chronos_parse_run_file():
    from jepsen_tpu.suites import chronos

    text = "7\n2026-07-29T10:00:00,500000+00:00\n" \
           "2026-07-29T10:00:02.500000+00:00\n"
    run = chronos.parse_run_file("n1", text)
    assert run["name"] == "7"
    assert run["end"] - run["start"] == 2.0


def test_chronos_db_commands():
    from jepsen_tpu.suites import chronos

    test, r = dummy_test(responses={
        "stat /etc/apt/sources.list.d/mesosphere.list": (1, "", "no"),
        "service chronos status": (1, "", "not running"),
        "dpkg-query": (1, "", "")})
    chronos.db().setup(test, "n1")
    cmds = [e[2] for e in r.log if e[0] == "n1" and e[1] == "exec"]
    assert any("repos.mesosphere.io" in c for c in cmds)
    assert any("mesos-master" in c and "--quorum=2" in c for c in cmds)
    assert any("mesos-slave" in c and "zk://n1:2181,n2:2181,n3:2181/mesos"
               in c for c in cmds)
    assert any("schedule_horizon" in c for c in cmds)
    assert any("service chronos start" in c for c in cmds)


def test_chronos_masters_subset():
    from jepsen_tpu.suites import chronos

    test = {"nodes": ["n5", "n1", "n3", "n2", "n4"]}
    assert chronos.masters(test) == ["n1", "n2", "n3"]


def test_chronos_add_job_gen_non_overlapping():
    from jepsen_tpu.suites import chronos
    from jepsen_tpu.checker.schedule import EPSILON_FORGIVENESS

    g = chronos.AddJobGen()
    for _ in range(20):
        op = g.op({}, 0)
        v = op["value"]
        assert v["interval"] > v["duration"] + v["epsilon"] + \
            EPSILON_FORGIVENESS
