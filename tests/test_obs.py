"""Flight recorder (jepsen_tpu/obs/): span tracing + metrics registry.

What must hold: spans nest and survive threads, ring buffers stay
bounded, the Chrome-trace export is schema-valid (Perfetto-loadable),
``/metrics`` on both the web UI and the stream service speaks
Prometheus text, ``/api/stats`` is a sane JSON snapshot, tracing OFF
costs ~nothing, and an instrumented end-to-end streamed run / traced
core.run actually produces the spans and files the docs promise.
"""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from jepsen_tpu import obs
from jepsen_tpu.history import info_op, invoke_op, ok_op
from jepsen_tpu.models import register
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs.report import phase_table, render_report
from jepsen_tpu.obs.trace import SpanRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tracing():
    """Tracing forced on, in a throwaway run buffer."""
    obs.enable(True)
    try:
        yield
    finally:
        obs.enable(None)
        obs.set_run(None)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_and_attrs(tracing):
    run = "t-nest"
    obs.drop_recorder(run)
    with obs.span("outer", cat="check", run=run):
        with obs.span("inner", cat="fold", run=run, rows=7):
            time.sleep(0.002)
    spans = {s["name"]: s for s in obs.recorder(run).spans()}
    assert set(spans) == {"outer", "inner"}
    assert spans["inner"]["args"] == {"rows": 7}
    assert spans["inner"]["dur"] <= spans["outer"]["dur"]
    # the inner span lies inside the outer's interval
    assert spans["outer"]["ts"] <= spans["inner"]["ts"]
    assert spans["inner"]["ts"] + spans["inner"]["dur"] \
        <= spans["outer"]["ts"] + spans["outer"]["dur"] + 1
    obs.drop_recorder(run)


def test_span_records_error_attr(tracing):
    run = "t-err"
    obs.drop_recorder(run)
    with pytest.raises(ValueError):
        with obs.span("boom", run=run):
            raise ValueError("x")
    (s,) = obs.recorder(run).spans()
    assert s["args"]["error"] == "ValueError"
    obs.drop_recorder(run)


def test_span_thread_safety(tracing):
    run = "t-threads"
    obs.drop_recorder(run)
    n_threads, per = 8, 200

    def work(i):
        for j in range(per):
            with obs.span(f"w{i}", cat="op", run=run, j=j):
                pass

    ts = [threading.Thread(target=work, args=(i,))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    spans = obs.recorder(run).spans()
    assert len(spans) == n_threads * per
    # every thread's spans landed under its own tid
    assert len({s["tid"] for s in spans}) == n_threads
    obs.drop_recorder(run)


def test_ring_buffer_is_bounded():
    rec = SpanRecorder("t-ring", cap=100)
    t0 = time.perf_counter()
    for i in range(250):
        rec.record(f"s{i}", "op", t0, t0 + 1e-6)
    assert len(rec) == 100
    assert rec.dropped == 150
    # the survivors are the NEWEST spans
    assert rec.spans()[-1]["name"] == "s249"
    assert rec.spans()[0]["name"] == "s150"


def test_traced_decorator(tracing):
    obs.set_run(None)
    obs.recorder(None).clear()

    @obs.traced("myfn", cat="host")
    def fn(x):
        return x * 2

    assert fn(21) == 42
    names = [s["name"] for s in obs.recorder(None).spans()]
    assert "myfn" in names


def test_tracing_off_is_near_free():
    obs.enable(False)
    try:
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            with obs.span("hot", cat="op", rows=1):
                pass
        dt = time.perf_counter() - t0
        # the off-path is one flag check + a shared no-op object; even
        # a loaded CI box does 50k in well under a second
        assert dt < 1.0, f"disabled tracing cost {dt:.3f}s for {n} spans"
    finally:
        obs.enable(None)


def test_tracing_off_enabled_check_allocates_nothing():
    """The per-op hot path gates on ``obs.enabled()`` (core.py builds
    the span name/attrs only inside the gate), so the OFF check itself
    must do zero allocation per call — the env knob is read once and
    cached, not ``os.environ.get(...).strip().lower()``ed per op."""
    import tracemalloc

    obs.enable(False)
    try:
        obs.enabled()  # prime any lazy caches outside the window
        tracemalloc.start()
        for _ in range(10_000):
            obs.enabled()
        _cur, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # tracemalloc's own bookkeeping shows up as a few hundred
        # bytes; 10k string allocations would be hundreds of KB
        assert peak < 8_192, f"enabled() allocated {peak}B over 10k " \
                             f"off-mode calls"
    finally:
        obs.enable(None)


def test_telemetry_off_is_near_free():
    """The telemetry knob's off mode (same contract as tracing off):
    the per-drive gate is one cached flag check, no env lookup, no
    allocation — off-mode kernels are the exact pre-telemetry builds,
    so the flag check IS the entire off-mode cost."""
    import tracemalloc

    from jepsen_tpu.obs import telemetry as tele

    tele.enable(False)
    try:
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            tele.enabled()
        dt = time.perf_counter() - t0
        assert dt < 1.0, f"disabled telemetry cost {dt:.3f}s for " \
                         f"{n} checks"
        tele.enabled()
        tracemalloc.start()
        for _ in range(10_000):
            tele.enabled()
        _cur, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert peak < 8_192, f"telemetry.enabled() allocated " \
                             f"{peak}B over 10k off-mode calls"
        # the off-mode accounting helpers are no-ops, not raisers
        tele.record_device_seconds(0.0)
        tele.record_transfer(0)
    finally:
        tele.enable(None)


def test_chrome_trace_schema(tracing):
    run = "t-schema"
    obs.drop_recorder(run)
    with obs.span("a", cat="check", run=run):
        with obs.span("b", cat="fold", run=run):
            pass
    tr = obs.chrome_trace(run)
    assert tr["displayTimeUnit"] == "ms"
    evs = tr["traceEvents"]
    assert isinstance(evs, list) and evs
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"a", "b"}
    for e in xs:
        # the Perfetto "complete event" contract
        assert isinstance(e["name"], str)
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], int)
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["args"], dict)
    metas = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "thread_name" for e in metas)
    json.dumps(tr)  # serializes clean
    obs.drop_recorder(run)


def test_write_trace_roundtrip(tracing, tmp_path):
    run = "t-write"
    obs.drop_recorder(run)
    with obs.span("x", run=run):
        pass
    p = obs.write_trace(str(tmp_path / "trace.json"), run=run)
    with open(p) as f:
        tr = json.load(f)
    assert any(e["name"] == "x" for e in tr["traceEvents"])
    obs.drop_recorder(run)


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


#: one Prometheus sample line: name{labels} value
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+Inf-]+$")


def test_prometheus_render_is_well_formed():
    reg = obs_metrics.Registry()
    c = reg.counter("t_ops_total", "ops", ("type",))
    c.inc(type="ok")
    c.inc(3, type="fail")
    g = reg.gauge("t_open", "open things")
    g.set(2)
    g.dec()
    h = reg.histogram("t_secs", "seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    text = reg.render()
    assert text.endswith("\n")
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        assert _PROM_LINE.match(ln), f"bad exposition line: {ln!r}"
    assert 't_ops_total{type="fail"} 3' in text
    assert "t_open 1" in text
    assert 't_secs_bucket{le="+Inf"} 2' in text
    assert "t_secs_count 2" in text
    # HELP/TYPE headers precede each family
    assert "# TYPE t_ops_total counter" in text
    assert "# TYPE t_open gauge" in text
    assert "# TYPE t_secs histogram" in text


def test_counter_label_discipline():
    reg = obs_metrics.Registry()
    c = reg.counter("t_x_total", "x", ("kind",))
    with pytest.raises(ValueError):
        c.inc()  # missing label
    with pytest.raises(ValueError):
        reg.gauge("t_x_total", "x", ("kind",))  # type clash


def test_snapshot_and_derived_ratios():
    reg = obs_metrics.Registry()
    vc = reg.counter("jtpu_verdict_cache_total", "vc", ("event",))
    for _ in range(3):
        vc.inc(event="hit")
    vc.inc(event="miss")
    b = reg.counter("jtpu_bucket_ops_total", "b", ("kind",))
    b.inc(65, kind="useful")
    b.inc(100, kind="padded")
    snap = reg.snapshot()
    assert snap["jtpu_verdict_cache_total"]["values"]["hit"] == 3
    d = snap["derived"]
    assert d["verdict_cache_hit_ratio"] == 0.75
    assert d["bucket_padding_efficiency"] == 0.65
    json.dumps(snap)


def test_reset_zeroes_in_place_keeping_handles():
    reg = obs_metrics.Registry()
    c = reg.counter("t_keep_total", "x")
    h = reg.histogram("t_keep_secs", "y")
    c.inc(5)
    h.observe(0.5)
    reg.reset()
    assert c.total() == 0
    # the ORIGINAL handle keeps feeding the registry after reset —
    # instrumented modules bind handles once at import
    c.inc()
    h.observe(1.0)
    assert reg.get("t_keep_total") is c
    assert "t_keep_total 1" in reg.render()
    assert "t_keep_secs_count 1" in reg.render()


def test_open_runs_gauge_counts_runs_not_header_lines():
    from jepsen_tpu.stream.service import StreamService

    g = obs_metrics.REGISTRY.gauge("jtpu_stream_runs_open", "")
    base = g.value()
    svc = StreamService(model=register(0))
    out: list = []
    svc.open_run("r1", register(0))
    svc.open_run("r1", register(0))  # reconnect replay of the header
    assert g.value() == base + 1
    svc.end_run("r1", out.append)
    assert g.value() == base


def test_service_drops_run_recorder_on_finalize(tracing):
    from jepsen_tpu.obs import trace as trace_mod
    from jepsen_tpu.stream.service import StreamService

    svc = StreamService(model=register(0))
    svc.open_run("r-drop", register(0))
    with obs.span("x", run="r-drop"):
        pass
    assert "r-drop" in trace_mod._recorders
    svc.end_run("r-drop", lambda d: None)
    # a finished run must not pin its ring buffer in a long-lived
    # multiplexing service
    assert "r-drop" not in trace_mod._recorders


def test_registry_declares_standing_taxonomy():
    # the acceptance set: cache-hit-ratio inputs, fold/fork, padding
    # efficiency, watchdog — declared up front so a fresh scrape shows
    # the whole taxonomy
    text = obs_metrics.render()
    for name in ("jtpu_verdict_cache_total", "jtpu_kernel_cache_total",
                 "jtpu_stream_segments_folded_total",
                 "jtpu_stream_forks_total", "jtpu_bucket_ops_total",
                 "jtpu_watchdog_total", "jtpu_shed_total",
                 "jtpu_backoff_exhausted_total",
                 "jtpu_stream_runs_open", "jtpu_ops_total"):
        assert f"# TYPE {name} " in text, name


# ---------------------------------------------------------------------------
# /metrics + /api/stats endpoints
# ---------------------------------------------------------------------------


def test_web_metrics_and_stats_endpoints(tmp_path):
    from jepsen_tpu import web

    srv = web.make_server("127.0.0.1", 0, base=str(tmp_path))
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            text = r.read().decode()
        assert "# TYPE jtpu_verdict_cache_total counter" in text
        assert "# TYPE jtpu_stream_runs_open gauge" in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/stats") as r:
            assert r.status == 200
            snap = json.loads(r.read().decode())
        assert "derived" in snap
        assert snap["jtpu_ops_total"]["type"] == "counter"
    finally:
        srv.shutdown()


def test_stream_service_tcp_metrics_scrape():
    from jepsen_tpu.stream.service import make_server

    srv = make_server("127.0.0.1", 0, model=register(0))
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=5) as s:
            # realistic scraper request: extra headers must be drained
            # before the reply, or the close-with-unread-bytes RSTs
            s.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n"
                      b"Accept: */*\r\nUser-Agent: prom\r\n\r\n")
            buf = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        head, _, body = buf.partition(b"\r\n\r\n")
        assert head.startswith(b"HTTP/1.0 200")
        assert b"text/plain" in head
        assert b"# TYPE jtpu_stream_runs_open gauge" in body
        # the same port still speaks the JSONL run protocol
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=5) as s:
            f = s.makefile("rw")
            f.write(json.dumps({"run": "r1", "model": "register",
                                "init": 0}) + "\n")
            f.write(json.dumps({"run": "r1", "op": {
                "process": 0, "type": "invoke", "f": "write",
                "value": 1}}) + "\n")
            f.write(json.dumps({"run": "r1", "op": {
                "process": 0, "type": "ok", "f": "write",
                "value": 1}}) + "\n")
            f.write(json.dumps({"run": "r1", "end": True}) + "\n")
            f.flush()
            s.shutdown(socket.SHUT_WR)
            final = None
            for line in f:
                d = json.loads(line)
                if "final" in d:
                    final = d
            assert final is not None
            assert final["final"]["valid"] is True
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# end-to-end: streamed run + traced core.run
# ---------------------------------------------------------------------------


def _crashy_register_history():
    """A register history with one real quiescence cut (-> a fold), a
    crash, and enough post-crash completions at pseudo-quiescent
    points to trigger the bounded :info lookahead (-> a fork)."""
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         # fresh invoke with nothing pending: closes the segment
         invoke_op(0, "write", 2), ok_op(0, "write", 2),
         invoke_op(1, "write", 3), info_op(1, "write", 3)]  # crash
    v = 10
    for i in range(2, 6):  # sequential post-crash oks (pending==0)
        h += [invoke_op(i, "write", v), ok_op(i, "write", v)]
        v += 1
    return h


def test_streamed_run_emits_fold_and_fork_spans(tracing):
    from jepsen_tpu.stream import StreamChecker

    run = "t-stream-spans"
    obs.drop_recorder(run)
    folded0 = obs_metrics.REGISTRY.counter(
        "jtpu_stream_segments_folded_total", "", ("route",)).total()
    forks0 = obs_metrics.REGISTRY.counter(
        "jtpu_stream_forks_total", "", ("outcome",)).value(
        outcome="spawned")
    sc = StreamChecker(register(0), info_lookahead=2, run_id=run)
    for op in _crashy_register_history():
        sc.ingest(op)
    res = sc.finalize()
    assert res["valid"] is True
    names = {s["name"] for s in obs.recorder(run).spans()}
    assert "stream.fold" in names, names
    assert "stream.fork" in names, names
    assert "stream.finalize" in names
    assert obs_metrics.REGISTRY.get(
        "jtpu_stream_segments_folded_total").total() > folded0
    assert obs_metrics.REGISTRY.get(
        "jtpu_stream_forks_total").value(outcome="spawned") > forks0
    obs.drop_recorder(run)


def _cas_run_test(state, store_base, **over):
    import random

    from jepsen_tpu import fixtures, generator as gen
    from jepsen_tpu.checker import linearizable as lin
    from jepsen_tpu.models import cas_register

    return fixtures.noop_test() | {
        "name": "obs-traced", "store_base": store_base,
        "db": fixtures.atom_db(state),
        "client": fixtures.atom_client(state),
        "model": cas_register(0),
        "checker": lin.linearizable(),
        "generator": gen.clients(
            gen.limit(30, gen.mix([
                {"type": "invoke", "f": "read", "value": None},
                lambda t, p: {"type": "invoke", "f": "write",
                              "value": random.randrange(5)}]))),
        "concurrency": 3,
    } | over


def test_traced_core_run_writes_trace_json(tracing, tmp_path):
    from jepsen_tpu import core, fixtures

    state = fixtures.AtomRegister()
    test = core.run(_cas_run_test(state, str(tmp_path)))
    assert test["results"]["valid"] is True
    run_dir = os.path.join(str(tmp_path), "obs-traced",
                           test["start_time"])
    p = os.path.join(run_dir, "trace.json")
    assert os.path.isfile(p), os.listdir(str(tmp_path))
    with open(p) as f:
        tr = json.load(f)
    xs = [e for e in tr["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in xs}
    # the whole vertical shows up: run envelope, phases, worker ops
    assert "run" in names
    assert "workload" in names
    assert "analyze" in names
    assert any(n.startswith("op:") for n in names)
    # the run envelope accounts for (almost) the whole trace extent
    run_span = next(e for e in xs if e["name"] == "run")
    t0 = min(e["ts"] for e in xs)
    t1 = max(e["ts"] + e["dur"] for e in xs)
    assert run_span["dur"] >= 0.90 * (t1 - t0)
    # always-on phase accounting rode along (campaign cells use it)
    assert set(test["phase_s"]) >= {"setup", "workload", "check"}


def test_phase_table_report(tracing, tmp_path):
    run = "t-report"
    obs.drop_recorder(run)
    with obs.span("run", cat="run", run=run):
        with obs.span("prep", cat="host", run=run):
            time.sleep(0.004)
        with obs.span("dispatch", cat="device", run=run):
            time.sleep(0.008)
    p = obs.write_trace(str(tmp_path / "trace.json"), run=run)
    rep = phase_table(json.load(open(p)))
    cats = {r["cat"]: r for r in rep["phases"]}
    assert {"run", "host", "device"} <= set(cats)
    assert cats["device"]["busy_s"] > cats["host"]["busy_s"] > 0
    # the run envelope is excluded from busy/idle accounting
    assert rep["idle_s"] < rep["wall_s"]
    assert rep["wall_s"] >= cats["device"]["busy_s"]
    assert "device" in render_report(rep)
    # a trace with NO telemetry spans keeps the pre-telemetry report
    # shape — no section in the dict, none in the rendering
    assert "telemetry" not in rep
    assert "device search telemetry" not in render_report(rep)
    obs.drop_recorder(run)


def test_phase_table_telemetry_section(tracing, tmp_path):
    """Traces recorded with device telemetry grow the per-level table
    + predicted-vs-observed prune row (the committed BENCH_trace_1k
    recording is the canonical instance)."""
    p = os.path.join(REPO, "BENCH_trace_1k.json")
    rep = phase_table(json.load(open(p)))
    t = rep["telemetry"]
    rows = t["levels"]
    assert rows and all(r["occupancy"] > 0 for r in rows)
    assert rows[0]["level"] == 0
    assert {"mask_kill_pct", "dedup_fold_pct", "busy_s"} \
        <= set(rows[0])
    s = t["search"]
    assert s["observed_prune_ratio"] is not None
    assert s["prune_ratio_delta"] is not None
    assert t["compiles"]["count"] >= 1
    assert t["transfer_bytes"] > 0
    txt = render_report(rep)
    assert "device search telemetry" in txt
    assert "prune ratio: observed" in txt
    assert "mask-kill%" in txt
    # the per-level table elides its middle rather than printing
    # hundreds of rows
    if len(rows) > 24:
        assert "elided" in txt


def test_trace_report_tool_smoke(tracing, tmp_path):
    run = "t-tool"
    obs.drop_recorder(run)
    with obs.span("fold", cat="fold", run=run):
        time.sleep(0.002)
    p = obs.write_trace(str(tmp_path / "trace.json"), run=run)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         p, "--json"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["phases"][0]["cat"] == "fold"
    obs.drop_recorder(run)


def test_obs_cli_trace_resolves_store_run(tracing, tmp_path):
    run = "t-cli"
    obs.drop_recorder(run)
    with obs.span("x", run=run):
        pass
    d = tmp_path / "mytest" / "20260101T000000"
    obs.write_trace(str(d / "trace.json"), run=run)
    from jepsen_tpu.obs.__main__ import resolve_trace

    assert resolve_trace("mytest/20260101T000000",
                         str(tmp_path)).endswith("trace.json")
    with pytest.raises(FileNotFoundError):
        resolve_trace("nope/run", str(tmp_path))
    obs.drop_recorder(run)


# ---------------------------------------------------------------------------
# log context + campaign tooltips
# ---------------------------------------------------------------------------


def test_log_ctx_stamps_fields(caplog):
    import logging

    lg = logging.getLogger("jepsen")
    with caplog.at_level(logging.WARNING, logger="jepsen"):
        obs.log_ctx(lg, run_id="r9", conn="1.2.3.4:5").warning(
            "line failed: %s", "boom")
    assert "[run_id=r9 conn=1.2.3.4:5] line failed: boom" \
        in caplog.text
    # None-valued fields are omitted, not rendered as "None"
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="jepsen"):
        obs.log_ctx(lg, run_id="r1", conn=None).warning("x")
    assert "[run_id=r1] x" in caplog.text


def test_campaign_grid_shows_phase_tooltips(tmp_path):
    from jepsen_tpu import web

    d = tmp_path / "campaigns" / "c1"
    os.makedirs(d)
    with open(d / "campaign.json", "w") as f:
        json.dump({"cells": [{
            "family": "kv", "nemesis": "kill-restart", "status": "ok",
            "valid": True,
            "phases": {"setup": 1.2, "workload": 8.0, "nemesis": 0.4,
                       "check": 0.6}}],
            "summary": {"ok": 1}}, f)
    page = web.campaign_html(str(tmp_path), "c1")
    assert 'title="setup 1.2s' in page
    assert "nemesis 0.4s" in page
    # the index page carries the fleet-health strip polling /api/stats
    idx = web.campaigns_html(str(tmp_path))
    assert "/api/stats" in idx


def test_phase_times_from_history():
    from dataclasses import replace

    from jepsen_tpu.history import Op
    from jepsen_tpu.live.campaign import _phase_times

    def nem(f, t):
        return Op(process="nemesis", type="info", f=f, value=None,
                  time=int(t * 1e9))

    test = {"phase_s": {"setup": 2.0, "workload": 9.0, "check": 1.0},
            "history": [nem("kill", 1.0), nem("kill", 1.5),
                        nem("restart", 2.0), nem("restart", 2.25)]}
    ph = _phase_times(test, "kill-restart")
    assert ph["setup"] == 2.0
    assert ph["workload"] == 9.0
    assert ph["check"] == 1.0
    assert ph["nemesis"] == pytest.approx(0.5)
    assert ph["heal"] == pytest.approx(0.25)
