"""Dirty-read checker family (checker/dirty.py) + galera/elasticsearch
suite wiring tests (dummy-remote command shapes)."""

from jepsen_tpu.checker import dirty
from jepsen_tpu.history import fail_op, info_op, invoke_op, ok_op

from test_suites import dummy_test


# --- galera-flavor dirty_reads --------------------------------------------


def test_dirty_reads_clean():
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         invoke_op(1, "read"), ok_op(1, "read", [1, 1, 1]),
         invoke_op(0, "write", 2), fail_op(0, "write", 2),
         invoke_op(1, "read"), ok_op(1, "read", [1, 1, 1])]
    out = dirty.dirty_reads().check({}, h)
    assert out["valid"] is True
    assert out["dirty_reads"] == []
    assert out["inconsistent_reads"] == []


def test_dirty_reads_catches_failed_write_visible():
    h = [invoke_op(0, "write", 7), fail_op(0, "write", 7),
         invoke_op(1, "read"), ok_op(1, "read", [7, 7, 7])]
    out = dirty.dirty_reads().check({}, h)
    assert out["valid"] is False
    assert out["dirty_reads"] == [[7, 7, 7]]


def test_dirty_reads_inconsistent_but_not_dirty():
    h = [invoke_op(0, "write", 1), ok_op(0, "write", 1),
         invoke_op(0, "write", 2), ok_op(0, "write", 2),
         invoke_op(1, "read"), ok_op(1, "read", [1, 2, 2])]
    out = dirty.dirty_reads().check({}, h)
    assert out["valid"] is True  # non-atomic, but no failed txn seen
    assert out["inconsistent_reads"] == [[1, 2, 2]]


# --- elasticsearch-flavor strong_dirty_read -------------------------------


def _es_history(strong_sets, reads_ok=(), writes_ok=()):
    h = []
    for v in writes_ok:
        h += [invoke_op(0, "write", v), ok_op(0, "write", v)]
    for v in reads_ok:
        h += [invoke_op(1, "read", v), ok_op(1, "read", v)]
    for i, s in enumerate(strong_sets):
        h += [invoke_op(i, "strong-read"),
              ok_op(i, "strong-read", sorted(s))]
    return h


def test_strong_dirty_read_clean():
    h = _es_history([{1, 2}, {1, 2}], reads_ok=[1], writes_ok=[1, 2])
    out = dirty.strong_dirty_read().check({}, h)
    assert out["valid"] is True
    assert out["nodes_agree"] is True


def test_strong_dirty_read_detects_dirty():
    # read 9 succeeded but 9 is absent from every strong read
    h = _es_history([{1}, {1}], reads_ok=[9], writes_ok=[1])
    out = dirty.strong_dirty_read().check({}, h)
    assert out["valid"] is False
    assert out["dirty"] == [9]


def test_strong_dirty_read_detects_lost():
    h = _es_history([{1}, {1}], writes_ok=[1, 5])
    out = dirty.strong_dirty_read().check({}, h)
    assert out["valid"] is False
    assert out["lost"] == [5]


def test_strong_dirty_read_divergence():
    h = _es_history([{1, 2}, {1}], writes_ok=[1])
    out = dirty.strong_dirty_read().check({}, h)
    assert out["valid"] is False
    assert out["nodes_agree"] is False
    assert out["not_on_all"] == [2]


def test_strong_dirty_read_no_strong_reads():
    out = dirty.strong_dirty_read().check({}, [])
    assert out["valid"] == "unknown"


# --- galera suite ---------------------------------------------------------


def test_galera_db_commands():
    from jepsen_tpu.suites import galera
    from jepsen_tpu.util import AbortableBarrier

    test, r = dummy_test(nodes=("n1",), responses={
        "stat /": (1, "", "no")})
    test["barrier"] = AbortableBarrier(1)
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        galera.db().setup(test, "n1")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("debconf-set-selections" in c for c in cmds)
    assert any("wsrep_cluster_address=gcomm://n1" in c for c in cmds)
    assert any("service mysql start --wsrep-new-cluster" in c
               for c in cmds)
    assert any("GRANT ALL PRIVILEGES" in c for c in cmds)


def test_galera_dirty_reads_test_map():
    from jepsen_tpu.suites import galera

    t = galera.galera_test({"workload": "dirty-reads",
                            "nodes": ["n1", "n2", "n3"]})
    assert isinstance(t["client"], galera.DirtyReadsClient)
    g = galera.dirty_reads_generator()
    from jepsen_tpu import generator as gen

    ops = [gen.gen_op(g, t, 0) for _ in range(20)]
    writes = [o["value"] for o in ops if o["f"] == "write"]
    assert writes == sorted(writes)  # unique ascending write values
    assert len(set(writes)) == len(writes)


# --- elasticsearch suite --------------------------------------------------


def test_es_config_and_db_commands():
    from jepsen_tpu.suites import elasticsearch as es

    test, r = dummy_test(responses={"stat /": (1, "", "no"),
                                    "ls -A": (0, "elasticsearch-5.0.0\n", ""),
                                    "dirname": (0, "/opt", ""),
                                    "id -u": (1, "", "no such user")})
    yml = es.config_yml(test, "n2")
    assert "minimum_master_nodes: 2" in yml
    assert '"n1", "n2", "n3"' in yml

    db = es.db()
    db.wait_healthy = lambda *a, **kw: None
    db.setup(test, "n1")
    cmds = [e[2] for e in r.log if e[0] == "n1" and e[1] == "exec"]
    assert any("vm.max_map_count=262144" in c for c in cmds)
    assert any("start-stop-daemon --start" in c and "elasticsearch" in c
               for c in cmds)


def test_es_rw_gen():
    from jepsen_tpu import generator as gen
    from jepsen_tpu.suites import elasticsearch as es

    g = es.RWGen(writers=1)
    test = {"nodes": ["n1", "n2"], "concurrency": 4}
    with gen.with_threads([0, 1, 2, 3]):
        w = g.op(test, 0)
        assert w == {"type": "invoke", "f": "write", "value": 0}
        r = g.op(test, 2)  # reader; node index 2 % 2 = 0 (writer's node)
        assert r["f"] == "read" and r["value"] == 0


def test_es_dirty_read_test_map():
    from jepsen_tpu.suites import elasticsearch as es

    t = es.es_test({"workload": "dirty-read",
                    "nodes": ["n1", "n2", "n3"], "concurrency": 6,
                    "time_limit": 1})
    assert isinstance(t["client"], es.DirtyReadClient)
    assert t["checker"] is not None
