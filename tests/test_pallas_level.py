"""Differential tests for the pallas level-loop kernel.

The pallas engine (checker/pallas_level.py) promises bit-for-bit the
SAME search as the XLA step kernel under the all-pairs prune: identical
carries slice by slice (frontier rows, counts, configs, overflow) and
identical verdicts through the full driver.  Off-TPU it runs in
interpret mode, so these tests exercise the exact kernel semantics the
chip will execute (Mosaic lowering itself can only be timed on real
hardware — tools/tpubench.py's engine rows do that in a tunnel window).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import jepsen_tpu.checker.linearizable as lin
from jepsen_tpu.checker import pallas_level as plev
from jepsen_tpu.checker.seq import check_opseq
from jepsen_tpu.history import encode_ops
from jepsen_tpu.models import cas_register, mutex
from jepsen_tpu.synth import (corrupt_read, register_history,
                              sim_mutex_history)


def _encode(model, h):
    seq = encode_ops(h, model.f_codes)
    es = lin.encode_search(seq)
    return seq, es


def _steps(model, dims):
    xla = jax.jit(lin.build_search_step_fn(model, dims))
    pal = jax.jit(plev.build_pallas_step_fn(model, dims, interpret=True))
    return xla, pal


def _args(es, esp):
    # the ONE signature home: identical for the XLA and pallas steps
    # (reduction planes inert here — unreduced differential runs)
    return lin.search_args(esp, es)


def _lockstep(model, h, *, frontier, bail, slices=12, lvl_cap=8,
              budget=10**8):
    """Drive both kernels slice by slice; assert identical carries."""
    seq, es = _encode(model, h)
    dims = lin.choose_dims(es, model, frontier=frontier)
    if not plev.eligible(model, dims):
        pytest.skip(f"dims not pallas-eligible: {dims}")
    esp = lin.pad_search(es, dims.n_det_pad, dims.n_crash_pad)
    old = lin._DOMINANCE_MODE
    lin._DOMINANCE_MODE = "allpairs"
    try:
        xla, pal = _steps(model, dims)
        a = _args(es, esp)
        cx = cp = tuple(jnp.asarray(c)
                        for c in lin._init_carry(dims, model))
        for s in range(slices):
            cx = xla(*a, jnp.int32(budget), jnp.int32(lvl_cap),
                     jnp.bool_(bail), *cx)
            cp = pal(*a, jnp.int32(budget), jnp.int32(lvl_cap),
                     jnp.bool_(bail), *cp)
            fx, cnx, stx, cfx, mdx, ovx = [np.asarray(v) for v in cx]
            fp, cnp_, stp, cfp, mdp, ovp = [np.asarray(v) for v in cp]
            assert (int(cnx), int(stx), int(cfx), int(mdx),
                    bool(ovx)) == (int(cnp_), int(stp), int(cfp),
                                   int(mdp), bool(ovp)), f"slice {s}"
            assert np.array_equal(fx[:int(cnx)], fp[:int(cnp_)]), \
                f"slice {s} frontier"
            if int(stx) != -1 or int(cnx) == 0 or (bail and bool(ovx)):
                return int(stx), int(cfx), bool(ovx)
        return int(stx), int(cfx), bool(ovx)
    finally:
        lin._DOMINANCE_MODE = old


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_lockstep_register_with_crashes(seed):
    rng = random.Random(seed)
    model = cas_register()
    h = register_history(rng, n_ops=56, n_procs=4, overlap=3,
                         crash_p=0.08, max_crashes=4, n_values=3)
    if seed % 2:
        h = corrupt_read(rng, h, at=0.85)
    _lockstep(model, h, frontier=16, bail=False)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_lockstep_mutex(seed):
    rng = random.Random(seed)
    model = mutex()
    h = sim_mutex_history(rng, n_ops=60, n_procs=3, crash_p=0.06,
                          max_crashes=4)
    _lockstep(model, h, frontier=16, bail=False)


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_lockstep_overflow_and_bail(seed):
    """A deliberately wide history at frontier 16 must overflow; the
    uncommitted-level revert under bail must match exactly."""
    rng = random.Random(seed)
    model = cas_register()
    h = register_history(rng, n_ops=64, n_procs=8, overlap=7,
                         crash_p=0.05, max_crashes=3, n_values=2)
    st, cfg, ovf = _lockstep(model, h, frontier=16, bail=True)
    # at least one run should overflow to exercise the revert path;
    # the equality assertions inside _lockstep are the real test
    _lockstep(model, h, frontier=16, bail=False)


def test_full_search_pallas_engine_matches_oracle():
    """search_opseq with the pallas engine forced end-to-end (driver,
    escalation ladder, checkpoint shape) vs the WGL oracle."""
    old = lin._ENGINE_MODE
    lin._ENGINE_MODE = "pallas"
    try:
        for seed in (31, 32, 33, 34):
            rng = random.Random(seed)
            model = cas_register()
            h = register_history(rng, n_ops=44, n_procs=3, overlap=2,
                                 crash_p=0.06, max_crashes=3,
                                 n_values=3)
            if seed % 2:
                h = corrupt_read(rng, h, at=0.8)
            seq = encode_ops(h, model.f_codes)
            out = lin.search_opseq(seq, model, budget=5_000_000)
            oracle = check_opseq(seq, model)
            assert out["valid"] == oracle["valid"], seed
    finally:
        lin._ENGINE_MODE = old


def test_full_search_configs_match_xla_allpairs():
    """Forced-pallas and forced-xla-allpairs searches must explore the
    IDENTICAL config count (same survivor order, same prune)."""
    rng = random.Random(41)
    model = cas_register()
    h = register_history(rng, n_ops=48, n_procs=4, overlap=3,
                         crash_p=0.08, max_crashes=4, n_values=3)
    seq = encode_ops(h, model.f_codes)
    old_e, old_d = lin._ENGINE_MODE, lin._DOMINANCE_MODE
    try:
        lin._DOMINANCE_MODE = "allpairs"
        lin._ENGINE_MODE = "pallas"
        a = lin.search_opseq(seq, model, budget=5_000_000)
        lin._ENGINE_MODE = "xla"
        b = lin.search_opseq(seq, model, budget=5_000_000)
    finally:
        lin._ENGINE_MODE, lin._DOMINANCE_MODE = old_e, old_d
    assert a["valid"] == b["valid"]
    assert a["configs"] == b["configs"]
    assert a["max_depth"] == b["max_depth"]


def test_search_batch_pallas_matches_oracle():
    """The batched escalation ladder with the pallas kernel forced
    (vmap of the fused level-loop) vs per-key oracle verdicts."""
    model = cas_register()
    seqs = []
    for k in range(8):
        rng = random.Random(f"pb{k}")
        h = register_history(rng, n_ops=40, n_procs=4, overlap=3,
                             crash_p=0.04, max_crashes=2, n_values=3)
        if k % 3 == 0:
            h = corrupt_read(rng, h, at=0.8)
        seqs.append(encode_ops(h, model.f_codes))
    old = lin._ENGINE_MODE
    lin._ENGINE_MODE = "pallas"
    try:
        got = lin.search_batch(seqs, model, budget=2_000_000)
    finally:
        lin._ENGINE_MODE = old
    for k, (s, r) in enumerate(zip(seqs, got)):
        oracle = check_opseq(s, model)
        assert r["valid"] == oracle["valid"], k


def test_checkpoint_resume_under_pallas(tmp_path):
    """The cross-tunnel-window accumulation path on the pallas engine:
    a deadline-killed pallas search checkpoints; resume_opseq (also on
    pallas) finishes it and labels the engine honestly.  This is
    exactly what a wedged window followed by a fresh one executes."""
    import time

    rng = random.Random(71)
    model = cas_register()
    h = register_history(rng, n_ops=80, n_procs=4, overlap=3,
                         crash_p=0.05, max_crashes=3, n_values=3)
    h = corrupt_read(rng, h, at=0.9)
    seq = encode_ops(h, model.f_codes)
    path = str(tmp_path / "ck.npz")
    old = lin._ENGINE_MODE
    lin._ENGINE_MODE = "pallas"
    try:
        saved = []

        def on_slice(carry, dims):
            lin.save_checkpoint(path, carry, dims, model, 10**7,
                                seq=seq)
            saved.append(1)

        out = lin.search_opseq(
            seq, model, budget=10**7, on_slice=on_slice,
            deadline=time.perf_counter())  # expire immediately
        if out["valid"] != "unknown" or not saved:
            pytest.skip("search decided before the deadline could cut "
                        "it (host too fast)")
        res = lin.resume_opseq(seq, model, path)
        assert res["valid"] is False
        assert res["engine"] == "device-bfs(pallas,resumed)"
        oracle = check_opseq(seq, model)
        assert res["valid"] == oracle["valid"]
    finally:
        lin._ENGINE_MODE = old


def test_cross_backend_resume_keeps_pallas_evidence(tmp_path):
    """A TPU window runs pallas slices and checkpoints; the next window
    resumes on a host where pallas is off.  The accumulated verdict's
    engine label must still carry the pallas evidence (the checkpoint
    persists the driver's actual-execution flag — through bench.py's
    tmp-path + rename save pattern too)."""
    import os
    import time

    rng = random.Random(72)
    model = cas_register()
    h = register_history(rng, n_ops=80, n_procs=4, overlap=3,
                         crash_p=0.05, max_crashes=3, n_values=3)
    h = corrupt_read(rng, h, at=0.9)
    seq = encode_ops(h, model.f_codes)
    path = str(tmp_path / "ck.npz")
    old = lin._ENGINE_MODE
    lin._ENGINE_MODE = "pallas"
    try:
        saved = []

        def on_slice(carry, dims):
            # bench.py's atomic save pattern: tmp path then rename
            # (np.savez appends .npz when the suffix is missing, so
            # the tmp name must keep it — same as bench.py's)
            lin.save_checkpoint(path + ".tmp.npz", carry, dims, model,
                                10**7, seq=seq)
            os.replace(path + ".tmp.npz", path)
            saved.append(1)

        out = lin.search_opseq(
            seq, model, budget=10**7, on_slice=on_slice,
            deadline=time.perf_counter())
        if out["valid"] != "unknown" or not saved:
            pytest.skip("search decided before the deadline could cut "
                        "it (host too fast)")
        lin._ENGINE_MODE = "xla"
        res = lin.resume_opseq(seq, model, path)
        assert res["valid"] is False
        assert res["engine"] == "device-bfs(pallas,resumed)"
    finally:
        lin._ENGINE_MODE = old


def test_eligibility_gates():
    model = cas_register()
    es_like = lin.SearchDims(n_det_pad=64, n_crash_pad=32, window=32,
                             k=4, state_width=1, frontier=16)
    assert plev.eligible(model, es_like)
    wide = lin.SearchDims(n_det_pad=64, n_crash_pad=32, window=128,
                          k=4, state_width=1, frontier=16)
    assert not plev.eligible(model, wide)
    big_f = lin.SearchDims(n_det_pad=64, n_crash_pad=32, window=32,
                           k=4, state_width=1, frontier=128)
    assert not plev.eligible(model, big_f)

    class FakeModel:
        name = "fifo-queue"

    assert not plev.eligible(FakeModel(), es_like)


def test_auto_mode_stays_xla_on_cpu():
    """auto never picks pallas off-TPU (interpret mode would be a
    silent slowdown on hosts)."""
    model = cas_register()
    dims = lin.SearchDims(n_det_pad=64, n_crash_pad=32, window=32,
                          k=4, state_width=1, frontier=16)
    old = lin._ENGINE_MODE
    lin._ENGINE_MODE = "auto"
    try:
        assert lin._use_pallas(model, dims) is False
    finally:
        lin._ENGINE_MODE = old
