"""Tests for checker/parallel.py — the multi-core host comparator
(the knossos-competition-on-N-cores stand-in, BASELINE.json)."""

import random

from jepsen_tpu.checker.parallel import batch_check_pool, portfolio_check
from jepsen_tpu.history import encode_ops
from jepsen_tpu.models import cas_register
from jepsen_tpu.synth import corrupt_read, register_history


def _mk_history(seed: int, corrupt: bool):
    model = cas_register()
    rng = random.Random(seed)
    h = register_history(rng, n_ops=60, n_procs=4, overlap=4, n_values=3)
    if corrupt:
        h = corrupt_read(rng, h, at=0.7)
    return encode_ops(h, model.f_codes), model


# module-level builders (spawned workers re-import this module)


def build_invalid():
    return _mk_history(5, True)


def build_valid():
    return _mk_history(6, False)


def build_key(k: int):
    return _mk_history(100 + k, k % 2 == 0)


def test_portfolio_decides_invalid():
    out = portfolio_check(build_invalid, n_procs=2, deadline_s=120)
    assert out["valid"] is False
    assert out["engine"].startswith("host2(")
    assert out["seconds"] >= 0


def test_portfolio_decides_valid():
    out = portfolio_check(build_valid, n_procs=2, deadline_s=120)
    assert out["valid"] is True


def test_batch_pool_all_keys():
    out = batch_check_pool(build_key, 6, n_procs=2, deadline_s=240)
    assert out["keys_done"] == 6
    for k, v in out["verdicts"].items():
        assert v is (k % 2 != 0), (k, v)
