"""Tier-1 generator tests — the analog of the reference's
generator_test.clj (fake threads/futures harness, deterministic op-stream
assertions) and independent_test.clj (key scheduling properties)."""

import random
import threading

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu import independent

TEST = {"concurrency": 4, "nodes": ["n1", "n2", "n3"]}


def pull_all(g, test, processes, max_ops=10_000):
    """Single-threaded harness: round-robin processes until exhausted."""
    out = []
    active = list(processes)
    while active and len(out) < max_ops:
        progressed = False
        for p in list(active):
            op = gen.op_and_validate(g, test, p)
            if op is None:
                active.remove(p)
            else:
                out.append((p, op))
                progressed = True
        if not progressed:
            break
    return out


def test_lifting_plain_objects():
    # a dict constantly yields itself
    g = gen.limit(3, {"type": "invoke", "f": "read", "value": None})
    ops = pull_all(g, TEST, [0])
    assert len(ops) == 3
    assert all(op["f"] == "read" for _, op in ops)

    # functions of (test, process) and of no args
    g2 = gen.limit(2, lambda test, process: {"type": "invoke", "f": "w",
                                             "value": process})
    assert [op["value"] for _, op in pull_all(g2, TEST, [7])] == [7, 7]

    g3 = gen.limit(2, lambda: {"type": "invoke", "f": "z", "value": 1})
    assert len(pull_all(g3, TEST, [0])) == 2


def test_process_thread_node_mapping():
    # process mod concurrency; thread mod node count (generator.clj:69-83)
    assert gen.process_to_thread(TEST, 6) == 2
    assert gen.process_to_thread(TEST, "nemesis") == "nemesis"
    assert gen.process_to_node(TEST, 4) == "n1"
    assert gen.process_to_node(TEST, 5) == "n2"
    assert gen.process_to_node(TEST, "nemesis") is None


def test_seq_one_op_per_element():
    g = gen.seq([{"type": "invoke", "f": "a"},
                 {"type": "invoke", "f": "b"},
                 {"type": "invoke", "f": "c"}])
    ops = [op["f"] for _, op in pull_all(g, TEST, [0])]
    assert ops == ["a", "b", "c"]


def test_once_and_concat():
    g = gen.concat(gen.once({"type": "invoke", "f": "first"}),
                   gen.limit(2, {"type": "invoke", "f": "rest"}))
    ops = [op["f"] for _, op in pull_all(g, TEST, [0])]
    assert ops == ["first", "rest", "rest"]


def test_f_map():
    g = gen.f_map({"start": "kill"},
                  gen.limit(1, {"type": "info", "f": "start"}))
    assert pull_all(g, TEST, [0])[0][1]["f"] == "kill"


def test_filter():
    src = gen.seq([{"type": "invoke", "f": "a", "value": i}
                   for i in range(6)])
    g = gen.filter(lambda op: op["value"] % 2 == 0, src)
    assert [op["value"] for _, op in pull_all(g, TEST, [0])] == [0, 2, 4]


def test_each_gives_independent_copies():
    g = gen.each(lambda: gen.seq([{"type": "invoke", "f": "x", "value": 1},
                                  {"type": "invoke", "f": "x", "value": 2}]))
    ops = pull_all(g, TEST, [0, 1])
    by_proc = {}
    for p, op in ops:
        by_proc.setdefault(p, []).append(op["value"])
    assert by_proc == {0: [1, 2], 1: [1, 2]}


def test_drain_queue():
    enq = gen.seq([{"type": "invoke", "f": "enqueue", "value": i}
                   for i in range(3)])
    g = gen.drain_queue(enq)
    ops = [op["f"] for _, op in pull_all(g, TEST, [0])]
    assert ops == ["enqueue"] * 3 + ["dequeue"] * 3


def test_reserve_partitions_threads():
    with gen.with_threads([0, 1, 2, 3, "nemesis"]):
        seen = {}

        def mk(tag):
            def f(test, process):
                # record the *threads* binding each pool sees
                seen[tag] = gen.current_threads()
                return {"type": "invoke", "f": tag}
            return f

        g = gen.reserve(2, mk("write"), 1, mk("cas"), mk("read"))
        assert g.op(TEST, 0)["f"] == "write"
        assert g.op(TEST, 1)["f"] == "write"
        assert g.op(TEST, 2)["f"] == "cas"
        assert g.op(TEST, 3)["f"] == "read"
        assert seen["write"] == [0, 1]
        assert seen["cas"] == [2]
        assert seen["read"] == [3, "nemesis"]


def test_on_nemesis_clients_routing():
    with gen.with_threads([0, 1, 2, 3, "nemesis"]):
        g = gen.nemesis({"type": "info", "f": "start"},
                        {"type": "invoke", "f": "read"})
        assert g.op(TEST, "nemesis")["f"] == "start"
        assert g.op(TEST, 2)["f"] == "read"
        c = gen.clients({"type": "invoke", "f": "read"})
        assert c.op(TEST, "nemesis") is None
        assert c.op(TEST, 1)["f"] == "read"


def test_phases_barrier_ordering():
    """All threads must finish phase a before any emits phase b
    (generator.clj:458-462)."""
    test = {"concurrency": 3, "nodes": ["n1"]}
    g = gen.phases(gen.limit(3, {"type": "invoke", "f": "a"}),
                   gen.limit(3, {"type": "invoke", "f": "b"}))
    order = []
    lock = threading.Lock()

    def worker(p):
        with gen.with_threads([0, 1, 2]):
            while True:
                op = gen.gen_op(g, test, p)
                if op is None:
                    return
                with lock:
                    order.append(op["f"])

    ts = [threading.Thread(target=worker, args=(p,)) for p in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in ts), "phase barrier deadlocked"
    assert len(order) == 6
    # every a precedes every b
    assert order[:3] == ["a"] * 3 and order[3:] == ["b"] * 3


def test_time_limit():
    g = gen.time_limit(0.2, {"type": "invoke", "f": "read"})
    assert g.op(TEST, 0) is not None
    import time

    time.sleep(0.25)
    assert g.op(TEST, 0) is None


def test_stagger_and_delay_still_emit():
    g = gen.stagger(0.001, gen.limit(2, {"type": "invoke", "f": "r"}))
    assert len(pull_all(g, TEST, [0])) == 2
    g2 = gen.delay(0.001, gen.limit(1, {"type": "invoke", "f": "r"}))
    assert len(pull_all(g2, TEST, [0])) == 1


def test_mix_seeded():
    random.seed(0)
    g = gen.limit(20, gen.mix([{"type": "invoke", "f": "a"},
                               {"type": "invoke", "f": "b"}]))
    fs = {op["f"] for _, op in pull_all(g, TEST, [0])}
    assert fs == {"a", "b"}


# --- independent generators ----------------------------------------------


def test_sequential_generator():
    g = independent.sequential_generator(
        ["k1", "k2"],
        lambda k: gen.limit(2, {"type": "invoke", "f": "w", "value": 1}))
    ops = [op for _, op in pull_all(g, TEST, [0])]
    assert len(ops) == 4
    assert [op["value"].key for op in ops] == ["k1", "k1", "k2", "k2"]
    assert all(op["value"].value == 1 for op in ops)


def test_concurrent_generator_groups_and_coverage():
    """10 threads in groups of 2 work 50 keys; each key's ops come from
    exactly one group and every key is fully processed
    (independent_test.clj:35-45 analog)."""
    n_threads, group_size, n_keys, ops_per_key = 10, 2, 50, 6
    test = {"concurrency": n_threads, "nodes": ["n1"]}
    g = independent.concurrent_generator(
        group_size, range(n_keys),
        lambda k: gen.limit(ops_per_key,
                            {"type": "invoke", "f": "w", "value": k}))
    ops = []
    lock = threading.Lock()

    def worker(p):
        with gen.with_threads(list(range(n_threads))):
            while True:
                op = gen.gen_op(g, test, p)
                if op is None:
                    return
                with lock:
                    ops.append((p, op))

    ts = [threading.Thread(target=worker, args=(p,))
          for p in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in ts)

    per_key: dict = {}
    for p, op in ops:
        kv = op["value"]
        per_key.setdefault(kv.key, []).append(p)
    assert set(per_key) == set(range(n_keys))
    for k, procs in per_key.items():
        assert len(procs) == ops_per_key
        groups = {p // group_size for p in procs}
        assert len(groups) == 1, f"key {k} served by groups {groups}"


def test_concurrent_generator_rejects_nemesis():
    test = {"concurrency": 2, "nodes": ["n1"]}
    g = independent.concurrent_generator(
        2, [1], lambda k: {"type": "invoke", "f": "w"})
    with gen.with_threads([0, 1, "nemesis"]):
        g.op(test, 0)  # init
        with pytest.raises(AssertionError):
            g.op(test, "nemesis")
