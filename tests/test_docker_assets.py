"""Structural validation of the Tier-3 docker harness assets.

No docker daemon exists in any round's build image, so `docker/` can
never be EXECUTED here (docker/smoke.sh runs on any docker host); these
tests keep the assets from bit-rotting invisibly in the meantime —
the compose topology, the sshd node image, and the smoke script's
step contract are all asserted against the files (the reference's
harness shape: docker/README.md, jepsen-control + n1..n5).
"""

import os
import re
import stat
import subprocess

import pytest

DOCKER = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docker")


def read(*parts: str) -> str:
    with open(os.path.join(DOCKER, *parts)) as f:
        return f.read()


def test_compose_topology():
    """control + n1..n5, nodes privileged (nemesis needs iptables/tc),
    repo mounted read-only into the control container."""
    yml = read("docker-compose.yml")
    services = re.findall(r"^  (\w+):", yml, re.M)
    assert "control" in services
    assert [f"n{i}" for i in range(1, 6)] == \
        [s for s in services if re.fullmatch(r"n\d", s)]
    assert yml.count("build: ./node") == 5
    assert yml.count("privileged: true") >= 6
    assert "/jepsen_tpu:ro" in yml
    # control waits for every node
    dep = re.search(r"depends_on: \[([^\]]+)\]", yml)
    assert dep and {s.strip() for s in dep.group(1).split(",")} == \
        {f"n{i}" for i in range(1, 6)}


def test_node_image_runs_sshd():
    """Each db node is an sshd container the control node can exec
    into — the whole point of the harness (SSHRemote's real path)."""
    df = read("node", "Dockerfile")
    assert "openssh-server" in df
    assert re.search(r'CMD.*sshd.*-D', df)
    # net-manipulation tooling the nemesis path needs (start-stop-daemon
    # ships in the debian base image; no install line to assert)
    for pkg in ("iptables", "iproute2"):
        assert pkg in df, f"node image lost {pkg}"


def test_control_image_has_framework_deps():
    df = read("control", "Dockerfile")
    assert "openssh-client" in df
    # the harness itself is volume-mounted, not baked, so the image must
    # carry python (base image or installed package)
    assert re.search(r"FROM python|python3", df)
    assert "PYTHONPATH=/jepsen_tpu" in df


def test_smoke_script_contract():
    """smoke.sh is executable, bash-clean, and runs both the atomdemo
    (in-process) and etcdemo (over-SSH) legs, plus the localnode tier
    folded in per VERDICT r3 item 8."""
    path = os.path.join(DOCKER, "smoke.sh")
    assert os.stat(path).st_mode & stat.S_IXUSR
    subprocess.run(["bash", "-n", path], check=True)
    sh = read("smoke.sh")
    for leg in ("atomdemo", "etcdemo", "localnode", "results.json"):
        assert leg in sh, f"smoke.sh lost its {leg} leg"


def test_up_script_is_clean():
    subprocess.run(["bash", "-n", os.path.join(DOCKER, "up.sh")],
                   check=True)


@pytest.mark.skipif(True, reason="no docker daemon in the build image; "
                    "run docker/smoke.sh on a docker host")
def test_smoke_executed():  # pragma: no cover — documentation marker
    raise AssertionError("unreachable")
