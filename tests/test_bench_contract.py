"""Bench honesty contracts (VERDICT r3 weak #3 / item 6).

The benchmark's labels must not overstate the verified work: a tier
named "1k" must carry EXACTLY 1000 encoded ops, and the per-core batch
accounting must bill only workers that actually ran.

The in-process label/accounting contracts ride tier-1; the tests that
spawn real ``bench.py`` child processes (checkpoint/resume, decided
carries, decomposed cold+warm) run under ``-m slow`` — they cost
10-50s each and were pushing the fast tier past its wall-clock budget.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


@pytest.mark.parametrize("name,nominal", [("1k", 1_000), ("10k", 10_000)])
def test_register_tiers_encode_to_nominal(name, nominal):
    seq, _model = bench.make_seq(name)
    assert len(seq) == nominal


def test_mutex_tier_close_to_nominal():
    # the mutex generator's acquire-chain suffix makes exact hits rare;
    # the scan must land within 0.2% (the emitted metric string always
    # carries the actual count either way)
    seq, _model = bench.make_seq("mutex2k")
    assert abs(len(seq) - 2_000) <= 4


def test_tier_history_deterministic_across_processes():
    # children rebuild the identical history from the resolved nominal
    # (shared via BENCH_NOMINAL_* env)
    import numpy as np

    s1, _ = bench.make_seq("1k")
    bench._SEQ_CACHE.clear()
    s2, _ = bench.make_seq("1k")
    assert np.array_equal(s1.f, s2.f) and np.array_equal(s1.inv, s2.inv)


def test_batch_stats_per_core_math():
    res = {"n_keys": 256, "t_first": 9.9}
    host = {"batch256": {"host_pool": {
        "keys_done": 128, "n_keys": 256, "seconds": 4.0,
        "configs": 1, "n_procs": 2}}}
    s = bench.batch_stats(res, host, t_dev=2.0)
    # pool: 128 keys / 4s = 32 keys/s on 2 procs -> 16 keys/s/core
    assert s["host_pool_keys_per_sec"] == 32.0
    assert s["host_pool_keys_per_sec_per_core"] == 16.0
    # full pool time extrapolates to 8s for all 256 keys
    assert s["speedup_vs_host_pool"] == 4.0
    # device: 128 keys/s vs 16/core
    assert s["speedup_vs_host_pool_per_core"] == 8.0
    # 16-core extrapolation: 256/(16*16) = 1s vs 2s device
    assert s["vs_baseline"] == 0.5
    assert "EXTRAPOLATED" in s["vs_baseline_basis"]


def test_batch_stats_no_pool():
    s = bench.batch_stats({"n_keys": 4, "t_first": 1.0}, {}, t_dev=1.0)
    assert s["vs_baseline"] is None


def _run_tier_child(tmp_path, tier_s, **extra_env):
    """Spawn one bench tier child (the shared harness for the
    checkpoint-contract tests) and parse its JSON line."""
    import json
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "BENCH_CKPT_DIR": str(tmp_path), "BENCH_TIER_S": str(tier_s),
           **extra_env}
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--run-tier", "1k", "--budget", "5000000"],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr[-800:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_checkpoint_resumes_across_prune_modes(tmp_path):
    """A carry accumulated under one prune implementation resumes under
    the other (the cross-backend reality: a TPU window checkpoints with
    the all-pairs kernel, the round-end CPU bench finishes the search
    with the sort kernel).  Both prunes are sound, so any interleaving
    must still decide correctly."""
    r1 = _run_tier_child(tmp_path, 3, JEPSEN_TPU_DOMINANCE="allpairs")
    if r1["valid"] != "unknown":
        pytest.skip("host too fast to leave a checkpoint")
    r2 = _run_tier_child(tmp_path, 150, JEPSEN_TPU_DOMINANCE="sort")
    assert r2["resumed"] is True
    assert r2["valid"] is False  # the 1k history's known verdict


def test_wide_tier_is_wide_and_near_nominal():
    # BASELINE config #5's 64-proc worst-case-frontier variant: the
    # encoding must actually be wide (the tier exists to stress big
    # levels) and close to its nominal size
    import jepsen_tpu.checker.linearizable as lin

    seq, model = bench.make_seq("10k64")
    assert abs(len(seq) - 10_000) <= 16
    es = lin.encode_search(seq)
    assert es.concurrency >= 24, es.concurrency
    assert es.window >= 128, es.window


def test_wide_tier_is_last_and_not_headline():
    # lowest priority: usually undecided; must never displace the 10k
    # headline or spend earlier tiers' budget
    names = [t[0] for t in bench.TIERS]
    assert names[-1] == "10k64"
    assert bench.TIERS[-1][4] is False


def test_uniq_tier_exercises_value_blocks():
    """ISSUE 2 satellite: the unique-writes wide tier must be exactly
    10k encoded ops, quiescence-free, and ELIGIBLE for the per-value
    block decomposition — so config 5's `applies: false` stops being
    the only decomposition data point at device scale."""
    from jepsen_tpu.decompose.partition import (quiescence_segments,
                                                value_block_verdict)

    seq, model = bench.make_seq("10kuniq")
    assert len(seq) == 10_000
    assert len(quiescence_segments(seq)) == 1  # no quiescent point
    vb = value_block_verdict(seq, model)
    assert vb in (True, False)  # the decomposition APPLIES
    d = bench._single_decomposed(seq, model, 1_000_000, vb, 1.0)
    assert d["applies"] is True
    assert d["valid"] == vb
    assert "value-blocks" in (d.get("methods") or [])
    # not the headline, and ordered before the 10k64 straggler
    names = [t[0] for t in bench.TIERS]
    assert names.index("10k") < names.index("10kuniq") \
        < names.index("10k64")
    spec = {t[0]: t for t in bench.TIERS}["10kuniq"]
    assert spec[4] is False


def test_batch_tier_runs_before_the_10k():
    # the 10k is the search observed to wedge an open tunnel (r4); it
    # must not be able to cost batch256 its only accelerator window
    names = [t[0] for t in bench.TIERS]
    assert names.index("batch256") < names.index("10k")


def test_compact_emit_fits_driver_tail():
    """The emitted stdout line must stay under the driver's recorded
    tail (VERDICT r4 weak #1: r3+r4 both shipped parsed:null because
    the full detail blob blew through ~2000 chars), and a non-TPU
    result must carry the best banked on-chip artifact."""
    import json

    # a worst-case-ish full result: long basis strings, several tiers,
    # probe diagnostics with a big stderr tail
    full = {
        "metric": "ops-verified/sec, 10000-op 32-proc CAS-register "
                  "history, decided verdict (invalid), cpu backend",
        "value": 29.4, "unit": "ops/s", "vs_baseline": 0.07,
        "detail": {
            "backend": "cpu", "engine": "device-bfs",
            "device_verdict": False, "device_seconds": 339.8,
            "n_ops": 10000, "vs_baseline_basis": "EXTRAPOLATED: " + "x" * 300,
            "host_linear": {"valid": False, "seconds": 23.5,
                            "configs": 12_900_000, "failing_depth": 7388},
            "probe": {"platform": None, "waited_s": 300.0,
                      "tunnel_endpoint_tcp": "open",
                      "stderr_tail": "y" * 2000},
            **{f"tier_{n}": {"backend": "cpu", "device_verdict": False,
                             "device_seconds": 1.0, "junk": "z" * 500}
               for n in ("1k", "mutex2k", "10k64")},
            "batch256": {"backend": "cpu", "valid": "192 valid",
                         "device_seconds": 1.5, "junk": "z" * 500},
        },
    }
    c = bench._compact_result(full)
    s = json.dumps(c)
    assert len(s) <= bench._COMPACT_LIMIT, len(s)
    # headline fields survive verbatim
    assert c["value"] == 29.4 and c["vs_baseline"] == 0.07
    # the repo carries r4 banked on-chip artifacts: a cpu result must
    # surface the best of them, tagged
    banked = c["detail"].get("banked_tpu")
    assert banked and banked["evidence"] == "banked"
    assert banked["kind"] == "bench_headline"
    assert "docs/tpu/" in banked["source"]


def test_compact_emit_tpu_result_carries_no_banked():
    c = bench._compact_result({
        "metric": "m", "value": 1.0, "unit": "ops/s",
        "vs_baseline": None, "detail": {"backend": "tpu"}})
    assert "banked_tpu" not in c["detail"]


@pytest.mark.slow
def test_decided_pending_tpu_checkpoint_is_left_alone(tmp_path):
    """ADVICE r4 bench.py:570: a CPU child deciding a search that TPU
    windows accumulated must bank the carry ONCE (marked decided) and
    later CPU children must run fresh without touching it — not replay
    it forever with ever-growing cumulative elapsed."""
    import json

    r1 = _run_tier_child(tmp_path, 3)  # leave a checkpoint
    if r1["valid"] != "unknown":
        pytest.skip("host too fast to leave a checkpoint")
    meta_p = tmp_path / "1k.npz.meta.json"
    # forge a TPU contribution into the carry's history
    m = json.loads(meta_p.read_text())
    m["backends"] = sorted(set(m.get("backends", [])) | {"tpu"})
    meta_p.write_text(json.dumps(m))
    # CPU child resumes and decides -> carry kept, marked decided
    r2 = _run_tier_child(tmp_path, 150)
    assert r2["valid"] is False and r2["resumed"] is True
    assert (tmp_path / "1k.npz").exists()
    m2 = json.loads(meta_p.read_text())
    assert m2["decided_pending_tpu"] is True
    assert m2["verdict_cpu"] is False
    ckpt_bytes = (tmp_path / "1k.npz").read_bytes()
    # a later CPU child must NOT resume (fresh accounting) and must NOT
    # touch the banked carry
    r3 = _run_tier_child(tmp_path, 150)
    assert r3["valid"] is False
    assert r3["resumed"] is False
    assert r3["elapsed_total"] == pytest.approx(r3["t_first"], abs=0.01)
    assert (tmp_path / "1k.npz").read_bytes() == ckpt_bytes
    assert json.loads(meta_p.read_text())["decided_pending_tpu"] is True


@pytest.mark.slow
def test_orphan_meta_is_discarded(tmp_path):
    """A meta file whose npz is gone (unlink raced or failed) must not
    leak stale accounting — phantom elapsed/backends — into a fresh
    run, and must not re-arm decided_pending_tpu forever."""
    import json

    (tmp_path / "1k.npz.meta.json").write_text(json.dumps(
        {"elapsed": 999.0, "slices": 50, "backends": ["cpu", "tpu"],
         "decided_pending_tpu": True}))
    r = _run_tier_child(tmp_path, 150)
    assert r["resumed"] is False
    assert r["elapsed_total"] == pytest.approx(r["t_first"], abs=0.01)
    assert r["backends_contributing"] == ["cpu"]
    assert not (tmp_path / "1k.npz.meta.json").exists()


def test_wide_tier_host_comparator_always_present(monkeypatch):
    """VERDICT r4 weak #4: the 10k64 row must never ship comparator-
    free — host_linear runs under its own cap and reports seconds +
    configs even when undecided."""
    monkeypatch.setenv("BENCH_HOST_10K64_S", "5")
    monkeypatch.setattr(bench, "HOST_S", 0.1)  # starve the other tiers
    wide_spec = [t for t in bench.TIERS if t[0] == "10k64"]
    out = bench.host_comparators(wide_spec)
    row = out["10k64"]["host_linear"]
    assert row["seconds"] > 0
    assert row["configs"] > 0


@pytest.mark.slow
def test_tier_child_checkpoints_and_resumes(tmp_path):
    """A deadline-killed tier child leaves a checkpoint; the next child
    resumes it (reporting resumed+cumulative time) and a decided run
    deletes it.  This is the cross-tunnel-window accumulation contract
    the r4 wedge motivated."""
    def run(tier_s):
        return _run_tier_child(tmp_path, tier_s)

    r1 = run("3")  # too short to decide on a cold cpu: must checkpoint
    if r1["valid"] == "unknown":
        assert (tmp_path / "1k.npz").exists()
        assert r1["resumed"] is False
        r2 = run("150")
        assert r2["resumed"] is True
        assert r2["valid"] is False
        assert r2["elapsed_total"] > r2["t_dev"]
    else:
        # machine fast enough to decide in 3s: the decided contract
        # still must hold below
        r2 = r1
    # decided: checkpoint cleaned up so later runs start fresh
    assert not (tmp_path / "1k.npz").exists()
    assert not (tmp_path / "1k.npz.meta.json").exists()


@pytest.mark.slow
def test_batch_child_reports_decomposed_cold_and_warm(tmp_path):
    """ISSUE 1 config 3 contract: the batch tier child must report the
    decomposed-vs-direct comparison — cold pass filling the canonical-
    hash cache, warm pass serving every key from it, verdicts
    bit-identical to the direct engine."""
    import json
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "BENCH_BATCH_KEYS": "8", "BENCH_TIER_S": "120",
           "BENCH_CKPT_DIR": str(tmp_path),
           "BENCH_DECOMPOSE_CACHE": str(tmp_path / "verdicts.jsonl")}
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--run-tier", "batch256", "--budget", "2000000"],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr[-800:]
    j = json.loads(out.stdout.strip().splitlines()[-1])
    dec = j["decomposed"]
    assert dec["verdicts_agree"] is True
    assert dec["prior_cache_entries"] == 0
    assert dec["warm_hits"] == 8 and dec["warm_hit_rate"] == 1.0
    assert dec["t_warm"] > 0 and dec["t_cold"] > 0
    # the criterion's evidence fields exist and are numbers
    assert isinstance(dec["speedup_warm_vs_direct"], (int, float))
    # the cache file persisted (store.py-style jsonl)
    assert (tmp_path / "verdicts.jsonl").exists()


def test_single_decomposed_probe_is_honest_when_nothing_splits():
    """ISSUE 1 config 5 contract: when neither cutter fires (permanent
    in-flight overlap, non-unique writes), the report must say
    applies=False instead of re-running the direct engine under a
    'decomposed' label."""
    import random

    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import register_history

    rng = random.Random(1)
    m = cas_register()
    h = register_history(rng, n_ops=60, n_procs=8, overlap=8,
                         crash_p=0.0, n_values=4)
    seq = encode_ops(h, m.f_codes)
    d = bench._single_decomposed(seq, m, 1_000_000, False, 1.0)
    if d.get("applies") is False:
        assert d["segments"] == 1 and d["cells"] == 1
        assert "direct engine" in d["note"]
    else:
        # the generator happened to quiesce: then a real decomposed
        # verdict must have been produced and must agree
        assert d["valid"] in (True, False)
