"""The campaign->fuzz regression net (jepsen_tpu/live/corpus.py +
tools/fuzz.py --corpus).

Tier-1 here: banking (canonical-id dedup, independent-key demux,
prefix truncation, queue drain expansion, pool bounding + metrics) and
the replay contract on a bounded seeded pool — every banked entry
rides ALL engine routes (direct device BFS, decomposed, bucketed,
streaming) with bit-identical verdicts and a clean certificate audit,
and an injected divergence/regression is actually caught (the net has
teeth, not just a green path).
"""

import os
import random
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _bank_register(base, rng, *, n_ops=26, crash_p=0.1, valid=True,
                   corrupt=False, family="kv", nemesis="kill-restart"):
    from jepsen_tpu.live import corpus
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import mutate, sim_register_history

    h = sim_register_history(rng, 4, n_ops, crash_p=crash_p, cas=True)
    if corrupt:
        h = mutate(rng, h)
    test = {"model": cas_register(), "history": h}
    return corpus.bank_cell(
        test, {"family": family, "nemesis": nemesis, "valid": valid},
        base=str(base)), h


def test_bank_dedup_and_pool_metrics(tmp_path):
    from jepsen_tpu.live import corpus
    from jepsen_tpu.obs import metrics as obs_metrics

    rng = random.Random(0)
    out, _h = _bank_register(tmp_path, rng)
    assert out == {"banked": 1, "pool": 1}
    # the exact same history (same canonical id) banks zero
    rng = random.Random(0)
    out2, _h = _bank_register(tmp_path, rng)
    assert out2 == {"banked": 0, "pool": 1}
    # a process-renamed copy is the SAME canonical shape: still deduped
    from dataclasses import replace

    from jepsen_tpu.live.corpus import bank, entries_from_test
    from jepsen_tpu.models import cas_register

    rng = random.Random(0)
    from jepsen_tpu.synth import sim_register_history

    h = sim_register_history(rng, 4, 26, crash_p=0.1, cas=True)
    renamed = [replace(op, process=op.process + 10) for op in h]
    entries = entries_from_test(
        {"model": cas_register(), "history": renamed},
        {"family": "kv", "nemesis": "x", "valid": True})
    assert bank(entries, base=str(tmp_path))["banked"] == 0
    assert obs_metrics.REGISTRY.get(
        "jtpu_corpus_pool_size").total() >= 1


def test_bank_truncates_long_histories_to_wellformed_prefix(tmp_path):
    from jepsen_tpu.live import corpus
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import sim_register_history

    rng = random.Random(1)
    h = sim_register_history(rng, 4, 400, crash_p=0.05, cas=True)
    assert len(h) > corpus.MAX_OPS
    entries = corpus.entries_from_test(
        {"model": cas_register(), "history": h},
        {"family": "kv", "nemesis": "pause", "valid": True})
    [e] = entries
    assert e["truncated"] is True
    assert e["n_ops"] <= corpus.MAX_OPS
    # a truncated prefix's verdict may differ from the cell's: the
    # banked expectation is dropped, parity still applies
    assert e["valid"] is None
    # the prefix is well-formed: every op has a type, invokes pair up
    from jepsen_tpu.history import Op, pair_index

    ops = [Op.from_dict(d) for d in e["ops"]]
    pair_index(ops)


def test_bank_demuxes_independent_keys(tmp_path):
    from jepsen_tpu import independent
    from jepsen_tpu.history import Op
    from jepsen_tpu.live import corpus
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import sim_register_history

    rng = random.Random(2)
    h0 = sim_register_history(rng, 2, 12, crash_p=0.0, cas=True)
    h1 = sim_register_history(rng, 2, 12, crash_p=0.0, cas=True)
    keyed = []
    for k, h in ((0, h0), (1, h1)):
        for op in h:
            keyed.append(Op(process=op.process + 4 * k, type=op.type,
                            f=op.f,
                            value=independent.tuple_(k, op.value),
                            time=op.time))
    entries = corpus.entries_from_test(
        {"model": cas_register(), "history": keyed},
        {"family": "register", "nemesis": "pause", "valid": True})
    assert len(entries) == 2
    for e in entries:
        assert e["routes"] == "engines"
        assert e["valid"] is None  # per-key verdict != cell verdict
        ops = [Op.from_dict(d) for d in e["ops"]]
        # demuxed: raw values, no [k v] tuples left
        assert not any(isinstance(o.value, dict) for o in ops)


def test_bank_queue_entries_expand_drains(tmp_path):
    from jepsen_tpu.history import invoke_op, ok_op
    from jepsen_tpu.live import corpus

    h = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
         invoke_op(1, "enqueue", 2), ok_op(1, "enqueue", 2),
         invoke_op(0, "dequeue"), ok_op(0, "dequeue", 1),
         invoke_op(1, "drain"), ok_op(1, "drain", [2])]
    out = corpus.bank_cell(
        {"model": None, "history": h},
        {"family": "queue", "nemesis": "kill-restart", "valid": True},
        base=str(tmp_path))
    assert out == {"banked": 1, "pool": 1}
    [e] = corpus.load_pool(corpus.corpus_dir(str(tmp_path)))
    assert e["routes"] == "queue"
    assert e["valid"] is True
    # the drain was expanded into dequeue pairs
    assert not any(d["f"] == "drain" for d in e["ops"])
    from jepsen_tpu.history import Op

    r = corpus.replay_queue([Op.from_dict(d) for d in e["ops"]])
    assert r["valid"] is True


def test_corpus_replay_parity_on_bounded_seeded_pool(tmp_path):
    """The acceptance path: a seeded pool (valid, corrupted, mutex,
    queue — crash ops included) replays through ALL engine routes with
    bit-identical verdicts and a clean audit."""
    import fuzz as fuzz_tool

    from jepsen_tpu.live import corpus
    from jepsen_tpu.models import mutex
    from jepsen_tpu.synth import sim_mutex_history

    rng = random.Random(7)
    _bank_register(tmp_path, rng, valid=True)
    # corrupted history: expectation unknown, cross-route parity must
    # still hold
    _bank_register(tmp_path, rng, corrupt=True, valid=None,
                   nemesis="partition")
    corpus.bank_cell(
        {"model": mutex(),
         "history": sim_mutex_history(rng, 20, 3, crash_p=0.1)},
        {"family": "lock", "nemesis": "pause", "valid": True},
        base=str(tmp_path))
    from jepsen_tpu.history import invoke_op, ok_op

    corpus.bank_cell(
        {"model": None,
         "history": [invoke_op(0, "enqueue", 5), ok_op(0, "enqueue", 5),
                     invoke_op(0, "drain"), ok_op(0, "drain", [5])]},
        {"family": "replicated-queue", "nemesis": "link-bridge",
         "valid": True}, base=str(tmp_path))
    pool = corpus.load_pool(corpus.corpus_dir(str(tmp_path)))
    assert len(pool) >= 4
    rc = fuzz_tool.corpus_replay(corpus.corpus_dir(str(tmp_path)))
    assert rc == 0


def test_corpus_replay_runs_hb_leg_on_decidable_entries(tmp_path):
    """A banked unique-writes register history is inside the HB
    solver's decide-fast class: the replay must run the HB leg (not
    vacuously skip it), its verdict must join the parity set, and the
    whole replay must come back clean — the satellite's regression
    teeth for the static order-solver."""
    import fuzz as fuzz_tool

    from jepsen_tpu.analyze.hb import hb_dispose
    from jepsen_tpu.history import Op, encode_ops
    from jepsen_tpu.live import corpus
    from jepsen_tpu.models import register
    from jepsen_tpu.synth import register_history, swap_read_values

    rng = random.Random(31)
    m = register(0)
    good = register_history(rng, n_ops=20, n_procs=3, overlap=3,
                            crash_p=0.0, cas=False, unique_writes=True)
    bad = swap_read_values(random.Random(32), register_history(
        random.Random(33), n_ops=20, n_procs=3, overlap=3, crash_p=0.0,
        cas=False, unique_writes=True))
    corpus.bank_cell({"model": m, "history": good},
                     {"family": "register", "nemesis": "none",
                      "valid": True}, base=str(tmp_path))
    corpus.bank_cell({"model": m, "history": bad},
                     {"family": "register", "nemesis": "none",
                      "valid": False}, base=str(tmp_path))
    d = corpus.corpus_dir(str(tmp_path))
    pool = corpus.load_pool(d)
    assert len(pool) == 2
    # the solver really decides these entries (invalid one by cycle)
    decided = []
    for e in pool:
        model = corpus.entry_model(e)
        s = encode_ops([Op.from_dict(x) for x in e["ops"]],
                       model.f_codes)
        r = hb_dispose(s, model)
        assert r is not None, "entry left the decide-fast class"
        decided.append(r)
    assert {r["valid"] for r in decided} == {True, False}
    assert any("hb_cycle" in r or "final_ops" in r for r in decided)
    assert fuzz_tool.corpus_replay(d) == 0


def test_corpus_replay_runs_dpor_leg_with_teeth(tmp_path, monkeypatch):
    """fuzz --corpus's dedup+DPOR parity leg (phase-2 satellite): every
    engine entry replays through the host DFS with the dynamic layer
    forced ON and OFF, bit-identical.  Teeth: a sabotaged sleep-set
    layer (over-pruning every sibling) flips verdicts, and the replay
    must catch it as a divergence."""
    import fuzz as fuzz_tool

    from jepsen_tpu.analyze import dpor as dpor_mod
    from jepsen_tpu.live import corpus

    rng = random.Random(61)
    _bank_register(tmp_path, rng, n_ops=20, crash_p=0.0, valid=True)
    _bank_register(tmp_path, rng, n_ops=20, crash_p=0.1, valid=None,
                   corrupt=True, nemesis="partition")
    d = corpus.corpus_dir(str(tmp_path))
    assert fuzz_tool.corpus_replay(d) == 0

    # sabotage: every child sleeps on everything — the dpor-on DFS
    # prunes all candidates below depth 1, the valid entry's witness
    # path dies, and the verdict flips to invalid.  The leg must catch
    # the on-vs-off divergence.
    monkeypatch.setattr(
        dpor_mod.SleepSets, "child_sleep",
        lambda self, state, taken, base: (1 << 4096) - 1)
    assert fuzz_tool.corpus_replay(d) == 1


def test_corpus_replay_catches_banked_verdict_regression(tmp_path):
    """The net has teeth: an entry whose banked expectation disagrees
    with what the engines say fails the replay loudly."""
    import json

    import fuzz as fuzz_tool

    from jepsen_tpu.live import corpus

    rng = random.Random(9)
    _bank_register(tmp_path, rng, n_ops=16, crash_p=0.0, valid=True)
    d = corpus.corpus_dir(str(tmp_path))
    with open(os.path.join(d, corpus.POOL)) as f:
        [entry] = [json.loads(x) for x in f if x.strip()]
    entry["valid"] = False  # claim the engines should say invalid
    with open(os.path.join(d, corpus.POOL), "w") as f:
        f.write(json.dumps(entry) + "\n")
    assert fuzz_tool.corpus_replay(d) == 1


def test_queue_replay_catches_lost_enqueue(tmp_path):
    """A lost acked enqueue — the seeded redelivery cell's violation —
    is invalid through the queue route."""
    from jepsen_tpu.history import invoke_op, ok_op
    from jepsen_tpu.live import corpus

    h = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
         invoke_op(1, "enqueue", 2), ok_op(1, "enqueue", 2),
         invoke_op(0, "drain"), ok_op(0, "drain", [2])]  # 1 LOST
    out = corpus.bank_cell(
        {"model": None, "history": h},
        {"family": "replicated-queue", "nemesis": "link-bridge",
         "seeded": True, "valid": False}, base=str(tmp_path))
    assert out["banked"] == 1
    import fuzz as fuzz_tool

    assert fuzz_tool.corpus_replay(
        corpus.corpus_dir(str(tmp_path))) == 0  # invalid == banked


# ---------------------------------------------------------------------------
# bank-time shrinking: the ddmin minimal repro alongside the full entry
# ---------------------------------------------------------------------------


def _lost_queue_history(n_jobs=14, lost=(3,)):
    from jepsen_tpu.history import invoke_op, ok_op

    h = []
    for j in range(n_jobs):
        h.append(invoke_op(j % 3, "enqueue", j))
        h.append(ok_op(j % 3, "enqueue", j))
    h.append(invoke_op(0, "drain", None))
    h.append(ok_op(0, "drain",
                   [j for j in range(n_jobs) if j not in lost]))
    return h


def test_bank_time_ddmin_attaches_minimal_repro(tmp_path):
    from jepsen_tpu.history import Op
    from jepsen_tpu.live import corpus

    h = _lost_queue_history()
    out = corpus.bank_cell(
        {"model": None, "history": h},
        {"family": "queue", "nemesis": "link-bridge", "valid": False},
        base=str(tmp_path))
    assert out["banked"] == 1
    entry = corpus.load_pool(corpus.corpus_dir(str(tmp_path)))[0]
    assert entry["valid"] is False
    mi = entry.get("minimal")
    assert mi is not None
    assert mi["n_ops"] < entry["n_ops"]
    # the minimal repro still reproduces the verdict on its route
    mops = [Op.from_dict(d) for d in mi["ops"]]
    assert corpus.replay_queue(mops)["valid"] is False
    # and it is tiny: the lost enqueue pair plus the drain pair
    assert mi["n_ops"] <= 6


def test_bank_time_ddmin_skips_small_and_valid_entries(tmp_path):
    from jepsen_tpu.history import invoke_op, ok_op
    from jepsen_tpu.live import corpus

    # invalid but already <= 10 ops: left alone
    h = [invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
         invoke_op(0, "drain", None), ok_op(0, "drain", [])]
    corpus.bank_cell(
        {"model": None, "history": h},
        {"family": "queue", "nemesis": "x", "valid": False},
        base=str(tmp_path))
    # valid and long: no shrink either
    h2 = _lost_queue_history(lost=())
    corpus.bank_cell(
        {"model": None, "history": h2},
        {"family": "queue", "nemesis": "x", "valid": True},
        base=str(tmp_path))
    pool = corpus.load_pool(corpus.corpus_dir(str(tmp_path)))
    assert all("minimal" not in e for e in pool)


def test_bank_time_ddmin_engine_route(tmp_path):
    from jepsen_tpu.history import Op, encode_ops
    from jepsen_tpu.live import corpus
    from jepsen_tpu.models import register
    from jepsen_tpu.synth import corrupt_read, register_history

    rng = random.Random(7)
    h = register_history(rng, n_ops=24, n_procs=3, cas=False,
                         unique_writes=True)
    h = corrupt_read(rng, h, at=0.5)
    out = corpus.bank_cell(
        {"model": register(0), "history": h},
        {"family": "kv", "nemesis": "kill-restart", "valid": False},
        base=str(tmp_path))
    assert out["banked"] == 1
    entry = corpus.load_pool(corpus.corpus_dir(str(tmp_path)))[0]
    mi = entry.get("minimal")
    assert mi is not None and mi["n_ops"] < entry["n_ops"]
    from jepsen_tpu.checker.seq import check_opseq

    mops = [Op.from_dict(d) for d in mi["ops"]]
    s = encode_ops(mops, register(0).f_codes)
    assert check_opseq(s, register(0),
                       max_configs=200_000)["valid"] is False


def test_corpus_replay_asserts_minimal_repro(tmp_path):
    """fuzz --corpus teeth: a minimal repro that no longer reproduces
    fails the replay."""
    import json

    import fuzz
    from jepsen_tpu.live import corpus

    h = _lost_queue_history()
    corpus.bank_cell(
        {"model": None, "history": h},
        {"family": "queue", "nemesis": "link-bridge", "valid": False},
        base=str(tmp_path))
    d = corpus.corpus_dir(str(tmp_path))
    assert fuzz.corpus_replay(d) == 0
    # tamper: make the stored minimal repro a VALID history
    pool = corpus.load_pool(d)
    pool[0]["minimal"]["ops"] = [
        {"process": 0, "type": "invoke", "f": "enqueue", "value": 1},
        {"process": 0, "type": "ok", "f": "enqueue", "value": 1},
        {"process": 1, "type": "invoke", "f": "dequeue",
         "value": None},
        {"process": 1, "type": "ok", "f": "dequeue", "value": 1},
    ]
    with open(os.path.join(d, "pool.jsonl"), "w") as f:
        for e in pool:
            f.write(json.dumps(e) + "\n")
    assert fuzz.corpus_replay(d) == 1
