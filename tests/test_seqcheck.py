"""Sequential linearizability oracle tests.

Histories mirror the reference's checker_test.clj style: hand-written
valid, invalid, and pathological cases, plus knossos's crashed-op
semantics (:info ops may linearize at any later point, or never).
"""

from jepsen_tpu.history import (
    encode_ops, fail_op, info_op, invoke_op, ok_op,
)
from jepsen_tpu.checker.seq import check_opseq
from jepsen_tpu.models import cas_register, mutex, register


def check(model, *ops):
    seq = encode_ops(list(ops), model.f_codes)
    return check_opseq(seq, model)


def test_empty_history_valid():
    r = check(register(0))
    assert r["valid"] is True


def test_sequential_read_write_valid():
    r = check(
        register(0),
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read"), ok_op(0, "read", 1),
    )
    assert r["valid"] is True
    assert r["linearization"] == [0, 1]


def test_stale_read_invalid():
    r = check(
        register(0),
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read"), ok_op(0, "read", 0),  # saw the old value
    )
    assert r["valid"] is False


def test_concurrent_reads_may_reorder():
    # write(1) overlaps two reads: one sees 0, one sees 1 — both orders
    # exist, so valid.
    r = check(
        register(0),
        invoke_op(0, "write", 1),
        invoke_op(1, "read"), ok_op(1, "read", 0),
        invoke_op(2, "read"), ok_op(2, "read", 1),
        ok_op(0, "write", 1),
    )
    assert r["valid"] is True


def test_read_before_overlap_must_see_old():
    # read completes before write invokes -> must see 0
    r = check(
        register(0),
        invoke_op(1, "read"), ok_op(1, "read", 1),
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
    )
    assert r["valid"] is False


def test_cas_register_valid_chain():
    r = check(
        cas_register(0),
        invoke_op(0, "cas", (0, 2)), ok_op(0, "cas", (0, 2)),
        invoke_op(1, "cas", (2, 3)), ok_op(1, "cas", (2, 3)),
        invoke_op(0, "read"), ok_op(0, "read", 3),
    )
    assert r["valid"] is True


def test_cas_from_wrong_value_invalid():
    r = check(
        cas_register(0),
        invoke_op(0, "cas", (5, 2)), ok_op(0, "cas", (5, 2)),
    )
    assert r["valid"] is False


def test_failed_op_did_not_happen():
    r = check(
        register(0),
        invoke_op(0, "write", 1), fail_op(0, "write", 1),
        invoke_op(0, "read"), ok_op(0, "read", 0),
    )
    assert r["valid"] is True


def test_info_op_may_have_happened():
    # crashed write(1); later read sees 1 -> valid only if the crashed
    # write is allowed to have taken effect
    r = check(
        register(0),
        invoke_op(0, "write", 1), info_op(0, "write", 1),
        invoke_op(1, "read"), ok_op(1, "read", 1),
    )
    assert r["valid"] is True


def test_info_op_may_not_have_happened():
    r = check(
        register(0),
        invoke_op(0, "write", 1), info_op(0, "write", 1),
        invoke_op(1, "read"), ok_op(1, "read", 0),
    )
    assert r["valid"] is True


def test_info_op_takes_effect_late():
    # crashed write(1) invoked FIRST; reads see 0, 0, then 1: the crashed
    # op may linearize arbitrarily late (knossos crashed-op semantics).
    r = check(
        register(0),
        invoke_op(0, "write", 1), info_op(0, "write", 1),
        invoke_op(1, "read"), ok_op(1, "read", 0),
        invoke_op(1, "read"), ok_op(1, "read", 1),
        invoke_op(1, "read"), ok_op(1, "read", 1),
    )
    assert r["valid"] is True


def test_info_cannot_unhappen():
    # 0 -> 1 -> 0 with only one crashed write(1): the final read of 0 is
    # impossible once 1 was observed (no op writes 0 again).
    r = check(
        register(0),
        invoke_op(0, "write", 1), info_op(0, "write", 1),
        invoke_op(1, "read"), ok_op(1, "read", 1),
        invoke_op(1, "read"), ok_op(1, "read", 0),
    )
    assert r["valid"] is False


def test_mutex_valid():
    r = check(
        mutex(),
        invoke_op(0, "acquire"), ok_op(0, "acquire"),
        invoke_op(0, "release"), ok_op(0, "release"),
        invoke_op(1, "acquire"), ok_op(1, "acquire"),
    )
    assert r["valid"] is True


def test_mutex_double_acquire_invalid():
    r = check(
        mutex(),
        invoke_op(0, "acquire"), ok_op(0, "acquire"),
        invoke_op(1, "acquire"), ok_op(1, "acquire"),
    )
    assert r["valid"] is False


def test_mutex_concurrent_handoff_valid():
    # release overlaps the second acquire -> legal interleaving exists
    r = check(
        mutex(),
        invoke_op(0, "acquire"), ok_op(0, "acquire"),
        invoke_op(0, "release"),
        invoke_op(1, "acquire"), ok_op(1, "acquire"),
        ok_op(0, "release"),
    )
    assert r["valid"] is True


def test_unknown_on_config_explosion():
    # tiny cap forces the unknown path
    ops = []
    for i in range(8):
        ops.append(invoke_op(i, "write", i))
    for i in range(8):
        ops.append(info_op(i, "write", i))
    # an ok read forces the search to actually order the crashed writes
    ops += [invoke_op(8, "read"), ok_op(8, "read", 3)]
    seq = encode_ops(ops, register(0).f_codes)
    r = check_opseq(seq, register(0), max_configs=2)
    assert r["valid"] == "unknown"


def test_invalid_reports_final_ops():
    r = check(
        register(0),
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read"), ok_op(0, "read", 5),
    )
    assert r["valid"] is False
    assert r["final_ops"], "should report the stuck frontier ops"


def test_multi_register_read_through_encode():
    # regression: compound read values (key, nil) must be filled in from
    # the ok completion, or a read of a never-written value passes.
    from jepsen_tpu.models import multi_register
    m = multi_register(3)
    r = check(
        m,
        invoke_op(0, "write", (0, 5)), ok_op(0, "write", (0, 5)),
        invoke_op(1, "read", (0, None)), ok_op(1, "read", (0, 7)),
    )
    assert r["valid"] is False
    r2 = check(
        m,
        invoke_op(0, "write", (0, 5)), ok_op(0, "write", (0, 5)),
        invoke_op(1, "read", (0, None)), ok_op(1, "read", (0, 5)),
    )
    assert r2["valid"] is True


def test_invalid_at_depth_zero_reports_final_ops():
    r = check(register(0), invoke_op(0, "read"), ok_op(0, "read", 5))
    assert r["valid"] is False
    assert r["final_ops"] == [0]
