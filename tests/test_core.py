"""Tier-2 harness self-tests against the in-process atom DB, mirroring
core_test.clj: a full run + linearizability check (basic-cas-test,
core_test.clj:18-30), crash-looping clients consuming exactly their ops
(worker-recovery-test, 88-104), and a generator exception unblocking
barrier-stuck workers (generator-recovery-test, 127-149)."""

import random
import threading
from dataclasses import replace

import pytest

from jepsen_tpu import client as client_mod
from jepsen_tpu import core, fixtures, generator as gen, independent
from jepsen_tpu.checker import linearizable as lin
from jepsen_tpu.models import cas_register


def cas_test(state, n_ops=60, concurrency=5):
    return fixtures.noop_test() | {
        "name": None,  # no store writes in unit tests
        "db": fixtures.atom_db(state),
        "client": fixtures.atom_client(state),
        "model": cas_register(0),  # atom-db resets the register to 0
        "checker": lin.linearizable(),
        "generator": gen.clients(
            gen.limit(n_ops, gen.mix([
                {"type": "invoke", "f": "read", "value": None},
                lambda t, p: {"type": "invoke", "f": "write",
                              "value": random.randrange(5)},
                lambda t, p: {"type": "invoke", "f": "cas",
                              "value": (random.randrange(5),
                                        random.randrange(5))},
            ]))),
        "concurrency": concurrency,
    }


def test_basic_cas_run():
    state = fixtures.AtomRegister()
    test = core.run(cas_test(state))
    assert test["results"]["valid"] is True
    h = test["history"]
    assert len(h) == 2 * 60  # every op completed
    assert all(op.index == i for i, op in enumerate(h))
    # atom-db teardown ran
    assert state.read() == "done"


class CrashyClient(client_mod.Client):
    """Crashes on every other invoke (worker-recovery-test analog)."""

    def __init__(self, state):
        self.state = state
        self.n = 0
        self.lock = threading.Lock()

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        with self.lock:
            self.n += 1
            if self.n % 2 == 0:
                raise RuntimeError("client crashed!")
        return replace(op, type="ok", value=self.state.read())


def test_worker_recovery():
    """Crash-looping clients still consume exactly n ops
    (core_test.clj:88-104)."""
    state = fixtures.AtomRegister()
    test = cas_test(state) | {
        "client": CrashyClient(state),
        "checker": __import__("jepsen_tpu.checker",
                              fromlist=["unbridled_dionysus"]
                              ).unbridled_dionysus,
        "generator": gen.clients(
            gen.limit(40, {"type": "invoke", "f": "read", "value": None})),
    }
    test = core.run(test)
    h = test["history"]
    invokes = [op for op in h if op.type == "invoke"]
    assert len(invokes) == 40
    infos = [op for op in h if op.type == "info" and op.process != "nemesis"]
    assert infos, "expected some crashed ops"
    # crashed processes retired: successor ids appear
    procs = {op.process for op in invokes}
    assert any(p >= test["concurrency"] for p in procs)


class ExplodingGen(gen.Generator):
    """Yields a few ops, then throws (generator-recovery-test analog)."""

    def __init__(self, n):
        self.n = n
        self.lock = threading.Lock()

    def op(self, test, process):
        with self.lock:
            self.n -= 1
            if self.n < 0:
                raise RuntimeError("generator exploded!")
        return {"type": "invoke", "f": "read", "value": None}


def test_generator_recovery_unblocks_barriers():
    """One worker's generator exception must unblock workers parked on a
    synchronize barrier and close all clients (core_test.clj:127-149)."""
    state = fixtures.AtomRegister()
    # phase 1: 3 ops (one per worker on average); phase 2 barrier; the
    # exploding generator blows up while some workers wait on the barrier
    g = gen.clients(
        gen.phases(ExplodingGen(2),
                   gen.limit(10, {"type": "invoke", "f": "read",
                                  "value": None})))
    test = cas_test(state) | {"generator": g, "concurrency": 3,
                              "checker": __import__(
                                  "jepsen_tpu.checker",
                                  fromlist=["unbridled_dionysus"]
                              ).unbridled_dionysus}
    with pytest.raises(RuntimeError, match="generator exploded"):
        core.run(test)


def test_nemesis_ops_in_history():
    state = fixtures.AtomRegister()
    test = cas_test(state, n_ops=10) | {
        "generator": gen.nemesis(
            gen.limit(2, {"type": "info", "f": "start", "value": None}),
            gen.limit(10, {"type": "invoke", "f": "read", "value": None})),
    }
    test = core.run(test)
    nem_ops = [op for op in test["history"] if op.process == "nemesis"]
    assert len(nem_ops) == 4  # 2 invocations + 2 completions
    assert all(op.type == "info" for op in nem_ops)


def test_run_with_independent_workload_and_store(tmp_path):
    """End-to-end: concurrent independent keys + store persistence."""
    state_by_key = {}
    lock = threading.Lock()

    class MapClient(client_mod.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op):
            k, v = op.value.key, op.value.value
            with lock:
                reg = state_by_key.setdefault(k, fixtures.AtomRegister(0))
            if op.f == "write":
                reg.write(v)
                return replace(op, type="ok")
            if op.f == "read":
                return replace(op, type="ok",
                               value=independent.tuple_(k, reg.read()))
            cur, new = v
            return replace(op, type="ok" if reg.cas(cur, new) else "fail")

    test = fixtures.noop_test() | {
        "name": "independent-cas",
        "store_base": str(tmp_path / "store"),
        "client": MapClient(),
        "model": cas_register(0),
        "checker": independent.checker(lin.linearizable()),
        "concurrency": 4,
        "generator": gen.clients(independent.concurrent_generator(
            2, range(4),
            lambda k: gen.limit(12, gen.mix([
                {"type": "invoke", "f": "read", "value": None},
                lambda t, p: {"type": "invoke", "f": "write",
                              "value": random.randrange(5)},
            ])))),
    }
    test = core.run(test)
    assert test["results"]["valid"] is True
    assert set(test["results"]["results"].keys()) == {0, 1, 2, 3}

    # store layout (store.clj:121-135 analog)
    import os

    base = test["store_base"]
    d = os.path.join(base, "independent-cas", test["start_time"])
    assert os.path.exists(os.path.join(d, "history.jsonl"))
    assert os.path.exists(os.path.join(d, "results.json"))
    assert os.path.exists(os.path.join(d, "test.json"))
    assert os.path.islink(os.path.join(base, "latest"))

    from jepsen_tpu import store as store_mod

    loaded = store_mod.load("independent-cas", test["start_time"], base)
    assert loaded["results"]["valid"] is True
    assert len(loaded["history"]) == len(test["history"])
