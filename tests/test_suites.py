"""Suite tests: test-map construction, db automation command shapes
(dummy remote), workload wiring, and the atomdemo end-to-end run."""

import itertools

import pytest

from jepsen_tpu import cli, generator as gen, independent
from jepsen_tpu.control import DummyRemote, Session
from jepsen_tpu.history import invoke_op, ok_op
from jepsen_tpu.suites import atomdemo, etcdemo, hazelcast, registry
from jepsen_tpu.suites import zookeeper as zk


def dummy_test(nodes=("n1", "n2", "n3"), responses=None):
    r = DummyRemote(responses)
    return {"nodes": list(nodes),
            "sessions": {n: Session(node=n, remote=r) for n in nodes}}, r


# --- etcdemo --------------------------------------------------------------


def test_etcd_urls_and_cluster():
    test = {"nodes": ["n1", "n2"]}
    assert etcdemo.peer_url("n1") == "http://n1:2380"
    assert etcdemo.client_url("n2") == "http://n2:2379"
    assert etcdemo.initial_cluster(test) == \
        "n1=http://n1:2380,n2=http://n2:2380"


def test_etcd_db_commands():
    test, r = dummy_test(responses={
        "stat /": (1, "", "none"),
        "ls -A": (0, "etcd-v3.1.5-linux-amd64\n", ""),
        "dirname": (0, "/opt", "")})
    db = etcdemo.db("v3.1.5")
    import time as time_mod

    orig_sleep = time_mod.sleep
    time_mod.sleep = lambda s: None  # skip the 10s cluster-join wait
    try:
        db.setup(test, "n1")
    finally:
        time_mod.sleep = orig_sleep
    cmds = [e[2] for e in r.log if e[1] == "exec" and e[0] == "n1"]
    assert any("wget" in c and "etcd-v3.1.5-linux-amd64.tar.gz" in c
               for c in cmds)
    assert any("start-stop-daemon --start" in c and
               "--initial-cluster n1=http://n1:2380" in c
               for c in cmds)
    db.teardown(test, "n1")
    cmds = [e[2] for e in r.log if e[1] == "exec" and e[0] == "n1"]
    assert any("killall -9 -w etcd" in c for c in cmds)
    assert any("rm -rf /opt/etcd" in c for c in cmds)
    assert db.log_files(test, "n1") == ["/opt/etcd/etcd.log"]


def test_etcd_test_map_and_workloads():
    opts = {"nodes": ["n1", "n2", "n3"], "concurrency": 10,
            "workload": "register", "ops_per_key": 10, "rate": 100,
            "time_limit": 1}
    test = etcdemo.etcd_test(opts)
    assert test["name"] == "etcd q=False register"
    assert test["quorum"] is False
    assert isinstance(test["checker"], object)
    # set workload wires the set checker and a final read
    opts["workload"] = "set"
    test2 = etcdemo.etcd_test(opts)
    assert "set" in test2["name"]


def test_etcd_cli_parses():
    cmds = cli.single_test_cmd(etcdemo.etcd_test,
                               add_opts=etcdemo.add_opts)
    # invalid workload name -> bad args
    rc = cli.run(cmds, ["test", "-w", "nope"])
    assert rc == cli.EXIT_BAD_ARGS


# --- zookeeper ------------------------------------------------------------


def test_zk_cfg_generation():
    test = {"nodes": ["a", "b", "c"]}
    assert zk.zk_node_id(test, "b") == 1
    cfg = zk.zoo_cfg_servers(test)
    assert "server.0=a:2888:3888" in cfg and "server.2=c:2888:3888" in cfg


def test_zk_db_commands():
    listing = "ii  zookeeper  3.4.13-2  all  coordination\n"
    test, r = dummy_test(responses={"dpkg": (0, listing, ""),
                                    "apt-cache":
                                        (0, "  Installed: 3.4.13-2\n", "")})
    zk.db().setup(test, "n2")
    cmds = [e[2] for e in r.log if e[1] == "exec" and e[0] == "n2"]
    assert any("echo 1 > /etc/zookeeper/conf/myid" in c for c in cmds)
    assert any("zoo.cfg" in c and "server.0=n1:2888:3888" in c
               for c in cmds)
    assert any("service zookeeper restart" in c for c in cmds)


def test_zk_test_map():
    test = zk.zk_test({"nodes": ["n1"], "concurrency": 2, "time_limit": 1})
    assert test["name"] == "zookeeper"
    assert test["model"].name == "cas-register"


# --- hazelcast lock -------------------------------------------------------


def test_lock_service_and_client():
    svc = hazelcast.InProcessLockService()
    c1 = hazelcast.LockClient(svc).open({}, "n1")
    c2 = hazelcast.LockClient(svc).open({}, "n2")
    acq = c1.invoke({}, invoke_op(0, "acquire", None))
    assert acq.type == "ok"
    assert c2.invoke({}, invoke_op(1, "acquire", None)).type == "fail"
    rel = c2.invoke({}, invoke_op(1, "release", None))
    assert rel.type == "fail" and rel.error == "not-lock-owner"
    assert c1.invoke({}, invoke_op(0, "release", None)).type == "ok"
    assert c2.invoke({}, invoke_op(1, "acquire", None)).type == "ok"


def test_hazelcast_lock_end_to_end_valid_and_broken():
    """Run the lock workload in-process; a broken lock service must be
    caught by the mutex linearizability check (BASELINE config #4
    shape)."""
    from jepsen_tpu import core

    def make(broken):
        svc = hazelcast.InProcessLockService()
        svc.broken = broken
        opts = {"nodes": ["n1", "n2"], "concurrency": 3, "time_limit": 2,
                "rate": 200, "workload": "lock-fixture", "name": None}
        test = hazelcast.hazelcast_test(opts)
        test["client"] = hazelcast.LockClient(svc)
        test["name"] = None  # no store writes
        # drop perf graphs for unit-test speed
        test["checker"] = hazelcast.lock_fixture_workload(
            opts, svc)["checker"]
        return test

    good = core.run(make(False))
    assert good["results"]["valid"] is True

    bad = core.run(make(True))
    assert bad["results"]["valid"] is False


def test_unique_ids_workload():
    wl = hazelcast.unique_ids_fixture_workload({})
    c = wl["client"].open({}, "n1")
    vals = {c.invoke({}, invoke_op(0, "generate", None)).value
            for _ in range(10)}
    assert len(vals) == 10


# --- registry -------------------------------------------------------------


def test_registry_builds_tests():
    reg = registry.Registry()

    @reg.workload("demo")
    def demo(opts):
        return {"client": atomdemo.AtomMapClient(),
                "generator": gen.limit(5, {"type": "invoke", "f": "read",
                                           "value": None}),
                "checker": __import__("jepsen_tpu.checker",
                                      fromlist=["unbridled_dionysus"]
                                      ).unbridled_dionysus}

    test = reg.build_test({"workload": "demo", "nemesis": "parts",
                           "nodes": ["n1"], "concurrency": 2,
                           "time_limit": 1})
    assert test["name"] == "demo nemesis=parts"
    assert "majority-ring" in reg.nemeses
    assert test["nemesis"].__class__.__name__ == "Partitioner"


# --- atomdemo end-to-end --------------------------------------------------


def test_atomdemo_end_to_end(tmp_path):
    from jepsen_tpu import core

    opts = {"nodes": ["n1", "n2"], "concurrency": 4, "time_limit": 2,
            "rate": 300, "ops_per_key": 20, "group_size": 2,
            "store_base": str(tmp_path / "store")}
    test = atomdemo.atom_test(opts)
    test = core.run(test)
    assert test["results"]["valid"] is True
    workload = test["results"]["workload"]
    assert workload["valid"] is True
    assert len(workload["results"]) >= 1  # checked at least one key
    import os

    assert os.path.exists(os.path.join(str(tmp_path / "store"), "latest"))


# --- consul ---------------------------------------------------------------


def test_consul_db_commands():
    responses = {"getent": (0, "10.1.1.1  STREAM x\n", "")}
    test, r = dummy_test(responses=responses)
    db = __import__("jepsen_tpu.suites.consul",
                    fromlist=["db"]).db()
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        db.setup(test, "n1")   # primary: -bootstrap
        db.setup(test, "n2")   # secondary: -join
    finally:
        time_mod.sleep = orig
    n1 = [e[2] for e in r.log if e[0] == "n1" and e[1] == "exec"]
    n2 = [e[2] for e in r.log if e[0] == "n2" and e[1] == "exec"]
    assert any("-bootstrap" in c for c in n1)
    assert any("-join 10.1.1.1" in c for c in n2)
    db.teardown(test, "n1")
    assert any("killall -9 consul" in e[2] for e in r.log)


def test_consul_test_map():
    from jepsen_tpu.suites import consul

    t = consul.consul_test({"nodes": ["n1"], "concurrency": 2,
                            "time_limit": 1})
    assert t["name"] == "consul"
    assert t["model"].name == "cas-register"


# --- rabbitmq -------------------------------------------------------------


def test_rabbitmq_test_map_and_db():
    from jepsen_tpu.suites import rabbitmq

    t = rabbitmq.rabbit_test({"nodes": ["n1", "n2"], "concurrency": 2,
                              "time_limit": 1})
    assert t["name"] == "rabbitmq-simple-partition"

    test, r = dummy_test(("n1", "n2"), responses={"dpkg": (0, "", "")})
    rabbitmq.db().setup(test, "n2")
    cmds = [e[2] for e in r.log if e[0] == "n2" and e[1] == "exec"]
    assert any("rabbitmqctl join_cluster rabbit@n1" in c for c in cmds)


# --- cockroach registry ---------------------------------------------------


def test_cockroach_registry_workloads():
    from jepsen_tpu.suites import cockroach

    assert set(cockroach.REGISTRY.workloads) >= \
        {"register", "bank", "monotonic", "sequential", "g2"}
    assert "skews" in cockroach.REGISTRY.nemeses
    t = cockroach.REGISTRY.build_test(
        {"workload": "bank", "nemesis": "parts", "nodes": ["n1"],
         "concurrency": 2, "time_limit": 1})
    assert "bank" in t["name"]

    import random

    random.seed(0)
    op = cockroach.bank_generator(t, 0)
    assert op["f"] in ("read", "transfer")
    if op["f"] == "transfer":
        assert op["value"]["from"] != op["value"]["to"]


def test_cockroach_db_commands():
    from jepsen_tpu.suites import cockroach

    test, r = dummy_test(responses={
        "stat /": (1, "", "no"),
        "ls -A": (0, "cockroach-v2.0.0.linux-amd64\n", ""),
        "dirname": (0, "/opt", "")})
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        cockroach.db().setup(test, "n1")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[0] == "n1" and e[1] == "exec"]
    assert any("--startas /opt/cockroach/cockroach -- start --insecure" in c
               and "--join=n1,n2,n3" in c for c in cmds)


def test_cockroach_no_noop_clients():
    """VERDICT r1 item 4: every registered workload must construct a
    runnable test with a real client (no client_mod.noop stubs)."""
    from jepsen_tpu import client as client_mod
    from jepsen_tpu.suites import cockroach

    for name in cockroach.REGISTRY.workloads:
        t = cockroach.REGISTRY.build_test(
            {"workload": name, "nemesis": "none",
             "nodes": ["n1", "n2", "n3"], "concurrency": 4,
             "time_limit": 1})
        assert t["client"] is not client_mod.noop, name
        assert t["generator"] is not None, name


def test_cockroach_nemesis_menu():
    from jepsen_tpu.suites import cockroach

    want = {"skews", "strobe-skews", "small-skews", "subcritical-skews",
            "critical-skews", "big-skews", "huge-skews", "startstop",
            "startstop2", "startkill", "startkill2", "parts", "majring",
            "split"}
    assert want <= set(cockroach.REGISTRY.nemeses)


def test_cockroach_monotonic_generator_and_final_read():
    from jepsen_tpu.suites import cockroach

    w = cockroach.monotonic_workload({"concurrency": 4})
    t = {"nodes": ["n1"]}
    op = gen.gen_op(w["generator"], t, 0)
    assert op["f"] == "add" and op["value"] is None
    fin = gen.gen_op(w["final_generator"], t, 0)
    assert fin["f"] == "read"


def test_cockroach_sequential_generator():
    from jepsen_tpu.suites import cockroach

    w = cockroach.sequential_workload({"concurrency": 4})
    test = {"nodes": ["n1", "n2"], "concurrency": 4}
    with gen.with_threads([0, 1, 2, 3]):
        # thread 0/1 are writers (n=2), 2+ read
        ops = [gen.gen_op(w["generator"], test, p) for p in (0, 1, 0, 1)]
    assert all(o["f"] == "write" for o in ops)
    assert [o["value"] for o in ops] == [0, 1, 2, 3]
    with gen.with_threads([0, 1, 2, 3]):
        r = gen.gen_op(w["generator"], test, 3)
    assert r["f"] == "read" and r["value"] in (0, 1, 2, 3)


def test_cockroach_sequential_client_tables():
    from jepsen_tpu.suites import cockroach

    c = cockroach.SequentialClient()
    sks = c._subkeys(3, 7)
    assert sks == ["7_0", "7_1", "7_2"]
    # stable hashing across processes (not Python's randomized hash)
    assert c._table_for("7_0") == c._table_for("7_0")
    assert all(c._table_for(s).startswith("seq_") for s in sks)


def test_cockroach_kill_start_node_commands():
    from jepsen_tpu.suites import cockroach

    test, r = dummy_test()
    cockroach.kill_node(test, "n2")
    cmds = [e[2] for e in r.log if e[0] == "n2" and e[1] == "exec"]
    assert any("kill" in c and "-9" in c and "cockroach" in c
               for c in cmds)
    cockroach.start_node(test, "n2")
    cmds = [e[2] for e in r.log if e[0] == "n2" and e[1] == "exec"]
    assert any("start-stop-daemon --start" in c and "--join=n1,n2,n3" in c
               for c in cmds)


def test_cockroach_split_nemesis_no_keyrange():
    from dataclasses import dataclass as dc

    from jepsen_tpu.suites import cockroach

    @dc
    class Op:
        f: str
        type: str = "invoke"
        value: object = None
        process: object = "nemesis"

    nem = cockroach.SplitNemesis()
    test, _ = dummy_test()
    out = nem.invoke(test, Op(f="split"))
    assert out.type == "info" and out.value == "nothing-to-split"
    cockroach.update_keyrange(test, "seq_0", "3_1")
    assert test["keyrange"] == {"seq_0": {"3_1"}}


def test_cockroach_bump_time_targeting():
    """BumpTimeNemesis start bumps each node w/ p=0.5; stop resets +
    restarts (nemesis.clj:232-255 semantics)."""
    from dataclasses import dataclass as dc

    import random as random_mod

    from jepsen_tpu.suites import cockroach

    @dc
    class Op:
        f: str
        type: str = "invoke"
        value: object = None
        process: object = "nemesis"

    test, r = dummy_test(responses={"stat /": (0, "yes", "")})
    nem = cockroach.BumpTimeNemesis(0.25)
    random_mod.seed(1)
    out = nem.invoke(test, Op(f="start"))
    assert out.type == "info"
    assert set(out.value) == {"n1", "n2", "n3"}
    assert all(v in (0, 0.25) for v in out.value.values())
    out = nem.invoke(test, Op(f="stop"))
    assert out.type == "info"
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("ntpdate" in c for c in cmds)
    assert any("start-stop-daemon --start" in c for c in cmds)


def test_hazelcast_db_commands():
    import os
    import tempfile

    from test_suites import dummy_test

    test, r = dummy_test(nodes=("n1", "n2"))
    r.responses["getent ahosts n2"] = (0, "10.0.0.2 STREAM n2\n", "")
    import time as time_mod

    orig = time_mod.sleep
    time_mod.sleep = lambda s: None
    try:
        with tempfile.NamedTemporaryFile(suffix=".jar") as jar:
            hazelcast.db(jar.name).setup(test, "n1")
    finally:
        time_mod.sleep = orig
    cmds = [e[2] for e in r.log if e[1] == "exec"]
    assert any("start-stop-daemon" in c
               and "-jar /opt/hazelcast/server.jar" in c
               and "--members 10.0.0.2" in c for c in cmds)
    ups = [e for e in r.log if e[1] == "upload"]
    assert any("/opt/hazelcast/server.jar" in str(e) for e in ups)


def test_hazelcast_rest_queue_client():
    import http.server
    import threading as th

    q = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            q.append(int(self.rfile.read(n)))
            self.send_response(200)
            self.end_headers()

        def do_DELETE(self):
            if q:
                body = str(q.pop(0)).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(204)
                self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    th.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        c = hazelcast.RestQueueClient()
        c.node = "127.0.0.1"
        old_port = hazelcast.PORT
        hazelcast.PORT = srv.server_address[1]
        try:
            out = c.invoke({}, invoke_op(0, "enqueue", 7))
            assert out.type == "ok"
            out = c.invoke({}, invoke_op(0, "dequeue", None))
            assert out.type == "ok" and out.value == 7
            out = c.invoke({}, invoke_op(0, "dequeue", None))
            assert out.type == "fail" and out.error == "empty"
            c.invoke({}, invoke_op(0, "enqueue", 8))
            c.invoke({}, invoke_op(0, "enqueue", 9))
            out = c.invoke({}, invoke_op(0, "drain", None))
            assert out.type == "ok" and out.value == [8, 9]
        finally:
            hazelcast.PORT = old_port
    finally:
        srv.shutdown()


def test_hazelcast_memcache_id_client():
    import socket as sock_mod
    import threading as th

    state = {"n": 0}

    def server(srv):
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            f = conn.makefile("rb")

            def serve(conn=conn, f=f):
                while True:
                    line = f.readline()
                    if not line:
                        return
                    parts = line.decode().split()
                    if parts and parts[0] == "add":
                        f.readline()  # payload
                        conn.sendall(b"STORED\r\n")
                    elif parts and parts[0] == "incr":
                        state["n"] += int(parts[2])
                        conn.sendall(f"{state['n']}\r\n".encode())

            th.Thread(target=serve, daemon=True).start()

    srv = sock_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    th.Thread(target=server, args=(srv,), daemon=True).start()
    try:
        old_port = hazelcast.PORT
        hazelcast.PORT = srv.getsockname()[1]
        try:
            c = hazelcast.MemcacheIdClient()
            c.node = "127.0.0.1"
            vals = [c.invoke({}, invoke_op(0, "generate", None)).value
                    for _ in range(5)]
            assert vals == [1, 2, 3, 4, 5]
        finally:
            hazelcast.PORT = old_port
    finally:
        srv.close()
